"""Tracing / metrics for the protocol hot paths.

The reference has no tracing at all (SURVEY.md §5: the only hook is a
disabled benchmark flag in its test simulator, `src/test.rs:229,341`);
errors are its only diagnostics. The rebuild adds the subsystem the
batched design needs: per-phase wall-clock timers and item counters
around every verify family and prover column, plus an optional XLA
profiler trace for kernel-level inspection.

Usage:
    from fsdkr_tpu.utils import get_tracer, phase

    with phase("verify_pdl", items=len(items)):
        ...
    print(get_tracer().report())

Timers are process-global and thread-safe; `FSDKR_TRACE=1` (or
`get_tracer().enable()`) turns collection on, and the protocol layer
stamps its phases unconditionally — a disabled tracer costs two
`time.perf_counter` calls per phase.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, Optional

__all__ = ["PhaseStats", "Tracer", "get_tracer", "phase", "jax_profile"]


@dataclass
class PhaseStats:
    calls: int = 0
    seconds: float = 0.0
    items: int = 0
    macs: float = 0.0  # analytic u16-MAC count (utils.roofline)

    @property
    def items_per_second(self) -> float:
        return self.items / self.seconds if self.seconds > 0 else 0.0

    def mfu(self, peak: float) -> float:
        return self.macs / self.seconds / peak if self.seconds > 0 else 0.0


@dataclass
class Tracer:
    enabled: bool = field(
        default_factory=lambda: os.environ.get("FSDKR_TRACE", "0") not in ("", "0")
    )
    _stats: Dict[str, PhaseStats] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)
    _local: threading.local = field(default_factory=threading.local)

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()

    @contextlib.contextmanager
    def phase(self, name: str, items: int = 0) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        stack = self._phase_stack()
        stack.append(name)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            stack.pop()
            with self._lock:
                st = self._stats.setdefault(name, PhaseStats())
                st.calls += 1
                st.seconds += dt
                st.items += items

    def _phase_stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current_phase(self) -> Optional[str]:
        """Innermost active phase of THIS thread (None outside any)."""
        stack = getattr(self._local, "stack", None)
        return stack[-1] if stack else None

    @contextlib.contextmanager
    def inherit_phase(self, name: Optional[str]) -> Iterator[None]:
        """Attribute work on a worker thread to the submitting thread's
        phase: pushes `name` onto this thread's phase stack WITHOUT
        timing it (the submitter's enclosing `phase` already owns the
        wall clock; a timed re-entry would double-count seconds). Used
        by utils.pipeline so add_macs from pipelined tiles lands in the
        right phase instead of \"(unphased)\"."""
        if not self.enabled or name is None:
            yield
            return
        stack = self._phase_stack()
        stack.append(name)
        try:
            yield
        finally:
            stack.pop()

    def add_macs(self, macs: float) -> None:
        """Attribute analytic device work (utils.roofline formulas) to the
        innermost active phase of this thread — the kernel launch layer
        calls this without knowing which protocol phase it serves."""
        if not self.enabled:
            return
        stack = self._phase_stack()
        name = stack[-1] if stack else "(unphased)"
        with self._lock:
            self._stats.setdefault(name, PhaseStats()).macs += macs

    def count(self, name: str, items: int = 1) -> None:
        if not self.enabled:
            return
        with self._lock:
            st = self._stats.setdefault(name, PhaseStats())
            st.calls += 1
            st.items += items

    def stats(self) -> Dict[str, PhaseStats]:
        with self._lock:
            return {
                k: PhaseStats(v.calls, v.seconds, v.items, v.macs)
                for k, v in self._stats.items()
            }

    def report(self) -> str:
        from .roofline import peak_macs

        peak = peak_macs()
        rows = sorted(self.stats().items(), key=lambda kv: -kv[1].seconds)
        if not rows:
            return "(no phases recorded)"
        width = max(len(k) for k, _ in rows)
        lines = [
            f"{'phase':{width}s} {'calls':>6s} {'seconds':>9s} {'items':>8s} "
            f"{'items/s':>10s} {'GMACs':>9s} {'mfu%':>7s}"
        ]
        for name, st in rows:
            lines.append(
                f"{name:{width}s} {st.calls:6d} {st.seconds:9.3f} "
                f"{st.items:8d} {st.items_per_second:10.1f} "
                f"{st.macs / 1e9:9.2f} {100 * st.mfu(peak):7.3f}"
            )
        return "\n".join(lines)


_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def phase(name: str, items: int = 0):
    """Module-level shorthand for `get_tracer().phase(...)`."""
    return _TRACER.phase(name, items=items)


@contextlib.contextmanager
def jax_profile(log_dir: Optional[str] = None) -> Iterator[None]:
    """XLA profiler trace around a block (view with xprof/tensorboard).
    No-op when jax is unavailable or log_dir is None and FSDKR_XPROF is
    unset."""
    log_dir = log_dir or os.environ.get("FSDKR_XPROF")
    if not log_dir:
        yield
        return
    try:
        import jax
    except ImportError:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
