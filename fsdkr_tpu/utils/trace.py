"""Back-compat shim: the tracer moved to `fsdkr_tpu.telemetry.spans`.

Every historical import site (`from fsdkr_tpu.utils.trace import phase`,
`from fsdkr_tpu.utils import get_tracer`, ...) keeps working unchanged;
the process-global tracer is the SAME object either way. New code should
import from `fsdkr_tpu.telemetry` directly, which also exposes the
metrics registry, exporters, and the flight recorder the old flat
aggregator never had.
"""

from __future__ import annotations

from ..telemetry.spans import (  # noqa: F401
    PhaseStats,
    Span,
    Tracer,
    get_tracer,
    jax_profile,
    phase,
)

__all__ = ["PhaseStats", "Span", "Tracer", "get_tracer", "phase", "jax_profile"]
