"""Tile pipelining for the batched launch paths.

A large batch is split into row tiles (HBM caps on the device path, L2
and staging-buffer pressure on the native path). Running the tiles
strictly one after another serializes host staging (bigint -> limb
packing, base inversions, Montgomery-domain entry) against engine
execution, even though the engine releases the GIL for the whole call
(ctypes native calls) or returns before the device finishes (async JAX
dispatch). `pipelined` keeps a bounded window of tiles in flight on a
small thread pool, so tile k+1's staging overlaps tile k's engine time —
the dataflow shape SZKP-style pipelines get their throughput from.

Determinism: every tile is an independent slice with its own output
slot; results are reassembled by index, so the output is bit-identical
to the sequential loop at any depth. FSDKR_PIPELINE=0 forces the
sequential loop (A/B isolation and debugging).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, List, Optional, Sequence

__all__ = [
    "pipeline_enabled",
    "pipelined",
    "prefetch_tiles",
    "submit_bg",
    "run_jobs",
    "BackgroundProducer",
]

_DEPTH = 2  # double-buffered: one tile staging while one executes


def pipeline_enabled() -> bool:
    return os.environ.get("FSDKR_PIPELINE", "1").lower() not in (
        "0", "off", "false", "no",
    )


def pipelined(run: Callable, args_list: Sequence[tuple], depth: int = _DEPTH) -> List:
    """run(*args) for each tuple in args_list, up to `depth` tiles in
    flight, results in submission order. Exceptions propagate (the first
    failing tile's error; later in-flight tiles are drained first).
    Worker threads inherit the submitting thread's tracer phase, so MAC
    accounting (utils.trace add_macs) stays attributed correctly."""
    n = len(args_list)
    if n <= 1 or depth <= 1 or not pipeline_enabled():
        return [run(*a) for a in args_list]
    from concurrent.futures import ThreadPoolExecutor

    from .trace import get_tracer

    tracer = get_tracer()
    # capture the submitting thread's SPAN (not just the name): child
    # spans opened on the workers then parent to it across the thread
    # hop, so the Chrome-trace timeline shows tile launches nested under
    # the phase that issued them
    parent = tracer.current_span() or tracer.current_phase()

    def worker(*args):
        with tracer.inherit_phase(parent):
            return run(*args)

    out: List = [None] * n
    with ThreadPoolExecutor(max_workers=depth) as ex:
        futs = {}
        nxt = 0
        for _ in range(min(depth, n)):
            futs[nxt] = ex.submit(worker, *args_list[nxt])
            nxt += 1
        for i in range(n):
            out[i] = futs.pop(i).result()
            if nxt < n:
                futs[nxt] = ex.submit(worker, *args_list[nxt])
                nxt += 1
    return out


def prefetch_tiles(spans, prepare: Callable, consume: Callable) -> None:
    """Double-buffered streaming for the memory-planned verification
    tiles (backend.memplan): while tile k's `consume` runs its engine
    launches (GIL-released native/GMP calls, async device dispatch),
    tile k+1's `prepare` — host-only staging: domain gates, Fiat-Shamir
    hashing, fold-row construction — runs on one background thread. At
    most TWO tiles' prepared state is live at any instant, which is
    exactly the `inflight` factor the tile planner budgets for.

    `consume` is always called on the submitting thread, in span order,
    so accumulator mutation needs no locks and the result is
    bit-identical to the sequential loop (same determinism contract as
    `pipelined`). Sequential when pipelining is disabled. `prepare` must
    be read-only over shared state. Exceptions propagate from whichever
    callable raised them first in span order."""
    spans = list(spans)
    if not spans:
        return
    if len(spans) == 1 or not pipeline_enabled():
        for s in spans:
            consume(prepare(*s))
        return
    from concurrent.futures import ThreadPoolExecutor

    from .trace import get_tracer

    tracer = get_tracer()
    parent = tracer.current_span() or tracer.current_phase()

    def worker(*args):
        with tracer.inherit_phase(parent):
            return prepare(*args)

    with ThreadPoolExecutor(max_workers=1) as ex:
        fut = ex.submit(worker, *spans[0])
        for i in range(len(spans)):
            prep = fut.result()
            if i + 1 < len(spans):
                fut = ex.submit(worker, *spans[i + 1])
            consume(prep)


def _sched_workers() -> int:
    """Worker count for the concurrent column scheduler (run_jobs):
    FSDKR_SCHED, with 0/auto resolving to 2 lanes on multicore hosts and
    1 (sequential, zero-overhead) when the FSDKR_THREADS row pool is
    serial. Two lanes, not one-per-core: every scheduled job's native
    engine already fans its rows across the FSDKR_THREADS pool, so wide
    scheduler pools would oversubscribe to ~jobs x cores threads —
    double-buffering is enough to keep one job's host staging (GIL-held
    limb packing) hidden behind another's GIL-released engine time, the
    same depth rationale as `pipelined`. An explicit FSDKR_SCHED=N
    forces N lanes for experiments."""
    val = os.environ.get("FSDKR_SCHED", "auto").strip().lower() or "auto"
    try:
        n = int(val)
    except ValueError:
        n = 0
    if n > 0:
        return n
    from ..native import thread_count

    return 2 if thread_count() > 1 else 1


def run_jobs(jobs: Sequence[Callable], workers: Optional[int] = None) -> List:
    """Run independent thunks concurrently on a bounded pool, results in
    submission order — the concurrent column scheduler of
    tpu_verifier.verify_pairs: the mod-N~ group, the mod-n^2 group, and
    the RLC full-width ladders are independent launch sets, so they
    overlap instead of running as one sequential powm_columns chain.

    Every job is an independent closure writing only its own result
    slot, so the output is bit-identical to the sequential loop at any
    worker count (same determinism contract as `pipelined`). Workers
    inherit the submitting thread's tracer span, keeping phase/MAC
    attribution correct. Sequential when workers == 1 or pipelining is
    disabled."""
    n = len(jobs)
    if n == 0:
        return []
    if workers is None:
        workers = _sched_workers()
    if n == 1 or workers <= 1 or not pipeline_enabled():
        return [job() for job in jobs]
    from concurrent.futures import ThreadPoolExecutor

    from .trace import get_tracer

    tracer = get_tracer()
    parent = tracer.current_span() or tracer.current_phase()

    def worker(job):
        with tracer.inherit_phase(parent):
            return job()

    with ThreadPoolExecutor(max_workers=min(workers, n)) as ex:
        return list(ex.map(worker, jobs))


def submit_bg(fn: Callable) -> Optional["object"]:
    """Run fn() on a single background thread, returning its Future —
    used to overlap an independent host computation (the PDL u1 EC
    column) with the modexp launch set. Returns None when pipelining is
    disabled; callers then run fn inline at the join point. The worker
    inherits the submitting thread's tracer phase (see pipelined)."""
    if not pipeline_enabled():
        return None
    from concurrent.futures import ThreadPoolExecutor

    from .trace import get_tracer

    tracer = get_tracer()
    parent = tracer.current_span() or tracer.current_phase()

    def worker():
        with tracer.inherit_phase(parent):
            return fn()

    ex = ThreadPoolExecutor(max_workers=1)
    fut = ex.submit(worker)
    ex.shutdown(wait=False)  # the future still completes; no leak
    return fut


class BackgroundProducer:
    """One daemon scheduling thread pulling work off a `step` callable —
    the producer half of the precompute offline/online split
    (fsdkr_tpu.precompute.producer builds `step` from the pool targets).

    `step()` performs one bounded unit of production and returns True if
    it did work; the thread loops while steps report work, then parks on
    an event until `kick()`. The scheduling thread itself is single (the
    production batches already fan out across the FSDKR_THREADS row
    pools of the native/GMP engines, and those calls release the GIL —
    which is exactly how production overlaps a concurrent collect() on
    the main thread); adding scheduler threads would only oversubscribe
    the same engine pool. Exceptions in `step` park the producer instead
    of killing the interpreter: production is an optimization, never a
    correctness dependency (consumers fall back inline on a dry pool).
    """

    def __init__(self, step: Callable[[], bool], name: str = "fsdkr-precompute"):
        self._step = step
        self._name = name
        self._wake = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._thread_stop: Optional[threading.Event] = None
        self.errors = 0
        # occupancy accounting (telemetry): productive seconds vs wall
        # since the first start — the producer/consumer balance gauge the
        # SZKP-style pipelining literature tunes against. Single-writer
        # (the producer thread), torn reads only perturb a gauge.
        self.busy_seconds = 0.0
        self.steps = 0
        self.started_at: Optional[float] = None

    def _loop(self, stop: threading.Event) -> None:
        import time

        if self.started_at is None:
            self.started_at = time.monotonic()
        while not stop.is_set():
            t0 = time.monotonic()
            try:
                worked = self._step()
            except Exception:
                self.errors += 1
                worked = False
            if worked:
                self.busy_seconds += time.monotonic() - t0
                self.steps += 1
            else:
                self._wake.wait(timeout=60.0)
                self._wake.clear()

    def occupancy(self) -> float:
        """Fraction of wall time (since first start) spent producing —
        0.0 before the first start."""
        import time

        if self.started_at is None:
            return 0.0
        wall = time.monotonic() - self.started_at
        return self.busy_seconds / wall if wall > 0 else 0.0

    def kick(self) -> None:
        """Start the thread if needed and wake it (idempotent, cheap)."""
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                # each thread gets its OWN stop event: a stop() racing
                # this kick() signals the old thread's event, and the
                # fresh thread cannot observe that (or any later) set —
                # two producer loops can never run side by side
                self._thread_stop = threading.Event()
                self._thread = threading.Thread(
                    target=self._loop, args=(self._thread_stop,),
                    name=self._name, daemon=True,
                )
                self._thread.start()
        self._wake.set()

    def stop(self, timeout: float = 5.0) -> None:
        with self._lock:
            t = self._thread
            stop = self._thread_stop
            self._thread = None
            self._thread_stop = None
            if stop is not None:
                stop.set()
        if t is None:
            return
        self._wake.set()
        t.join(timeout=timeout)

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()
