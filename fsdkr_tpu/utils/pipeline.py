"""Tile pipelining for the batched launch paths.

A large batch is split into row tiles (HBM caps on the device path, L2
and staging-buffer pressure on the native path). Running the tiles
strictly one after another serializes host staging (bigint -> limb
packing, base inversions, Montgomery-domain entry) against engine
execution, even though the engine releases the GIL for the whole call
(ctypes native calls) or returns before the device finishes (async JAX
dispatch). `pipelined` keeps a bounded window of tiles in flight on a
small thread pool, so tile k+1's staging overlaps tile k's engine time —
the dataflow shape SZKP-style pipelines get their throughput from.

Determinism: every tile is an independent slice with its own output
slot; results are reassembled by index, so the output is bit-identical
to the sequential loop at any depth. FSDKR_PIPELINE=0 forces the
sequential loop (A/B isolation and debugging).
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence

__all__ = ["pipeline_enabled", "pipelined", "submit_bg"]

_DEPTH = 2  # double-buffered: one tile staging while one executes


def pipeline_enabled() -> bool:
    return os.environ.get("FSDKR_PIPELINE", "1").lower() not in (
        "0", "off", "false", "no",
    )


def pipelined(run: Callable, args_list: Sequence[tuple], depth: int = _DEPTH) -> List:
    """run(*args) for each tuple in args_list, up to `depth` tiles in
    flight, results in submission order. Exceptions propagate (the first
    failing tile's error; later in-flight tiles are drained first).
    Worker threads inherit the submitting thread's tracer phase, so MAC
    accounting (utils.trace add_macs) stays attributed correctly."""
    n = len(args_list)
    if n <= 1 or depth <= 1 or not pipeline_enabled():
        return [run(*a) for a in args_list]
    from concurrent.futures import ThreadPoolExecutor

    from .trace import get_tracer

    tracer = get_tracer()
    phase_name = tracer.current_phase()

    def worker(*args):
        with tracer.inherit_phase(phase_name):
            return run(*args)

    out: List = [None] * n
    with ThreadPoolExecutor(max_workers=depth) as ex:
        futs = {}
        nxt = 0
        for _ in range(min(depth, n)):
            futs[nxt] = ex.submit(worker, *args_list[nxt])
            nxt += 1
        for i in range(n):
            out[i] = futs.pop(i).result()
            if nxt < n:
                futs[nxt] = ex.submit(worker, *args_list[nxt])
                nxt += 1
    return out


def submit_bg(fn: Callable) -> Optional["object"]:
    """Run fn() on a single background thread, returning its Future —
    used to overlap an independent host computation (the PDL u1 EC
    column) with the modexp launch set. Returns None when pipelining is
    disabled; callers then run fn inline at the join point. The worker
    inherits the submitting thread's tracer phase (see pipelined)."""
    if not pipeline_enabled():
        return None
    from concurrent.futures import ThreadPoolExecutor

    from .trace import get_tracer

    tracer = get_tracer()
    phase_name = tracer.current_phase()

    def worker():
        with tracer.inherit_phase(phase_name):
            return fn()

    ex = ThreadPoolExecutor(max_workers=1)
    fut = ex.submit(worker)
    ex.shutdown(wait=False)  # the future still completes; no leak
    return fut
