from .trace import Tracer, get_tracer, jax_profile, phase  # noqa: F401
