"""Roofline / MFU accounting for the modexp kernel families.

proofs/s alone cannot distinguish "fast" from "busy": a collect() that
spends its time in host orchestration and a collect() that saturates the
MXU can post the same throughput at small n. Each device launch therefore
reports an *analytic* MAC count (u16 x u16 partial products — the native
word of both kernel families: CIOS multiplies 16-bit limbs on the VPU,
the RNS path rides 16-bit-channel matmuls on the MXU) to the tracer,
which divides by wall-clock and the chip's peak to give a model-flops
utilization per phase.

Peak normalization: TPU v5e ~197 TFLOP/s bf16 = 98.5e12 MAC/s. A u16
product is work-equivalent to a bf16 MAC on the MXU (one systolic cell
pass), so `mfu = macs / seconds / V5E_PEAK_MACS`. The number is an
engineering roofline (analytic op counts, padded rows included — padding
is real device work), not a profiler measurement; use
`utils.trace.jax_profile` for ground truth.

The formulas intentionally count only multiply work (the >95% term);
additions, selects and layout ops ride along. Reference workload being
priced: the collect() verify loop, `/root/reference/src/refresh_message.rs:321-467`.
"""

from __future__ import annotations

import os

__all__ = [
    "V5E_PEAK_MACS",
    "peak_macs",
    "montmul_macs",
    "generic_modexp_macs",
    "shared_modexp_macs",
    "modmul_macs",
    "k16",
    "stamp_generic_host",
    "stamp_shared_host",
]

# v5e bf16 peak, in MACs/s (197 TFLOP/s / 2 FLOPs-per-MAC). Override for
# other parts (v4: 137.5e12, v5p: 229.5e12) via FSDKR_PEAK_MACS.
V5E_PEAK_MACS = 98.5e12


def peak_macs() -> float:
    return float(os.environ.get("FSDKR_PEAK_MACS", V5E_PEAK_MACS))


def montmul_macs(k: int) -> float:
    """u16 MACs per k-limb Montgomery multiply.

    CIOS: the product scan and the reduction scan each run k x (k+1)
    limb multiplies -> ~2k^2. The RNS equivalent (one MontMul = two
    base-extension matmuls of shape (rows, k) @ (k, k+1) plus O(k)
    channel ops) prices the same to leading order, so one formula serves
    both routers.
    """
    return 2.0 * k * k


def generic_modexp_macs(rows: int, exp_bits: int, k: int) -> float:
    """Generic windowed (4-bit) kernel: per row, exp_bits squarings +
    exp_bits/4 table muls + ~17 fixed muls (15 table entries, domain
    enter/exit)."""
    montmuls = rows * (exp_bits + exp_bits // 4 + 17)
    return montmuls * montmul_macs(k)


def shared_modexp_macs(
    groups: int, rows_per_group: int, windows: int, k: int
) -> float:
    """Fixed-base comb: accumulation is `windows` MontMuls per row; the
    fly-built 16-entry tables are ~15 products per (window, group); the
    device power ladder is 4 squarings per (window, group)."""
    montmuls = windows * (groups * rows_per_group + 19 * groups)
    return montmuls * montmul_macs(k)


def modmul_macs(rows: int, k: int) -> float:
    """One MontMul per row plus domain enter/exit (~3 total)."""
    return rows * 3 * montmul_macs(k)


# ---------------------------------------------------------------------------
# Host-engine stamping (ISSUE 6 satellite). The device launch layer has
# stamped its analytic MACs since round 2, but the prover / CRT /
# precompute phases run through the HOST engines (GMP, native Montgomery,
# fixed-base combs) and stamped nothing — so their per-phase mfu() read
# 0 and the roofline only described the verify phases. These helpers are
# the host engines' one-line stamp: same u16-MAC pricing (a host limb
# multiply is work-equivalent for the ANALYTIC count; measured MFU stays
# the profiler's job), attributed to the innermost active phase.

def k16(mod_bits: int) -> int:
    """Width in 16-bit limbs — the unit every formula above prices."""
    return max(1, (int(mod_bits) + 15) // 16)


def stamp_generic_host(rows: int, exp_bits: int, mod_bits: int) -> None:
    """Stamp a host generic-modexp batch (GMP / native Montgomery /
    CRT legs): rows x (exp_bits squarings + exp_bits/4 muls)."""
    from .trace import get_tracer

    tr = get_tracer()
    if not tr.enabled or rows <= 0 or exp_bits <= 0:
        return
    tr.add_macs(generic_modexp_macs(rows, exp_bits, k16(mod_bits)))


def stamp_shared_host(
    groups: int, rows_per_group: int, exp_bits: int, mod_bits: int
) -> None:
    """Stamp a host fixed-base comb batch (native modexp_shared / the
    prover's persistent Lim-Lee combs)."""
    from .trace import get_tracer

    tr = get_tracer()
    if not tr.enabled or rows_per_group <= 0 or exp_bits <= 0:
        return
    windows = max(1, exp_bits // 4)
    tr.add_macs(
        shared_modexp_macs(groups, rows_per_group, windows, k16(mod_bits))
    )
