"""Bytes-budgeted LRU for persistent verification precompute.

One process-wide cache holds the precompute that is a pure function of
PUBLIC launch parameters and repeats across `collect()` / `distribute()`
calls of a stable committee: native comb window tables (keyed by base,
modulus, geometry), the device comb's host power ladders, and Montgomery
contexts (keyed by the modulus vector). Steady-state refreshes of the
same committee skip every table build; interleaved sessions with
different committees simply occupy distinct keys — entries are only ever
*read* under full-key equality, so cross-committee contamination is
structurally impossible (pinned by tests/test_cache_isolation.py).

SECURITY invariant (SECURITY.md "persistent precompute cache"): values
stored here must derive ONLY from public bases/moduli and static
geometry. Exponents, shares, nonces, and anything else covered by the
wipe discipline (`wipe_array`/`_wipe_buf`/`secure_wipe`) must never be
inserted; secret-base callers keep the one-shot wiped paths.

Budget: FSDKR_CACHE_BUDGET_MB megabytes (default 512; 0 disables
caching entirely). Overflow evicts least-recently-used entries one at a
time — never the whole cache (the old `_CTX_CACHE.clear()` behavior
flushed hot contexts mid-run). The default doubled in round 8 so a full
n=16 committee's Lim-Lee comb set (4 width classes x 16 receivers at
the widened persistent-table windows, ~370 MB) stays resident across
epochs instead of thrashing.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Any, Dict, Optional

__all__ = ["BudgetLRU", "global_cache", "cache_stats", "clear_caches"]


class BudgetLRU:
    """Thread-safe LRU keyed by hashable tuples, evicting by byte budget.

    Each entry carries the caller's byte estimate; `put` evicts oldest
    entries until the new entry fits. An entry larger than the whole
    budget is simply not cached (callers fall back to building
    per-call). Hit/miss/eviction counters back the bench battery's
    cache-hit assertions.
    """

    def __init__(self, budget_bytes: int):
        self.budget = int(budget_bytes)
        self._d: OrderedDict = OrderedDict()
        self._bytes: Dict[Any, int] = {}
        self._total = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key) -> Optional[Any]:
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                self.hits += 1
                return self._d[key]
            self.misses += 1
            return None

    def peek(self, key) -> Optional[Any]:
        """Presence probe that does NOT count as a hit/miss and does not
        refresh recency — callers that only want to know whether a table
        is already resident (the fold-ladder cache's deferred-build
        heuristic) must not distort the bench battery's hit accounting."""
        with self._lock:
            return self._d.get(key)

    def put(self, key, value, nbytes: int) -> None:
        nbytes = max(1, int(nbytes))
        with self._lock:
            if nbytes > self.budget:
                return  # larger than the whole budget: never cached
            if key in self._d:
                self._total -= self._bytes.pop(key)
                del self._d[key]
            while self._total + nbytes > self.budget and self._d:
                old_key, _ = self._d.popitem(last=False)  # oldest first
                self._total -= self._bytes.pop(old_key)
                self.evictions += 1
            self._d[key] = value
            self._bytes[key] = nbytes
            self._total += nbytes

    def clear(self) -> None:
        with self._lock:
            self._d.clear()
            self._bytes.clear()
            self._total = 0

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._d),
                "bytes": self._total,
                "budget": self.budget,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }


_GLOBAL: Optional[BudgetLRU] = None
_GLOBAL_LOCK = threading.Lock()


def _budget_bytes() -> int:
    try:
        mb = float(os.environ.get("FSDKR_CACHE_BUDGET_MB", "512"))
    except ValueError:
        mb = 512.0
    return int(mb * (1 << 20))


def global_cache() -> BudgetLRU:
    """The process-wide precompute cache (budget read once at first use)."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = BudgetLRU(_budget_bytes())
    return _GLOBAL


def cache_stats() -> Dict[str, int]:
    """Counters of the global precompute cache (zeros before first use)."""
    if _GLOBAL is None:
        return {
            "entries": 0, "bytes": 0, "budget": _budget_bytes(),
            "hits": 0, "misses": 0, "evictions": 0,
        }
    return _GLOBAL.stats()


def clear_caches() -> None:
    """Drop every cached entry (cold-cache A/B runs; tests)."""
    if _GLOBAL is not None:
        _GLOBAL.clear()


def _register_gauges() -> None:
    """Expose the persistent precompute cache's counters as telemetry
    function gauges (read lazily at snapshot time) — the `powm_cache`
    block of the bench JSON reads the same numbers."""
    from ..telemetry import registry

    for field in ("entries", "bytes", "hits", "misses", "evictions"):
        registry.gauge(
            f"fsdkr_powm_cache_{field}",
            f"persistent precompute cache lifetime {field} (utils.lru)",
        ).set_function(lambda f=field: cache_stats()[f])


_register_gauges()
