"""AOT TPU compile-checking without a device.

JAX's ahead-of-time API lowers a jitted function for any platform on the
host: `fn.trace(*args).lower(lowering_platforms=("tpu",))` runs the full
StableHLO pipeline *including the Mosaic Pallas-kernel lowering* and
raises exactly where a real chip compile would. Interpret mode and the
XLA:CPU backend accept programs Mosaic rejects (unsigned<->float casts,
unsigned reductions, ...), so this is the only way to catch that class
in a chipless environment — both round-5 hardware-only compile failures
reproduce under it.

Used by tests/test_tpu_lowering.py (per-kernel audit) and
scripts/preflight_tpu.py (whole-protocol capture sweep before burning
tunnel time on a bench run).

Limits: lowering stops short of the Mosaic backend (register allocation,
VMEM budgeting), so out-of-memory failures still need the chip.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, List, Tuple

import jax

__all__ = [
    "abstractify",
    "lower_for_tpu",
    "jitted_functions",
    "capture_jitted",
]


def abstractify(tree: Any) -> Any:
    """Replace every array-like leaf (incl. live tracers) with a
    ShapeDtypeStruct so captured calls can be re-lowered after the trace
    that produced them is gone."""

    def leaf(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x

    return jax.tree_util.tree_map(leaf, tree)


def lower_for_tpu(fn: Callable, args: Tuple, kwargs: Dict) -> str:
    """AOT-lower one (possibly captured) call for platform `tpu`."""
    kwargs = dict(kwargs)
    # interpret mode bypasses Mosaic entirely; force the real TPU path.
    # pallas_mode follows the same convention (2 = interpret, 1 = real):
    # calls captured on the CPU host carry mode 2 and must be promoted,
    # or the fused path would lower without ever reaching Mosaic.
    if "interpret" in kwargs:
        kwargs["interpret"] = False
    if kwargs.get("pallas_mode") == 2:
        kwargs["pallas_mode"] = 1
    args, kwargs = abstractify((args, kwargs))
    lowered = fn.trace(*args, **kwargs).lower(lowering_platforms=("tpu",))
    return lowered.as_text()


def jitted_functions(module) -> List[str]:
    """Names of module-level jitted callables (the AOT `Wrapped` API)."""
    out = []
    for name, val in vars(module).items():
        if callable(val) and hasattr(val, "trace") and hasattr(val, "lower"):
            out.append(name)
    return sorted(out)


@contextlib.contextmanager
def capture_jitted(modules, into: List):
    """Wrap every jitted function in `modules` with a delegating recorder.

    Each call appends (qualname, fn, abstract_args, abstract_kwargs) to
    `into` — abstracted immediately, so recording calls that happen
    inside an enclosing jit trace (tracer arguments) stays legal after
    that trace ends — then runs the original so the driver proceeds.
    """
    saved = []
    try:
        for module in modules:
            for name in jitted_functions(module):
                orig = getattr(module, name)
                saved.append((module, name, orig))

                def recorder(*args, _orig=orig, _mod=module, _name=name,
                             **kwargs):
                    a, kw = abstractify((args, kwargs))
                    into.append((f"{_mod.__name__}.{_name}", _orig, a, kw))
                    return _orig(*args, **kwargs)

                # the sharded wrappers unwrap the jit to re-wrap it in
                # shard_map (`_modexp_kernel.__wrapped__`); keep that
                # working while the recorder is installed
                recorder.__wrapped__ = getattr(orig, "__wrapped__", orig)
                setattr(module, name, recorder)
        yield
    finally:
        for module, name, orig in saved:
            setattr(module, name, orig)
