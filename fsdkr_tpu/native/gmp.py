"""ctypes bridge to the system GMP — the reference's own bigint backend.

The reference's host bignum layer IS GMP: `/root/reference/Cargo.toml:42-44`
selects curv/kzen-paillier's GMP backend, so every prover modexp of the
original fs-dkr runs through `mpz_powm`. This container ships
`libgmp.so.10`; binding it closes most of the remaining gap between the
rebuild's host path and the reference's (measured on this box, 2048-bit
exponent mod a 4096-bit n^2: own CIOS core 20.9 ms, `mpz_powm` 10.7 ms,
CPython pow 101 ms). The own Montgomery core (csrc/fsdkr_native.cpp)
remains the fallback and the engine for the comb / joint-ladder /
Miller-Rabin shapes GMP has no amortized entry for.

Routing: `FSDKR_GMP` (default on) gates this bridge; `backend.powm`'s
host engine and the secret-CRT legs (backend/crt.py) prefer it when
available. The CRT legs — whose exponents are factorization-derived —
use `mpz_powm_sec` (GMP's constant-time ladder, designed for exactly
this: secret exponents over odd moduli); everything else uses the plain
`mpz_powm` and inherits the documented variable-time host residual
(SECURITY.md).

Wipe discipline: mpz operands created here expose their limb pointer
(`_mp_d`), which is zeroed with memset before `mpz_clear` whenever the
value was secret. GMP's INTERNAL powm scratch cannot be wiped from
outside — a documented residual of the same class as the CIOS core's
inner temporaries (SECURITY.md "known residuals").
"""

from __future__ import annotations

import ctypes
import ctypes.util
import os
import threading
from typing import List, Optional, Sequence

__all__ = [
    "available", "enabled", "powm", "powm_batch", "gcd", "PublicOperand",
]


class _mpz_t(ctypes.Structure):
    # GMP's public __mpz_struct ABI (gmp.h): {int _mp_alloc; int _mp_size;
    # mp_limb_t *_mp_d} with 64-bit limbs on every platform this repo
    # targets (x86-64 / aarch64 glibc).
    _fields_ = [
        ("_mp_alloc", ctypes.c_int),
        ("_mp_size", ctypes.c_int),
        ("_mp_d", ctypes.POINTER(ctypes.c_uint64)),
    ]


_LIB: Optional[ctypes.CDLL] = None
_TRIED = False
_LOCK = threading.Lock()


def enabled() -> bool:
    """FSDKR_GMP gate (default on): =0 removes the GMP route everywhere,
    reverting host modexp to the own native core for A/B isolation and
    for exercising the fallback engines in CI."""
    return os.environ.get("FSDKR_GMP", "1").lower() not in (
        "0", "off", "false", "no",
    )


def _load() -> Optional[ctypes.CDLL]:
    for name in ("gmp", "gmp.10"):
        path = ctypes.util.find_library(name)
        if path:
            try:
                return ctypes.CDLL(path)
            except OSError:
                continue
    for soname in ("libgmp.so.10", "libgmp.so"):
        try:
            return ctypes.CDLL(soname)
        except OSError:
            continue
    return None


def _get() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if not _TRIED:
        with _LOCK:
            if not _TRIED:
                lib = _load()
                if lib is not None:
                    try:
                        P = ctypes.POINTER(_mpz_t)
                        lib.__gmpz_init.argtypes = [P]
                        lib.__gmpz_clear.argtypes = [P]
                        lib.__gmpz_import.argtypes = [
                            P, ctypes.c_size_t, ctypes.c_int, ctypes.c_size_t,
                            ctypes.c_int, ctypes.c_size_t, ctypes.c_void_p,
                        ]
                        lib.__gmpz_export.argtypes = [
                            ctypes.c_void_p, ctypes.POINTER(ctypes.c_size_t),
                            ctypes.c_int, ctypes.c_size_t, ctypes.c_int,
                            ctypes.c_size_t, P,
                        ]
                        lib.__gmpz_export.restype = ctypes.c_void_p
                        lib.__gmpz_powm.argtypes = [P, P, P, P]
                        lib.__gmpz_powm_sec.argtypes = [P, P, P, P]
                        lib.__gmpz_gcd.argtypes = [P, P, P]
                        lib.__gmpz_tdiv_r.argtypes = [P, P, P]
                    except AttributeError:
                        lib = None
                _LIB = lib
                _TRIED = True
    return _LIB


def available() -> bool:
    return enabled() and _get() is not None


def _to_mpz(lib, x: int) -> _mpz_t:
    z = _mpz_t()
    lib.__gmpz_init(ctypes.byref(z))
    nb = (x.bit_length() + 7) // 8 or 1
    buf = bytearray(x.to_bytes(nb, "little"))
    lib.__gmpz_import(
        ctypes.byref(z), nb, -1, 1, 0, 0,
        (ctypes.c_char * nb).from_buffer(buf),
    )
    buf[:] = bytes(nb)  # wipe the staging copy in place
    return z


def _from_mpz(lib, z: _mpz_t) -> int:
    size = abs(z._mp_size)
    if size == 0:
        return 0
    buf = ctypes.create_string_buffer(size * 8)
    cnt = ctypes.c_size_t()
    lib.__gmpz_export(buf, ctypes.byref(cnt), -1, 1, 0, 0, ctypes.byref(z))
    out = int.from_bytes(buf.raw[: cnt.value], "little")
    ctypes.memset(buf, 0, len(buf))
    return out


def _clear(lib, *zs: _mpz_t) -> None:
    """Zero the mpz limb storage (the only heap copy GMP lets us reach),
    then free it — the bridge leg of the wipe discipline."""
    for z in zs:
        if z._mp_d and z._mp_alloc > 0:
            ctypes.memset(z._mp_d, 0, z._mp_alloc * 8)
        lib.__gmpz_clear(ctypes.byref(z))


def powm(base: int, exp: int, mod: int, secret: bool = False) -> int:
    """base^exp mod mod via mpz_powm (secret=True: mpz_powm_sec, GMP's
    constant-time ladder — requires exp > 0 and mod odd, which every
    secret-CRT leg satisfies; other shapes silently take the plain
    route). Falls back to CPython pow when GMP is unavailable, the
    exponent is negative (mpz_powm raises a process-fatal divide-by-zero
    on non-invertible bases — pow's ValueError is the contract callers
    expect), or the modulus is out of domain."""
    lib = _get() if enabled() else None
    if lib is None or exp < 0 or mod <= 0:
        return pow(base, exp, mod)
    zb = _to_mpz(lib, base % mod)
    ze = _to_mpz(lib, exp)
    zm = _to_mpz(lib, mod)
    zr = _to_mpz(lib, 0)
    if secret and exp > 0 and mod % 2 == 1:
        lib.__gmpz_powm_sec(
            ctypes.byref(zr), ctypes.byref(zb), ctypes.byref(ze),
            ctypes.byref(zm),
        )
    else:
        lib.__gmpz_powm(
            ctypes.byref(zr), ctypes.byref(zb), ctypes.byref(ze),
            ctypes.byref(zm),
        )
    res = _from_mpz(lib, zr)
    _clear(lib, zb, ze, zm, zr)
    return res


def powm_batch(
    bases: Sequence[int],
    exps: Sequence[int],
    mods: Sequence[int],
    secret: bool = False,
) -> List[int]:
    """Row-wise bases^exps mod mods through mpz_powm(_sec). ctypes
    releases the GIL around each GMP call, so rows split across a Python
    thread pool sized by FSDKR_THREADS (0/auto = cores) — the same knob
    and bit-identity contract as the native row pool (rows are
    independent; per-row math is untouched by the split)."""
    if not bases:
        return []
    if not (len(bases) == len(exps) == len(mods)):
        raise ValueError("batch length mismatch")
    lib = _get() if enabled() else None
    if lib is None:
        return [pow(b, e, m) for b, e, m in zip(bases, exps, mods)]
    rows = len(bases)
    nt = _pool_threads()
    if nt > 1 and rows > 1:
        from concurrent.futures import ThreadPoolExecutor

        nt = min(nt, rows)
        spans = [
            (i * rows // nt, (i + 1) * rows // nt) for i in range(nt)
        ]
        with ThreadPoolExecutor(max_workers=nt) as ex:
            parts = list(
                ex.map(
                    lambda s: [
                        powm(bases[i], exps[i], mods[i], secret)
                        for i in range(s[0], s[1])
                    ],
                    spans,
                )
            )
        return [v for part in parts for v in part]
    return [powm(b, e, m, secret) for b, e, m in zip(bases, exps, mods)]


class PublicOperand:
    """A PUBLIC integer imported into mpz form once and reused across
    calls (the prime sieve's ~94kbit primorial would otherwise pay a
    ~12 KB import per gcd). Only for public values: the held limbs are
    never wiped."""

    def __init__(self, x: int):
        self.value = abs(x)
        self._z: Optional[_mpz_t] = None

    def _mpz(self, lib) -> _mpz_t:
        if self._z is None:
            self._z = _to_mpz(lib, self.value)
        return self._z


def gcd(a: int, b) -> int:
    """gcd via mpz_gcd (GMP's subquadratic HGCD — CPython's Euclid costs
    ~0.2 ms against the prime-generation sieve's primorial, GMP ~0.02 ms
    once the big public operand is cached as a PublicOperand). Secret
    operand limbs are wiped before free (prime candidates are secret)."""
    lib = _get() if enabled() else None
    if lib is None:
        import math

        return math.gcd(a, b.value if isinstance(b, PublicOperand) else b)
    za = _to_mpz(lib, abs(a))
    zr = _to_mpz(lib, 0)
    if isinstance(b, PublicOperand):
        # fold the big cached operand down to |a| first with one GMP
        # division (mpz_gcd's own first step, but without its per-call
        # working copy of the 94kbit operand), then gcd the small pair:
        # ~3x the straight mpz_gcd at the sieve shape
        zb = b._mpz(lib)
        lib.__gmpz_tdiv_r(ctypes.byref(zr), ctypes.byref(zb), ctypes.byref(za))
        lib.__gmpz_gcd(ctypes.byref(zr), ctypes.byref(za), ctypes.byref(zr))
        res = _from_mpz(lib, zr)
        _clear(lib, za, zr)  # zb is cached and public: not cleared
        return res
    zb = _to_mpz(lib, abs(b))
    lib.__gmpz_gcd(ctypes.byref(zr), ctypes.byref(za), ctypes.byref(zb))
    res = _from_mpz(lib, zr)
    _clear(lib, za, zb, zr)
    return res


def _pool_threads() -> int:
    val = os.environ.get("FSDKR_THREADS", "0").strip().lower() or "0"
    try:
        n = int(val)
    except ValueError:
        n = 0  # auto
    if n <= 0:
        n = os.cpu_count() or 1
    return n
