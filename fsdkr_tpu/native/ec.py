"""ctypes bridge to the native secp256k1 host core (csrc/fsdkr_ec.cpp).

The reference's EC layer is curv's pure-Rust secp256k1; the rebuild's
Python Jacobian oracle (fsdkr_tpu/core/secp256k1.py) carries the
semantics, and this module is the same math in C++ for the host-routed
verification paths, where interpreter overhead dominates (a t=128
Feldman check costs ~26 ms in Python, ~95% of it interpreter work).
Check sites served: `/root/reference/src/refresh_message.rs:177-188`
(Feldman), `/root/reference/src/zk_pdl_with_slack.rs:124-127` (PDL u1).

Same build discipline as the bignum core: compiled on first use with
g++, hash-tagged .so cached next to this file, every entry point
degrades to pure Python when the toolchain is unavailable, and
FSDKR_NATIVE_EC=0 disables the whole module. Inputs here are public
broadcast values (commitments, proof points, indices), so no wipe
discipline applies; arithmetic is variable-time, matching the Python
oracle it replaces.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Sequence, Tuple

from . import _loader

__all__ = [
    "available",
    "horner_batch",
    "scalar_mul_batch",
    "lincomb2_batch",
]

Affine = Optional[Tuple[int, int]]  # None = point at infinity

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc", "fsdkr_ec.cpp")

_LIB = _loader.get_lib(
    os.path.abspath(_SRC),
    "_fsdkr_ec",
    (
        "fsdkr_ec_horner_batch",
        "fsdkr_ec_scalar_mul_batch",
        "fsdkr_ec_lincomb2_batch",
        "fsdkr_ec_set_threads",
    ),
    env_var="FSDKR_NATIVE_EC",
    thread_symbol="fsdkr_ec_set_threads",
)


def _get() -> Optional[ctypes.CDLL]:
    # every entry point is a batch over independent rows: sync the
    # FSDKR_THREADS row pool alongside the lazy load
    lib = _LIB.get()
    if lib is not None:
        _LIB.sync_threads()
    return lib


def available() -> bool:
    return _LIB.available()


def _points_buf(points: Sequence[Affine]) -> ctypes.Array:
    """(x, y) pairs as 8 LE u64 limbs each; None -> (0, 0) identity."""
    buf = bytearray(len(points) * 64)
    for i, pt in enumerate(points):
        if pt is not None:
            x, y = pt
            buf[i * 64 : i * 64 + 32] = x.to_bytes(32, "little")
            buf[i * 64 + 32 : i * 64 + 64] = y.to_bytes(32, "little")
    return (ctypes.c_uint64 * (len(points) * 8)).from_buffer_copy(buf)


def _scalars_buf(scalars: Sequence[int]) -> Optional[ctypes.Array]:
    """32-byte LE scalar staging. Returns None for any scalar outside
    [0, 2^256) instead of raising OverflowError mid-batch: callers fall
    back to the Python oracle, which owns the reduction/rejection
    semantics for out-of-range values."""
    if any(not (0 <= s < (1 << 256)) for s in scalars):
        return None
    buf = bytearray(len(scalars) * 32)
    for i, s in enumerate(scalars):
        buf[i * 32 : (i + 1) * 32] = s.to_bytes(32, "little")
    return (ctypes.c_uint64 * (len(scalars) * 4)).from_buffer_copy(buf)


def _read_points(out: ctypes.Array, n: int) -> List[Affine]:
    mv = memoryview(bytearray(out))
    res: List[Affine] = []
    for i in range(n):
        x = int.from_bytes(mv[i * 64 : i * 64 + 32], "little")
        y = int.from_bytes(mv[i * 64 + 32 : i * 64 + 64], "little")
        res.append(None if x == 0 and y == 0 else (x, y))
    return res


def horner_batch(
    commitments: Sequence[Affine], indices: Sequence[int]
) -> Optional[List[Affine]]:
    """[sum_k A_k * u^k for u in indices] — the Feldman evaluation.
    Returns None when the native core is unavailable (caller falls back
    to the Python oracle)."""
    lib = _get()
    if lib is None or not commitments or not indices:
        return None
    if any(not (0 <= u < (1 << 32)) for u in indices):
        return None
    commits = _points_buf(commitments)
    idx = (ctypes.c_uint32 * len(indices))(*indices)
    out = (ctypes.c_uint64 * (len(indices) * 8))()
    rc = lib.fsdkr_ec_horner_batch(
        commits, len(commitments), idx, len(indices), out
    )
    if rc != 0:
        return None
    return _read_points(out, len(indices))


def scalar_mul_batch(
    points: Sequence[Affine], scalars: Sequence[int]
) -> Optional[List[Affine]]:
    """[s_i * P_i]; scalars must be reduced mod the group order. A
    length mismatch or out-of-range scalar returns None (Python oracle
    fallback) — the C core reads exactly len(points) rows from both
    buffers, so a short scalar buffer would be an out-of-bounds read and
    silently wrong verdicts, never an exception."""
    lib = _get()
    if lib is None or not points or len(scalars) != len(points):
        return None
    pts = _points_buf(points)
    sc = _scalars_buf(scalars)
    if sc is None:
        return None
    out = (ctypes.c_uint64 * (len(points) * 8))()
    rc = lib.fsdkr_ec_scalar_mul_batch(pts, sc, len(points), out)
    if rc != 0:
        return None
    return _read_points(out, len(points))


def lincomb2_batch(
    P: Sequence[Affine],
    a: Sequence[int],
    Q: Sequence[Affine],
    b: Sequence[int],
) -> Optional[List[Affine]]:
    """[a_i*P_i + b_i*Q_i] — the PDL u1 shape. Scalars reduced mod q.
    All four sequences must match len(P); mismatches and out-of-range
    scalars return None (see scalar_mul_batch: the C core trusts the
    row count, so a short buffer is an out-of-bounds read)."""
    lib = _get()
    if lib is None or not P:
        return None
    if not (len(a) == len(b) == len(Q) == len(P)):
        return None
    a_buf = _scalars_buf(a)
    b_buf = _scalars_buf(b)
    if a_buf is None or b_buf is None:
        return None
    rc_out = (ctypes.c_uint64 * (len(P) * 8))()
    rc = lib.fsdkr_ec_lincomb2_batch(
        _points_buf(P), a_buf, _points_buf(Q), b_buf, len(P), rc_out,
    )
    if rc != 0:
        return None
    return _read_points(rc_out, len(P))
