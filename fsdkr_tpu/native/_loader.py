"""Shared build/cache/load machinery for the native C++ cores.

Both ctypes bridges (the bignum core in __init__.py, the secp256k1 core
in ec.py) compile their single source file on first use with g++, cache
the .so next to this package tagged by source hash + machine arch (a
stale or cross-arch artifact can never be picked up), prune artifacts
from older revisions, and degrade to pure Python when anything fails.
One implementation here so compile flags and race handling cannot
drift between the cores.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import platform
import subprocess
import tempfile
import threading
from typing import Dict, Optional, Sequence


def _cpu_feature_tag() -> str:
    """Fingerprint of this host's CPU feature set, folded into the .so
    cache filename. The artifacts are compiled with -march=native, and
    VM instances of this environment share checkouts across hosts whose
    CPUs differ slightly: an .so built under one feature set can SIGILL
    under another — `platform.machine()` alone cannot see that. Same
    discipline as the XLA compilation cache (bench.py _host_cpu_tag).
    """
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    return hashlib.sha256(feats.encode()).hexdigest()[:10]
    except OSError:
        pass
    return "nofeat"


class NativeLib:
    """Lazy, thread-safe loader for one C++ source file.

    src: absolute path to the .cpp; prefix: cached-.so name prefix
    (also the prune pattern); symbols: exported function names, each
    given restype c_int; env_var: optional kill switch (value in
    {0, off, false, no} disables the build entirely).
    """

    def __init__(
        self,
        src: str,
        prefix: str,
        symbols: Sequence[str],
        env_var: Optional[str] = None,
        thread_symbol: Optional[str] = None,
        mpn_symbol: Optional[str] = None,
    ):
        self._src = src
        self._prefix = prefix
        self._symbols = list(symbols)
        self._env_var = env_var
        self._thread_symbol = thread_symbol
        self._mpn_symbol = mpn_symbol
        self._applied_threads: Optional[str] = None
        self._applied_mpn: Optional[str] = None
        self._lib: Optional[ctypes.CDLL] = None
        self._tried = False
        self._lock = threading.Lock()

    def _so_path(self) -> str:
        with open(self._src, "rb") as f:
            tag = hashlib.sha256(f.read()).hexdigest()[:16]
        return os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            f"{self._prefix}_{tag}_{platform.machine()}_{_cpu_feature_tag()}.so",
        )

    def _build(self) -> Optional[ctypes.CDLL]:
        if self._env_var and os.environ.get(self._env_var, "1") in (
            "0", "off", "false", "no",
        ):
            return None
        src = os.path.abspath(self._src)
        if not os.path.exists(src):
            return None
        so = self._so_path()
        if not os.path.exists(so):
            fd, tmp = tempfile.mkstemp(
                suffix=".so", prefix="_fsdkr_build_", dir=os.path.dirname(so)
            )
            os.close(fd)
            # -pthread is load-bearing on glibc < 2.34 (this image ships
            # 2.31): std::thread in a dlopened .so without it aborts at
            # the first spawn instead of failing the link. -ldl likewise:
            # the bignum core resolves the optional GMP mpn backend with
            # dlopen at runtime (csrc/fsdkr_native.cpp, FSDKR_MPN), and
            # pre-2.34 glibc keeps dlopen in libdl.
            cmd = [
                "g++", "-O3", "-march=native", "-shared", "-fPIC",
                "-pthread", "-o", tmp, src, "-ldl",
            ]
            try:
                subprocess.run(cmd, check=True, capture_output=True, timeout=120)
                os.replace(tmp, so)
            except (subprocess.SubprocessError, OSError):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return None
            here = os.path.dirname(so)
            for name in os.listdir(here):
                if name.startswith(self._prefix) and name.endswith(".so"):
                    path = os.path.join(here, name)
                    if path != so:
                        try:
                            os.unlink(path)
                        except OSError:
                            pass
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            return None
        for sym in self._symbols:
            getattr(lib, sym).restype = ctypes.c_int
        return lib

    def get(self) -> Optional[ctypes.CDLL]:
        if not self._tried:
            with self._lock:
                if not self._tried:
                    # fsdkr-lint: allow(lock-blocking-call) one-time double-checked build: racers SHOULD wait for the single compile
                    self._lib = self._build()
                    self._tried = True
        return self._lib

    def available(self) -> bool:
        return self.get() is not None

    def sync_threads(self) -> None:
        """Apply FSDKR_THREADS to the core's row-parallel batch loops
        (0/auto = all cores, 1 = serial; results are bit-identical at
        any setting — see parallel_rows in the C++ sources). Read at
        call time so the bench battery can toggle it per step; a benign
        read/apply race just re-applies the same value."""
        if self._thread_symbol is None:
            return
        lib = self.get()
        if lib is None:
            return
        val = os.environ.get("FSDKR_THREADS", "0").strip().lower() or "0"
        if val != self._applied_threads:
            try:
                n = int(val)
            except ValueError:
                n = 0  # "auto" (or anything unparseable) -> all cores
            getattr(lib, self._thread_symbol)(n)
            self._applied_threads = val
        if self._mpn_symbol is not None:
            # FSDKR_MPN: auto (default) resolves the GMP mpn inner loop
            # when libgmp is present, 0 forces the portable u128 core —
            # a pure-speed A/B, results bit-identical (csrc dispatch)
            mval = os.environ.get("FSDKR_MPN", "auto").strip().lower() or "auto"
            if mval != self._applied_mpn:
                getattr(lib, self._mpn_symbol)(
                    0 if mval in ("0", "off", "false", "no") else -1
                )
                self._applied_mpn = mval


_REGISTRY: Dict[str, NativeLib] = {}


def get_lib(
    src: str,
    prefix: str,
    symbols: Sequence[str],
    env_var: Optional[str] = None,
    thread_symbol: Optional[str] = None,
    mpn_symbol: Optional[str] = None,
) -> NativeLib:
    """Process-wide NativeLib per prefix (so repeated imports share one
    build attempt)."""
    if prefix not in _REGISTRY:
        _REGISTRY[prefix] = NativeLib(
            src, prefix, symbols, env_var, thread_symbol, mpn_symbol
        )
    return _REGISTRY[prefix]
