"""ctypes bridge to the native host bignum core (csrc/fsdkr_native.cpp).

The reference's host-serial native layer is GMP under curv/kzen-paillier
(`/root/reference/Cargo.toml:42-44` selects the GMP backend by default);
this module is the rebuild's equivalent for the paths that stay on the
host: Miller-Rabin prime generation, the comb kernel's power ladder, and
the host-backend modexp oracle. The shared object is compiled on first
use with g++ (no pybind11 in this environment — plain C ABI + ctypes) and
cached next to this file; every entry point degrades to the pure-Python
implementation when the toolchain is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import secrets
from typing import List, Optional, Sequence

from . import _loader

__all__ = [
    "available",
    "modexp",
    "modexp_batch",
    "modexp_shared",
    "is_probable_prime",
]

_LIMB_BYTES = 8
_MAX_LIMBS = 64  # 4096 bits, keep in sync with MAXL in csrc
_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc", "fsdkr_native.cpp")

_LIB = _loader.get_lib(
    os.path.abspath(_SRC),
    "_fsdkr_native",
    ("fsdkr_modexp", "fsdkr_modexp_batch", "fsdkr_modexp_shared",
     "fsdkr_miller_rabin"),
)


def _get() -> Optional[ctypes.CDLL]:
    return _LIB.get()


def available() -> bool:
    return _LIB.available()


def _limbs_for(x: int) -> int:
    return max(1, -(-x.bit_length() // 64))


def _to_buf(xs: Sequence[int], limbs: int) -> ctypes.Array:
    """Limb staging for the C ABI. The bytearray is wiped in place before
    returning (no immutable `bytes` copy is ever made), so the only
    surviving host copy of a secret operand is the returned ctypes array —
    which callers wipe with _wipe_buf after the native call."""
    step = limbs * _LIMB_BYTES
    buf = bytearray(len(xs) * step)
    for row, x in enumerate(xs):
        buf[row * step : (row + 1) * step] = x.to_bytes(step, "little")
    arr = (ctypes.c_uint64 * (len(xs) * limbs)).from_buffer_copy(buf)
    buf[:] = bytes(len(buf))
    return arr


def _wipe_buf(*arrays) -> None:
    """Zero ctypes limb buffers that held secret operands (exponents,
    prime candidates, secret bases) once the native call returns — the
    host-bridge leg of the zeroize discipline (SECURITY.md)."""
    for a in arrays:
        ctypes.memset(a, 0, ctypes.sizeof(a))


def _from_buf(buf, rows: int, limbs: int) -> List[int]:
    """Read results without an immutable `bytes` copy: int.from_bytes
    accepts memoryview slices directly, so the only surviving host copies
    of a secret result are the returned Python ints (a documented
    residual — see SECURITY.md) and `buf` itself, which callers wipe."""
    mv = memoryview(buf).cast("B")
    step = limbs * _LIMB_BYTES
    return [
        int.from_bytes(mv[i * step : (i + 1) * step], "little")
        for i in range(rows)
    ]


def modexp(base: int, exp: int, mod: int) -> int:
    """base^exp mod mod via the native Montgomery core; CPython pow when
    the native library is unavailable or the modulus is out of range."""
    lib = _get()
    L = _limbs_for(mod)
    if lib is None or L > _MAX_LIMBS or mod % 2 == 0 or mod <= 1:
        return pow(base, exp, mod)
    EL = max(1, _limbs_for(exp))
    out = (ctypes.c_uint64 * L)()
    base_buf = _to_buf([base % mod], L)
    exp_buf = _to_buf([exp], EL)
    # the modulus and result are secret too on the Paillier-decrypt path
    # (mod = p^2; gcd(out - 1, N) = p), so all four buffers are wiped
    mod_buf = _to_buf([mod], L)
    rc = lib.fsdkr_modexp(base_buf, exp_buf, mod_buf, out, L, EL)
    if rc != 0:
        _wipe_buf(base_buf, exp_buf, mod_buf, out)
        return pow(base, exp, mod)
    res = _from_buf(out, 1, L)[0]
    _wipe_buf(base_buf, exp_buf, mod_buf, out)
    return res


def modexp_batch(
    bases: Sequence[int], exps: Sequence[int], mods: Sequence[int]
) -> List[int]:
    """Row-wise bases^exps mod mods. Rows are padded to the widest modulus
    and exponent in the batch; even/oversized-modulus rows fall back to
    CPython pow row-wise."""
    if not bases:
        return []
    if not (len(bases) == len(exps) == len(mods)):
        raise ValueError("batch length mismatch")
    lib = _get()
    L = max(_limbs_for(m) for m in mods)
    if (
        lib is None
        or L > _MAX_LIMBS
        or any(m % 2 == 0 or m <= 1 for m in mods)
    ):
        return [pow(b, e, m) for b, e, m in zip(bases, exps, mods)]
    EL = max(1, max(_limbs_for(e) for e in exps))
    rows = len(bases)
    out = (ctypes.c_uint64 * (rows * L))()
    base_buf = _to_buf([b % m for b, m in zip(bases, mods)], L)
    exp_buf = _to_buf(list(exps), EL)
    mod_buf = _to_buf(list(mods), L)
    rc = lib.fsdkr_modexp_batch(base_buf, exp_buf, mod_buf, out, rows, L, EL)
    if rc != 0:
        # rows before the failing one have already written results
        _wipe_buf(base_buf, exp_buf, mod_buf, out)
        return [pow(b, e, m) for b, e, m in zip(bases, exps, mods)]
    res = _from_buf(out, rows, L)
    _wipe_buf(base_buf, exp_buf, mod_buf, out)
    return res


def modexp_shared(
    base: int, exps: Sequence[int], mod: int
) -> List[int]:
    """base^exps[i] mod mod via the fixed-base comb — the shared-base
    column shape of the verify loop (one squaring ladder amortized over
    the whole group). Falls back to CPython pow when native is
    unavailable or the modulus is even/oversized."""
    if not exps:
        return []
    lib = _get()
    L = _limbs_for(mod)
    if lib is None or L > _MAX_LIMBS or mod % 2 == 0 or mod <= 1:
        return [pow(base, e, mod) for e in exps]
    EL = max(1, max(_limbs_for(e) for e in exps))
    if EL > 2 * _MAX_LIMBS:  # comb table would be attacker-sized
        return [pow(base, e, mod) for e in exps]
    m_rows = len(exps)
    out = (ctypes.c_uint64 * (m_rows * L))()
    base_buf = _to_buf([base % mod], L)
    exp_buf = _to_buf(list(exps), EL)
    mod_buf = _to_buf([mod], L)
    rc = lib.fsdkr_modexp_shared(base_buf, exp_buf, mod_buf, out, m_rows, L, EL)
    if rc != 0:
        _wipe_buf(base_buf, exp_buf, mod_buf, out)
        return [pow(base, e, mod) for e in exps]
    res = _from_buf(out, m_rows, L)
    _wipe_buf(base_buf, exp_buf, mod_buf, out)
    return res


def is_probable_prime(n: int, rounds: int = 30) -> Optional[bool]:
    """Miller-Rabin with CSPRNG witnesses, native squaring loop. Returns
    None when the native path cannot handle the input (caller falls back
    to the Python implementation)."""
    lib = _get()
    L = _limbs_for(n)
    if lib is None or L > _MAX_LIMBS or n < 5 or n % 2 == 0:
        return None
    witnesses = [2 + secrets.randbelow(n - 3) for _ in range(rounds)]
    n_buf = _to_buf([n], L)  # prime candidate: secret key material
    rc = lib.fsdkr_miller_rabin(n_buf, L, _to_buf(witnesses, L), rounds)
    _wipe_buf(n_buf)
    if rc < 0:
        return None
    return bool(rc)
