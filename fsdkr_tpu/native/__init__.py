"""ctypes bridge to the native host bignum core (csrc/fsdkr_native.cpp).

The reference's host-serial native layer is GMP under curv/kzen-paillier
(`/root/reference/Cargo.toml:42-44` selects the GMP backend by default);
this module is the rebuild's equivalent for the paths that stay on the
host: Miller-Rabin prime generation, the comb kernel's power ladder, and
the host-backend modexp oracle. The shared object is compiled on first
use with g++ (no pybind11 in this environment — plain C ABI + ctypes) and
cached next to this file; every entry point degrades to the pure-Python
implementation when the toolchain is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import secrets
from typing import List, Optional, Sequence

from . import _loader

__all__ = [
    "available",
    "modexp",
    "modexp_batch",
    "modexp_shared",
    "shared_exp_powm",
    "comb2_apply",
    "multi_modexp_batch",
    "modmul_batch",
    "crt_modexp_batch",
    "is_probable_prime",
    "is_probable_prime_batch",
    "widen_limbs",
    "narrow_limbs",
    "thread_count",
    "engine_kind",
]

_LIMB_BYTES = 8
_MAX_LIMBS = 64  # 4096 bits, keep in sync with MAXL in csrc
_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc", "fsdkr_native.cpp")

_LIB = _loader.get_lib(
    os.path.abspath(_SRC),
    "_fsdkr_native",
    ("fsdkr_modexp", "fsdkr_modexp_w", "fsdkr_modexp_batch",
     "fsdkr_modexp_batch_w", "fsdkr_modexp_shared", "fsdkr_modexp_shared_w",
     "fsdkr_shared_exp_powm", "fsdkr_comb2_apply",
     "fsdkr_multi_modexp_batch", "fsdkr_miller_rabin",
     "fsdkr_miller_rabin_batch", "fsdkr_modmul_batch",
     "fsdkr_crt_modexp_batch",
     "fsdkr_comb_table_words", "fsdkr_comb_precompute", "fsdkr_comb_apply",
     "fsdkr_limbs_widen_u16", "fsdkr_limbs_narrow_u16",
     "fsdkr_set_threads", "fsdkr_get_threads",
     "fsdkr_set_mpn", "fsdkr_engine_kind"),
    thread_symbol="fsdkr_set_threads",
    mpn_symbol="fsdkr_set_mpn",
)


def thread_count() -> int:
    """The row-parallel thread count the native cores will use (after
    FSDKR_THREADS resolution; 1 when the library is unavailable)."""
    lib = _get()
    if lib is None:
        return 1
    _LIB.sync_threads()
    return int(lib.fsdkr_get_threads())


def engine_kind() -> str:
    """Active Montgomery inner loop of the native core after FSDKR_MPN
    resolution: "mpn" (GMP asm basecase via dlopen — ~2.4x the portable
    loop at 64 limbs), "portable" (the own u128 CIOS/SOS core), or
    "none" (library unavailable). Results are bit-identical across all
    three — this is bench/telemetry provenance, not a semantic switch."""
    lib = _get()
    if lib is None:
        return "none"
    _LIB.sync_threads()
    return "mpn" if int(lib.fsdkr_engine_kind()) else "portable"


def _tile_rows() -> int:
    """Row-tile size for the pipelined batch entry points (0 disables
    tiling). Staging of tile k+1 (the Python-side bigint -> limb packing)
    overlaps the GIL-released native execution of tile k."""
    try:
        return int(os.environ.get("FSDKR_TILE_ROWS", "512"))
    except ValueError:
        return 512


def _gen_window_bits(total_exp_bits: int, terms: int = 1) -> int:
    """Window width for the generic/joint windowed ladders: lookups cost
    total_exp_bits/w, the per-term tables 2^w - 2 multiplies each. w=6
    wins for full-width exponents, w=4 for short challenge columns."""
    best, best_cost = 4, None
    for w in (4, 5, 6):
        cost = total_exp_bits / w + terms * ((1 << w) - 2)
        if best_cost is None or cost < best_cost:
            best, best_cost = w, cost
    return best


def _gen_window_bits_terms(ebits: Sequence[int]) -> int:
    """Width-adaptive window for a joint row with heterogeneous term
    widths (the RLC aggregated rows: n short 128-384-bit terms, and a
    ~168-bit shared chain): per-term lookups cost ceil(ebits_t / w),
    the per-term tables 2^w - 2 multiplies each, and the shared
    squaring chain (max ebits_t) is w-independent — so many short terms
    push the optimum down to w=4 even when the summed width alone would
    pick w=6."""
    best, best_cost = 4, None
    for w in (4, 5, 6):
        cost = sum(-(-eb // w) for eb in ebits) + len(ebits) * ((1 << w) - 2)
        if best_cost is None or cost < best_cost:
            best, best_cost = w, cost
    return best


def _get() -> Optional[ctypes.CDLL]:
    return _LIB.get()


def available() -> bool:
    return _LIB.available()


def _limbs_for(x: int) -> int:
    return max(1, -(-x.bit_length() // 64))


def _to_buf(xs: Sequence[int], limbs: int) -> ctypes.Array:
    """Limb staging for the C ABI. The bytearray is wiped in place before
    returning (no immutable `bytes` copy is ever made), so the only
    surviving host copy of a secret operand is the returned ctypes array —
    which callers wipe with _wipe_buf after the native call."""
    step = limbs * _LIMB_BYTES
    buf = bytearray(len(xs) * step)
    for row, x in enumerate(xs):
        buf[row * step : (row + 1) * step] = x.to_bytes(step, "little")
    arr = (ctypes.c_uint64 * (len(xs) * limbs)).from_buffer_copy(buf)
    buf[:] = bytes(len(buf))
    return arr


def _wipe_buf(*arrays) -> None:
    """Zero ctypes limb buffers that held secret operands (exponents,
    prime candidates, secret bases) once the native call returns — the
    host-bridge leg of the zeroize discipline (SECURITY.md)."""
    for a in arrays:
        ctypes.memset(a, 0, ctypes.sizeof(a))


def _from_buf(buf, rows: int, limbs: int) -> List[int]:
    """Read results without an immutable `bytes` copy: int.from_bytes
    accepts memoryview slices directly, so the only surviving host copies
    of a secret result are the returned Python ints (a documented
    residual — see SECURITY.md) and `buf` itself, which callers wipe."""
    mv = memoryview(buf).cast("B")
    step = limbs * _LIMB_BYTES
    return [
        int.from_bytes(mv[i * step : (i + 1) * step], "little")
        for i in range(rows)
    ]


def modexp(base: int, exp: int, mod: int) -> int:
    """base^exp mod mod via the native Montgomery core; CPython pow when
    the native library is unavailable or the modulus is out of range."""
    lib = _get()
    L = _limbs_for(mod)
    if lib is None or L > _MAX_LIMBS or mod % 2 == 0 or mod <= 1:
        return pow(base, exp, mod)
    EL = max(1, _limbs_for(exp))
    out = (ctypes.c_uint64 * L)()
    base_buf = _to_buf([base % mod], L)
    exp_buf = _to_buf([exp], EL)
    # the modulus and result are secret too on the Paillier-decrypt path
    # (mod = p^2; gcd(out - 1, N) = p), so all four buffers are wiped
    mod_buf = _to_buf([mod], L)
    rc = lib.fsdkr_modexp_w(
        base_buf, exp_buf, mod_buf, out, L, EL,
        _gen_window_bits(exp.bit_length()),
    )
    if rc != 0:
        _wipe_buf(base_buf, exp_buf, mod_buf, out)
        return pow(base, exp, mod)
    res = _from_buf(out, 1, L)[0]
    _wipe_buf(base_buf, exp_buf, mod_buf, out)
    return res


def modexp_batch(
    bases: Sequence[int], exps: Sequence[int], mods: Sequence[int]
) -> List[int]:
    """Row-wise bases^exps mod mods. Rows are padded to the widest modulus
    and exponent in the batch; even/oversized-modulus rows fall back to
    CPython pow row-wise. Large batches split into FSDKR_TILE_ROWS tiles
    run through the double-buffered pipeline: tile k+1's limb staging
    overlaps tile k's (GIL-released) native execution, and each tile's
    rows additionally split across the FSDKR_THREADS row pool."""
    if not bases:
        return []
    if not (len(bases) == len(exps) == len(mods)):
        raise ValueError("batch length mismatch")
    rows = len(bases)
    tile = _tile_rows()
    if tile > 0 and rows > tile:
        from ..utils.pipeline import pipelined

        bases, exps, mods = list(bases), list(exps), list(mods)
        spans = [(lo, min(lo + tile, rows)) for lo in range(0, rows, tile)]
        parts = pipelined(
            lambda lo, hi: modexp_batch(bases[lo:hi], exps[lo:hi], mods[lo:hi]),
            spans,
        )
        return [v for part in parts for v in part]
    lib = _get()
    _LIB.sync_threads()
    L = max(_limbs_for(m) for m in mods)
    if (
        lib is None
        or L > _MAX_LIMBS
        or any(m % 2 == 0 or m <= 1 for m in mods)
    ):
        return [pow(b, e, m) for b, e, m in zip(bases, exps, mods)]
    EL = max(1, max(_limbs_for(e) for e in exps))
    rows = len(bases)
    out = (ctypes.c_uint64 * (rows * L))()
    base_buf = _to_buf([b % m for b, m in zip(bases, mods)], L)
    exp_buf = _to_buf(list(exps), EL)
    mod_buf = _to_buf(list(mods), L)
    rc = lib.fsdkr_modexp_batch_w(
        base_buf, exp_buf, mod_buf, out, rows, L, EL,
        _gen_window_bits(max(e.bit_length() for e in exps)),
    )
    if rc != 0:
        # rows before the failing one have already written results
        _wipe_buf(base_buf, exp_buf, mod_buf, out)
        return [pow(b, e, m) for b, e, m in zip(bases, exps, mods)]
    res = _from_buf(out, rows, L)
    _wipe_buf(base_buf, exp_buf, mod_buf, out)
    return res


def _comb_window_bits(ebits: int, m_rows: int) -> int:
    """Comb window width minimizing per-row cost: lookups shrink as
    ebits/w while the per-group table build ((2^w - 2 per window,
    amortized over the group's rows) grows exponentially in w. At the
    ring-Pedersen shape (M=256 rows, 2048-bit exponents) w=6 beats w=4
    by ~22%; small pair groups (M~n) stay at w=4."""
    best, best_cost = 4, None
    for w in (4, 5, 6, 7, 8):
        cost = (ebits / w) * (1.0 + ((1 << w) - 2) / m_rows)
        if best_cost is None or cost < best_cost:
            best, best_cost = w, cost
    return best


def _comb_window_bits_cached(
    ebits: int, m_rows: int, L: int, budget: int, reuse: int = 4
) -> int:
    """Lim-Lee-style width for PERSISTENT comb tables: when the table
    lives in the bytes-budgeted LRU it is keyed by committee state
    (h1/h2, N~) and survives across epochs — proactive refresh re-runs
    on the same committee — so the build amortizes over epochs, not just
    this call's rows. The width therefore optimizes apply cost with the
    build discounted by an expected-reuse factor (`reuse`, conservative
    default 4; the comb2 fused-apply caller passes a higher one — its
    tables back every warm verify_pairs of a stable committee), subject
    to a per-table byte cap that keeps a full committee's table set
    (~3-4 tables per receiver: one per exponent width class) resident
    inside the budget instead of thrashing the LRU."""
    cap = max(budget // 48, 1 << 20)
    best, best_cost = 4, None
    for w in (4, 5, 6, 7, 8):
        W = -(-ebits // w)
        if w > 4 and W * (1 << w) * L * _LIMB_BYTES > cap:
            continue
        cost = W * (1.0 + ((1 << w) - 2) / (m_rows * reuse))
        if best_cost is None or cost < best_cost:
            best, best_cost = w, cost
    return best


def _cached_comb_table(lib, base_red: int, mod: int, L: int, EL: int, wbits: int):
    """Comb window table for (base, modulus, geometry) from the
    process-wide persistent cache (utils.lru), building and inserting on
    miss. The table derives ONLY from the public base/modulus — no
    exponent ever enters it — so it is safe to keep across collect()
    calls; callers with a SECRET base must pass cache=False to
    modexp_shared and ride the one-shot wiped path instead. Returns None
    when caching is disabled (budget 0) or the build fails."""
    from ..utils.lru import global_cache

    cache = global_cache()
    if cache.budget <= 0:
        return None
    key = ("native-comb", base_red, mod, EL, wbits)
    tbl = cache.get(key)
    if tbl is not None:
        return tbl
    words = lib.fsdkr_comb_table_words(L, EL, wbits)
    if words <= 0:
        return None
    tbl = (ctypes.c_uint64 * words)()
    base_buf = _to_buf([base_red], L)
    mod_buf = _to_buf([mod], L)
    rc = lib.fsdkr_comb_precompute(base_buf, mod_buf, tbl, L, EL, wbits)
    _wipe_buf(base_buf, mod_buf)
    if rc != 0:
        return None
    cache.put(key, tbl, words * _LIMB_BYTES)
    return tbl


def modexp_shared(
    base: int, exps: Sequence[int], mod: int, cache: bool = True
) -> List[int]:
    """base^exps[i] mod mod via the fixed-base comb — the shared-base
    column shape of the verify loop (one squaring ladder amortized over
    the whole group; window width chosen by group shape; rows split
    across the FSDKR_THREADS pool). With cache=True (all in-repo callers:
    their bases are public ring-Pedersen parameters h1/h2/T) the window
    table persists in the bytes-budgeted LRU keyed by (base, modulus,
    geometry), so steady-state refreshes of a stable committee skip the
    build entirely; cache=False keeps the old build-use-wipe path for
    secret bases. Falls back to CPython pow when native is unavailable
    or the modulus is even/oversized."""
    if not exps:
        return []
    from ..utils.roofline import stamp_shared_host
    from ..utils.trace import get_tracer

    # prover-comb roofline stamp: the host comb carries the same
    # analytic pricing as the device comb kernel, with exponents priced
    # at the (public) modulus width — actual widths are secret-derived
    # on prover paths (SECURITY.md "Telemetry discipline")
    if get_tracer().enabled:
        stamp_shared_host(1, len(exps), mod.bit_length(), mod.bit_length())
    lib = _get()
    L = _limbs_for(mod)
    if lib is None or L > _MAX_LIMBS or mod % 2 == 0 or mod <= 1:
        return [pow(base, e, mod) for e in exps]
    EL = max(1, max(_limbs_for(e) for e in exps))
    if EL > 2 * _MAX_LIMBS:  # comb table would be attacker-sized
        return [pow(base, e, mod) for e in exps]
    _LIB.sync_threads()
    m_rows = len(exps)
    if cache:
        from ..utils.lru import global_cache

        budget = global_cache().budget
        wbits = (
            _comb_window_bits_cached(EL * 64, m_rows, L, budget)
            if budget > 0
            else _comb_window_bits(EL * 64, m_rows)
        )
    else:
        wbits = _comb_window_bits(EL * 64, m_rows)
    out = (ctypes.c_uint64 * (m_rows * L))()
    exp_buf = _to_buf(list(exps), EL)
    mod_buf = _to_buf([mod], L)
    table = (
        _cached_comb_table(lib, base % mod, mod, L, EL, wbits)
        if cache
        else None
    )
    if table is not None:
        rc = lib.fsdkr_comb_apply(
            table, exp_buf, mod_buf, out, m_rows, L, EL, wbits
        )
        if rc == 0:
            res = _from_buf(out, m_rows, L)
            _wipe_buf(exp_buf, mod_buf, out)
            return res
        # geometry rejected (cannot normally happen once cached): fall
        # through to the one-shot path below
    base_buf = _to_buf([base % mod], L)
    rc = lib.fsdkr_modexp_shared_w(
        base_buf, exp_buf, mod_buf, out, m_rows, L, EL, wbits
    )
    if rc != 0:
        _wipe_buf(base_buf, exp_buf, mod_buf, out)
        return [pow(base, e, mod) for e in exps]
    res = _from_buf(out, m_rows, L)
    _wipe_buf(base_buf, exp_buf, mod_buf, out)
    return res


def _shared_exp_wbits(exp_bits: int) -> int:
    """Sliding-window width for the shared-exponent ladder: expected
    multiplies ~exp_bits/(w+1) (odd-digit windows with skipped zero
    runs) trade against the per-row odd-power table build (2^(w-1)
    entries), so w=7 wins for the full-width public-modulus exponent
    and narrow windows for short shared exponents."""
    best, best_cost = 4, None
    for w in (3, 4, 5, 6, 7, 8):
        cost = exp_bits / (w + 1) + (1 << (w - 1))
        if best_cost is None or cost < best_cost:
            best, best_cost = w, cost
    return best


def shared_exp_powm(
    bases: Sequence[int],
    exp: int,
    mod: int,
    aux_bases: Optional[Sequence[int]] = None,
    aux_exps: Optional[Sequence[int]] = None,
) -> List[int]:
    """outs[r] = bases[r]^exp * aux_bases[r]^aux_exps[r] mod mod, with ONE
    shared public exponent and modulus for the whole batch — the Alice
    range family's s^n (* c^{-e}) column shape (backend.tpu_verifier,
    FSDKR_RANGEOPT). The window schedule derives from the shared exponent
    once and is replayed per row (rows split across the FSDKR_THREADS
    pool); the optional per-row aux term rides the same squaring chain
    Straus-style, so the 256-bit challenge power costs ~70 extra
    multiplies per row instead of its own 256-deep ladder.

    VERIFIER engine: every operand (wire integers s/c/e, the public
    modulus n) is public, so the data-dependent zero-digit skipping in
    the native kernel is in-contract — never route secret exponents here
    (SECURITY.md "Range-opt verifier engines"). Falls back to the
    GMP/CPython split chains when the native core is unavailable or the
    modulus is even/oversized — bit-identical results either way."""
    if not bases:
        return []
    if (aux_bases is None) != (aux_exps is None):
        raise ValueError(
            "shared_exp_powm: aux_bases and aux_exps must be passed together"
        )
    if aux_bases is not None and (
        len(aux_bases) != len(bases) or len(aux_exps) != len(bases)
    ):
        raise ValueError("aux column length mismatch")
    if exp < 0 or (aux_exps is not None and any(e < 0 for e in aux_exps)):
        raise ValueError("shared_exp_powm: exponents must be non-negative")
    rows = len(bases)
    lib = _get()
    L = _limbs_for(mod)
    aux = aux_bases is not None

    def _split_chains():  # GMP (or CPython) split-chain fallback
        from . import gmp

        out = gmp.powm_batch(list(bases), [exp] * rows, [mod] * rows)
        if aux:
            ap = gmp.powm_batch(list(aux_bases), list(aux_exps), [mod] * rows)
            out = [x * y % mod for x, y in zip(out, ap)]
        return out

    if (
        lib is None
        or L > _MAX_LIMBS
        or mod % 2 == 0
        or mod <= 1
        or _limbs_for(exp) > 2 * _MAX_LIMBS
        or (aux and max(
            (_limbs_for(e) for e in aux_exps), default=1
        ) > 2 * _MAX_LIMBS)
    ):
        return _split_chains()
    _LIB.sync_threads()
    EL = max(1, _limbs_for(exp))
    AEL = max(1, max((_limbs_for(e) for e in aux_exps), default=1)) if aux else 0
    out_buf = (ctypes.c_uint64 * (rows * L))()
    base_buf = _to_buf([b % mod for b in bases], L)
    exp_buf = _to_buf([exp], EL)
    mod_buf = _to_buf([mod], L)
    if aux:
        aux_base_buf = _to_buf([b % mod for b in aux_bases], L)
        aux_exp_buf = _to_buf(list(aux_exps), AEL)
    else:
        aux_base_buf = None
        aux_exp_buf = None
    rc = lib.fsdkr_shared_exp_powm(
        base_buf, exp_buf, mod_buf, aux_base_buf, aux_exp_buf, out_buf,
        rows, L, EL, AEL, _shared_exp_wbits(exp.bit_length() or 1),
    )
    if rc != 0:
        _wipe_buf(out_buf)
        return _split_chains()
    return _from_buf(out_buf, rows, L)


def comb2_apply(
    base1: int,
    exps1: Sequence[int],
    base2: int,
    exps2: Sequence[int],
    mod: int,
    stats_out: Optional[dict] = None,
    min_exp_limbs: int = 0,
) -> Optional[List[int]]:
    """outs[m] = base1^exps1[m] * base2^exps2[m] mod mod in ONE native
    pass over both bases' persistent comb window tables (the h1^s1 *
    h2^s2 mod N~ shape of the range/PDL equations) with a single
    Montgomery exit — no separate columns, no recombination modmul.
    Both tables come from (or are inserted into) the process-wide
    public-base LRU, so warm epochs of a stable committee skip every
    build. PUBLIC bases only (cache-key contract of _cached_comb_table);
    returns None when the native core, the cache, or the geometry is
    unavailable — callers fall back to the split comb columns.

    `stats_out`, when a dict, receives ``cached=True`` iff BOTH tables
    were already resident before this call (the fold-ladder cache counts
    warm applies vs builds from it, via a no-side-effect peek).
    `min_exp_limbs` > 0 floors the exponent limb width AND opts into
    width-tolerant table reuse (see the _resolve comment below) for
    callers whose exponent widths jitter launch-to-launch."""
    if not exps1:
        return []
    if len(exps1) != len(exps2):
        raise ValueError("comb2 column length mismatch")
    lib = _get()
    L = _limbs_for(mod)
    if (
        lib is None
        or L > _MAX_LIMBS
        or mod % 2 == 0
        or mod <= 1
        or any(e < 0 for e in exps1)
        or any(e < 0 for e in exps2)
    ):
        return None
    EL1 = max(1, min_exp_limbs, max(_limbs_for(e) for e in exps1))
    EL2 = max(1, min_exp_limbs, max(_limbs_for(e) for e in exps2))
    if max(EL1, EL2) > 2 * _MAX_LIMBS:
        return None
    _LIB.sync_threads()
    from ..utils.lru import global_cache

    budget = global_cache().budget
    if budget <= 0:
        return None  # persistent tables are the point of this engine
    m_rows = len(exps1)

    # reuse=16: these tables back every warm verify_pairs of a stable
    # committee, so the optimizer leans toward apply cost (wider
    # windows). When that picks a different wbits than modexp_shared's
    # reuse=4 policy for the same (base, modulus, EL) — e.g. an
    # FSDKR_RANGEOPT A/B toggle inside one process — the LRU holds one
    # table per geometry key, so both paths stay correct at the price of
    # a second build; in a single-policy process only one exists.
    def _wbits(el: int) -> int:
        return _comb_window_bits_cached(el * 64, m_rows, L, budget, reuse=16)

    if min_exp_limbs:
        # Width-tolerant table resolution (the fold-ladder cache's
        # contract, min_exp_limbs > 0): the caller's exponents are
        # random linear-combination sums whose NATURAL limb width
        # jitters launch-to-launch around the committee's value-width
        # center (e.g. 14 <-> 15 limbs), and an exact-EL key would fork
        # the table per jitter and never go warm. A table built for a
        # wider EL evaluates narrower exponents exactly (leading zero
        # windows), so: reuse any resident table within +4 limbs of the
        # natural width, and on miss build with +2 limbs of slack so
        # every +-1-jittered future launch lands inside the window.
        def _resolve(base_red: int, el_nat: int):
            cache = global_cache()
            hi = min(el_nat + 4, 2 * _MAX_LIMBS)
            for cand in range(el_nat, hi + 1):
                wc = _wbits(cand)
                key = ("native-comb", base_red, mod, cand, wc)
                if cache.peek(key) is not None:
                    return cand, wc, True
            cand = min(el_nat + 2, 2 * _MAX_LIMBS)
            return cand, _wbits(cand), False

        EL1, w1, hit1 = _resolve(base1 % mod, EL1)
        EL2, w2, hit2 = _resolve(base2 % mod, EL2)
        if stats_out is not None:
            stats_out["cached"] = hit1 and hit2
    else:
        w1 = _wbits(EL1)
        w2 = _wbits(EL2)
        if stats_out is not None:
            cache = global_cache()
            stats_out["cached"] = (
                cache.peek(("native-comb", base1 % mod, mod, EL1, w1))
                is not None
                and cache.peek(("native-comb", base2 % mod, mod, EL2, w2))
                is not None
            )
    t1 = _cached_comb_table(lib, base1 % mod, mod, L, EL1, w1)
    t2 = _cached_comb_table(lib, base2 % mod, mod, L, EL2, w2)
    if t1 is None or t2 is None:
        return None
    out_buf = (ctypes.c_uint64 * (m_rows * L))()
    e1_buf = _to_buf(list(exps1), EL1)
    e2_buf = _to_buf(list(exps2), EL2)
    mod_buf = _to_buf([mod], L)
    rc = lib.fsdkr_comb2_apply(
        t1, e1_buf, EL1, w1, t2, e2_buf, EL2, w2, mod_buf, out_buf,
        m_rows, L,
    )
    if rc != 0:
        return None
    res = _from_buf(out_buf, m_rows, L)
    _wipe_buf(e1_buf, e2_buf, out_buf)
    return res


def multi_modexp_batch(
    bases: Sequence[Sequence[int]],
    exps: Sequence[Sequence[int]],
    mods: Sequence[int],
) -> List[int]:
    """Joint (Straus) multi-exponentiation: one interleaved windowed
    ladder per row, prod_t bases[r][t]^exps[r][t] mod mods[r]. All rows
    must carry the same term count k — from 2-term verifier equations up
    to the n-term RLC aggregated groups (backend.rlc); the native kernel
    allocates its per-term tables on the heap, so k is bounded only by
    the 4096-term allocation backstop. Exponents must be non-negative
    (negative exponents are folded upstream by inverting the base —
    backend.powm). The shared squaring chain is as deep as the widest
    term's window count; per-term window counts follow the launch-wide
    max width of that term position, so a k-term row of full-width
    exponents costs ~(max_E + sum_E/4) Montgomery operations instead of
    ~1.27 * sum_E, and an n-term aggregate row shares one short chain
    across all n lookups. Falls back to row-wise CPython pow products
    when the native core is unavailable or a modulus is
    even/oversized."""
    if not bases:
        return []
    if not (len(bases) == len(exps) == len(mods)):
        raise ValueError("batch length mismatch")
    k = len(bases[0])
    if any(len(b) != k or len(e) != k for b, e in zip(bases, exps)):
        raise ValueError("multi-exponentiation rows must share a term count")
    tile = _tile_rows()
    if tile > 0 and len(mods) > tile:  # see modexp_batch: staged pipeline
        from ..utils.pipeline import pipelined

        bases, exps, mods = list(bases), list(exps), list(mods)
        spans = [
            (lo, min(lo + tile, len(mods)))
            for lo in range(0, len(mods), tile)
        ]
        parts = pipelined(
            lambda lo, hi: multi_modexp_batch(
                bases[lo:hi], exps[lo:hi], mods[lo:hi]
            ),
            spans,
        )
        return [v for part in parts for v in part]
    lib = _get()
    _LIB.sync_threads()
    L = max(_limbs_for(m) for m in mods)
    # per-term exponent widths: launch-wide column shape (max bit length
    # of the term position), so the shared chain and each term's window
    # count are exact for the widest row and uniform across the launch
    ebits = [
        max(1, max(e[t].bit_length() for e in exps)) for t in range(k)
    ]
    EL = max(1, -(-max(ebits) // 64))
    if (
        lib is None
        or L > _MAX_LIMBS
        or k > 4096  # keep in sync with MAXK in csrc
        or EL > 2 * _MAX_LIMBS
        or any(m % 2 == 0 or m <= 1 for m in mods)
        or any(e_t < 0 for e in exps for e_t in e)
    ):
        out = []
        for b, e, m in zip(bases, exps, mods):
            acc = 1
            for b_t, e_t in zip(b, e):
                acc = acc * pow(b_t, e_t, m) % m
            out.append(acc)
        return out
    rows = len(bases)
    out_buf = (ctypes.c_uint64 * (rows * L))()
    base_buf = _to_buf(
        [b_t % m for b, m in zip(bases, mods) for b_t in b], L
    )
    exp_buf = _to_buf([e_t for e in exps for e_t in e], EL)
    mod_buf = _to_buf(list(mods), L)
    ebits_arr = (ctypes.c_int * k)(*ebits)
    rc = lib.fsdkr_multi_modexp_batch(
        base_buf, exp_buf, mod_buf, out_buf, ebits_arr, rows, k, L, EL,
        _gen_window_bits_terms(ebits),
    )
    if rc != 0:
        _wipe_buf(base_buf, exp_buf, mod_buf, out_buf)
        out = []
        for b, e, m in zip(bases, exps, mods):
            acc = 1
            for b_t, e_t in zip(b, e):
                acc = acc * pow(b_t, e_t, m) % m
            out.append(acc)
        return out
    res = _from_buf(out_buf, rows, L)
    _wipe_buf(base_buf, exp_buf, mod_buf, out_buf)
    return res


def crt_modexp_batch(
    bases: Sequence[int], exps: Sequence[int], mods: Sequence[int]
) -> List[int]:
    """Row-wise bases^exps mod mods for the secret-CRT legs
    (backend/crt.py): every operand here is secret-derived — the leg
    modulus p*r itself contains a factor of the prover's key, so ALL
    four buffers ride the wipe discipline, and Montgomery constants are
    amortized over runs of equal consecutive moduli (the planner submits
    legs grouped per CRT context). Falls back to CPython pow when the
    native core is unavailable or a leg modulus is even/oversized —
    bit-identical either way."""
    if not bases:
        return []
    if not (len(bases) == len(exps) == len(mods)):
        raise ValueError("batch length mismatch")
    lib = _get()
    _LIB.sync_threads()
    L = max(_limbs_for(m) for m in mods)
    if (
        lib is None
        or L > _MAX_LIMBS
        or any(m % 2 == 0 or m <= 1 for m in mods)
        or any(e < 0 for e in exps)
    ):
        return [pow(b, e, m) for b, e, m in zip(bases, exps, mods)]
    EL = max(1, max(_limbs_for(e) for e in exps))
    rows = len(bases)
    out = (ctypes.c_uint64 * (rows * L))()
    base_buf = _to_buf([b % m for b, m in zip(bases, mods)], L)
    exp_buf = _to_buf(list(exps), EL)
    mod_buf = _to_buf(list(mods), L)
    rc = lib.fsdkr_crt_modexp_batch(
        base_buf, exp_buf, mod_buf, out, rows, L, EL,
        _gen_window_bits(max(e.bit_length() for e in exps)),
    )
    if rc != 0:
        _wipe_buf(base_buf, exp_buf, mod_buf, out)
        return [pow(b, e, m) for b, e, m in zip(bases, exps, mods)]
    res = _from_buf(out, rows, L)
    _wipe_buf(base_buf, exp_buf, mod_buf, out)
    return res


def is_probable_prime_batch(
    ns: Sequence[int], rounds: int = 30
) -> Optional[List[bool]]:
    """Miller-Rabin over a batch of candidates with CSPRNG witnesses,
    candidates split across the FSDKR_THREADS row pool (the prime-
    generation shape: one native call per sieve window instead of one
    per candidate). Returns None when the native path cannot handle the
    inputs — the caller falls back to per-candidate testing."""
    if not ns:
        return []
    lib = _get()
    L = max(_limbs_for(n) for n in ns)
    if (
        lib is None
        or L > _MAX_LIMBS
        or any(n < 5 or n % 2 == 0 for n in ns)
    ):
        return None
    _LIB.sync_threads()
    rows = len(ns)
    witnesses = [
        2 + secrets.randbelow(n - 3) for n in ns for _ in range(rounds)
    ]
    verdicts = (ctypes.c_int * rows)()
    n_buf = _to_buf(list(ns), L)  # prime candidates: secret key material
    wit_buf = _to_buf(witnesses, L)
    rc = lib.fsdkr_miller_rabin_batch(n_buf, wit_buf, verdicts, rows, L, rounds)
    _wipe_buf(n_buf, wit_buf)
    if rc != 0:
        return None
    return [bool(v) for v in verdicts]


def modmul_batch(
    a: Sequence[int], b: Sequence[int], mods: Sequence[int]
) -> List[int]:
    """Row-wise a*b mod mods via the native Montgomery core, rows split
    across the FSDKR_THREADS pool. Rows are sorted by modulus before the
    native call (and scattered back) so the per-modulus Montgomery
    constants amortize over each receiver's whole row group; CPython
    mulmod fallback when native is unavailable or a modulus is
    even/oversized."""
    if not a:
        return []
    if not (len(a) == len(b) == len(mods)):
        raise ValueError("batch length mismatch")
    lib = _get()
    L = max(_limbs_for(m) for m in mods)
    if (
        lib is None
        or L > _MAX_LIMBS
        or any(m % 2 == 0 or m <= 1 for m in mods)
    ):
        return [x * y % m for x, y, m in zip(a, b, mods)]
    _LIB.sync_threads()
    order = sorted(range(len(mods)), key=lambda i: mods[i])
    rows = len(order)
    out = (ctypes.c_uint64 * (rows * L))()
    a_buf = _to_buf([a[i] % mods[i] for i in order], L)
    b_buf = _to_buf([b[i] % mods[i] for i in order], L)
    mod_buf = _to_buf([mods[i] for i in order], L)
    rc = lib.fsdkr_modmul_batch(a_buf, b_buf, mod_buf, out, rows, L)
    if rc != 0:
        _wipe_buf(a_buf, b_buf, mod_buf, out)
        return [x * y % m for x, y, m in zip(a, b, mods)]
    sorted_res = _from_buf(out, rows, L)
    _wipe_buf(a_buf, b_buf, mod_buf, out)
    res: List[int] = [0] * rows
    for pos, i in enumerate(order):
        res[i] = sorted_res[pos]
    return res


def widen_limbs(arr16):
    """u16 -> u32 limb widening (the device kernels' staging layout)
    through the native threaded pass; None when the core is unavailable
    (ops.limbs falls back to numpy astype). The input is NOT wiped here —
    ints_to_limbs owns the staging-wipe discipline for both paths."""
    lib = _get()
    if lib is None:
        return None
    import numpy as np

    src = np.ascontiguousarray(arr16, dtype=np.uint16)
    out = np.empty(src.shape, dtype=np.uint32)
    _LIB.sync_threads()
    lib.fsdkr_limbs_widen_u16(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        ctypes.c_longlong(src.size),
    )
    return out


def narrow_limbs(arr32):
    """u32 -> u16 limb narrowing with the canonicality check fused into
    the same threaded pass (one sweep instead of numpy's check + astype).
    Returns None when the core is unavailable; raises ValueError on a
    pending-carry limb exactly like ops.limbs.limbs_to_ints."""
    lib = _get()
    if lib is None:
        return None
    import numpy as np

    src = np.ascontiguousarray(arr32, dtype=np.uint32)
    out = np.empty(src.shape, dtype=np.uint16)
    _LIB.sync_threads()
    rc = lib.fsdkr_limbs_narrow_u16(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        ctypes.c_longlong(src.size),
    )
    if rc != 0:
        raise ValueError("limb array not canonical (pending carries)")
    return out


def is_probable_prime(n: int, rounds: int = 30) -> Optional[bool]:
    """Miller-Rabin with CSPRNG witnesses, native squaring loop (rounds
    split across the FSDKR_THREADS pool). Returns None when the native
    path cannot handle the input (caller falls back to the Python
    implementation)."""
    lib = _get()
    L = _limbs_for(n)
    if lib is None or L > _MAX_LIMBS or n < 5 or n % 2 == 0:
        return None
    _LIB.sync_threads()
    witnesses = [2 + secrets.randbelow(n - 3) for _ in range(rounds)]
    n_buf = _to_buf([n], L)  # prime candidate: secret key material
    rc = lib.fsdkr_miller_rabin(n_buf, L, _to_buf(witnesses, L), rounds)
    _wipe_buf(n_buf)
    if rc < 0:
        return None
    return bool(rc)
