"""ctypes bridge to the native host bignum core (csrc/fsdkr_native.cpp).

The reference's host-serial native layer is GMP under curv/kzen-paillier
(`/root/reference/Cargo.toml:42-44` selects the GMP backend by default);
this module is the rebuild's equivalent for the paths that stay on the
host: Miller-Rabin prime generation, the comb kernel's power ladder, and
the host-backend modexp oracle. The shared object is compiled on first
use with g++ (no pybind11 in this environment — plain C ABI + ctypes) and
cached next to this file; every entry point degrades to the pure-Python
implementation when the toolchain is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import secrets
from typing import List, Optional, Sequence

from . import _loader

__all__ = [
    "available",
    "modexp",
    "modexp_batch",
    "modexp_shared",
    "multi_modexp_batch",
    "is_probable_prime",
]

_LIMB_BYTES = 8
_MAX_LIMBS = 64  # 4096 bits, keep in sync with MAXL in csrc
_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc", "fsdkr_native.cpp")

_LIB = _loader.get_lib(
    os.path.abspath(_SRC),
    "_fsdkr_native",
    ("fsdkr_modexp", "fsdkr_modexp_w", "fsdkr_modexp_batch",
     "fsdkr_modexp_batch_w", "fsdkr_modexp_shared", "fsdkr_modexp_shared_w",
     "fsdkr_multi_modexp_batch", "fsdkr_miller_rabin"),
)


def _gen_window_bits(total_exp_bits: int, terms: int = 1) -> int:
    """Window width for the generic/joint windowed ladders: lookups cost
    total_exp_bits/w, the per-term tables 2^w - 2 multiplies each. w=6
    wins for full-width exponents, w=4 for short challenge columns."""
    best, best_cost = 4, None
    for w in (4, 5, 6):
        cost = total_exp_bits / w + terms * ((1 << w) - 2)
        if best_cost is None or cost < best_cost:
            best, best_cost = w, cost
    return best


def _get() -> Optional[ctypes.CDLL]:
    return _LIB.get()


def available() -> bool:
    return _LIB.available()


def _limbs_for(x: int) -> int:
    return max(1, -(-x.bit_length() // 64))


def _to_buf(xs: Sequence[int], limbs: int) -> ctypes.Array:
    """Limb staging for the C ABI. The bytearray is wiped in place before
    returning (no immutable `bytes` copy is ever made), so the only
    surviving host copy of a secret operand is the returned ctypes array —
    which callers wipe with _wipe_buf after the native call."""
    step = limbs * _LIMB_BYTES
    buf = bytearray(len(xs) * step)
    for row, x in enumerate(xs):
        buf[row * step : (row + 1) * step] = x.to_bytes(step, "little")
    arr = (ctypes.c_uint64 * (len(xs) * limbs)).from_buffer_copy(buf)
    buf[:] = bytes(len(buf))
    return arr


def _wipe_buf(*arrays) -> None:
    """Zero ctypes limb buffers that held secret operands (exponents,
    prime candidates, secret bases) once the native call returns — the
    host-bridge leg of the zeroize discipline (SECURITY.md)."""
    for a in arrays:
        ctypes.memset(a, 0, ctypes.sizeof(a))


def _from_buf(buf, rows: int, limbs: int) -> List[int]:
    """Read results without an immutable `bytes` copy: int.from_bytes
    accepts memoryview slices directly, so the only surviving host copies
    of a secret result are the returned Python ints (a documented
    residual — see SECURITY.md) and `buf` itself, which callers wipe."""
    mv = memoryview(buf).cast("B")
    step = limbs * _LIMB_BYTES
    return [
        int.from_bytes(mv[i * step : (i + 1) * step], "little")
        for i in range(rows)
    ]


def modexp(base: int, exp: int, mod: int) -> int:
    """base^exp mod mod via the native Montgomery core; CPython pow when
    the native library is unavailable or the modulus is out of range."""
    lib = _get()
    L = _limbs_for(mod)
    if lib is None or L > _MAX_LIMBS or mod % 2 == 0 or mod <= 1:
        return pow(base, exp, mod)
    EL = max(1, _limbs_for(exp))
    out = (ctypes.c_uint64 * L)()
    base_buf = _to_buf([base % mod], L)
    exp_buf = _to_buf([exp], EL)
    # the modulus and result are secret too on the Paillier-decrypt path
    # (mod = p^2; gcd(out - 1, N) = p), so all four buffers are wiped
    mod_buf = _to_buf([mod], L)
    rc = lib.fsdkr_modexp_w(
        base_buf, exp_buf, mod_buf, out, L, EL,
        _gen_window_bits(exp.bit_length()),
    )
    if rc != 0:
        _wipe_buf(base_buf, exp_buf, mod_buf, out)
        return pow(base, exp, mod)
    res = _from_buf(out, 1, L)[0]
    _wipe_buf(base_buf, exp_buf, mod_buf, out)
    return res


def modexp_batch(
    bases: Sequence[int], exps: Sequence[int], mods: Sequence[int]
) -> List[int]:
    """Row-wise bases^exps mod mods. Rows are padded to the widest modulus
    and exponent in the batch; even/oversized-modulus rows fall back to
    CPython pow row-wise."""
    if not bases:
        return []
    if not (len(bases) == len(exps) == len(mods)):
        raise ValueError("batch length mismatch")
    lib = _get()
    L = max(_limbs_for(m) for m in mods)
    if (
        lib is None
        or L > _MAX_LIMBS
        or any(m % 2 == 0 or m <= 1 for m in mods)
    ):
        return [pow(b, e, m) for b, e, m in zip(bases, exps, mods)]
    EL = max(1, max(_limbs_for(e) for e in exps))
    rows = len(bases)
    out = (ctypes.c_uint64 * (rows * L))()
    base_buf = _to_buf([b % m for b, m in zip(bases, mods)], L)
    exp_buf = _to_buf(list(exps), EL)
    mod_buf = _to_buf(list(mods), L)
    rc = lib.fsdkr_modexp_batch_w(
        base_buf, exp_buf, mod_buf, out, rows, L, EL,
        _gen_window_bits(max(e.bit_length() for e in exps)),
    )
    if rc != 0:
        # rows before the failing one have already written results
        _wipe_buf(base_buf, exp_buf, mod_buf, out)
        return [pow(b, e, m) for b, e, m in zip(bases, exps, mods)]
    res = _from_buf(out, rows, L)
    _wipe_buf(base_buf, exp_buf, mod_buf, out)
    return res


def _comb_window_bits(ebits: int, m_rows: int) -> int:
    """Comb window width minimizing per-row cost: lookups shrink as
    ebits/w while the per-group table build ((2^w - 2 per window,
    amortized over the group's rows) grows exponentially in w. At the
    ring-Pedersen shape (M=256 rows, 2048-bit exponents) w=6 beats w=4
    by ~22%; small pair groups (M~n) stay at w=4."""
    best, best_cost = 4, None
    for w in (4, 5, 6, 7, 8):
        cost = (ebits / w) * (1.0 + ((1 << w) - 2) / m_rows)
        if best_cost is None or cost < best_cost:
            best, best_cost = w, cost
    return best


def modexp_shared(
    base: int, exps: Sequence[int], mod: int
) -> List[int]:
    """base^exps[i] mod mod via the fixed-base comb — the shared-base
    column shape of the verify loop (one squaring ladder amortized over
    the whole group; window width chosen by group shape). Falls back to
    CPython pow when native is unavailable or the modulus is
    even/oversized."""
    if not exps:
        return []
    lib = _get()
    L = _limbs_for(mod)
    if lib is None or L > _MAX_LIMBS or mod % 2 == 0 or mod <= 1:
        return [pow(base, e, mod) for e in exps]
    EL = max(1, max(_limbs_for(e) for e in exps))
    if EL > 2 * _MAX_LIMBS:  # comb table would be attacker-sized
        return [pow(base, e, mod) for e in exps]
    m_rows = len(exps)
    wbits = _comb_window_bits(EL * 64, m_rows)
    out = (ctypes.c_uint64 * (m_rows * L))()
    base_buf = _to_buf([base % mod], L)
    exp_buf = _to_buf(list(exps), EL)
    mod_buf = _to_buf([mod], L)
    rc = lib.fsdkr_modexp_shared_w(
        base_buf, exp_buf, mod_buf, out, m_rows, L, EL, wbits
    )
    if rc != 0:
        _wipe_buf(base_buf, exp_buf, mod_buf, out)
        return [pow(base, e, mod) for e in exps]
    res = _from_buf(out, m_rows, L)
    _wipe_buf(base_buf, exp_buf, mod_buf, out)
    return res


def multi_modexp_batch(
    bases: Sequence[Sequence[int]],
    exps: Sequence[Sequence[int]],
    mods: Sequence[int],
) -> List[int]:
    """Joint (Straus) multi-exponentiation: one interleaved windowed
    ladder per row, prod_t bases[r][t]^exps[r][t] mod mods[r]. All rows
    must carry the same term count k; exponents must be non-negative
    (negative exponents are folded upstream by inverting the base —
    backend.powm). The shared squaring chain is as deep as the widest
    term's window count; per-term window counts follow the launch-wide
    max width of that term position, so a k-term row of full-width
    exponents costs ~(max_E + sum_E/4) Montgomery operations instead of
    ~1.27 * sum_E. Falls back to row-wise CPython pow products when the
    native core is unavailable or a modulus is even/oversized."""
    if not bases:
        return []
    if not (len(bases) == len(exps) == len(mods)):
        raise ValueError("batch length mismatch")
    k = len(bases[0])
    if any(len(b) != k or len(e) != k for b, e in zip(bases, exps)):
        raise ValueError("multi-exponentiation rows must share a term count")
    lib = _get()
    L = max(_limbs_for(m) for m in mods)
    # per-term exponent widths: launch-wide column shape (max bit length
    # of the term position), so the shared chain and each term's window
    # count are exact for the widest row and uniform across the launch
    ebits = [
        max(1, max(e[t].bit_length() for e in exps)) for t in range(k)
    ]
    EL = max(1, -(-max(ebits) // 64))
    if (
        lib is None
        or L > _MAX_LIMBS
        or k > 8
        or EL > 2 * _MAX_LIMBS
        or any(m % 2 == 0 or m <= 1 for m in mods)
        or any(e_t < 0 for e in exps for e_t in e)
    ):
        out = []
        for b, e, m in zip(bases, exps, mods):
            acc = 1
            for b_t, e_t in zip(b, e):
                acc = acc * pow(b_t, e_t, m) % m
            out.append(acc)
        return out
    rows = len(bases)
    out_buf = (ctypes.c_uint64 * (rows * L))()
    base_buf = _to_buf(
        [b_t % m for b, m in zip(bases, mods) for b_t in b], L
    )
    exp_buf = _to_buf([e_t for e in exps for e_t in e], EL)
    mod_buf = _to_buf(list(mods), L)
    ebits_arr = (ctypes.c_int * k)(*ebits)
    rc = lib.fsdkr_multi_modexp_batch(
        base_buf, exp_buf, mod_buf, out_buf, ebits_arr, rows, k, L, EL,
        _gen_window_bits(sum(ebits), k),
    )
    if rc != 0:
        _wipe_buf(base_buf, exp_buf, mod_buf, out_buf)
        out = []
        for b, e, m in zip(bases, exps, mods):
            acc = 1
            for b_t, e_t in zip(b, e):
                acc = acc * pow(b_t, e_t, m) % m
            out.append(acc)
        return out
    res = _from_buf(out_buf, rows, L)
    _wipe_buf(base_buf, exp_buf, mod_buf, out_buf)
    return res


def is_probable_prime(n: int, rounds: int = 30) -> Optional[bool]:
    """Miller-Rabin with CSPRNG witnesses, native squaring loop. Returns
    None when the native path cannot handle the input (caller falls back
    to the Python implementation)."""
    lib = _get()
    L = _limbs_for(n)
    if lib is None or L > _MAX_LIMBS or n < 5 or n % 2 == 0:
        return None
    witnesses = [2 + secrets.randbelow(n - 3) for _ in range(rounds)]
    n_buf = _to_buf([n], L)  # prime candidate: secret key material
    rc = lib.fsdkr_miller_rabin(n_buf, L, _to_buf(witnesses, L), rounds)
    _wipe_buf(n_buf)
    if rc < 0:
        return None
    return bool(rc)
