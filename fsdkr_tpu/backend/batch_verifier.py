"""Batch verification interface + host implementation.

Each method takes a list of proof instances (one per (sender, receiver)
pair or per sender) and returns one verdict per instance, in order.
Verdicts are never short-circuited: the caller maps failing rows back to
party indices for identifiable abort (reference error semantics,
`/root/reference/src/error.rs`).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..config import ProtocolConfig
from ..core.paillier import EncryptionKey
from ..core.secp256k1 import Point
from ..core.vss import VerifiableSS
from ..errors import PDLwSlackProofError
from ..proofs.alice_range import AliceProof
from ..proofs.composite_dlog import CompositeDLogProof, DLogStatement
from ..proofs.correct_key import NiCorrectKeyProof
from ..proofs.pdl_slack import PDLwSlackProof, PDLwSlackStatement
from ..proofs.ring_pedersen import RingPedersenProof, RingPedersenStatement


class BatchVerifier:
    """Interface; see HostBatchVerifier for reference semantics."""

    def verify_pdl(
        self, items: Sequence[Tuple[PDLwSlackProof, PDLwSlackStatement]]
    ) -> List[Optional[Tuple[bool, bool, bool]]]:
        """Per item: None if valid, else the (u1, u2, u3) equation booleans."""
        raise NotImplementedError

    def verify_range(
        self, items: Sequence[Tuple[AliceProof, int, EncryptionKey, DLogStatement]]
    ) -> List[bool]:
        raise NotImplementedError

    def verify_pairs(self, pdl_items, range_items, session_spans=None):
        """Both families of the O(n^2) pair loop
        (`src/refresh_message.rs:330-350`). Default: two family calls;
        the TPU backend overrides to share one fused launch set, which
        matters when small batches underfeed the chip. `session_spans`
        (session -> [lo, hi) row span of a fused multi-session launch)
        is advisory: the base implementation's verdicts are already
        per-row exact, so it is accepted and ignored here."""
        return self.verify_pdl(pdl_items), self.verify_range(range_items)

    def verify_ring_pedersen(
        self, items: Sequence[Tuple[RingPedersenProof, RingPedersenStatement]], m_security: int
    ) -> List[bool]:
        raise NotImplementedError

    def verify_correct_key(
        self, items: Sequence[Tuple[NiCorrectKeyProof, EncryptionKey]], rounds: int
    ) -> List[bool]:
        raise NotImplementedError

    def verify_composite_dlog(
        self, items: Sequence[Tuple[CompositeDLogProof, DLogStatement]]
    ) -> List[bool]:
        raise NotImplementedError

    def validate_feldman(
        self, items: Sequence[Tuple[VerifiableSS, Point, int]]
    ) -> List[bool]:
        """Per item: scheme, public share point, 1-based evaluation index."""
        raise NotImplementedError


class HostBatchVerifier(BatchVerifier):
    def __init__(self, hash_alg: Optional[str] = None):
        # None -> the process-default digest (core.transcript). get_backend
        # binds the session's config.hash_alg here so interleaved sessions
        # with different digests stay self-consistent.
        self._hash_alg = hash_alg

    def verify_pdl(self, items):
        out = []
        for proof, st in items:
            try:
                proof.verify(st, hash_alg=self._hash_alg)
                out.append(None)
            except PDLwSlackProofError as e:
                out.append((e.is_u1_eq, e.is_u2_eq, e.is_u3_eq))
        return out

    def verify_range(self, items):
        return [
            proof.verify(c, ek, dlog, hash_alg=self._hash_alg)
            for proof, c, ek, dlog in items
        ]

    def verify_ring_pedersen(self, items, m_security):
        out = []
        for proof, st in items:
            try:
                proof.verify(st, m_security, hash_alg=self._hash_alg)
                out.append(True)
            except Exception:
                out.append(False)
        return out

    def verify_correct_key(self, items, rounds):
        return [
            proof.verify(ek, rounds=rounds, hash_alg=self._hash_alg)
            for proof, ek in items
        ]

    def verify_composite_dlog(self, items):
        return [proof.verify(st, hash_alg=self._hash_alg) for proof, st in items]

    def validate_feldman(self, items):
        """Feldman share validation, with the FSDKR_DELEGATE certificate
        pre-pass (proofs.msm_delegate): rows of a scheme whose
        broadcast certificate checks out are resolved without any
        per-row MSM; everything else (arm disabled, no/failing cert,
        partial coverage) takes the honest native-Horner/per-row path
        below — verdicts bit-identical in both knob positions."""
        from ..proofs import msm_delegate

        pre = msm_delegate.try_delegate(items, self._hash_alg)
        if pre is not None:
            remaining = [i for i, v in enumerate(pre) if v is None]
            if not remaining:
                return [bool(v) for v in pre]
            sub = self._validate_feldman_honest(
                [items[i] for i in remaining]
            )
            for i, v in zip(remaining, sub):
                pre[i] = v
            return pre
        return self._validate_feldman_honest(items)

    def _validate_feldman_honest(self, items):
        from ..native import ec as native_ec

        if not native_ec.available() or not items:
            return [
                scheme.validate_share_public(point, idx)
                for scheme, point, idx in items
            ]
        # one native Horner launch per commitment vector: rows sharing a
        # scheme (every receiver slot of one message) marshal the t+1
        # commitments once, not per row
        groups: dict = {}
        for row, (scheme, _, _) in enumerate(items):
            groups.setdefault(id(scheme), []).append(row)
        out = [False] * len(items)
        for rows in groups.values():
            scheme = items[rows[0]][0]
            commits = [
                None if c.infinity else (c.x, c.y)
                for c in scheme.commitments
            ]
            evals = native_ec.horner_batch(
                commits, [items[row][2] for row in rows]
            )
            if evals is None:  # u32 overflow or native failure: fall back
                for row in rows:
                    scheme, point, idx = items[row]
                    out[row] = scheme.validate_share_public(point, idx)
                continue
            for row, ev in zip(rows, evals):
                point = items[row][1]
                if ev is None:
                    out[row] = point.infinity
                else:
                    out[row] = (not point.infinity) and (
                        point.x == ev[0] and point.y == ev[1]
                    )
        return out


class TracedVerifier:
    """Wraps any backend with per-family phase timers/counters
    (fsdkr_tpu.utils.trace) — the observability the reference lacks
    entirely (SURVEY.md §5). Deliberately NOT a BatchVerifier subclass:
    inherited abstract methods would shadow __getattr__ delegation."""

    def __init__(self, inner: BatchVerifier):
        self._inner = inner

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name.startswith(("verify_", "validate_")) and callable(attr):
            from ..utils.trace import phase

            def traced(items, *args, _attr=attr, _name=name, **kwargs):
                # multi-list calls (verify_pairs) count every list's rows
                rows = len(items) + sum(
                    len(a) for a in args if isinstance(a, (list, tuple))
                )
                with phase(f"collect.{_name}", items=rows):
                    return _attr(items, *args, **kwargs)

            return traced
        return attr


def get_backend(config: ProtocolConfig) -> "TracedVerifier":
    """Returns the configured backend wrapped in a TracedVerifier (which
    quacks like a BatchVerifier via delegation). The session's hash_alg is
    bound into the returned verifier — never installed process-wide — so
    sessions with different digests can interleave in one process."""
    if config.backend == "host":
        return TracedVerifier(HostBatchVerifier(config.hash_alg))
    if config.backend == "tpu":
        try:
            from .tpu_verifier import TpuBatchVerifier
        except ImportError as e:
            raise NotImplementedError(
                "the TPU batch-verifier backend is unavailable in this build"
            ) from e
        return TracedVerifier(TpuBatchVerifier(config))
    raise ValueError(f"unknown backend {config.backend!r}")
