"""Cross-proof randomized batch verification for the modexp families.

Bellare-Garay-Rabin small-exponent random linear combination (RLC):
verification rows that share a modulus — all ring-Pedersen rows of one
proof (mod N), all correct-key rounds of one proof (mod N), the n PDL
rows addressed to one receiver (mod N~ and mod N^2) — fold into ONE
combined equation per group,

    prod_i (lhs_i / rhs_i)^{rho_i} == 1  (mod M),

with secret fresh rho_i in [1, 2^128) drawn from the OS CSPRNG per
verification. A group containing at least one failing row passes with
probability at most 2^-128 over the verifier's own coins (see
SECURITY.md for the bound's fine print in groups of unknown order).
Division never happens: each family's fold moves terms so both sides
are products of non-negative powers and the check is an equality of two
computed group elements.

Where the per-row check costs one full-width (2048/4096-bit) squaring
chain per row, the folded check costs O(1) full-width chains per GROUP
(the bases shared across rows — h1, h2, T, g = N+1 — merge their
exponents into one full-width term) plus one short aggregated chain
over the per-row bases, whose exponents are only 128-384 bits wide.

Blame semantics: a failing combined check triggers recursive bisection
(`bisect_rows`) — subsets are re-checked with fresh rho, and leaves
fall back to the exact per-row equation — so a row is only ever marked
INVALID through its exact per-row check (false blame is impossible:
all-valid subsets pass with probability 1, products of true
equations). The converse inference — a passing subset is all-valid —
is the probabilistic one: it fails only with the group soundness
error, i.e. 2^-128 per check, DEGRADED for a row whose equation
residue has small order in an adversary-chosen modulus (SECURITY.md).
Within that bound, per-row verdicts (and the reference's
identifiable-abort attribution, `/root/reference/src/error.rs`) match
the per-row path.

`FSDKR_RLC` gates the whole mechanism (default on); `=0` reverts every
caller to the per-row column/joint path for A/B isolation.
"""

from __future__ import annotations

import os
import secrets
from typing import Callable, Dict, List, Sequence

__all__ = [
    "RLC_BITS",
    "rlc_enabled",
    "xsession_dedup_enabled",
    "sample_rhos",
    "bisect_rows",
    "bisect_sessions",
    "StreamFold",
    "stats",
    "stats_reset",
    "count",
]

RLC_BITS = 128


def rlc_enabled() -> bool:
    """FSDKR_RLC gates cross-proof randomized batch verification: =0
    reverts the verifier to the per-row column/joint path. Read at call
    time so the bench battery and the CI legs can toggle it per step."""
    return os.environ.get("FSDKR_RLC", "1").lower() not in (
        "0", "off", "false", "no",
    )


def xsession_dedup_enabled() -> bool:
    """FSDKR_XSESSION_DEDUP gates cross-session value dedup in fused
    multi-session launches (tpu_verifier.verify_pairs): same-committee
    sessions produce value-identical pair rows, so one representative
    per distinct row value is verified and its verdict fanned out. =0
    verifies every row of the fused batch for A/B isolation. Read at
    call time so the bench battery and the CI legs can toggle it per
    step."""
    return os.environ.get("FSDKR_XSESSION_DEDUP", "1").lower() not in (
        "0", "off", "false", "no",
    )


def sample_rhos(count: int) -> List[int]:
    """count secret coefficients rho_i in [1, 2^128), fresh from the OS
    CSPRNG. Never cached, never persisted, never part of any cache key
    (SECURITY.md): rho only ever flows into exponent staging buffers,
    which carry the standard wipe discipline."""
    top = (1 << RLC_BITS) - 1
    return [1 + secrets.randbelow(top) for _ in range(count)]


# ---------------------------------------------------------------------------
# Fold statistics (emitted in the bench JSON as the `rlc` field): how many
# groups folded, how many per-row equations they absorbed, how many
# full-width ladders the folded plan still launches (the O(1)-per-group
# count the fold exists to achieve), and how many groups fell back to
# bisection. Since ISSUE 6 the backing store is the process-global
# telemetry registry (one labeled counter); `stats()`/`stats_reset()`
# remain the legacy window view bench.py and the tests use.

_EVENTS = (
    "rlc_groups", "rows_folded", "fullwidth_ladders", "bisect_fallbacks",
    "stream_tiles", "session_bisects", "ladder_cache_hits",
    "ladder_cache_misses", "xsession_rows_deduped",
)


def _metric():
    from ..telemetry import registry

    return registry.counter(
        "fsdkr_rlc_events",
        "randomized-batch-verification fold statistics (backend.rlc)",
        labelnames=("event",),
    )


def count(name: str, n: int = 1) -> None:
    _metric().inc(n, event=name)


def stats() -> Dict[str, int]:
    m = _metric()
    return {e: int(m.value(event=e)) for e in _EVENTS}


def stats_reset() -> None:
    _metric().reset()


# ---------------------------------------------------------------------------


class StreamFold:
    """Running partial state of one RLC group folded across streaming
    tiles (the memory-plan path, backend.memplan): the combined check

        prod_i lhs_i^{rho_i} == (shared bases)^{merged exponents} ...

    factorizes over any partition of the rows — prod_tiles prod_{i in
    tile} x_i^{rho_i} — so a tile only ever contributes (a) its partial
    products over the per-row bases (evaluated on the tile's short
    aggregated chains and multiplied in here) and (b) plain integer
    sums of its merged shared-base exponents. The full-width ladders
    raising the shared bases to the merged exponents run ONCE per group
    at finish, so the O(1)-full-width-ladders-per-group property of the
    monolithic fold is preserved at every budget, while no tile's
    staged rows outlive its own verify step.

    `prods` are the running per-row-base partial products (one slot per
    aggregated chain the family folds: PDL mod-N~ uses 1, mod-n^2 uses
    2); `exp_sums` the running merged-exponent integer sums; `rows` the
    absorbed global row indices, in absorption order, for the bisection
    fallback (which re-folds from the retained row data exactly like
    the monolithic path — blame semantics are shared code)."""

    __slots__ = ("modulus", "prods", "exp_sums", "rows")

    def __init__(self, modulus: int, n_prods: int = 1, n_exps: int = 0):
        self.modulus = modulus
        self.prods = [1] * n_prods
        self.exp_sums = [0] * n_exps
        self.rows: List[int] = []

    def absorb(self, prod_vals, exp_vals=(), rows=()) -> None:
        m = self.modulus
        for i, v in enumerate(prod_vals):
            self.prods[i] = self.prods[i] * v % m
        for i, e in enumerate(exp_vals):
            self.exp_sums[i] += e
        self.rows.extend(rows)


def bisect_rows(
    indices: Sequence[int],
    combined_check: Callable[[List[int]], bool],
    row_check: Callable[[int], bool],
    leaf: int = 2,
) -> Dict[int, bool]:
    """Per-row verdicts for a group whose combined check failed.

    Recursively halves the row set: a subset passing `combined_check`
    (fresh rho each call) is marked all-valid, while a failing subset
    splits further until `leaf` rows remain, which are decided by the
    exact `row_check`. Rows are therefore only marked INVALID through
    the exact check — an all-valid subset passes with probability 1
    (products of true equations), so false blame is impossible. The
    all-valid marking of a PASSING subset is the probabilistic
    inference: it inherits the combined check's soundness error (see
    the module docstring for the bound and its small-order caveat).
    A group with b bad rows costs O(b * log(n)) combined sub-checks
    plus O(b * leaf) exact row checks, against the n exact checks of a
    flat re-verify.
    """
    out: Dict[int, bool] = {}
    stack: List[List[int]] = [list(indices)]
    while stack:
        rows = stack.pop()
        if len(rows) <= leaf:
            for i in rows:
                out[i] = bool(row_check(i))
            continue
        mid = (len(rows) + 1) // 2
        for half in (rows[:mid], rows[mid:]):
            if combined_check(half):
                for i in half:
                    out[i] = True
            else:
                stack.append(half)
    return out


def bisect_sessions(
    indices: Sequence[int],
    session_of: Callable[[int], int],
    combined_check: Callable[[List[int]], bool],
    row_check: Callable[[int], bool],
    leaf: int = 2,
) -> Dict[int, bool]:
    """`bisect_rows` with a session-first split for groups whose rows
    were merged across fused sessions: partition the failing group's
    rows by owning session (absorption order preserved within each),
    combined-check each session's subset once, and only bisect WITHIN
    the sessions whose subset fails. An honest session fused with a
    tampered sibling is therefore cleared by one combined sub-check —
    never blamed, never even row-checked — so fusion can only *sharpen*
    attribution cost, and verdicts stay bit-identical to S independent
    collects (each session's rows are decided by exactly the shared
    `bisect_rows`/`row_check` machinery an unfused collect would use).

    With rows from <= 1 distinct session the partition is a no-op and
    this degrades to plain `bisect_rows` (no extra combined check)."""
    by_session: Dict[int, List[int]] = {}
    for i in indices:
        by_session.setdefault(session_of(i), []).append(i)
    if len(by_session) <= 1:
        return bisect_rows(indices, combined_check, row_check, leaf)
    out: Dict[int, bool] = {}
    for rows in by_session.values():
        count("session_bisects")
        if combined_check(rows):
            for i in rows:
                out[i] = True
        else:
            out.update(bisect_rows(rows, combined_check, row_check, leaf))
    return out
