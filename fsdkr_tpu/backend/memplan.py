"""Bytes-budgeted streaming verification plan (ISSUE 10).

The monolithic collect() path gathers every pair row of a batch, stages
all of them (limb widening, Montgomery entry, fold buffers, per-row
intermediate columns), verifies, and only then lets the staged data die.
At the north-star shape (n=256, 2048-bit Paillier, M=256) the pair rows
are 4096-bit and the all-rows-resident plan peaks well past a gigabyte
of staged operands — the same wall hardware ZKP pipelines hit on-chip,
and solve with tiled operand movement under an explicit budget (SZKP,
arXiv:2408.05890). This module is the host-side version of that
discipline:

- `plan_rows` cuts a row axis into tiles sized so that the staged bytes
  of the tiles in flight stay under `FSDKR_MEM_BUDGET_MB`. Tile sizes
  are derived ONLY from public quantities — row counts and the batch's
  bucketed width class (`pair_row_bytes`) — so the plan can never leak
  secret-dependent structure (SECURITY.md "Memory plan discipline").
  With a device mesh active, tiles are cut mesh-aligned via
  `shard_kernels.tile_rows_for_mesh` so no tile falls off the sharded
  path.
- The stage/release tracker accounts the live staged-tile bytes and
  exports `fsdkr_mem_*` gauges (peak resident, cumulative bytes staged,
  tiles/tile-rows per family) that land in every bench JSON through the
  telemetry snapshot.
- `streamed_rows` runs a row-local verdict call tile by tile under the
  plan (the Feldman/EC columns of collect ride this).

The consumer of the pair plan is `tpu_verifier.TpuBatchVerifier`
(`_verify_pairs_streamed`): build -> widen/stage -> verify -> wipe per
tile, with the cross-proof RLC folds accumulated as running per-group
partial products (`backend.rlc.StreamFold`) so the combined checks never
need all rows live. `FSDKR_MEM_PLAN=0` restores the monolithic path for
A/B isolation; verdicts and identifiable-abort blame are bit-identical
at every budget (tests/test_memplan.py).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

__all__ = [
    "memplan_enabled",
    "mem_budget_bytes",
    "pair_row_bytes",
    "ec_row_bytes",
    "TilePlan",
    "plan_rows",
    "stage",
    "release",
    "streamed_rows",
    "mem_stats",
    "stats_reset",
    "vmhwm_bytes",
]

_OFF = ("0", "off", "false", "no")

# Per-row staged-bytes estimate for one pair row (PDL + Alice range
# verified together). Engineering estimate covering, per row: the staged
# limb copies of the modexp columns (u32 limbs are 2x the value bytes),
# the per-row intermediate integer columns of both families (u/w parts,
# base inversions, fold aggregates), and engine scratch. Derived from
# PUBLIC width buckets only — never from wire values.
_PAIR_ROW_FACTOR = 16
_PAIR_ROW_BASE = 512  # EC points, object headers, span bookkeeping


def memplan_enabled() -> bool:
    """FSDKR_MEM_PLAN gates the bytes-budgeted streaming verification
    plan (default on): =0 restores the all-rows-resident monolithic
    gather/stage/verify sequence for A/B isolation. Read at call time so
    the bench battery and CI legs can toggle it per step."""
    return os.environ.get("FSDKR_MEM_PLAN", "1").lower() not in _OFF


def mem_budget_bytes() -> int:
    """The staged-bytes budget from FSDKR_MEM_BUDGET_MB (float MB;
    default 256). The planner sizes tiles so the tiles concurrently in
    flight (two under the double-buffered pipeline) fit the budget; a
    budget below one row's estimate degrades to 1-row tiles — the plan
    never refuses to run. Under an active fault plan (FSDKR_FAULTS,
    ISSUE 11) a mem_squeeze injection shrinks one planning decision's
    budget by the plan's squeeze factor — verdicts and blame are
    budget-independent by the memplan contract, so a squeeze costs
    tiles, never correctness."""
    try:
        mb = float(os.environ.get("FSDKR_MEM_BUDGET_MB", "256"))
    except ValueError:
        mb = 256.0
    return _fault_squeeze(max(1, int(mb * (1 << 20))))


def _fault_squeeze(budget: int) -> int:
    """Consult the serving fault plan via sys.modules only (never an
    import): zero cost unless a chaos run already loaded
    fsdkr_tpu.serving.faults AND configured a plan."""
    import sys

    m = sys.modules.get("fsdkr_tpu.serving.faults")
    if m is None:
        return budget
    plan = m.active()
    return budget if plan is None else plan.squeeze_budget(budget)


def pair_row_bytes(nn_bits: int, nt_bits: int) -> int:
    """Staged-bytes estimate for one pair row at the batch's PUBLIC
    width bucket (mod-n^2 and mod-N~ widths rounded up the limb
    ladder). Width-bucketed by construction: every row of a collect
    shares the config's width class, so one estimate prices the whole
    batch and the tile cut depends only on (row count, width bucket)."""
    from ..ops.limbs import LIMB_BITS, limbs_for_bits

    nn_b = limbs_for_bits(max(1, nn_bits)) * (LIMB_BITS // 8)
    nt_b = limbs_for_bits(max(1, nt_bits)) * (LIMB_BITS // 8)
    return _PAIR_ROW_FACTOR * (nn_b + nt_b) + _PAIR_ROW_BASE


def ec_row_bytes() -> int:
    """Staged-bytes estimate for one Feldman/EC row (points, scalars,
    MSM staging; curve width is fixed)."""
    return 1024


@dataclass(frozen=True)
class TilePlan:
    """One planned tiling of a row axis. `tiles` are [lo, hi) spans;
    `inflight` is how many tiles the streaming driver may hold staged at
    once (the budget divides by it)."""

    rows: int
    row_bytes: int
    budget: int
    inflight: int
    tile_rows: int
    tiles: Tuple[Tuple[int, int], ...]

    def tile_bytes(self, rows: int) -> int:
        return rows * self.row_bytes

    @property
    def multi_tile(self) -> bool:
        return len(self.tiles) > 1


def plan_rows(
    rows: int, row_bytes: int, label: str = "pairs"
) -> Optional[TilePlan]:
    """Cut `rows` into tiles whose in-flight staged bytes fit the
    budget. Returns None when the plan is disabled or there is nothing
    to cut. Tile sizes are floored at one row (a starvation budget
    degrades, never refuses) and rounded to the active mesh's device
    count via tile_rows_for_mesh so cut tiles stay on the sharded
    path."""
    if rows <= 0 or row_bytes <= 0 or not memplan_enabled():
        return None
    from ..utils.pipeline import pipeline_enabled

    budget = mem_budget_bytes()
    inflight = 2 if pipeline_enabled() else 1
    tile = max(1, budget // max(1, row_bytes * inflight))
    if tile < rows:
        from .powm import active_mesh

        mesh = active_mesh()
        if mesh is not None:
            from ..parallel.shard_kernels import tile_rows_for_mesh

            tile = tile_rows_for_mesh(tile, mesh)
    tile = min(tile, rows)
    tiles = tuple(
        (lo, min(lo + tile, rows)) for lo in range(0, rows, tile)
    )
    _record_plan(label, rows, budget, tile, len(tiles))
    return TilePlan(
        rows=rows,
        row_bytes=row_bytes,
        budget=budget,
        inflight=inflight,
        tile_rows=tile,
        tiles=tiles,
    )


# ---------------------------------------------------------------------------
# Telemetry: the fsdkr_mem_* family. Gauges describe the latest plan and
# the staged-bytes high-water mark; counters accumulate across the
# measurement window (bench.py embeds the registry snapshot in every
# bench JSON, so these are stamped into every report).


def _plan_gauges():
    from ..telemetry import registry

    return (
        registry.gauge(
            "fsdkr_mem_budget_bytes",
            "staged-bytes budget of the streaming verification plan "
            "(FSDKR_MEM_BUDGET_MB)",
        ),
        registry.gauge(
            "fsdkr_mem_tile_rows",
            "rows per tile of the latest memory plan",
            labelnames=("family",),
        ),
        registry.gauge(
            "fsdkr_mem_plan_rows",
            "total rows of the latest memory plan",
            labelnames=("family",),
        ),
        registry.counter(
            "fsdkr_mem_tiles",
            "tiles executed by the streaming verification plan",
            labelnames=("family",),
        ),
        registry.counter(
            "fsdkr_mem_plans",
            "memory plans computed (multi=1 rows that needed >1 tile)",
            labelnames=("family", "multi"),
        ),
    )


def _record_plan(label, rows, budget, tile, n_tiles) -> None:
    budget_g, tile_g, rows_g, _tiles_c, plans_c = _plan_gauges()
    budget_g.set(budget)
    tile_g.set(tile, family=label)
    rows_g.set(rows, family=label)
    plans_c.inc(1, family=label, multi=(n_tiles > 1))


def count_tile(label: str) -> None:
    _plan_gauges()[3].inc(1, family=label)


class _StageTracker:
    """Live staged-tile bytes with a high-water mark. Single process-
    wide instance: the streaming drivers stage() a tile's estimated
    bytes before building it and release() after the verify+wipe, so
    the peak gauge is the enforceable reading the budget tests assert
    against."""

    def __init__(self):
        self._lock = threading.Lock()
        self.current = 0
        self.peak = 0

    def stage(self, nbytes: int) -> None:
        with self._lock:
            self.current += nbytes
            if self.current > self.peak:
                self.peak = self.current

    def release(self, nbytes: int) -> None:
        with self._lock:
            self.current = max(0, self.current - nbytes)

    def reset(self) -> None:
        with self._lock:
            self.current = 0
            self.peak = 0


_TRACKER = _StageTracker()


def _tracker_gauges():
    from ..telemetry import registry

    peak = registry.gauge(
        "fsdkr_mem_peak_resident_bytes",
        "high-water mark of live staged tile bytes (memory-plan "
        "estimate, stage/release accounted)",
    )
    peak.set_function(lambda: float(_TRACKER.peak))
    return peak, registry.install_rss_gauge()


def stage(nbytes: int) -> None:
    """Account a tile's estimated staged bytes as live (call before
    building/widening the tile)."""
    _tracker_gauges()
    _TRACKER.stage(nbytes)


def release(nbytes: int) -> None:
    """Release a tile's accounted bytes (call after verify + wipe)."""
    _TRACKER.release(nbytes)


def staged_peak_bytes() -> int:
    return _TRACKER.peak


def vmhwm_bytes() -> int:
    """Process peak RSS in bytes (telemetry.registry.vmhwm_bytes — the
    canonical reader; re-exported here so the memory-plan consumers and
    the `mem` bench block share one implementation)."""
    from ..telemetry.registry import vmhwm_bytes as _v

    return _v()


def mem_stats() -> dict:
    """The `mem` stat block of a bench JSON: the active budget, the
    cumulative staged-bytes counter, the tracked peak-resident estimate,
    and the process VmHWM ground truth. Tile/plan details live in the
    labeled fsdkr_mem_* metrics of the embedded telemetry snapshot."""
    from ..telemetry import registry

    _tracker_gauges()
    staged = registry.counter(
        "fsdkr_mem_bytes_staged",
        "cumulative bytes staged through the limb encoder",
    )
    tiles = _plan_gauges()[3]
    return {
        "plan_enabled": memplan_enabled(),
        "budget_bytes": mem_budget_bytes(),
        "bytes_staged": int(staged.total()),
        "peak_resident_bytes": int(_TRACKER.peak),
        "rss_peak_bytes": vmhwm_bytes(),
        "tiles": int(tiles.total()),
    }


def stats_reset() -> None:
    """Zero the stage tracker AND the cumulative tile/bytes counters
    for a fresh measurement window — the same windowing contract as
    rlc.stats_reset (bench.py calls both before each measured section,
    so a record's `mem` block describes that section, not the whole
    process). Plan gauges keep their readings (point-in-time state)."""
    _TRACKER.reset()
    from ..telemetry import registry

    registry.get_registry().reset_window(
        names=("fsdkr_mem_tiles", "fsdkr_mem_bytes_staged",
               "fsdkr_mem_plans")
    )


# ---------------------------------------------------------------------------


def streamed_rows(call, items: Sequence, row_bytes: int, label: str) -> List:
    """Run a ROW-LOCAL verdict call tile by tile under the memory plan
    and concatenate. Row-local means each row's verdict is a function of
    that row alone (any internal batching — e.g. validate_feldman's
    per-scheme RLC combine — must fall back to exact per-row checks on
    failure, which every backend batcher here does), so cutting the row
    axis cannot change any verdict. Single-tile plans call through
    unchanged."""
    plan = plan_rows(len(items), row_bytes, label=label)
    if plan is None or not plan.multi_tile:
        return call(items)
    out: List = []
    for lo, hi in plan.tiles:
        nbytes = plan.tile_bytes(hi - lo)
        stage(nbytes)
        try:
            count_tile(label)
            out.extend(call(items[lo:hi]))
        finally:
            release(nbytes)
    return out
