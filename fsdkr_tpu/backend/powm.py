"""Pluggable batched modular exponentiation for the *prover* side.

`distribute`'s per-receiver fan-out (SURVEY.md §1 "parallelism note": n
independent {encrypt, commit, PDL-prove, range-prove} units) is expressed
against a `batch_powm(bases, exps, moduli) -> list[int]` callable:

- host_powm: CPython pow loop (oracle).
- tpu_powm: one multi-modulus Montgomery launch per column
  (fsdkr_tpu.ops.montgomery), with the same padding/bucketing as the
  verifier backend.
"""

from __future__ import annotations

from typing import Callable, List, Sequence

from ..config import ProtocolConfig, DEFAULT_CONFIG

BatchPowm = Callable[[Sequence[int], Sequence[int], Sequence[int]], List[int]]

# Montgomery contexts keyed by (moduli, limb count): a refresh reuses the
# same modulus vectors across many launches (fused prover columns, beta^n,
# r^e, verifier equations), so the per-row host precompute (n', R^2 mod N)
# and the modulus tensor upload are paid once per vector, not per launch.
_CTX_CACHE: dict = {}
_CTX_CACHE_MAX = 64


def _cached_ctx(moduli, num_limbs):
    from ..ops.montgomery import BatchModExp

    key = (hash(tuple(moduli)), num_limbs)
    ctx = _CTX_CACHE.get(key)
    if ctx is None or ctx.ctx.moduli != list(moduli):
        if len(_CTX_CACHE) >= _CTX_CACHE_MAX:
            _CTX_CACHE.clear()
        ctx = BatchModExp(moduli, num_limbs)
        _CTX_CACHE[key] = ctx
    return ctx


def _pad_pow2(rows: int) -> int:
    """Pad batch sizes to powers of two (>= 8) so kernel shapes — and
    therefore XLA compilations — are reused across calls and rounds."""
    return max(8, 1 << (rows - 1).bit_length())


def host_powm(bases, exps, moduli) -> List[int]:
    return [pow(b, e, m) for b, e, m in zip(bases, exps, moduli)]


def tpu_powm(bases, exps, moduli) -> List[int]:
    from ..ops.limbs import limbs_for_bits

    if not bases:
        return []
    b = len(bases)
    pad = _pad_pow2(b) - b
    bases = list(bases) + [1] * pad
    exps = list(exps) + [0] * pad
    moduli = list(moduli) + [3] * pad
    k = limbs_for_bits(max(m.bit_length() for m in moduli))
    return _cached_ctx(moduli, k).modexp(bases, exps)[:b]


def get_batch_powm(config: ProtocolConfig = DEFAULT_CONFIG) -> BatchPowm:
    return tpu_powm if config.backend == "tpu" else host_powm


def powm_columns(powm: BatchPowm, *columns):
    """Fuse several (bases, exps, moduli) columns of the same modulus
    width class into ONE batched launch and split the results back.

    Rationale: a batched modexp costs sequential depth proportional to the
    *widest* exponent in the batch regardless of row count, so columns with
    narrow exponents ride free when concatenated with a wide column —
    turning k launches of depth d_1..d_k into one launch of depth max(d_i).
    """
    flat_b, flat_e, flat_m, sizes = [], [], [], []
    for bases, exps, moduli in columns:
        flat_b += list(bases)
        flat_e += list(exps)
        flat_m += list(moduli)
        sizes.append(len(bases))
    res = powm(flat_b, flat_e, flat_m)
    out, at = [], 0
    for s in sizes:
        out.append(res[at : at + s])
        at += s
    return out
