"""Pluggable batched modular exponentiation for the *prover* side.

`distribute`'s per-receiver fan-out (SURVEY.md §1 "parallelism note": n
independent {encrypt, commit, PDL-prove, range-prove} units) is expressed
against a `batch_powm(bases, exps, moduli) -> list[int]` callable:

- host_powm: CPython pow loop (oracle).
- tpu_powm: one multi-modulus Montgomery launch per column
  (fsdkr_tpu.ops.montgomery), with the same padding/bucketing as the
  verifier backend.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from ..config import ProtocolConfig

BatchPowm = Callable[[Sequence[int], Sequence[int], Sequence[int]], List[int]]

# Active device mesh for sharded launches. The protocol entry points
# (get_batch_powm on the prover side, TpuBatchVerifier on the verifier
# side) install the mesh described by config.mesh_shape; None means
# single-device execution (the JAX default placement). Process-wide by
# design: a collect()/distribute() call configures it on entry.
_MESH = None


def apply_mesh(config: "ProtocolConfig") -> None:
    """Install (or clear) the device mesh described by config.mesh_shape."""
    global _MESH
    if config.backend != "tpu" or config.mesh_shape is None:
        _MESH = None
        return
    from ..parallel.mesh import make_mesh

    shape = tuple(config.mesh_shape)
    if _MESH is None or _MESH.devices.shape != shape:
        _MESH = make_mesh(
            shape, tuple(f"batch{i}" if i else "batch" for i in range(len(shape)))
        )


def active_mesh():
    return _MESH


# Montgomery contexts keyed by (moduli, limb count, mesh): a refresh reuses
# the same modulus vectors across many launches (fused prover columns,
# beta^n, r^e, verifier equations) AND across collect()/distribute() calls
# of a stable committee, so the per-row host precompute (n', R^2 mod N)
# and the modulus tensor upload are paid once per vector. Lives in the
# process-wide bytes-budgeted precompute LRU (utils.lru) alongside the
# comb window tables; overflow evicts the OLDEST entry only — the old
# clear()-on-overflow behavior flushed every hot context mid-run. Keyed
# by a hash prefix with a full moduli-equality check on hit, so a
# collision can only cost a rebuild, never reuse the wrong constants.


def _cached_ctx(moduli, num_limbs):
    from ..ops.montgomery import BatchModExp
    from ..utils.lru import global_cache

    cache = global_cache()
    key = ("mont-ctx", hash(tuple(moduli)), num_limbs, id(_MESH))
    ctx = cache.get(key) if cache.budget > 0 else None
    # the mesh is validated BY IDENTITY on every hit: the cache outlives
    # apply_mesh reconfigurations, and a recycled id() for a new Mesh
    # object must rebuild rather than reuse arrays sharded for the old one
    if ctx is None or ctx.ctx.moduli != list(moduli) or ctx.mesh is not _MESH:
        ctx = BatchModExp(moduli, num_limbs, mesh=_MESH)
        if cache.budget > 0:
            # host arrays: n/r2/one_mont (rows x limbs u32) + n_prime,
            # roughly doubled for the device copies
            cache.put(key, ctx, len(moduli) * num_limbs * 4 * 8)
    return ctx


def powm_cache_stats():
    """Counters of the persistent precompute cache (Montgomery contexts,
    comb window tables, comb power ladders): {entries, bytes, budget,
    hits, misses, evictions}. The bench battery asserts table-build
    elimination on warm collects through the hit counter."""
    from ..utils.lru import cache_stats

    return cache_stats()


def _pad_pow2(rows: int) -> int:
    """Pad batch sizes to powers of two (>= 8) so kernel shapes — and
    therefore XLA compilations — are reused across calls and rounds. With
    a mesh active, additionally round up to a multiple of the device count
    so rows split evenly."""
    p = max(8, 1 << (rows - 1).bit_length())
    if _MESH is not None:
        from ..parallel.shard_kernels import padded_rows

        p = padded_rows(p, _MESH)
    return p


def host_powm(bases, exps, moduli) -> List[int]:
    """Host batched modexp: the system GMP (the reference's own bigint
    backend — native/gmp.py, FSDKR_GMP gate) when present, the own
    native Montgomery core otherwise, CPython pow as the last fallback.
    Measured on this box at the distribute() wall shape (2048-bit
    exponent mod a 4096-bit n^2): GMP 10.7 ms/op, own core 20.9 ms/op,
    CPython 101 ms/op. This is the CPU baseline the TPU backend is
    benchmarked against."""
    from .. import native
    from ..native import gmp

    if bases:
        # prover/precompute roofline stamp: the device launches have
        # stamped since round 2; the host engines carry the same
        # analytic pricing so per-phase mfu() covers the prover columns
        # too. Exponents are priced at the MODULUS width: actual
        # exponent bit-lengths are secret-derived on prover paths and
        # must not influence exported MAC counts (SECURITY.md
        # "Telemetry discipline"); the enabled-gate also keeps the
        # O(rows) width scan off the untraced hot path.
        from ..utils.roofline import stamp_generic_host
        from ..utils.trace import get_tracer

        if get_tracer().enabled:
            mod_bits = max(m.bit_length() for m in moduli)
            stamp_generic_host(len(bases), mod_bits, mod_bits)
    if gmp.available():
        return gmp.powm_batch(list(bases), list(exps), list(moduli))
    return native.modexp_batch(list(bases), list(exps), list(moduli))


def tpu_modmul(a, b, moduli) -> List[int]:
    """Row-wise a*b mod moduli as one padded multi-modulus launch."""
    if not a:
        return []
    if not _device_powm():  # CPU fallback: a bigint mulmod is pure C —
        # unless the native row pool has real parallelism to offer
        # (FSDKR_THREADS > 1), where the threaded Montgomery batch wins
        from .. import native

        if len(a) >= 64 and native.available() and native.thread_count() > 1:
            return native.modmul_batch(list(a), list(b), list(moduli))
        return [(x * y) % m for x, y, m in zip(a, b, moduli)]
    from ..ops.limbs import limbs_for_bits
    from ..utils.roofline import modmul_macs
    from ..utils.trace import get_tracer

    rows = len(a)
    pad = _pad_pow2(rows) - rows
    a = list(a) + [1] * pad
    b = list(b) + [1] * pad
    moduli = list(moduli) + [3] * pad
    k = limbs_for_bits(max(m.bit_length() for m in moduli))
    get_tracer().add_macs(modmul_macs(len(a), k))
    return _cached_ctx(moduli, k).modmul(a, b)[:rows]


# Generic-kernel routing: batches at least this wide take the RNS/MXU
# pipeline (ops.rns) instead of the CIOS/VPU kernel. Measured crossover
# on v5e is a few hundred rows; override with FSDKR_RNS_MIN_ROWS
# (0 = always RNS, large = never).
import os as _os

_RNS_MIN_ROWS = int(_os.environ.get("FSDKR_RNS_MIN_ROWS", "512"))


def _device_powm() -> bool:
    """config.device_powm's routing, sans the backend gate — these
    helpers are only reachable from the tpu backend (get_batch_powm
    returns host_powm for backend="host"). The tests force =1
    (tests/conftest.py) to keep kernel coverage; auto routes host on
    XLA:CPU, where the native C++ core beats the batched kernels."""
    from ..config import _route_device

    return _route_device("FSDKR_DEVICE_POWM")

# HBM ceiling: the modexp kernels materialize a 16-entry window table
# over the whole batch (generic: 16*R rows; comb: 16*W*G rows with
# W = exp_bits/4 windows). At the n=256 collect shape an unchunked
# launch would need a multi-GB (comb: multi-TB) table, so batches are
# tiled: generic launches at most _MAX_ROWS rows, comb launches at most
# _MAX_ROWS table rows (w_cnt * group-chunk). Tiles run through the
# double-buffered pipeline (utils.pipeline): tile k+1's host staging
# (limb packing, Montgomery entry) overlaps tile k's engine execution;
# at most two tiles are in flight so the HBM cap still holds at 2x tile.
_MAX_ROWS = int(_os.environ.get("FSDKR_MAX_ROWS_PER_LAUNCH", "16384"))


def _tile_spans(total: int, tile: int):
    """Row spans of at most `tile` rows, aligned to the active mesh so a
    cut tile never falls off the sharded path."""
    if _MESH is not None:
        from ..parallel.shard_kernels import tile_rows_for_mesh

        tile = tile_rows_for_mesh(tile, _MESH)
    return [(lo, min(lo + tile, total)) for lo in range(0, total, tile)]

# modulus width classes with prepared RNS bases (caps distinct compiled
# kernel shapes; moduli bucket up to the nearest class)
_RNS_WIDTH_CLASSES = (256, 512, 1024, 1536, 2048, 3072, 4096)


def tpu_powm(bases, exps, moduli) -> List[int]:
    if not bases:
        return []
    if not _device_powm():  # CPU fallback: native C++ Montgomery core
        return host_powm(bases, exps, moduli)
    if len(bases) > _MAX_ROWS:  # HBM tiling: double-buffered launches
        from ..utils.pipeline import pipelined

        parts = pipelined(
            lambda lo, hi: tpu_powm(bases[lo:hi], exps[lo:hi], moduli[lo:hi]),
            _tile_spans(len(bases), _MAX_ROWS),
        )
        return [v for part in parts for v in part]
    from ..ops.limbs import bucket_exp_bits, limbs_for_bits
    from ..utils.roofline import generic_modexp_macs
    from ..utils.trace import get_tracer

    b = len(bases)
    pad = _pad_pow2(b) - b
    bases = list(bases) + [1] * pad
    exps = list(exps) + [0] * pad
    moduli = list(moduli) + [3] * pad

    width = max(m.bit_length() for m in moduli)
    e_bits = bucket_exp_bits(exps)
    if b >= _RNS_MIN_ROWS:
        for cls in _RNS_WIDTH_CLASSES:
            if width <= cls:
                from ..ops.rns import rns_modexp

                get_tracer().add_macs(
                    generic_modexp_macs(len(bases), e_bits, cls // 16)
                )
                return rns_modexp(bases, exps, moduli, cls, mesh=_MESH)[:b]

    k = limbs_for_bits(width)
    get_tracer().add_macs(generic_modexp_macs(len(bases), e_bits, k))
    return _cached_ctx(moduli, k).modexp(bases, exps)[:b]


def tpu_powm_shared(bases, exps_per_group, moduli) -> List[List[int]]:
    """Fixed-base comb launch: bases[g]^exps_per_group[g][m] mod moduli[g].

    Group count and rows-per-group are padded to powers of two (dummy
    groups use modulus 3, dummy rows exponent 0) so compiled kernel shapes
    are reused across committee sizes. Launches tile so the comb's
    16 * w_cnt * G-row window table stays under the HBM cap.
    """
    from ..ops.limbs import WINDOW_BITS, bucket_exp_bits, limbs_for_bits
    from ..ops.montgomery import shared_base_modexp

    if not bases:
        return []
    if not _device_powm():  # CPU fallback: native fixed-base comb —
        # one squaring ladder per (base, modulus), amortized over the
        # group's rows (same structure the device comb kernel exploits)
        from .. import native

        return [
            native.modexp_shared(b, es, m) if es else []
            for b, es, m in zip(bases, exps_per_group, moduli)
        ]
    w_cnt = max(
        1,
        bucket_exp_bits(e for grp in exps_per_group for e in grp)
        // WINDOW_BITS,
    )
    m_max = max((len(e) for e in exps_per_group), default=1) or 1
    m_pad = max(8, 1 << (m_max - 1).bit_length())
    width = max(m.bit_length() for m in moduli)
    # The RNS comb builds window tables on the fly, so its footprint is
    # the (w_cnt, G) power ladder and the (G*M) accumulator — budget
    # 16*_MAX_ROWS rows for each. The CIOS comb — small batches, and any
    # modulus wider than the largest prepared RNS class — materializes
    # (16, w_cnt, G) tables: budget _MAX_ROWS.
    rns_path = (
        len(bases) * m_max >= _RNS_MIN_ROWS and width <= _RNS_WIDTH_CLASSES[-1]
    )
    budget = (16 * _MAX_ROWS) if rns_path else _MAX_ROWS
    # power-of-two chunk sizes: a full chunk's padded size equals the
    # chunk, so tiling terminates for any FSDKR_MAX_ROWS_PER_LAUNCH value
    row_chunk = max(8, 1 << (budget.bit_length() - 1))
    if m_pad > row_chunk:  # huge per-group row counts: tile the row axis
        from ..utils.pipeline import pipelined

        parts = pipelined(
            lambda lo, hi: tpu_powm_shared(
                bases, [e[lo:hi] for e in exps_per_group], moduli
            ),
            [
                (lo, lo + row_chunk)
                for lo in range(0, m_max, row_chunk)
            ],
        )
        return [
            [v for part in parts for v in part[i]] for i in range(len(bases))
        ]
    g_cap = max(
        1, 1 << max(0, min(budget // w_cnt, budget // m_pad).bit_length() - 1)
    )
    if len(bases) > g_cap:  # HBM tiling over group chunks, double-buffered
        from ..utils.pipeline import pipelined

        parts = pipelined(
            lambda lo, hi: tpu_powm_shared(
                bases[lo:hi], exps_per_group[lo:hi], moduli[lo:hi]
            ),
            [
                (lo, min(lo + g_cap, len(bases)))
                for lo in range(0, len(bases), g_cap)
            ],
        )
        return [grp for part in parts for grp in part]
    g = len(bases)
    g_pad = max(2, 1 << (g - 1).bit_length())
    if _MESH is not None:
        from ..parallel.shard_kernels import padded_rows

        g_pad = padded_rows(g_pad, _MESH)
    bases = list(bases) + [1] * (g_pad - g)
    moduli = list(moduli) + [3] * (g_pad - g)
    exps = [list(e) + [0] * (m_pad - len(e)) for e in exps_per_group]
    exps += [[0] * m_pad] * (g_pad - g)

    from ..utils.roofline import shared_modexp_macs
    from ..utils.trace import get_tracer

    width = max(m.bit_length() for m in moduli)
    if g_pad * m_pad >= _RNS_MIN_ROWS:
        for cls in _RNS_WIDTH_CLASSES:
            if width <= cls:
                from ..ops.rns import rns_modexp_shared

                get_tracer().add_macs(
                    shared_modexp_macs(g_pad, m_pad, w_cnt, cls // 16)
                )
                out = rns_modexp_shared(bases, exps, moduli, cls, mesh=_MESH)
                return [out[i][: len(exps_per_group[i])] for i in range(g)]

    k = limbs_for_bits(width)
    get_tracer().add_macs(shared_modexp_macs(g_pad, m_pad, w_cnt, k))
    out = shared_base_modexp(
        bases, exps, moduli, k, ctx=_cached_ctx(moduli, k).ctx, mesh=_MESH
    )
    return [out[i][: len(exps_per_group[i])] for i in range(g)]


# Below this row count, a (base, modulus) group takes the generic windowed
# kernel: the comb's per-group ladder only pays for itself once its cost is
# amortized over enough rows.
_SHARED_MIN_ROWS = 4


def multiexp_enabled() -> bool:
    """FSDKR_MULTIEXP gates the joint multi-exponentiation planner: =0
    reverts every caller (verifier equations, prover columns) to the
    per-term column path for A/B isolation. Read at call time so the
    bench battery can toggle it per step."""
    return _os.environ.get("FSDKR_MULTIEXP", "1").lower() not in (
        "0", "off", "false", "no",
    )


def rangeopt_enabled() -> bool:
    """FSDKR_RANGEOPT gates the range-family verifier optimizations
    (shared-exponent ladders for the s^n mod n^2 column, the joint
    fixed-base comb for h1^s1*h2^s2 mod N~, and the concurrent column
    scheduler in tpu_verifier.verify_pairs): =0 reverts the range family
    to the per-row joint/column path for A/B isolation. Verdicts and
    identifiable-abort blame are bit-identical either way
    (tests/test_range_engines.py). Read at call time so the bench
    battery can toggle it per step."""
    return _os.environ.get("FSDKR_RANGEOPT", "1").lower() not in (
        "0", "off", "false", "no",
    )


def tpu_powm_shared_exp(bases, exp, modulus, aux_bases=None, aux_exps=None):
    """Shared-exponent column: bases[r]^exp (* aux_bases[r]^aux_exps[r])
    mod modulus — ONE public exponent and modulus across the whole batch
    (the Alice-range u-power shape: every row of a receiver's s^n column
    raises a different wire base to the receiver's public Paillier n).

    Host route: the native shared-schedule threaded engine
    (native.shared_exp_powm; GMP mpn inner loop when present), which
    folds the optional per-row short term into the one squaring chain.
    Device route: the rows x limbs shared-exponent kernel
    (ops.montgomery.shared_exp_modexp) — the digit schedule is a dynamic
    input, so committees share compiled kernels per shape bucket — with
    the aux term through the generic windowed kernel and a batched
    modmul recombine. Mesh launches ride the sharded generic kernel
    (exponent replicated row-wise): correctness-identical, and the
    sharded path keeps its own tuning."""
    rows = len(bases)
    if rows == 0:
        return []
    if not _device_powm():
        from .. import native

        if native.available():
            from ..utils.roofline import stamp_generic_host
            from ..utils.trace import get_tracer

            if get_tracer().enabled:
                mod_bits = modulus.bit_length()
                stamp_generic_host(rows, mod_bits, mod_bits)
            return native.shared_exp_powm(
                bases, exp, modulus, aux_bases, aux_exps
            )
        out = host_powm(bases, [exp] * rows, [modulus] * rows)
        if aux_bases is not None:
            ap = host_powm(aux_bases, aux_exps, [modulus] * rows)
            out = [x * y % modulus for x, y in zip(out, ap)]
        return out
    from ..ops.limbs import bucket_exp_bits, limbs_for_bits
    from ..utils.roofline import generic_modexp_macs
    from ..utils.trace import get_tracer

    if _MESH is not None or rows > _MAX_ROWS:
        # sharded/tiled launches keep the generic per-row kernel path
        out = tpu_powm(bases, [exp] * rows, [modulus] * rows)
    else:
        from ..ops.montgomery import shared_exp_modexp

        pad = _pad_pow2(rows) - rows
        padded = list(bases) + [1] * pad
        k = limbs_for_bits(modulus.bit_length())
        get_tracer().add_macs(
            generic_modexp_macs(len(padded), bucket_exp_bits([exp]), k)
        )
        ctx = _cached_ctx([modulus] * len(padded), k)
        out = shared_exp_modexp(
            padded, exp, modulus, k, ctx=ctx
        )[:rows]
    if aux_bases is not None:
        ap = tpu_powm(list(aux_bases), list(aux_exps), [modulus] * rows)
        out = tpu_modmul(out, ap, [modulus] * rows)
    return out


def joint_comb2(base1, exps1, base2, exps2, modulus):
    """base1^exps1[r] * base2^exps2[r] mod modulus — the 2-term
    fixed-base shape of the mod-N~ equations (h1^s1 * h2^s2 per receiver
    environment), as a single joint comb apply: one pass over both
    persistent window tables per row, one Montgomery exit, no separate
    columns and no recombination modmul. Tables persist cross-epoch in
    the public-base LRU (native._cached_comb_table — PUBLIC bases only).
    Device route: both groups in one comb launch + a batched modmul."""
    rows = len(exps1)
    if rows == 0:
        return []
    if len(exps2) != rows:
        raise ValueError("joint_comb2 column length mismatch")
    if not _device_powm():
        from .. import native
        from ..utils.roofline import stamp_shared_host
        from ..utils.trace import get_tracer

        if get_tracer().enabled:
            mod_bits = modulus.bit_length()
            stamp_shared_host(2, rows, mod_bits, mod_bits)
        res = native.comb2_apply(base1, exps1, base2, exps2, modulus)
        if res is not None:
            return res
        r1 = native.modexp_shared(base1, list(exps1), modulus)
        r2 = native.modexp_shared(base2, list(exps2), modulus)
        return [a * b % modulus for a, b in zip(r1, r2)]
    r1, r2 = tpu_powm_shared(
        [base1, base2], [list(exps1), list(exps2)], [modulus, modulus]
    )
    return tpu_modmul(r1, r2, [modulus] * rows)


def fold_cache_enabled() -> bool:
    """FSDKR_FOLD_CACHE gates the cross-launch fold-ladder cache
    (fold_ladder2): =0 reverts merged fold lhs rows to the plain
    multi_powm ladder for A/B isolation. Read at call time so the bench
    battery and the CI legs can toggle it per step."""
    return _os.environ.get("FSDKR_FOLD_CACHE", "1").lower() not in (
        "0", "off", "false", "no",
    )


def fold_ladder2(rows):
    """Merged 2-term shared-base fold lhs rows
    ``[((b1, b2), (e1, e2), mod), ...]`` — ONE row per RLC group (the
    h1^S1 * h2^S3 mod N~ ladder each merged pair-family group launches
    at finish) — through the persistent public-base comb tables when
    the shard is warm.

    A lone merged row sits far below multi_powm's _SHARED_MIN_ROWS comb
    threshold, so without this helper every launch re-runs a full-width
    Straus ladder per group even when the committee's h1/h2 tables
    could be resident. Deferred build keeps one-shot committees
    untaxed (a comb build costs several ladders): the FIRST launch of a
    (b1, b2, mod) family only drops a "fold-seen" marker in the LRU and
    takes the one-shot ladder; a SECOND launch proves the shard is warm
    and builds + applies the comb tables; later launches apply the
    resident tables with no full-width squaring chain at all. Warm
    applies vs builds/fallbacks are counted into backend.rlc's event
    stats (ladder_cache_hits / ladder_cache_misses).

    Host route only — the device comb has its own batching economics,
    so the device route and FSDKR_FOLD_CACHE=0 take the multi_powm
    path. Bit-identical results on every route (pinned by
    tests/test_xsession.py)."""
    if not rows:
        return []
    if not fold_cache_enabled() or _device_powm():
        return multi_powm(
            [r[0] for r in rows], [r[1] for r in rows], [r[2] for r in rows]
        )
    from . import rlc
    from .. import native
    from ..utils.lru import global_cache
    from ..utils.roofline import stamp_shared_host
    from ..utils.trace import get_tracer

    cache = global_cache()
    out: List[Optional[int]] = [None] * len(rows)
    fallback: List[int] = []
    buckets = {}
    for i, ((b1, b2), _exps, mod) in enumerate(rows):
        buckets.setdefault((b1, b2, mod), []).append(i)
    for (b1, b2, mod), idxs in buckets.items():
        if cache.budget <= 0:
            fallback.extend(idxs)
            continue
        seen_key = ("fold-seen", b1, b2, mod)
        if cache.peek(seen_key) is None:
            # first launch of this base family on this shard: mark it
            # seen and keep the one-shot ladder — building tables only
            # pays once a repeat launch proves reuse
            cache.put(seen_key, True, 64)
            rlc.count("ladder_cache_misses", len(idxs))
            fallback.extend(idxs)
            continue
        if get_tracer().enabled:
            mod_bits = mod.bit_length()
            stamp_shared_host(2, len(idxs), mod_bits, mod_bits)
        st: dict = {}
        res = native.comb2_apply(
            b1,
            [rows[i][1][0] for i in idxs],
            b2,
            [rows[i][1][1] for i in idxs],
            mod,
            stats_out=st,
            # the fold exponents are random rho-weighted sums whose
            # natural limb width jitters launch-to-launch; a nonzero
            # min_exp_limbs opts into comb2_apply's width-tolerant
            # table reuse so the jitter cannot fork the cache key and
            # turn warm applies into rebuilds
            min_exp_limbs=rlc.RLC_BITS // 64 + 1,
        )
        if res is None:
            rlc.count("ladder_cache_misses", len(idxs))
            fallback.extend(idxs)
            continue
        rlc.count(
            "ladder_cache_hits" if st.get("cached") else "ladder_cache_misses",
            len(idxs),
        )
        for i, v in zip(idxs, res):
            out[i] = v
    if fallback:
        vals = multi_powm(
            [rows[i][0] for i in fallback],
            [rows[i][1] for i in fallback],
            [rows[i][2] for i in fallback],
        )
        for i, v in zip(fallback, vals):
            out[i] = v
    return out


def batch_base_inv(values, moduli):
    """Montgomery-trick batched modular inversion on the host: rows group
    by modulus, one `pow(prod, -1, m)` per group plus ~3 bigint mulmods
    per row (CPython bigint mulmul is C-speed; the serial `pow(v,-1,m)`
    this replaces costs 0.5-1.7 ms per row at protocol widths). Returns
    one entry per row; a non-invertible value poisons only its own group,
    which falls back to per-row inversion and reports None for the bad
    rows — the caller decides the failure semantics (the verifier fails
    the row exactly as the host oracle does).

    This is the host-side sibling of the device product tree
    (ops.montgomery.batch_mod_inv_grouped, used by the column path's
    result inversions): both implement the same group-by-modulus /
    poison-only-own-group policy, and the joint/column verdict-identity
    guarantee (tests/test_multiexp.py) depends on the two staying in
    semantic lockstep."""
    groups: dict = {}
    for i, m in enumerate(moduli):
        groups.setdefault(m, []).append(i)
    out: List = [None] * len(values)
    for m, idxs in groups.items():
        if m <= 1:
            continue
        # prefix products: pref[j] = v_0 * ... * v_{j-1} mod m
        pref = [1] * (len(idxs) + 1)
        for j, i in enumerate(idxs):
            pref[j + 1] = pref[j] * (values[i] % m) % m
        try:
            inv = pow(pref[-1], -1, m)
        except ValueError:  # some row not invertible: per-row fallback
            for i in idxs:
                try:
                    out[i] = pow(values[i] % m, -1, m)
                except ValueError:
                    out[i] = None
            continue
        for j in range(len(idxs) - 1, -1, -1):
            out[idxs[j]] = pref[j] * inv % m
            inv = inv * (values[idxs[j]] % m) % m
    return out


# Device joint-ladder term cap: an n-term row (the FSDKR_RLC aggregated
# groups reach 2n+1 terms) is split into sub-rows of at most this many
# terms before a device launch — the CIOS/RNS kernels unroll one table
# lookup per term per window inside the traced loop body, so an
# unbounded term count would compile a fresh, enormous kernel variant
# per group shape. Sub-rows share the launch (same bucket) and their
# partial products recombine with host bigint mulmods; the repeated
# short squaring chains cost ~(chunks-1)*chain_bits extra squarings,
# noise at the 128-384-bit aggregate-chain widths. The native C++
# engine takes n-term rows directly (no cap below 4096 terms).
_DEVICE_MAX_TERMS = int(_os.environ.get("FSDKR_DEVICE_MAX_TERMS", "16"))


def _joint_rows(bases_rows, exps_rows, moduli, device: bool) -> List[int]:
    """Straus joint ladders for rows of >= 2 per-row-base terms, bucketed
    by (term count, modulus limb class) per launch. Rows may carry
    different term counts (variable arity: the RLC aggregated groups mix
    2-term merged-base rows with n-term per-row-base rows); each arity
    shape is its own launch bucket. Exponents must be non-negative
    (negatives are folded by multi_powm)."""
    from ..ops.limbs import bucket_exp_bits, limbs_for_bits

    cap = _DEVICE_MAX_TERMS if device else 0
    if cap and any(len(bs) > cap for bs in bases_rows):
        # split oversized rows into <= cap-term sub-rows; evaluate the
        # whole (split + small) row set in one recursion, then fold each
        # original row's partials back with host mulmods (C-speed bigint)
        sub_b: List = []
        sub_e: List = []
        sub_m: List = []
        owners: List[List[int]] = []
        for i, (bs, es, m) in enumerate(zip(bases_rows, exps_rows, moduli)):
            slots = []
            for lo in range(0, len(bs), cap) if len(bs) > cap else [0]:
                hi = min(lo + cap, len(bs)) if len(bs) > cap else len(bs)
                slots.append(len(sub_m))
                sub_b.append(tuple(bs[lo:hi]))
                sub_e.append(tuple(es[lo:hi]))
                sub_m.append(m)
            owners.append(slots)
        res = _joint_rows(sub_b, sub_e, sub_m, device)
        return [
            _prod_mod([res[s] for s in slots], m)
            for slots, m in zip(owners, moduli)
        ]
    out: List = [None] * len(moduli)
    # bucket by (term count, modulus limb class, per-term width classes):
    # a launch's shared chain is as deep as its widest term and each term
    # position's window count follows the launch-wide max, so fusing rows
    # of different width shapes would inflate the narrow ones (same
    # pricing rule as powm_columns)
    buckets: dict = {}
    for i, (bs, es, m) in enumerate(zip(bases_rows, exps_rows, moduli)):
        key = (
            len(bs),
            limbs_for_bits(m.bit_length()),
            tuple(bucket_exp_bits([e_t]) for e_t in es),
        )
        buckets.setdefault(key, []).append(i)
    for (k, _limbs, _widths), idxs in buckets.items():
        b = [tuple(bases_rows[i]) for i in idxs]
        e = [tuple(exps_rows[i]) for i in idxs]
        m = [moduli[i] for i in idxs]
        if device:
            res = _device_joint_launch(b, e, m, k)
        else:
            from .. import native
            from ..utils.roofline import stamp_generic_host
            from ..utils.trace import get_tracer

            # host joint ladder: one shared squaring chain per row —
            # priced at the modulus width (exponent widths may be
            # secret-derived; see SECURITY.md "Telemetry discipline")
            if get_tracer().enabled:
                mod_bits = max(mi.bit_length() for mi in m)
                stamp_generic_host(len(b), mod_bits, mod_bits)
            res = native.multi_modexp_batch(b, e, m)
        for i, v in zip(idxs, res):
            out[i] = v
    return out


def _device_joint_launch(bases_rows, exps_rows, moduli, k) -> List[int]:
    """One padded device multi-exp launch (CIOS or RNS by row count),
    mirroring tpu_powm's routing/padding."""
    from ..ops.limbs import bucket_exp_bits, limbs_for_bits
    from ..utils.roofline import generic_modexp_macs, montmul_macs
    from ..utils.trace import get_tracer

    rows = len(moduli)
    if rows > _MAX_ROWS:  # HBM tiling: double-buffered launches
        from ..utils.pipeline import pipelined

        parts = pipelined(
            lambda lo, hi: _device_joint_launch(
                bases_rows[lo:hi], exps_rows[lo:hi], moduli[lo:hi], k
            ),
            _tile_spans(rows, _MAX_ROWS),
        )
        return [v for part in parts for v in part]
    pad = _pad_pow2(rows) - rows
    bases_rows = list(bases_rows) + [(1,) * k] * pad
    exps_rows = list(exps_rows) + [(0,) * k] * pad
    moduli = list(moduli) + [3] * pad
    width = max(m.bit_length() for m in moduli)
    exp_bits = tuple(
        bucket_exp_bits([e[t] for e in exps_rows]) for t in range(k)
    )
    kk = limbs_for_bits(width)
    # the shared chain is as deep as the widest term; every further term
    # adds only its own window lookups (+ table build) on top
    extra = sorted(exp_bits, reverse=True)[1:]
    get_tracer().add_macs(
        generic_modexp_macs(len(moduli), max(exp_bits), kk)
        + sum(eb // 4 + 15 for eb in extra) * len(moduli) * montmul_macs(kk)
    )
    if len(moduli) >= _RNS_MIN_ROWS:
        for cls in _RNS_WIDTH_CLASSES:
            if width <= cls:
                from ..ops.rns import rns_multi_modexp

                return rns_multi_modexp(
                    bases_rows, exps_rows, moduli, cls, exp_bits, mesh=_MESH
                )[:rows]
    from ..ops.montgomery import multi_modexp

    return multi_modexp(
        bases_rows, exps_rows, moduli, kk, exp_bits,
        ctx=_cached_ctx(moduli, kk), mesh=_MESH,
    )[:rows]


def multi_powm(bases_rows, exps_rows, moduli, device: Optional[bool] = None):
    """Joint multi-exponentiation rows: prod_t bases[r][t]^exps[r][t] mod
    moduli[r], each term routed to the engine that prices it best:

    - negative exponents fold into the ladder by inverting the base once
      (batch_base_inv; a non-invertible base raises ValueError — callers
      needing per-row failure semantics pre-fold and gate themselves);
    - terms whose (base, modulus) pair repeats across >= _SHARED_MIN_ROWS
      rows ride the fixed-base comb (their squaring chain is already
      amortized per group, which a per-row joint ladder cannot beat);
    - rows left with >= 2 per-row terms ride the Straus joint ladder
      (one shared squaring chain, k window lookups per window);
    - rows left with 1 term ride the generic windowed kernel, fused by
      exponent width;
    - per-row recombination of the parts happens here (batched modmul on
      the device path, C-speed bigint mulmod on the host path), so the
      planner's callers never submit recombination columns.

    This is algebraically exact — no random linear combination, no
    soundness assumption on the (adversarial) moduli; see SECURITY.md.
    """
    rows = len(moduli)
    if rows == 0:
        return []
    if device is None:
        device = _device_powm()

    # fold negative exponents: invert those bases, batched per modulus
    neg_idx = [
        (i, t)
        for i, es in enumerate(exps_rows)
        for t, e_t in enumerate(es)
        if e_t < 0
    ]
    if neg_idx:
        bases_rows = [list(bs) for bs in bases_rows]
        exps_rows = [list(es) for es in exps_rows]
        invs = batch_base_inv(
            [bases_rows[i][t] for i, t in neg_idx],
            [moduli[i] for i, _ in neg_idx],
        )
        for (i, t), inv in zip(neg_idx, invs):
            if inv is None:
                raise ValueError(
                    "multi_powm: negative exponent with non-invertible base"
                )
            bases_rows[i][t] = inv
            exps_rows[i][t] = -exps_rows[i][t]

    # shared-base detection across all (row, term) instances; groups
    # split by exponent width class as well — the comb's per-row lookup
    # count follows the group's widest exponent, so a 256-bit share
    # column must not ride a 2048-bit nonce column's window count
    from ..ops.limbs import bucket_exp_bits

    counts: dict = {}
    for i, (bs, es, m) in enumerate(zip(bases_rows, exps_rows, moduli)):
        for t, (b, e_t) in enumerate(zip(bs, es)):
            counts.setdefault((b, m, bucket_exp_bits([e_t])), []).append(
                (i, t)
            )
    comb_groups = [
        (key, inst)
        for key, inst in counts.items()
        if len(inst) >= _SHARED_MIN_ROWS
    ]

    parts: List[List[int]] = [[] for _ in range(rows)]  # factors per row
    if comb_groups:
        g_bases = [key[0] for key, _ in comb_groups]
        g_exps = [
            [exps_rows[i][t] for i, t in inst] for _, inst in comb_groups
        ]
        g_mods = [key[1] for key, _ in comb_groups]
        if device:
            res = tpu_powm_shared(g_bases, g_exps, g_mods)
        else:  # host engine: native fixed-base comb per group
            from .. import native

            res = [
                native.modexp_shared(b, es, m) if es else []
                for b, es, m in zip(g_bases, g_exps, g_mods)
            ]
        for (_, inst), vals in zip(comb_groups, res):
            for (i, t), v in zip(inst, vals):
                parts[i].append(v)
        comb_instances = {it for _, inst in comb_groups for it in inst}
    else:
        comb_instances = set()

    loners: List[List[int]] = [[] for _ in range(rows)]  # term idx per row
    for i, bs in enumerate(bases_rows):
        for t in range(len(bs)):
            if (i, t) not in comb_instances:
                loners[i].append(t)

    joint_idx = [i for i in range(rows) if len(loners[i]) >= 2]
    single_idx = [i for i in range(rows) if len(loners[i]) == 1]
    if joint_idx:
        res = _joint_rows(
            [[bases_rows[i][t] for t in loners[i]] for i in joint_idx],
            [[exps_rows[i][t] for t in loners[i]] for i in joint_idx],
            [moduli[i] for i in joint_idx],
            device,
        )
        for i, v in zip(joint_idx, res):
            parts[i].append(v)
    if single_idx:
        # fuse by exponent-width/limb class exactly like powm_columns
        from ..ops.limbs import bucket_exp_bits, limbs_for_bits

        buckets: dict = {}
        for i in single_idx:
            (t,) = loners[i]
            e = exps_rows[i][t]
            w = (bucket_exp_bits([e]), limbs_for_bits(moduli[i].bit_length()))
            buckets.setdefault(w, []).append((i, t))
        gen = tpu_powm if device else host_powm
        for pairs_ in buckets.values():
            res = gen(
                [bases_rows[i][t] for i, t in pairs_],
                [exps_rows[i][t] for i, t in pairs_],
                [moduli[i] for i, _ in pairs_],
            )
            for (i, _), v in zip(pairs_, res):
                parts[i].append(v)

    # per-row recombination
    max_parts = max(len(p) for p in parts)
    if max_parts == 1:
        return [p[0] for p in parts]
    if not device:
        return [
            _prod_mod(p, m) for p, m in zip(parts, moduli)
        ]
    acc = [p[0] for p in parts]
    for step in range(1, max_parts):
        nxt = [p[step] if len(p) > step else 1 for p in parts]
        acc = tpu_modmul(acc, nxt, moduli)
    return acc


def _prod_mod(factors, m):
    acc = factors[0] % m
    for f in factors[1:]:
        acc = acc * f % m
    return acc


def tpu_powm_grouped(bases, exps, moduli) -> List[int]:
    """Like tpu_powm, but rows sharing a (base, modulus) pair are routed
    through the fixed-base comb kernel; loner rows take the generic path.

    This is the shape of the collect() columns: ring-Pedersen rows share
    (T, N) per message and PDL/range rows share (h1|h2, N~) per receiver,
    so almost everything lands in a comb group.
    """
    groups: dict = {}
    for i, (b, m) in enumerate(zip(bases, moduli)):
        groups.setdefault((b, m), []).append(i)
    shared = [(k, rows) for k, rows in groups.items() if len(rows) >= _SHARED_MIN_ROWS]
    loners = [i for k, rows in groups.items() if len(rows) < _SHARED_MIN_ROWS for i in rows]

    out: List = [None] * len(bases)
    if shared:
        res = tpu_powm_shared(
            [k[0] for k, _ in shared],
            [[exps[i] for i in rows] for _, rows in shared],
            [k[1] for k, _ in shared],
        )
        for (_, rows), vals in zip(shared, res):
            for i, v in zip(rows, vals):
                out[i] = v
    if loners:
        vals = tpu_powm(
            [bases[i] for i in loners],
            [exps[i] for i in loners],
            [moduli[i] for i in loners],
        )
        for i, v in zip(loners, vals):
            out[i] = v
    return out


def crt_powm(bases, exps, moduli, factors, powm=None):
    """Planner route for prover-owned moduli (FSDKR_CRT, backend.crt):
    rows whose factorization is supplied as factors[i] = (p, q) ride the
    secret-CRT engine — two fault-checked half-width legs with exponents
    reduced mod the leg group orders, Garner-recombined — and rows with
    factors[i] = None (or with the gate off) take `powm` unchanged.
    Results are bit-identical to the full-width path (the decomposition
    is an arithmetic identity; pinned by tests/test_crt.py), so callers
    thread transcripts through without caring which engine ran."""
    if powm is None:
        powm = host_powm
    from . import crt

    if not crt.crt_enabled() or not any(f is not None for f in factors):
        return powm(bases, exps, moduli)
    contexts = [
        crt.get_context(m, *f) if f is not None else None
        for m, f in zip(moduli, factors)
    ]
    return crt.crt_modexp_batch(
        bases, exps, contexts, fallback=powm, moduli=moduli
    )


def get_batch_powm(config: ProtocolConfig) -> BatchPowm:
    # config is REQUIRED: this getter activates the device mesh, which is
    # genuinely process-global hardware state. The transcript digest is
    # NOT installed here — hash_alg flows by parameter (see get_backend)
    apply_mesh(config)
    return tpu_powm_grouped if config.backend == "tpu" else host_powm


def powm_columns(powm: BatchPowm, *columns):
    """Fuse several (bases, exps, moduli) columns into per-exponent-width
    batched launches and split the results back.

    Columns are fused ONLY within the same bucketed exponent width AND
    the same modulus limb width: a batched modexp costs sequential depth
    proportional to the widest exponent in the batch, so a 256-bit-
    challenge column concatenated with a 2048-bit column would do ~8x
    its necessary work riding the wide launch — and a launch is limb-
    sized by its widest modulus, so a mod-N~ (2048-bit) column fused
    with a mod-n^2 (4096-bit) column would pay ~4x per modmul. Columns
    matching on both still share one launch (row count is nearly free
    next to depth).

    A column whose bases/exps entries are TUPLES is a joint multi-
    exponentiation column (one product-of-powers per row): all such
    columns pool into one multi_powm planning pass, which routes each
    term to the comb / Straus / generic engine and recombines per row.
    """
    from ..ops.limbs import bucket_exp_bits, limbs_for_bits

    # Identical columns share one computation: the PDL and Alice range
    # provers both commit h1^x mod N~ over the same share column, so
    # distribute_batch submits that column twice. Full-content comparison
    # (big-int lists) happens only on a prefix collision, so always-
    # distinct columns (the verifier paths) pay a 4-tuple hash, not a
    # whole-column hash.
    by_prefix: dict = {}  # cheap prefix -> [column indices]
    alias: dict = {}  # later column index -> first column index
    flat: dict = {}  # width class -> (bases, exps, moduli, [(col, lo, hi)])
    multi: list = []  # (col, lo, hi) spans into the pooled multi rows
    mb, me, mm = [], [], []  # pooled multi-exponentiation rows
    for col, (bases, exps, moduli) in enumerate(columns):
        prefix = (
            len(bases),
            bases[0] if bases else 0,
            exps[0] if exps else 0,
            moduli[0] if moduli else 0,
        )
        dup = None
        for prev in by_prefix.get(prefix, ()):
            pb, pe, pm = columns[prev]
            if list(pb) == list(bases) and list(pe) == list(exps) and list(pm) == list(moduli):
                dup = prev
                break
        if dup is not None:
            alias[col] = dup
            continue
        by_prefix.setdefault(prefix, []).append(col)
        if bases and isinstance(bases[0], (tuple, list)):
            multi.append((col, len(mb), len(mb) + len(bases)))
            mb += list(bases)
            me += list(exps)
            mm += list(moduli)
            continue
        w = (
            bucket_exp_bits(exps),
            limbs_for_bits(max(m.bit_length() for m in moduli)) if moduli else 0,
        )
        b, e, m, spans = flat.setdefault(w, ([], [], [], []))
        spans.append((col, len(b), len(b) + len(bases)))
        b += list(bases)
        e += list(exps)
        m += list(moduli)

    out: list = [None] * len(columns)
    # width buckets are independent launches: run them through the
    # double-buffered pipeline so one bucket's host staging overlaps
    # another's engine execution (results land by span, order-exact)
    jobs = list(flat.values())
    if jobs:
        from ..utils.pipeline import pipelined

        results = pipelined(
            lambda b, e, m: powm(b, e, m), [(b, e, m) for b, e, m, _ in jobs]
        )
        for (_, _, _, spans), res in zip(jobs, results):
            for col, lo, hi in spans:
                out[col] = res[lo:hi]
    if multi:
        # host backend always takes host engines; the tpu backend follows
        # the platform routing (native core on XLA:CPU, kernels on chip)
        res = multi_powm(
            mb, me, mm,
            device=False if powm is host_powm else _device_powm(),
        )
        for col, lo, hi in multi:
            out[col] = res[lo:hi]
    for col, dup in alias.items():
        out[col] = list(out[dup])  # fresh list: no aliasing across columns
    return out
