"""Verification backends.

The reference verifies every proof serially inside `collect`'s O(n^2) loop
(`/root/reference/src/refresh_message.rs:330-350`). This framework instead
*gathers* all proof instances of a collect into per-family batches and
dispatches them to a backend (SURVEY.md §7 step 7):

- "host": the pure-Python oracle — verifies each instance with the proofs
  module; ground truth for differential tests.
- "tpu": batched multi-modulus modexp / EC kernels over limb tensors
  (fsdkr_tpu.ops), one launch per proof family.

Both return *per-instance verdicts* (never early-exit), so identifiable
abort attribution — mapping a failing batch row back to the offending
party — is preserved exactly (`src/error.rs` semantics).
"""

from .batch_verifier import BatchVerifier, HostBatchVerifier, get_backend

__all__ = ["BatchVerifier", "HostBatchVerifier", "get_backend"]
