"""TPU batch verifier: every proof family of collect() as batched
multi-modulus modexp launches (the north-star lift, BASELINE.json).

Equation strategy per family (derivations from the reference verify
routines, rewritten to avoid modular inverses wherever the proof carries
the commitment being checked — a product comparison replaces an inversion):

- PDL-with-slack (`/root/reference/src/zk_pdl_with_slack.rs:113-168`):
    u2 * c^e  == (1+n)^s1 * s2^n   (mod n^2)
    u3 * z^e  == h1^s1 * h2^s3     (mod N~)
    u1        == s1*G - e*Q        (EC; host or ec_batch)
  — no inverses; (1+n)^s1 mod n^2 has the closed form 1 + (s1 mod n)*n.
- Alice range (`src/range_proofs.rs:112-164`): the challenge is recomputed
  from reconstructed u, w, so the actual values are needed:
    w = h1^s1 h2^s2 (z^e)^{-1},  u = (1+s1*n) s^n (c^e)^{-1}
  — z^e, c^e, h1^s1, h2^s2, s^n on TPU; the two inversions per row on host
  (CPython pow(x,-1,n); the modexp work dominates by ~50x).
- Ring-Pedersen (`src/ring_pedersen_proof.rs:138-155`): rows (item, i):
    T^{Z_i} == A_i * S^{e_i}  (mod N), e_i in {0,1} — one n*M-row batch.
- Correct-key: sigma_i^N == rho_i (mod N); rho derivation + small-factor
  gates on host.
- Composite dlog: g^y * ni^e == C (mod N).

Hash transcripts (SHA-256) are always recomputed on host — they are
microseconds against milliseconds of 2048-bit modexp.
"""

from __future__ import annotations

from typing import Dict, List

from ..config import ProtocolConfig, DEFAULT_CONFIG
from ..core.secp256k1 import N as CURVE_ORDER
from ..core.secp256k1 import Scalar
from ..core.transcript import challenge_bits
from ..ops.limbs import limbs_for_bits
from ..proofs import alice_range, correct_key
from ..proofs.pdl_slack import PDLwSlackProof
from ..proofs.ring_pedersen import RingPedersenProof
from ..utils.trace import phase
from .batch_verifier import BatchVerifier, HostBatchVerifier

# (wire-integer width caps — the q^3 slack-range bound of the GG-style
# sigma protocols, `src/range_proofs.rs:125` — live in the proof
# modules' domain_gate helpers, shared by the RLC and column paths)


def _modexp(bases, exps, moduli) -> List[int]:
    """One batched multi-modulus modexp launch. Rows sharing a (base,
    modulus) pair — ring-Pedersen's (T, N) per message, PDL/range's
    (h1|h2, N~) per receiver — ride the fixed-base comb kernel; the rest
    take the generic windowed kernel (see backend.powm)."""
    from .powm import tpu_powm_grouped

    return tpu_powm_grouped(bases, exps, moduli)


def _modmul(a, b, moduli) -> List[int]:
    from .powm import tpu_modmul

    return tpu_modmul(a, b, moduli)


class TpuBatchVerifier(BatchVerifier):
    """Batched verification on the accelerator, host oracle semantics.

    EC checks (PDL u1, Feldman) use random-linear-combination batching:
    sample secret 128-bit coefficients rho_j per verification, check one
    combined multi-scalar multiplication per group instead of one EC
    equation per row (soundness error 2^-128 per group). On a combined-
    check failure the rows of that group are re-verified individually on
    the host oracle, preserving exact per-row verdicts for identifiable
    abort (reference error semantics, `/root/reference/src/error.rs`)."""

    def __init__(self, config: ProtocolConfig = DEFAULT_CONFIG):
        self.config = config
        self._host = HostBatchVerifier(config.hash_alg)
        # install the device mesh described by config.mesh_shape: every
        # modexp/modmul launch below row-shards over it (backend.powm)
        from .powm import apply_mesh

        apply_mesh(config)

    # ------------------------------------------------------------------
    def _pdl_prepare(self, items, joint: bool = False):
        """Recompute challenges; return (the family's modexp columns,
        carry state for _pdl_finish). Column order matches _pdl_finish.

        With joint=True (FSDKR_MULTIEXP), the two mod-n^2 columns and
        their recombination collapse into ONE joint multi-exponentiation
        row per item — u2 ?= gs1 * s2^n * c^{-e} (the reference's own
        equation shape, `src/zk_pdl_with_slack.rs:140-149`) — sharing a
        single squaring chain instead of two. c^{-1} comes from a batched
        host inversion; a non-invertible c (adversarial) sends just that
        row through the column-exact per-row check in _pdl_finish, so
        joint and column verdicts are bit-identical.

        Out-of-domain rows (PDLwSlackProof.domain_gate — attacker-chosen
        wire integers must not crash the limb encoder or inflate the
        fused launch width; see the gate's docstring) are staged with
        zeros and force-failed in _pdl_finish; base-position fields
        reduce mod n on staging. Transcript-position fields must be
        gated BEFORE hashing: chain_int rejects negatives with a raw
        ValueError."""
        row_ok = [PDLwSlackProof.domain_gate(p, st) for p, st in items]
        with phase("pdl.challenge", items=len(items)):
            e_vec = [
                PDLwSlackProof._challenge(
                    st, p.z, p.u1, p.u2, p.u3, self.config.hash_alg
                )
                if ok
                else 0
                for (p, st), ok in zip(items, row_ok)
            ]
        s1_col = [p.s1 if ok else 0 for (p, _), ok in zip(items, row_ok)]
        s3_col = [p.s3 if ok else 0 for (p, _), ok in zip(items, row_ok)]
        nn_mod = [st.ek.nn for _, st in items]
        nt_mod = [st.N_tilde for _, st in items]
        nt_cols = (
            ([p.z for p, _ in items], e_vec, nt_mod),
            ([st.h1 for _, st in items], s1_col, nt_mod),
            ([st.h2 for _, st in items], s3_col, nt_mod),
        )
        if not joint:
            cols = (
                ([st.ciphertext for _, st in items], e_vec, nn_mod),
                ([p.s2 for p, _ in items], [st.ek.n for _, st in items], nn_mod),
            ) + nt_cols
            return cols, (e_vec, nn_mod, nt_mod, row_ok, None)
        from .powm import batch_base_inv

        need = [
            i for i in range(len(items)) if row_ok[i] and e_vec[i] != 0
        ]
        with phase("pdl.base_inv", items=len(need)):
            invs = batch_base_inv(
                [items[i][1].ciphertext for i in need],
                [nn_mod[i] for i in need],
            )
        c_inv = [1] * len(items)
        inv_fail = [False] * len(items)
        for i, v in zip(need, invs):
            if v is None:
                inv_fail[i] = True  # column-exact per-row check in finish
            else:
                c_inv[i] = v
        live = [
            ok and not fail for ok, fail in zip(row_ok, inv_fail)
        ]
        multi = (
            [
                (p.s2 % st.ek.nn if lv else 1, ci)
                for (p, st), ci, lv in zip(items, c_inv, live)
            ],
            [
                (st.ek.n if lv else 0, e if lv else 0)
                for (_, st), e, lv in zip(items, e_vec, live)
            ],
            nn_mod,
        )
        cols = nt_cols + (multi,)
        return cols, (e_vec, nn_mod, nt_mod, row_ok, inv_fail)

    def _pdl_finish(self, items, state, results, u1_vec=None,
                    session_of=None):
        """Combine the modexp column results into per-row verdicts.
        u1_vec carries the EC u1 column when the caller overlapped it
        with the modexp launches (pipeline mode); None computes it here
        (the pdl.ec_u1 phase then measures compute, not just the join).
        session_of is accepted for signature parity with the RLC finish
        and ignored: column verdicts are already exact per row."""
        e_vec, nn_mod, nt_mod, row_ok, inv_fail = state
        with phase("pdl.combine", items=len(items)):
            gs1 = [
                (1 + (p.s1 % st.ek.n) * st.ek.n) % st.ek.nn for p, st in items
            ]
            if inv_fail is None:  # column path
                c_e, s2_n, z_e, h1_s1, h2_s3 = results
                lhs2 = _modmul([p.u2 for p, _ in items], c_e, nn_mod)
                rhs2 = _modmul(gs1, s2_n, nn_mod)
                ok2_vec = [
                    lhs2[i] == rhs2[i] and row_ok[i] for i in range(len(items))
                ]
            else:  # joint path: u2 ?= gs1 * s2^n * c^{-e}
                z_e, h1_s1, h2_s3, v2 = results
                rhs2 = _modmul(gs1, v2, nn_mod)
                ok2_vec = []
                for i, (p, st) in enumerate(items):
                    if inv_fail[i]:
                        # adversarial c with gcd(c, n^2) > 1: evaluate the
                        # column-form equality for exactly this row
                        from ..core import intops

                        lhs = p.u2 * intops.mod_pow(
                            st.ciphertext % st.ek.nn, e_vec[i], st.ek.nn
                        ) % st.ek.nn
                        rhs = gs1[i] * intops.mod_pow(
                            p.s2 % st.ek.nn, st.ek.n, st.ek.nn
                        ) % st.ek.nn
                        ok2_vec.append(lhs == rhs and row_ok[i])
                    else:
                        ok2_vec.append(
                            p.u2 % st.ek.nn == rhs2[i] and row_ok[i]
                        )
            lhs3 = _modmul([p.u3 for p, _ in items], z_e, nt_mod)
            rhs3 = _modmul(h1_s1, h2_s3, nt_mod)

        with phase("pdl.ec_u1", items=len(items)):
            ok1_vec = (
                u1_vec if u1_vec is not None
                else self._pdl_u1_batch(items, e_vec)
            )

        out = []
        for idx, (proof, st) in enumerate(items):
            ok1 = ok1_vec[idx] and row_ok[idx]
            ok2 = ok2_vec[idx]
            ok3 = lhs3[idx] == rhs3[idx] and row_ok[idx]
            out.append(None if (ok1 and ok2 and ok3) else (ok1, ok2, ok3))
        return out

    # -- FSDKR_RLC: cross-proof randomized batch verification ----------
    def _pdl_rlc_prepare(self, items):
        """Gate rows, recompute challenges, and fold the live rows into
        per-receiver-modulus RLC groups (backend.rlc). Rows addressed to
        one receiver share that receiver's (h1, h2, N~) statement and
        Paillier key, so a collect() batch folds into one mod-N~ and one
        mod-n^2 group per receiver slot — each costing O(1) full-width
        ladders instead of one per row.

        Returns (cols, state): cols is ONE joint multi-exponentiation
        column holding every group's phase-1 rows (eq3's merged-h1/h2
        ladder + per-row aggregate; eq2's s2 aggregate + u2/c
        aggregate), which powm_columns pools with any co-launched
        family — verify_pairs fuses it with the range columns. Phase 2
        (raising each eq2 s2-aggregate to n, the group's one remaining
        full-width ladder) runs in _pdl_rlc_finish after phase 1 lands.
        Domain gating runs BEFORE aggregation: an out-of-domain row
        never enters a fold (it would poison its group's verdict and
        force a needless bisection) and is force-failed in finish."""
        from . import rlc

        row_ok = [PDLwSlackProof.domain_gate(p, st) for p, st in items]
        with phase("pdl.challenge", items=len(items)):
            e_vec = [
                PDLwSlackProof._challenge(
                    st, p.z, p.u1, p.u2, p.u3, self.config.hash_alg
                )
                if ok
                else 0
                for (p, st), ok in zip(items, row_ok)
            ]
        nt_groups: Dict[tuple, List[int]] = {}
        nn_groups: Dict[tuple, List[int]] = {}
        for i, ((p, st), ok) in enumerate(zip(items, row_ok)):
            if not ok:
                continue
            nt_groups.setdefault(self._pdl_nt_key(st), []).append(i)
            nn_groups.setdefault(self._pdl_nn_key(st), []).append(i)

        mb: list = []
        me: list = []
        mm: list = []
        nt_plan = []  # (row indices, lhs slot in nt_lhs, rhs position)
        nt_lhs = []  # merged 2-term (h1,h2) ladder rows -> fold_ladder2
        for (h1, h2, nt), idxs in nt_groups.items():
            rho = rlc.sample_rhos(len(idxs))
            rows = self._pdl_nt_rows(items, e_vec, idxs)
            lhs, rhs = PDLwSlackProof.rlc_fold_nt(h1, h2, nt, rows, rho)
            # the lhs is the group's ONE merged shared-base ladder
            # (h1^S1 * h2^S3): it runs through the cross-launch
            # fold-ladder cache (powm.fold_ladder2) instead of the joint
            # column, so warm shards skip its full-width squaring chain
            nt_plan.append((idxs, len(nt_lhs), len(mm)))
            nt_lhs.append(lhs)
            mb.append(rhs[0])
            me.append(rhs[1])
            mm.append(rhs[2])
        nn_plan = []  # (row indices, n, nn, gs1, s2 position, commit position)
        for (n, nn), idxs in nn_groups.items():
            rho = rlc.sample_rhos(len(idxs))
            rows = self._pdl_nn_rows(items, e_vec, idxs)
            s2_row, commit_row, gs1 = PDLwSlackProof.rlc_fold_nn(
                n, nn, rows, rho
            )
            nn_plan.append((idxs, n, nn, gs1, len(mm), len(mm) + 1))
            for b, e, m in (s2_row, commit_row):
                mb.append(b)
                me.append(e)
                mm.append(m)
        rlc.count("rlc_groups", len(nt_plan) + len(nn_plan))
        rlc.count(
            "rows_folded",
            sum(len(g[0]) for g in nt_plan) + sum(len(g[0]) for g in nn_plan),
        )
        # eq3's merged h1/h2 2-term ladder + eq2's phase-2 A^n: one
        # full-width squaring chain per group, down from one per row
        rlc.count("fullwidth_ladders", len(nt_plan) + len(nn_plan))
        return ((mb, me, mm),), (e_vec, row_ok, nt_plan, nn_plan, nt_lhs)

    def _pdl_eq3_exact(self, items, e_vec, i) -> bool:
        """Column-form mod-N~ equality for exactly row i (bisection
        leaf; same residues the column path compares)."""
        from ..core import intops

        p, st = items[i]
        nt = st.N_tilde
        lhs = p.u3 % nt * intops.mod_pow(p.z % nt, e_vec[i], nt) % nt
        rhs = (
            intops.mod_pow(st.h1 % nt, p.s1, nt)
            * intops.mod_pow(st.h2 % nt, p.s3, nt)
            % nt
        )
        return lhs == rhs

    def _pdl_eq2_exact(self, items, e_vec, i) -> bool:
        """Column-form mod-n^2 equality for exactly row i."""
        from ..core import intops

        p, st = items[i]
        n, nn = st.ek.n, st.ek.nn
        lhs = (
            p.u2 % nn
            * intops.mod_pow(st.ciphertext % nn, e_vec[i], nn)
            % nn
        )
        gs1 = (1 + (p.s1 % n) * n) % nn
        rhs = gs1 * intops.mod_pow(p.s2 % nn, n, nn) % nn
        return lhs == rhs

    # -- shared fold-input construction + bisection blame resolution
    # (monolithic AND streamed RLC paths — keeping the group keys, the
    # fold row layouts, the subset re-folds, and the exact leaf checks
    # in ONE set of helpers is what makes memory-planned-vs-monolithic
    # verdict/blame identity a structural property; see
    # _verify_pairs_streamed)

    @staticmethod
    def _pdl_nt_key(st):
        return (st.h1, st.h2, st.N_tilde)

    @staticmethod
    def _pdl_nn_key(st):
        return (st.ek.n, st.ek.nn)

    @staticmethod
    def _pdl_nt_rows(items, e_vec, idxs):
        """rlc_fold_nt's row layout: (z, u3, e, s1, s3) per row."""
        return [
            (items[i][0].z, items[i][0].u3, e_vec[i],
             items[i][0].s1, items[i][0].s3)
            for i in idxs
        ]

    @staticmethod
    def _pdl_nn_rows(items, e_vec, idxs):
        """rlc_fold_nn's row layout: (u2, c, e, s1, s2) per row."""
        return [
            (items[i][0].u2, items[i][1].ciphertext, e_vec[i],
             items[i][0].s1, items[i][0].s2)
            for i in idxs
        ]

    def _pdl_nt_subset_check(self, items, e_vec, h1, h2, nt, sub) -> bool:
        """Fresh-rho combined mod-N~ check over an arbitrary row subset
        (bisection node). Host engines: a bisection is the rare
        adversarial path, never the throughput path."""
        from . import rlc
        from .powm import multi_powm

        rho = rlc.sample_rhos(len(sub))
        rows = self._pdl_nt_rows(items, e_vec, sub)
        lhs, rhs = PDLwSlackProof.rlc_fold_nt(h1, h2, nt, rows, rho)
        va, vb = multi_powm(
            [lhs[0], rhs[0]], [lhs[1], rhs[1]], [nt, nt], device=False,
        )
        return va == vb

    def _pdl_nn_subset_check(self, items, e_vec, n, nn, sub) -> bool:
        """Fresh-rho combined mod-n^2 check over an arbitrary row
        subset (bisection node)."""
        from ..core import intops
        from . import rlc
        from .powm import multi_powm

        rho = rlc.sample_rhos(len(sub))
        rows = self._pdl_nn_rows(items, e_vec, sub)
        s2_row, commit_row, g1 = PDLwSlackProof.rlc_fold_nn(n, nn, rows, rho)
        av, cv = multi_powm(
            [s2_row[0], commit_row[0]],
            [s2_row[1], commit_row[1]],
            [nn, nn],
            device=False,
        )
        return cv == g1 * intops.mod_pow(av, n, nn) % nn

    def _pdl_nt_bisect(
        self, items, e_vec, h1, h2, nt, idxs, ok3_vec, session_of=None
    ):
        from . import rlc

        rlc.count("bisect_fallbacks")
        combined = lambda sub: self._pdl_nt_subset_check(  # noqa: E731
            items, e_vec, h1, h2, nt, sub
        )
        exact = lambda i: self._pdl_eq3_exact(items, e_vec, i)  # noqa: E731
        verdicts = (
            rlc.bisect_sessions(idxs, session_of, combined, exact)
            if session_of is not None
            else rlc.bisect_rows(idxs, combined, exact)
        )
        for i, v in verdicts.items():
            ok3_vec[i] = v

    def _pdl_nn_bisect(
        self, items, e_vec, n, nn, idxs, ok2_vec, session_of=None
    ):
        from . import rlc

        rlc.count("bisect_fallbacks")
        combined = lambda sub: self._pdl_nn_subset_check(  # noqa: E731
            items, e_vec, n, nn, sub
        )
        exact = lambda i: self._pdl_eq2_exact(items, e_vec, i)  # noqa: E731
        verdicts = (
            rlc.bisect_sessions(idxs, session_of, combined, exact)
            if session_of is not None
            else rlc.bisect_rows(idxs, combined, exact)
        )
        for i, v in verdicts.items():
            ok2_vec[i] = v

    def _pdl_rlc_finish(
        self, items, state, results, u1_vec=None, session_of=None
    ):
        """Compare each group's folded equation, bisect failing groups
        down to exact per-row verdicts (backend.rlc.bisect_rows — or
        session-first via bisect_sessions when the rows were merged
        across fused sessions), and assemble the same (u1, u2, u3)
        triples as _pdl_finish."""
        from .powm import fold_ladder2

        e_vec, row_ok, nt_plan, nn_plan, nt_lhs = state
        multi_res = results[0]
        ok2_vec = [False] * len(items)
        ok3_vec = [False] * len(items)

        with phase("pdl.rlc_eq3", items=sum(len(g[0]) for g in nt_plan)):
            lhs_vals = fold_ladder2(nt_lhs)
            for idxs, lhs_slot, rhs_pos in nt_plan:
                if lhs_vals[lhs_slot] == multi_res[rhs_pos]:
                    for i in idxs:
                        ok3_vec[i] = True
                    continue
                st0 = items[idxs[0]][1]
                self._pdl_nt_bisect(
                    items, e_vec, st0.h1, st0.h2, st0.N_tilde, idxs,
                    ok3_vec, session_of=session_of,
                )

        with phase("pdl.rlc_eq2", items=sum(len(g[0]) for g in nn_plan)):
            # phase 2: every group's s2-aggregate to the n-th power in
            # one fused generic launch (the O(1)-per-group ladder)
            a_pow = _modexp(
                [multi_res[g[4]] for g in nn_plan],
                [g[1] for g in nn_plan],
                [g[2] for g in nn_plan],
            )
            for (idxs, n, nn, gs1, _s2_pos, commit_pos), ap in zip(
                nn_plan, a_pow
            ):
                if multi_res[commit_pos] == gs1 * ap % nn:
                    for i in idxs:
                        ok2_vec[i] = True
                    continue
                self._pdl_nn_bisect(
                    items, e_vec, n, nn, idxs, ok2_vec,
                    session_of=session_of,
                )

        with phase("pdl.ec_u1", items=len(items)):
            ok1_vec = (
                u1_vec if u1_vec is not None
                else self._pdl_u1_batch(items, e_vec)
            )

        out = []
        for idx in range(len(items)):
            ok1 = ok1_vec[idx] and row_ok[idx]
            ok2 = ok2_vec[idx]
            ok3 = ok3_vec[idx]
            out.append(None if (ok1 and ok2 and ok3) else (ok1, ok2, ok3))
        return out

    def verify_pdl(self, items):
        if not items:
            return []
        from ..utils.pipeline import submit_bg
        from .powm import multiexp_enabled, powm_columns
        from .rlc import rlc_enabled

        if rlc_enabled():
            cols, state = self._pdl_rlc_prepare(items)
            finish = self._pdl_rlc_finish
        else:
            cols, state = self._pdl_prepare(items, joint=multiexp_enabled())
            finish = self._pdl_finish
        # the EC u1 column needs only (items, e_vec), both fixed before
        # any launch: run it on a background thread so the host EC work
        # hides behind the modexp columns' engine time
        e_vec = state[0]
        u1_fut = submit_bg(lambda: self._pdl_u1_batch(items, e_vec))
        with phase("pdl.modexp_columns", items=len(cols) * len(items)):
            results = powm_columns(_modexp, *cols)
        return finish(
            items, state, results,
            u1_vec=u1_fut.result() if u1_fut is not None else None,
        )

    def _pdl_u1_batch(self, items, e_vec) -> List[bool]:
        """u1 == s1*G - e*Q per row (`src/zk_pdl_with_slack.rs:124-127`),
        as ONE combined check:
            sum_j rho_j*u1_j + sum_j (rho_j e_j)*Q_j + (-sum_j rho_j s1_j)*G
            == identity
        with secret 128-bit rho_j. Host per-row fallback on failure.

        Routed by config.device_ec: on the XLA:CPU fallback platform the
        per-row host check is 3-40x faster than the combined device MSM
        (bench_results/ec_ab_cpu.json), so the device path engages only
        with a real accelerator behind JAX."""
        import secrets as _secrets

        from ..ops.ec_batch import batch_msm

        if not self.config.device_ec:
            return self._pdl_u1_host(items, e_vec)
        g = items[0][1].G
        if any(st.G != g for _, st in items):
            return self._pdl_u1_host(items, e_vec)

        rho = [_secrets.randbits(128) for _ in items]
        points = (
            [p.u1 for p, _ in items]
            + [st.Q for _, st in items]
            + [g]
        )
        s_combined = sum(
            r * (p.s1 % CURVE_ORDER) for r, (p, _) in zip(rho, items)
        ) % CURVE_ORDER
        scalars = (
            list(rho)
            + [r * e % CURVE_ORDER for r, e in zip(rho, e_vec)]
            + [CURVE_ORDER - s_combined]
        )
        (combined,) = batch_msm([points], [scalars])
        if combined.infinity:
            return [True] * len(items)
        return self._pdl_u1_host(items, e_vec)

    @staticmethod
    def _pdl_u1_host(items, e_vec) -> List[bool]:
        from ..native import ec as native_ec

        if native_ec.available() and items:
            # one native launch: u1 ?= s1*G + (q - e)*Q per row
            evals = native_ec.lincomb2_batch(
                [None if st.G.infinity else (st.G.x, st.G.y)
                 for _, st in items],
                [p.s1 % CURVE_ORDER for p, _ in items],
                [None if st.Q.infinity else (st.Q.x, st.Q.y)
                 for _, st in items],
                [(CURVE_ORDER - e % CURVE_ORDER) % CURVE_ORDER
                 for e in e_vec],
            )
            if evals is not None:
                out = []
                for (proof, _), ev in zip(items, evals):
                    if ev is None:
                        out.append(proof.u1.infinity)
                    else:
                        out.append(
                            (not proof.u1.infinity)
                            and proof.u1.x == ev[0]
                            and proof.u1.y == ev[1]
                        )
                return out
        out = []
        for idx, (proof, st) in enumerate(items):
            g_s1 = st.G * Scalar.from_int(proof.s1)
            e_neg = Scalar.from_int(CURVE_ORDER - e_vec[idx] % CURVE_ORDER)
            out.append(proof.u1 == g_s1 + st.Q * e_neg)
        return out

    # ------------------------------------------------------------------
    @staticmethod
    def _batch_inv(values, moduli):
        """Row-wise modular inverses via the device-side Montgomery
        product tree (ops.montgomery.batch_mod_inv_grouped): rows group
        by modulus (the collect() batch has n rows per receiver modulus),
        one host inversion per group. Serial CPython pow(v,-1,m) costs
        0.5-1.7 ms per row — ~450 s over the n=256 pair loop."""
        from ..ops.montgomery import batch_mod_inv_grouped

        groups: Dict[int, List[int]] = {}
        for i, m in enumerate(moduli):
            groups.setdefault(m, []).append(i)
        glist = [(m, [values[i] for i in idxs]) for m, idxs in groups.items()]
        k = limbs_for_bits(max(m.bit_length() for m in moduli))
        res = batch_mod_inv_grouped(glist, k)
        out: List = [None] * len(values)
        for (m, idxs), invs in zip(groups.items(), res):
            for i, vi in zip(idxs, invs):
                out[i] = vi
        return out

    def _range_gate(self, items):
        """Shared row gating of the range family: domain-gate every row
        (AliceProof.domain_gate, including the q^3 slack bound on s1)
        and zero the challenge of gated rows. ONE implementation for
        the column/joint and rangeopt paths — the FSDKR_RANGEOPT=0/1
        verdict-identity contract depends on both paths gating
        identically."""
        nn_mod = [ek.nn for _, _, ek, _ in items]
        nt_mod = [dlog.N for _, _, _, dlog in items]
        row_ok = [
            alice_range.AliceProof.domain_gate(p, c, dlog)
            for p, c, _, dlog in items
        ]
        e_vec = [
            p.e if ok else 0 for (p, _, _, _), ok in zip(items, row_ok)
        ]
        return nn_mod, nt_mod, row_ok, e_vec

    def _range_base_inv(self, items, nn_mod, nt_mod, row_ok, e_vec):
        """Shared batched base inversions of the range family (z mod N~,
        c mod n^2) for the live e != 0 rows; e == 0 rows never invert
        (x^0 = 1 is always invertible, matching the host oracle).
        Returns (z_inv, c_inv, inv_fail) — a non-invertible z or c
        (gcd > 1, adversarial) marks only its own row, which the caller
        force-fails exactly like the host oracle. ONE implementation for
        the joint and rangeopt paths (see _range_gate)."""
        from .powm import batch_base_inv

        rows = len(items)
        need = [i for i in range(rows) if row_ok[i] and e_vec[i] != 0]
        with phase("range.base_inv", items=2 * len(need)):
            z_invs = batch_base_inv(
                [items[i][0].z for i in need], [nt_mod[i] for i in need]
            )
            c_invs = batch_base_inv(
                [items[i][1] for i in need], [nn_mod[i] for i in need]
            )
        z_inv = [1] * rows
        c_inv = [1] * rows
        inv_fail = [False] * rows
        for i, zv, cv in zip(need, z_invs, c_invs):
            if zv is None or cv is None:
                inv_fail[i] = True  # verdict False, like the host oracle
            else:
                z_inv[i], c_inv[i] = zv, cv
        return z_inv, c_inv, inv_fail

    def _range_prepare(self, items, joint: bool = False):
        """Return (the family's modexp columns, carry state for
        _range_finish). Column order matches _range_finish.

        Same out-of-domain gating as _pdl_prepare, via
        AliceProof.domain_gate: exponent-position wire fields (s1, s2,
        e) must be in their honest domains or the row is staged with
        zeros and force-failed — never crash or inflate the batch.
        s1's q^3 slack bound (`src/range_proofs.rs:125`) is enforced
        HERE, pre-launch. Transcript fields (z, cipher, s) are gated
        non-negative for chain_int.

        With joint=True (FSDKR_MULTIEXP) the verifier computes the
        reference's own equation shapes directly — w = h1^s1 h2^s2
        (z^{-1})^e and u = gs1 * s^n * c^{-e} — by inverting the BASES
        once per row (batched host inversion) instead of exponentiating
        and then inverting the results through the device product tree:
        the mod-n^2 pair shares one squaring chain as a joint 2-term row
        and range.batch_inv disappears from the launch plan. gcd(z, N~)
        > 1 or gcd(c, n^2) > 1 fails the row exactly as the host oracle
        (mod_inv -> None) and the column path (product-tree fallback)
        do."""
        nn_mod, nt_mod, row_ok, e_vec = self._range_gate(items)
        s1_col = [
            p.s1 if ok else 0 for (p, _, _, _), ok in zip(items, row_ok)
        ]
        s2_col = [
            p.s2 if ok else 0 for (p, _, _, _), ok in zip(items, row_ok)
        ]
        comb_cols = (
            ([dlog.g for _, _, _, dlog in items], s1_col, nt_mod),
            ([dlog.ni for _, _, _, dlog in items], s2_col, nt_mod),
        )
        if not joint:
            return (
                ([p.z for p, _, _, _ in items], e_vec, nt_mod),
            ) + comb_cols + (
                ([c for _, c, _, _ in items], e_vec, nn_mod),
                (
                    [p.s for p, _, _, _ in items],
                    [ek.n for _, _, ek, _ in items],
                    nn_mod,
                ),
            ), (nn_mod, nt_mod, row_ok, None)
        z_inv, c_inv, inv_fail = self._range_base_inv(
            items, nn_mod, nt_mod, row_ok, e_vec
        )
        live = [ok and not fail for ok, fail in zip(row_ok, inv_fail)]
        e_live = [e if lv else 0 for e, lv in zip(e_vec, live)]
        multi = (
            [
                (p.s % ek.nn if lv else 1, ci)
                for (p, _, ek, _), ci, lv in zip(items, c_inv, live)
            ],
            [
                (ek.n if lv else 0, e)
                for (_, _, ek, _), e, lv in zip(items, e_live, live)
            ],
            nn_mod,
        )
        return (
            (z_inv, e_live, nt_mod),
        ) + comb_cols + (multi,), (nn_mod, nt_mod, row_ok, inv_fail)

    def _range_finish(self, items, mods, results):
        nn_mod, nt_mod, row_ok, inv_fail = mods
        if inv_fail is None:  # column path
            z_e, h1_s1, h2_s2, c_e, s_n = results
        else:
            z_inv_e, h1_s1, h2_s2, v_u = results

        with phase("range.combine", items=len(items)):
            w_part = _modmul(h1_s1, h2_s2, nt_mod)
            # domain-gated rows are force-failed below and must be
            # skipped HERE: an adversarial s1 on a gated row can be
            # arbitrarily wide (multi-megabit), and building its
            # (1 + s1*n) % nn anyway would burn a giant host multiply
            # per dead row (tests/test_wire_negative.py pins this)
            gs1 = [
                (1 + p.s1 * ek.n) % ek.nn if ok else 1
                for (p, _, ek, _), ok in zip(items, row_ok)
            ]
            if inv_fail is None:
                u_part = _modmul(gs1, s_n, nn_mod)
            else:
                w_vec = _modmul(w_part, z_inv_e, nt_mod)
                u_vec = _modmul(gs1, v_u, nn_mod)

        if inv_fail is None:
            with phase("range.batch_inv", items=2 * len(items)):
                z_e_inv_vec = self._batch_inv(z_e, nt_mod)
                c_e_inv_vec = self._batch_inv(c_e, nn_mod)

        with phase("range.challenge", items=len(items)):
            out = []
            for idx, (proof, cipher, ek, dlog) in enumerate(items):
                # row_ok is the single domain gate (incl. the q^3 bound)
                if not row_ok[idx]:
                    out.append(False)
                    continue
                if inv_fail is None:
                    z_e_inv = z_e_inv_vec[idx]
                    c_e_inv = c_e_inv_vec[idx]
                    if z_e_inv is None or c_e_inv is None:
                        out.append(False)
                        continue
                    w = w_part[idx] * z_e_inv % dlog.N
                    u = u_part[idx] * c_e_inv % ek.nn
                else:
                    if inv_fail[idx]:
                        out.append(False)
                        continue
                    w = w_vec[idx]
                    u = u_vec[idx]
                out.append(
                    alice_range._challenge(
                        ek.n, cipher, proof.z, u, w, self.config.hash_alg
                    )
                    == proof.e
                )
        return out

    # -- FSDKR_RANGEOPT: shared-exponent / joint-comb range engines ----
    def _range_opt_prepare(self, items):
        """Gate rows, batch the base inversions, and group live rows by
        receiver environment for the structure-exploiting engines:

        - the mod-n^2 u-power u = gs1 * s^n * c^{-e}: every row of a
          receiver's group shares the receiver's PUBLIC 2048-bit
          exponent n (and modulus n^2), so the group runs as ONE
          square-and-multiply schedule through the shared-exponent
          engine (backend.powm.tpu_powm_shared_exp), the c^{-e} term
          riding the same chain Straus-style;
        - the mod-N~ w-part h1^s1 * h2^s2: a 2-term fixed-base shape per
          receiver environment, ONE joint comb apply over both
          persistent window tables (backend.powm.joint_comb2);
        - the z^{-e} column stays a generic 256-bit launch.

        Out-of-domain rows (AliceProof.domain_gate, including the q^3
        slack bound on s1) and rows whose z/c is non-invertible are
        NEVER staged — no group contains a dead row, and in particular
        no gs1 is ever built from an ungated (potentially multi-megabit)
        s1. Verdicts are bit-identical to the joint/column paths —
        gating and inversion semantics are literally shared code
        (_range_gate / _range_base_inv; tests/test_range_engines.py)."""
        rows = len(items)
        nn_mod, nt_mod, row_ok, e_vec = self._range_gate(items)
        z_inv, c_inv, inv_fail = self._range_base_inv(
            items, nn_mod, nt_mod, row_ok, e_vec
        )
        live = [
            ok and not fail for ok, fail in zip(row_ok, inv_fail)
        ]
        nn_groups: Dict[tuple, List[int]] = {}
        nt_groups: Dict[tuple, List[int]] = {}
        for i in range(rows):
            if not live[i]:
                continue
            _, _, ek, dlog = items[i]
            nn_groups.setdefault((ek.n, ek.nn), []).append(i)
            nt_groups.setdefault((dlog.g, dlog.ni, dlog.N), []).append(i)
        return dict(
            nn_mod=nn_mod, nt_mod=nt_mod, row_ok=row_ok, e_vec=e_vec,
            z_inv=z_inv, c_inv=c_inv, live=live,
            nn_groups=nn_groups, nt_groups=nt_groups,
            u_pow=[1] * rows, hs=[1] * rows, z_pow=[1] * rows,
        )

    def _range_opt_jobs(self, items, state):
        """Independent launch-group thunks for the concurrent column
        scheduler (utils.pipeline.run_jobs): one shared-exponent job per
        mod-n^2 receiver group, one joint-comb job per mod-N~ receiver
        environment, and one generic z^{-e} column job. Each thunk
        writes only its own rows of the state vectors, so any execution
        order/interleaving produces identical results."""
        from .powm import joint_comb2, tpu_powm_shared_exp

        e_vec, c_inv, z_inv = state["e_vec"], state["c_inv"], state["z_inv"]
        live = state["live"]
        jobs = []
        for (n, nn), idxs in state["nn_groups"].items():
            def u_job(n=n, nn=nn, idxs=idxs):
                with phase("range.u_pow", items=len(idxs)):
                    res = tpu_powm_shared_exp(
                        [items[i][0].s for i in idxs], n, nn,
                        aux_bases=[c_inv[i] for i in idxs],
                        aux_exps=[e_vec[i] for i in idxs],
                    )
                for i, v in zip(idxs, res):
                    state["u_pow"][i] = v

            jobs.append(u_job)
        for (h1, h2, nt), idxs in state["nt_groups"].items():
            def w_job(h1=h1, h2=h2, nt=nt, idxs=idxs):
                with phase("range.comb2", items=len(idxs)):
                    res = joint_comb2(
                        h1, [items[i][0].s1 for i in idxs],
                        h2, [items[i][0].s2 for i in idxs], nt,
                    )
                for i, v in zip(idxs, res):
                    state["hs"][i] = v

            jobs.append(w_job)
        z_rows = [i for i in range(len(items)) if live[i] and e_vec[i]]
        if z_rows:
            def z_job():
                with phase("range.z_e", items=len(z_rows)):
                    res = _modexp(
                        [z_inv[i] for i in z_rows],
                        [e_vec[i] for i in z_rows],
                        [state["nt_mod"][i] for i in z_rows],
                    )
                for i, v in zip(z_rows, res):
                    state["z_pow"][i] = v

            jobs.append(z_job)
        return jobs

    def _range_opt_finish(self, items, state):
        """Combine the scheduled launch groups' results into verdicts:
        u = gs1 * u_pow mod n^2, w = hs * z_pow mod N~, then the
        Fiat-Shamir challenge recomputation per live row."""
        live, e_vec = state["live"], state["e_vec"]
        idxs = [i for i in range(len(items)) if live[i]]
        with phase("range.combine", items=len(idxs)):
            # gs1 only for live rows: s1 <= q^3 here BY the domain gate
            gs1 = [
                (1 + items[i][0].s1 * items[i][2].n) % items[i][2].nn
                for i in idxs
            ]
            u_col = _modmul(
                gs1, [state["u_pow"][i] for i in idxs],
                [state["nn_mod"][i] for i in idxs],
            )
            w_col = _modmul(
                [state["hs"][i] for i in idxs],
                [state["z_pow"][i] for i in idxs],
                [state["nt_mod"][i] for i in idxs],
            )
        out = [False] * len(items)
        with phase("range.challenge", items=len(idxs)):
            for i, u, w in zip(idxs, u_col, w_col):
                proof, cipher, ek, _ = items[i]
                out[i] = (
                    alice_range._challenge(
                        ek.n, cipher, proof.z, u, w, self.config.hash_alg
                    )
                    == proof.e
                )
        return out

    def verify_range(self, items):
        if not items:
            return []
        from ..utils.pipeline import run_jobs
        from .powm import multiexp_enabled, powm_columns, rangeopt_enabled

        if rangeopt_enabled():
            state = self._range_opt_prepare(items)
            run_jobs(self._range_opt_jobs(items, state))
            return self._range_opt_finish(items, state)
        cols, mods = self._range_prepare(items, joint=multiexp_enabled())
        with phase("range.modexp_columns", items=len(cols) * len(items)):
            results = powm_columns(_modexp, *cols)
        return self._range_finish(items, mods, results)

    def verify_pairs(self, pdl_items, range_items, session_spans=None):
        """Both pair-loop families of a collect. Dispatch:

        - A fused multi-session launch (`session_spans` maps session ->
          [lo, hi) row span; refresh.collect_sessions and
          streaming.finalize_streams pass it) first runs cross-session
          value dedup (FSDKR_XSESSION_DEDUP): same-committee sessions
          produce VALUE-IDENTICAL (proof, statement) row pairs, so one
          representative per distinct row value is verified and its
          verdict fanned out — the fused batch collapses to ~one
          session's size. Residual distinct rows keep per-session
          attribution: failing merged RLC groups bisect session-first
          (rlc.bisect_sessions), so blame stays bit-identical to S
          independent collects.
        - Under the bytes-budgeted memory plan (FSDKR_MEM_PLAN, default
          on) a batch whose estimated staged bytes exceed
          FSDKR_MEM_BUDGET_MB runs tile-by-tile through
          `_verify_pairs_streamed` — build/stage/verify/wipe per tile,
          RLC folds accumulated as running per-group partial products —
          so resident staged data is O(tile), not O(rows).
        - Batches that fit the budget (and the FSDKR_MEM_PLAN=0 arm)
          take the monolithic single-launch-set path unchanged.

        Verdicts and identifiable-abort blame are bit-identical between
        all paths (tests/test_memplan.py, tests/test_xsession.py)."""
        if not pdl_items or not range_items:
            return super().verify_pairs(pdl_items, range_items)
        from .rlc import xsession_dedup_enabled

        if (
            session_spans is not None
            and len(session_spans) > 1
            and len(pdl_items) == len(range_items)
            and xsession_dedup_enabled()
        ):
            ded = self._xsession_dedup(pdl_items, range_items)
            if ded is not None:
                return ded
        session_of = self._session_of(session_spans, len(pdl_items))
        if len(pdl_items) == len(range_items):
            # the streamed driver slices BOTH families with one row
            # axis; unequal lists (not produced by any collect path,
            # but allowed by the base contract) stay monolithic
            plan = self._pair_plan(pdl_items)
            if plan is not None and plan.multi_tile:
                return self._verify_pairs_streamed(
                    pdl_items, range_items, plan, session_of=session_of
                )
        return self._verify_pairs_monolithic(
            pdl_items, range_items, session_of=session_of
        )

    @staticmethod
    def _session_of(session_spans, n_rows):
        """Row index -> owning session callable (None when the launch
        has no cross-session structure to exploit)."""
        if not session_spans or len(session_spans) <= 1:
            return None
        owner = [0] * n_rows
        for s, (lo, hi) in session_spans.items():
            for i in range(lo, hi):
                owner[i] = s
        return owner.__getitem__

    def _xsession_dedup(self, pdl_items, range_items):
        """Fuse value-identical rows across sessions: every component of
        a pair row — PDLwSlackProof/Statement, AliceProof, EncryptionKey,
        DLogStatement — is a frozen dataclass over ints/Points, so the
        (pdl_row, range_row) pair itself is the value key, covering
        EVERY input the row's verdict depends on (verdicts are
        deterministic functions of row values up to the RLC soundness
        coin, and a row is only ever marked INVALID through its exact
        per-row check — so fanning a representative's verdict out to its
        duplicates is exact, not approximate). Returns None when the
        sessions share nothing (distinct committees): the caller then
        runs the fused path with session-first blame instead."""
        from . import rlc

        first: Dict[tuple, int] = {}
        rep_idx: List[int] = []
        owners: List[List[int]] = []
        for i, row in enumerate(zip(pdl_items, range_items)):
            j = first.get(row)
            if j is None:
                first[row] = len(rep_idx)
                rep_idx.append(i)
                owners.append([i])
            else:
                owners[j].append(i)
        if len(rep_idx) == len(pdl_items):
            return None
        rlc.count("xsession_rows_deduped", len(pdl_items) - len(rep_idx))
        with phase(
            "pairs.xsession_dedup",
            items=len(pdl_items),
            unique=len(rep_idx),
        ):
            p_u, r_u = self.verify_pairs(
                [pdl_items[i] for i in rep_idx],
                [range_items[i] for i in rep_idx],
            )
        pdl_out = [None] * len(pdl_items)
        range_out = [False] * len(range_items)
        for j, dup_rows in enumerate(owners):
            for i in dup_rows:
                pdl_out[i] = p_u[j]
                range_out[i] = r_u[j]
        return pdl_out, range_out

    def _pair_plan(self, pdl_items):
        """Tile plan for a pair batch. The widths feeding the row-bytes
        estimate come from the RECEIVER's own key vectors (ek.nn, N~) —
        verifier-local public values, so the tile cut depends only on
        public row counts and width buckets (SECURITY.md "Memory plan
        discipline"); adversarial wire fields cannot shape it."""
        from . import memplan

        if not memplan.memplan_enabled():
            return None
        nn_bits = max(st.ek.nn.bit_length() for _, st in pdl_items)
        nt_bits = max(st.N_tilde.bit_length() for _, st in pdl_items)
        return memplan.plan_rows(
            len(pdl_items),
            memplan.pair_row_bytes(nn_bits, nt_bits),
            label="pairs",
        )

    def _verify_pairs_streamed(
        self, pdl_items, range_items, plan, session_of=None
    ):
        """Memory-planned pair verification: the row axis runs as
        budget-sized tiles (mesh-aligned cuts, backend.memplan), each
        tile built -> staged -> verified -> wiped before the next is
        admitted, with tile k+1's host staging (gates, Fiat-Shamir
        hashing) prefetched behind tile k's engine time
        (utils.pipeline.prefetch_tiles — at most two tiles in flight,
        the planner's `inflight` factor).

        Row-local work (the whole range family, the EC u1 column, the
        FSDKR_RLC=0 column path) completes inside its tile. The
        cross-proof RLC folds accumulate as running per-group partial
        products (rlc.StreamFold): a tile contributes its short
        aggregated chains and its merged-exponent integer sums, and the
        O(1) full-width ladders per group run once at finish — so the
        combined checks never need all rows live, and the fold's
        full-width-ladder count matches the monolithic plan exactly.
        Failing groups bisect through the SAME subset-check/exact-leaf
        helpers as the monolithic path (blame identity is shared code,
        not a re-implementation)."""
        from ..utils.pipeline import prefetch_tiles, run_jobs
        from . import memplan, rlc
        from .powm import (
            fold_ladder2,
            multi_powm,
            multiexp_enabled,
            powm_columns,
            rangeopt_enabled,
        )
        from .rlc import rlc_enabled

        rows = len(pdl_items)
        range_out = [False] * rows

        if not rlc_enabled():
            # per-row column/joint path: verdicts are row-local, so each
            # tile runs the monolithic path on its own slice
            pdl_out = [None] * rows

            def consume_cols(span):
                lo, hi = span
                nbytes = plan.tile_bytes(hi - lo)
                memplan.stage(nbytes)
                try:
                    memplan.count_tile("pairs")
                    rlc.count("stream_tiles")
                    p_v, r_v = self._verify_pairs_monolithic(
                        pdl_items[lo:hi], range_items[lo:hi]
                    )
                    pdl_out[lo:hi] = p_v
                    range_out[lo:hi] = r_v
                finally:
                    memplan.release(nbytes)

            with phase(
                "pairs.stream_tiles", items=rows, tiles=len(plan.tiles)
            ):
                prefetch_tiles(
                    plan.tiles, lambda lo, hi: (lo, hi), consume_cols
                )
            return pdl_out, range_out

        e_vec = [0] * rows
        row_ok = [False] * rows
        ok1_vec = [False] * rows
        nt_folds: Dict[tuple, rlc.StreamFold] = {}
        nn_folds: Dict[tuple, rlc.StreamFold] = {}

        def prepare(lo, hi):
            # host-only staging of the NEXT tile: domain gates and
            # Fiat-Shamir challenges (read-only over shared state)
            tile = pdl_items[lo:hi]
            p_ok = [PDLwSlackProof.domain_gate(p, st) for p, st in tile]
            with phase("pdl.challenge", items=len(tile)):
                e_tile = [
                    PDLwSlackProof._challenge(
                        st, p.z, p.u1, p.u2, p.u3, self.config.hash_alg
                    )
                    if ok
                    else 0
                    for (p, st), ok in zip(tile, p_ok)
                ]
            return lo, hi, p_ok, e_tile

        def consume(prep):
            lo, hi, p_ok, e_tile = prep
            row_ok[lo:hi] = p_ok
            e_vec[lo:hi] = e_tile
            nbytes = plan.tile_bytes(hi - lo)
            memplan.stage(nbytes)
            try:
                memplan.count_tile("pairs")
                rlc.count("stream_tiles")
                # ---- PDL: this tile's fold contributions -------------
                nt_groups: Dict[tuple, List[int]] = {}
                nn_groups: Dict[tuple, List[int]] = {}
                for i in range(lo, hi):
                    if not row_ok[i]:
                        continue
                    st = pdl_items[i][1]
                    nt_groups.setdefault(self._pdl_nt_key(st), []).append(i)
                    nn_groups.setdefault(self._pdl_nn_key(st), []).append(i)
                mb: list = []
                me: list = []
                mm: list = []
                joins = []  # (fold, result slots, exp sums, row indices)
                for (h1, h2, nt), idxs in nt_groups.items():
                    rho = rlc.sample_rhos(len(idxs))
                    rows_d = self._pdl_nt_rows(pdl_items, e_vec, idxs)
                    lhs, rhs = PDLwSlackProof.rlc_fold_nt(
                        h1, h2, nt, rows_d, rho
                    )
                    fold = nt_folds.get((h1, h2, nt))
                    if fold is None:
                        fold = nt_folds[(h1, h2, nt)] = rlc.StreamFold(
                            nt, n_prods=1, n_exps=2
                        )
                    joins.append((fold, (len(mm),), lhs[1], idxs))
                    mb.append(rhs[0])
                    me.append(rhs[1])
                    mm.append(nt)
                for (n, nn), idxs in nn_groups.items():
                    rho = rlc.sample_rhos(len(idxs))
                    rows_d = self._pdl_nn_rows(pdl_items, e_vec, idxs)
                    s2_row, commit_row, gs1 = PDLwSlackProof.rlc_fold_nn(
                        n, nn, rows_d, rho
                    )
                    # the tile's merged (1+n)-exponent, recovered from
                    # the closed form: gs1 = 1 + (sum rho s1 mod n) * n
                    s1_part = (gs1 - 1) // n
                    fold = nn_folds.get((n, nn))
                    if fold is None:
                        fold = nn_folds[(n, nn)] = rlc.StreamFold(
                            nn, n_prods=2, n_exps=1
                        )
                    joins.append(
                        (fold, (len(mm), len(mm) + 1), (s1_part,), idxs)
                    )
                    for b, e, m in (s2_row, commit_row):
                        mb.append(b)
                        me.append(e)
                        mm.append(m)
                rlc.count(
                    "rows_folded",
                    sum(len(g) for g in nt_groups.values())
                    + sum(len(g) for g in nn_groups.values()),
                )
                with phase("pdl.rlc_fold", items=len(mm)):
                    res = multi_powm(mb, me, mm) if mm else []
                for fold, slots, exps, idxs in joins:
                    fold.absorb([res[s] for s in slots], exps, idxs)

                # ---- range family: row-local, completes in-tile ------
                r_slice = range_items[lo:hi]
                if rangeopt_enabled():
                    rstate = self._range_opt_prepare(r_slice)
                    run_jobs(self._range_opt_jobs(r_slice, rstate))
                    range_out[lo:hi] = self._range_opt_finish(
                        r_slice, rstate
                    )
                else:
                    cols, rmods = self._range_prepare(
                        r_slice, joint=multiexp_enabled()
                    )
                    with phase(
                        "range.modexp_columns",
                        items=len(cols) * len(r_slice),
                    ):
                        results = powm_columns(_modexp, *cols)
                    range_out[lo:hi] = self._range_finish(
                        r_slice, rmods, results
                    )

                # ---- EC u1 column of the tile ------------------------
                with phase("pdl.ec_u1", items=hi - lo):
                    ok1_vec[lo:hi] = self._pdl_u1_batch(
                        pdl_items[lo:hi], e_tile
                    )
            finally:
                memplan.release(nbytes)

        with phase("pairs.stream_tiles", items=rows, tiles=len(plan.tiles)):
            prefetch_tiles(plan.tiles, prepare, consume)

        # ---- finish: the O(1) full-width ladders per group -----------
        ok2_vec = [False] * rows
        ok3_vec = [False] * rows
        rlc.count("rlc_groups", len(nt_folds) + len(nn_folds))
        rlc.count("fullwidth_ladders", len(nt_folds) + len(nn_folds))
        with phase(
            "pdl.rlc_eq3",
            items=sum(len(f.rows) for f in nt_folds.values()),
        ):
            groups = list(nt_folds.items())
            if groups:
                lhs_vals = fold_ladder2(
                    [
                        ((h1, h2), tuple(f.exp_sums), nt)
                        for (h1, h2, nt), f in groups
                    ]
                )
                for ((h1, h2, nt), fold), lv in zip(groups, lhs_vals):
                    if lv == fold.prods[0]:
                        for i in fold.rows:
                            ok3_vec[i] = True
                    else:
                        self._pdl_nt_bisect(
                            pdl_items, e_vec, h1, h2, nt, fold.rows,
                            ok3_vec, session_of=session_of,
                        )
        with phase(
            "pdl.rlc_eq2",
            items=sum(len(f.rows) for f in nn_folds.values()),
        ):
            groups = list(nn_folds.items())
            if groups:
                a_pow = _modexp(
                    [f.prods[0] for _, f in groups],
                    [n for (n, _nn), _ in groups],
                    [nn for (_n, nn), _ in groups],
                )
                for ((n, nn), fold), ap in zip(groups, a_pow):
                    gs1 = (1 + (fold.exp_sums[0] % n) * n) % nn
                    if fold.prods[1] == gs1 * ap % nn:
                        for i in fold.rows:
                            ok2_vec[i] = True
                    else:
                        self._pdl_nn_bisect(
                            pdl_items, e_vec, n, nn, fold.rows, ok2_vec,
                            session_of=session_of,
                        )

        out = []
        for idx in range(rows):
            ok1 = ok1_vec[idx] and row_ok[idx]
            ok2 = ok2_vec[idx]
            ok3 = ok3_vec[idx]
            out.append(None if (ok1 and ok2 and ok3) else (ok1, ok2, ok3))
        return out, range_out

    def _verify_pairs_monolithic(
        self, pdl_items, range_items, session_of=None
    ):
        """Both pair-loop families through ONE fused launch set: every
        modexp column submitted together, so same-width columns across
        families share launches (e.g. both 256-bit challenge columns) —
        and under FSDKR_MULTIEXP both families' mod-n^2 equations pool
        into one joint multi-exponentiation launch (identical row shape:
        [s, c^{-1}] with exponents [n, e]). Cuts the pair loop's
        sequential launch count roughly in half, which dominates when
        small committees underfeed the chip."""
        from ..utils.pipeline import run_jobs, submit_bg
        from .powm import multiexp_enabled, powm_columns, rangeopt_enabled
        from .rlc import rlc_enabled

        joint = multiexp_enabled()
        if rlc_enabled():
            # PDL folds into per-receiver RLC groups (O(1) full-width
            # ladders per group); the range family cannot fold — its
            # Fiat-Shamir challenge binds the reconstructed per-row u/w
            # values (see proofs.alice_range) — so its columns ride the
            # joint/column path and share phase 1's fused launch set
            # with the RLC aggregate rows.
            pcols, state = self._pdl_rlc_prepare(pdl_items)
            pdl_finish = self._pdl_rlc_finish
        else:
            pcols, state = self._pdl_prepare(pdl_items, joint=joint)
            pdl_finish = self._pdl_finish
        # overlap the host EC u1 column with the fused modexp launch set
        # (see verify_pdl)
        e_vec = state[0]
        u1_fut = submit_bg(lambda: self._pdl_u1_batch(pdl_items, e_vec))
        if rangeopt_enabled():
            # FSDKR_RANGEOPT concurrent column scheduler: the PDL fold
            # columns, each receiver's mod-n^2 shared-exponent group,
            # each receiver environment's mod-N~ joint comb, and the
            # z^{-e} column are independent launch sets — run them
            # through the scheduler pool (sequential and bit-identical
            # at 1 worker) instead of one serial powm_columns chain.
            rstate = self._range_opt_prepare(range_items)
            presults = [None]

            def pdl_job():
                with phase(
                    "pdl.modexp_columns",
                    items=len(pcols) * len(pdl_items),
                ):
                    presults[0] = powm_columns(_modexp, *pcols)

            jobs = [pdl_job] + self._range_opt_jobs(range_items, rstate)
            n_rows = len(pcols) * len(pdl_items) + len(range_items)
            with phase("pairs.modexp_columns", items=n_rows):
                run_jobs(jobs)
            return (
                pdl_finish(
                    pdl_items, state, presults[0],
                    u1_vec=u1_fut.result() if u1_fut is not None else None,
                    session_of=session_of,
                ),
                self._range_opt_finish(range_items, rstate),
            )
        rcols, rmods = self._range_prepare(range_items, joint=joint)
        n_rows = len(pcols) * len(pdl_items) + len(rcols) * len(range_items)
        with phase("pairs.modexp_columns", items=n_rows):
            results = powm_columns(_modexp, *pcols, *rcols)
        return (
            pdl_finish(
                pdl_items, state, results[: len(pcols)],
                u1_vec=u1_fut.result() if u1_fut is not None else None,
                session_of=session_of,
            ),
            self._range_finish(range_items, rmods, results[len(pcols) :]),
        )

    # ------------------------------------------------------------------
    def _ring_pedersen_gate(self, proof, st, m_security) -> bool:
        """The statement modulus and the proof vectors are wire data: an
        even/tiny N crashes the Montgomery context, a negative A_i/Z_i
        crashes the limb encoder or the transcript, and oversized values
        inflate the launch — gate the row instead (honest: A_i < N,
        Z_i < phi < N). Must run BEFORE aggregation (FSDKR_RLC) or
        staging (column path)."""
        n_cap = self.config.paillier_bits + 64
        return (
            len(proof.A) == m_security
            and len(proof.Z) == m_security
            and st.N > 2
            and st.N % 2 == 1
            and st.N.bit_length() <= n_cap
            and 0 <= st.S < st.N
            and 0 <= st.T < st.N
            and all(0 <= z < st.N for z in proof.Z)
            and all(0 <= a < st.N for a in proof.A)
        )

    def verify_ring_pedersen(self, items, m_security):
        if not items:
            return []
        from .rlc import rlc_enabled

        if rlc_enabled():
            return self._ring_pedersen_rlc(items, m_security)
        bases, exps, moduli, rhs_a, rhs_s = [], [], [], [], []
        shapes_ok = []
        with phase("ringped.challenge", items=len(items)):
            for proof, st in items:
                ok = self._ring_pedersen_gate(proof, st, m_security)
                shapes_ok.append(ok)
                if not ok:
                    continue
                e = RingPedersenProof._challenge(proof.A, self.config.hash_alg)
                bits = challenge_bits(e, m_security, self.config.hash_alg)
                for a_i, z_i, b in zip(proof.A, proof.Z, bits):
                    bases.append(st.T)
                    exps.append(z_i)
                    moduli.append(st.N)
                    rhs_a.append(a_i)
                    rhs_s.append(st.S if b else 1)

        with phase("ringped.modexp", items=len(bases)):
            lhs = _modexp(bases, exps, moduli)
            rhs = _modmul(rhs_a, rhs_s, moduli)

        out = []
        row = 0
        for ok in shapes_ok:
            if not ok:
                out.append(False)
                continue
            good = all(
                lhs[row + i] == rhs[row + i] for i in range(m_security)
            )
            row += m_security
            out.append(good)
        return out

    def _ring_pedersen_rlc(self, items, m_security):
        """FSDKR_RLC path: each proof's M binary-challenge rows — all
        sharing (T, S, N) — fold into one RLC group
        (RingPedersenProof.rlc_fold): ONE full-width T-ladder plus one
        short M+1-term aggregated chain, instead of M full-width comb
        rows. A failing group bisects to exact per-row verdicts."""
        from ..core import intops
        from . import rlc
        from .powm import multi_powm, powm_columns

        shapes_ok = []
        plan = []  # (proof, st, bits, rho, position)
        lhs_b, lhs_e, lhs_m = [], [], []
        mb, me, mm = [], [], []
        with phase("ringped.challenge", items=len(items)):
            for proof, st in items:
                ok = self._ring_pedersen_gate(proof, st, m_security)
                shapes_ok.append(ok)
                if not ok:
                    continue
                e = RingPedersenProof._challenge(proof.A, self.config.hash_alg)
                bits = challenge_bits(e, m_security, self.config.hash_alg)
                rho = rlc.sample_rhos(m_security)
                lhs, rhs = RingPedersenProof.rlc_fold(st, proof, bits, rho)
                plan.append((proof, st, bits, len(mm)))
                lhs_b.append(lhs[0][0])
                lhs_e.append(lhs[1][0])
                lhs_m.append(lhs[2])
                mb.append(rhs[0])
                me.append(rhs[1])
                mm.append(rhs[2])
        if not plan:
            return [False] * len(items)
        rlc.count("rlc_groups", len(plan))
        rlc.count("rows_folded", len(plan) * m_security)
        rlc.count("fullwidth_ladders", len(plan))

        with phase("ringped.modexp", items=len(plan) * (m_security + 2)):
            lhs_vals, rhs_vals = powm_columns(
                _modexp, (lhs_b, lhs_e, lhs_m), (mb, me, mm)
            )

        out = []
        k = 0
        for ok in shapes_ok:
            if not ok:
                out.append(False)
                continue
            proof, st, bits, pos = plan[k]
            k += 1
            if lhs_vals[k - 1] == rhs_vals[pos]:
                out.append(True)
                continue
            rlc.count("bisect_fallbacks")

            def check(sub, proof=proof, st=st, bits=bits):
                rho = rlc.sample_rhos(len(sub))
                e_merged = sum(r * proof.Z[i] for r, i in zip(rho, sub))
                e_s = sum(r for r, i in zip(rho, sub) if bits[i])
                lhs = intops.mod_pow(st.T % st.N, e_merged, st.N)
                (rhs,) = multi_powm(
                    [tuple(proof.A[i] for i in sub) + (st.S,)],
                    [tuple(rho) + (e_s,)],
                    [st.N],
                    device=False,
                )
                return lhs == rhs

            def row_check(i, proof=proof, st=st, bits=bits):
                return (
                    intops.mod_pow(st.T % st.N, proof.Z[i], st.N)
                    == proof.A[i] * (st.S if bits[i] else 1) % st.N
                )

            verdicts = rlc.bisect_rows(range(m_security), check, row_check)
            out.append(all(verdicts[i] for i in range(m_security)))
        return out

    # ------------------------------------------------------------------
    def _correct_key_gate(self, proof, ek, rounds) -> bool:
        """Wire-ek gate (parity / small-factor / width cap), applied
        BEFORE aggregation or staging."""
        import math

        n = ek.n
        n_cap = self.config.paillier_bits + 64
        return (
            len(proof.sigma_vec) == rounds
            and n > 0
            and n % 2 == 1
            and n.bit_length() <= n_cap
            and math.gcd(n, correct_key._PRIMORIAL) == 1
            and all(0 < s < n for s in proof.sigma_vec)
        )

    def verify_correct_key(self, items, rounds):
        if not items:
            return []
        from .rlc import rlc_enabled

        if rlc_enabled():
            return self._correct_key_rlc(items, rounds)
        bases, exps, moduli, want = [], [], [], []
        gates = []
        with phase("correct_key.rho_derive", items=len(items)):
            for proof, ek in items:
                gate = self._correct_key_gate(proof, ek, rounds)
                gates.append(gate)
                if not gate:
                    continue
                n = ek.n
                for i, sigma in enumerate(proof.sigma_vec):
                    bases.append(sigma)
                    exps.append(n)
                    moduli.append(n)
                    want.append(
                        correct_key._derive_rho(
                            n, correct_key.SALT_STRING, i,
                            self.config.hash_alg,
                        )
                    )

        with phase("correct_key.modexp", items=len(bases)):
            got = _modexp(bases, exps, moduli)

        out = []
        row = 0
        for gate in gates:
            if not gate:
                out.append(False)
                continue
            good = all(got[row + i] == want[row + i] for i in range(rounds))
            row += rounds
            out.append(good)
        return out

    def _correct_key_rlc(self, items, rounds):
        """FSDKR_RLC path: each proof's `rounds` checks sigma_i^N ==
        rho_i (mod N) fold into (prod sigma_i^{rho_i})^N == prod
        rho_i^{rho_i} (NiCorrectKeyProof.rlc_fold): two short aggregated
        chains in phase 1, then ONE full-width ^N ladder per proof in a
        fused phase-2 launch — down from `rounds` full-width ladders."""
        from ..core import intops
        from . import rlc
        from .powm import multi_powm, powm_columns

        gates = []
        plan = []  # (sigma_vec, want, n, sigma position, target position)
        mb, me, mm = [], [], []
        with phase("correct_key.rho_derive", items=len(items)):
            for proof, ek in items:
                gate = self._correct_key_gate(proof, ek, rounds)
                gates.append(gate)
                if not gate:
                    continue
                n = ek.n
                want = [
                    correct_key._derive_rho(
                        n, correct_key.SALT_STRING, i, self.config.hash_alg
                    )
                    for i in range(rounds)
                ]
                rho = rlc.sample_rhos(rounds)
                sig_row, tgt_row = correct_key.NiCorrectKeyProof.rlc_fold(
                    proof.sigma_vec, want, n, rho
                )
                plan.append((proof.sigma_vec, want, n, len(mm), len(mm) + 1))
                for b, e, m in (sig_row, tgt_row):
                    mb.append(b)
                    me.append(e)
                    mm.append(m)
        if not plan:
            return [False] * len(items)
        rlc.count("rlc_groups", len(plan))
        rlc.count("rows_folded", len(plan) * rounds)
        rlc.count("fullwidth_ladders", len(plan))

        with phase("correct_key.modexp", items=len(plan) * (rounds + 1)):
            (multi_res,) = powm_columns(_modexp, (mb, me, mm))
            # phase 2: every aggregate to the N-th power, one fused launch
            a_pow = _modexp(
                [multi_res[g[3]] for g in plan],
                [g[2] for g in plan],
                [g[2] for g in plan],
            )

        out = []
        k = 0
        for gate in gates:
            if not gate:
                out.append(False)
                continue
            sigma_vec, want, n, _sig_pos, tgt_pos = plan[k]
            ap = a_pow[k]
            k += 1
            if ap == multi_res[tgt_pos]:
                out.append(True)
                continue
            rlc.count("bisect_fallbacks")

            def check(sub, sigma_vec=sigma_vec, want=want, n=n):
                rho = rlc.sample_rhos(len(sub))
                sv, wv = multi_powm(
                    [
                        tuple(sigma_vec[i] for i in sub),
                        tuple(want[i] for i in sub),
                    ],
                    [tuple(rho), tuple(rho)],
                    [n, n],
                    device=False,
                )
                return intops.mod_pow(sv, n, n) == wv

            def row_check(i, sigma_vec=sigma_vec, want=want, n=n):
                return intops.mod_pow(sigma_vec[i], n, n) == want[i]

            verdicts = rlc.bisect_rows(range(rounds), check, row_check)
            out.append(all(verdicts[i] for i in range(rounds)))
        return out

    # ------------------------------------------------------------------
    def verify_composite_dlog(self, items):
        if not items:
            return []
        from ..proofs.composite_dlog import STAT_BITS, CompositeDLogProof

        # the join statement (N, g, ni) and proof (x_commit, y) are all
        # wire data: gate the row's domain before transcripts/staging
        # (honest y = r + e*x < N * 2^(STAT_BITS + 256 + small))
        n_cap = self.config.paillier_bits + 64
        row_ok = [
            st.N > 2
            and st.N % 2 == 1
            and st.N.bit_length() <= n_cap
            and 0 <= st.g < st.N
            and 0 <= st.ni < st.N
            and 0 < p.x_commit < st.N
            and 0 <= p.y
            and p.y.bit_length() <= st.N.bit_length() + STAT_BITS + 320
            for p, st in items
        ]
        with phase("composite_dlog.challenge", items=len(items)):
            e_vec = [
                CompositeDLogProof._challenge(
                    p.x_commit, st, self.config.hash_alg
                )
                if ok
                else 0
                for (p, st), ok in zip(items, row_ok)
            ]
        moduli = [st.N if ok else 3 for (_, st), ok in zip(items, row_ok)]
        y_col = [p.y if ok else 0 for (p, _), ok in zip(items, row_ok)]
        with phase("composite_dlog.modexp", items=2 * len(items)):
            g_y = _modexp([st.g for _, st in items], y_col, moduli)
            ni_e = _modexp([st.ni for _, st in items], e_vec, moduli)
            lhs = _modmul(g_y, ni_e, moduli)
        return [
            row_ok[idx] and lhs[idx] == p.x_commit
            for idx, (p, st) in enumerate(items)
        ]

    # ------------------------------------------------------------------
    def validate_feldman(self, items):
        """sum_k A_k * u^k == S_u per row (`src/refresh_message.rs:177-188`),
        combined per VSS scheme:
            sum_u rho_u*S_u + sum_k (-sum_u rho_u u^k)*A_k == identity
        (the inner scalar sums are cheap host int math); per-row host
        fallback only for the rows of a failing scheme."""
        if not items:
            return []
        if not self.config.device_ec:  # see _pdl_u1_batch routing note
            return self._host.validate_feldman(items)
        # FSDKR_DELEGATE certificate pre-pass (proofs.msm_delegate):
        # schemes with an accepted broadcast certificate skip the device
        # MSM entirely; unresolved rows take the device path below. The
        # host route above runs the same pre-pass inside
        # HostBatchVerifier.validate_feldman.
        from ..proofs import msm_delegate

        pre = msm_delegate.try_delegate(items, self.config.hash_alg)
        if pre is not None:
            remaining = [i for i, v in enumerate(pre) if v is None]
            if not remaining:
                return [bool(v) for v in pre]
            sub = self._validate_feldman_device(
                [items[i] for i in remaining]
            )
            for i, v in zip(remaining, sub):
                pre[i] = v
            return pre
        return self._validate_feldman_device(items)

    def _validate_feldman_device(self, items):
        import secrets as _secrets

        from ..ops.ec_batch import batch_msm

        groups: Dict[int, List[int]] = {}
        for row, (scheme, _, _) in enumerate(items):
            groups.setdefault(id(scheme), []).append(row)

        group_rows = list(groups.values())
        g_points, g_scalars = [], []
        for rows in group_rows:
            scheme = items[rows[0]][0]
            rho = [_secrets.randbits(128) for _ in rows]
            # c_k = sum_u rho_u * u^k, built with incremental powers
            # (u <= n is small, so rho_u * u^k grows only ~8 bits per
            # step; one reduction at the end) — pow(u, k, q) per term is
            # ~5x slower over the n*(t+1) grid at n=256
            t1 = len(scheme.commitments)
            c_acc = [0] * t1
            for r, row in zip(rho, rows):
                u = items[row][2]
                pw = r
                for k in range(t1):
                    c_acc[k] += pw
                    pw *= u
            c_vec = [(CURVE_ORDER - c % CURVE_ORDER) % CURVE_ORDER for c in c_acc]
            g_points.append(
                [items[row][1] for row in rows] + list(scheme.commitments)
            )
            g_scalars.append(rho + c_vec)

        combined = batch_msm(g_points, g_scalars)

        out: List[bool] = [False] * len(items)
        for rows, comb in zip(group_rows, combined):
            if comb.infinity:
                for row in rows:
                    out[row] = True
            else:
                # honest per-row resolution (not the host's public
                # validate_feldman: the delegate pre-pass already ran)
                verdicts = self._host._validate_feldman_honest(
                    [items[row] for row in rows]
                )
                for row, v in zip(rows, verdicts):
                    out[row] = v
        return out
