"""Secret-CRT modexp engine for prover-owned moduli (FSDKR_CRT).

Everywhere the prover owns the factorization of its modulus — the
ring-Pedersen setup S = T^lambda and its M-round commitment column
(`proofs/ring_pedersen.py`), the correct-key N-th roots
(`proofs/correct_key.py`), and the Paillier decrypt legs
(`core/paillier.py`) — a full-width modexp mod N = p*q decomposes into
two half-width legs with exponents reduced modulo the leg group orders:

    x^e mod N  =  Garner( x^{e mod (p-1)} mod p,  x^{e mod (q-1)} mod q )

(lambda-reduced mod p^2/q^2 on the N^2 shapes). Each leg costs ~1/8 of
the full ladder (half the squarings at a quarter the per-multiply
price), so the pair is a ~4x algorithmic win before engine choice; the
accelerator-ZKP literature gets its prover throughput from exactly this
residue decomposition (SZKP, arXiv:2408.05890).

## Fault check (Bellcore), mandatory

A single faulted CRT leg is catastrophic: if S' differs from the true
S = x^e mod N in exactly one leg, gcd(S' - S mod N, N) — computable by
anyone who sees both a good and a faulted output, or one faulted output
plus the verification equation — recovers a prime factor (Boneh-DeMillo-
Lipton). Every leg here is therefore computed modulo p*r (q*r) for a
FRESH 64-bit prime r drawn from the OS CSPRNG per engine call, and the
leg is re-verified modulo r against an independently computed 64-bit
reference pow(x mod r, e mod (r-1), r) — valid because (r-1) divides
the leg's exponent-reduction modulus lcm(leg_order, r-1), and checked
against the ORIGINAL unreduced exponent, so a fault in the reduction
staging is caught too. The recombined value is additionally re-checked
against both leg residues. Any mismatch raises CrtFaultError BEFORE any
output is produced or any partial value escapes: a faulted leg can
never leak factor information. A random fault survives each check with
probability ~2^-64.

## Secret store

CRT contexts (p, q, leg orders, the Garner coefficient q^{-1} mod p —
all factorization-equivalent) live in a per-session in-process store in
THIS module, never in the public precompute LRU (`utils/lru.py`): the
LRU persists unwiped across sessions under the public-value-only rule
(SECURITY.md), which these values violate by definition. The store is
bounded, clears on demand (`clear_store()`), and wipes by reference-
dropping plus container clearing — the Python-int leg of the repo's
zeroize discipline. `tests/test_crt.py` pins that no factorization-
derived integer ever appears in the public LRU's keys or entries.

FSDKR_CRT=0 reverts every caller to the full-width path; results are
bit-identical either way (the decomposition is an arithmetic identity),
pinned by the parity suite.
"""

from __future__ import annotations

import math
import os
import secrets
import threading
from typing import Dict, List, Optional, Sequence

from ..errors import CrtFaultError

__all__ = [
    "crt_enabled",
    "CrtContext",
    "get_context",
    "clear_store",
    "store_stats",
    "crt_modexp_batch",
    "crt_powm_shared",
    "fault_checked_powm",
    "crt_stats",
    "stats_reset",
]


def crt_enabled() -> bool:
    """FSDKR_CRT gates the secret-CRT prover engine: =0 reverts every
    caller (ring-Pedersen gen/prove, correct-key, Paillier decrypt) to
    the full-width path for A/B isolation. Read at call time so the
    bench battery can toggle it per step."""
    return os.environ.get("FSDKR_CRT", "1").lower() not in (
        "0", "off", "false", "no",
    )


class CrtContext:
    """Factorization-derived constants for one prover-owned modulus.

    p_leg/q_leg are the leg moduli (p and q, or p^2 and q^2 for the N^2
    shapes); d_p/d_q the exponent-reduction moduli (the leg group
    orders p-1 / q-1, or p(p-1) / q(q-1)); qinv the Garner coefficient
    q_leg^{-1} mod p_leg. Every field is secret: holding any of them is
    holding the factorization.
    """

    __slots__ = ("modulus", "p_leg", "q_leg", "d_p", "d_q", "qinv")

    def __init__(self, modulus: int, p: int, q: int):
        if p <= 2 or q <= 2 or p == q:
            raise ValueError("CRT context needs two distinct odd primes")
        if modulus == p * q:
            self.p_leg, self.q_leg = p, q
            self.d_p, self.d_q = p - 1, q - 1
        elif modulus == (p * q) ** 2:
            # lambda(p^2) = p(p-1) for odd prime p
            self.p_leg, self.q_leg = p * p, q * q
            self.d_p, self.d_q = p * (p - 1), q * (q - 1)
        else:
            raise ValueError("modulus is neither p*q nor (p*q)^2")
        self.modulus = modulus
        self.qinv = pow(self.q_leg, -1, self.p_leg)

    def wipe(self) -> None:
        """Drop the factorization-derived references (Python ints cannot
        be overwritten in place; this is the documented int-level wipe —
        SECURITY.md)."""
        self.modulus = self.p_leg = self.q_leg = 0
        self.d_p = self.d_q = self.qinv = 0


class _SecretStore:
    """Per-session store of CrtContexts, keyed by modulus. Deliberately
    NOT utils.lru: entries are factorization-equivalent secrets and must
    never ride the persistent public cache. Bounded (oldest wiped on
    overflow), thread-safe, wiped wholesale by clear_store()."""

    MAX_ENTRIES = 4096

    def __init__(self):
        self._d: Dict[int, CrtContext] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get_or_build(self, modulus: int, p: int, q: int) -> CrtContext:
        with self._lock:
            ctx = self._d.get(modulus)
            if ctx is not None and ctx.p_leg and (
                modulus == p * q or modulus == (p * q) ** 2
            ):
                self.hits += 1
                return ctx
            self.misses += 1
            ctx = CrtContext(modulus, p, q)
            if len(self._d) >= self.MAX_ENTRIES:  # wipe the oldest entry
                old = self._d.pop(next(iter(self._d)))
                old.wipe()
            self._d[modulus] = ctx
            return ctx

    def clear(self) -> None:
        with self._lock:
            for ctx in self._d.values():
                ctx.wipe()
            self._d.clear()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._d),
                "hits": self.hits,
                "misses": self.misses,
            }


_STORE = _SecretStore()


def get_context(modulus: int, p: int, q: int) -> CrtContext:
    """Context for a prover-owned modulus from the per-session secret
    store (built and inserted on miss). modulus must be p*q or (p*q)^2."""
    return _STORE.get_or_build(modulus, p, q)


def clear_store() -> None:
    """Wipe every stored CRT context (session teardown / tests)."""
    _STORE.clear()


def store_stats() -> Dict[str, int]:
    return _STORE.stats()


# ---------------------------------------------------------------------------
# Engine statistics (bench.py emits these as the "crt" block). Backed by
# the process-global telemetry registry since ISSUE 6: one labeled
# counter for the engine events, function gauges for the secret store's
# occupancy (values never leave this module — only counts do).

_EVENTS = (
    "rows",            # rows routed through the CRT decomposition
    "legs",            # half-width legs computed (2 per row)
    "fault_checks",    # 64-bit-prime leg verifications performed
    "fallback_rows",   # rows that had to take the full-width path
    # ANALYTIC exponent-width reduction over all legs, priced from
    # structural modulus widths (public-modulus bits minus leg bits per
    # leg) — never from actual exponent bit-lengths, which are
    # secret-derived (SECURITY.md "Telemetry discipline")
    "exp_bits_saved",
)


def _metric():
    from ..telemetry import registry

    return registry.counter(
        "fsdkr_crt_events",
        "secret-CRT prover engine statistics (backend.crt)",
        labelnames=("event",),
    )


def _count(**kw) -> None:
    m = _metric()
    for k, v in kw.items():
        m.inc(v, event=k)


def crt_stats() -> Dict[str, int]:
    m = _metric()
    return {e: int(m.value(event=e)) for e in _EVENTS}


def stats_reset() -> None:
    _metric().reset()


def _register_store_gauges() -> None:
    from ..telemetry import registry

    registry.gauge(
        "fsdkr_crt_store_entries",
        "CRT secret-store occupancy (contexts held; values never exported)",
    ).set_function(lambda: _STORE.stats()["entries"])
    registry.gauge(
        "fsdkr_crt_store_hits",
        "CRT secret-store lifetime hits",
    ).set_function(lambda: _STORE.stats()["hits"])
    registry.gauge(
        "fsdkr_crt_store_misses",
        "CRT secret-store lifetime misses",
    ).set_function(lambda: _STORE.stats()["misses"])


_register_store_gauges()


# ---------------------------------------------------------------------------
# Fresh 64-bit fault-check prime

# Deterministic Miller-Rabin witness set for 64-bit candidates (exact
# below 3.3 * 10^24): the check prime itself is not secret-critical, but
# a composite r would silently weaken the fault check's 2^-64 bound.
_MR64_BASES = (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37)


def _is_prime64(n: int) -> bool:
    if n < 2:
        return False
    for b in _MR64_BASES:
        if n % b == 0:
            return n == b
    d = n - 1
    s = (d & -d).bit_length() - 1
    d >>= s
    for b in _MR64_BASES:
        x = pow(b, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(s - 1):
            x = x * x % n
            if x == n - 1:
                break
        else:
            return False
    return True


def _fresh_check_prime(bases: Sequence[int]) -> int:
    """Fresh 64-bit prime from the OS CSPRNG, resampled until it divides
    no base in the batch (a base = 0 mod r would defeat the Fermat-form
    reference value; probability ~rows * 2^-63 per draw)."""
    while True:
        r = secrets.randbits(64) | (1 << 63) | 1
        if not _is_prime64(r):
            continue
        if any(b % r == 0 for b in bases):
            continue
        return r


# ---------------------------------------------------------------------------
# Engines for the half-width legs

def _leg_powm(bases: List[int], exps: List[int], mods: List[int]) -> List[int]:
    """One batch of CRT legs: mpz_powm_sec when GMP is present (the leg
    exponents are factorization-derived — GMP's constant-time ladder is
    exactly the right tool), the native fsdkr_crt_modexp_batch otherwise
    (run-grouped Montgomery constants, full wipe discipline), CPython
    pow as the last fallback."""
    from ..native import gmp
    from ..utils.roofline import stamp_generic_host
    from ..utils.trace import get_tracer

    # CRT-phase roofline stamp: legs priced at the leg-MODULUS width
    # (structurally half the public modulus) — the leg exponents are
    # factorization-derived secrets and their true bit-lengths must not
    # reach exported MAC counts (SECURITY.md "Telemetry discipline");
    # reduced exponents are ~modulus-width anyway so the price is tight
    if bases and get_tracer().enabled:
        mod_bits = max(m.bit_length() for m in mods)
        stamp_generic_host(len(bases), mod_bits, mod_bits)
    if gmp.available():
        return gmp.powm_batch(bases, exps, mods, secret=True)
    from .. import native

    return native.crt_modexp_batch(bases, exps, mods)


def _check_leg(base: int, exp: int, r: int, leg_value: int) -> None:
    """Bellcore fault check for one leg computed mod p_leg*r: the leg's
    residue mod r must equal the independently computed 64-bit Fermat
    reference pow(base mod r, exp mod (r-1), r) — exp is the ORIGINAL
    unreduced exponent, so reduction-staging faults are caught too.
    The fault_checks counter is maintained by the BATCH callers (one
    registry touch per batch, not per leg — the hot-path rule)."""
    if leg_value % r != pow(base % r, exp % (r - 1), r):
        raise CrtFaultError()


def _recombine_checked(
    base: int, exp: int, r: int, sp: int, sq: int, ctx: CrtContext
) -> int:
    """The security-critical per-row sequence, in exactly one place for
    every CRT path: verify BOTH legs against the fresh prime BEFORE any
    recombination (a bad leg aborts without anything derived from it),
    Garner-recombine, then re-check the result against both leg residues
    and its range (a faulted Garner step is caught here)."""
    _check_leg(base, exp, r, sp)
    _check_leg(base, exp, r, sq)
    xp, xq = sp % ctx.p_leg, sq % ctx.q_leg
    v = xq + (xp - xq) * ctx.qinv % ctx.p_leg * ctx.q_leg
    if v % ctx.p_leg != xp or v % ctx.q_leg != xq or not (
        0 <= v < ctx.modulus
    ):
        raise CrtFaultError()
    return v


def crt_modexp_batch(
    bases: Sequence[int],
    exps: Sequence[int],
    contexts: Sequence[Optional[CrtContext]],
    fallback=None,
    moduli: Optional[Sequence[int]] = None,
) -> List[int]:
    """bases[i]^exps[i] mod contexts[i].modulus with CRT decomposition,
    fresh-prime fault checks, and Garner recombination. Rows whose
    context is None (modulus then read from `moduli`), whose base shares
    a factor with the modulus, or whose exponent is negative take
    `fallback(bases, exps, mods)` (pow when omitted) — exact, just not
    decomposed. Raises CrtFaultError (and returns nothing) if any leg or
    the recombination fails its check."""
    rows = len(bases)
    if rows == 0:
        return []
    if not (rows == len(exps) == len(contexts)):
        raise ValueError("batch length mismatch")

    def _mod(i: int) -> int:
        if contexts[i] is not None:
            return contexts[i].modulus
        if moduli is None:
            raise ValueError("row without context needs a modulus")
        return moduli[i]

    crt_idx: List[int] = []
    fb_idx: List[int] = []
    for i, (b, e, ctx) in enumerate(zip(bases, exps, contexts)):
        if ctx is None or e < 0 or math.gcd(b, ctx.modulus) != 1:
            fb_idx.append(i)
        else:
            crt_idx.append(i)

    out: List[Optional[int]] = [None] * rows
    if fb_idx:
        _count(fallback_rows=len(fb_idx))
        if fallback is None:
            for i in fb_idx:
                out[i] = pow(bases[i], exps[i], _mod(i))
        else:
            res = fallback(
                [bases[i] for i in fb_idx],
                [exps[i] for i in fb_idx],
                [_mod(i) for i in fb_idx],
            )
            for i, v in zip(fb_idx, res):
                out[i] = v
    if not crt_idx:
        return out  # type: ignore[return-value]

    r = _fresh_check_prime([bases[i] for i in crt_idx])
    r1 = r - 1

    # stage both legs of every row into ONE engine batch: [p-legs, q-legs]
    # grouped so equal-modulus runs stay consecutive for the native
    # engine's constants amortization
    leg_b: List[int] = []
    leg_e: List[int] = []
    leg_m: List[int] = []
    bits_saved = 0
    for leg in ("p", "q"):
        for i in crt_idx:
            ctx = contexts[i]
            leg_mod = (ctx.p_leg if leg == "p" else ctx.q_leg) * r
            d = ctx.d_p if leg == "p" else ctx.d_q
            # exponent reduced mod lcm(leg group order, r-1): valid for
            # bases coprime to leg and r (both guaranteed above)
            red = exps[i] % (d // math.gcd(d, r1) * r1)
            leg_b.append(bases[i] % leg_mod)
            leg_e.append(red)
            leg_m.append(leg_mod)
            # ANALYTIC savings from structural modulus widths only —
            # the true exponent/reduced bit-lengths are secret-derived
            # and must not reach the exported counter (SECURITY.md
            # "Telemetry discipline"); accumulated locally so the
            # registry is touched once per batch, not per leg
            bits_saved += max(
                0, ctx.modulus.bit_length() - leg_mod.bit_length()
            )
    _count(
        rows=len(crt_idx), legs=2 * len(crt_idx),
        fault_checks=2 * len(crt_idx), exp_bits_saved=bits_saved,
    )

    res = _leg_powm(leg_b, leg_e, leg_m)
    k = len(crt_idx)
    for j, i in enumerate(crt_idx):
        out[i] = _recombine_checked(
            bases[i], exps[i], r, res[j], res[k + j], contexts[i]
        )
    return out  # type: ignore[return-value]


def crt_powm_shared(
    base: int, exps: Sequence[int], ctx: CrtContext
) -> List[int]:
    """Fixed-base column base^exps[i] mod ctx.modulus via half-width
    comb legs — the ring-Pedersen M-round commitment shape (M=256 rows
    sharing one secret-owned modulus). Each leg runs the native one-shot
    comb (`modexp_shared(cache=False)`: the reduced base and its window
    table are factorization-derived, so they ride the build-use-wipe
    path, never the public LRU) with the leg's squaring ladder paid once
    and amortized over all M rows; fault checks and Garner per row as in
    crt_modexp_batch."""
    m = len(exps)
    if m == 0:
        return []
    if math.gcd(base, ctx.modulus) != 1 or any(e < 0 for e in exps):
        _count(fallback_rows=m)
        from ..native import gmp

        if gmp.available():
            return gmp.powm_batch(
                [base] * m, list(exps), [ctx.modulus] * m, secret=True
            )
        return [pow(base, e, ctx.modulus) for e in exps]

    r = _fresh_check_prime([base])
    r1 = r - 1
    from .. import native

    legs = []
    bits_saved = 0
    for leg_mod0, d in ((ctx.p_leg, ctx.d_p), (ctx.q_leg, ctx.d_q)):
        leg_mod = leg_mod0 * r
        lcm = d // math.gcd(d, r1) * r1
        red = [e % lcm for e in exps]
        # analytic, structural-width savings (see crt_modexp_batch)
        bits_saved += m * max(
            0, ctx.modulus.bit_length() - leg_mod.bit_length()
        )
        legs.append(
            native.modexp_shared(base % leg_mod, red, leg_mod, cache=False)
        )
    _count(rows=m, legs=2 * m, fault_checks=2 * m, exp_bits_saved=bits_saved)
    return [
        _recombine_checked(base, e, r, sp, sq, ctx)
        for e, sp, sq in zip(exps, legs[0], legs[1])
    ]


def fault_checked_powm(base: int, exp: int, leg_mod: int) -> int:
    """One fault-checked HALF exponentiation: base^exp mod leg_mod,
    computed mod leg_mod*r and verified mod the fresh 64-bit prime r —
    the Paillier-decrypt shape, whose two legs carry DIFFERENT exponents
    (c^{p-1} mod p^2, c^{q-1} mod q^2) and are consumed separately by
    the L-function, so cross-leg agreement cannot apply; each leg is
    verified independently instead. Requires gcd(base, leg_mod) == 1;
    callers fall back to the unchecked path otherwise."""
    if exp < 0 or math.gcd(base, leg_mod) != 1:
        raise ValueError("fault_checked_powm needs a unit base, exp >= 0")
    r = _fresh_check_prime([base])
    (v,) = _leg_powm([base % (leg_mod * r)], [exp], [leg_mod * r])
    _count(legs=1, fault_checks=1)
    _check_leg(base, exp, r, v)
    return v % leg_mod
