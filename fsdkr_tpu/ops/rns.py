"""RNS (residue number system) Montgomery modexp: the MXU path.

The CIOS limb kernel (ops.montgomery) is VPU-bound: every 2048-bit
Montgomery product is ~128 sequential carry-coupled vector steps. In RNS
the same product decomposes over ~130 independent 16-bit prime channels
(CRT), where multiplication is elementwise and the only cross-channel
work is *base extension* — and base extension is a literal matrix
multiplication: q_B = xi (B, k) @ T (k, k) with a SHARED constant matrix.
That routes the O(k^2) heart of every modular multiplication through the
MXU systolic array, which is what the n=256 < 1 s north-star needs
(`BASELINE.json`); the reference's serial GMP `mod_pow` calls
(`/root/reference/src/range_proofs.rs:129-148` etc.) have no analogue of
this because CPUs have no 100+ TOP/s matmul unit to feed.

Method (Bajard-Plantard-style full-RNS Montgomery with a Shenoy-Kumaresan
exact second extension):

- Two bases A = {a_1..a_k}, B = {b_1..b_k} of distinct 16-bit primes with
  2 channels of slack (A > (k+1)^2 * N), plus one redundant channel m_r.
  Working domain: values < (k+1) * N, chain-stable.
- MontMul(x, y) -> x*y*A^{-1} mod N (up to the domain bound):
    d    = x .* y                 (elementwise, all channels)
    xi   = d_A .* c1_A            (c1 folds -N^{-1} and (A/a_i)^{-1})
    S1   = xi @ T1                (MXU; T1[i,j] = |A/a_i| mod (B, m_r))
    q^   = S1 mod (B, m_r)        (fast extension: off by alpha*A <= k*A,
                                   absorbed by the slack channels)
    r    = (d + q^ .* N) .* A^{-1}   (in B and m_r)
    zeta = r_B .* c2_B            (c2 = |(B/b_j)^{-1}| mod b_j)
    S2   = zeta @ T2              (MXU; T2[j,i] = |B/b_j| mod (A, m_r))
    beta = (S2_r - r_r) * |B|^{-1} mod m_r     (exact: beta < k < m_r)
    r_A  = S2_A - beta * |B| mod A             (exact second extension)
- 16-bit channel products fit uint32; channel reduction uses 2^16-fold
  steps (primes are drawn downward from 2^16, so 2^16 mod m is small).
- The matmuls run as four 8-bit-split bf16 dots with f32 accumulation:
  products < 2^16, sums over <= 128-channel chunks < 2^23 — exact.
- Host <-> device: big integers cross as 16-bit limb tensors (C-speed
  bytes conversion); limbs -> residues is itself one matmul against
  W[l, c] = 2^(16 l) mod m_c. Residues -> integer is a host CRT over A.

Exponentiation is the same MSB-first 4-bit fixed window as the CIOS
kernel, so wall-clock is ~1.27 RNS MontMuls per exponent bit.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .limbs import (
    LIMB_BITS,
    WINDOW_BITS,
    bucket_exp_bits,
    ints_to_limbs,
    limbs_to_ints,
    wipe_array,
)
from .montgomery import _normalize_carries

__all__ = ["RNSBases", "rns_modexp", "rns_multi_modexp", "rns_bases_for_bits"]

_U32 = jnp.uint32
_LANE = 128  # matmul contraction chunk: k-slices of <= 128 keep f32 sums exact


def _resplit(lo, hi):
    """Chunk a pre-split constant matrix along the contraction dim at
    _LANE terms (f32 dot exactness bound)."""
    ksz = lo.shape[0]
    return [
        (lo[s : s + _LANE], hi[s : s + _LANE], s, min(_LANE, ksz - s))
        for s in range(0, ksz, _LANE)
    ]


def _pallas_mode() -> int:
    """0 = plain XLA chain; 1 = fused Pallas MontMul (ops.pallas_rns);
    2 = Pallas in interpret mode (CPU tests). FSDKR_PALLAS=0/1 forces;
    default 'auto' uses Pallas on real TPU only."""
    mode = os.environ.get("FSDKR_PALLAS", "auto")
    if mode == "0":
        return 0
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        on_tpu = False
    if mode == "1":
        return 1 if on_tpu else 2
    return 1 if on_tpu else 0


def _gen_channel_primes(count: int) -> List[int]:
    """`count` distinct 16-bit primes, descending from 2^16 (keeps
    2^16 mod m small, so channel reduction folds converge fast)."""
    from ..core.primes import is_probable_prime

    out = []
    cand = (1 << 16) - 1
    while len(out) < count and cand > (1 << 15):
        if is_probable_prime(cand, rounds=16):
            out.append(cand)
        cand -= 2
    if len(out) < count:
        raise ValueError("not enough 16-bit primes for the requested base")
    return out


class RNSBases:
    """Shared per-width-class constants: the channel primes, extension
    matrices, and the limb->residue conversion matrix. Independent of the
    batch's moduli (those enter per-launch as residue tensors)."""

    def __init__(self, value_bits: int, num_limbs: int):
        # Domain invariant: every chained value stays < (k+1)*N. With the
        # fast (uncorrected) first extension this needs A > (k+1)^2 * N
        # and B likewise; channel primes are < 2^16 and shrink as the list
        # deepens, so k is grown until the bound holds with 2^16 margin.
        self.value_bits = value_bits
        self.num_limbs = num_limbs
        k = -(-value_bits // 16) + 2
        while True:
            primes = _gen_channel_primes(2 * k + 1)
            a_primes = primes[0::2][:k]
            b_primes = primes[1::2][:k]
            A = 1
            for p in a_primes:
                A *= p
            B = 1
            for p in b_primes:
                B *= p
            bound = (k + 1) * (k + 1) << (value_bits + 16)
            if A > bound and B > bound:
                break
            k += 1
        self.k = k
        self.A_primes = a_primes
        self.B_primes = b_primes
        self.m_r = primes[2 * k]
        self.A = A
        self.B = B

        A, B, m_r = self.A, self.B, self.m_r
        aps, bps = self.A_primes, self.B_primes

        Ai = [A // p for p in aps]
        Bj = [B // p for p in bps]
        # c-constant halves (the -N^{-1} factor joins per launch)
        self.Ai_inv = np.array(
            [pow(Ai[i] % aps[i], -1, aps[i]) for i in range(k)], np.uint32
        )
        self.c2_B = np.array(
            [pow(Bj[j] % bps[j], -1, bps[j]) for j in range(k)], np.uint32
        )
        # extension matrices, target channels B+mr / A+mr
        self.T1 = np.array(
            [[Ai[i] % m for m in bps + [m_r]] for i in range(k)], np.uint32
        )  # (k, k+1)
        self.T2 = np.array(
            [[Bj[j] % m for m in aps + [m_r]] for j in range(k)], np.uint32
        )  # (k, k+1)
        self.Ainv_B = np.array(
            [pow(A % m, -1, m) for m in bps + [m_r]], np.uint32
        )  # (k+1,) inverse of A in B channels and m_r
        self.B_mod_A = np.array([B % m for m in aps], np.uint32)
        self.Binv_r = np.uint32(pow(B % m_r, -1, m_r))

        self.mA = np.array(aps, np.uint32)
        self.mB = np.array(bps, np.uint32)
        self.m_all = np.array(aps + bps + [m_r], np.uint32)  # (2k+1,)
        # limb -> residue conversion matrix W[l, c] = 2^(16 l) mod m_c
        self.Wconv = np.array(
            [[pow(1 << (16 * l), 1, int(m)) for m in self.m_all]
             for l in range(num_limbs)],
            np.uint32,
        )  # (num_limbs, 2k+1)

    # -- device CRT-exit constants (built lazily: only the exit needs them)
    @property
    def exit_consts(self):
        """(Ai_inv, Ai_mod_mr, Ainv_mr, Ai_limbs, A_limbs, mA, m_r,
        value-limb count) for the device-side residues->limbs kernel."""
        if not hasattr(self, "_exit_consts"):
            k = self.k
            Ai = [self.A // p for p in self.A_primes]
            # v = sum xi_i*Ai < sum a_i*Ai = k*A -> A bits + ~log2(k) <= 10
            # extra bits; lv rounds up with 24 bits of headroom so the
            # limb layout's top limbs are provably zero (the carry
            # normalization drops the top limb's overflow)
            lv = -(-(self.A.bit_length() + 24) // 16)
            self._exit_consts = (
                jnp.asarray(self.Ai_inv),
                jnp.asarray(np.array([a % self.m_r for a in Ai], np.uint32)),
                jnp.asarray(np.uint32(self.Ainv_B[k])),  # A^{-1} mod m_r
                jnp.asarray(ints_to_limbs(Ai, lv)),  # (k, Lv)
                jnp.asarray(ints_to_limbs([self.A], lv)[0]),  # (Lv,)
                jnp.asarray(self.mA),
                jnp.asarray(np.uint32(self.m_r)),
                lv,
            )
        return self._exit_consts


# Above this group count the comb's power ladder runs on the device
# batch (sequential squarings over G rows amortize); below it the host's
# native modexp chain wins (mirrors montgomery._HOST_LADDER_MAX_GROUPS).
_DEVICE_LADDER_MIN_GROUPS = 64

_BASES_CACHE: Dict[Tuple[int, int], RNSBases] = {}


def rns_bases_for_bits(value_bits: int, num_limbs: int) -> RNSBases:
    key = (value_bits, num_limbs)
    if key not in _BASES_CACHE:
        _BASES_CACHE[key] = RNSBases(value_bits, num_limbs)
    return _BASES_CACHE[key]


# ---------------------------------------------------------------------------
# device kernels


def _channel_mod(v, m, u16m, folds=6):
    """v mod m per channel, v uint32 < 2^32, m a 16-bit prime close to
    2^16, u16m = 2^16 mod m (<= 8536 for primes >= 57000). Each fold
    maps v -> (v>>16)*u16m + (v&0xffff), shrinking the high part by
    ~2^-3 per pass; six folds take a full 2^32-1 input below 3m in the
    worst case (65535*8536 chain), which the two conditional subtracts
    then finish. Callers with tighter input bounds pass a smaller
    `folds`."""
    for _ in range(folds):
        v = (v >> 16) * u16m + (v & jnp.uint32(0xFFFF))
    v = jnp.where(v >= m, v - m, v)
    v = jnp.where(v >= m, v - m, v)
    return v


def _mulmod(a, b, m, u16m):
    return _channel_mod(a * b, m, u16m)


def _matmul_mod(x, T_splits, mods, u16m):
    """x (R, k) uint32 16-bit values; T pre-split into bf16 lo/hi chunks;
    returns (R, C) sums mod per-column modulus.

    Each 8-bit-split product sum over a <=128 chunk is < 2^23, exact in
    f32; chunk results add in uint32 (< 2^25 * chunks) and reduce by
    channel folds."""
    xl = (x & jnp.uint32(0xFF)).astype(jnp.bfloat16)
    xh = (x >> 8).astype(jnp.bfloat16)
    out = None
    for lo, hi, start, size in T_splits:
        xs_l = lax.dynamic_slice_in_dim(xl, start, size, axis=1)
        xs_h = lax.dynamic_slice_in_dim(xh, start, size, axis=1)
        pll = jax.lax.dot(xs_l, lo, precision=lax.Precision.HIGHEST,
                          preferred_element_type=jnp.float32).astype(_U32)
        plh = jax.lax.dot(xs_l, hi, precision=lax.Precision.HIGHEST,
                          preferred_element_type=jnp.float32).astype(_U32)
        phl = jax.lax.dot(xs_h, lo, precision=lax.Precision.HIGHEST,
                          preferred_element_type=jnp.float32).astype(_U32)
        phh = jax.lax.dot(xs_h, hi, precision=lax.Precision.HIGHEST,
                          preferred_element_type=jnp.float32).astype(_U32)
        # combine pll + 2^8(plh+phl) + 2^16 phh with interleaved folds;
        # worst-case bound stays < 2^31 for <=128-term chunks and
        # channel primes >= 57000 (u16m <= 8536)
        lo16 = jnp.uint32(0xFFFF)
        t1 = plh + phl  # < 2^24
        t1 = (t1 >> 16) * u16m + (t1 & lo16)  # < 2^21.1
        v = pll + (t1 << 8)  # < 2^29.2
        t2 = (phh >> 16) * u16m + (phh & lo16)  # < 2^20.2
        t2 = t2 << 8  # < 2^28.2
        t2 = (t2 >> 16) * u16m + (t2 & lo16)  # < 2^25.3
        t2 = (t2 >> 16) * u16m + (t2 & lo16)  # < 2^22.4
        v = v + (t2 << 8)  # < 2^31
        part = _channel_mod(v, mods, u16m, folds=6)
        out = part if out is None else out + part
    return _channel_mod(out, mods, u16m, folds=1)


def _split_T(T: np.ndarray):
    """Pre-split a constant uint32 matrix (k, C) into bf16 lo/hi chunks
    along the contraction dim."""
    k = T.shape[0]
    out = []
    for start in range(0, k, _LANE):
        size = min(_LANE, k - start)
        chunk = T[start : start + size]
        out.append(
            (
                jnp.asarray((chunk & 0xFF).astype(np.float32), jnp.bfloat16),
                jnp.asarray((chunk >> 8).astype(np.float32), jnp.bfloat16),
                start,
                size,
            )
        )
    return out


def _rns_mont_mul(x, y, consts):
    """One RNS Montgomery product. x, y, out: (R, 2k+1) residues
    (channels ordered A | B | m_r)."""
    k = consts["k"]
    if consts.get("pallas"):
        from .pallas_rns import rns_mont_mul_pallas

        return rns_mont_mul_pallas(
            x,
            y,
            consts["c1_A"],
            consts["N_Bmr"],
            consts["pallas"],
            k=k,
            interpret=consts["pallas_interpret"],
        )
    m_all, u_all = consts["m_all"], consts["u_all"]
    d = _mulmod(x, y, m_all, u_all)
    d_A = d[:, :k]
    xi = _mulmod(d_A, consts["c1_A"], m_all[:k], u_all[:k])
    q = _matmul_mod(xi, consts["T1s"], m_all[k:], u_all[k:])  # (R, k+1) in B|mr
    t = _mulmod(q, consts["N_Bmr"], m_all[k:], u_all[k:])
    t = t + d[:, k:]
    t = jnp.where(t >= m_all[k:], t - m_all[k:], t)
    r_Bmr = _mulmod(t, consts["Ainv_B"], m_all[k:], u_all[k:])
    zeta = _mulmod(r_Bmr[:, :k], consts["c2_B"], m_all[k : 2 * k], u_all[k : 2 * k])
    s = _matmul_mod(zeta, consts["T2s"], consts["mA_mr"], consts["uA_mr"])  # (R, k+1) in A|mr
    # exact Shenoy correction from the redundant channel
    m_r, u_r = m_all[2 * k], u_all[2 * k]
    diff = jnp.where(
        s[:, k] >= r_Bmr[:, k], s[:, k] - r_Bmr[:, k], s[:, k] + m_r - r_Bmr[:, k]
    )
    beta = _mulmod(diff, consts["Binv_r"], m_r, u_r)  # (R,) < k
    corr = _mulmod(
        jnp.broadcast_to(beta[:, None], (x.shape[0], k)),
        consts["B_mod_A"],
        m_all[:k],
        u_all[:k],
    )
    r_A = jnp.where(s[:, :k] >= corr, s[:, :k] - corr, s[:, :k] + m_all[:k] - corr)
    return jnp.concatenate([r_A, r_Bmr], axis=1)


def _limbs_to_residues(limbs, consts):
    """(R, L) 16-bit limb rows -> (R, 2k+1) residues via the conversion
    matmul."""
    return _matmul_mod(limbs, consts["Ws"], consts["m_all"], consts["u_all"])


_EXIT_CHUNK = 64  # 8-bit-split dot sums < 64*255^2 < 2^22: exact in f32
# AND small enough that three accumulated chunks stay in uint32 planes


@partial(jax.jit, static_argnames=("k", "lv"))
def _crt_exit_kernel(
    res, Ai_inv, Ai_mr, Ainv_mr, Ai_limbs, A_limbs, mA, m_r, *, k, lv
):
    """Device-side CRT exit: (R, 2k+1) result residues -> (R, lv+1)
    canonical base-2^16 limbs of the exact value v < (k+1)*N.

    v = sum_i xi_i * (A/a_i) - alpha*A with xi_i = |res_i * (A/a_i)^{-1}|
    mod a_i; the wrap count alpha <= k is recovered exactly from the
    redundant channel: alpha = (S - v) * A^{-1} mod m_r. The big
    sum-of-products rides the MXU as 8-bit-split bf16 dots accumulated in
    two uint32 planes (delayed carries), then one carry normalization and
    one borrow-scan subtraction. Replaces the ~80 us/row host CRT loop
    (~60 s over an n=256 collect)."""
    r_cnt = res.shape[0]
    u_mA = jnp.uint32(1 << 16) % mA
    u_r = jnp.uint32(1 << 16) % m_r
    xi = _mulmod(res[:, :k], Ai_inv[None, :], mA[None, :], u_mA[None, :])

    # wrap count from the redundant channel
    T_mr = _resplit(
        (Ai_mr[:, None] & 0xFF).astype(jnp.bfloat16),
        (Ai_mr[:, None] >> 8).astype(jnp.bfloat16),
    )
    S_r = _matmul_mod(xi, T_mr, m_r[None], u_r[None])[:, 0]  # (R,)
    v_r = res[:, 2 * k]
    diff = jnp.where(S_r >= v_r, S_r - v_r, S_r + m_r - v_r)
    alpha = _mulmod(diff, Ainv_mr, m_r, u_r)  # (R,) <= k

    # S = xi @ Ai_limbs in two delayed-carry planes
    xl = (xi & jnp.uint32(0xFF)).astype(jnp.bfloat16)
    xh = (xi >> 8).astype(jnp.bfloat16)
    Tl = (Ai_limbs & jnp.uint32(0xFF)).astype(jnp.bfloat16)
    Th = (Ai_limbs >> 8).astype(jnp.bfloat16)
    dot = partial(
        jax.lax.dot,
        precision=lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    planeA = jnp.zeros((r_cnt, lv + 1), _U32)  # units of 2^(16j)
    planeB = jnp.zeros((r_cnt, lv + 1), _U32)  # units of 2^(16j+8)
    for s in range(0, k, _EXIT_CHUNK):
        e = min(s + _EXIT_CHUNK, k)
        pll = dot(xl[:, s:e], Tl[s:e]).astype(_U32)
        plh = dot(xl[:, s:e], Th[s:e]).astype(_U32)
        phl = dot(xh[:, s:e], Tl[s:e]).astype(_U32)
        phh = dot(xh[:, s:e], Th[s:e]).astype(_U32)
        planeA = planeA.at[:, :lv].add(pll)
        planeA = planeA.at[:, 1 : lv + 1].add(phh)  # 2^16 shift: +1 limb
        planeB = planeB.at[:, :lv].add(plh + phl)
    low = (planeB & jnp.uint32(0xFFFF)) << 8
    hi = (planeB >> 16) << 8  # units of 2^(16(j+1))
    v = planeA + low
    v = v.at[:, 1:].add(hi[:, :-1])
    v = _normalize_carries(v)

    # subtract alpha * A (v >= alpha*A by construction)
    aA = alpha[:, None] * A_limbs[None, :]  # < 2^9 * 2^16 per limb
    aA = jnp.concatenate([aA, jnp.zeros((r_cnt, 1), _U32)], axis=1)
    aA = _normalize_carries(aA)
    return _sub_limbs(v, aA)


def _sub_limbs(a, b):
    """Limb-wise a - b (a >= b), borrow scan over canonical base-2^16."""
    r_cnt = a.shape[0]

    def step(borrow, limbs):
        aj, bj = limbs
        d = aj + (jnp.uint32(1) << LIMB_BITS) - bj - borrow
        return jnp.uint32(1) - (d >> LIMB_BITS), d & jnp.uint32(0xFFFF)

    _, diff_t = lax.scan(step, jnp.zeros((r_cnt,), _U32), (a.T, b.T))
    return diff_t.T


def _pallas_shared(consts_arrays):
    """Shape the shared constants for ops.pallas_rns (rank >= 2)."""
    (m_all, u_all, T1l, T1h, T2l, T2h, Ainv_B, c2_B, B_mod_A, Binv_r, _Wl, _Wh) = (
        consts_arrays
    )
    return (
        m_all[None, :],
        u_all[None, :],
        T1l,
        T1h,
        T2l,
        T2h,
        Ainv_B[None, :],
        c2_B[None, :],
        B_mod_A[None, :],
        Binv_r.reshape(1, 1),
    )


@partial(jax.jit, static_argnames=("exp_bits", "k", "pallas_mode"))
def _rns_modexp_kernel(
    base_limbs, exp, a2n_limbs, c1_A, N_Bmr, consts_arrays, *, exp_bits, k,
    pallas_mode=0,
):
    """base^exp per row. All big values arrive as 16-bit limb tensors and
    convert to residues on device. Returns the full residue rows (host
    finishes with one CRT sum per row over the A channels)."""
    (m_all, u_all, T1l, T1h, T2l, T2h, Ainv_B, c2_B, B_mod_A, Binv_r, Wl, Wh) = (
        consts_arrays
    )

    consts = dict(
        k=k,
        m_all=m_all,
        u_all=u_all,
        T1s=_resplit(T1l, T1h),
        T2s=_resplit(T2l, T2h),
        Ws=_resplit(Wl, Wh),
        mA_mr=jnp.concatenate([m_all[:k], m_all[2 * k :]]),
        uA_mr=jnp.concatenate([u_all[:k], u_all[2 * k :]]),
        Ainv_B=Ainv_B,
        c2_B=c2_B,
        B_mod_A=B_mod_A,
        Binv_r=Binv_r,
        c1_A=c1_A,
        N_Bmr=N_Bmr,
        pallas=_pallas_shared(consts_arrays) if pallas_mode else None,
        pallas_interpret=pallas_mode == 2,
    )

    base_res = _limbs_to_residues(base_limbs, consts)
    a2n_res = _limbs_to_residues(a2n_limbs, consts)
    one = jnp.ones_like(base_res)  # residues of 1 in every channel

    # into the A-Montgomery domain: x*A = MontMul(x, A^2 mod N)
    base_m = _rns_mont_mul(base_res, a2n_res, consts)
    one_m = _rns_mont_mul(one, a2n_res, consts)  # A mod N residues

    # 16-entry window table
    def build(j, table):
        prev = table[j - 1]
        table = table.at[j].set(_rns_mont_mul(prev, base_m, consts))
        return table

    table0 = jnp.zeros((1 << WINDOW_BITS,) + base_m.shape, _U32)
    table0 = table0.at[0].set(one_m).at[1].set(base_m)
    table = lax.fori_loop(2, 1 << WINDOW_BITS, build, table0)

    idx = jnp.arange(1 << WINDOW_BITS, dtype=_U32)[:, None, None]

    def step(wi, acc):
        shift = exp_bits - WINDOW_BITS * (wi + 1)
        limb = lax.dynamic_index_in_dim(
            exp, shift // LIMB_BITS, axis=1, keepdims=False
        )
        w = (limb >> (shift % LIMB_BITS)) & ((1 << WINDOW_BITS) - 1)
        for _ in range(WINDOW_BITS):
            acc = _rns_mont_mul(acc, acc, consts)
        sel = jnp.sum(
            jnp.where(w[None, :, None] == idx, table, jnp.uint32(0)), axis=0
        )
        return _rns_mont_mul(acc, sel, consts)

    acc = lax.fori_loop(0, exp_bits // WINDOW_BITS, step, one_m)
    return _rns_mont_mul(acc, one, consts)  # leave Montgomery domain


def _prep_consts(bases: RNSBases):
    """Device-ready shared constant arrays for the kernel."""
    m_all = bases.m_all
    u_all = ((1 << 16) % m_all.astype(np.uint64)).astype(np.uint32)
    return (
        jnp.asarray(m_all),
        jnp.asarray(u_all),
        jnp.asarray((bases.T1 & 0xFF).astype(np.float32), jnp.bfloat16),
        jnp.asarray((bases.T1 >> 8).astype(np.float32), jnp.bfloat16),
        jnp.asarray((bases.T2 & 0xFF).astype(np.float32), jnp.bfloat16),
        jnp.asarray((bases.T2 >> 8).astype(np.float32), jnp.bfloat16),
        jnp.asarray(bases.Ainv_B),
        jnp.asarray(bases.c2_B),
        jnp.asarray(bases.B_mod_A),
        jnp.asarray(np.full((1,), bases.Binv_r, np.uint32)[0]),
        jnp.asarray((bases.Wconv & 0xFF).astype(np.float32), jnp.bfloat16),
        jnp.asarray((bases.Wconv >> 8).astype(np.float32), jnp.bfloat16),
    )


@partial(jax.jit, static_argnames=("exp_bits", "k", "interpret"))
def _rns_modexp_full_pallas(
    base_limbs, exp, a2n_limbs, c1_A, N_Bmr, consts_arrays, *, exp_bits, k,
    interpret,
):
    """Whole-modexp fusion: limb->residue conversion in XLA (one matmul),
    then ops.pallas_rns.rns_modexp_pallas runs the entire window loop in
    VMEM (table + accumulator never touch HBM)."""
    (m_all, u_all, _T1l, _T1h, _T2l, _T2h, _AinvB, _c2B, _BmodA, _Binvr, Wl, Wh) = (
        consts_arrays
    )

    conv = dict(m_all=m_all, u_all=u_all, Ws=_resplit(Wl, Wh))
    base_res = _limbs_to_residues(base_limbs, conv)
    a2n_res = _limbs_to_residues(a2n_limbs, conv)
    from .pallas_rns import rns_modexp_pallas

    return rns_modexp_pallas(
        base_res, exp, a2n_res, c1_A, N_Bmr, _pallas_shared(consts_arrays),
        exp_bits=exp_bits, k=k, interpret=interpret,
    )


@partial(jax.jit, static_argnames=("exp_bits", "k", "pallas_mode", "device_ladder", "tree_chunk"))
def _rns_shared_modexp_kernel(
    powers_limbs, exp, a2n_limbs, c1_A, N_Bmr, consts_arrays, *, exp_bits, k,
    pallas_mode=0, device_ladder=False, tree_chunk=1,
):
    """Fixed-base comb over RNS MontMuls: groups share (base, modulus).

    powers_limbs: (W, G, L) limb rows of base^(16^w) mod n (host ladder),
    or — with device_ladder=True — (1, G, L) holding just the bases, the
    4*W sequential squarings running on the G-row device batch instead
    (the host ladder costs G*W native modexp steps, seconds at G=256).
    exp: (G, M, EL); a2n_limbs: (G, L); c1_A: (G, k); N_Bmr: (G, k+1).
    Same comb structure as ops.montgomery._shared_modexp_kernel — ladder
    amortized per group, log-depth 16-entry tables, one table multiply
    per window on the (G*M)-row batch — but every multiply is an RNS
    MontMul whose base extensions ride the MXU. Returns (G*M, 2k+1)
    residues for the host CRT exit.
    """
    (m_all, u_all, T1l, T1h, T2l, T2h, Ainv_B, c2_B, B_mod_A, Binv_r, Wl, Wh) = (
        consts_arrays
    )

    # w_cnt always follows the (static) exponent width — with the device
    # ladder, powers_limbs is (1, G, L) and its leading dim is NOT the
    # window count
    w_cnt = exp_bits // WINDOW_BITS
    _, g, L = powers_limbs.shape
    m = exp.shape[1]
    c = 2 * k + 1

    def consts_for(c1_rows, n_rows):
        return dict(
            k=k,
            m_all=m_all,
            u_all=u_all,
            T1s=_resplit(T1l, T1h),
            T2s=_resplit(T2l, T2h),
            Ws=_resplit(Wl, Wh),
            mA_mr=jnp.concatenate([m_all[:k], m_all[2 * k :]]),
            uA_mr=jnp.concatenate([u_all[:k], u_all[2 * k :]]),
            Ainv_B=Ainv_B,
            c2_B=c2_B,
            B_mod_A=B_mod_A,
            Binv_r=Binv_r,
            c1_A=c1_rows,
            N_Bmr=n_rows,
            pallas=_pallas_shared(consts_arrays) if pallas_mode else None,
            pallas_interpret=pallas_mode == 2,
        )

    # group consts broadcast to the batch layouts used below
    consts_g = consts_for(c1_A, N_Bmr)
    c1_gm = jnp.broadcast_to(c1_A[:, None], (g, m, k)).reshape(g * m, k)
    n_gm = jnp.broadcast_to(N_Bmr[:, None], (g, m, k + 1)).reshape(g * m, k + 1)
    consts_gm = consts_for(c1_gm, n_gm)

    def consts_rep(times):
        return consts_for(
            jnp.concatenate([c1_A] * times, axis=0),
            jnp.concatenate([N_Bmr] * times, axis=0),
        )

    consts_2g, consts_4g, consts_7g = consts_rep(2), consts_rep(4), consts_rep(7)

    a2n_res = _limbs_to_residues(a2n_limbs, consts_g)  # (G, C)
    if device_ladder:
        # powers_limbs is (1, G, L): just the bases. Build the ladder on
        # the G-row batch: powers[w] = base_m^(16^w), 4 squarings apart.
        base_res = _limbs_to_residues(powers_limbs.reshape(g, L), consts_g)
        base_m = _rns_mont_mul(base_res, a2n_res, consts_g)

        def ladder_step(w, carry):
            p, pws = carry
            pws = lax.dynamic_update_index_in_dim(pws, p, w, axis=0)
            for _ in range(WINDOW_BITS):
                p = _rns_mont_mul(p, p, consts_g)
            return p, pws

        powers0 = jnp.zeros((w_cnt, g, c), _U32)
        _, powers = lax.fori_loop(0, w_cnt, ladder_step, (base_m, powers0))
    else:
        c1_wg = jnp.broadcast_to(c1_A[None], (w_cnt, g, k)).reshape(w_cnt * g, k)
        n_wg = jnp.broadcast_to(
            N_Bmr[None], (w_cnt, g, k + 1)
        ).reshape(w_cnt * g, k + 1)
        consts_wg = consts_for(c1_wg, n_wg)
        a2n_wg = jnp.broadcast_to(
            a2n_res[None], (w_cnt, g, c)
        ).reshape(w_cnt * g, c)
        p_res = _limbs_to_residues(powers_limbs.reshape(w_cnt * g, L), consts_wg)
        powers = _rns_mont_mul(p_res, a2n_wg, consts_wg).reshape(w_cnt, g, c)

    one_g = jnp.ones((g, c), _U32)
    one_m_g = _rns_mont_mul(one_g, a2n_res, consts_g)  # (G, C)

    # Per-window 16-entry tables are built ON THE FLY inside the window
    # loop from powers[w] (log-depth products): a materialized
    # all-windows table is (16, W, G, C) — terabytes at the n=256
    # ring-Pedersen shape — while a fly-built one is (16, reps*G, C)
    # live at a time (reps = 1 sequential, tree_chunk for a tree chunk),
    # and the extra ~14 products per window are ~5% of the (G*M)-row
    # accumulation work. One builder serves both paths so their product
    # ladders cannot diverge.
    def make_table_fn(reps):
        rows = reps * g
        cc1 = consts_g if reps == 1 else consts_rep(reps)
        cc2, cc4, cc7 = (
            consts_rep(2 * reps), consts_rep(4 * reps), consts_rep(7 * reps)
        )
        one_rows = jnp.broadcast_to(one_m_g[None], (reps, g, c)).reshape(
            rows, c
        )

        def table_fn(p1):  # p1: (rows, C) -> (16, rows, C)
            def mul_many(pairs, cc):
                a = jnp.concatenate([x for x, _ in pairs], axis=0)
                b = jnp.concatenate([y for _, y in pairs], axis=0)
                out = _rns_mont_mul(a, b, cc)
                return [
                    out[i * rows : (i + 1) * rows] for i in range(len(pairs))
                ]

            p2 = _rns_mont_mul(p1, p1, cc1)
            p3, p4 = mul_many([(p2, p1), (p2, p2)], cc2)
            p5, p6, p7, p8 = mul_many(
                [(p4, p1), (p4, p2), (p4, p3), (p4, p4)], cc4
            )
            p9, p10, p11, p12, p13, p14, p15 = mul_many(
                [(p8, p1), (p8, p2), (p8, p3), (p8, p4), (p8, p5), (p8, p6),
                 (p8, p7)],
                cc7,
            )
            return jnp.stack(
                [one_rows, p1, p2, p3, p4, p5, p6, p7, p8,
                 p9, p10, p11, p12, p13, p14, p15],
                axis=0,
            )

        return table_fn

    acc0 = jnp.broadcast_to(one_m_g[:, None], (g, m, c)).reshape(g * m, c)

    CH = tree_chunk

    if CH == 1:
        window_table = make_table_fn(1)  # (G, C) -> (16, G, C)
        idx = jnp.arange(1 << WINDOW_BITS, dtype=_U32)[:, None, None, None]

        def acc_step(w, acc):
            shift = WINDOW_BITS * w
            limb = lax.dynamic_index_in_dim(
                exp, shift // LIMB_BITS, axis=2, keepdims=False
            )  # (G, M)
            d = (limb >> (shift % LIMB_BITS)) & ((1 << WINDOW_BITS) - 1)
            entries = window_table(
                lax.dynamic_index_in_dim(powers, w, axis=0, keepdims=False)
            )  # (16, G, C)
            sel = jnp.sum(
                jnp.where(
                    d[None, :, :, None] == idx, entries[:, :, None, :], jnp.uint32(0)
                ),
                axis=0,
            )
            return _rns_mont_mul(acc, sel.reshape(g * m, c), consts_gm)

        acc = lax.fori_loop(0, w_cnt, acc_step, acc0)
    else:
        # Tree chunking: CH windows' tables built in one batched set of
        # log-depth products, their selected entries reduced in log2(CH)
        # MontMul levels. Padded windows read zero exponent digits and
        # select entry 0 = Montgomery one (the MontMul identity), so
        # non-power-of-two window counts stay exact.
        n_chunks = -(-w_cnt // CH)
        w_pad = n_chunks * CH
        el_pad = w_pad * WINDOW_BITS // LIMB_BITS
        if el_pad > exp.shape[2]:
            exp = jnp.pad(exp, ((0, 0), (0, 0), (0, el_pad - exp.shape[2])))
        if w_pad > w_cnt:
            powers = jnp.pad(
                powers, ((0, w_pad - w_cnt), (0, 0), (0, 0)), mode="edge"
            )
        table_chunk = make_table_fn(CH)

        # per-level consts for the tree reductions (static level ladder)
        consts_lvl = {}
        half = CH // 2
        while half >= 1:
            consts_lvl[half] = consts_for(
                jnp.tile(c1_gm, (half, 1)), jnp.tile(n_gm, (half, 1))
            )
            half //= 2

        mask = jnp.uint32((1 << WINDOW_BITS) - 1)
        ws0 = jnp.arange(CH, dtype=jnp.int32)
        idx5 = jnp.arange(1 << WINDOW_BITS, dtype=_U32)[:, None, None, None, None]

        def chunk_step(ci, acc):
            shifts = WINDOW_BITS * (ci * CH + ws0)  # (CH,)
            limbs = jnp.take(exp, shifts // LIMB_BITS, axis=2)  # (G, M, CH)
            sh = (shifts % LIMB_BITS).astype(limbs.dtype)
            d = (limbs >> sh[None, None, :]) & mask
            p_chunk = lax.dynamic_slice_in_dim(powers, ci * CH, CH, axis=0)
            entries = table_chunk(p_chunk.reshape(CH * g, c)).reshape(
                16, CH, g, c
            )
            dt = d.transpose(2, 0, 1)  # (CH, G, M)
            sel = jnp.sum(
                jnp.where(
                    dt[None, :, :, :, None] == idx5,
                    entries[:, :, :, None, :],
                    jnp.uint32(0),
                ),
                axis=0,
            )  # (CH, G, M, C)
            x = sel.reshape(CH, g * m, c)
            lvl = CH
            while lvl > 1:
                half = lvl // 2
                a = x[0:lvl:2].reshape(half * g * m, c)
                b = x[1:lvl:2].reshape(half * g * m, c)
                x = _rns_mont_mul(a, b, consts_lvl[half]).reshape(
                    half, g * m, c
                )
                lvl = half
            return _rns_mont_mul(acc, x[0], consts_gm)

        acc = lax.fori_loop(0, n_chunks, chunk_step, acc0)
    one_rows = jnp.ones((g * m, c), _U32)
    return _rns_mont_mul(acc, one_rows, consts_gm)


def rns_modexp_shared(
    bases_int: Sequence[int],
    exps_per_group: Sequence[Sequence[int]],
    moduli: Sequence[int],
    value_bits: int,
    mesh=None,
) -> List[List[int]]:
    """Fixed-base comb through the RNS/MXU pipeline:
    bases[g]^exps[g][m] mod moduli[g]. The per-group power ladder runs on
    the host (native modexp chain) for small group counts, on the device
    batch above _DEVICE_LADDER_MIN_GROUPS; rows pad with exponent 0.
    Moduli sharing a factor with a channel prime fall back per group."""
    g_cnt = len(bases_int)
    if g_cnt == 0:
        return []
    num_limbs = -(-value_bits // LIMB_BITS)
    rb = rns_bases_for_bits(value_bits, num_limbs)
    k = rb.k
    m_max = max(len(e) for e in exps_per_group)
    exp_bits = bucket_exp_bits([e for grp in exps_per_group for e in grp])
    el = -(-exp_bits // LIMB_BITS)
    w_cnt = exp_bits // WINDOW_BITS

    bases_int = [b % n for b, n in zip(bases_int, moduli)]
    a2n = []
    c1 = np.zeros((g_cnt, k), np.uint32)
    n_bmr = np.zeros((g_cnt, k + 1), np.uint32)
    fallback_groups = {}
    moduli = list(moduli)
    work_bases = list(bases_int)
    for r, n in enumerate(moduli):
        try:
            row = [
                (-pow(n, -1, a)) % a * int(rb.Ai_inv[i]) % a
                for i, a in enumerate(rb.A_primes)
            ]
        except ValueError:
            fallback_groups[r] = [
                pow(bases_int[r], e, n) for e in exps_per_group[r]
            ]
            moduli[r], work_bases[r] = 3, 1
            row = [
                (-pow(3, -1, a)) % a * int(rb.Ai_inv[i]) % a
                for i, a in enumerate(rb.A_primes)
            ]
        c1[r, :] = row
        n_bmr[r, :k] = [moduli[r] % b for b in rb.B_primes]
        n_bmr[r, k] = moduli[r] % rb.m_r
        a2n.append(pow(rb.A, 2, moduli[r]))

    device_ladder = g_cnt > _DEVICE_LADDER_MIN_GROUPS
    if device_ladder:
        # bases only; the kernel runs the 4*W sequential squarings on the
        # G-row device batch (host chain would be G*W native modexps)
        powers_limbs = ints_to_limbs(work_bases, num_limbs).reshape(
            1, g_cnt, num_limbs
        )
    else:
        # host power ladder, Montgomery-free (plain residue inputs; the
        # kernel converts and enters the Montgomery domain itself);
        # squarings ride the native C++ core via intops.mod_pow
        from ..core import intops

        flat_powers: List[int] = []
        for b, n in zip(work_bases, moduli):
            p = b % n
            for _ in range(w_cnt):
                flat_powers.append(p)
                p = intops.mod_pow(p, 1 << WINDOW_BITS, n)
        powers_limbs = (
            ints_to_limbs(flat_powers, num_limbs)
            .reshape(g_cnt, w_cnt, num_limbs)
            .transpose(1, 0, 2)
        )

    flat_exps: List[int] = []
    for grp in exps_per_group:
        flat_exps.extend(list(grp) + [0] * (m_max - len(grp)))
    exp_limbs = ints_to_limbs(flat_exps, el).reshape(g_cnt, m_max, el)

    args = (
        jnp.asarray(powers_limbs),
        jnp.asarray(exp_limbs),
        jnp.asarray(ints_to_limbs(a2n, num_limbs)),
        jnp.asarray(c1),
        jnp.asarray(n_bmr),
        _prep_consts(rb),
    )
    if mesh is not None and g_cnt % int(mesh.devices.size) == 0:
        from ..parallel.shard_kernels import sharded_rns_shared_modexp_fn

        from .montgomery import _comb_tree_chunk

        out_res = sharded_rns_shared_modexp_fn(
            mesh, exp_bits, k, _pallas_mode(), device_ladder,
            tree_chunk=_comb_tree_chunk(w_cnt, g_cnt * m_max, 2 * k + 1, table_rows=g_cnt),
        )(*args)
    else:
        from .montgomery import _comb_tree_chunk

        out_res = _rns_shared_modexp_kernel(
            *args,
            exp_bits=exp_bits,
            k=k,
            pallas_mode=_pallas_mode(),
            device_ladder=device_ladder,
            tree_chunk=_comb_tree_chunk(w_cnt, g_cnt * m_max, 2 * k + 1, table_rows=g_cnt),
        )
    # device CRT exit over all (group, row) cells at once
    ec = rb.exit_consts
    v_limbs = _crt_exit_kernel(out_res, *ec[:-1], k=k, lv=ec[-1])
    vs = limbs_to_ints(np.asarray(v_limbs))
    wipe_array(exp_limbs)  # comb exponents are prover secrets

    out: List[List[int]] = []
    for r in range(g_cnt):
        if r in fallback_groups:
            out.append(fallback_groups[r])
            continue
        out.append(
            [
                vs[r * m_max + mi] % moduli[r]
                for mi in range(len(exps_per_group[r]))
            ]
        )
    return out


@partial(jax.jit, static_argnames=("exp_bits_seq", "k", "pallas_mode"))
def _rns_multi_modexp_kernel(
    base_limbs, exp, a2n_limbs, c1_A, N_Bmr, consts_arrays, *, exp_bits_seq,
    k, pallas_mode=0,
):
    """Joint (Straus) multi-exponentiation through the RNS/MXU pipeline:
    result[b] = prod_t base[t, b]^exp[t, b] mod n[b], returned as residue
    rows for the CRT exit.

    base_limbs: (T, B, L); exp: (T, B, EL); a2n_limbs: (B, L); c1_A:
    (B, k); N_Bmr: (B, k+1). exp_bits_seq: per-term bucketed widths,
    descending. Same shared-squaring-chain schedule as the CIOS
    _multi_modexp_kernel — one 4-bit chain as deep as the widest term,
    one 16-entry table multiply per active term per window — with every
    product an RNS MontMul (base extensions on the MXU; the fused Pallas
    MontMul rides through `pallas_mode` exactly as in _rns_modexp_kernel).
    """
    (m_all, u_all, T1l, T1h, T2l, T2h, Ainv_B, c2_B, B_mod_A, Binv_r, Wl, Wh) = (
        consts_arrays
    )
    t_cnt, b_rows, L = base_limbs.shape
    c = 2 * k + 1

    def consts_for(c1_rows, n_rows):
        return dict(
            k=k,
            m_all=m_all,
            u_all=u_all,
            T1s=_resplit(T1l, T1h),
            T2s=_resplit(T2l, T2h),
            Ws=_resplit(Wl, Wh),
            mA_mr=jnp.concatenate([m_all[:k], m_all[2 * k :]]),
            uA_mr=jnp.concatenate([u_all[:k], u_all[2 * k :]]),
            Ainv_B=Ainv_B,
            c2_B=c2_B,
            B_mod_A=B_mod_A,
            Binv_r=Binv_r,
            c1_A=c1_rows,
            N_Bmr=n_rows,
            pallas=_pallas_shared(consts_arrays) if pallas_mode else None,
            pallas_interpret=pallas_mode == 2,
        )

    consts_b = consts_for(c1_A, N_Bmr)
    c1_tb = jnp.broadcast_to(c1_A[None], (t_cnt, b_rows, k)).reshape(
        t_cnt * b_rows, k
    )
    n_tb = jnp.broadcast_to(N_Bmr[None], (t_cnt, b_rows, k + 1)).reshape(
        t_cnt * b_rows, k + 1
    )
    consts_tb = consts_for(c1_tb, n_tb)

    a2n_res = _limbs_to_residues(a2n_limbs, consts_b)  # (B, C)
    a2n_tb = jnp.broadcast_to(a2n_res[None], (t_cnt, b_rows, c)).reshape(
        t_cnt * b_rows, c
    )
    base_res = _limbs_to_residues(base_limbs.reshape(t_cnt * b_rows, L), consts_tb)
    base_m = _rns_mont_mul(base_res, a2n_tb, consts_tb)
    one = jnp.ones((b_rows, c), _U32)
    one_m = _rns_mont_mul(one, a2n_res, consts_b)
    one_m_tb = jnp.broadcast_to(one_m[None], (t_cnt, b_rows, c)).reshape(
        t_cnt * b_rows, c
    )

    def build(j, table):
        prev = table[j - 1]
        return table.at[j].set(_rns_mont_mul(prev, base_m, consts_tb))

    table0 = jnp.zeros((1 << WINDOW_BITS, t_cnt * b_rows, c), _U32)
    table0 = table0.at[0].set(one_m_tb).at[1].set(base_m)
    table = lax.fori_loop(2, 1 << WINDOW_BITS, build, table0).reshape(
        1 << WINDOW_BITS, t_cnt, b_rows, c
    )

    w_total = exp_bits_seq[0] // WINDOW_BITS
    idx = jnp.arange(1 << WINDOW_BITS, dtype=_U32)[:, None, None]

    def window_step(wi, acc, active):
        for _ in range(WINDOW_BITS):
            acc = _rns_mont_mul(acc, acc, consts_b)
        sels = []
        for t in active:
            w_t = exp_bits_seq[t] // WINDOW_BITS
            shift = exp_bits_seq[t] - WINDOW_BITS * (wi - (w_total - w_t) + 1)
            limb = lax.dynamic_index_in_dim(
                exp[t], shift // LIMB_BITS, axis=1, keepdims=False
            )
            sh = (shift % LIMB_BITS).astype(_U32)
            d = (limb >> sh) & ((1 << WINDOW_BITS) - 1)
            sels.append(jnp.sum(
                jnp.where(d[None, :, None] == idx, table[:, t], jnp.uint32(0)),
                axis=0,
            ))
        if len(sels) < 4:  # few-term rows: the sequential fold's shape
            for sel in sels:
                acc = _rns_mont_mul(acc, sel, consts_b)
            return acc
        # n-term rows (the RLC aggregated groups): log-depth tree of
        # batched RNS Montgomery products over the selected entries —
        # exact (one A^{-1} factor per combine, same as the sequential
        # fold; odd levels pad with one_m, the RNS MontMul identity).
        # See ops.montgomery._multi_modexp_kernel for the CIOS twin.
        while len(sels) > 1:
            if len(sels) % 2:
                sels.append(one_m)
            half = len(sels) // 2
            consts_h = consts_for(
                jnp.tile(c1_A, (half, 1)), jnp.tile(N_Bmr, (half, 1))
            )
            prod = _rns_mont_mul(
                jnp.concatenate(sels[0::2], axis=0),
                jnp.concatenate(sels[1::2], axis=0),
                consts_h,
            )
            sels = [
                prod[i * b_rows : (i + 1) * b_rows] for i in range(half)
            ]
        return _rns_mont_mul(acc, sels[0], consts_b)

    acc = one_m
    starts = [w_total - eb // WINDOW_BITS for eb in exp_bits_seq]
    bounds = sorted(set(starts + [w_total]))
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        active = tuple(t for t in range(t_cnt) if starts[t] <= lo)

        def seg(wi, acc, _active=active):
            return window_step(wi, acc, _active)

        acc = lax.fori_loop(lo, hi, seg, acc)
    return _rns_mont_mul(acc, one, consts_b)  # leave the Montgomery domain


def rns_multi_modexp(
    bases_rows: Sequence[Sequence[int]],
    exps_rows: Sequence[Sequence[int]],
    moduli: Sequence[int],
    value_bits: int,
    exp_bits_seq: Sequence[int],
    mesh=None,
) -> List[int]:
    """Joint multi-exponentiation rows through the RNS/MXU pipeline:
    prod_t bases_rows[r][t]^exps_rows[r][t] mod moduli[r]. Moduli sharing
    a factor with a channel prime fall back to host pow per row (same
    policy as rns_modexp)."""
    rows = len(moduli)
    if rows == 0:
        return []
    k_terms = len(exp_bits_seq)
    order = sorted(range(k_terms), key=lambda t: -exp_bits_seq[t])
    eb = tuple(exp_bits_seq[t] for t in order)
    num_limbs = -(-value_bits // LIMB_BITS)
    rb = rns_bases_for_bits(value_bits, num_limbs)
    k = rb.k
    el = -(-eb[0] // LIMB_BITS)

    a2n = []
    c1 = np.zeros((rows, k), np.uint32)
    n_bmr = np.zeros((rows, k + 1), np.uint32)
    fallback_rows = {}
    moduli = list(moduli)
    bases_rows = [list(bs) for bs in bases_rows]
    exps_rows = [list(es) for es in exps_rows]
    for r, n in enumerate(moduli):
        try:
            for i, a in enumerate(rb.A_primes):
                c1[r, i] = (-pow(n, -1, a)) % a * int(rb.Ai_inv[i]) % a
            for j, b in enumerate(rb.B_primes):
                n_bmr[r, j] = n % b
            n_bmr[r, k] = n % rb.m_r
        except ValueError:  # gcd(n, a_i) > 1: host fallback, neutral row
            acc = 1
            for b_t, e_t in zip(bases_rows[r], exps_rows[r]):
                acc = acc * pow(b_t % n, e_t, n) % n
            fallback_rows[r] = acc
            moduli[r] = 3
            bases_rows[r] = [1] * k_terms
            exps_rows[r] = [0] * k_terms
            c1[r, :] = [
                (-pow(3, -1, a)) % a * int(rb.Ai_inv[i]) % a
                for i, a in enumerate(rb.A_primes)
            ]
            n_bmr[r, :k] = [3 % b for b in rb.B_primes]
            n_bmr[r, k] = 3 % rb.m_r
        a2n.append(pow(rb.A, 2, moduli[r]))

    base_limbs = ints_to_limbs(
        [bases_rows[r][t] % moduli[r] for t in order for r in range(rows)],
        num_limbs,
    ).reshape(k_terms, rows, num_limbs)
    exp_limbs = ints_to_limbs(
        [exps_rows[r][t] for t in order for r in range(rows)], el
    ).reshape(k_terms, rows, el)
    args = (
        jnp.asarray(base_limbs),
        jnp.asarray(exp_limbs),
        jnp.asarray(ints_to_limbs(a2n, num_limbs)),
        jnp.asarray(c1),
        jnp.asarray(n_bmr),
        _prep_consts(rb),
    )
    pmode = _pallas_mode()
    if mesh is not None and rows % int(mesh.devices.size) == 0:
        from ..parallel.shard_kernels import sharded_rns_multi_modexp_fn

        out_res = sharded_rns_multi_modexp_fn(mesh, eb, k, pmode)(*args)
    else:
        out_res = _rns_multi_modexp_kernel(
            *args, exp_bits_seq=eb, k=k, pallas_mode=pmode
        )
    ec = rb.exit_consts
    v_limbs = _crt_exit_kernel(out_res, *ec[:-1], k=k, lv=ec[-1])
    vs = limbs_to_ints(np.asarray(v_limbs))
    wipe_array(exp_limbs, base_limbs)
    out = []
    for r in range(rows):
        if r in fallback_rows:
            out.append(fallback_rows[r])
        else:
            out.append(vs[r] % moduli[r])
    return out


def rns_modexp(
    bases_int: Sequence[int],
    exps: Sequence[int],
    moduli: Sequence[int],
    value_bits: int,
    mesh=None,
) -> List[int]:
    """bases^exps mod moduli row-wise through the RNS/MXU pipeline."""
    if not bases_int:
        return []
    rows = len(bases_int)
    num_limbs = -(-value_bits // LIMB_BITS)
    rb = rns_bases_for_bits(value_bits, num_limbs)
    k = rb.k

    exp_bits = bucket_exp_bits(exps)
    el = -(-exp_bits // LIMB_BITS)

    # per-row host precomputes (cheap bigint work). A modulus sharing a
    # factor with a channel prime cannot ride the RNS pipeline (real
    # Paillier/ring-Pedersen moduli are products of large primes, but a
    # malicious party could craft one): those rows fall back to host pow
    # and the row is neutralized in the launch.
    a2n = [pow(rb.A, 2, n) for n in moduli]
    c1 = np.zeros((rows, k), np.uint32)
    n_bmr = np.zeros((rows, k + 1), np.uint32)
    fallback_rows = {}
    moduli = list(moduli)
    bases_int = list(bases_int)
    exps = list(exps)
    for r, n in enumerate(moduli):
        try:
            for i, a in enumerate(rb.A_primes):
                c1[r, i] = (-pow(n, -1, a)) % a * int(rb.Ai_inv[i]) % a
            for j, b in enumerate(rb.B_primes):
                n_bmr[r, j] = n % b
            n_bmr[r, k] = n % rb.m_r
        except ValueError:  # gcd(n, a_i) > 1: only the A channels need n invertible
            fallback_rows[r] = pow(bases_int[r] % n, exps[r], n)
            moduli[r], bases_int[r], exps[r] = 3, 1, 0
            a2n[r] = pow(rb.A, 2, 3)
            c1[r, :] = [
                (-pow(3, -1, a)) % a * int(rb.Ai_inv[i]) % a
                for i, a in enumerate(rb.A_primes)
            ]
            n_bmr[r, :k] = [3 % b for b in rb.B_primes]
            n_bmr[r, k] = 3 % rb.m_r

    base_limbs = ints_to_limbs(
        [b % n for b, n in zip(bases_int, moduli)], num_limbs
    )
    exp_limbs = ints_to_limbs(list(exps), el)
    args = (
        jnp.asarray(base_limbs),
        jnp.asarray(exp_limbs),
        jnp.asarray(ints_to_limbs(a2n, num_limbs)),
        jnp.asarray(c1),
        jnp.asarray(n_bmr),
        _prep_consts(rb),
    )
    pmode = _pallas_mode()
    if mesh is not None and rows % int(mesh.devices.size) == 0:
        from ..parallel.shard_kernels import sharded_rns_modexp_fn

        out_res = sharded_rns_modexp_fn(mesh, exp_bits, k, pmode)(*args)
    elif pmode:
        out_res = _rns_modexp_full_pallas(
            *args, exp_bits=exp_bits, k=k, interpret=pmode == 2
        )
    else:
        out_res = _rns_modexp_kernel(*args, exp_bits=exp_bits, k=k)
    # device CRT exit: canonical limbs of the exact value, host only does
    # limbs->int and one reduction mod N per row
    ec = rb.exit_consts
    v_limbs = _crt_exit_kernel(out_res, *ec[:-1], k=k, lv=ec[-1])
    vs = limbs_to_ints(np.asarray(v_limbs))
    wipe_array(exp_limbs, base_limbs)  # secret exponents/bases; vs is out
    out = []
    for r in range(rows):
        if r in fallback_rows:
            out.append(fallback_rows[r])
        else:
            out.append(vs[r] % moduli[r])
    return out
