"""TPU numeric layer (SURVEY.md §7 steps 1, 4-5).

Big integers become fixed-shape limb tensors: 16-bit digits held in uint32
lanes, so a single digit product (< 2^32) and long runs of lazy-carry
accumulation both stay inside native TPU integer arithmetic. Everything is
structure-of-arrays over a proof batch, and every batch is *multi-modulus*
— each row carries its own modulus (each receiver has a different
N / N^2 / N-tilde), which is the defining feature of the collect()
workload (SURVEY.md §7 hard part 1).

Modules:
- limbs: int <-> limb-tensor conversion, Montgomery constants
- montgomery: batched CIOS Montgomery multiplication + windowless modexp
  (JAX/XLA; the Pallas kernel variant lives in pallas_montmul)
- ec_batch: batched secp256k1 over 16-bit limb field elements
"""

from . import limbs, montgomery

__all__ = ["limbs", "montgomery"]
