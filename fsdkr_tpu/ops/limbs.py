"""Limb-tensor representation of big integers.

Base 2^16 digits in uint32 lanes: a b-bit integer is ceil(b/16) limbs,
little-endian along the last axis. The choice of 16-bit digits makes a
digit product fit uint32 exactly ((2^16-1)^2 < 2^32) and leaves ~2^15
headroom for lazy-carry accumulation across a 2048/4096-bit CIOS pass
(SURVEY.md §7 step 1).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

LIMB_BITS = 16
LIMB_MASK = (1 << LIMB_BITS) - 1

__all__ = [
    "LIMB_BITS",
    "LIMB_MASK",
    "WINDOW_BITS",
    "limbs_for_bits",
    "bucket_exp_bits",
    "ints_to_limbs",
    "limbs_to_ints",
    "wipe_array",
    "MontgomeryContext",
]

WINDOW_BITS = 4  # fixed-window width of the modexp kernels

# Below this many limbs the numpy passes win (ctypes call overhead);
# above it the native threaded widen/narrow passes (csrc/fsdkr_native
# fsdkr_limbs_widen_u16 / fsdkr_limbs_narrow_u16) take over, so tile
# staging scales with the FSDKR_THREADS row pool.
_NATIVE_STAGE_MIN_LIMBS = 4096

# cumulative bytes-staged telemetry (ISSUE 10, fsdkr_mem_* family): one
# counter bump per ENCODE CALL (a whole batch column), not per row — the
# memory plan's bytes-staged accounting rides the actual staging path.
# Cached child; telemetry is dependency-free (no jax, no native).
_STAGED_COUNTER = None


def _count_staged(nbytes: int) -> None:
    global _STAGED_COUNTER
    if _STAGED_COUNTER is None:
        from ..telemetry import registry

        _STAGED_COUNTER = registry.counter(
            "fsdkr_mem_bytes_staged",
            "cumulative bytes staged through the limb encoder",
        )
    _STAGED_COUNTER.inc(nbytes)

# Exponent-width ladder: modexp wall-clock is proportional to the bucketed
# width (sequential window loop), so the ladder is finer than powers of two
# where the protocol's exponent sizes actually fall (q*Ntilde ~ 2304 bits,
# q^3*Ntilde ~ 2816 bits for 2048-bit moduli). All entries are multiples of
# the window width; the compiled-variant count per batch shape stays bounded.
_EXP_BUCKETS = (
    64, 128, 256, 512, 768, 1024, 1536, 2048, 2560, 3072, 4096,
    5120, 6144, 8192, 12288, 16384,
)


def bucket_exp_bits(exps) -> int:
    """Exponent width for a batch: the max bit length rounded up the
    bucket ladder. Guarantees the multiple-of-window width the kernels
    require and caps compiled variants per batch shape. Pure host math —
    deliberately jax-free for the host-backend prover path."""
    bits = max((e.bit_length() for e in exps), default=1) or 1
    for b in _EXP_BUCKETS:
        if bits <= b:
            return b
    return -(-bits // WINDOW_BITS) * WINDOW_BITS


def limbs_for_bits(bits: int) -> int:
    return -(-bits // LIMB_BITS)


def ints_to_limbs(xs: Sequence[int], num_limbs: int) -> np.ndarray:
    """(B,) Python ints -> (B, num_limbs) uint32 little-endian base-2^16.

    Via to_bytes + frombuffer: CPython serializes in C, so the host-side
    conversion cost is O(bytes) rather than a Python-level shift loop.

    The staging bytearray is wiped in place before returning (astype
    copies out of it), so the returned array is the ONLY host copy — call
    wipe_array on it after device upload when the values are secret
    (exponents, shares, nonces); see SECURITY.md.
    """
    nbytes = num_limbs * (LIMB_BITS // 8)
    _count_staged(len(xs) * nbytes * 3)  # u16 staging + the u32 copy
    buf = bytearray(len(xs) * nbytes)
    for row, x in enumerate(xs):
        if x < 0:
            raise ValueError("limb encoding takes non-negative integers")
        try:
            buf[row * nbytes : (row + 1) * nbytes] = x.to_bytes(nbytes, "little")
        except OverflowError:
            raise ValueError(
                f"integer of {x.bit_length()} bits exceeds {num_limbs} limbs"
            ) from None
    arr16 = np.frombuffer(buf, dtype="<u2").reshape(len(xs), num_limbs)
    out = None
    if arr16.size >= _NATIVE_STAGE_MIN_LIMBS:
        try:
            from .. import native

            out = native.widen_limbs(arr16)  # threaded u16 -> u32 pass
        except Exception:
            out = None
    if out is None:
        out = arr16.astype(np.uint32)
    buf[:] = bytes(len(buf))  # wipe staging bytes (out never aliases buf)
    return out


def wipe_array(*arrays) -> None:
    """Zero numpy staging arrays that held secret limb material, once the
    device computation consuming them has materialized its results (jax
    may alias host numpy buffers on the CPU backend, so wiping is only
    safe after the dependent outputs exist). No-op for None entries."""
    for a in arrays:
        if a is not None and isinstance(a, np.ndarray) and a.flags.writeable:
            a.fill(0)


def limbs_to_ints(arr) -> List[int]:
    """(B, K) limb array -> list of Python ints."""
    a = np.asarray(arr)
    if a.ndim != 2:
        raise ValueError("expected a (B, K) limb array")
    raw = None
    if a.size >= _NATIVE_STAGE_MIN_LIMBS:
        try:
            from .. import native

            # one threaded pass fusing the canonicality check with the
            # narrow (raises ValueError itself on pending carries)
            a16 = native.narrow_limbs(a)
        except ValueError:
            raise
        except Exception:
            a16 = None
        if a16 is not None:
            raw = a16.astype("<u2", copy=False).tobytes()
    if raw is None:
        if (a >> LIMB_BITS).any():
            raise ValueError("limb array not canonical (pending carries)")
        raw = a.astype("<u2").tobytes()
    nbytes = a.shape[1] * (LIMB_BITS // 8)
    return [
        int.from_bytes(raw[i * nbytes : (i + 1) * nbytes], "little")
        for i in range(a.shape[0])
    ]


class MontgomeryContext:
    """Per-batch-row Montgomery constants for a multi-modulus batch.

    For each (odd) modulus N_i with R = 2^(16*K):
      n_prime_i = -N_i^{-1} mod 2^16   (digit-level CIOS constant)
      r2_i      = R^2 mod N_i          (to-Montgomery conversion factor)
      one_i     = R mod N_i            (Montgomery representation of 1)
    """

    def __init__(self, moduli: Sequence[int], num_limbs: int):
        for n in moduli:
            if n % 2 == 0 or n <= 1:
                raise ValueError("Montgomery arithmetic requires odd moduli > 1")
            if n.bit_length() > num_limbs * LIMB_BITS:
                raise ValueError("modulus wider than limb layout")
        self.num_limbs = num_limbs
        self.moduli = list(moduli)
        r = 1 << (LIMB_BITS * num_limbs)
        self.n = ints_to_limbs(moduli, num_limbs)
        self.n_prime = np.array(
            [(-pow(n, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS) for n in moduli],
            dtype=np.uint32,
        )
        self.r2 = ints_to_limbs([r * r % n for n in moduli], num_limbs)
        self.one_mont = ints_to_limbs([r % n for n in moduli], num_limbs)
