"""Batched secp256k1 point arithmetic on the accelerator.

The reference does all EC work serially through `curv` (point muls in the
PDL verify `/root/reference/src/zk_pdl_with_slack.rs:124-127`, Feldman
share validation `src/refresh_message.rs:177-188`, pk_vec rebuild
:455-464). Here the O(n^2) EC checks of collect() become a handful of
batched multi-scalar multiplications.

Design (SURVEY.md §7 step 4, hard part 2 — branchless batched EC):

- Field: F_p for p = 2^256 - 2^32 - 977, as 16 x 16-bit limbs in uint32
  lanes, multiplied with the same Montgomery CIOS kernel the big-modexp
  path uses (`fsdkr_tpu.ops.montgomery.mont_mul_limbs` with the modulus
  row broadcast to p). All field elements on device live in the
  Montgomery domain (x*R mod p, R = 2^256).
- Points: homogeneous projective (X : Y : Z), identity (0 : 1 : 0), with
  the *complete* addition law of Renes-Costello-Batina 2016 (Alg. 7,
  a = 0): one formula valid for add, double, identity, and inverses —
  no data-dependent control flow anywhere, so the whole point op vmaps
  and shards like any dense kernel.
- Scalar mul: MSB-first double-and-always-add over a fixed bit width
  (256 for group-order scalars, 128 for random-linear-combination
  coefficients); the "add nothing" case multiplies by the identity,
  which the complete formula handles for free.
- MSM: one batched scalar-mul launch over all rows, then a log-depth
  tree of complete adds within each group (groups padded to a power of
  two with identity points).

The host oracle for all of this is `fsdkr_tpu.core.secp256k1`.
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.secp256k1 import N as CURVE_ORDER
from ..core.secp256k1 import P as FIELD_P
from ..core.secp256k1 import Point
from .limbs import LIMB_BITS, LIMB_MASK, ints_to_limbs, limbs_to_ints, wipe_array
from .montgomery import _cond_subtract, _normalize_carries, mont_mul_limbs

__all__ = ["batch_scalar_mul", "batch_msm", "points_to_device", "device_to_points"]

_U32 = jnp.uint32
_K = 16  # 256 bits / 16-bit limbs
_R = 1 << 256
_R_INV = pow(_R, -1, FIELD_P)

# Montgomery constants for the fixed field prime
_P_LIMBS = np.asarray(ints_to_limbs([FIELD_P], _K)[0])
_N_PRIME = np.uint32((-pow(FIELD_P, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS))
_ONE_M = np.asarray(ints_to_limbs([_R % FIELD_P], _K)[0])  # 1 in Montgomery form
_B3_M = np.asarray(ints_to_limbs([21 * _R % FIELD_P], _K)[0])  # 3*b = 21


def _bcast(const_row, b):
    return jnp.broadcast_to(jnp.asarray(const_row)[None, :], (b, _K))


def _fmul(x, y):
    b = x.shape[0]
    return mont_mul_limbs(
        x, y, _bcast(_P_LIMBS, b), jnp.full((b,), _N_PRIME, _U32)
    )


def _fadd(x, y):
    t = _normalize_carries(
        jnp.concatenate([x + y, jnp.zeros((x.shape[0], 1), _U32)], axis=1)
    )
    return _cond_subtract(t, _bcast(_P_LIMBS, x.shape[0]))


def _fsub(x, y):
    # x - y mod p as (x + p) - y: the minuend is >= y, one borrow scan,
    # then a conditional subtract brings the result back under p.
    b = x.shape[0]
    s = _normalize_carries(
        jnp.concatenate(
            [x + _bcast(_P_LIMBS, b), jnp.zeros((b, 1), _U32)], axis=1
        )
    )  # (B, 17) canonical
    y_pad = jnp.concatenate([y, jnp.zeros((b, 1), _U32)], axis=1)

    def step(borrow, limbs):
        s_j, y_j = limbs
        d = s_j + (jnp.uint32(1) << LIMB_BITS) - y_j - borrow
        return jnp.uint32(1) - (d >> LIMB_BITS), d & LIMB_MASK

    _, diff_t = lax.scan(step, jnp.zeros((b,), _U32), (s.T, y_pad.T))
    return _cond_subtract(diff_t.T, _bcast(_P_LIMBS, b))


def _padd(p1, p2):
    """Complete projective addition, Renes-Costello-Batina Alg. 7 (a=0,
    b3 = 21). p1, p2: (B, 3, K) Montgomery-domain (X : Y : Z)."""
    x1, y1, z1 = p1[:, 0], p1[:, 1], p1[:, 2]
    x2, y2, z2 = p2[:, 0], p2[:, 1], p2[:, 2]
    b = x1.shape[0]
    b3 = _bcast(_B3_M, b)

    t0 = _fmul(x1, x2)
    t1 = _fmul(y1, y2)
    t2 = _fmul(z1, z2)
    t3 = _fmul(_fadd(x1, y1), _fadd(x2, y2))
    t3 = _fsub(t3, _fadd(t0, t1))
    t4 = _fmul(_fadd(y1, z1), _fadd(y2, z2))
    t4 = _fsub(t4, _fadd(t1, t2))
    x3 = _fmul(_fadd(x1, z1), _fadd(x2, z2))
    y3 = _fsub(x3, _fadd(t0, t2))
    x3 = _fadd(_fadd(t0, t0), t0)
    t2 = _fmul(b3, t2)
    z3 = _fadd(t1, t2)
    t1 = _fsub(t1, t2)
    y3 = _fmul(b3, y3)
    out_x = _fsub(_fmul(t3, t1), _fmul(t4, y3))
    out_y = _fadd(_fmul(y3, x3), _fmul(t1, z3))
    out_z = _fadd(_fmul(z3, t4), _fmul(x3, t3))
    return jnp.stack([out_x, out_y, out_z], axis=1)


def _identity_rows(b):
    pt = jnp.zeros((b, 3, _K), _U32)
    return pt.at[:, 1, :].set(_bcast(_ONE_M, b))


_EC_WINDOW = 4


@partial(jax.jit, static_argnames=("scalar_bits",))
def _scalar_mul_kernel(points, scalars, *, scalar_bits):
    """points: (B, 3, K); scalars: (B, SL) limbs. MSB-first 4-bit fixed
    windows: a 16-entry multiples table (15 sequential adds), then per
    window 4 doublings and one branchless table add — ~335 complete
    additions for 256-bit scalars vs 512 for bit-at-a-time. The w=0 entry
    is the identity (absorbed by the complete formula), so every window
    costs the same."""
    assert scalar_bits % _EC_WINDOW == 0
    b = points.shape[0]
    ident = _identity_rows(b)

    def build(j, table):
        table = table.at[j].set(_padd(table[j - 1], points))
        return table

    table0 = jnp.zeros((1 << _EC_WINDOW, b, 3, _K), _U32)
    table0 = table0.at[0].set(ident).at[1].set(points)
    table = lax.fori_loop(2, 1 << _EC_WINDOW, build, table0)

    idx = jnp.arange(1 << _EC_WINDOW, dtype=_U32)[:, None, None, None]

    def step(wi, acc):
        shift = scalar_bits - _EC_WINDOW * (wi + 1)
        limb = lax.dynamic_index_in_dim(
            scalars, shift // LIMB_BITS, axis=1, keepdims=False
        )
        w = (limb >> (shift % LIMB_BITS)) & ((1 << _EC_WINDOW) - 1)  # (B,)
        for _ in range(_EC_WINDOW):
            acc = _padd(acc, acc)
        sel = jnp.sum(
            jnp.where(w[None, :, None, None] == idx, table, jnp.uint32(0)),
            axis=0,
        )
        return _padd(acc, sel)

    return lax.fori_loop(0, scalar_bits // _EC_WINDOW, step, ident)


@jax.jit
def _tree_sum_kernel(points):
    """points: (G, M, 3, K), M a power of two -> (G, 3, K) group sums via
    log2(M) levels of complete adds."""
    g, m = points.shape[0], points.shape[1]
    flat = points
    while m > 1:
        m //= 2
        lhs = flat[:, :m].reshape(g * m, 3, _K)
        rhs = flat[:, m:].reshape(g * m, 3, _K)
        flat = _padd(lhs, rhs).reshape(g, m, 3, _K)
    return flat[:, 0]


# ---------------------------------------------------------------------------
# host <-> device conversion


def points_to_device(points: Sequence[Point]) -> jnp.ndarray:
    """Affine host points -> (B, 3, K) Montgomery-domain projective."""
    xs, ys, zs = [], [], []
    for pt in points:
        if pt.infinity:
            xs.append(0)
            ys.append(_R % FIELD_P)
            zs.append(0)
        else:
            xs.append(pt.x * _R % FIELD_P)
            ys.append(pt.y * _R % FIELD_P)
            zs.append(_R % FIELD_P)
    arr = ints_to_limbs(xs + ys + zs, _K).reshape(3, len(points), _K)
    return jnp.asarray(arr.transpose(1, 0, 2))


def device_to_points(arr) -> List[Point]:
    """(B, 3, K) Montgomery-domain projective -> affine host points.

    Z inverses use Montgomery's batch-inversion chain (one pow(-1) for
    the whole batch): per-row CPython inversion costs ~0.5 ms, which at
    the n=256 protocol scale (65k points per launch) would be ~30 s of
    serial host work; the chain is 3B cheap 256-bit multiplications."""
    a = np.asarray(arr)
    b = a.shape[0]
    flat = limbs_to_ints(a.reshape(b * 3, _K))
    zs = [flat[3 * i + 2] * _R_INV % FIELD_P for i in range(b)]
    # prefix-product chain, skipping identity rows (z == 0)
    prefix = [1] * (b + 1)
    for i, z in enumerate(zs):
        prefix[i + 1] = prefix[i] * (z or 1) % FIELD_P
    acc = pow(prefix[b], -1, FIELD_P)
    zinvs = [0] * b
    for i in range(b - 1, -1, -1):
        zinvs[i] = prefix[i] * acc % FIELD_P
        acc = acc * (zs[i] or 1) % FIELD_P
    out = []
    for i in range(b):
        if zs[i] == 0:
            out.append(Point.identity())
        else:
            x = flat[3 * i] * _R_INV % FIELD_P
            y = flat[3 * i + 1] * _R_INV % FIELD_P
            out.append(Point(x * zinvs[i] % FIELD_P, y * zinvs[i] % FIELD_P))
    return out


def _scalars_to_limbs(scalars: Sequence[int], scalar_bits: int) -> np.ndarray:
    """Returns the NUMPY staging array (not a device array): callers
    upload it via jnp.asarray and wipe it with wipe_array once the
    dependent results have materialized — EC scalars are key shares and
    prover nonces (SECURITY.md)."""
    sl = -(-scalar_bits // LIMB_BITS)
    return ints_to_limbs([s % CURVE_ORDER for s in scalars], sl)


# ---------------------------------------------------------------------------
# public batch entry points


def _pad_pow2(rows: int, floor: int = 8) -> int:
    return max(floor, 1 << (rows - 1).bit_length())


def batch_scalar_mul(
    points: Sequence[Point], scalars: Sequence[int], scalar_bits: int = 256
) -> List[Point]:
    """Row-wise scalar * point, one launch. Scalars are reduced mod the
    group order; scalar_bits picks the kernel depth (128 suffices for
    random-linear-combination coefficients)."""
    if not points:
        return []
    rows = len(points)
    pad = _pad_pow2(rows) - rows
    pts = list(points) + [Point.identity()] * pad
    scs = [s % CURVE_ORDER for s in scalars] + [0] * pad
    sc_limbs = _scalars_to_limbs(scs, scalar_bits)
    out = _scalar_mul_kernel(
        points_to_device(pts),
        jnp.asarray(sc_limbs),
        scalar_bits=scalar_bits,
    )
    res = device_to_points(out)[:rows]  # materializes the kernel output
    wipe_array(sc_limbs)
    return res


def batch_generator_mul(scalars: Sequence[int]) -> List[Point]:
    """s_i * G row-wise, one launch — the prover's per-receiver point
    fan-out (S_i = sigma_i * G, reference refresh_message.rs:67-69) and
    the PDL prover's u1 column, batched instead of ~2 ms/row host
    ladders."""
    from ..core.secp256k1 import GENERATOR

    return batch_scalar_mul([GENERATOR] * len(scalars), scalars)


def batch_msm(
    groups_points: Sequence[Sequence[Point]],
    groups_scalars: Sequence[Sequence[int]],
    scalar_bits: int = 256,
) -> List[Point]:
    """Per-group multi-scalar multiplication: sum_i s_i * P_i for each
    group, as ONE scalar-mul launch over all rows plus a log-depth
    in-group tree sum. Groups are padded to a common power-of-two size
    with identity points."""
    if not groups_points:
        return []
    g = len(groups_points)
    m_max = max(len(p) for p in groups_points)
    m_pad = _pad_pow2(max(1, m_max), floor=1)

    pts: List[Point] = []
    scs: List[int] = []
    for gp, gs in zip(groups_points, groups_scalars):
        if len(gp) != len(gs):
            raise ValueError(
                f"group length mismatch: {len(gp)} points, {len(gs)} scalars"
            )
        pts.extend(list(gp) + [Point.identity()] * (m_pad - len(gp)))
        scs.extend([s % CURVE_ORDER for s in gs] + [0] * (m_pad - len(gs)))

    sc_limbs = _scalars_to_limbs(scs, scalar_bits)
    prods = _scalar_mul_kernel(
        points_to_device(pts),
        jnp.asarray(sc_limbs),
        scalar_bits=scalar_bits,
    )
    sums = _tree_sum_kernel(prods.reshape(g, m_pad, 3, _K))
    res = device_to_points(sums)  # materializes the kernel output
    wipe_array(sc_limbs)
    return res
