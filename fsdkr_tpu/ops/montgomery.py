"""Batched multi-modulus Montgomery arithmetic in JAX (SURVEY.md §7 step 1,
hard part 1).

The workhorse of the TPU rebuild: the reference's O(n^2) serial
`BigInt::mod_pow` calls (e.g. `/root/reference/src/range_proofs.rs:129-148`,
`src/ring_pedersen_proof.rs:144`) become one batched modexp launch per
proof-family equation. Each batch row carries its own modulus.

Algorithm: CIOS (coarsely integrated operand scanning) over base-2^16
digits in uint32 lanes, with lazy carries — per outer step each
accumulator limb gains at most 4*(2^16-1) < 2^18, so across K <= 256 steps
values stay < 2^26 << 2^32 and no per-step normalization is needed. The
digit-product trick (lo/hi 16-bit split) keeps everything in native 32-bit
TPU integer ops; there is no data-dependent control flow anywhere
(exponent bits select between squared and multiplied values branchlessly),
so the whole modexp jits to a single XLA loop nest and vmaps/shards
cleanly.

Exponentiation is MSB-first fixed-window (4-bit): per window, 4
Montgomery squarings and one branchless 16-entry table multiply —
~1.27 Montgomery multiplications per exponent bit, constant shape.
Exponent widths are bucketed up a fixed ladder of multiples of 4 (see
`bucket_exp_bits`), which keeps the sequential depth close to the true
exponent width while capping the number of compiled kernel variants.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .limbs import (
    LIMB_BITS,
    LIMB_MASK,
    WINDOW_BITS,
    MontgomeryContext,
    bucket_exp_bits,
    ints_to_limbs,
    limbs_to_ints,
    wipe_array,
)

__all__ = [
    "mont_mul_limbs",
    "batch_modexp",
    "batch_modmul",
    "bucket_exp_bits",
    "BatchModExp",
    "shared_base_modexp",
    "shared_exp_modexp",
    "multi_modexp",
]


_U32 = jnp.uint32


def _normalize_carries(t):
    """Fully propagate pending carries: limbs -> canonical base-2^16.
    Runs until fixpoint (data-dependent trip count, but each pass is a
    fixed-shape vector op; 3-4 passes in practice)."""

    def cond(t):
        return jnp.any(t >> LIMB_BITS)

    def body(t):
        lo = t & LIMB_MASK
        hi = t >> LIMB_BITS
        hi_shift = jnp.concatenate(
            [jnp.zeros_like(hi[:, :1]), hi[:, :-1]], axis=1
        )
        return lo + hi_shift

    return lax.while_loop(cond, body, t)


def _cond_subtract(t, n):
    """Return t - n if t >= n else t, limbwise with a borrow scan.
    t: (B, K+1) canonical limbs (value < 2n); n: (B, K)."""
    b, k = n.shape
    n_pad = jnp.concatenate([n, jnp.zeros((b, 1), _U32)], axis=1)

    def step(borrow, limbs):
        t_j, n_j = limbs
        d = t_j + (jnp.uint32(1) << LIMB_BITS) - n_j - borrow
        new_borrow = jnp.uint32(1) - (d >> LIMB_BITS)
        return new_borrow, d & LIMB_MASK

    borrow, diff_t = lax.scan(
        step, jnp.zeros((b,), _U32), (t.T, n_pad.T)
    )
    diff = diff_t.T
    keep = (borrow != 0)[:, None]  # borrow => t < n => keep t
    return jnp.where(keep, t, diff)[:, :k]


def mont_mul_limbs(x, y, n, n_prime):
    """Batched Montgomery product x*y*R^{-1} mod n.

    x, y, n: (B, K) canonical base-2^16 limbs, x,y < n; n_prime: (B,).
    Returns canonical (B, K) limbs < n.
    """
    b, k = x.shape
    t = jnp.zeros((b, k + 2), _U32)

    def step(i, t):
        x_i = lax.dynamic_index_in_dim(x, i, axis=1, keepdims=False)  # (B,)
        p = x_i[:, None] * y  # digit products fit uint32 exactly
        p_lo = p & LIMB_MASK
        p_hi = p >> LIMB_BITS
        m = ((t[:, 0] + p_lo[:, 0]) * n_prime) & LIMB_MASK
        pm = m[:, None] * n
        pm_lo = pm & LIMB_MASK
        pm_hi = pm >> LIMB_BITS
        t = t.at[:, :k].add(p_lo + pm_lo)
        t = t.at[:, 1 : k + 1].add(p_hi + pm_hi)
        # low limb is now 0 mod 2^16: divide by 2^16 (shift one limb down)
        carry0 = t[:, 0] >> LIMB_BITS
        t = jnp.concatenate([t[:, 1:], jnp.zeros((b, 1), _U32)], axis=1)
        t = t.at[:, 0].add(carry0)
        return t

    t = lax.fori_loop(0, k, step, t)
    t = _normalize_carries(t)
    return _cond_subtract(t[:, : k + 1], n)


_WINDOW = WINDOW_BITS  # 4-bit fixed windows: 4 squarings + 1 table multiply


@partial(jax.jit, static_argnames=("exp_bits",))
def _modexp_kernel(base, exp, n, n_prime, r2, one_mont, *, exp_bits):
    """result = base^exp mod n, per row. exp: (B, EL) limbs.

    Fixed-window exponentiation, MSB-first: per 4-bit window, 4 Montgomery
    squarings and one branchless table multiply (the w=0 entry is the
    Montgomery one, so every window costs the same — no data-dependent
    control flow). exp_bits must be a multiple of 4 — guaranteed by
    `bucket_exp_bits` at every call site — so window shifts are 4-aligned
    and a window never straddles a 16-bit exponent limb.
    """
    assert exp_bits % _WINDOW == 0
    base_m = mont_mul_limbs(base, r2, n, n_prime)  # to Montgomery domain

    # table[j] = base_m^j (Montgomery domain), j = 0..15
    def build(j, table):
        prev = table[j - 1]
        table = table.at[j].set(mont_mul_limbs(prev, base_m, n, n_prime))
        return table

    table0 = jnp.zeros((1 << _WINDOW,) + base.shape, _U32)
    table0 = table0.at[0].set(one_mont).at[1].set(base_m)
    table = lax.fori_loop(2, 1 << _WINDOW, build, table0)

    idx = jnp.arange(1 << _WINDOW, dtype=_U32)[:, None, None]

    def step(wi, acc):
        shift = exp_bits - _WINDOW * (wi + 1)
        limb = lax.dynamic_index_in_dim(
            exp, shift // LIMB_BITS, axis=1, keepdims=False
        )
        w = (limb >> (shift % LIMB_BITS)) & ((1 << _WINDOW) - 1)  # (B,)
        for _ in range(_WINDOW):
            acc = mont_mul_limbs(acc, acc, n, n_prime)
        # branchless table select: sum over one-hot window match
        sel = jnp.sum(
            jnp.where(w[None, :, None] == idx, table, jnp.uint32(0)), axis=0
        )
        return mont_mul_limbs(acc, sel, n, n_prime)

    acc = lax.fori_loop(0, exp_bits // _WINDOW, step, one_mont)
    # leave Montgomery domain: multiply by 1
    one = jnp.zeros_like(acc).at[:, 0].set(1)
    return mont_mul_limbs(acc, one, n, n_prime)


@partial(jax.jit, static_argnames=("n_windows",))
def _shared_exp_kernel(base, digits, n, n_prime, r2, one_mont, *, n_windows):
    """result[b] = base[b]^E mod n[b] for ONE shared exponent E whose
    4-bit window digits (MSB-first) arrive as a dynamic i32 vector — the
    Alice-range s^n column shape (FSDKR_RANGEOPT): every row of a
    receiver's column raises a different base to the receiver's PUBLIC
    Paillier modulus n, so the whole batch replays one square-and-
    multiply schedule as per-step row-parallel Montgomery muls over the
    rows x limbs tensors.

    Against the generic `_modexp_kernel` this drops the per-row (B, EL)
    exponent tensor and its per-row one-hot digit compare: the digit is
    ONE traced scalar per window, so the table select is a single
    dynamic index shared by every row. Digits are DYNAMIC inputs (the
    schedule is data, not shape): committees with different moduli reuse
    one compiled kernel per (rows, limbs, n_windows) bucket. The digit
    schedule derives from the public modulus only — no per-row wire data
    enters it (SECURITY.md "Range-opt verifier engines").
    """
    base_m = mont_mul_limbs(base, r2, n, n_prime)

    def build(j, table):
        prev = table[j - 1]
        table = table.at[j].set(mont_mul_limbs(prev, base_m, n, n_prime))
        return table

    table0 = jnp.zeros((1 << _WINDOW,) + base.shape, _U32)
    table0 = table0.at[0].set(one_mont).at[1].set(base_m)
    table = lax.fori_loop(2, 1 << _WINDOW, build, table0)

    def step(wi, acc):
        for _ in range(_WINDOW):
            acc = mont_mul_limbs(acc, acc, n, n_prime)
        sel = lax.dynamic_index_in_dim(table, digits[wi], axis=0,
                                       keepdims=False)
        return mont_mul_limbs(acc, sel, n, n_prime)

    acc = lax.fori_loop(0, n_windows, step, one_mont)
    one = jnp.zeros_like(acc).at[:, 0].set(1)
    return mont_mul_limbs(acc, one, n, n_prime)


def shared_exp_modexp(
    bases: Sequence[int],
    exp: int,
    modulus: int,
    num_limbs: int,
    ctx=None,
    mesh=None,
) -> List[int]:
    """bases[r]^exp mod modulus through the shared-exponent device
    kernel: one shared PUBLIC exponent/modulus, per-row bases. The window
    schedule (4-bit digits, MSB-first) is computed on the host from the
    shared exponent and shipped as a dynamic vector. Mesh sharding rides
    the caller's generic fallback (backend.powm routes mesh launches to
    the per-row kernel), so this entry is single-device."""
    rows = len(bases)
    if rows == 0:
        return []
    if exp < 0:
        raise ValueError("shared_exp_modexp: exponent must be non-negative")
    exp_bits = bucket_exp_bits([exp])
    n_windows = exp_bits // _WINDOW
    digits = np.zeros((max(1, n_windows),), dtype=np.int32)
    for w in range(n_windows):
        shift = exp_bits - _WINDOW * (w + 1)
        digits[w] = (exp >> shift) & ((1 << _WINDOW) - 1)
    if ctx is None:
        ctx = BatchModExp([modulus] * rows, num_limbs)
    base_limbs = ints_to_limbs([b % modulus for b in bases], num_limbs)
    out = _shared_exp_kernel(
        jnp.asarray(base_limbs),
        jnp.asarray(digits),
        ctx._n,
        ctx._n_prime,
        ctx._r2,
        ctx._one_mont,
        n_windows=n_windows,
    )
    res = limbs_to_ints(np.asarray(out))
    wipe_array(base_limbs)
    return res


def _comb_tree_chunk(
    w_cnt: int, rows: int, width: int, table_rows: int = 0
) -> int:
    """Tree-accumulation chunk size (windows per chunk, power of two).

    A comb row's W window products are independent, so a chunk of C
    windows' selected entries can tree-reduce in log2(C) MontMul levels
    instead of C sequential table multiplies — the depth reduction that
    matters when small committees leave the chip latency-bound (total
    multiply count is unchanged, so saturated batches are unaffected).
    C is capped so the materialized (C, rows, width) selection stays
    within an element budget (FSDKR_COMB_TREE_BUDGET, default 2^24 u32
    lanes ~ 64 MB); FSDKR_COMB_TREE=0 disables chunking (C=1 == the
    sequential ladder).
    """
    import os

    if os.environ.get("FSDKR_COMB_TREE", "1") in ("", "0"):
        return 1
    budget = int(os.environ.get("FSDKR_COMB_TREE_BUDGET", str(1 << 24)))
    c = budget // max(1, rows * width)
    if table_rows:  # fly-built tables: 16 entries per window-group row
        c = min(c, budget // max(1, 16 * table_rows * width))
    if c < 2:
        return 1
    c = 1 << (c.bit_length() - 1)
    w_pow2 = 1 << ((w_cnt - 1).bit_length())
    return min(c, w_pow2)


@partial(jax.jit, static_argnames=("exp_bits", "tree_chunk"))
def _shared_modexp_kernel(base, exp, n, n_prime, r2, one_mont, powers=None, *, exp_bits, tree_chunk=1):
    """result[g, m] = base[g]^exp[g, m] mod n[g] — fixed-base comb.

    The O(n^2) verification loop has whole columns whose rows share one
    (base, modulus) pair: every ring-Pedersen row of a message shares
    (T, N) (`/root/reference/src/ring_pedersen_proof.rs:144`), and the n
    PDL/range rows addressed to one receiver share that receiver's
    (h1|h2, N~) (`src/zk_pdl_with_slack.rs:129-157`). For such a column
    the per-row squaring chain of the generic windowed kernel is wasted:
    precompute the base's window powers ONCE per group, then each row is
    only one table multiply per window.

    Cost per group of M rows at exp_bits=E (vs generic windowed kernel):
      ladder:   E squarings            on G-row batches   (amortized /M)
      table:    14 E/4 muls            on (W*G)-row batches, depth 4
      per-row:  E/4 muls + 2           on (G*M)-row batches
    i.e. heavy-batch work drops from ~1.27*E to ~0.25*E muls per row.

    base: (G, K); exp: (G, M, EL) limbs; n/r2/one_mont: (G, K);
    n_prime: (G,). Returns (G, M, K).
    """
    assert exp_bits % _WINDOW == 0
    g, k = base.shape
    m = exp.shape[1]
    w_cnt = exp_bits // _WINDOW

    if powers is None:
        base_m = mont_mul_limbs(base, r2, n, n_prime)

        # Ladder: powers[w] = base_m^(16^w). Sequential squarings, but on
        # G rows only — this is the chain the comb amortizes over the M rows.
        def ladder_step(w, carry):
            p, pws = carry
            pws = lax.dynamic_update_index_in_dim(pws, p, w, axis=0)
            for _ in range(_WINDOW):
                p = mont_mul_limbs(p, p, n, n_prime)
            return p, pws

        powers0 = jnp.zeros((w_cnt, g, k), _U32)
        _, powers = lax.fori_loop(0, w_cnt, ladder_step, (base_m, powers0))

    # Table entries c = powers^c for c = 1..15, built in log depth over a
    # flattened (W*G) batch: {2}, {3,4}, {5..8}, {9..15}.
    nf = jnp.broadcast_to(n[None], (w_cnt, g, k)).reshape(w_cnt * g, k)
    npf = jnp.broadcast_to(n_prime[None], (w_cnt, g)).reshape(w_cnt * g)
    p1 = powers.reshape(w_cnt * g, k)

    def mulf(a, b):
        return mont_mul_limbs(a, b, nf, npf)

    def mul_many(pairs):
        # one batched launch for a whole level: concat rows, split back
        a = jnp.concatenate([x for x, _ in pairs], axis=0)
        b = jnp.concatenate([y for _, y in pairs], axis=0)
        n_rep = jnp.concatenate([nf] * len(pairs), axis=0)
        np_rep = jnp.concatenate([npf] * len(pairs), axis=0)
        out = mont_mul_limbs(a, b, n_rep, np_rep)
        return [
            out[i * w_cnt * g : (i + 1) * w_cnt * g] for i in range(len(pairs))
        ]

    p2 = mulf(p1, p1)
    p3, p4 = mul_many([(p2, p1), (p2, p2)])
    p5, p6, p7, p8 = mul_many([(p4, p1), (p4, p2), (p4, p3), (p4, p4)])
    p9, p10, p11, p12, p13, p14, p15 = mul_many(
        [(p8, p1), (p8, p2), (p8, p3), (p8, p4), (p8, p5), (p8, p6), (p8, p7)]
    )
    one_f = jnp.broadcast_to(one_mont[None], (w_cnt, g, k)).reshape(w_cnt * g, k)
    # table: (16, W, G, K)
    table = jnp.stack(
        [t.reshape(w_cnt, g, k) for t in
         (one_f, p1, p2, p3, p4, p5, p6, p7, p8, p9, p10, p11, p12, p13, p14, p15)],
        axis=0,
    )

    # Accumulation on the (G*M)-row batch. With tree chunking (C > 1),
    # each chunk of C windows' selected entries reduces in log2(C)
    # MontMul levels; padded windows read zero exponent digits and
    # select table entry 0 = one_mont, the MontMul identity, so
    # non-power-of-two window counts stay exact.
    n_rows = jnp.broadcast_to(n[:, None], (g, m, k)).reshape(g * m, k)
    np_rows = jnp.broadcast_to(n_prime[:, None], (g, m)).reshape(g * m)
    acc0 = jnp.broadcast_to(one_mont[:, None], (g, m, k)).reshape(g * m, k)
    C = tree_chunk

    if C == 1:
        idx = jnp.arange(1 << _WINDOW, dtype=_U32)[:, None, None, None]

        def acc_step(w, acc):
            shift = _WINDOW * w
            limb = lax.dynamic_index_in_dim(
                exp, shift // LIMB_BITS, axis=2, keepdims=False
            )  # (G, M)
            d = (limb >> (shift % LIMB_BITS)) & ((1 << _WINDOW) - 1)
            entries = lax.dynamic_index_in_dim(table, w, axis=1, keepdims=False)
            # branchless per-row pick of entries[d[g,m], g, :] -> (G, M, K)
            sel = jnp.sum(
                jnp.where(d[None, :, :, None] == idx, entries[:, :, None, :], jnp.uint32(0)),
                axis=0,
            )
            return mont_mul_limbs(acc, sel.reshape(g * m, k), n_rows, np_rows)

        acc = lax.fori_loop(0, w_cnt, acc_step, acc0)
    else:
        n_chunks = -(-w_cnt // C)
        w_pad = n_chunks * C
        el_pad = w_pad * _WINDOW // LIMB_BITS  # LIMB_BITS % _WINDOW == 0
        if el_pad > exp.shape[2]:
            exp = jnp.pad(exp, ((0, 0), (0, 0), (0, el_pad - exp.shape[2])))
        if w_pad > w_cnt:  # entry 0 of every window is one_mont
            table = jnp.pad(
                table, ((0, 0), (0, w_pad - w_cnt), (0, 0), (0, 0)), mode="edge"
            )
        mask = jnp.uint32((1 << _WINDOW) - 1)
        ws0 = jnp.arange(C, dtype=jnp.int32)
        idx5 = jnp.arange(1 << _WINDOW, dtype=_U32)[:, None, None, None, None]

        def chunk_step(ci, acc):
            shifts = _WINDOW * (ci * C + ws0)  # (C,)
            limbs = jnp.take(exp, shifts // LIMB_BITS, axis=2)  # (G, M, C)
            sh = (shifts % LIMB_BITS).astype(limbs.dtype)
            d = (limbs >> sh[None, None, :]) & mask
            entries = lax.dynamic_slice_in_dim(
                table, ci * C, C, axis=1
            )  # (16, C, G, K)
            dt = d.transpose(2, 0, 1)  # (C, G, M)
            sel = jnp.sum(
                jnp.where(
                    dt[None, :, :, :, None] == idx5,
                    entries[:, :, :, None, :],
                    jnp.uint32(0),
                ),
                axis=0,
            )  # (C, G, M, K)
            x = sel.reshape(C, g * m, k)
            lvl = C
            while lvl > 1:
                half = lvl // 2
                a = x[0:lvl:2].reshape(half * g * m, k)
                b = x[1:lvl:2].reshape(half * g * m, k)
                nn = jnp.tile(n_rows, (half, 1))
                pp = jnp.tile(np_rows, (half,))
                x = mont_mul_limbs(a, b, nn, pp).reshape(half, g * m, k)
                lvl = half
            return mont_mul_limbs(acc, x[0], n_rows, np_rows)

        acc = lax.fori_loop(0, n_chunks, chunk_step, acc0)
    one = jnp.zeros_like(acc).at[:, 0].set(1)
    out = mont_mul_limbs(acc, one, n_rows, np_rows)
    return out.reshape(g, m, k)


@partial(jax.jit, static_argnames=("exp_bits_seq",))
def _multi_modexp_kernel(bases, exps, n, n_prime, r2, one_mont, *, exp_bits_seq):
    """Joint (Straus) multi-exponentiation: result[b] = prod_t
    bases[t, b]^exps[t, b] mod n[b].

    bases: (T, B, K); exps: (T, B, EL) limbs; n/r2/one_mont: (B, K);
    n_prime: (B,). exp_bits_seq: per-term bucketed widths, DESCENDING
    (callers sort terms) and each a multiple of the window width.

    One shared 4-bit squaring chain as deep as the widest term; per
    window, one branchless 16-entry table multiply per *active* term —
    term t's digits occupy the last exp_bits_seq[t]/4 windows of the
    chain, so a k-term full-width row costs ~(E_max + sum E_t/4)
    Montgomery products instead of the ~1.27 * sum E_t of k separate
    ladders. The window schedule is static (widths are launch shape, not
    data), so there is still no data-dependent control flow.
    """
    t_cnt, b_rows, k = bases.shape
    assert all(eb % _WINDOW == 0 for eb in exp_bits_seq)
    assert len(exp_bits_seq) == t_cnt
    assert list(exp_bits_seq) == sorted(exp_bits_seq, reverse=True)

    # all terms' window tables in one flattened (T*B)-row batch
    nf = jnp.broadcast_to(n[None], (t_cnt, b_rows, k)).reshape(t_cnt * b_rows, k)
    npf = jnp.broadcast_to(n_prime[None], (t_cnt, b_rows)).reshape(t_cnt * b_rows)
    r2f = jnp.broadcast_to(r2[None], (t_cnt, b_rows, k)).reshape(t_cnt * b_rows, k)
    onef = jnp.broadcast_to(one_mont[None], (t_cnt, b_rows, k)).reshape(
        t_cnt * b_rows, k
    )
    base_m = mont_mul_limbs(bases.reshape(t_cnt * b_rows, k), r2f, nf, npf)

    def build(j, table):
        prev = table[j - 1]
        table = table.at[j].set(mont_mul_limbs(prev, base_m, nf, npf))
        return table

    table0 = jnp.zeros((1 << _WINDOW, t_cnt * b_rows, k), _U32)
    table0 = table0.at[0].set(onef).at[1].set(base_m)
    table = lax.fori_loop(2, 1 << _WINDOW, build, table0).reshape(
        1 << _WINDOW, t_cnt, b_rows, k
    )

    w_total = exp_bits_seq[0] // _WINDOW
    idx = jnp.arange(1 << _WINDOW, dtype=_U32)[:, None, None]

    def window_step(wi, acc, active):
        """One shared window: 4 squarings then the active terms' table
        entries folded into acc. wi counts from the TOP of the chain."""
        for _ in range(_WINDOW):
            acc = mont_mul_limbs(acc, acc, n, n_prime)
        sels = []
        for t in active:
            w_t = exp_bits_seq[t] // _WINDOW
            # this term's digit index from its own MSB end (wi is traced,
            # so the bit shift is a traced scalar: cast for the uint >>)
            shift = exp_bits_seq[t] - _WINDOW * (wi - (w_total - w_t) + 1)
            limb = lax.dynamic_index_in_dim(
                exps[t], shift // LIMB_BITS, axis=1, keepdims=False
            )
            sh = (shift % LIMB_BITS).astype(_U32)
            d = (limb >> sh) & ((1 << _WINDOW) - 1)
            sels.append(jnp.sum(
                jnp.where(d[None, :, None] == idx, table[:, t], jnp.uint32(0)),
                axis=0,
            ))
        if len(sels) < 4:  # few-term rows: the sequential fold's shape
            for sel in sels:
                acc = mont_mul_limbs(acc, sel, n, n_prime)
            return acc
        # n-term rows (the RLC aggregated groups): fold the selected
        # entries in a log-depth tree of batched Montgomery products —
        # log2(k) wide launches instead of k sequential multiplies.
        # Exact, not approximate: every combine contributes exactly one
        # R^{-1} like the sequential fold, and odd levels pad with
        # one_mont (R mod n), the MontMul identity.
        b_rows_ = acc.shape[0]
        while len(sels) > 1:
            if len(sels) % 2:
                sels.append(one_mont)
            half = len(sels) // 2
            a = jnp.concatenate(sels[0::2], axis=0)
            b = jnp.concatenate(sels[1::2], axis=0)
            prod = mont_mul_limbs(
                a, b, jnp.tile(n, (half, 1)), jnp.tile(n_prime, (half,))
            )
            sels = [
                prod[i * b_rows_ : (i + 1) * b_rows_] for i in range(half)
            ]
        return mont_mul_limbs(acc, sels[0], n, n_prime)

    # segments: between consecutive distinct term widths the active-term
    # set is constant, so the window loop runs as a static ladder of
    # fori_loops (<= T segments) with the per-window term ops unrolled
    acc = one_mont
    starts = [w_total - eb // _WINDOW for eb in exp_bits_seq]  # ascending
    bounds = sorted(set(starts + [w_total]))
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        active = tuple(t for t in range(t_cnt) if starts[t] <= lo)

        def seg(wi, acc, _active=active):
            return window_step(wi, acc, _active)

        acc = lax.fori_loop(lo, hi, seg, acc)
    one = jnp.zeros_like(acc).at[:, 0].set(1)
    return mont_mul_limbs(acc, one, n, n_prime)


def multi_modexp(
    bases_rows: Sequence[Sequence[int]],
    exps_rows: Sequence[Sequence[int]],
    moduli: Sequence[int],
    num_limbs: int,
    exp_bits_seq: Sequence[int],
    ctx=None,
    mesh=None,
) -> List[int]:
    """Device joint multi-exponentiation: prod_t bases_rows[r][t] ^
    exps_rows[r][t] mod moduli[r] through the CIOS kernel. exp_bits_seq
    gives each term position's bucketed exponent width (launch shape);
    terms are sorted widest-first internally so the shared chain depth is
    the first entry."""
    rows = len(moduli)
    if rows == 0:
        return []
    k = len(exp_bits_seq)
    order = sorted(range(k), key=lambda t: -exp_bits_seq[t])
    eb = tuple(exp_bits_seq[t] for t in order)
    el = -(-eb[0] // LIMB_BITS)
    if ctx is None:
        ctx = BatchModExp(moduli, num_limbs)
    base_limbs = ints_to_limbs(
        [bases_rows[r][t] % n for t in order for r, n in enumerate(ctx.ctx.moduli)],
        num_limbs,
    ).reshape(k, rows, num_limbs)
    exp_limbs = ints_to_limbs(
        [exps_rows[r][t] for t in order for r in range(rows)], el
    ).reshape(k, rows, el)
    args = (
        jnp.asarray(base_limbs),
        jnp.asarray(exp_limbs),
        ctx._n,
        ctx._n_prime,
        ctx._r2,
        ctx._one_mont,
    )
    if mesh is not None and rows % int(mesh.devices.size) == 0:
        from ..parallel.shard_kernels import sharded_multi_modexp_fn

        out = sharded_multi_modexp_fn(mesh, eb)(*args)
    else:
        out = _multi_modexp_kernel(*args, exp_bits_seq=eb)
    res = limbs_to_ints(np.asarray(out))
    wipe_array(exp_limbs, base_limbs)  # secret staging (SECURITY.md)
    return res


@jax.jit
def _modmul_kernel(a, b, n, n_prime, r2):
    """a*b mod n per row (via a*R * b * R^{-1})."""
    a_m = mont_mul_limbs(a, r2, n, n_prime)
    return mont_mul_limbs(a_m, b, n, n_prime)


class BatchModExp:
    """Reusable multi-modulus batch context: fix the moduli once (they are
    per-party constants of a refresh), then run modexp/modmul batches.

    Device placement follows JAX defaults (the single real TPU chip under
    the bench, virtual CPU devices under tests); sharded execution across a
    mesh is layered on in fsdkr_tpu.parallel.
    """

    def __init__(self, moduli: Sequence[int], num_limbs: int, mesh=None):
        self.ctx = MontgomeryContext(moduli, num_limbs)
        self.mesh = mesh  # optional jax.sharding.Mesh: rows shard over it
        self._n = jnp.asarray(self.ctx.n)
        self._n_prime = jnp.asarray(self.ctx.n_prime)
        self._r2 = jnp.asarray(self.ctx.r2)
        self._one_mont = jnp.asarray(self.ctx.one_mont)

    def _mesh_for_rows(self, rows: int):
        if self.mesh is not None and rows % int(self.mesh.devices.size) == 0:
            return self.mesh
        return None

    def modexp(self, bases: Sequence[int], exps: Sequence[int]) -> List[int]:
        k = self.ctx.num_limbs
        bases = [b % n for b, n in zip(bases, self.ctx.moduli)]
        exp_bits = bucket_exp_bits(exps)
        exp_limbs = ints_to_limbs(exps, -(-exp_bits // LIMB_BITS))
        base_limbs = ints_to_limbs(bases, k)
        mesh = self._mesh_for_rows(len(bases))
        if mesh is not None:
            from ..parallel.shard_kernels import sharded_modexp_fn

            kernel = sharded_modexp_fn(mesh, exp_bits)
            out = kernel(
                jnp.asarray(base_limbs),
                jnp.asarray(exp_limbs),
                self._n,
                self._n_prime,
                self._r2,
                self._one_mont,
            )
        else:
            out = _modexp_kernel(
                jnp.asarray(base_limbs),
                jnp.asarray(exp_limbs),
                self._n,
                self._n_prime,
                self._r2,
                self._one_mont,
                exp_bits=exp_bits,
            )
        res = limbs_to_ints(np.asarray(out))
        # exponents (and sometimes bases) are prover secrets; results have
        # materialized above, so the staging copies can go (SECURITY.md)
        wipe_array(exp_limbs, base_limbs)
        return res

    def modmul(self, a: Sequence[int], b: Sequence[int]) -> List[int]:
        k = self.ctx.num_limbs
        a = [x % n for x, n in zip(a, self.ctx.moduli)]
        b = [x % n for x, n in zip(b, self.ctx.moduli)]
        args = (
            jnp.asarray(ints_to_limbs(a, k)),
            jnp.asarray(ints_to_limbs(b, k)),
            self._n,
            self._n_prime,
            self._r2,
        )
        mesh = self._mesh_for_rows(len(a))
        if mesh is not None:
            from ..parallel.shard_kernels import sharded_modmul_fn

            out = sharded_modmul_fn(mesh)(*args)
        else:
            out = _modmul_kernel(*args)
        return limbs_to_ints(np.asarray(out))


# Below this group count the comb's power ladder runs on the host: 4*E
# sequential device squarings on a handful of rows underfeed the chip,
# while the host pays G * E/4 CPython `pow(p, 16, n)` steps (~10 ms per
# 2048-bit group). Above it, the G-row device batch is wide enough.
_HOST_LADDER_MAX_GROUPS = 64


def shared_base_modexp(
    bases: Sequence[int],
    exps_per_group: Sequence[Sequence[int]],
    moduli: Sequence[int],
    num_limbs: int,
    host_ladder: bool | None = None,
    ctx: MontgomeryContext | None = None,
    mesh=None,
) -> List[List[int]]:
    """bases[g]^exps_per_group[g][m] mod moduli[g] via the fixed-base comb.

    Groups may have unequal row counts; rows are padded to the widest group
    with exponent 0 (base^0 = 1, discarded on the way out). Callers with a
    stable modulus vector pass a cached MontgomeryContext (backend.powm).
    """
    g_cnt = len(bases)
    if g_cnt == 0:
        return []
    m_max = max(len(e) for e in exps_per_group)
    exp_bits = bucket_exp_bits([e for grp in exps_per_group for e in grp])
    el = -(-exp_bits // LIMB_BITS)

    if ctx is None:
        ctx = MontgomeryContext(moduli, num_limbs)
    flat_exps: List[int] = []
    for grp in exps_per_group:
        flat_exps.extend(list(grp) + [0] * (m_max - len(grp)))
    exp_limbs = ints_to_limbs(flat_exps, el).reshape(g_cnt, m_max, el)

    if host_ladder is None:
        host_ladder = g_cnt <= _HOST_LADDER_MAX_GROUPS
    powers = None
    if host_ladder:
        from ..core import intops
        from ..utils.lru import global_cache

        w_cnt = exp_bits // _WINDOW
        r = 1 << (LIMB_BITS * num_limbs)
        # the per-group power ladder is a pure function of the PUBLIC
        # (base, modulus) pair and the launch geometry — persist it in
        # the precompute LRU so steady-state refreshes of a stable
        # committee (same h1/h2/T bases) skip the ~10 ms/group host
        # ladder entirely (cache-isolation pinned by test_cache_isolation)
        cache = global_cache()
        flat_powers: List[int] = []
        for b, n in zip(bases, ctx.moduli):
            key = ("comb-powers", b % n, n, w_cnt, num_limbs)
            pws = cache.get(key) if cache.budget > 0 else None
            if pws is None:
                p = b % n
                pws = []
                for _ in range(w_cnt):
                    pws.append(p * r % n)  # Montgomery domain
                    p = intops.mod_pow(p, 1 << _WINDOW, n)
                pws = tuple(pws)
                if cache.budget > 0:
                    cache.put(key, pws, w_cnt * (num_limbs * 2 + 48))
            flat_powers.extend(pws)
        powers = jnp.asarray(
            ints_to_limbs(flat_powers, num_limbs)
            .reshape(g_cnt, w_cnt, num_limbs)
            .transpose(1, 0, 2)
        )

    args = (
        jnp.asarray(ints_to_limbs([b % n for b, n in zip(bases, ctx.moduli)], num_limbs)),
        jnp.asarray(exp_limbs),
        jnp.asarray(ctx.n),
        jnp.asarray(ctx.n_prime),
        jnp.asarray(ctx.r2),
        jnp.asarray(ctx.one_mont),
    )
    if mesh is not None and g_cnt % int(mesh.devices.size) == 0:
        from ..parallel.shard_kernels import sharded_shared_modexp_fn

        kernel = sharded_shared_modexp_fn(
            mesh, exp_bits, powers is not None,
            tree_chunk=_comb_tree_chunk(
                exp_bits // _WINDOW, g_cnt * m_max, num_limbs
            ),
        )
        out = kernel(*args, powers) if powers is not None else kernel(*args)
    else:
        out = _shared_modexp_kernel(
            *args, powers, exp_bits=exp_bits,
            tree_chunk=_comb_tree_chunk(exp_bits // _WINDOW, g_cnt * m_max, num_limbs),
        )
    flat = limbs_to_ints(np.asarray(out).reshape(g_cnt * m_max, num_limbs))
    wipe_array(exp_limbs)  # ring-Pedersen nonces etc.; results are out
    return [
        flat[g * m_max : g * m_max + len(exps_per_group[g])] for g in range(g_cnt)
    ]


@partial(jax.jit, static_argnames=("levels",))
def _inv_tree_up_kernel(vals_m, n, n_prime, *, levels):
    """Product tree ascent, all groups batched. vals_m: (G, M, K) values
    in the Montgomery domain (x*R mod n), M = 2^levels; n/n_prime are
    per-group, broadcast over the M axis by the caller's layout.
    Returns the per-level arrays (for the descent) and the (G, 1, K)
    roots. Montgomery products of domain values stay in domain."""
    g, m, k = vals_m.shape
    lvls = [vals_m]
    cur = vals_m
    for _ in range(levels):
        half = cur.shape[1] // 2
        a = cur[:, 0::2].reshape(g * half, k)
        b = cur[:, 1::2].reshape(g * half, k)
        nn = jnp.broadcast_to(n[:, None], (g, half, k)).reshape(g * half, k)
        npp = jnp.broadcast_to(n_prime[:, None], (g, half)).reshape(g * half)
        cur = mont_mul_limbs(a, b, nn, npp).reshape(g, half, k)
        lvls.append(cur)
    return tuple(lvls)


@partial(jax.jit, static_argnames=("levels",))
def _inv_tree_down_kernel(lvls, root_inv_m, n, n_prime, *, levels):
    """Descent: inv(left child) = inv(parent) * right sibling, and vice
    versa. root_inv_m: (G, 1, K) Montgomery-domain inverse of each
    group's root. Returns (G, M, K) per-leaf inverses (Montgomery
    domain)."""
    g, _, k = root_inv_m.shape
    inv = root_inv_m
    for lvl in range(levels - 1, -1, -1):
        sib = lvls[lvl]  # (G, 2*half, K)
        half = sib.shape[1] // 2
        left = sib[:, 0::2].reshape(g * half, k)
        right = sib[:, 1::2].reshape(g * half, k)
        par = inv.reshape(g * half, k)
        nn = jnp.broadcast_to(n[:, None], (g, half, k)).reshape(g * half, k)
        npp = jnp.broadcast_to(n_prime[:, None], (g, half)).reshape(g * half)
        inv_left = mont_mul_limbs(par, right, nn, npp).reshape(g, half, k)
        inv_right = mont_mul_limbs(par, left, nn, npp).reshape(g, half, k)
        inv = jnp.stack([inv_left, inv_right], axis=2).reshape(g, 2 * half, k)
    return inv


def batch_mod_inv_grouped(
    groups: Sequence[Tuple[int, Sequence[int]]], num_limbs: int
):
    """Batched modular inversion via a device-side Montgomery product
    tree: for each (modulus, values) group, ONE host inversion of the
    tree root replaces len(values) serial CPython `pow(v, -1, m)` calls
    (467 us each at 2048 bits, 1.7 ms at 4096 — the O(n^2) range-proof
    loop at n=256 would spend ~450 s there; the tree's 2M on-device
    Montgomery products are noise next to the modexp work).

    Returns a list of per-group lists; a non-invertible value poisons
    only its own group, which falls back to per-row host inversion (an
    adversarial input can force the slow path for its group, never a
    wrong result — same policy as the RLC EC fallback).
    """
    from .limbs import MontgomeryContext

    if not groups:
        return []
    g_cnt = len(groups)
    m_max = max(len(vs) for _, vs in groups)
    levels = max(1, (m_max - 1).bit_length())
    m_pad = 1 << levels

    ctx = MontgomeryContext([m for m, _ in groups], num_limbs)
    r = 1 << (LIMB_BITS * num_limbs)
    flat: List[int] = []
    for (mod, vs) in groups:
        # Montgomery domain (x*R mod n); pad with R (domain rep of 1)
        flat.extend(v % mod * r % mod for v in vs)
        flat.extend([r % mod] * (m_pad - len(vs)))
    vals_m = jnp.asarray(
        ints_to_limbs(flat, num_limbs).reshape(g_cnt, m_pad, num_limbs)
    )
    n = jnp.asarray(ctx.n)
    n_prime = jnp.asarray(ctx.n_prime)

    lvls = _inv_tree_up_kernel(vals_m, n, n_prime, levels=levels)
    roots_m = np.asarray(lvls[-1]).reshape(g_cnt, num_limbs)
    # roots are x*R mod n; R^{-1} factors cancel in pairs up the tree so
    # root_m = (prod v_i) * R mod n — host-invert the plain product
    roots = limbs_to_ints(roots_m)
    out: List[Optional[List[int]]] = [None] * g_cnt
    root_inv_m: List[int] = []
    live: List[int] = []
    for gi, ((mod, vs), rt) in enumerate(zip(groups, roots)):
        try:
            inv = pow(rt * pow(r, -1, mod) % mod, -1, mod)
            root_inv_m.append(inv * r % mod)
            live.append(gi)
        except ValueError:  # some value in the group not invertible
            from ..core import intops

            out[gi] = [intops.mod_inv(v, mod) for v in vs]
            root_inv_m.append(1 * r % ctx.moduli[gi])  # dummy, discarded

    inv_leaves = _inv_tree_down_kernel(
        lvls[:-1],
        jnp.asarray(ints_to_limbs(root_inv_m, num_limbs)).reshape(
            g_cnt, 1, num_limbs
        ),
        n,
        n_prime,
        levels=levels,
    )
    # leave the Montgomery domain: montmul(x_m, 1) = x
    flat_m = inv_leaves.reshape(g_cnt * m_pad, num_limbs)
    one = jnp.zeros((g_cnt * m_pad, num_limbs), _U32).at[:, 0].set(1)
    nn = jnp.broadcast_to(n[:, None], (g_cnt, m_pad, num_limbs)).reshape(
        g_cnt * m_pad, num_limbs
    )
    npp = jnp.broadcast_to(
        n_prime[:, None], (g_cnt, m_pad)
    ).reshape(g_cnt * m_pad)
    plain = np.asarray(_modmul_exit_kernel(flat_m, one, nn, npp))
    leaf_ints = limbs_to_ints(plain)
    for gi in live:
        mod, vs = groups[gi]
        out[gi] = leaf_ints[gi * m_pad : gi * m_pad + len(vs)]
    return out


@jax.jit
def _modmul_exit_kernel(a_m, one, n, n_prime):
    return mont_mul_limbs(a_m, one, n, n_prime)


def batch_modexp(
    bases: Sequence[int], exps: Sequence[int], moduli: Sequence[int], num_limbs: int
) -> List[int]:
    """One-shot convenience wrapper: bases^exps mod moduli, row-wise."""
    return BatchModExp(moduli, num_limbs).modexp(bases, exps)


def batch_modmul(
    a: Sequence[int], b: Sequence[int], moduli: Sequence[int], num_limbs: int
) -> List[int]:
    return BatchModExp(moduli, num_limbs).modmul(a, b)
