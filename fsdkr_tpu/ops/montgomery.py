"""Batched multi-modulus Montgomery arithmetic in JAX (SURVEY.md §7 step 1,
hard part 1).

The workhorse of the TPU rebuild: the reference's O(n^2) serial
`BigInt::mod_pow` calls (e.g. `/root/reference/src/range_proofs.rs:129-148`,
`src/ring_pedersen_proof.rs:144`) become one batched modexp launch per
proof-family equation. Each batch row carries its own modulus.

Algorithm: CIOS (coarsely integrated operand scanning) over base-2^16
digits in uint32 lanes, with lazy carries — per outer step each
accumulator limb gains at most 4*(2^16-1) < 2^18, so across K <= 256 steps
values stay < 2^26 << 2^32 and no per-step normalization is needed. The
digit-product trick (lo/hi 16-bit split) keeps everything in native 32-bit
TPU integer ops; there is no data-dependent control flow anywhere
(exponent bits select between squared and multiplied values branchlessly),
so the whole modexp jits to a single XLA loop nest and vmaps/shards
cleanly.

Exponentiation is MSB-first fixed-window (4-bit): per window, 4
Montgomery squarings and one branchless 16-entry table multiply —
~1.27 Montgomery multiplications per exponent bit, constant shape.
Exponent widths are bucketed up a fixed ladder of multiples of 4 (see
`bucket_exp_bits`), which keeps the sequential depth close to the true
exponent width while capping the number of compiled kernel variants.
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .limbs import LIMB_BITS, LIMB_MASK, MontgomeryContext, ints_to_limbs, limbs_to_ints

__all__ = [
    "mont_mul_limbs",
    "batch_modexp",
    "batch_modmul",
    "bucket_exp_bits",
    "BatchModExp",
]


# Exponent-width ladder: wall-clock is proportional to the bucketed width
# (sequential window loop), so the ladder is finer than powers of two where
# the protocol's exponent sizes actually fall (q*Ntilde ~ 2304 bits,
# q^3*Ntilde ~ 2816 bits for 2048-bit moduli). All entries are multiples of
# 4 (window width); the variant count per (B, K) stays bounded.
_EXP_BUCKETS = (
    64, 128, 256, 512, 768, 1024, 1536, 2048, 2560, 3072, 4096,
    5120, 6144, 8192, 12288, 16384,
)


def bucket_exp_bits(exps) -> int:
    """Exponent width for a batch: the max bit length rounded up the
    bucket ladder. Guarantees the multiple-of-4 width the windowed kernel
    requires and caps compiled variants per (B, K)."""
    bits = max((e.bit_length() for e in exps), default=1) or 1
    for b in _EXP_BUCKETS:
        if bits <= b:
            return b
    return -(-bits // _WINDOW) * _WINDOW

_U32 = jnp.uint32


def _normalize_carries(t):
    """Fully propagate pending carries: limbs -> canonical base-2^16.
    Runs until fixpoint (data-dependent trip count, but each pass is a
    fixed-shape vector op; 3-4 passes in practice)."""

    def cond(t):
        return jnp.any(t >> LIMB_BITS)

    def body(t):
        lo = t & LIMB_MASK
        hi = t >> LIMB_BITS
        hi_shift = jnp.concatenate(
            [jnp.zeros_like(hi[:, :1]), hi[:, :-1]], axis=1
        )
        return lo + hi_shift

    return lax.while_loop(cond, body, t)


def _cond_subtract(t, n):
    """Return t - n if t >= n else t, limbwise with a borrow scan.
    t: (B, K+1) canonical limbs (value < 2n); n: (B, K)."""
    b, k = n.shape
    n_pad = jnp.concatenate([n, jnp.zeros((b, 1), _U32)], axis=1)

    def step(borrow, limbs):
        t_j, n_j = limbs
        d = t_j + (jnp.uint32(1) << LIMB_BITS) - n_j - borrow
        new_borrow = jnp.uint32(1) - (d >> LIMB_BITS)
        return new_borrow, d & LIMB_MASK

    borrow, diff_t = lax.scan(
        step, jnp.zeros((b,), _U32), (t.T, n_pad.T)
    )
    diff = diff_t.T
    keep = (borrow != 0)[:, None]  # borrow => t < n => keep t
    return jnp.where(keep, t, diff)[:, :k]


def mont_mul_limbs(x, y, n, n_prime):
    """Batched Montgomery product x*y*R^{-1} mod n.

    x, y, n: (B, K) canonical base-2^16 limbs, x,y < n; n_prime: (B,).
    Returns canonical (B, K) limbs < n.
    """
    b, k = x.shape
    t = jnp.zeros((b, k + 2), _U32)

    def step(i, t):
        x_i = lax.dynamic_index_in_dim(x, i, axis=1, keepdims=False)  # (B,)
        p = x_i[:, None] * y  # digit products fit uint32 exactly
        p_lo = p & LIMB_MASK
        p_hi = p >> LIMB_BITS
        m = ((t[:, 0] + p_lo[:, 0]) * n_prime) & LIMB_MASK
        pm = m[:, None] * n
        pm_lo = pm & LIMB_MASK
        pm_hi = pm >> LIMB_BITS
        t = t.at[:, :k].add(p_lo + pm_lo)
        t = t.at[:, 1 : k + 1].add(p_hi + pm_hi)
        # low limb is now 0 mod 2^16: divide by 2^16 (shift one limb down)
        carry0 = t[:, 0] >> LIMB_BITS
        t = jnp.concatenate([t[:, 1:], jnp.zeros((b, 1), _U32)], axis=1)
        t = t.at[:, 0].add(carry0)
        return t

    t = lax.fori_loop(0, k, step, t)
    t = _normalize_carries(t)
    return _cond_subtract(t[:, : k + 1], n)


_WINDOW = 4  # 4-bit fixed windows: 4 squarings + 1 table multiply per window


@partial(jax.jit, static_argnames=("exp_bits",))
def _modexp_kernel(base, exp, n, n_prime, r2, one_mont, *, exp_bits):
    """result = base^exp mod n, per row. exp: (B, EL) limbs.

    Fixed-window exponentiation, MSB-first: per 4-bit window, 4 Montgomery
    squarings and one branchless table multiply (the w=0 entry is the
    Montgomery one, so every window costs the same — no data-dependent
    control flow). exp_bits must be a multiple of 4 — guaranteed by
    `bucket_exp_bits` at every call site — so window shifts are 4-aligned
    and a window never straddles a 16-bit exponent limb.
    """
    assert exp_bits % _WINDOW == 0
    base_m = mont_mul_limbs(base, r2, n, n_prime)  # to Montgomery domain

    # table[j] = base_m^j (Montgomery domain), j = 0..15
    def build(j, table):
        prev = table[j - 1]
        table = table.at[j].set(mont_mul_limbs(prev, base_m, n, n_prime))
        return table

    table0 = jnp.zeros((1 << _WINDOW,) + base.shape, _U32)
    table0 = table0.at[0].set(one_mont).at[1].set(base_m)
    table = lax.fori_loop(2, 1 << _WINDOW, build, table0)

    idx = jnp.arange(1 << _WINDOW, dtype=_U32)[:, None, None]

    def step(wi, acc):
        shift = exp_bits - _WINDOW * (wi + 1)
        limb = lax.dynamic_index_in_dim(
            exp, shift // LIMB_BITS, axis=1, keepdims=False
        )
        w = (limb >> (shift % LIMB_BITS)) & ((1 << _WINDOW) - 1)  # (B,)
        for _ in range(_WINDOW):
            acc = mont_mul_limbs(acc, acc, n, n_prime)
        # branchless table select: sum over one-hot window match
        sel = jnp.sum(
            jnp.where(w[None, :, None] == idx, table, jnp.uint32(0)), axis=0
        )
        return mont_mul_limbs(acc, sel, n, n_prime)

    acc = lax.fori_loop(0, exp_bits // _WINDOW, step, one_mont)
    # leave Montgomery domain: multiply by 1
    one = jnp.zeros_like(acc).at[:, 0].set(1)
    return mont_mul_limbs(acc, one, n, n_prime)


@jax.jit
def _modmul_kernel(a, b, n, n_prime, r2):
    """a*b mod n per row (via a*R * b * R^{-1})."""
    a_m = mont_mul_limbs(a, r2, n, n_prime)
    return mont_mul_limbs(a_m, b, n, n_prime)


class BatchModExp:
    """Reusable multi-modulus batch context: fix the moduli once (they are
    per-party constants of a refresh), then run modexp/modmul batches.

    Device placement follows JAX defaults (the single real TPU chip under
    the bench, virtual CPU devices under tests); sharded execution across a
    mesh is layered on in fsdkr_tpu.parallel.
    """

    def __init__(self, moduli: Sequence[int], num_limbs: int):
        self.ctx = MontgomeryContext(moduli, num_limbs)
        self._n = jnp.asarray(self.ctx.n)
        self._n_prime = jnp.asarray(self.ctx.n_prime)
        self._r2 = jnp.asarray(self.ctx.r2)
        self._one_mont = jnp.asarray(self.ctx.one_mont)

    def modexp(self, bases: Sequence[int], exps: Sequence[int]) -> List[int]:
        k = self.ctx.num_limbs
        bases = [b % n for b, n in zip(bases, self.ctx.moduli)]
        exp_bits = bucket_exp_bits(exps)
        exp_limbs = ints_to_limbs(exps, -(-exp_bits // LIMB_BITS))
        out = _modexp_kernel(
            jnp.asarray(ints_to_limbs(bases, k)),
            jnp.asarray(exp_limbs),
            self._n,
            self._n_prime,
            self._r2,
            self._one_mont,
            exp_bits=exp_bits,
        )
        return limbs_to_ints(np.asarray(out))

    def modmul(self, a: Sequence[int], b: Sequence[int]) -> List[int]:
        k = self.ctx.num_limbs
        a = [x % n for x, n in zip(a, self.ctx.moduli)]
        b = [x % n for x, n in zip(b, self.ctx.moduli)]
        out = _modmul_kernel(
            jnp.asarray(ints_to_limbs(a, k)),
            jnp.asarray(ints_to_limbs(b, k)),
            self._n,
            self._n_prime,
            self._r2,
        )
        return limbs_to_ints(np.asarray(out))


def batch_modexp(
    bases: Sequence[int], exps: Sequence[int], moduli: Sequence[int], num_limbs: int
) -> List[int]:
    """One-shot convenience wrapper: bases^exps mod moduli, row-wise."""
    return BatchModExp(moduli, num_limbs).modexp(bases, exps)


def batch_modmul(
    a: Sequence[int], b: Sequence[int], moduli: Sequence[int], num_limbs: int
) -> List[int]:
    return BatchModExp(moduli, num_limbs).modmul(a, b)
