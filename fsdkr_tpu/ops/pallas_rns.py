"""Pallas TPU kernels for RNS Montgomery arithmetic (SURVEY.md §7's
"Pallas kernels for every hot numeric path", hard part 1).

Two kernels:

- `rns_mont_mul_pallas`: one RNS Montgomery product fused into a single
  launch. The XLA expression (`ops.rns._rns_mont_mul`) is a chain of ~20
  elementwise passes around two small matmuls; between fused regions XLA
  materializes (R, 2k+1) uint32 intermediates to HBM, and at 2048 bits a
  single modexp runs ~2560 such products — HBM traffic, not MXU time,
  bounds the pipeline. Here the whole product for a row tile runs inside
  VMEM: the only HBM traffic per product is x, y in and r out.

- `rns_modexp_pallas`: the ENTIRE windowed exponentiation in one launch.
  The 16-entry window table and the accumulator live in VMEM scratch for
  the whole ~E/4-window loop, so HBM sees only the inputs once and the
  result once — the kernel-fusion endgame of the north-star plan
  (BASELINE.json). Per row tile: 2 + 14 table + 5*E/4 MontMuls, each
  two MXU base-extension matmuls.

The matmuls run as 8-bit-split bf16 dots with f32 accumulation, chunked
at 128 contraction terms so every partial sum stays exact (255^2 * 128 <
2^23; the 4096-bit width class has k = 260 channels, past the 2^24
full-width exactness bound).

Numerics are IDENTICAL to `_rns_mont_mul` (same fold bounds, same
Shenoy correction); `tests/test_pallas.py` pins the kernels against the
XLA chain and against CPython pow. Interpret mode (`interpret=True`)
runs the same kernels on CPU for the test suite; the real target is the
MXU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .limbs import LIMB_BITS, WINDOW_BITS

_U32 = jnp.uint32


def _fold(v, u16m):
    return (v >> 16) * u16m + (v & jnp.uint32(0xFFFF))


def _channel_mod(v, m, u16m, folds=6):
    for _ in range(folds):
        v = _fold(v, u16m)
    v = jnp.where(v >= m, v - m, v)
    v = jnp.where(v >= m, v - m, v)
    return v


def _mulmod(a, b, m, u16m):
    return _channel_mod(a * b, m, u16m)


_LANE = 128  # contraction chunk: <=128-term 8-bit-split sums < 2^23, exact
# in f32 — the 4096-bit class has k=260 channels, where a full-width dot
# would exceed 2^24 and round (the same bound the XLA chain's _LANE
# chunking enforces)


def _matmul_mod(x, lo, hi, mods, u16m):
    """x (R, k) uint32 16-bit values, T pre-split bf16 (k, C): returns
    (R, C) sums mod per-column modulus. The contraction is chunked at
    _LANE terms so every f32-accumulated dot stays exact (static Python
    loop — shapes are compile-time constants inside the kernel)."""
    # Mosaic has no unsigned<->float casts: route u32->i32->f32->bf16
    # (and f32->i32->u32 on the way back); all values are < 2^31 so the
    # signed detour is exact
    xl = (x & jnp.uint32(0xFF)).astype(jnp.int32).astype(jnp.float32)
    xl = xl.astype(jnp.bfloat16)
    xh = (x >> 8).astype(jnp.int32).astype(jnp.float32).astype(jnp.bfloat16)
    dot = functools.partial(
        jnp.dot,
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    k = x.shape[1]
    out = None
    for s in range(0, k, _LANE):
        e = min(s + _LANE, k)
        pll = dot(xl[:, s:e], lo[s:e]).astype(jnp.int32).astype(_U32)
        plh = dot(xl[:, s:e], hi[s:e]).astype(jnp.int32).astype(_U32)
        phl = dot(xh[:, s:e], lo[s:e]).astype(jnp.int32).astype(_U32)
        phh = dot(xh[:, s:e], hi[s:e]).astype(jnp.int32).astype(_U32)
        # combine pll + 2^8(plh+phl) + 2^16 phh with interleaved folds;
        # all intermediates stay < 2^31 for <=128-term chunks
        # (u16m <= 8536)
        t1 = _fold(plh + phl, u16m)
        v = pll + (t1 << 8)
        t2 = _fold(phh, u16m) << 8
        t2 = _fold(_fold(t2, u16m), u16m)
        v = v + (t2 << 8)
        part = _channel_mod(v, mods, u16m, folds=6)
        out = part if out is None else out + part
    return _channel_mod(out, mods, u16m, folds=1)


def _mont_mul_body(x, y, c1, nbmr, consts, k):
    """The RNS Montgomery product on in-register/VMEM values.

    x, y: (R, 2k+1) residues (channels A | B | m_r); c1: (R, k);
    nbmr: (R, k+1); consts: dict of shared (1, ...) arrays.
    """
    m_all, u_all = consts["m_all"], consts["u_all"]
    mA, uA = m_all[:, :k], u_all[:, :k]
    mB_r, uB_r = m_all[:, k:], u_all[:, k:]
    mB, uB = m_all[:, k : 2 * k], u_all[:, k : 2 * k]

    d = _mulmod(x, y, m_all, u_all)
    xi = _mulmod(d[:, :k], c1, mA, uA)
    q = _matmul_mod(xi, consts["T1l"], consts["T1h"], mB_r, uB_r)  # (R, k+1)
    t = _mulmod(q, nbmr, mB_r, uB_r) + d[:, k:]
    t = jnp.where(t >= mB_r, t - mB_r, t)
    r_Bmr = _mulmod(t, consts["Ainv_B"], mB_r, uB_r)
    zeta = _mulmod(r_Bmr[:, :k], consts["c2_B"], mB, uB)
    mA_mr = jnp.concatenate([mA, m_all[:, 2 * k :]], axis=1)
    uA_mr = jnp.concatenate([uA, u_all[:, 2 * k :]], axis=1)
    s = _matmul_mod(zeta, consts["T2l"], consts["T2h"], mA_mr, uA_mr)  # (R, k+1)
    # exact Shenoy correction from the redundant channel (2-D slices —
    # TPU vector lanes want rank >= 2)
    m_r = m_all[:, 2 * k :]  # (1, 1)
    u_r = u_all[:, 2 * k :]
    s_r, r_r = s[:, k : k + 1], r_Bmr[:, k : k + 1]  # (R, 1)
    diff = jnp.where(s_r >= r_r, s_r - r_r, s_r + m_r - r_r)
    beta = _mulmod(diff, consts["Binv_r"], m_r, u_r)  # (R, 1), < k
    corr = _mulmod(beta, consts["B_mod_A"], mA, uA)
    r_A = jnp.where(s[:, :k] >= corr, s[:, :k] - corr, s[:, :k] + mA - corr)
    return jnp.concatenate([r_A, r_Bmr], axis=1)


def _mont_mul_kernel(
    x_ref,
    y_ref,
    c1_ref,
    nbmr_ref,
    mall_ref,
    uall_ref,
    T1l_ref,
    T1h_ref,
    T2l_ref,
    T2h_ref,
    ainv_ref,
    c2_ref,
    bmoda_ref,
    binvr_ref,
    out_ref,
    *,
    k,
):
    consts = dict(
        m_all=mall_ref[:],
        u_all=uall_ref[:],
        T1l=T1l_ref[:],
        T1h=T1h_ref[:],
        T2l=T2l_ref[:],
        T2h=T2h_ref[:],
        Ainv_B=ainv_ref[:],
        c2_B=c2_ref[:],
        B_mod_A=bmoda_ref[:],
        Binv_r=binvr_ref[:],
    )
    out_ref[:] = _mont_mul_body(
        x_ref[:], y_ref[:], c1_ref[:], nbmr_ref[:], consts, k
    )


def _row_tile(rows: int, cap: int = 256) -> int:
    """Largest power-of-two divisor of `rows`, capped (VMEM budget)."""
    t = rows & -rows  # lowest set bit
    return min(t, cap) if t else 1


@functools.partial(
    jax.jit, static_argnames=("k", "interpret", "tile")
)
def rns_mont_mul_pallas(
    x, y, c1, nbmr, shared, *, k, interpret=False, tile=None
):
    """One RNS Montgomery product as a single fused Pallas launch.

    x, y: (R, 2k+1) uint32 residues; c1: (R, k); nbmr: (R, k+1);
    shared: tuple (m_all, u_all, T1l, T1h, T2l, T2h, Ainv_B, c2_B,
    B_mod_A, Binv_r) with 1-D entries shaped (1, ...) by the caller.
    R must be divisible by the row tile (callers pad to powers of two).
    """
    rows, C = x.shape
    t = tile or _row_tile(rows)
    grid = (rows // t,)

    def row_spec(width):
        return pl.BlockSpec((t, width), lambda i: (i, 0))

    def const_spec(a):
        return pl.BlockSpec(a.shape, lambda i: (0,) * a.ndim)

    (m_all, u_all, T1l, T1h, T2l, T2h, ainv, c2, bmoda, binvr) = shared
    kernel = functools.partial(_mont_mul_kernel, k=k)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, C), jnp.uint32),
        grid=grid,
        in_specs=[
            row_spec(C),  # x
            row_spec(C),  # y
            row_spec(k),  # c1
            row_spec(k + 1),  # nbmr
            const_spec(m_all),
            const_spec(u_all),
            const_spec(T1l),
            const_spec(T1h),
            const_spec(T2l),
            const_spec(T2h),
            const_spec(ainv),
            const_spec(c2),
            const_spec(bmoda),
            const_spec(binvr),
        ],
        out_specs=row_spec(C),
        interpret=interpret,
    )(x, y, c1, nbmr, m_all, u_all, T1l, T1h, T2l, T2h, ainv, c2, bmoda, binvr)


# ---------------------------------------------------------------------------
# full windowed modexp in one launch


def _modexp_kernel_pallas(
    base_ref,
    exp_ref,
    a2n_ref,
    c1_ref,
    nbmr_ref,
    mall_ref,
    uall_ref,
    T1l_ref,
    T1h_ref,
    T2l_ref,
    T2h_ref,
    ainv_ref,
    c2_ref,
    bmoda_ref,
    binvr_ref,
    out_ref,
    table_ref,
    *,
    k,
    exp_bits,
):
    consts = dict(
        m_all=mall_ref[:],
        u_all=uall_ref[:],
        T1l=T1l_ref[:],
        T1h=T1h_ref[:],
        T2l=T2l_ref[:],
        T2h=T2h_ref[:],
        Ainv_B=ainv_ref[:],
        c2_B=c2_ref[:],
        B_mod_A=bmoda_ref[:],
        Binv_r=binvr_ref[:],
    )
    c1 = c1_ref[:]
    nbmr = nbmr_ref[:]

    def mul(a, b):
        return _mont_mul_body(a, b, c1, nbmr, consts, k)

    a2n = a2n_ref[:]
    one = jnp.ones_like(a2n)
    base_m = mul(base_ref[:], a2n)  # into the A-Montgomery domain
    one_m = mul(one, a2n)

    # 16-entry window table in VMEM scratch (static unroll: 14 products)
    table_ref[0] = one_m
    table_ref[1] = base_m
    prev = base_m
    for j in range(2, 1 << WINDOW_BITS):
        prev = mul(prev, base_m)
        table_ref[j] = prev

    idx = jax.lax.broadcasted_iota(
        _U32, (1 << WINDOW_BITS, 1, 1), dimension=0
    )

    def step(wi, acc):
        shift = exp_bits - WINDOW_BITS * (wi + 1)
        limb = exp_ref[:, pl.ds(shift // LIMB_BITS, 1)]  # (R, 1)
        w = (limb >> (shift % LIMB_BITS)) & jnp.uint32((1 << WINDOW_BITS) - 1)
        for _ in range(WINDOW_BITS):
            acc = mul(acc, acc)
        # Mosaic has no unsigned — and on older versions no integer —
        # reductions: collapse the one-hot-masked table with a static
        # log2(16)-deep tree of elementwise adds instead of reduce_sum
        # (15 of the 16 terms are zero, so plain adds are exact)
        masked = jnp.where(w[None, :, :] == idx, table_ref[:], jnp.uint32(0))
        terms = [masked[j] for j in range(1 << WINDOW_BITS)]
        while len(terms) > 1:
            terms = [
                terms[i] + terms[i + 1] for i in range(0, len(terms), 2)
            ]
        return mul(acc, terms[0])

    acc = jax.lax.fori_loop(0, exp_bits // WINDOW_BITS, step, one_m)
    out_ref[:] = mul(acc, one)  # leave the Montgomery domain


@functools.partial(
    jax.jit, static_argnames=("exp_bits", "k", "interpret", "tile")
)
def rns_modexp_pallas(
    base_res, exp, a2n_res, c1, nbmr, shared, *, exp_bits, k,
    interpret=False, tile=None,
):
    """base^exp per row, the whole window loop fused in one Pallas launch.

    base_res, a2n_res: (R, 2k+1) uint32 residues; exp: (R, EL) 16-bit
    limbs; c1: (R, k); nbmr: (R, k+1); shared: as rns_mont_mul_pallas.
    """
    rows, C = base_res.shape
    t = tile or _row_tile(rows, cap=128)
    grid = (rows // t,)

    def row_spec(width):
        return pl.BlockSpec((t, width), lambda i: (i, 0))

    def const_spec(a):
        return pl.BlockSpec(a.shape, lambda i: (0,) * a.ndim)

    (m_all, u_all, T1l, T1h, T2l, T2h, ainv, c2, bmoda, binvr) = shared
    kernel = functools.partial(_modexp_kernel_pallas, k=k, exp_bits=exp_bits)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, C), jnp.uint32),
        grid=grid,
        in_specs=[
            row_spec(C),  # base residues
            row_spec(exp.shape[1]),  # exponent limbs
            row_spec(C),  # A^2 mod n residues
            row_spec(k),  # c1
            row_spec(k + 1),  # nbmr
            const_spec(m_all),
            const_spec(u_all),
            const_spec(T1l),
            const_spec(T1h),
            const_spec(T2l),
            const_spec(T2h),
            const_spec(ainv),
            const_spec(c2),
            const_spec(bmoda),
            const_spec(binvr),
        ],
        out_specs=row_spec(C),
        scratch_shapes=[
            pltpu.VMEM((1 << WINDOW_BITS, t, C), jnp.uint32),
        ],
        interpret=interpret,
    )(
        base_res, exp, a2n_res, c1, nbmr,
        m_all, u_all, T1l, T1h, T2l, T2h, ainv, c2, bmoda, binvr,
    )
