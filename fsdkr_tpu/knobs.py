"""Central registry of every FSDKR_* environment knob.

Single source of truth for the knob surface (ISSUE 14): every
``FSDKR_*`` environment read anywhere in the package or scripts must
have a row here, and every row must have a matching entry in README.md's
knob table — both enforced statically by the knob-drift pass
(`fsdkr_tpu.analysis.knobs`, run by ``scripts/fsdkr_lint.py`` and the
ci.sh analysis leg). A knob declared here but read nowhere is flagged as
dead; a read of an undeclared knob is flagged as drift.

KNOBS must stay a PURE dict literal (name -> one-line description): the
static pass reads it with ``ast.literal_eval`` so linting never has to
import jax or the package.
"""

from __future__ import annotations

__all__ = ["KNOBS"]

KNOBS = {
    # -- engines / A-B gates ------------------------------------------------
    "FSDKR_RLC": "cross-proof randomized batch verification (1/0)",
    "FSDKR_MULTIEXP": "joint multi-exponentiation planner (1/0)",
    "FSDKR_RANGEOPT": "range-family verifier engines (1/0)",
    "FSDKR_CRT": "secret-CRT prover engine (1/0)",
    "FSDKR_GMP": "libgmp host bridge (1/0)",
    "FSDKR_MPN": "GMP mpn Montgomery inner loop (auto/0)",
    "FSDKR_PRECOMPUTE": "offline/online prover split via pools (1/0)",
    "FSDKR_PRECOMPUTE_BG": "background precompute producer thread (1/0)",
    "FSDKR_MEM_PLAN": "bytes-budgeted streaming verification plan (1/0)",
    "FSDKR_PIPELINE": "double-buffered tile prefetch (1/0)",
    "FSDKR_SCHED": "concurrent column scheduler workers (auto/int)",
    "FSDKR_NATIVE_POW": "native C++ Montgomery host core (1/0)",
    "FSDKR_NATIVE_EC": "native C++ EC core (1/0)",
    "FSDKR_DEVICE_EC": "device EC hot-path routing (auto/1/0)",
    "FSDKR_DEVICE_POWM": "device batched modexp routing (auto/1/0)",
    "FSDKR_PALLAS": "fused Pallas MontMul kernels (auto/1/0)",
    "FSDKR_NO_PALLAS": "bench-side hard disable of Pallas probes (1/0)",
    "FSDKR_XSESSION_DEDUP": "cross-session pair-row value dedup (1/0)",
    "FSDKR_FOLD_CACHE": "cross-launch shared-base fold-ladder cache (1/0)",
    "FSDKR_DELEGATE": "Feldman-MSM delegation certificate arm (0/1)",
    # -- sizing / tuning ----------------------------------------------------
    "FSDKR_THREADS": "native row-pool worker threads (auto/int)",
    "FSDKR_TILE_ROWS": "native-path tile size in rows (0 = whole batch)",
    "FSDKR_MAX_ROWS_PER_LAUNCH": "HBM tiling cap per device launch",
    "FSDKR_RNS_MIN_ROWS": "CIOS/VPU vs RNS/MXU router crossover (rows)",
    "FSDKR_DEVICE_MAX_TERMS": "device joint-ladder term cap",
    "FSDKR_COMB_TREE": "log-depth comb combination tree (1/0)",
    "FSDKR_COMB_TREE_BUDGET": "comb-tree table byte budget",
    "FSDKR_MEM_BUDGET_MB": "staged-bytes budget of the memory plan (MB)",
    "FSDKR_CACHE_BUDGET_MB": "persistent public precompute LRU budget (MB)",
    "FSDKR_POOL_DEPTH": "per-(kind,key) precompute pool entry cap",
    "FSDKR_POOL_BUDGET_MB": "total pooled-bytes budget (MB)",
    "FSDKR_POOL_TTL_S": "wall-clock backstop retiring owned pool targets",
    "FSDKR_PEAK_MACS": "roofline peak MAC/s override for mfu()",
    "FSDKR_JAX_CACHE": "persistent XLA compilation-cache base directory",
    # -- telemetry ----------------------------------------------------------
    "FSDKR_TRACE": "per-phase span tracing (1/0)",
    "FSDKR_TRACE_OUT": "Chrome-trace export path",
    "FSDKR_TRACE_EVENTS": "recorded span cap",
    "FSDKR_METRICS_DUMP": "Prometheus text exposition path",
    "FSDKR_FLIGHT": "flight-recorder dump path (or 1 = default path)",
    "FSDKR_FLIGHT_EVENTS": "flight ring size (events)",
    "FSDKR_XPROF": "jax.profiler trace alongside the span tracer",
    # -- serving ------------------------------------------------------------
    "FSDKR_SERVE": "refresh-as-a-service scheduler (1/0)",
    "FSDKR_SERVE_WORKERS": "prover-side worker threads",
    "FSDKR_SERVE_BATCH": "fused finalize batch size cap",
    "FSDKR_SERVE_LINGER_MS": "finalize coalescing linger budget (ms)",
    "FSDKR_SERVE_SHUFFLE": "shuffled per-session arrival order (1/0)",
    "FSDKR_SERVE_DEADLINE_S": "per-session deadline (0 = off)",
    "FSDKR_SERVE_RETRIES": "transient-failure retry cap",
    "FSDKR_SERVE_BACKOFF_MS": "retry backoff base (ms, jittered exp)",
    "FSDKR_SERVE_HISTORY": "finished-session records retained",
    "FSDKR_SERVE_MAX_QUEUE": "admission-control queue depth shed bound",
    "FSDKR_SERVE_SHED_P99": "admission shed multiplier over SLO p99",
    "FSDKR_SERVE_BISECT_BUDGET": "per-committee bisection budget",
    "FSDKR_SERVE_BISECT_WINDOW_S": "bisection budget sliding window (s)",
    "FSDKR_SERVE_HORIZON_S": "capacity-planner pool runway horizon (s)",
    "FSDKR_SERVE_MAX_AHEAD": "capacity-planner epochs-ahead clamp",
    "FSDKR_FAULTS": "deterministic fault-injection plan spec",
    # -- ingress / journal --------------------------------------------------
    "FSDKR_INGRESS_MAX_FRAME_MB": "TCP wire-frame size cap (MB)",
    "FSDKR_INGRESS_INFLIGHT_MB": "server-global inflight byte budget (MB)",
    "FSDKR_INGRESS_CONN_INFLIGHT_MB": "per-connection inflight budget (MB)",
    "FSDKR_INGRESS_IDLE_S": "idle-connection hygiene sweep timeout (s)",
    "FSDKR_INGRESS_WRITE_S": "slow-write (slow-loris) sweep timeout (s)",
    "FSDKR_INGRESS_PEER_RPS": "per-peer token-bucket rate limit",
    "FSDKR_INGRESS_HANDLERS": "executor threads for blocking ingress ops",
    "FSDKR_JOURNAL_SYNC": "journal fsync policy (always/batch/off)",
    "FSDKR_JOURNAL_BATCH": "records per fsync under batch policy",
    "FSDKR_JOURNAL_SEGMENT_MB": "journal segment rotation size (MB)",
    # -- bench / debug ------------------------------------------------------
    "FSDKR_POINT_TIMEOUT": "per-point timeout of the kernel battery (s)",
    "FSDKR_LOCK_CHECK": "runtime lock-order watchdog (1/0, tier-1 debug)",
    "FSDKR_TEST_KEYGEN_CACHE": "session-scoped keygen cache in tests (1/0)",
}
