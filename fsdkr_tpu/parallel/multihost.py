"""Multi-host (multi-slice / DCN) initialization.

SURVEY.md §5: the compute fabric is JAX collectives over ICI within a
slice; DCN enters only for multi-slice scale-out of independent
sessions (BASELINE config 5 grown past one v5e-8). The protocol needs
no cross-chip communication beyond verdict reductions, so multi-host
setup is exactly jax.distributed initialization + a global mesh whose
outer axis spans hosts (data-parallel over sessions, DCN) and whose
inner axis spans each host's local chips (proof rows, ICI).

Usage on each host of a multi-host deployment:

    from fsdkr_tpu.parallel import multihost
    multihost.initialize()            # no-op on a single host
    mesh = multihost.global_mesh()    # ("session", "batch") mesh
    config = ProtocolConfig(backend="tpu",
                            mesh_shape=tuple(mesh.devices.shape))

Process layout follows JAX's standard env detection (coordinator
address, process count/index from the cluster environment); explicit
arguments override it.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np

__all__ = [
    "initialize",
    "global_mesh",
    "is_multihost",
    "rows_to_global",
    "gather_rows",
]

_initialized = False

# launcher environments whose presence means jax.distributed's own
# auto-detection can resolve the process layout
_CLUSTER_MARKERS = (
    "JAX_COORDINATOR_ADDRESS",
    "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",  # multi-slice
    "TPU_WORKER_HOSTNAMES",  # GKE / TPU jobsets
    "SLURM_JOB_ID",
)


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bring up jax.distributed when running multi-process; harmless
    single-host no-op. Idempotent for detection-based calls; explicit
    arguments always reach jax.distributed (which itself rejects a
    second, conflicting initialization). An initialization done by
    other code is treated as success."""
    global _initialized
    explicit = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
    )
    if _initialized and not explicit:
        return
    if not explicit and not any(os.environ.get(m) for m in _CLUSTER_MARKERS):
        _initialized = True  # single host: nothing to bring up
        return
    if not explicit:
        # distributed init is illegal once a backend is up; a
        # detection-based call that arrives late degrades to single host
        # rather than crashing (explicit calls below still fail loudly).
        # The degradation is warned, not silent: on a real cluster it
        # means every host runs its own single-host protocol.
        if _backend_already_up():
            import warnings

            warnings.warn(
                "fsdkr_tpu.multihost.initialize() called after the JAX "
                "backend initialized; degrading to single-host. Call "
                "initialize() before any jax.devices()/computation, or "
                "pass explicit coordinator arguments.",
                RuntimeWarning,
                stacklevel=2,
            )
            _initialized = True
            return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        if "already" not in str(e):  # initialized elsewhere == success
            raise
    except ValueError:
        # a cluster marker was present but auto-detection could not
        # resolve the layout (e.g. this box's TPU tunnel sets
        # TPU_WORKER_HOSTNAMES for a single worker): explicit arguments
        # must fail loudly, detection-based calls degrade to single host
        if explicit:
            raise
    _initialized = True


def _backend_already_up() -> bool:
    """True if any JAX backend has initialized in this process."""
    try:
        from jax._src import xla_bridge

        if hasattr(xla_bridge, "backends_are_initialized"):
            return xla_bridge.backends_are_initialized()
        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:
        return False  # unknown internals: let jax.distributed decide


def is_multihost() -> bool:
    return jax.process_count() > 1


def rows_to_global(mesh: "jax.sharding.Mesh", local_rows, spec):
    """Assemble a process-spanning global array from each host's row
    block. In a multi-process deployment every host holds only its own
    slice of the proof-row axis; the sharded kernels (parallel.
    shard_kernels) consume global arrays laid out over the global mesh,
    so each host contributes `local_rows` (its contiguous block, in
    process order — matching global_mesh's host-aligned outer axis) under
    PartitionSpec `spec`. Single-host this is just device_put with the
    sharding."""
    return jax.make_array_from_process_local_data(
        jax.sharding.NamedSharding(mesh, spec), np.asarray(local_rows)
    )


def gather_rows(global_array) -> np.ndarray:
    """Fetch a fully-materialized copy of a (possibly process-spanning)
    global array on every host — the verdict-gather step after a sharded
    verification launch. DCN traffic is exactly this gather, matching
    SURVEY.md §5's layout (compute never crosses hosts)."""
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(global_array, tiled=True))


def global_mesh(
    axis_names: Sequence[str] = ("session", "batch"),
) -> jax.sharding.Mesh:
    """All devices across all hosts as a 2-D (hosts, chips-per-host)
    mesh: independent sessions shard over the outer axis (traffic rides
    DCN only at result gather), proof rows over the inner axis (ICI).
    Rows are host-aligned: devices group by process index, so the inner
    axis never crosses DCN. Single-host, this degenerates to
    (1, local chips)."""
    devices = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    hosts = jax.process_count()
    per_host, rem = divmod(len(devices), hosts)
    if rem:
        raise ValueError(
            f"uneven device count: {len(devices)} devices across {hosts} hosts"
        )
    return jax.sharding.Mesh(
        np.array(devices).reshape(hosts, per_host), tuple(axis_names)
    )
