"""Mesh-sharded wrappers for the production modexp kernels.

The row axis of every batch launch (proof rows for the generic CIOS and
RNS kernels, (base, modulus) groups for the two comb kernels) shards over
ALL axes of the configured `jax.sharding.Mesh`; constants (RNS extension
matrices etc.) replicate. No collective is algorithmically required —
every row is an independent verification/prover equation (SURVEY.md §5) —
so each device runs the identical kernel on its row slice and XLA
assembles the output. Verdict reduction (`sharded_verdict_step`) keeps
its explicit psum in parallel.sharded_verify.

Wrappers are cached per (mesh, static-shape) so repeat launches reuse the
compiled executable, mirroring the jit caching of the unsharded kernels.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
from jax.sharding import PartitionSpec as P

__all__ = [
    "padded_rows",
    "tile_rows_for_mesh",
    "align_session_batch",
    "shard_map_compat",
    "sharded_modexp_fn",
    "sharded_modmul_fn",
    "sharded_shared_modexp_fn",
    "sharded_multi_modexp_fn",
    "sharded_rns_modexp_fn",
    "sharded_rns_shared_modexp_fn",
    "sharded_rns_multi_modexp_fn",
]


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """jax.shard_map across the jax versions this repo meets: the public
    `jax.shard_map(check_vma=...)` API when present, the older
    `jax.experimental.shard_map.shard_map(check_rep=...)` otherwise."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, check_vma=False, in_specs=in_specs,
            out_specs=out_specs,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        fn, mesh=mesh, check_rep=False, in_specs=in_specs,
        out_specs=out_specs,
    )


def padded_rows(rows: int, mesh) -> int:
    """Round `rows` up so it splits evenly across the mesh."""
    n_dev = int(mesh.devices.size)
    return -(-rows // n_dev) * n_dev


def align_session_batch(count: int, rows_per_session: int, n_dev: int) -> int:
    """Largest batch size <= `count` whose total fused-launch row count
    (batch * rows_per_session) divides evenly across `n_dev` devices —
    the serving coalescer's mesh-aware sizing (ISSUE 9): a fused
    finalize launch that does not split evenly falls back to padded
    rows, wasting device time exactly when the scheduler is trying to
    keep the mesh full. Returns `count` unchanged when no smaller batch
    aligns (or on a single device), so coalescing never stalls on an
    impossible alignment."""
    if n_dev <= 1 or count <= 0 or rows_per_session <= 0:
        return count
    for k in range(count, 0, -1):
        if (k * rows_per_session) % n_dev == 0:
            return k
    return count


def tile_rows_for_mesh(tile_rows: int, mesh) -> int:
    """Round a pipeline tile size DOWN to a device-count multiple (but
    never below one row per device): the double-buffered dispatch in
    backend.powm cuts batches at tile boundaries, and a tile that does
    not divide across the mesh would silently fall off the sharded path
    inside the engines (`rows % devices == 0` gate) onto single-device
    execution."""
    n_dev = int(mesh.devices.size)
    return max(n_dev, (tile_rows // n_dev) * n_dev)


@lru_cache(maxsize=128)
def sharded_modexp_fn(mesh, exp_bits: int):
    from ..ops.montgomery import _modexp_kernel

    row = tuple(mesh.axis_names)
    kernel = partial(_modexp_kernel.__wrapped__, exp_bits=exp_bits)
    sm = shard_map_compat(
        kernel,
        mesh,
        (
            P(row, None),  # base
            P(row, None),  # exp
            P(row, None),  # n
            P(row),  # n_prime
            P(row, None),  # r2
            P(row, None),  # one_mont
        ),
        P(row, None),
    )
    return jax.jit(sm)


@lru_cache(maxsize=32)
def sharded_modmul_fn(mesh):
    from ..ops.montgomery import _modmul_kernel

    row = tuple(mesh.axis_names)
    sm = shard_map_compat(
        _modmul_kernel.__wrapped__,
        mesh,
        (P(row, None),) * 3 + (P(row), P(row, None)),
        P(row, None),
    )
    return jax.jit(sm)


@lru_cache(maxsize=128)
def sharded_shared_modexp_fn(mesh, exp_bits: int, with_powers: bool, tree_chunk: int = 1):
    """Comb kernel sharded over the GROUP axis: each device owns whole
    (base, modulus) groups, so the per-group ladder/table work never
    crosses devices."""
    from ..ops.montgomery import _shared_modexp_kernel

    row = tuple(mesh.axis_names)
    base_specs = (
        P(row, None),  # base (G, K)
        P(row, None, None),  # exp (G, M, EL)
        P(row, None),  # n
        P(row),  # n_prime
        P(row, None),  # r2
        P(row, None),  # one_mont
    )
    if with_powers:

        def kernel(base, exp, n, n_prime, r2, one_mont, powers):
            return _shared_modexp_kernel.__wrapped__(
                base, exp, n, n_prime, r2, one_mont, powers,
                exp_bits=exp_bits, tree_chunk=tree_chunk,
            )

        in_specs = base_specs + (P(None, row, None),)  # powers (W, G, K)
    else:

        def kernel(base, exp, n, n_prime, r2, one_mont):
            return _shared_modexp_kernel.__wrapped__(
                base, exp, n, n_prime, r2, one_mont, None,
                exp_bits=exp_bits, tree_chunk=tree_chunk,
            )

        in_specs = base_specs
    sm = shard_map_compat(kernel, mesh, in_specs, P(row, None, None))
    return jax.jit(sm)


@lru_cache(maxsize=128)
def sharded_multi_modexp_fn(mesh, exp_bits_seq: tuple):
    """Joint multi-exponentiation kernel sharded over the ROW axis; the
    term axis (leading) replicates its per-row slices alongside."""
    from ..ops.montgomery import _multi_modexp_kernel

    row = tuple(mesh.axis_names)
    kernel = partial(
        _multi_modexp_kernel.__wrapped__, exp_bits_seq=exp_bits_seq
    )
    sm = shard_map_compat(
        kernel,
        mesh,
        (
            P(None, row, None),  # bases (T, B, K)
            P(None, row, None),  # exps (T, B, EL)
            P(row, None),  # n
            P(row),  # n_prime
            P(row, None),  # r2
            P(row, None),  # one_mont
        ),
        P(row, None),
    )
    return jax.jit(sm)


@lru_cache(maxsize=128)
def sharded_rns_multi_modexp_fn(
    mesh, exp_bits_seq: tuple, k: int, pallas_mode: int = 0
):
    from ..ops.rns import _rns_multi_modexp_kernel

    row = tuple(mesh.axis_names)
    kernel = partial(
        _rns_multi_modexp_kernel.__wrapped__,
        exp_bits_seq=exp_bits_seq,
        k=k,
        pallas_mode=pallas_mode,
    )
    sm = shard_map_compat(
        kernel,
        mesh,
        (
            P(None, row, None),  # base limbs (T, B, L)
            P(None, row, None),  # exp limbs (T, B, EL)
            P(row, None),  # a2n limbs
            P(row, None),  # c1_A
            P(row, None),  # N_Bmr
            P(),  # shared constants (replicated pytree)
        ),
        P(row, None),
    )
    return jax.jit(sm)


@lru_cache(maxsize=128)
def sharded_rns_modexp_fn(mesh, exp_bits: int, k: int, pallas_mode: int = 0):
    from ..ops.rns import _rns_modexp_kernel

    row = tuple(mesh.axis_names)
    kernel = partial(
        _rns_modexp_kernel.__wrapped__,
        exp_bits=exp_bits,
        k=k,
        pallas_mode=pallas_mode,
    )
    sm = shard_map_compat(
        kernel,
        mesh,
        (
            P(row, None),  # base limbs
            P(row, None),  # exp limbs
            P(row, None),  # a2n limbs
            P(row, None),  # c1_A
            P(row, None),  # N_Bmr
            P(),  # shared constants (replicated pytree)
        ),
        P(row, None),
    )
    return jax.jit(sm)


@lru_cache(maxsize=128)
def sharded_rns_shared_modexp_fn(
    mesh, exp_bits: int, k: int, pallas_mode: int = 0,
    device_ladder: bool = False, tree_chunk: int = 1,
):
    """RNS comb sharded over groups. The kernel returns (G*M, C) rows in
    group-major order, so a leading-axis shard over G devices concatenates
    back in the right order."""
    from ..ops.rns import _rns_shared_modexp_kernel

    row = tuple(mesh.axis_names)
    kernel = partial(
        _rns_shared_modexp_kernel.__wrapped__,
        exp_bits=exp_bits,
        k=k,
        pallas_mode=pallas_mode,
        device_ladder=device_ladder,
        tree_chunk=tree_chunk,
    )
    sm = shard_map_compat(
        kernel,
        mesh,
        (
            P(None, row, None),  # powers (W, G, L)
            P(row, None, None),  # exp (G, M, EL)
            P(row, None),  # a2n (G, L)
            P(row, None),  # c1_A (G, k)
            P(row, None),  # N_Bmr (G, k+1)
            P(),  # shared constants
        ),
        P(row, None),
    )
    return jax.jit(sm)
