"""Device-mesh parallelism (SURVEY.md §5 "distributed communication",
§7 step 9).

The verification workload is embarrassingly parallel over proof rows:
sharding is a 1-D mesh over the batch axis, each device verifies its row
slice, and the only cross-device communication algorithmically required is
the reduction of verdict bits (a psum over the mesh, riding ICI). Sessions
(independent refreshes) stack onto the same batch axis — multi-session
scale-out is a reshape, not a new mechanism.
"""

from .mesh import make_mesh
from .sharded_verify import sharded_modexp, sharded_verdict_step
from . import multihost

__all__ = ["make_mesh", "multihost", "sharded_modexp", "sharded_verdict_step"]
