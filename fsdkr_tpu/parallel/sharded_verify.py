"""Sharded batch verification over a device mesh.

Two entry points:

- `sharded_modexp`: the multi-modulus modexp batch with rows sharded over
  the mesh's "batch" axis via shard_map; each device runs the same CIOS
  loop on its row slice. Returns the full result (XLA all-gathers on
  output resolution).

- `sharded_verdict_step`: the "training step" shape of this framework —
  one fused, jitted step that takes an equation batch
  (lhs_base^lhs_exp ?= rhs mod N, rows sharded), verifies every row on its
  owning device, and psums the per-device failure counts across the mesh,
  so the only cross-device traffic is verdict bits (SURVEY.md §5:
  "no cross-chip communication is algorithmically required ... only an
  all-gather of verdict bits").

Sessions are a leading reshape: 64 independent n=16 refreshes stack their
rows on the same batch axis (BASELINE.json config 5).
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.limbs import LIMB_BITS, MontgomeryContext, ints_to_limbs, limbs_to_ints
from ..ops.montgomery import _modexp_kernel, bucket_exp_bits

__all__ = ["sharded_modexp", "sharded_verdict_step", "pad_rows"]


def pad_rows(n_rows: int, n_devices: int) -> int:
    """Rows must split evenly across devices; pad with dummy rows."""
    return -(-n_rows // n_devices) * n_devices


def sharded_modexp(
    bases: Sequence[int],
    exps: Sequence[int],
    moduli: Sequence[int],
    num_limbs: int,
    mesh: jax.sharding.Mesh,
) -> List[int]:
    """bases^exps mod moduli row-wise, rows sharded over mesh axis "batch".

    Dummy padding rows (modulus 3, base 1, exp 0) make the row count divide
    the mesh; they are stripped before returning.
    """
    n_dev = mesh.devices.size
    b = len(bases)
    b_pad = pad_rows(b, n_dev)
    bases = list(bases) + [1] * (b_pad - b)
    exps = list(exps) + [0] * (b_pad - b)
    moduli = list(moduli) + [3] * (b_pad - b)

    ctx = MontgomeryContext(moduli, num_limbs)
    exp_bits = bucket_exp_bits(exps)
    exp_limbs = ints_to_limbs(exps, -(-exp_bits // LIMB_BITS))
    base_limbs = ints_to_limbs(
        [x % n for x, n in zip(bases, moduli)], num_limbs
    )

    row = tuple(mesh.axis_names)  # rows shard over every mesh axis
    kernel = partial(_modexp_kernel.__wrapped__, exp_bits=exp_bits)
    from .shard_kernels import shard_map_compat

    sharded = shard_map_compat(
        kernel,
        mesh,
        (
            P(row, None),  # base
            P(row, None),  # exp
            P(row, None),  # n
            P(row),  # n_prime
            P(row, None),  # r2
            P(row, None),  # one_mont
        ),
        P(row, None),
    )
    out = jax.jit(sharded)(
        jnp.asarray(base_limbs),
        jnp.asarray(exp_limbs),
        jnp.asarray(ctx.n),
        jnp.asarray(ctx.n_prime),
        jnp.asarray(ctx.r2),
        jnp.asarray(ctx.one_mont),
    )
    return limbs_to_ints(np.asarray(out))[:b]


def sharded_verdict_step(
    bases: Sequence[int],
    exps: Sequence[int],
    moduli: Sequence[int],
    expected: Sequence[int],
    num_limbs: int,
    mesh: jax.sharding.Mesh,
) -> tuple[np.ndarray, int]:
    """One fused verification step: row-sharded modexp, per-row comparison
    against `expected`, and a psum of failure counts over the mesh.

    Returns (per-row ok bits, global failure count). The failure count is
    computed with an explicit cross-device collective — the protocol's
    only required communication.
    """
    n_dev = mesh.devices.size
    b = len(bases)
    b_pad = pad_rows(b, n_dev)
    pad = b_pad - b
    bases = list(bases) + [1] * pad
    exps = list(exps) + [0] * pad
    moduli = list(moduli) + [3] * pad
    expected = list(expected) + [1] * pad

    ctx = MontgomeryContext(moduli, num_limbs)
    exp_bits = bucket_exp_bits(exps)
    exp_limbs = ints_to_limbs(exps, -(-exp_bits // LIMB_BITS))
    base_limbs = ints_to_limbs([x % n for x, n in zip(bases, moduli)], num_limbs)
    want_limbs = ints_to_limbs([x % n for x, n in zip(expected, moduli)], num_limbs)

    row = tuple(mesh.axis_names)  # rows shard over every mesh axis

    def step(base, exp, n, n_prime, r2, one_mont, want):
        got = _modexp_kernel.__wrapped__(
            base, exp, n, n_prime, r2, one_mont, exp_bits=exp_bits
        )
        ok = jnp.all(got == want, axis=1)
        failures = jax.lax.psum(jnp.sum(~ok), row)
        return ok, failures

    from .shard_kernels import shard_map_compat

    sharded = shard_map_compat(
        step,
        mesh,
        (
            P(row, None),
            P(row, None),
            P(row, None),
            P(row),
            P(row, None),
            P(row, None),
            P(row, None),
        ),
        (P(row), P()),
    )
    ok, failures = jax.jit(sharded)(
        jnp.asarray(base_limbs),
        jnp.asarray(exp_limbs),
        jnp.asarray(ctx.n),
        jnp.asarray(ctx.n_prime),
        jnp.asarray(ctx.r2),
        jnp.asarray(ctx.one_mont),
        jnp.asarray(want_limbs),
    )
    return np.asarray(ok)[:b], int(failures)
