"""Mesh construction helpers."""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np

__all__ = ["make_mesh"]


def make_mesh(
    shape: Optional[Tuple[int, ...]] = None,
    axis_names: Sequence[str] = ("batch",),
) -> jax.sharding.Mesh:
    """A device mesh over the local devices; default is all devices on one
    "batch" axis (proof rows shard over it; verdict psum rides ICI)."""
    devices = jax.devices()
    if shape is None:
        shape = (len(devices),)
    count = math.prod(shape)
    if count > len(devices):
        raise ValueError(f"mesh {shape} needs {count} devices, have {len(devices)}")
    if len(shape) != len(axis_names):
        raise ValueError("shape and axis_names rank mismatch")
    return jax.sharding.Mesh(
        np.array(devices[:count]).reshape(shape), tuple(axis_names)
    )
