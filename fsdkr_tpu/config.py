"""Protocol configuration.

The reference fixes its parameters at compile time
(`/root/reference/src/lib.rs:26-27`: PAILLIER_KEY_SIZE=2048, M_SECURITY=256,
plus cargo features selecting the bigint backend, `Cargo.toml:41-44`).
Here the same knobs are a runtime config object, extended with the
TPU-specific choices (backend selection and device-mesh shape), mirroring
the feature-flag pattern with a first-class object instead.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, replace
from typing import Optional, Tuple

_accel_probe: Optional[bool] = None


def _accelerator_present() -> bool:
    """Whether the default JAX backend is an accelerator. Measured EC
    crossover (bench_results/ec_ab_cpu.json): the batched complete-law
    EC kernels lose 3-20x to the host Jacobian oracle on XLA:CPU at
    every protocol shape, so EC rides the device only when a real
    accelerator is behind JAX.

    Only a successful jax.devices() probe is cached: TPU backend init is
    flaky in this environment (bench.py retries it), and pinning a
    transient failure would silently lock EC routing to the host for the
    whole process."""
    global _accel_probe
    if _accel_probe is None:
        try:
            import jax

            _accel_probe = jax.devices()[0].platform != "cpu"
        except Exception:
            return False  # transient: do not cache
    return _accel_probe


def _route_device(env_var: str) -> bool:
    """Shared device/host routing token table: `0/off/false/no` forces
    host, `1/on/true/yes` forces device, anything else (auto) picks the
    device only when a real accelerator is behind JAX."""
    env = os.environ.get(env_var, "auto").lower()
    if env in ("0", "off", "false", "no"):
        return False
    if env in ("1", "on", "true", "yes"):
        return True
    return _accelerator_present()


@dataclass(frozen=True)
class ProtocolConfig:
    """All security / execution parameters of the refresh protocol.

    paillier_bits: modulus size of every Paillier key and every ring-Pedersen
        / h1-h2-N-tilde modulus (reference: PAILLIER_KEY_SIZE=2048,
        `src/lib.rs:26`). The moduli acceptance gate admits
        [paillier_bits-1, paillier_bits] bit moduli
        (`src/refresh_message.rs:385-391`).
    m_security: number of binary-challenge rounds of the ring-Pedersen
        parameter proof (reference: M_SECURITY=256, `src/lib.rs:27`).
    correct_key_rounds: number of Fiat-Shamir challenges of the Paillier
        correct-key proof (zk-paillier uses 11).
    backend: "host" (pure-Python oracle) or "tpu" (batched JAX/Pallas
        verification kernels). Mirrors the reference's bigint feature switch.
    mesh_shape: optional device-mesh shape for sharded batch verification;
        None means "use all local devices on one axis".
    """

    paillier_bits: int = 2048
    m_security: int = 256
    correct_key_rounds: int = 11
    backend: str = "host"
    mesh_shape: Optional[Tuple[int, ...]] = None
    # Fiat-Shamir digest (reference: generic `HashChoice<H>` type param,
    # src/refresh_message.rs:31,46-47). Any name in core.transcript._HASHES;
    # wider digests admit m_security > 256. Threaded by parameter from the
    # protocol layer through every proof's prove/verify, so sessions with
    # different digests coexist in one process; the process-global default
    # (core.transcript.set_hash_algorithm) only covers standalone
    # prove/verify calls made without an explicit hash_alg.
    hash_alg: str = "sha256"
    # Group (reference: generic curve `E`). The host oracle layer is
    # generic (core.curves.make_curve); the batched device EC kernels are
    # specialized to secp256k1, so the protocol layer currently accepts
    # only "secp256k1" here — other curves run through core.curves
    # directly.
    curve: str = "secp256k1"

    def __post_init__(self):
        # Share recovery is only exact when the Lagrange-weighted plaintext
        # sum (t+1 terms, each < q^2 ~ 2^512 for secp256k1) cannot wrap mod
        # the Paillier modulus; 640 bits leaves 128 bits of committee-size
        # headroom. collect() additionally checks the recovered share
        # against the Feldman commitments.
        if self.paillier_bits < 640:
            raise ValueError("paillier_bits must be >= 640 for exact share recovery")
        if self.paillier_bits % 2:
            raise ValueError("paillier_bits must be even")
        from .core.transcript import digest_bytes

        if not 0 < self.m_security <= 8 * digest_bytes(self.hash_alg):
            raise ValueError(
                f"m_security must be in (0, {8 * digest_bytes(self.hash_alg)}] "
                f"for hash_alg={self.hash_alg}"
            )
        if self.curve != "secp256k1":
            raise ValueError(
                "the protocol layer is specialized to secp256k1 (device EC "
                "kernels); use core.curves for other groups"
            )

    def with_backend(self, backend: str) -> "ProtocolConfig":
        return replace(self, backend=backend)

    @property
    def device_ec(self) -> bool:
        """Whether EC hot paths (commit-point fan-out, PDL u1 column,
        Feldman RLC checks, pk_vec MSM) run on the accelerator. Single
        dispatch point for the protocol layer and the batch verifier.

        Routing: off for the host backend; for backend="tpu",
        FSDKR_DEVICE_EC=1/0 forces the device/host route, and the
        default (auto) picks the device only when JAX is actually
        backed by an accelerator — on the XLA:CPU fallback platform the
        host Jacobian oracle beats the batched kernels at every
        protocol shape (bench_results/ec_ab_cpu.json)."""
        if self.backend != "tpu":
            return False
        return _route_device("FSDKR_DEVICE_EC")

    @property
    def device_powm(self) -> bool:
        """Whether batched modexp/modmul launches ride the JAX device
        kernels (same contract as device_ec: forceable via
        FSDKR_DEVICE_POWM, auto picks the device only behind a real
        accelerator — on XLA:CPU the native C++ Montgomery core wins;
        modexp columns are ~70% of a warm fallback collect,
        bench_results/cpu_scale_n64_r5b.json)."""
        if self.backend != "tpu":
            return False
        return _route_device("FSDKR_DEVICE_POWM")

    @property
    def prime_bits(self) -> int:
        return self.paillier_bits // 2

    @property
    def key_material_pool_key(self) -> Tuple[int, int, int, str]:
        """Pool key of the precompute key-material pool
        (fsdkr_tpu/precompute, FSDKR_PRECOMPUTE): everything a pooled
        (ek, dk, correct-key proof, ring-Pedersen statement+proof)
        bundle depends on — sessions with different parameters can never
        consume each other's key material."""
        return (
            self.paillier_bits,
            self.m_security,
            self.correct_key_rounds,
            self.hash_alg,
        )


DEFAULT_CONFIG = ProtocolConfig()

# Small-parameter config for fast tests: 768-bit Paillier moduli are the
# smallest size at which share recovery is still exact (the Lagrange-weighted
# plaintext sum is < q^2 * (t+1) ~ 2^520 for secp256k1) while keeping the
# single-core host oracle fast. Production remains 2048/256.
TEST_CONFIG = ProtocolConfig(paillier_bits=768, m_security=32, correct_key_rounds=3)
