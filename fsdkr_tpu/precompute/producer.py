"""Producers for the precompute pools: offline constructors + the
background fill thread (the producer half of the offline/online split;
the pool store and hygiene rules live in `pools.py`).

Production rides the SAME batch engines as the inline path (the
backend/powm host route — GMP/native Montgomery with their
FSDKR_THREADS row pools and wipe discipline), so offline+online total
work equals the inline cost plus pool bookkeeping. The background
thread (`utils.pipeline.BackgroundProducer`) produces in small bounded
steps whenever targets registered by `distribute()` are under depth;
`collect()` kicks it on entry, so production overlaps the verifier's
GIL-releasing native launches — the SZKP-style producer/consumer
decoupling that keeps the modexp engines saturated between rounds.

Targets are metadata only (pool kind + PUBLIC key + desired depth); the
secret entries themselves go straight into the pool store. Registration
happens at the end of `distribute_batch` (it knows the committee), via
`register_committee` for serving systems, or implicitly through
`prefill` (the synchronous one-shot used by bench.py's offline
measurement and the tests).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
from typing import Dict, List, Optional, Tuple

from . import pools

__all__ = [
    "background_enabled",
    "produce_enc",
    "produce_keys",
    "produce_for",
    "register_targets",
    "register_committee",
    "prefill",
    "kick",
    "stop_background",
    "producer_running",
    "clear_targets",
    "committee_owner",
    "KEYS_POOL_OWNER",
    "owner_scope",
    "current_registration_owner",
    "invalidate_owner",
    "invalidate_targets",
    "replace_targets",
    "suspend_targets",
    "retarget_committee",
    "target_keys",
    "deficit_total",
]

# production step caps: one background step stays bounded (and stop()
# responsive) while still amortizing the batch engines' launch overhead
_PAIR_BATCH = 16
_KEY_BATCH = 2


def background_enabled() -> bool:
    """FSDKR_PRECOMPUTE_BG gates the background producer thread only
    (default on); =0 keeps the pools purely prefill-driven — bench.py
    forces =0 around its measured sections so the offline/online A/B is
    not contaminated by concurrent production on the same cores."""
    return pools.enabled() and os.environ.get(
        "FSDKR_PRECOMPUTE_BG", "1"
    ).lower() not in ("0", "off", "false", "no")


# ---------------------------------------------------------------------------
# per-kind constructors


def produce_enc(n: int, count: int, powm=None) -> List[tuple]:
    """`count` Paillier randomizer entries (r, r^n mod n^2) for receiver
    modulus n — r drawn exactly like paillier.sample_randomness (the
    seeded-parity contract), the power through the batched host engines."""
    from ..core import intops

    if powm is None:
        from ..backend.powm import host_powm as powm
    rs = [intops.sample_unit(n) for _ in range(count)]
    rn = powm(rs, [n] * count, [n * n] * count)
    return list(zip(rs, rn))


def produce_keys(params: tuple, count: int) -> List[tuple]:
    """`count` complete key-material bundles (ek, dk, NiCorrectKeyProof,
    RingPedersenStatement, RingPedersenProof) for pool key
    (paillier_bits, m_security, correct_key_rounds, hash_alg) — the
    exact call sequence of distribute_batch's four key phases, so seeded
    runs produce identical material. Ring-Pedersen witnesses are
    reference-dropped as soon as their proofs exist (the pooled bundle
    never carries phi/lambda)."""
    bits, m_security, ck_rounds, hash_alg = params
    from ..core import paillier
    from ..proofs.correct_key import NiCorrectKeyProof
    from ..proofs.ring_pedersen import RingPedersenProof, RingPedersenStatement
    from ..config import ProtocolConfig

    cfg = ProtocolConfig(
        paillier_bits=bits, m_security=m_security,
        correct_key_rounds=ck_rounds, hash_alg=hash_alg,
    )
    ek_dk = paillier.keygen_batch(bits, count)
    rp = RingPedersenStatement.generate_batch(count, cfg)
    ck_proofs = NiCorrectKeyProof.proof_batch(
        [dk for _, dk in ek_dk], rounds=ck_rounds, hash_alg=hash_alg
    )
    rp_proofs = RingPedersenProof.prove_batch(
        [w for _, w in rp], [st for st, _ in rp], m_security,
        None, hash_alg,
    )
    out = [
        (ek, dk, ck, st_w[0], rp_p)
        for (ek, dk), ck, st_w, rp_p in zip(ek_dk, ck_proofs, rp, rp_proofs)
    ]
    rp.clear()  # drop the ring-Pedersen witnesses (phi/lambda) now
    return out


def produce_for(kind: str, key, count: int) -> int:
    """Produce and pool up to `count` entries of (kind, key); returns
    how many the pool absorbed. Keys are self-describing: every value
    production needs is in the (public) pool key. Each production bout
    is a span (`precompute.produce.<kind>`): on the background thread
    these are the producer's own track in the Chrome-trace timeline —
    the occupancy picture the offline/online split is tuned by."""
    if count <= 0:
        return 0
    from ..utils.trace import phase

    with phase(f"precompute.produce.{kind}", items=count):
        if kind == "enc":
            entries = produce_enc(key, count)
        elif kind == "pdl":
            from ..proofs.pdl_slack import PDLwSlackProof

            h1, h2, nt, n = key
            entries = PDLwSlackProof.produce_stage1(h1, h2, nt, n, count)
        elif kind == "alice":
            from ..proofs.alice_range import AliceProof

            h1, h2, nt, n = key
            entries = AliceProof.produce_stage1(h1, h2, nt, n, count)
        elif kind == "keys":
            entries = produce_keys(key, count)
        else:
            raise ValueError(f"unknown pool kind {kind!r}")
        stored = 0
        for e in entries:
            if pools.put(kind, key, e):
                stored += 1
    return stored


# ---------------------------------------------------------------------------
# target registry + background thread

# (kind, key) -> (want, generation, owner, monotonic stamp). One
# register_targets call = one generation. Retirement (pool wiped with
# the target — refresh rotates every sender's Paillier modulus each
# epoch, so a retired key's entries can never be consumed again and
# must not hold secrets or byte budget until process teardown):
#
# - owner=None targets (legacy prefill/bench flows) retire after
#   _TARGET_TTL_GENS registrations without a refresh — the pre-ISSUE-9
#   lifecycle, unchanged.
# - OWNED targets (ISSUE 9 / ROADMAP 5a) have an explicit lifecycle
#   instead: suspend_targets at epoch start, replace_targets at epoch
#   handover, invalidate_owner on churn/eviction. They are EXEMPT from
#   the generation TTL — with hundreds of interleaved committees each
#   registration ages every other committee, so a generation TTL
#   retires pools BETWEEN a committee's own epochs (measured in the
#   serving loadgen: the TTL caused more dry fallbacks than every other
#   effect combined). A generous wall-clock TTL (_TARGET_TTL_S,
#   FSDKR_POOL_TTL_S) backstops abandoned owners.
_TARGETS: Dict[
    Tuple[str, object], Tuple[int, int, Optional[object], float]
] = {}
_TARGETS_LOCK = threading.Lock()
_TARGET_GEN = 0
_TARGET_TTL_GENS = 16
_PRODUCER = None  # lazily built BackgroundProducer


def _target_ttl_s() -> float:
    try:
        return float(os.environ.get("FSDKR_POOL_TTL_S", "900"))
    except ValueError:
        return 900.0

# ambient owner for registrations made inside protocol code (the serving
# layer wraps distribute in owner_scope(committee_id) so the auto-
# registration at the end of distribute_batch lands under the serving
# committee identity — clones sharing a mod-N~ fingerprint stay distinct)
_REG_OWNER: contextvars.ContextVar = contextvars.ContextVar(
    "fsdkr_precompute_owner", default=None
)


# owner of every ("keys", ...) target: the key-material pool is keyed by
# config parameters alone, so it is SHARED by every committee with that
# config — it must never be claimed by (or invalidated with) any single
# committee's owner tag, or one committee's churn would wipe the fleet's
# pooled key bundles
KEYS_POOL_OWNER = ("keys-pool",)


def committee_owner(dlog_statements) -> tuple:
    """Stable committee fingerprint for target ownership: the tuple of
    the committee's mod-N~ moduli in slot order. The environments are
    stable across refreshes (only churn changes them), public, and
    unique per real committee — exactly the lifetime pool targets share."""
    return ("committee-ntilde",) + tuple(d.N for d in dlog_statements)


@contextlib.contextmanager
def owner_scope(owner):
    """Ambient registration owner for the duration of the block: every
    register_targets call without an explicit owner (notably the
    auto-registration at the end of distribute_batch) is tagged with
    `owner`. Thread-local (contextvar), so concurrent serving workers
    tag their own committees."""
    tok = _REG_OWNER.set(owner)
    try:
        yield
    finally:
        _REG_OWNER.reset(tok)


def current_registration_owner():
    return _REG_OWNER.get()


def register_targets(targets, owner=None) -> None:
    """Record desired pool depths: targets = [(kind, key, want)] —
    re-registering refreshes a key's generation, want, and owner.
    Retirement sweep (see the _TARGETS comment): owner-less keys not
    re-registered for _TARGET_TTL_GENS calls, plus any key older than
    the wall-clock backstop, are dropped and their pools wiped.
    clear_targets() forgets everything at once."""
    global _TARGET_GEN
    import time

    if owner is None:
        owner = _REG_OWNER.get()
    now = time.monotonic()
    ttl_s = _target_ttl_s()
    stale = []
    with _TARGETS_LOCK:
        _TARGET_GEN += 1
        for kind, key, want in targets:
            _TARGETS[(kind, key)] = (int(want), _TARGET_GEN, owner, now)
        for k, (_want, gen, o, stamp) in list(_TARGETS.items()):
            gen_stale = o is None and gen <= _TARGET_GEN - _TARGET_TTL_GENS
            if gen_stale or now - stamp > ttl_s:
                del _TARGETS[k]
                stale.append(k)
    store = pools.get_store()
    for kind, key in stale:
        store.drop(kind, key)


def target_keys(owner=None) -> List[Tuple[str, object]]:
    """Currently registered (kind, key) targets, optionally filtered to
    one owner (introspection for tests and the capacity planner)."""
    with _TARGETS_LOCK:
        return [
            k
            for k, (_w, _g, o, _t) in _TARGETS.items()
            if owner is None or o == owner
        ]


def invalidate_targets(keys) -> int:
    """Drop the given (kind, key) targets and WIPE their pools — every
    unconsumed single-use entry keyed by them is destroyed now, not when
    the TTL fires. Returns the number of targets dropped."""
    keys = list(keys)
    dropped = []
    with _TARGETS_LOCK:
        for k in keys:
            if k in _TARGETS:
                del _TARGETS[k]
                dropped.append(k)
    store = pools.get_store()
    # wipe pools for every requested key, registered or not: produce_for
    # can fill a pool without a live target (prefill races, direct use)
    for kind, key in keys:
        store.drop(kind, key)
    return len(dropped)


def invalidate_owner(owner) -> int:
    """Drop every target registered under `owner` and wipe its pools —
    the churn entry point (join/replace/remove re-keys the committee, so
    the old owner's pooled secrets can never be consumed again). Returns
    the number of targets dropped."""
    if owner is None:
        return 0
    with _TARGETS_LOCK:
        keys = [k for k, (_w, _g, o, _t) in _TARGETS.items() if o == owner]
        for k in keys:
            del _TARGETS[k]
    store = pools.get_store()
    for kind, key in keys:
        store.drop(kind, key)
    return len(keys)


def suspend_targets(owner) -> int:
    """Unregister `owner`'s targets WITHOUT wiping their pools — called
    at the start of an epoch's distribute, which is about to consume
    those pools. While a target is live the producer cannot distinguish
    "empty because not yet filled" from "empty because the epoch just
    drained it", so mid-epoch kicks (another committee's collect) made
    it refill pools whose keys were minutes from rotation — production
    that the end-of-epoch replace_targets then wiped. Suspending for
    the epoch's duration closes that window; the end of distribute
    re-registers the next epoch's targets. Returns targets removed."""
    if owner is None:
        return 0
    with _TARGETS_LOCK:
        keys = [k for k, (_w, _g, o, _t) in _TARGETS.items() if o == owner]
        for k in keys:
            del _TARGETS[k]
    return len(keys)


def replace_targets(targets, owner) -> None:
    """register_targets PLUS wipe-on-invalidate for `owner`: any target
    currently registered under `owner` but absent from `targets` is
    dropped and its pool wiped. This is how an epoch hands over — the
    end of distribute_batch replaces the committee's per-receiver
    targets with next-epoch keys, so the producer never refills pools
    the epoch just drained (measured: the additive registration made
    the producer refill-then-wipe ~1 entry for every entry served)."""
    fresh_keys = {(kind, key) for kind, key, _want in targets}
    with _TARGETS_LOCK:
        stale = [
            k
            for k, (_w, _g, o, _t) in _TARGETS.items()
            if o == owner and k not in fresh_keys
        ]
        for k in stale:
            del _TARGETS[k]
    store = pools.get_store()
    for kind, key in stale:
        store.drop(kind, key)
    register_targets(targets, owner=owner)


def retarget_committee(
    local_key, new_n: int, senders: int, config, owner, keys_want=None
) -> None:
    """Atomic churn-safe retarget: wipe everything registered under
    `owner` that the committee's CURRENT layout no longer wants, then
    register the fresh target set under the same owner. The capacity
    planner calls this after every completed epoch (the committee's
    paillier_key_vec just rotated) and after churn.

    Depth economics: `senders` sizes the per-receiver enc/pdl/alice
    pools, whose keys rotate EVERY epoch — depth beyond one epoch of
    consumption is guaranteed wipe-waste, so callers pass one epoch's
    demand. The config-keyed "keys" pool is the opposite: shared across
    committees and epoch-stable, so it is registered under
    KEYS_POOL_OWNER (never this committee's owner) with `keys_want`
    (default: the committee's own epoch demand; the planner passes the
    fleet-wide figure)."""
    fresh = committee_targets(local_key, new_n, senders, config)
    keys_target = fresh.pop()  # ("keys", pool_key, senders) — documented last
    replace_targets(fresh, owner=owner)
    register_targets(
        [(keys_target[0], keys_target[1], keys_want or keys_target[2])],
        owner=KEYS_POOL_OWNER,
    )


def committee_targets(local_key, new_n: int, senders: int, config) -> list:
    """Target list for one committee: `senders` entries per receiver
    pool (every sender consumes one entry per receiver per epoch) and
    `senders` key bundles — one epoch ahead of steady-state demand.
    The ("keys", ...) target is always LAST (retarget_committee and the
    serving planner split it off for shared fleet ownership)."""
    out = []
    for i in range(new_n):
        ek = local_key.paillier_key_vec[i]
        d = local_key.h1_h2_n_tilde_vec[i]
        env = (d.g, d.ni, d.N, ek.n)
        out.append(("enc", ek.n, senders))
        out.append(("pdl", env, senders))
        out.append(("alice", env, senders))
    out.append(("keys", pools.key_material_pool_key(config), senders))
    return out


def register_committee(local_key, new_n: int, senders: int, config, owner=None) -> None:
    register_targets(
        committee_targets(local_key, new_n, senders, config), owner=owner
    )


def clear_targets() -> None:
    with _TARGETS_LOCK:
        _TARGETS.clear()


def _deficits() -> List[Tuple[str, object, int]]:
    store = pools.get_store()
    with _TARGETS_LOCK:
        items = list(_TARGETS.items())
    out = []
    for (kind, key), (want, _gen, _owner, _stamp) in items:
        room = store.room(kind, key, want)
        if room > 0:
            out.append((kind, key, room))
    return out


def deficit_total() -> int:
    """Entries still missing across every registered target (0 = every
    pool at depth) — the prefill-progress probe the serving load
    generator polls while the background producer fills."""
    return sum(room for _kind, _key, room in _deficits())


def _step() -> bool:
    """One bounded background production step: fill the first deficit
    that actually absorbs entries, a small batch at a time. Returns
    False when every target is at depth OR nothing can be stored (the
    byte budget is the binding constraint: depth-based room alone would
    report work forever while every put is wiped, and the loop would
    busy-spin producing discarded key material) — the producer then
    parks until the next kick."""
    if not background_enabled():
        return False
    from ..utils.trace import phase

    for kind, key, room in _deficits():
        cap = _KEY_BATCH if kind == "keys" else _PAIR_BATCH
        # the step span is the producer thread's unit of work in the
        # timeline; produce_for opens the per-kind child span under it
        with phase("precompute.producer.step"):
            produced = produce_for(kind, key, min(room, cap))
        if produced > 0:
            return True
    return False


def _producer():
    global _PRODUCER
    if _PRODUCER is None:
        from ..utils.pipeline import BackgroundProducer

        _PRODUCER = BackgroundProducer(_step)
    return _PRODUCER


def _register_gauges() -> None:
    """Producer-occupancy telemetry: productive fraction of the
    background thread's wall clock (the producer/consumer balance the
    SZKP-style pipelining tunes), plus lifetime step/error counts. All
    read lazily at snapshot time; zeros before the first kick."""
    from ..telemetry import registry

    registry.gauge(
        "fsdkr_producer_occupancy",
        "background producer busy-fraction since first start (0..1)",
    ).set_function(lambda: _PRODUCER.occupancy() if _PRODUCER else 0.0)
    registry.gauge(
        "fsdkr_producer_busy_seconds",
        "background producer cumulative productive seconds",
    ).set_function(lambda: _PRODUCER.busy_seconds if _PRODUCER else 0.0)
    registry.gauge(
        "fsdkr_producer_steps",
        "background producer lifetime productive steps",
    ).set_function(lambda: _PRODUCER.steps if _PRODUCER else 0)
    registry.gauge(
        "fsdkr_producer_errors",
        "background producer lifetime step exceptions",
    ).set_function(lambda: _PRODUCER.errors if _PRODUCER else 0)


_register_gauges()


def kick() -> None:
    """Wake (starting if needed) the background producer — called at the
    end of distribute_batch (targets just registered) and on entry to
    collect/collect_sessions (idle-time overlap with verification's
    GIL-releasing launches). No-op when gated off or target-free."""
    if not background_enabled():
        return
    with _TARGETS_LOCK:
        if not _TARGETS:
            return
    _producer().kick()


def stop_background(timeout: float = 5.0) -> None:
    if _PRODUCER is not None:
        _PRODUCER.stop(timeout=timeout)


def producer_running() -> bool:
    return _PRODUCER is not None and _PRODUCER.running()


def prefill(local_key, new_n: int, senders: int, config) -> int:
    """Synchronous offline fill: bring every pool of this committee up
    to one epoch of depth and return the number of entries produced.
    This is the `precompute_offline_s` measurement target in bench.py
    and the deterministic fill used by the seeded-parity tests."""
    if not pools.enabled():
        return 0
    targets = committee_targets(local_key, new_n, senders, config)
    register_targets(targets)
    store = pools.get_store()
    produced = 0
    for kind, key, want in targets:
        room = store.room(kind, key, want)
        if room > 0:
            produced += produce_for(kind, key, room)
    return produced
