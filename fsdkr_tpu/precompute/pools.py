"""Bounded, secret-hygienic precompute pools (FSDKR_PRECOMPUTE).

Round-8 traces put almost all of a warm `distribute()` in work that does
not depend on the epoch's inputs: Paillier randomizer powers r^n mod n^2
and the sigma-protocol beta^n columns (8.2 s), the mod-N~ first-message
commitments (2.2 s of the commit wall), and fresh key material with its
proofs (~5.5 s of keygen + ring-Pedersen gen + correct-key / rp proving).
This module is the offline half of the classic MPC offline/online split:
pools of single-use entries produced ahead of the refresh round (by the
background producer in `producer.py`, riding the same batch engines) and
consumed by `distribute()` at each phase boundary, with per-row inline
fallback when a pool runs dry — the consumed values are bit-identical to
what the inline path would have sampled and computed, so transcripts do
not depend on the gate (pinned by tests/test_precompute.py).

## Pool kinds

- ("enc", n): Paillier encryption randomizers for receiver modulus n —
  entries (r, r^n mod n^2) with r drawn exactly like
  `paillier.sample_randomness`.
- ("pdl", (h1, h2, N~, n)) and ("alice", (h1, h2, N~, n)): sigma
  first-messages for one receiver environment — entries
  (alpha, beta, rho, gamma, beta^n mod n^2, h2^rho mod N~,
  h1^alpha*h2^gamma mod N~), i.e. the prover's round-1 state plus every
  input-independent power. The witness-dependent factor h1^x stays
  online; the Fiat-Shamir challenge binds the commitments only AFTER
  the (online) statement is fixed, so nothing challenge-derived is ever
  poolable (SECURITY.md "Precompute pool discipline").
- ("keys", (paillier_bits, m_security, correct_key_rounds, hash_alg)):
  complete key-material bundles (ek, dk, NiCorrectKeyProof,
  RingPedersenStatement, RingPedersenProof) — both proofs are functions
  of the fresh key alone, so the whole block is offline.

## Secret hygiene

Every entry is secret material (randomizers, nonces, decryption keys).
Entries live ONLY in this module's in-process store — never the public
precompute LRU (`utils/lru.py`), whose entries persist unwiped under
the public-value-only rule (pinned by tests/test_precompute.py).
Entries are STRICTLY single-use: `PoolEntry.take()` returns the values
once, drops the references (the Python-int wipe discipline,
SECURITY.md), and raises `PrecomputeReuseError` forever after — a
reused sigma nonce answers two challenges and reveals the witness.
`clear_pools()` wipes every unconsumed entry (session teardown).

Pool KEYS are broadcast-public values (receiver moduli, ring-Pedersen
bases, config parameters); only entry VALUES are secret.

FSDKR_PRECOMPUTE=0 reverts every consumer to the inline path; the
bounded budget is FSDKR_POOL_DEPTH entries per (kind, key) under an
FSDKR_POOL_BUDGET_MB total byte cap.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from ..errors import PrecomputeReuseError

__all__ = [
    "enabled",
    "PoolEntry",
    "PrecomputeStore",
    "get_store",
    "take",
    "put",
    "clear_pools",
    "precompute_stats",
    "stats_reset",
    "key_material_pool_key",
]


def enabled() -> bool:
    """FSDKR_PRECOMPUTE gates the whole offline/online split (default
    on). Read at call time so the bench battery and the ci.sh leg can
    toggle it per step; =0 makes every consumer inline and every
    producer a no-op."""
    return os.environ.get("FSDKR_PRECOMPUTE", "1").lower() not in (
        "0", "off", "false", "no",
    )


def _pool_depth() -> int:
    """Per-(kind, key) entry cap (default 64: four n=16 epochs ahead)."""
    try:
        return max(1, int(os.environ.get("FSDKR_POOL_DEPTH", "64")))
    except ValueError:
        return 64


def _pool_budget_bytes() -> int:
    try:
        mb = float(os.environ.get("FSDKR_POOL_BUDGET_MB", "64"))
    except ValueError:
        mb = 64.0
    return int(mb * (1 << 20))


def _nbytes(v) -> int:
    """Byte estimate of an entry value for the pool budget: ints by bit
    length, containers and proof/statement objects by their int fields."""
    if isinstance(v, int):
        return v.bit_length() // 8 + 1
    if isinstance(v, (list, tuple)):
        return sum(_nbytes(x) for x in v)
    d = getattr(v, "__dict__", None)
    if d:
        return sum(_nbytes(x) for x in d.values())
    slots = getattr(type(v), "__slots__", None)
    if slots:
        return sum(_nbytes(getattr(v, s, 0)) for s in slots)
    return 64


class PoolEntry:
    """One single-use pooled value set. `take()` returns the values
    exactly once and drops the internal references; any further take
    raises PrecomputeReuseError (see errors.py for why reuse is a
    zero-knowledge break, not just a bug)."""

    __slots__ = ("_values", "nbytes")

    def __init__(self, values: tuple):
        self._values = tuple(values)
        self.nbytes = _nbytes(self._values)

    def take(self) -> tuple:
        if self._values is None:
            raise PrecomputeReuseError()
        v = self._values
        self._values = None  # int-level wipe: drop the only pool refs
        return v

    def wipe(self) -> None:
        self._values = None


def _events():
    """Pool event counter in the process-global telemetry registry,
    labeled by event AND pool kind (the per-kind split is new with
    ISSUE 6 — a dry 'keys' pool and a dry 'enc' pool have very
    different costs); `precompute_stats()` sums kinds for the legacy
    view."""
    from ..telemetry import registry

    return registry.counter(
        "fsdkr_pool_events",
        "precompute pool events (produced/consumed/dry_fallbacks/wiped)",
        labelnames=("event", "kind"),
    )


def _dry_events():
    """Cause-labeled dry-fallback counter (ISSUE 11): an injected
    pool-dry storm (FSDKR_FAULTS) must be distinguishable from a real
    producer regression, or chaos runs would hide exactly the
    regressions the dry-rate gate exists to catch. The legacy
    `fsdkr_pool_events{event=dry_fallbacks}` counter keeps counting
    BOTH causes (precompute_stats totals are unchanged); this counter
    splits them."""
    from ..telemetry import registry

    return registry.counter(
        "fsdkr_pool_dry",
        "pool dry fallbacks by kind and cause (real | injected)",
        labelnames=("kind", "cause"),
    )


def _injected_dry() -> bool:
    """Consult the serving fault plan WITHOUT importing it: a process
    that never ran chaos pays one sys.modules dict hit here and never
    imports the serving package (the zero-cost-when-disabled rule,
    SECURITY.md "Fault-injection discipline")."""
    import sys

    m = sys.modules.get("fsdkr_tpu.serving.faults")
    if m is None:
        return False
    plan = m.active()
    return plan is not None and plan.fire_seq("pool_dry")


def _bytes_gauge():
    from ..telemetry import registry

    return registry.gauge(
        "fsdkr_pool_bytes",
        "total bytes currently pooled (budget: FSDKR_POOL_BUDGET_MB)",
    )


class PrecomputeStore:
    """Per-session store of pools keyed by (kind, key). Bounded by
    per-key depth and a total byte budget; FIFO within a pool so
    consumption order matches production order (the seeded-parity
    contract). Thread-safe: the background producer puts while
    distribute() takes. Event counts live in the telemetry registry
    (labeled by kind); only counts are exported — entry VALUES never
    leave this module (SECURITY.md "Telemetry discipline")."""

    def __init__(self):
        self._pools: Dict[Tuple, deque] = OrderedDict()
        self._lock = threading.RLock()
        self._bytes = 0

    # -- consumption ----------------------------------------------------
    def take(self, kind: str, key) -> Optional[tuple]:
        """Pop and consume the oldest entry of pool (kind, key); None
        (counted as a dry fallback) when the pool is dry — the caller
        then computes inline, bit-identically. An injected pool-dry
        storm (FSDKR_FAULTS) forces the same dry fallback on a full
        pool — the entry stays pooled, only this take is starved — and
        is labeled cause=injected."""
        if _injected_dry():
            _events().inc(event="dry_fallbacks", kind=kind)
            _dry_events().inc(kind=kind, cause="injected")
            return None
        with self._lock:
            pool = self._pools.get((kind, key))
            if not pool:
                _events().inc(event="dry_fallbacks", kind=kind)
                _dry_events().inc(kind=kind, cause="real")
                return None
            ent = pool.popleft()
            if not pool:
                # drop the empty shell: refresh rotates pool keys every
                # epoch, so drained pools are never refilled under the
                # same key — keeping the deque would grow the store by
                # committees x receivers x epochs over a serving run
                del self._pools[(kind, key)]
            self._bytes -= ent.nbytes
            _events().inc(event="consumed", kind=kind)
            _bytes_gauge().set(self._bytes)
        return ent.take()

    # -- production -----------------------------------------------------
    def put(self, kind: str, key, values: tuple) -> bool:
        """Append one entry; False (entry wiped, not stored) when the
        per-key depth or the total byte budget is exhausted."""
        ent = PoolEntry(values)
        with self._lock:
            pool = self._pools.setdefault((kind, key), deque())
            if (
                len(pool) >= _pool_depth()
                or self._bytes + ent.nbytes > _pool_budget_bytes()
            ):
                ent.wipe()
                _events().inc(event="wiped", kind=kind)
                return False
            pool.append(ent)
            self._bytes += ent.nbytes
            _events().inc(event="produced", kind=kind)
            _bytes_gauge().set(self._bytes)
            return True

    def depth(self, kind: str, key) -> int:
        with self._lock:
            pool = self._pools.get((kind, key))
            return len(pool) if pool else 0

    def room(self, kind: str, key, want: int) -> int:
        """How many entries pool (kind, key) can still absorb toward a
        target of `want` (producer scheduling)."""
        with self._lock:
            have = self.depth(kind, key)
            return max(0, min(want, _pool_depth()) - have)

    # -- teardown / accounting ------------------------------------------
    def drop(self, kind: str, key) -> None:
        """Wipe and remove one whole pool (target retirement: refresh
        rotates receiver moduli every epoch, so pools keyed by retired
        moduli hold never-again-consumable secrets)."""
        with self._lock:
            pool = self._pools.pop((kind, key), None)
            if not pool:
                return
            for ent in pool:
                self._bytes -= ent.nbytes
                ent.wipe()
                _events().inc(event="wiped", kind=kind)
            pool.clear()
            _bytes_gauge().set(self._bytes)

    def clear(self) -> None:
        """Wipe every unconsumed entry (session teardown, tests, A/B)."""
        with self._lock:
            for (kind, _key), pool in self._pools.items():
                for ent in pool:
                    ent.wipe()
                    _events().inc(event="wiped", kind=kind)
                pool.clear()
            self._pools.clear()
            self._bytes = 0
            _bytes_gauge().set(0)

    def depths_by_kind(self) -> Dict[str, int]:
        """Entries currently pooled, summed per kind (the pool-depth
        gauge the SLO/serving work targets)."""
        out: Dict[str, int] = {}
        with self._lock:
            for (kind, _key), pool in self._pools.items():
                out[kind] = out.get(kind, 0) + len(pool)
        return out

    def snapshot(self) -> Dict[str, int]:
        m = _events()
        sums = {
            e: 0.0 for e in ("produced", "consumed", "dry_fallbacks", "wiped")
        }
        for rec in m.snapshot_values():
            ev = rec["labels"].get("event")
            if ev in sums:
                sums[ev] += rec["value"]
        with self._lock:
            return {
                **{k: int(v) for k, v in sums.items()},
                "bytes_pooled": self._bytes,
                "entries": sum(len(p) for p in self._pools.values()),
                "pools": len(self._pools),
            }

    def stats_reset(self) -> None:
        _events().reset()
        with self._lock:
            _bytes_gauge().set(self._bytes)

    def secret_values(self) -> List[int]:
        """Every int currently pooled, recursing into proof/statement/
        key objects like _nbytes does — the key-material bundles hold
        their secrets (dk.p, dk.q, proof fields) inside objects, and the
        LRU-isolation suite must see those too, not just the bare-int
        entries (tests: asserts none of these ever appears in the
        public cache)."""
        out: List[int] = []

        def walk(v):
            if isinstance(v, bool) or v is None:
                return
            if isinstance(v, int):
                out.append(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    walk(x)
            else:
                d = getattr(v, "__dict__", None)
                if d:
                    for x in d.values():
                        walk(x)
                else:
                    for s in getattr(type(v), "__slots__", ()):
                        walk(getattr(v, s, None))

        with self._lock:
            for pool in self._pools.values():
                for ent in pool:
                    if ent._values is not None:
                        walk(ent._values)
        return out


_STORE = PrecomputeStore()


def _register_gauges() -> None:
    from ..telemetry import registry

    registry.gauge(
        "fsdkr_pool_depth",
        "entries currently pooled, per kind (pool-occupancy gauge)",
        labelnames=("kind",),
    ).set_labeled_function(
        lambda: {(k,): v for k, v in _STORE.depths_by_kind().items()}
    )
    registry.gauge(
        "fsdkr_pool_count",
        "distinct (kind, key) pools currently held",
    ).set_function(lambda: len(_STORE._pools))


_register_gauges()


def get_store() -> PrecomputeStore:
    return _STORE


def take(kind: str, key) -> Optional[tuple]:
    return _STORE.take(kind, key)


def put(kind: str, key, values: tuple) -> bool:
    return _STORE.put(kind, key, values)


def clear_pools() -> None:
    _STORE.clear()


def precompute_stats() -> Dict[str, int]:
    return _STORE.snapshot()


def stats_reset() -> None:
    _STORE.stats_reset()


def key_material_pool_key(config) -> tuple:
    """Pool key of the key-material pool — delegates to
    ProtocolConfig.key_material_pool_key so producer-side and
    consumer-side keys can never drift apart (a silent divergence would
    let sessions with different parameters consume each other's key
    material, exactly what the key exists to prevent)."""
    return config.key_material_pool_key
