"""Offline/online split for distribute(): input-independent precompute
pools + background producer (FSDKR_PRECOMPUTE, default on).

`pools` holds the bounded single-use secret store and its hygiene rules;
`producer` holds the per-kind constructors, the committee target
registry, and the background fill thread. See SECURITY.md "Precompute
pool discipline" for what is and is not poolable.
"""

from .pools import (  # noqa: F401
    PoolEntry,
    PrecomputeStore,
    clear_pools,
    enabled,
    get_store,
    key_material_pool_key,
    precompute_stats,
    put,
    stats_reset,
    take,
)
from . import producer  # noqa: F401
from .producer import (  # noqa: F401
    KEYS_POOL_OWNER,
    background_enabled,
    clear_targets,
    committee_owner,
    committee_targets,
    current_registration_owner,
    deficit_total,
    invalidate_owner,
    invalidate_targets,
    kick,
    owner_scope,
    prefill,
    produce_for,
    producer_running,
    register_committee,
    register_targets,
    replace_targets,
    retarget_committee,
    stop_background,
    suspend_targets,
    target_keys,
)
