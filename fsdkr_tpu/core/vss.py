"""Feldman verifiable secret sharing over secp256k1.

Capability surface of curv's `VerifiableSS` as consumed by the reference
(SURVEY.md §2b): `share(t, n, secret)`, `validate_share_public`,
`map_share_to_new_params` (Lagrange basis at 0), `reconstruct` (usage
`/root/reference/src/refresh_message.rs:62,180-183,211-219`,
`src/test.rs:53-65`).

Conventions match curv: party i (1-based) holds the polynomial evaluation
f(i); `map_share_to_new_params(params, index, s)` takes 0-based indices and
evaluates the Lagrange basis of point index+1 at 0 over the points
{ j+1 : j in s }.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from .secp256k1 import GENERATOR, N, Point, Scalar

__all__ = ["ShamirSecretSharing", "VerifiableSS", "share", "map_share_to_new_params", "reconstruct"]


@dataclass(frozen=True)
class ShamirSecretSharing:
    """(t, n) parameters: degree-t polynomial, n shares; t+1 reconstruct."""

    threshold: int
    share_count: int


@dataclass
class VerifiableSS:
    """A Feldman VSS instance: parameters + commitments A_k = a_k * G to the
    t+1 polynomial coefficients.

    `delegate_cert` is the optional 2G2T-style MSM-delegation certificate
    (proofs.msm_delegate, FSDKR_DELEGATE): one broadcast-public point
    R = (sum_u rho_u f(u)) * G emitted by the dealer so verifiers can
    check the certificate instead of computing the per-share Horner
    MSMs. None (the default, and the wire default — the serialization
    omits the key entirely) means the honest per-row path."""

    parameters: ShamirSecretSharing
    commitments: List[Point] = field(default_factory=list)
    delegate_cert: Optional[Point] = None

    def validate_share_public(self, public_share: Point, index: int) -> bool:
        """Check sum_k A_k * index^k == public_share
        (reference check site `/root/reference/src/refresh_message.rs:180-183`).

        Horner evaluation: the scalar `index` is tiny (<= share_count), so
        this is t small-scalar muls — the same shape the TPU batch uses.
        """
        acc = Point.identity()
        for a_k in reversed(self.commitments):
            acc = acc * index + a_k
        return acc == public_share

    def reconstruct(self, indices: Sequence[int], shares: Sequence[Scalar]) -> Scalar:
        """Lagrange-interpolate f(0) from shares at 0-based `indices`."""
        if len(indices) != len(shares):
            raise ValueError("indices/shares length mismatch")
        if len(set(indices)) != len(indices):
            raise ValueError("duplicate share indices")
        if len(shares) < self.parameters.threshold + 1:
            raise ValueError(
                f"need at least {self.parameters.threshold + 1} shares, got {len(shares)}"
            )
        acc = Scalar.zero()
        for idx, sh in zip(indices, shares):
            lam = map_share_to_new_params(self.parameters, idx, indices)
            acc = acc + lam * sh
        return acc


def sample_poly(t: int, n: int, secret: Scalar) -> tuple[List[Scalar], List[Scalar]]:
    """Sample a degree-t polynomial with f(0)=secret; return (coefficients,
    shares f(1..n)). Commitment to the coefficients is a separate step so
    many senders' coefficient columns can share one batched EC launch
    (fsdkr_tpu.ops.ec_batch.batch_generator_mul)."""
    coeffs = [secret] + [Scalar(secrets.randbelow(N)) for _ in range(t)]
    shares = []
    for i in range(1, n + 1):
        acc = 0
        for c in reversed(coeffs):
            acc = (acc * i + c.v) % N
        shares.append(Scalar(acc))
    return coeffs, shares


def share(t: int, n: int, secret: Scalar) -> tuple[VerifiableSS, List[Scalar]]:
    """Sample a degree-t polynomial with f(0)=secret; return commitments to
    its coefficients and the n shares f(1..n)
    (reference call site `/root/reference/src/refresh_message.rs:62`)."""
    coeffs, shares = sample_poly(t, n, secret)
    commitments = [GENERATOR * c for c in coeffs]
    return VerifiableSS(ShamirSecretSharing(t, n), commitments), shares


def map_share_to_new_params(
    params: ShamirSecretSharing, index: int, s: Sequence[int]
) -> Scalar:
    """Lagrange basis coefficient of point index+1 evaluated at 0 over the
    point set { j+1 : j in s } (curv semantics; reference call site
    `/root/reference/src/refresh_message.rs:211-219`)."""
    xi = index + 1
    num, den = 1, 1
    for j in s:
        xj = j + 1
        if xj == xi:
            continue
        num = (num * xj) % N
        den = (den * (xj - xi)) % N
    return Scalar(num * pow(den, -1, N))


def reconstruct(
    params: ShamirSecretSharing, indices: Sequence[int], shares: Sequence[Scalar]
) -> Scalar:
    return VerifiableSS(params).reconstruct(indices, shares)
