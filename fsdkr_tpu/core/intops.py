"""Arbitrary-precision integer helpers over CPython ints.

Provides the `curv::BigInt` operation surface the reference consumes
(SURVEY.md §2b row "Arbitrary/fixed-precision modular arithmetic"):
mod_pow / mod_inv / mod_mul / sampling / bit_length / byte conversion
(usage sites e.g. `/root/reference/src/range_proofs.rs:54-63`,
`src/zk_pdl_with_slack.rs:177-187`). CPython `pow` is the host oracle; the
TPU limb kernels in `fsdkr_tpu.ops.montgomery` are differential-tested
against these functions.
"""

from __future__ import annotations

import math
import os
import secrets

__all__ = [
    "mod_pow",
    "mod_pow_signed",
    "mod_inv",
    "mod_mul",
    "mod_mul_col",
    "sample_below",
    "sample_range",
    "sample_bits",
    "sample_unit",
    "bit_length",
    "to_bytes",
    "from_bytes",
    "gcd",
]


# Wide odd-modulus exponentiation routes through the native C++ Montgomery
# core (csrc/fsdkr_native.cpp) so that "host backend" means the repo's best
# CPU path, not CPython pow — this is the baseline the TPU backend is
# benchmarked against. FSDKR_NATIVE_POW=0 restores pure CPython (the
# independent oracle used when differential-testing the native core itself).
_NATIVE_POW_MIN_BITS = 1024  # below this, ctypes overhead beats the win
_native_modexp = None


def _get_native_modexp():
    global _native_modexp
    if _native_modexp is None:
        if os.environ.get("FSDKR_NATIVE_POW", "1") != "1":
            _native_modexp = False
        else:
            try:
                from .. import native

                _native_modexp = native.modexp if native.available() else False
            except Exception:
                _native_modexp = False
    return _native_modexp


def mod_pow(base: int, exp: int, modulus: int) -> int:
    """base^exp mod modulus for exp >= 0. Wide odd-modulus rows prefer
    the system GMP (native/gmp.py — the reference's own backend; gated
    by FSDKR_GMP AND this module's FSDKR_NATIVE_POW oracle switch), then
    the own native core, then CPython pow."""
    if exp >= 0 and modulus & 1 and modulus.bit_length() >= _NATIVE_POW_MIN_BITS:
        # FSDKR_NATIVE_POW=0 is the pure-CPython oracle switch and is
        # read per call; the GMP route does NOT depend on the own core's
        # build status (gmp.available() is its own gate)
        if os.environ.get("FSDKR_NATIVE_POW", "1") == "1":
            from ..native import gmp

            if gmp.available():
                return gmp.powm(base, exp, modulus)
            impl = _get_native_modexp()
            if impl:
                return impl(base, exp, modulus)
    return pow(base, exp, modulus)


def mod_pow_signed(base: int, exp: int, modulus: int) -> int:
    """base^exp mod modulus, handling negative exponents via modular inverse.

    Mirrors the negative-exponent branch of `commitment_unknown_order`
    (`/root/reference/src/zk_pdl_with_slack.rs:178-185`).
    """
    if exp < 0:
        inv = mod_inv(base, modulus)
        if inv is None:
            raise ValueError("base not invertible for negative exponent")
        return mod_pow(inv, -exp, modulus)
    return mod_pow(base, exp, modulus)


def mod_inv(x: int, modulus: int):
    """Modular inverse, or None when gcd(x, modulus) != 1 (the reference's
    `BigInt::mod_inv` returns Option)."""
    try:
        return pow(x, -1, modulus)
    except ValueError:
        return None


def mod_mul(a: int, b: int, modulus: int) -> int:
    return (a * b) % modulus


def mod_mul_col(a, b, moduli) -> list:
    """Row-wise a[i]*b[i] mod moduli[i] — the commitment pair-combine of
    the staged provers (z = c1*c2, u3/w = c3*c4 over unknown-order Z_N~)."""
    return [x * y % m for x, y, m in zip(a, b, moduli)]


def sample_below(bound: int) -> int:
    """Uniform sample in [0, bound)."""
    if bound <= 0:
        raise ValueError("bound must be positive")
    return secrets.randbelow(bound)


def sample_range(lo: int, hi: int) -> int:
    """Uniform sample in [lo, hi)."""
    return lo + secrets.randbelow(hi - lo)


def sample_bits(bits: int) -> int:
    return secrets.randbits(bits)


def sample_unit(modulus: int) -> int:
    """Uniform sample from the multiplicative group Z_modulus^* (rejection
    sampling, reference `SampleFromMultiplicativeGroup`
    `/root/reference/src/range_proofs.rs:598-612`)."""
    while True:
        r = secrets.randbelow(modulus)
        if r and math.gcd(r, modulus) == 1:
            return r


def bit_length(x: int) -> int:
    return x.bit_length()


def to_bytes(x: int) -> bytes:
    """Minimal big-endian magnitude bytes; 0 encodes as b'' (matching the
    transcript convention in fsdkr_tpu.core.transcript)."""
    if x < 0:
        raise ValueError("to_bytes takes non-negative integers")
    return x.to_bytes((x.bit_length() + 7) // 8, "big")


def from_bytes(b: bytes) -> int:
    return int.from_bytes(b, "big")


def gcd(a: int, b: int) -> int:
    return math.gcd(a, b)


def zeroize_ints(*lists) -> None:
    """Drop proof-nonce references as soon as the proof is assembled
    (reference zeroizes its ZKP round state,
    `/root/reference/src/range_proofs.rs:28-29,222-243`).

    Python ints are immutable, so the values cannot be overwritten in
    place; clearing the containers releases the only references so the
    values become collectable immediately instead of surviving in live
    round-state objects. See README "Security notes" for the limits of
    this relative to Rust's zeroize."""
    for lst in lists:
        lst.clear()
