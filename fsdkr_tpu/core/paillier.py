"""Paillier cryptosystem (host oracle).

Capability surface of `kzen-paillier` as consumed by the reference
(SURVEY.md §2b): `keypair_with_modulus_size(bits)`, encryption with chosen
randomness `(1+n)^m * r^n mod n^2`, homomorphic add (ciphertext x
ciphertext) and mul (ciphertext x plaintext), CRT decryption with
`dk = {p, q}` (usage `/root/reference/src/refresh_message.rs:72-84,118,
221-236,439`).

The TPU path batches enc / homomorphic ops / the verification modexps over
limb tensors (`fsdkr_tpu.ops`); keygen stays host-side (SURVEY.md §7 step 3).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from . import intops, primes

__all__ = [
    "EncryptionKey",
    "DecryptionKey",
    "keygen",
    "keygen_batch",
    "encrypt",
    "encrypt_with_randomness",
    "encrypt_with_randomness_batch",
    "decrypt",
    "add",
    "mul",
    "sample_randomness",
]


@dataclass(frozen=True)
class EncryptionKey:
    """Public key; field names mirror the reference's `EncryptionKey{n, nn}`
    (`/root/reference/src/add_party_message.rs:248-251`)."""

    n: int
    nn: int

    @staticmethod
    def from_n(n: int) -> "EncryptionKey":
        return EncryptionKey(n=n, nn=n * n)


@dataclass
class DecryptionKey:
    """Secret key; `DecryptionKey{p, q}` as in the reference. Mutable so the
    protocol can zeroize it on refresh
    (`/root/reference/src/refresh_message.rs:446-448`)."""

    p: int
    q: int

    def zeroize(self) -> None:
        self.p = 0
        self.q = 0


def keygen(modulus_bits: int) -> tuple[EncryptionKey, DecryptionKey]:
    n, p, q = primes.gen_modulus(modulus_bits)
    return EncryptionKey.from_n(n), DecryptionKey(p=p, q=q)


def keygen_batch(
    modulus_bits: int, count: int
) -> list[tuple[EncryptionKey, DecryptionKey]]:
    """`count` fresh keypairs through one batched prime pipeline (the
    per-sender keygen loop of distribute_batch: candidates sieve, MR,
    and confirm as FSDKR_THREADS-parallel windows instead of 2*count
    serial gen_prime loops)."""
    return [
        (EncryptionKey.from_n(n), DecryptionKey(p=p, q=q))
        for n, p, q in primes.gen_moduli_batch(modulus_bits, count)
    ]


def sample_randomness(ek: EncryptionKey) -> int:
    return intops.sample_unit(ek.n)


def encrypt_with_randomness(ek: EncryptionKey, m: int, r: int) -> int:
    """c = (1+n)^m * r^n mod n^2, with (1+n)^m computed as 1 + m*n mod n^2.

    r must be a unit of Z_n; a zero / non-unit r would make the ciphertext
    undecryptable garbage rather than fail loudly.
    """
    if r <= 0 or math.gcd(r, ek.n) != 1:
        raise ValueError("Paillier randomness must be a unit of Z_n")
    gm = (1 + (m % ek.n) * ek.n) % ek.nn
    return (gm * intops.mod_pow(r, ek.n, ek.nn)) % ek.nn


def combine_with_rn(ms, rn, nv, nnv) -> list:
    """Assemble ciphertexts from a precomputed r^n column:
    c = (1 + (m mod n)*n) * r^n mod n^2. The one place the encryption
    formula lives — callers that batch the modexp column themselves
    (distribute's fused prover launch) come through here too."""
    return [
        (1 + (m % n) * n) * x % nn for m, x, n, nn in zip(ms, rn, nv, nnv)
    ]


def encrypt_with_randomness_batch(eks, ms, rs, powm=None) -> list:
    """Batched chosen-randomness encryption: one modexp column r^n mod n^2
    (the per-receiver encryption fan-out of distribute,
    `/root/reference/src/refresh_message.rs:72-84`)."""
    if powm is None:
        powm = lambda b, e, mod: [
            intops.mod_pow(x, y, z) for x, y, z in zip(b, e, mod)
        ]
    if not (len(eks) == len(ms) == len(rs)):
        raise ValueError(
            f"batch length mismatch: {len(eks)} keys, {len(ms)} plaintexts, "
            f"{len(rs)} randomness values"
        )
    for ek, r in zip(eks, rs):
        if r <= 0 or math.gcd(r, ek.n) != 1:
            raise ValueError("Paillier randomness must be a unit of Z_n")
    rn = powm(rs, [ek.n for ek in eks], [ek.nn for ek in eks])
    return combine_with_rn(
        ms, rn, [ek.n for ek in eks], [ek.nn for ek in eks]
    )


def encrypt(ek: EncryptionKey, m: int) -> int:
    return encrypt_with_randomness(ek, m, sample_randomness(ek))


def decrypt(dk: DecryptionKey, ek: EncryptionKey, c: int) -> int:
    """CRT decryption: m = L(c^lambda mod n^2) * lambda^{-1} mod n, done
    separately mod p^2 and q^2 and recombined. Under FSDKR_CRT each leg
    runs through the secret-CRT engine's fault-checked path
    (backend.crt.fault_checked_powm): computed mod p^2*r for a fresh
    64-bit prime r and re-verified mod r, so a faulted leg aborts
    (CrtFaultError) instead of producing a wrong plaintext — the decrypt
    output feeds the refreshed key share, and the Bellcore gcd attack
    applies to a faulted CRT leg here exactly as it does to RSA-CRT
    signatures."""
    p, q = dk.p, dk.q
    if p == 0 or q == 0:
        raise ValueError("decryption key has been zeroized")
    n = p * q
    pp, qq = p * p, q * q
    from ..backend import crt

    if crt.crt_enabled() and math.gcd(c, n) == 1:
        cp_pow = crt.fault_checked_powm(c % pp, p - 1, pp)
        cq_pow = crt.fault_checked_powm(c % qq, q - 1, qq)
    else:  # gate off, or a non-unit ciphertext (decryptable garbage):
        # the historical unchecked legs
        cp_pow = intops.mod_pow(c % pp, p - 1, pp)
        cq_pow = intops.mod_pow(c % qq, q - 1, qq)
    # With g = 1+n: L_p(g^{p-1} mod p^2) = (p-1)*q mod p, so the CRT
    # correction factor is h_p = ((p-1)*q)^{-1} mod p (and symmetrically q).
    hp = pow((p - 1) * q % p, -1, p)
    hq = pow((q - 1) * p % q, -1, q)
    mp = ((cp_pow - 1) // p) * hp % p
    mq = ((cq_pow - 1) // q) * hq % q
    # CRT combine
    qinv = pow(q, -1, p)
    diff = (mp - mq) * qinv % p
    return (mq + diff * q) % n


def add(ek: EncryptionKey, c1: int, c2: int) -> int:
    """Homomorphic addition: Enc(m1) (+) Enc(m2) = c1*c2 mod n^2."""
    return (c1 * c2) % ek.nn


def mul(ek: EncryptionKey, c: int, k: int) -> int:
    """Homomorphic scalar multiplication: Enc(m) (*) k = c^k mod n^2."""
    return intops.mod_pow(c, k % ek.n, ek.nn)
