"""secp256k1 elliptic-curve arithmetic (host oracle).

The capability surface the reference gets from
`curv::elliptic::curves::{Point, Scalar, Secp256k1}` (SURVEY.md §2b):
generator mul, point add, scalar arithmetic mod the group order, compressed
encoding, coordinate access, `Scalar::from(BigInt)` reduction (usage sites
`/root/reference/src/refresh_message.rs:67-69,443,455-463`,
`src/zk_pdl_with_slack.rs:124-127`, `src/range_proofs.rs:428-431`).

Implementation: Jacobian coordinates over CPython ints. The batched TPU
equivalents (branchless limb-tensor field ops) live in
`fsdkr_tpu.ops.ec_batch`; this module is their differential oracle.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass

__all__ = ["P", "N", "Scalar", "Point", "GENERATOR", "CURVE_ORDER"]

# Curve parameters: y^2 = x^3 + 7 over F_P.
P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
_GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

CURVE_ORDER = N


def _inv(x: int, m: int) -> int:
    return pow(x, -1, m)


@dataclass(frozen=True)
class Scalar:
    """Element of Z_N (the scalar field). Immutable."""

    v: int

    def __post_init__(self):
        object.__setattr__(self, "v", self.v % N)

    @staticmethod
    def random() -> "Scalar":
        while True:
            v = secrets.randbelow(N)
            if v:
                return Scalar(v)

    @staticmethod
    def from_int(x: int) -> "Scalar":
        return Scalar(x % N)

    @staticmethod
    def zero() -> "Scalar":
        return Scalar(0)

    def to_int(self) -> int:
        return self.v

    def __add__(self, other: "Scalar") -> "Scalar":
        if not isinstance(other, Scalar):
            return NotImplemented
        return Scalar(self.v + other.v)

    def __sub__(self, other: "Scalar") -> "Scalar":
        if not isinstance(other, Scalar):
            return NotImplemented
        return Scalar(self.v - other.v)

    def __mul__(self, other):
        # Scalar * Point defers to Point.__rmul__ via NotImplemented.
        if not isinstance(other, Scalar):
            return NotImplemented
        return Scalar(self.v * other.v)

    def __neg__(self) -> "Scalar":
        return Scalar(-self.v)

    def invert(self) -> "Scalar":
        return Scalar(_inv(self.v, N))

    def __bool__(self) -> bool:
        return self.v != 0


class Point:
    """Curve point (affine, with identity). Immutable by convention."""

    __slots__ = ("x", "y", "infinity")

    def __init__(self, x: int | None, y: int | None):
        if x is None:
            self.x, self.y, self.infinity = 0, 0, True
        else:
            self.x, self.y, self.infinity = x, y, False

    # -- constructors ------------------------------------------------------
    @staticmethod
    def identity() -> "Point":
        return Point(None, None)

    @staticmethod
    def generator() -> "Point":
        return GENERATOR

    @staticmethod
    def from_bytes(b: bytes) -> "Point":
        if b == b"\x00":
            return Point.identity()
        if len(b) != 33 or b[0] not in (2, 3):
            raise ValueError("bad compressed point")
        x = int.from_bytes(b[1:], "big")
        if x >= P:
            raise ValueError("x coordinate not canonical")
        rhs = (pow(x, 3, P) + 7) % P
        y = pow(rhs, (P + 1) // 4, P)
        if (y * y) % P != rhs:
            raise ValueError("point not on curve")
        if (y & 1) != (b[0] & 1):
            y = P - y
        return Point(x, y)

    # -- encoding ----------------------------------------------------------
    def to_bytes(self, compressed: bool = True) -> bytes:
        if self.infinity:
            return b"\x00"
        if compressed:
            return bytes([2 | (self.y & 1)]) + self.x.to_bytes(32, "big")
        return b"\x04" + self.x.to_bytes(32, "big") + self.y.to_bytes(32, "big")

    def x_coord(self) -> int:
        if self.infinity:
            raise ValueError("identity has no coordinates")
        return self.x

    def y_coord(self) -> int:
        if self.infinity:
            raise ValueError("identity has no coordinates")
        return self.y

    # -- group law ---------------------------------------------------------
    def __eq__(self, other) -> bool:
        if not isinstance(other, Point):
            return NotImplemented
        if self.infinity or other.infinity:
            return self.infinity == other.infinity
        return self.x == other.x and self.y == other.y

    def __hash__(self) -> int:
        return hash((self.infinity, self.x, self.y))

    def __add__(self, other: "Point") -> "Point":
        if self.infinity:
            return other
        if other.infinity:
            return self
        if self.x == other.x:
            if (self.y + other.y) % P == 0:
                return Point.identity()
            return self._double()
        lam = ((other.y - self.y) * _inv(other.x - self.x, P)) % P
        x3 = (lam * lam - self.x - other.x) % P
        y3 = (lam * (self.x - x3) - self.y) % P
        return Point(x3, y3)

    def _double(self) -> "Point":
        if self.infinity or self.y == 0:
            return Point.identity()
        lam = (3 * self.x * self.x * _inv(2 * self.y, P)) % P
        x3 = (lam * lam - 2 * self.x) % P
        y3 = (lam * (self.x - x3) - self.y) % P
        return Point(x3, y3)

    def __neg__(self) -> "Point":
        if self.infinity:
            return self
        return Point(self.x, (-self.y) % P)

    def __sub__(self, other: "Point") -> "Point":
        return self + (-other)

    def __mul__(self, scalar) -> "Point":
        k = scalar.v if isinstance(scalar, Scalar) else int(scalar) % N
        if k == 0 or self.infinity:
            return Point.identity()
        if self.x == _GX and self.y == _GY:
            # fixed-base comb for the generator: the protocol's host EC
            # cost is dominated by G-multiples (commit-point fan-out, PDL
            # u1, pk_vec interpolation, ECDSA) — the 64x16 nibble table
            # replaces ~256 doublings + ~128 adds with <= 64 mixed adds
            return _fixed_base_mul(k)
        # Jacobian double-and-add
        rx, ry, rz = 0, 1, 0  # identity in Jacobian (z=0)
        px, py, pz = self.x, self.y, 1
        for bit in bin(k)[2:]:
            rx, ry, rz = _jdouble(rx, ry, rz)
            if bit == "1":
                rx, ry, rz = _jadd(rx, ry, rz, px, py, pz)
        return _jac_to_affine(rx, ry, rz)

    __rmul__ = __mul__

    def __repr__(self) -> str:
        if self.infinity:
            return "Point(identity)"
        return f"Point(x={hex(self.x)[:12]}..., y={hex(self.y)[:12]}...)"


def _jdouble(x, y, z):
    if z == 0 or y == 0:
        return 0, 1, 0
    a = (x * x) % P
    b = (y * y) % P
    c = (b * b) % P
    d = (2 * ((x + b) * (x + b) - a - c)) % P
    e = (3 * a) % P
    f = (e * e) % P
    x3 = (f - 2 * d) % P
    y3 = (e * (d - x3) - 8 * c) % P
    z3 = (2 * y * z) % P
    return x3, y3, z3


def _jadd(x1, y1, z1, x2, y2, z2):
    if z1 == 0:
        return x2, y2, z2
    if z2 == 0:
        return x1, y1, z1
    z1z1 = (z1 * z1) % P
    z2z2 = (z2 * z2) % P
    u1 = (x1 * z2z2) % P
    u2 = (x2 * z1z1) % P
    s1 = (y1 * z2 * z2z2) % P
    s2 = (y2 * z1 * z1z1) % P
    if u1 == u2:
        if s1 != s2:
            return 0, 1, 0
        return _jdouble(x1, y1, z1)
    h = (u2 - u1) % P
    i = (4 * h * h) % P
    j = (h * i) % P
    r = (2 * (s2 - s1)) % P
    v = (u1 * i) % P
    x3 = (r * r - j - 2 * v) % P
    y3 = (r * (v - x3) - 2 * s1 * j) % P
    z3 = (2 * h * z1 * z2) % P
    return x3, y3, z3


# ---------------------------------------------------------------------------
# Fixed-base comb table for the generator: T[w][d-1] = d * 2^(4w) * G in
# affine, for 64 4-bit windows. Built lazily on the first G-multiple (~1024
# Jacobian ops + one batched inversion chain, tens of ms, once per process).
# Like the rest of this host oracle it is NOT constant-time — the oracle
# trades side-channel hardening for auditability; see README security notes.

_G_TABLE: list | None = None


def _jac_to_affine(x, y, z) -> "Point":
    """Jacobian (x, y, z) -> affine Point; the single conversion shared by
    both scalar-mul paths (auditability: one place to get it right)."""
    if z == 0:
        return Point.identity()
    zinv = _inv(z, P)
    z2 = (zinv * zinv) % P
    return Point((x * z2) % P, (y * z2 % P) * zinv % P)


def _build_g_table():
    rows = []  # Jacobian triples, 64 rows x 15 entries (d = 1..15)
    bx, by, bz = _GX, _GY, 1  # B_w = 2^(4w) * G
    for _ in range(64):
        row = [(bx, by, bz)]
        for _d in range(14):
            row.append(_jadd(*row[-1], bx, by, bz))
        rows.append(row)
        for _s in range(4):
            bx, by, bz = _jdouble(bx, by, bz)
    # batch-normalize all 960 points to affine with one inversion chain
    flat = [pt for row in rows for pt in row]
    zs = [z for _, _, z in flat]
    prefix = [1] * (len(zs) + 1)
    for i, z in enumerate(zs):
        prefix[i + 1] = prefix[i] * z % P
    acc = _inv(prefix[-1], P)
    zinvs = [0] * len(zs)
    for i in range(len(zs) - 1, -1, -1):
        zinvs[i] = prefix[i] * acc % P
        acc = acc * zs[i] % P
    affine = []
    for (x, y, _z), zi in zip(flat, zinvs):
        z2 = zi * zi % P
        affine.append((x * z2 % P, y * z2 % P * zi % P))
    return [affine[w * 15 : (w + 1) * 15] for w in range(64)]


def _fixed_base_mul(k: int) -> "Point":
    global _G_TABLE
    if _G_TABLE is None:
        _G_TABLE = _build_g_table()
    rx, ry, rz = 0, 1, 0
    for w in range(64):
        d = (k >> (4 * w)) & 0xF
        if d:
            ax, ay = _G_TABLE[w][d - 1]
            rx, ry, rz = _jadd(rx, ry, rz, ax, ay, 1)
    return _jac_to_affine(rx, ry, rz)


GENERATOR = Point(_GX, _GY)
