"""Prime generation for Paillier / ring-Pedersen moduli.

The reference delegates to GMP through `kzen-paillier`'s
`keypair_with_modulus_size` (`/root/reference/src/refresh_message.rs:118`).
Host-serial work stays host-side here (SURVEY.md §7 step 3): a small-prime
sieve plus Miller-Rabin over CPython ints. Generation cost is amortized —
keygen happens once per refresh per party, while verification is O(n²).
"""

from __future__ import annotations

import math
import secrets

__all__ = ["is_probable_prime", "gen_prime", "gen_modulus"]

# Product of odd primes below 4000 — one gcd against a candidate rejects
# nearly all composites before any modexp is spent on Miller-Rabin.
def _primorial(limit: int = 4000) -> int:
    sieve = bytearray([1]) * limit
    sieve[0:2] = b"\x00\x00"
    for i in range(2, int(limit**0.5) + 1):
        if sieve[i]:
            sieve[i * i :: i] = b"\x00" * len(sieve[i * i :: i])
    out = 1
    for p in range(3, limit):
        if sieve[p]:
            out *= p
    return out


_PRIMORIAL = _primorial()


def is_probable_prime(n: int, rounds: int = 30) -> bool:
    """Miller-Rabin with `rounds` random bases (error <= 4^-rounds).

    Dispatches to the native Montgomery core (fsdkr_tpu.native, the
    rebuild's GMP-equivalent) when available; the pure-Python path below
    is the fallback and differential oracle."""
    if n < 2:
        return False
    for small in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37):
        if n % small == 0:
            return n == small

    from .. import native

    verdict = native.is_probable_prime(n, rounds)
    if verdict is not None:
        return verdict

    d = n - 1
    r = (d & -d).bit_length() - 1
    d >>= r
    for _ in range(rounds):
        a = 2 + secrets.randbelow(n - 3)
        x = pow(a, d, n)
        if x == 1 or x == n - 1:
            continue
        for _ in range(r - 1):
            x = (x * x) % n
            if x == n - 1:
                break
        else:
            return False
    return True


def gen_prime(bits: int) -> int:
    """Random prime with exactly `bits` bits and the top two bits set.

    Forcing the two leading bits guarantees a product of two such primes has
    exactly 2*bits bits, satisfying the reference's moduli acceptance gate of
    [2*bits - 1, 2*bits] (`/root/reference/src/refresh_message.rs:385-391`).
    """
    if bits < 8:
        raise ValueError("prime too small")
    while True:
        cand = secrets.randbits(bits) | (1 << (bits - 1)) | (1 << (bits - 2)) | 1
        if math.gcd(cand, _PRIMORIAL) != 1:
            continue
        # one cheap round first: almost every sieved composite dies here
        if not is_probable_prime(cand, rounds=1):
            continue
        if is_probable_prime(cand, rounds=29):
            return cand


def gen_modulus(modulus_bits: int) -> tuple[int, int, int]:
    """Generate (n, p, q) with n = p*q of `modulus_bits` bits, p != q."""
    if modulus_bits % 2:
        raise ValueError("modulus_bits must be even")
    half = modulus_bits // 2
    p = gen_prime(half)
    while True:
        q = gen_prime(half)
        if q != p:
            return p * q, p, q
