"""Prime generation for Paillier / ring-Pedersen moduli.

The reference delegates to GMP through `kzen-paillier`'s
`keypair_with_modulus_size` (`/root/reference/src/refresh_message.rs:118`).
Host-serial work stays host-side here (SURVEY.md §7 step 3): a small-prime
sieve plus Miller-Rabin over CPython ints. Generation cost is amortized —
keygen happens once per refresh per party, while verification is O(n²).
"""

from __future__ import annotations

import secrets

__all__ = [
    "is_probable_prime",
    "gen_prime",
    "gen_primes_batch",
    "gen_modulus",
    "gen_moduli_batch",
    "gen_stats",
    "gen_stats_reset",
]

# Generation-work counters (bench.py's keygen-anomaly pin): prime search
# is a randomized algorithm with geometric-tail work, so wall-clock
# comparisons between two keygen runs are meaningless without
# normalizing by the work actually drawn — candidates sieved and
# Miller-Rabin rounds requested. Backed by the process-global telemetry
# registry since ISSUE 6 (one labeled counter); increments are batched
# per candidate window, so the counter lock is cold.


def _gen_metric():
    from ..telemetry import registry

    return registry.counter(
        "fsdkr_primegen_events",
        "prime-search work drawn (candidates sieved / MR rounds requested)",
        labelnames=("event",),
    )


def gen_stats() -> dict:
    m = _gen_metric()
    return {
        "candidates": int(m.value(event="candidates")),
        "mr_rounds": int(m.value(event="mr_rounds")),
    }


def gen_stats_reset() -> None:
    _gen_metric().reset()

# Product of odd primes below 4000 — one gcd against a candidate rejects
# nearly all composites before any modexp is spent on Miller-Rabin.
def _primorial(limit: int = 4000) -> int:
    sieve = bytearray([1]) * limit
    sieve[0:2] = b"\x00\x00"
    for i in range(2, int(limit**0.5) + 1):
        if sieve[i]:
            sieve[i * i :: i] = b"\x00" * len(sieve[i * i :: i])
    out = 1
    for p in range(3, limit):
        if sieve[p]:
            out *= p
    return out


_PRIMORIAL = _primorial()

# Wider sieve for the GENERATION path only: one gcd against the product
# of odd primes below 2^14 rejects ~15% more composites than the 4000
# sieve before any Miller-Rabin modexp is spent. 2^14 is the measured
# cost optimum on this box: the per-draw gcd fold grows linearly with
# the primorial while each avoided composite saves one ~0.43 ms MR
# modexp — past ~2^14 the fold costs more than the MR calls it saves.
# The verify-side small-factor gate (correct_key) keeps the documented
# 4000 bound — widening it would change the acceptance predicate on the
# wire.
_WIDE_LIMIT = 1 << 14
_PRIMORIAL_WIDE = None
_SIEVE_CACHE: dict = {}


def _wide_primorial() -> int:
    global _PRIMORIAL_WIDE
    if _PRIMORIAL_WIDE is None:
        _PRIMORIAL_WIDE = _primorial(_WIDE_LIMIT)
    return _PRIMORIAL_WIDE


def _sieve_for_bits(bits: int):
    """(primorial, cached GMP operand or None) for the generation sieve
    at this candidate width. The sieve bound must lie strictly BELOW the
    smallest candidate 3*2^(bits-2): a bound at or past it would reject
    every prime in the range as 'divides the primorial' and spin the
    search forever (the bound is capped, never raised, for small bits).
    The operand is a cached mpz import of a public value (no wipe
    needed; see native.gmp.PublicOperand)."""
    lo = 3 << (bits - 2)
    bound = min(_WIDE_LIMIT, lo)
    ent = _SIEVE_CACHE.get(bound)
    if ent is None:
        prim = _wide_primorial() if bound == _WIDE_LIMIT else _primorial(bound)
        from ..native import gmp

        ent = (prim, gmp.PublicOperand(prim))
        _SIEVE_CACHE[bound] = ent
    return ent


def _mr_rounds(n: int, rounds: int, powm=pow) -> bool:
    """Miller-Rabin rounds with CSPRNG witnesses over an arbitrary powm
    engine (CPython pow, or native.gmp.powm for the batched generation
    pipeline) — the ONE copy of the witness/decompose/square-
    continuation logic, so engines cannot drift semantically."""
    d = n - 1
    r = (d & -d).bit_length() - 1
    d >>= r
    for _ in range(rounds):
        a = 2 + secrets.randbelow(n - 3)
        x = powm(a, d, n)
        if x == 1 or x == n - 1:
            continue
        for _ in range(r - 1):
            x = (x * x) % n
            if x == n - 1:
                break
        else:
            return False
    return True


def is_probable_prime(n: int, rounds: int = 30) -> bool:
    """Miller-Rabin with `rounds` random bases (error <= 4^-rounds).

    Dispatches to the native Montgomery core (fsdkr_tpu.native, the
    rebuild's GMP-equivalent) when available; the pure-Python path
    (_mr_rounds) is the fallback and differential oracle."""
    if n < 2:
        return False
    for small in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37):
        if n % small == 0:
            return n == small

    from .. import native

    verdict = native.is_probable_prime(n, rounds)
    if verdict is not None:
        return verdict
    return _mr_rounds(n, rounds)


def _mr_batch(cands, rounds: int):
    """Batched Miller-Rabin with CSPRNG witnesses: the GMP powm ladder
    when the bridge is up (candidates split across an FSDKR_THREADS
    thread pool — ctypes releases the GIL around each mpz_powm), the
    native FSDKR_THREADS row-pool batch otherwise (one staging + one
    native call per window — the per-call bridge overhead of the old
    candidate loop was most of its wall-clock), per-candidate Python as
    the last fallback. Verdicts are engine-independent (same test, same
    witness distribution)."""
    from ..native import gmp

    if gmp.available():
        nt = min(gmp._pool_threads(), len(cands))
        if nt > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=nt) as ex:
                return list(
                    ex.map(
                        lambda c: _mr_rounds(c, rounds, powm=gmp.powm), cands
                    )
                )
        return [_mr_rounds(c, rounds, powm=gmp.powm) for c in cands]
    from .. import native

    verdicts = native.is_probable_prime_batch(cands, rounds)
    if verdicts is None:
        verdicts = [is_probable_prime(c, rounds) for c in cands]
    return verdicts


def gen_primes_batch(bits: int, count: int) -> list:
    """`count` independent random primes with exactly `bits` bits and the
    top two bits set (see gen_prime for why). The pipeline is windowed:
    draw a window of independent CSPRNG candidates, reject by one gcd
    against the wide primorial, run ONE native MR(1) batch over the
    window (candidates split across the FSDKR_THREADS row pool), then
    one 29-round confirmation batch over the survivors. Candidate
    distribution is identical to the serial loop — every candidate is an
    independent uniform draw, windows only change call granularity."""
    if bits < 8:
        raise ValueError("prime too small")
    sieve = _sieve_for_bits(bits)[1]
    found: list = []
    while len(found) < count:
        need = count - len(found)
        # ~bits/28 sieved survivors per prime expected; mild over-draw,
        # the loop refills on shortfall
        target = need * max(4, bits // 28 + 2)
        from ..native import gmp

        # GMP's subquadratic gcd against the cached-import primorial is
        # ~10x CPython's Euclid here (gmp.gcd itself falls back to
        # math.gcd when the bridge is down)
        cands = []
        while len(cands) < target:
            c = (
                secrets.randbits(bits)
                | (1 << (bits - 1))
                | (1 << (bits - 2))
                | 1
            )
            if gmp.gcd(c, sieve) == 1:
                cands.append(c)
        gen = _gen_metric()
        gen.inc(len(cands), event="candidates")
        # one cheap round first: almost every sieved composite dies here
        pre = _mr_batch(cands, 1)
        gen.inc(len(cands), event="mr_rounds")
        survivors = [c for c, v in zip(cands, pre) if v]
        if not survivors:
            continue
        conf = _mr_batch(survivors, 29)
        gen.inc(29 * len(survivors), event="mr_rounds")
        found += [c for c, v in zip(survivors, conf) if v]
    return found[:count]


def gen_prime(bits: int) -> int:
    """Random prime with exactly `bits` bits and the top two bits set.

    Forcing the two leading bits guarantees a product of two such primes has
    exactly 2*bits bits, satisfying the reference's moduli acceptance gate of
    [2*bits - 1, 2*bits] (`/root/reference/src/refresh_message.rs:385-391`).
    """
    return gen_primes_batch(bits, 1)[0]


def gen_moduli_batch(modulus_bits: int, count: int) -> list:
    """`count` moduli (n, p, q) with n = p*q of `modulus_bits` bits,
    p != q — all 2*count primes generated through one batched pipeline
    (the cross-sender keygen axis of distribute_batch)."""
    if modulus_bits % 2:
        raise ValueError("modulus_bits must be even")
    half = modulus_bits // 2
    ps = gen_primes_batch(half, 2 * count)
    out = []
    for k in range(count):
        p, q = ps[2 * k], ps[2 * k + 1]
        while q == p:  # astronomically unlikely; regenerate q
            q = gen_prime(half)
        out.append((p * q, p, q))
    return out


def gen_modulus(modulus_bits: int) -> tuple[int, int, int]:
    """Generate (n, p, q) with n = p*q of `modulus_bits` bits, p != q."""
    return gen_moduli_batch(modulus_bits, 1)[0]
