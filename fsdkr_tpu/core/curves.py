"""Generic short-Weierstrass curve layer (host oracle).

The reference is generic over the curve `E` (`curv::elliptic::Curve`,
`/root/reference/src/refresh_message.rs:31`); this module provides the
equivalent capability for the rebuild: `make_curve(params)` manufactures a
(Scalar, Point, GENERATOR) triple for any y^2 = x^3 + ax + b group, and
`get_curve(name)` serves registered instances.

secp256k1 is NOT built here — `core.secp256k1` is its specialized fast
path (a=0 shortcuts) and the differential oracle for the batched device
kernels (`ops.ec_batch`); `get_curve("secp256k1")` returns that module's
classes so there is exactly one secp256k1 Point type in the process.
Other curves (secp256r1/P-256 registered below) run host-side through the
generic classes: the protocol layer stays specialized to secp256k1 (see
ProtocolConfig.curve), matching how the reference's test/consumer code
pins `E = Secp256k1`, while the primitives — VSS, Shamir, transcripts,
ECDSA — work over any registered curve.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass
from types import SimpleNamespace

__all__ = ["CurveParams", "make_curve", "get_curve", "register_curve", "SECP256R1"]


@dataclass(frozen=True)
class CurveParams:
    name: str
    p: int  # field prime
    n: int  # group order (prime)
    a: int
    b: int
    gx: int
    gy: int


def make_curve(params: CurveParams) -> SimpleNamespace:
    """Manufacture Scalar/Point classes bound to `params`. The API mirrors
    core.secp256k1 exactly (Scalar arithmetic mod n, affine Point with
    identity, compressed encoding, Jacobian scalar mul) so generic code can
    take either."""
    P, N, A, B = params.p, params.n, params.a, params.b

    def _inv(x: int, m: int) -> int:
        return pow(x, -1, m)

    class Scalar:
        __slots__ = ("v",)

        def __init__(self, v: int):
            object.__setattr__(self, "v", v % N)

        def __setattr__(self, *_):
            raise AttributeError("Scalar is immutable")

        @staticmethod
        def random() -> "Scalar":
            while True:
                v = secrets.randbelow(N)
                if v:
                    return Scalar(v)

        @staticmethod
        def from_int(x: int) -> "Scalar":
            return Scalar(x % N)

        @staticmethod
        def zero() -> "Scalar":
            return Scalar(0)

        def to_int(self) -> int:
            return self.v

        def __eq__(self, other):
            return isinstance(other, Scalar) and self.v == other.v

        def __hash__(self):
            return hash((params.name, self.v))

        def __add__(self, other):
            if not isinstance(other, Scalar):
                return NotImplemented
            return Scalar(self.v + other.v)

        def __sub__(self, other):
            if not isinstance(other, Scalar):
                return NotImplemented
            return Scalar(self.v - other.v)

        def __mul__(self, other):
            if not isinstance(other, Scalar):
                return NotImplemented  # Scalar * Point -> Point.__rmul__
            return Scalar(self.v * other.v)

        def __neg__(self):
            return Scalar(-self.v)

        def invert(self) -> "Scalar":
            return Scalar(_inv(self.v, N))

        def __bool__(self):
            return self.v != 0

        def __repr__(self):
            return f"Scalar<{params.name}>({hex(self.v)[:12]}...)"

    class Point:
        __slots__ = ("x", "y", "infinity")

        def __init__(self, x: int | None, y: int | None):
            if x is None:
                self.x, self.y, self.infinity = 0, 0, True
            else:
                self.x, self.y, self.infinity = x, y, False

        @staticmethod
        def identity() -> "Point":
            return Point(None, None)

        @staticmethod
        def generator() -> "Point":
            return GENERATOR

        @staticmethod
        def from_bytes(b: bytes) -> "Point":
            size = (P.bit_length() + 7) // 8
            if b == b"\x00":
                return Point.identity()
            if len(b) == 1 + 2 * size and b[0] == 4:  # uncompressed
                x = int.from_bytes(b[1 : 1 + size], "big")
                y = int.from_bytes(b[1 + size :], "big")
                if x >= P or y >= P:
                    raise ValueError("coordinate not canonical")
                if (y * y - (pow(x, 3, P) + A * x + B)) % P:
                    raise ValueError("point not on curve")
                return Point(x, y)
            if len(b) != 1 + size or b[0] not in (2, 3):
                raise ValueError("bad point encoding")
            x = int.from_bytes(b[1:], "big")
            if x >= P:
                raise ValueError("x coordinate not canonical")
            rhs = (pow(x, 3, P) + A * x + B) % P
            if P % 4 != 3:  # all registered curves use p = 3 mod 4
                raise ValueError("unsupported field for sqrt")
            y = pow(rhs, (P + 1) // 4, P)
            if (y * y) % P != rhs:
                raise ValueError("point not on curve")
            if (y & 1) != (b[0] & 1):
                y = P - y
            return Point(x, y)

        def to_bytes(self, compressed: bool = True) -> bytes:
            size = (P.bit_length() + 7) // 8
            if self.infinity:
                return b"\x00"
            if compressed:
                return bytes([2 | (self.y & 1)]) + self.x.to_bytes(size, "big")
            return (
                b"\x04"
                + self.x.to_bytes(size, "big")
                + self.y.to_bytes(size, "big")
            )

        def x_coord(self) -> int:
            if self.infinity:
                raise ValueError("identity has no coordinates")
            return self.x

        def y_coord(self) -> int:
            if self.infinity:
                raise ValueError("identity has no coordinates")
            return self.y

        def __eq__(self, other):
            if not isinstance(other, Point):
                return NotImplemented
            if self.infinity or other.infinity:
                return self.infinity == other.infinity
            return self.x == other.x and self.y == other.y

        def __hash__(self):
            return hash((params.name, self.infinity, self.x, self.y))

        def __add__(self, other: "Point") -> "Point":
            if self.infinity:
                return other
            if other.infinity:
                return self
            if self.x == other.x:
                if (self.y + other.y) % P == 0:
                    return Point.identity()
                return self._double()
            lam = ((other.y - self.y) * _inv(other.x - self.x, P)) % P
            x3 = (lam * lam - self.x - other.x) % P
            y3 = (lam * (self.x - x3) - self.y) % P
            return Point(x3, y3)

        def _double(self) -> "Point":
            if self.infinity or self.y == 0:
                return Point.identity()
            lam = ((3 * self.x * self.x + A) * _inv(2 * self.y, P)) % P
            x3 = (lam * lam - 2 * self.x) % P
            y3 = (lam * (self.x - x3) - self.y) % P
            return Point(x3, y3)

        def __neg__(self) -> "Point":
            if self.infinity:
                return self
            return Point(self.x, (-self.y) % P)

        def __sub__(self, other: "Point") -> "Point":
            return self + (-other)

        def __mul__(self, scalar) -> "Point":
            k = scalar.v if isinstance(scalar, Scalar) else int(scalar) % N
            if k == 0 or self.infinity:
                return Point.identity()
            # Jacobian double-and-add: one field inversion total
            rx, ry, rz = 0, 1, 0
            px, py = self.x, self.y
            for bit in bin(k)[2:]:
                rx, ry, rz = _jdouble(rx, ry, rz)
                if bit == "1":
                    rx, ry, rz = _jadd_affine(rx, ry, rz, px, py)
            if rz == 0:
                return Point.identity()
            zinv = _inv(rz, P)
            z2 = (zinv * zinv) % P
            return Point((rx * z2) % P, (ry * z2 % P) * zinv % P)

        __rmul__ = __mul__

        def __repr__(self):
            if self.infinity:
                return f"Point<{params.name}>(identity)"
            return f"Point<{params.name}>(x={hex(self.x)[:12]}...)"

    def _jdouble(x, y, z):
        # general-a Jacobian doubling: M = 3x^2 + a*z^4
        if z == 0 or y == 0:
            return 0, 1, 0
        ysq = (y * y) % P
        s = (4 * x * ysq) % P
        zsq = (z * z) % P
        m = (3 * x * x + A * zsq % P * zsq) % P
        x3 = (m * m - 2 * s) % P
        y3 = (m * (s - x3) - 8 * ysq * ysq) % P
        z3 = (2 * y * z) % P
        return x3, y3, z3

    def _jadd_affine(x1, y1, z1, x2, y2):
        # mixed Jacobian+affine addition (a-independent)
        if z1 == 0:
            return x2, y2, 1
        z1z1 = (z1 * z1) % P
        u2 = (x2 * z1z1) % P
        s2 = (y2 * z1 * z1z1) % P
        if x1 == u2:
            if y1 != s2:
                return 0, 1, 0
            return _jdouble(x1, y1, z1)
        h = (u2 - x1) % P
        hh = (h * h) % P
        i = (4 * hh) % P
        j = (h * i) % P
        r = (2 * (s2 - y1)) % P
        v = (x1 * i) % P
        x3 = (r * r - j - 2 * v) % P
        y3 = (r * (v - x3) - 2 * y1 * j) % P
        z3 = (2 * h * z1) % P
        return x3, y3, z3

    # --- parameter validation: unsupported curves fail HERE, not at the
    # first from_bytes / share-combine deep inside a protocol run -------
    if P % 4 != 3:
        # compressed-point decode uses the p = 3 (mod 4) square root
        # shortcut; reject at registration rather than on first decode
        raise ValueError(
            f"{params.name}: field prime must be 3 mod 4 (compressed-point "
            "sqrt); Tonelli-Shanks fields are unsupported"
        )
    if (params.gy**2 - (params.gx**3 + A * params.gx + B)) % P:
        raise ValueError(f"{params.name}: generator not on curve")
    # cofactor-1 check (the VSS/ECDSA layers assume a prime-order group
    # with no small subgroup): ord(G) | #E and n*G = identity with n prime
    # gives ord(G) = n; Hasse bounds #E <= p + 1 + 2*sqrt(p), so
    # 2n > p + 1 + 2*sqrt(p) forces #E = n exactly (cofactor 1).
    import math

    if 2 * N <= P + 1 + 2 * math.isqrt(P) + 1:
        raise ValueError(
            f"{params.name}: group order too small for a cofactor-1 curve"
        )

    GENERATOR = Point(params.gx, params.gy)
    # ord(G) == n without tripping Scalar's mod-n reduction (G * n would
    # compute 0*G and pass for ANY n): (n-1)*G + G must be the identity
    if not ((GENERATOR * (N - 1)) + GENERATOR).infinity:
        raise ValueError(f"{params.name}: generator order is not n")
    return SimpleNamespace(
        name=params.name,
        params=params,
        P=P,
        N=N,
        CURVE_ORDER=N,
        Scalar=Scalar,
        Point=Point,
        GENERATOR=GENERATOR,
    )


SECP256R1 = CurveParams(
    name="secp256r1",
    p=0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF,
    n=0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551,
    a=-3,
    b=0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B,
    gx=0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296,
    gy=0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5,
)

_REGISTRY: dict = {}


def register_curve(params: CurveParams) -> None:
    _REGISTRY[params.name] = make_curve(params)


register_curve(SECP256R1)


def get_curve(name: str):
    """Registered curve namespace (P, N, Scalar, Point, GENERATOR)."""
    if name == "secp256k1":
        from . import secp256k1

        return secp256k1
    if name not in _REGISTRY:
        raise ValueError(f"unknown curve {name!r}")
    return _REGISTRY[name]
