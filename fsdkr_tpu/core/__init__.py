"""Host-side cryptographic core: the capability surface the reference gets
from `curv` (bigint + secp256k1 + Feldman VSS + hashing) and `kzen-paillier`
(see SURVEY.md §2b). Pure Python over CPython ints — this layer is the
correctness oracle for the TPU limb kernels in `fsdkr_tpu.ops`.
"""

from . import intops, primes, transcript, secp256k1, paillier, vss

__all__ = ["intops", "primes", "transcript", "secp256k1", "paillier", "vss"]
