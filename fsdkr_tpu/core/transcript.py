"""Fiat-Shamir transcript hashing (SHA-256).

Provides the `curv` `Digest`/`DigestExt` capability the reference uses for
every NIZK challenge (`chain_bigint` / `result_bigint`, usage e.g.
`/root/reference/src/range_proofs.rs:150-157`,
`src/zk_pdl_with_slack.rs:87-95`, `src/ring_pedersen_proof.rs:96-105`).

This framework defines its own canonical encoding (SURVEY.md §7 step 2):
each chained value is hashed as a 4-byte big-endian length prefix followed
by its minimal big-endian magnitude bytes. The length prefix removes the
concatenation ambiguity of the reference's raw-byte chaining; prover and
verifier only ever need to agree with each other, not with the Rust wire
format.

Challenge-bit extraction replicates the reference's semantics
(`bitvec` Lsb0 over the digest bytes, `src/ring_pedersen_proof.rs:106,136`):
bit i of the challenge is bit (i % 8) of digest byte (i // 8), with the
digest taken as exactly 32 big-endian bytes.
"""

from __future__ import annotations

import hashlib

__all__ = ["Transcript", "hash_ints", "challenge_bits"]


class Transcript:
    """SHA-256 transcript over a sequence of non-negative integers / bytes."""

    def __init__(self, domain: bytes = b""):
        self._h = hashlib.sha256()
        if domain:
            self.chain_bytes(domain)

    def chain_bytes(self, b: bytes) -> "Transcript":
        self._h.update(len(b).to_bytes(4, "big"))
        self._h.update(b)
        return self

    def chain_int(self, x: int) -> "Transcript":
        if x < 0:
            raise ValueError("transcript integers must be non-negative")
        return self.chain_bytes(x.to_bytes((x.bit_length() + 7) // 8, "big"))

    def chain_point(self, point) -> "Transcript":
        """Chain a curve point via its compressed encoding, as the reference
        hashes `to_bytes(true)` (`src/zk_pdl_with_slack.rs:88-92`)."""
        return self.chain_bytes(point.to_bytes(compressed=True))

    def result_int(self) -> int:
        return int.from_bytes(self._h.digest(), "big")

    def result_bytes(self) -> bytes:
        return self._h.digest()


def hash_ints(values, domain: bytes = b"") -> int:
    t = Transcript(domain)
    for v in values:
        t.chain_int(v)
    return t.result_int()


def challenge_bits(e: int, m: int) -> list[int]:
    """Extract m binary challenges from challenge integer e, Lsb0 order over
    the 32-byte big-endian digest representation
    (reference: `src/ring_pedersen_proof.rs:106`)."""
    if m > 256:
        raise ValueError("SHA-256 transcripts yield at most 256 challenge bits")
    raw = e.to_bytes(32, "big")
    return [(raw[i >> 3] >> (i & 7)) & 1 for i in range(m)]
