"""Fiat-Shamir transcript hashing (pluggable digest, SHA-256 default).

Provides the `curv` `Digest`/`DigestExt` capability the reference uses for
every NIZK challenge (`chain_bigint` / `result_bigint`, usage e.g.
`/root/reference/src/range_proofs.rs:150-157`,
`src/zk_pdl_with_slack.rs:87-95`, `src/ring_pedersen_proof.rs:96-105`).
The reference is generic over the digest (`HashChoice<H>`, a per-message
type parameter, `src/refresh_message.rs:31,46-47`); here the equivalent
knob is `ProtocolConfig.hash_alg`, threaded BY PARAMETER from the
protocol entry points through every proof's prove/verify into
`Transcript(algorithm=...)` / `challenge_bits(..., algorithm)` — so
sessions with different digests coexist and interleave in one process,
matching the reference's per-instance binding. Wider digests (sha512,
sha3_512, blake2b) raise the ring-Pedersen challenge capacity above 256
rounds.

`set_hash_algorithm` installs only the process-wide DEFAULT, used when a
proof is proven/verified standalone without an explicit algorithm (e.g.
ad-hoc after deserialization). Protocol-layer correctness never depends
on it.

This framework defines its own canonical encoding (SURVEY.md §7 step 2):
each chained value is hashed as a 4-byte big-endian length prefix followed
by its minimal big-endian magnitude bytes. The length prefix removes the
concatenation ambiguity of the reference's raw-byte chaining; prover and
verifier only ever need to agree with each other, not with the Rust wire
format.

Challenge-bit extraction replicates the reference's semantics
(`bitvec` Lsb0 over the digest bytes, `src/ring_pedersen_proof.rs:106,136`):
bit i of the challenge is bit (i % 8) of digest byte (i // 8), with the
digest taken as exactly 32 big-endian bytes.
"""

from __future__ import annotations

import hashlib

__all__ = [
    "Transcript",
    "hash_ints",
    "challenge_bits",
    "set_hash_algorithm",
    "get_hash_algorithm",
    "digest_bytes",
]

# name -> (constructor, digest size in bytes); blake2b at its native 64
_HASHES = {
    "sha256": (hashlib.sha256, 32),
    "sha384": (hashlib.sha384, 48),
    "sha512": (hashlib.sha512, 64),
    "sha3_256": (hashlib.sha3_256, 32),
    "sha3_512": (hashlib.sha3_512, 64),
    "blake2b": (hashlib.blake2b, 64),
}

_active = "sha256"


def set_hash_algorithm(name: str) -> None:
    """Install the process-wide transcript digest (ProtocolConfig.hash_alg)."""
    if name not in _HASHES:
        raise ValueError(f"unknown hash_alg {name!r}; choose from {sorted(_HASHES)}")
    global _active
    _active = name


def get_hash_algorithm() -> str:
    return _active


def digest_bytes(algorithm: str | None = None) -> int:
    name = algorithm or _active
    if name not in _HASHES:
        raise ValueError(f"unknown hash_alg {name!r}; choose from {sorted(_HASHES)}")
    return _HASHES[name][1]


class Transcript:
    """Transcript over a sequence of non-negative integers / bytes, using
    the active digest (default SHA-256)."""

    def __init__(self, domain: bytes = b"", algorithm: str | None = None):
        digest_bytes(algorithm)  # uniform ValueError on unknown names
        self._h = _HASHES[algorithm or _active][0]()
        if domain:
            self.chain_bytes(domain)

    def chain_bytes(self, b: bytes) -> "Transcript":
        self._h.update(len(b).to_bytes(4, "big"))
        self._h.update(b)
        return self

    def chain_int(self, x: int) -> "Transcript":
        if x < 0:
            raise ValueError("transcript integers must be non-negative")
        return self.chain_bytes(x.to_bytes((x.bit_length() + 7) // 8, "big"))

    def chain_point(self, point) -> "Transcript":
        """Chain a curve point via its compressed encoding, as the reference
        hashes `to_bytes(true)` (`src/zk_pdl_with_slack.rs:88-92`)."""
        return self.chain_bytes(point.to_bytes(compressed=True))

    def result_int(self) -> int:
        return int.from_bytes(self._h.digest(), "big")

    def result_challenge(self, bits: int = 256) -> int:
        """Digest truncated to a fixed challenge width. The integer-
        challenge sigma protocols (range, PDL, composite-dlog) size their
        blinding/range gates for a 256-bit challenge (q^3 slack,
        STAT_BITS); a wider configured digest must not widen e, or
        honest s1 = e*a + alpha overflows the verifier's range gate and
        integer responses lose statistical hiding. For sha256 this is
        the identity, preserving reference-exact challenges."""
        return self.result_int() & ((1 << bits) - 1)

    def result_bytes(self) -> bytes:
        return self._h.digest()


def hash_ints(values, domain: bytes = b"") -> int:
    t = Transcript(domain)
    for v in values:
        t.chain_int(v)
    return t.result_int()


def challenge_bits(e: int, m: int, algorithm: str | None = None) -> list[int]:
    """Extract m binary challenges from challenge integer e, Lsb0 order over
    the big-endian digest representation of the active hash
    (reference: `src/ring_pedersen_proof.rs:106`)."""
    size = digest_bytes(algorithm)
    if m > 8 * size:
        raise ValueError(
            f"{algorithm or _active} transcripts yield at most {8 * size} "
            "challenge bits"
        )
    raw = e.to_bytes(size, "big")
    return [(raw[i >> 3] >> (i & 7)) & 1 for i in range(m)]
