"""Secret-flow taint pass: no secret carrier may reach a persistence or
export sink without a declared sanitizer in between.

SECURITY.md names the secret carriers this repo handles — LocalKeys
(``paillier_dk``, ``keys_linear``), Paillier ``DecryptionKey`` p/q,
Shamir shares, precompute ``PoolEntry`` payloads, CRT contexts, and
``MemoryKeystore`` deposits — and thirteen discipline sections promise
they never reach the public surfaces: the journal, the ingress wire,
telemetry labels/attrs/flight fields, the public LRU, logs, or bench
JSON. Until now every one of those promises was enforced only by
runtime grep tests over the paths a test happens to exercise. This pass
checks the promise on every code path, mechanically.

Model (deliberately intra-procedural — the planted-violation fixtures
in tests/test_analysis.py pin exactly what it must catch):

- **Sources.** A name becomes tainted when bound from: a parameter
  whose name is a known secret carrier (``dk``, ``dks``, ``local_key``,
  ``keys`` ...); a call returning secret material (``paillier.keygen``,
  ``simulate_keygen``, pool ``take``, keystore getters, CRT context
  constructors); or an attribute access naming a secret field
  (``.paillier_dk``, ``.keys_linear``, ``.dk``, a DecryptionKey's
  ``.p``/``.q`` — matched only through an already-tainted base for the
  ambiguous short names, so a curve's public ``.p`` stays clean).
- **Propagation.** Assignment, tuple unpack, f-strings, str/repr/hex,
  containers, subscripts, attributes of tainted bases, loop variables
  over tainted iterables, and augmented assignment all carry taint.
  Ordinary calls do NOT propagate (a hash, a length, a count of a
  secret is public by this codebase's rules) — the sanitizer set is the
  default, not the exception, which keeps the pass quiet on the 100+
  legitimate secret *computations* per module.
- **Sinks.** A tainted expression in an argument (or keyword) of:
  ``journal.append`` / ``_jappend*``; ``encode_frame`` / ``_write_frame``
  / ``sendall`` (ingress wire); ``.labels(...)`` / ``flight.record`` /
  ``telemetry.phase(**attrs)`` / metric ``set``/``inc``/``observe``
  (telemetry); the public LRU's ``cache.put`` / ``global_cache().put``;
  ``logging``/``print``; ``json.dump(s)`` (bench/report emission).

Findings name the flow: source name, sink kind, line.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional

from .common import Finding, ProjectIndex, SourceFile, dotted_name, \
    iter_functions

__all__ = ["run", "RULES"]

RULES = ("secret-flow",)

# parameters that carry secret material by this codebase's naming
# conventions (SECURITY.md carriers; 'key'/'keys' of dict-key fame are
# disambiguated: bare `key` is NOT here, `keys` — always LocalKeys in
# this repo — is)
SECRET_PARAMS = {
    "dk", "dks", "new_dk", "dk_new", "paillier_dk", "local_key",
    "local_keys", "keys", "new_keys", "secret", "secrets", "shares",
    "new_shares", "crt_ctx", "secret_values",
}

# calls whose results are secret carriers (match on the dotted tail)
_SOURCE_CALL_RE = re.compile(
    r"(^|\.)(keygen|keygen_batch|simulate_keygen|take|committee_keys|"
    r"session_dks|get_context|secret_values|sample_stage1|"
    r"sample_commit)(\(\))?$"
)

# attribute names that are secret on ANY base
SECRET_ATTRS_ALWAYS = {"paillier_dk", "keys_linear", "new_dk"}
# attribute names that are secret only on an already-tainted base
SECRET_ATTRS_TAINTED_BASE = {"p", "q", "dk", "x_i", "p_leg", "q_leg",
                             "d_p", "d_q", "qinv", "values"}
# PUBLIC fields of the secret carriers: reading one of these off a
# tainted base yields broadcast-public data (the LocalKey dataclass
# split — SECURITY.md's "queue holds public data only" rule depends on
# exactly these fields being safe to export)
PUBLIC_ATTRS = {"t", "n", "i", "nn", "ek", "pk_vec", "y_sum_s",
                "paillier_key_vec", "h1_h2_n_tilde_vec", "vss_scheme",
                "modulus"}

# calls that launder taint explicitly (results clean; being the
# argument of one of these is NOT a sink) — hashing, counting, wiping
_CLEAN_CALL_RE = re.compile(
    r"(^|\.)(len|bool|type|id|hash|sha256|sha512|blake2b|hexdigest|"
    r"digest|fingerprint|shard_for|check_label_value|sanitize_fields|"
    r"zeroize\w*|wipe\w*|secure_wipe|bit_length)$"
)

# builtins/conversions that PROPAGATE taint through their result
_PROPAGATE_CALLS = {
    "str", "repr", "hex", "oct", "bytes", "bytearray", "int", "list",
    "tuple", "set", "dict", "sorted", "reversed", "format", "vars",
    "deepcopy", "copy.deepcopy", "copy.copy", "abs", "pow", "divmod",
}

_LOG_METHODS = {"debug", "info", "warning", "error", "exception",
                "critical", "log"}


def _sink_kind(call: ast.Call, index: ProjectIndex) -> Optional[str]:
    """Classify a call as a sink. Returns a short kind or None."""
    name = dotted_name(call.func)
    if not name:
        return None
    parts = name.split(".")
    meth = parts[-1]
    recv = ".".join(parts[:-1])
    recv_last = parts[-2].rstrip("()") if len(parts) > 1 else ""

    if meth == "append" and len(parts) > 1:
        cls = index.receiver_class(recv)
        if cls == "Journal" or "journal" in recv_last.lower():
            return "journal append"
    if meth in ("_jappend", "_jappend_safe"):
        return "journal append"
    if meth in ("encode_frame", "_write_frame", "sendall", "send") \
            and (meth != "send" or "sock" in recv_last.lower()
                 or "conn" in recv_last.lower()
                 or "transport" in recv_last.lower()):
        return "wire frame"
    if meth == "labels":
        return "telemetry label"
    if meth == "record" and recv_last in ("flight", ""):
        return "flight-recorder field"
    if meth == "phase" and recv_last in ("telemetry", "spans", "tracer",
                                         "_tracer") and call.keywords:
        return "span attribute"
    if meth in ("set", "inc", "observe") and (
            "gauge" in recv.lower() or "counter" in recv.lower()
            or "hist" in recv.lower() or "metric" in recv.lower()):
        return "telemetry metric"
    if meth == "put" and len(parts) > 1:
        cls = index.receiver_class(recv)
        low = recv_last.lower()
        if cls == "BudgetLRU" or "cache" in low or "lru" in low \
                or recv.endswith("global_cache()"):
            return "public LRU"
    if parts[0] in ("logging", "log", "logger") and meth in _LOG_METHODS:
        return "log"
    if meth == "print" and len(parts) == 1:
        return "log"
    if name in ("json.dump", "json.dumps"):
        return "JSON emission"
    return None


class _FnTaint(ast.NodeVisitor):
    def __init__(self, sf: SourceFile, fn: ast.AST,
                 index: ProjectIndex, findings: List[Finding]):
        self.sf = sf
        self.fn = fn
        self.index = index
        self.findings = findings
        self.tainted: Dict[str, str] = {}  # name -> source description
        args = fn.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            if a.arg in SECRET_PARAMS:
                self.tainted[a.arg] = f"parameter {a.arg!r}"

    # -- expression taint ----------------------------------------------
    def taint_of(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            return self.tainted.get(node.id)
        if isinstance(node, ast.Attribute):
            if node.attr in SECRET_ATTRS_ALWAYS:
                return f"secret field .{node.attr}"
            base = self.taint_of(node.value)
            if base is None:
                return None
            if node.attr in PUBLIC_ATTRS:
                return None  # the carrier's declared-public fields
            if node.attr in SECRET_ATTRS_TAINTED_BASE:
                return f"{base}.{node.attr}"
            return base
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            if _CLEAN_CALL_RE.search(name):
                return None
            if _SOURCE_CALL_RE.search(name):
                return f"call {name.split('.')[-1]}()"
            if name in _PROPAGATE_CALLS or \
                    name.split(".")[-1] in _PROPAGATE_CALLS:
                for a in node.args:
                    t = self.taint_of(a)
                    if t:
                        return t
            return None
        if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            for e in node.elts:
                t = self.taint_of(e)
                if t:
                    return t
            return None
        if isinstance(node, ast.Dict):
            for e in list(node.keys) + list(node.values):
                if e is None:
                    continue
                t = self.taint_of(e)
                if t:
                    return t
            return None
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    t = self.taint_of(v.value)
                    if t:
                        return t
            return None
        if isinstance(node, ast.FormattedValue):
            return self.taint_of(node.value)
        if isinstance(node, ast.BinOp):
            return self.taint_of(node.left) or self.taint_of(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.taint_of(node.operand)
        if isinstance(node, ast.Subscript):
            return self.taint_of(node.value)
        if isinstance(node, ast.Starred):
            return self.taint_of(node.value)
        if isinstance(node, ast.IfExp):
            return self.taint_of(node.body) or self.taint_of(node.orelse)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            # comprehension over a tainted iterable yields tainted items
            for gen in node.generators:
                t = self.taint_of(gen.iter)
                if t:
                    return t
            return self.taint_of(node.elt)
        return None

    # -- statements ----------------------------------------------------
    def _bind(self, target: ast.AST, taint: Optional[str]) -> None:
        if isinstance(target, ast.Name):
            if taint:
                self.tainted[target.id] = taint
            else:
                self.tainted.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, taint)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, taint)

    def visit_Assign(self, node: ast.Assign) -> None:
        t = self.taint_of(node.value)
        for target in node.targets:
            self._bind(target, t)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._bind(node.target, self.taint_of(node.value))
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        t = self.taint_of(node.value) or self.taint_of(node.target)
        self._bind(node.target, t)
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        self._bind(node.target, self.taint_of(node.iter))
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node is not self.fn:
            return  # nested functions get their own visitor
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        kind = _sink_kind(node, self.index)
        if kind:
            for arg in list(node.args) + [k.value for k in node.keywords]:
                t = self.taint_of(arg)
                if t:
                    self.findings.append(Finding(
                        self.sf.rel, node.lineno, "secret-flow",
                        f"secret ({t}) reaches {kind} without a "
                        "declared sanitizer",
                    ))
                    break
        self.generic_visit(node)


def run(files: List[SourceFile], index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        for qual, cls, fn in iter_functions(sf.tree):
            _FnTaint(sf, fn, index, findings).visit(fn)
    return findings
