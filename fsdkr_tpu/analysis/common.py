"""Shared infrastructure of the fsdkr-lint static-analysis framework.

Every pass (`taint`, `locks`, `knobs`, `imports`) consumes the same
parsed view of the tree — a list of :class:`SourceFile` (source text +
AST + parsed inline suppressions) plus a :class:`ProjectIndex` of
classes, their methods, and cheap receiver-type facts used by the lock
and taint passes to resolve ``self._journal.append(...)``-style calls.

Suppressions are in-code and auditable::

    something_flagged()  # fsdkr-lint: allow(lock-blocking-call) reason

A suppression covers findings of the named rule(s) on its own line or,
when the comment stands alone, on the next line. A suppression without
a reason is itself a finding (``suppression-missing-reason``): the
point of the mechanism is that known residuals stay *documented*.
"""

from __future__ import annotations

import ast
import pathlib
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set

__all__ = [
    "Finding",
    "SourceFile",
    "ProjectIndex",
    "load_files",
    "build_index",
    "dotted_name",
    "iter_functions",
]

_SUPPRESS_RE = re.compile(
    r"#\s*fsdkr-lint:\s*allow\(([a-z0-9_,\- ]+)\)\s*(.*)"
)


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class SourceFile:
    """One parsed source file: text, AST, and suppression map."""

    def __init__(self, path: pathlib.Path, root: pathlib.Path):
        self.path = path
        self.rel = path.relative_to(root).as_posix() if root in path.parents \
            or path == root else path.as_posix()
        self.text = path.read_text()
        self.tree = ast.parse(self.text, filename=str(path))
        self.module = self._module_name()
        # line -> set of allowed rules ("*" = all); parallel reason map
        self.suppressions: Dict[int, Set[str]] = {}
        self.suppression_reasons: Dict[int, str] = {}
        self._parse_suppressions()

    def _module_name(self) -> str:
        parts = self.path.with_suffix("").parts
        if "fsdkr_tpu" in parts:
            i = parts.index("fsdkr_tpu")
            mod = parts[i:]
            if mod[-1] == "__init__":
                mod = mod[:-1]
            return ".".join(mod)
        return self.path.stem

    def _parse_suppressions(self) -> None:
        lines = self.text.splitlines()
        for i, raw in enumerate(lines, start=1):
            m = _SUPPRESS_RE.search(raw)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = m.group(2).strip()
            # comment-only line covers the NEXT line; trailing comment
            # covers its own line
            target = i + 1 if raw.lstrip().startswith("#") else i
            self.suppressions.setdefault(target, set()).update(rules)
            if reason:
                self.suppression_reasons[target] = reason
            else:
                self.suppression_reasons.setdefault(target, "")

    def suppressed(self, line: int, rule: str) -> bool:
        allowed = self.suppressions.get(line)
        return bool(allowed) and (rule in allowed or "*" in allowed)

    def suppression_findings(self) -> List[Finding]:
        out = []
        for line, reason in sorted(self.suppression_reasons.items()):
            if not reason:
                out.append(Finding(
                    self.rel, line, "suppression-missing-reason",
                    "fsdkr-lint: allow(...) must carry a reason — "
                    "suppressions document residuals, not hide them",
                ))
        return out


def load_files(paths: Iterable[str], root: Optional[str] = None
               ) -> List[SourceFile]:
    rootp = pathlib.Path(root or ".").resolve()
    out: List[SourceFile] = []
    for p in paths:
        pp = pathlib.Path(p)
        if not pp.exists():
            raise FileNotFoundError(
                f"fsdkr-lint: no such path: {p} (a renamed root must fail "
                "the gate, not shrink its coverage)"
            )
        files = [pp] if pp.is_file() else sorted(pp.rglob("*.py"))
        for f in files:
            if "__pycache__" in f.parts:
                continue
            out.append(SourceFile(f.resolve(), rootp))
    return out


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, 'f().g' collapses the call:
    Call nodes contribute their func's dotted name + '()'."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else None
    if isinstance(node, ast.Call):
        base = dotted_name(node.func)
        return f"{base}()" if base else None
    return None


def iter_functions(tree: ast.Module):
    """Yield (qualname, class_name_or_None, funcdef) for every function
    and method, including nested ones (qualname carries the nesting)."""

    def walk(node, prefix: str, cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                yield q, cls, child
                yield from walk(child, q + ".", cls)
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{child.name}.", child.name)

    yield from walk(tree, "", None)


# ---------------------------------------------------------------------------
# project index: classes, methods, and receiver-type facts


@dataclass
class ClassInfo:
    module: str
    name: str
    methods: Dict[str, ast.AST] = field(default_factory=dict)

    @property
    def qual(self) -> str:
        return f"{self.module}.{self.name}"


class ProjectIndex:
    """Cross-file facts the passes share.

    - ``classes``: ClassName -> ClassInfo (last definition wins on the
      rare duplicate; passes that care disambiguate by module).
    - ``attr_types``: attribute/variable name -> class name, built from
      every ``x = ClassName(...)`` / ``self.x = ClassName(...)`` /
      ``x: ClassName`` in the project where the name->class mapping is
      UNIQUE project-wide. This is deliberately name-based: the codebase
      names instances after their class (``self._journal = Journal(...)``)
      and the passes only need "which class might this receiver be".
    """

    def __init__(self) -> None:
        self.classes: Dict[str, ClassInfo] = {}
        self.attr_types: Dict[str, str] = {}
        self._attr_candidates: Dict[str, Set[str]] = {}

    def note_binding(self, attr: str, cls: str) -> None:
        self._attr_candidates.setdefault(attr, set()).add(cls)

    def finalize(self) -> None:
        for attr, cands in self._attr_candidates.items():
            if len(cands) == 1:
                self.attr_types[attr] = next(iter(cands))

    def receiver_class(self, recv: str) -> Optional[str]:
        """Best-effort class of a receiver's last component: explicit
        binding first, then the instance-named-after-class convention
        (``_journal`` -> Journal)."""
        last = recv.split(".")[-1].rstrip("()")
        if last in self.attr_types:
            return self.attr_types[last]
        norm = last.lstrip("_").replace("_", "").lower()
        for cname in self.classes:
            if cname.lower() == norm:
                return cname
        return None


def _annotation_class(node: Optional[ast.AST]) -> Optional[str]:
    """Unwrap Optional[...]/'quoted' annotations down to a bare Name."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(node, ast.Subscript):
        return _annotation_class(node.slice)
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def build_index(files: List[SourceFile]) -> ProjectIndex:
    idx = ProjectIndex()
    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                info = ClassInfo(sf.module, node.name)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        info.methods[item.name] = item
                idx.classes[node.name] = info
    class_names = set(idx.classes)
    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                cls = dotted_name(node.value.func)
                cls = cls.split(".")[-1] if cls else None
                if cls in class_names:
                    for t in node.targets:
                        name = dotted_name(t)
                        if name:
                            idx.note_binding(name.split(".")[-1], cls)
            elif isinstance(node, ast.AnnAssign):
                cls = _annotation_class(node.annotation)
                if cls in class_names:
                    name = dotted_name(node.target)
                    if name:
                        idx.note_binding(name.split(".")[-1], cls)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for arg in (list(node.args.posonlyargs) + list(node.args.args)
                            + list(node.args.kwonlyargs)):
                    cls = _annotation_class(arg.annotation)
                    if cls in class_names:
                        idx.note_binding(arg.arg, cls)
    idx.finalize()
    return idx
