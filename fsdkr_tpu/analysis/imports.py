"""Import hygiene + layering pass (the former scripts/lint_imports.py).

Two rules:

- ``unused-import``: flags imports never referenced. Conservative by
  design — ``__all__`` entries, re-export modules (``__init__.py``),
  names starting with ``_``, and names referenced from quoted string
  annotations are exempt.
- ``layering``: `fsdkr_tpu/serving` is an orchestration layer and must
  reach the cryptography only through the protocol surface — importing
  ``proofs``, ``backend``, ``ops``, ``native``, or ``core`` internals
  from serving (absolute or relative) is a finding. Same for the new
  ``fsdkr_tpu/analysis`` package, which must stay importable without
  jax: it may import nothing from the package except ``telemetry`` (the
  flight recorder, for the runtime watchdog) — keeping the linter free
  of the engines it lints.
"""

from __future__ import annotations

import ast
import pathlib
from typing import List

from .common import Finding, SourceFile

__all__ = ["run", "RULES"]

RULES = ("unused-import", "layering")

# package-dir -> module prefixes its files must not import. Checked for
# every *.py under the directory, __init__.py included.
LAYERING_RULES = {
    "fsdkr_tpu/serving": (
        "fsdkr_tpu.proofs",
        "fsdkr_tpu.backend",
        "fsdkr_tpu.ops",
        "fsdkr_tpu.native",
        "fsdkr_tpu.core",
    ),
    "fsdkr_tpu/analysis": (
        # everything except telemetry (flight recorder, for lockwatch):
        # the linter must stay loadable without jax or the engines
        "fsdkr_tpu.proofs",
        "fsdkr_tpu.backend",
        "fsdkr_tpu.ops",
        "fsdkr_tpu.native",
        "fsdkr_tpu.core",
        "fsdkr_tpu.protocol",
        "fsdkr_tpu.serving",
        "fsdkr_tpu.precompute",
        "fsdkr_tpu.parallel",
        "fsdkr_tpu.utils",
    ),
}


def _abs_module(node: ast.ImportFrom, path: pathlib.Path) -> str:
    """Absolute dotted module of an ImportFrom, resolving relative
    imports against the file's package (CPython semantics: __package__
    is the containing package for BOTH regular modules and __init__.py,
    and level N strips N-1 trailing components from it)."""
    if node.level == 0:
        return node.module or ""
    parts = path.resolve().parts
    try:
        root = parts.index("fsdkr_tpu")
    except ValueError:
        return node.module or ""
    pkg = list(parts[root:-1])  # the module's package path
    base = pkg[: len(pkg) - (node.level - 1)] if node.level > 1 else pkg
    return ".".join(base + ([node.module] if node.module else []))


def _check_layering(sf: SourceFile) -> List[Finding]:
    rel = sf.rel
    rules = [
        (prefix, banned)
        for prefix, banned in LAYERING_RULES.items()
        if f"/{prefix}/" in f"/{rel}" or rel.startswith(prefix + "/")
    ]
    if not rules:
        return []
    findings = []
    for node in ast.walk(sf.tree):
        mods = []
        if isinstance(node, ast.Import):
            mods = [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom):
            mods = [_abs_module(node, sf.path)]
        for mod in mods:
            for prefix, banned in rules:
                layer = prefix.split("/")[-1]
                for b in banned:
                    if mod == b or mod.startswith(b + "."):
                        findings.append(Finding(
                            sf.rel, node.lineno, "layering",
                            f"{layer} must not import {mod!r} "
                            + ("(use the protocol surface)"
                               if layer == "serving"
                               else "(the linter must not import what "
                                    "it lints)"),
                        ))
    return findings


def _check_unused(sf: SourceFile) -> List[Finding]:
    if sf.path.name == "__init__.py":
        return []  # re-export wiring: imports are the point
    tree = sf.tree
    exported = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    try:
                        exported = set(ast.literal_eval(node.value))
                    except ValueError:
                        pass

    imported = {}  # name -> lineno
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                imported[name] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue  # compiler directives, not names
            for a in node.names:
                if a.name == "*":
                    continue
                imported[a.asname or a.name] = node.lineno

    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            # quoted annotations ('-> "ProtocolConfig"', TYPE_CHECKING
            # uses) reference names as strings: count their roots as used
            try:
                sub = ast.parse(node.value, mode="eval")
            except SyntaxError:
                continue
            for n in ast.walk(sub):
                if isinstance(n, ast.Name):
                    used.add(n.id)
        elif isinstance(node, ast.Attribute):
            # record the root of dotted access: jax.numpy -> jax
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)

    findings = []
    for name, lineno in sorted(imported.items(), key=lambda kv: kv[1]):
        if name in used or name in exported or name.startswith("_"):
            continue
        findings.append(Finding(
            sf.rel, lineno, "unused-import", f"unused import {name!r}"
        ))
    return findings


def run(files: List[SourceFile], index=None) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        findings += _check_layering(sf)
        findings += _check_unused(sf)
    return findings
