"""fsdkr-lint: AST-based static analysis of the fs-dkr-tpu tree.

Four passes over the whole package (driver: ``scripts/fsdkr_lint.py``,
gating ci.sh):

- ``taint``   — secret-flow: SECURITY.md's secret carriers must not
  reach journal/wire/telemetry/LRU/log/JSON sinks unsanitized.
- ``locks``   — lock discipline: static lock-order graph (cycles) and
  blocking calls inside ``with <lock>:`` bodies.
- ``knobs``   — knob drift: every FSDKR_* env read declared in
  `fsdkr_tpu.knobs.KNOBS` + README-documented; no dead knobs; no
  loop-body env reads.
- ``imports`` — unused imports + package layering (the former
  scripts/lint_imports.py).

The package deliberately imports nothing from the rest of fsdkr_tpu
except (lazily, in `lockwatch`) the telemetry flight recorder, so
linting never loads jax or the engines — enforced by its own layering
rule. `lockwatch` is the runtime counterpart: a FSDKR_LOCK_CHECK=1
lock-order watchdog that validates the static graph during tier-1.
"""

from __future__ import annotations

import pathlib
from typing import Dict, Iterable, List, Optional

from . import imports, knobs, locks, taint
from .common import Finding, SourceFile, build_index, load_files

__all__ = [
    "Finding",
    "PASSES",
    "run_passes",
    "load_files",
]

# name -> (module, needs_repo_root)
PASSES = {
    "taint": taint,
    "locks": locks,
    "knobs": knobs,
    "imports": imports,
}


def run_passes(
    paths: Iterable[str],
    which: Optional[Iterable[str]] = None,
    repo_root: Optional[str] = None,
    registry_checks: bool = True,
) -> Dict[str, object]:
    """Run the selected passes (default: all) over `paths`. Returns
    ``{"findings": [...], "suppressed": int, "files": int}`` with
    suppressions already applied and suppression-syntax findings
    included. ``registry_checks=False`` disables the knob pass's
    registry-wide dead/undocumented reconciliation — required when
    `paths` is a subset of the tree (the read surface is incomplete)."""
    root = pathlib.Path(repo_root or ".").resolve()
    files = load_files(paths, root=str(root))
    index = build_index(files)
    selected = list(which) if which else list(PASSES)
    raw: List[Finding] = []
    for name in selected:
        if name not in PASSES:
            raise ValueError(
                f"unknown pass {name!r} (have: {', '.join(PASSES)})"
            )
        mod = PASSES[name]
        if name == "knobs":
            raw += mod.run(files, index, repo_root=root,
                           registry_checks=registry_checks)
        else:
            raw += mod.run(files, index)

    by_rel = {sf.rel: sf for sf in files}
    findings: List[Finding] = []
    suppressed = 0
    for f in raw:
        sf = by_rel.get(f.path)
        if sf is not None and sf.suppressed(f.line, f.rule):
            suppressed += 1
            continue
        findings.append(f)
    for sf in files:
        findings += sf.suppression_findings()
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return {
        "findings": findings,
        "suppressed": suppressed,
        "files": len(files),
    }
