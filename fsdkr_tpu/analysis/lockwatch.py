"""Runtime lock-order watchdog (``FSDKR_LOCK_CHECK=1``) — the dynamic
counterpart of the static lock pass (`fsdkr_tpu.analysis.locks`).

``install()`` replaces ``threading.Lock`` / ``threading.RLock`` with
factories that hand fsdkr_tpu code (construction-site filtered) tracked
wrappers. Each wrapper records its construction site; every acquisition
while other tracked locks are held adds a ``held -> acquiring`` edge to
a process-global order graph, lockdep-style. An acquisition whose
reverse path already exists in the graph is a **lock-order violation**:
two threads interleaving those regions can deadlock, even if this run
did not. Violations are counted
(``fsdkr_lock_order_violations``), stamped into the flight recorder
like injected faults (kind ``lock_check``), and kept for
``violations()`` — tier-1's conftest fails the session on any.

The wrappers are Condition-compatible: a plain-Lock wrapper exposes
acquire/release/locked and lets ``threading.Condition`` fall back to
its acquire(False) ownership probe; the RLock wrapper implements
``_is_owned`` / ``_release_save`` / ``_acquire_restore`` itself. CV
waits therefore pop and re-push held state through the same
bookkeeping, so a ``cv.wait()`` never reads as holding the lock.

Deliberately NOT installed outside tests: the bookkeeping costs one
dict touch per acquisition on every hot lock. ``FSDKR_LOCK_CHECK`` is a
debug knob, default off everywhere.
"""

from __future__ import annotations

import os
import sys
import threading
import _thread
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "install",
    "uninstall",
    "installed",
    "enabled",
    "violations",
    "edges",
    "reset",
    "make_lock",
    "make_rlock",
]

_REAL_LOCK = _thread.allocate_lock
_REAL_RLOCK = threading.RLock

_state = _thread.allocate_lock()          # guards the graph (untracked)
_edges: Dict[str, Set[str]] = {}          # site -> sites acquired under it
_edge_sites: Dict[Tuple[str, str], str] = {}
_violations: List[dict] = []
_installed = False
_tls = threading.local()


def enabled() -> bool:
    return os.environ.get("FSDKR_LOCK_CHECK", "0").lower() not in (
        "", "0", "false", "off")


def _held() -> List["_TrackedBase"]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _busy() -> bool:
    return getattr(_tls, "busy", False)


def _path_exists(src: str, dst: str) -> Optional[List[str]]:
    """DFS: a path src -> ... -> dst in the order graph (caller holds
    _state)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _edges.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _stamp(violation: dict) -> None:
    """Flight-recorder + counter stamp, like an injected fault. Guarded
    against re-entrancy: the telemetry layer takes its own (tracked)
    locks."""
    _tls.busy = True
    try:
        from ..telemetry import flight, registry

        registry.counter(
            "fsdkr_lock_order_violations",
            "runtime lock-order violations (FSDKR_LOCK_CHECK watchdog)",
        ).inc()
        flight.record(
            "lock_check", "order_violation",
            held=violation["held"], acquiring=violation["acquiring"],
            thread=violation["thread"],
        )
    except Exception:
        pass  # the watchdog must never take the process down
    finally:
        _tls.busy = False


def _note_acquire(lock: "_TrackedBase") -> None:
    if _busy():
        return
    held = _held()
    new_violations = []
    with _state:
        for h in held:
            if h.site == lock.site:
                continue
            edge = (h.site, lock.site)
            if edge not in _edge_sites:
                # reverse path first: adding this edge would close a
                # cycle — that interleaving is a deadlock waiting for
                # the right schedule
                rev = _path_exists(lock.site, h.site)
                if rev is not None:
                    v = {
                        "held": h.site,
                        "acquiring": lock.site,
                        "thread": threading.current_thread().name,
                        "cycle": rev + [lock.site],
                    }
                    _violations.append(v)
                    new_violations.append(v)
                _edge_sites[edge] = threading.current_thread().name
                _edges.setdefault(h.site, set()).add(lock.site)
    held.append(lock)
    for v in new_violations:
        _stamp(v)


def _note_release(lock: "_TrackedBase") -> None:
    if _busy():
        return
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] is lock:
            del held[i]
            return


class _TrackedBase:
    def __init__(self, site: str):
        self.site = site


class _TrackedLock(_TrackedBase):
    """threading.Lock wrapper with order tracking."""

    def __init__(self, site: str):
        super().__init__(site)
        self._lock = _REAL_LOCK()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            _note_acquire(self)
        return got

    def release(self) -> None:
        _note_release(self)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<TrackedLock {self.site} locked={self.locked()}>"


class _TrackedRLock(_TrackedBase):
    """threading.RLock wrapper: order noted on FIRST acquisition only,
    Condition-compatible via the private RLock protocol."""

    def __init__(self, site: str):
        super().__init__(site)
        self._lock = _REAL_LOCK()
        self._owner: Optional[int] = None
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        me = _thread.get_ident()
        if self._owner == me:
            self._count += 1
            return True
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._owner = me
            self._count = 1
            _note_acquire(self)
        return got

    __enter__ = acquire

    def release(self) -> None:
        if self._owner != _thread.get_ident():
            raise RuntimeError("cannot release un-acquired lock")
        self._count -= 1
        if self._count == 0:
            self._owner = None
            _note_release(self)
            self._lock.release()

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition protocol
    def _is_owned(self) -> bool:
        return self._owner == _thread.get_ident()

    def _release_save(self):
        count, self._count = self._count, 0
        self._owner = None
        _note_release(self)
        self._lock.release()
        return count

    def _acquire_restore(self, count) -> None:
        self._lock.acquire()
        self._owner = _thread.get_ident()
        self._count = count
        _note_acquire(self)

    def __repr__(self) -> str:
        return f"<TrackedRLock {self.site} count={self._count}>"


def _caller_site(depth: int = 2) -> Tuple[str, bool]:
    """(construction site 'file:line', is_fsdkr) of the caller."""
    f = sys._getframe(depth)
    fname = f.f_code.co_filename
    site = f"{os.path.basename(fname)}:{f.f_lineno}"
    return site, ("fsdkr_tpu" in fname or "test_analysis" in fname)


def make_lock(site: str) -> _TrackedLock:
    """Explicitly tracked lock (tests, fixtures)."""
    return _TrackedLock(site)


def make_rlock(site: str) -> _TrackedRLock:
    return _TrackedRLock(site)


def _lock_factory():
    site, ours = _caller_site()
    return _TrackedLock(site) if ours else _REAL_LOCK()


def _rlock_factory():
    site, ours = _caller_site()
    return _TrackedRLock(site) if ours else _REAL_RLOCK()


def install() -> None:
    """Patch threading.Lock/RLock. Call BEFORE importing fsdkr_tpu
    modules (module-level locks are created at import time); jax and
    the stdlib keep real locks (construction-site filter)."""
    global _installed
    if _installed:
        return
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    _installed = True


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    _installed = False


def installed() -> bool:
    return _installed


def violations() -> List[dict]:
    with _state:
        return list(_violations)


def edges() -> Dict[str, Set[str]]:
    with _state:
        return {k: set(v) for k, v in _edges.items()}


def reset() -> None:
    with _state:
        _edges.clear()
        _edge_sites.clear()
        _violations.clear()


def snapshot_state() -> dict:
    """Copy of the global graph + violations, for tests that must
    isolate their own planted inversions WITHOUT wiping violations an
    earlier test legitimately recorded (the FSDKR_LOCK_CHECK session
    gate reads the global list at sessionfinish)."""
    with _state:
        return {
            "edges": {k: set(v) for k, v in _edges.items()},
            "edge_sites": dict(_edge_sites),
            "violations": list(_violations),
        }


def restore_state(saved: dict) -> None:
    with _state:
        _edges.clear()
        _edges.update({k: set(v) for k, v in saved["edges"].items()})
        _edge_sites.clear()
        _edge_sites.update(saved["edge_sites"])
        _violations.clear()
        _violations.extend(saved["violations"])
