"""Knob-drift pass: every ``FSDKR_*`` environment read must be declared
in the central registry (`fsdkr_tpu/knobs.py`) and documented in the
README knob table; declared knobs must actually be read somewhere.

Rules:

- ``knob-undeclared``: an env read of an ``FSDKR_*`` name with no row in
  ``fsdkr_tpu.knobs.KNOBS``.
- ``knob-undocumented``: a registry row with no ``FSDKR_*`` mention in
  README.md (reported against knobs.py).
- ``knob-dead``: a registry row no scanned file reads (reported against
  knobs.py) — dead knobs in the README are how retuning instructions rot.
- ``knob-hot-read``: an env read inside a ``for``/``while`` body — env
  reads are cheap but not free, and a loop-body ``getenv`` is how a
  per-row hot path ends up re-parsing configuration per call. Hoist it.

Env reads are recognized syntactically: ``os.environ.get/[]``,
``os.environ.setdefault``, ``os.getenv``, with a string literal first
argument matching ``FSDKR_[A-Z0-9_]+``. The registry is read with
``ast.literal_eval`` so the pass never imports the package.
"""

from __future__ import annotations

import ast
import pathlib
import re
from typing import Dict, List, Optional, Set, Tuple

from .common import Finding, SourceFile, dotted_name

__all__ = ["run", "RULES", "load_registry"]

RULES = ("knob-undeclared", "knob-undocumented", "knob-dead",
         "knob-hot-read")

_KNOB_RE = re.compile(r"^FSDKR_[A-Z0-9_]+$")

# env-read call heads: any alias of os.environ (`_os.environ.get`), the
# getenv builtins, and the repo's `_env_*` literal-name helpers
# (`_env_int`/`_env_float`/`_env_mb`/...)
_ENV_GETTER_SUFFIXES = ("environ.get", "environ.setdefault",
                        "environ.pop")
_ENV_SUBSCRIPT_SUFFIX = "environ"


def _is_env_getter(head: str) -> bool:
    if head.endswith(_ENV_GETTER_SUFFIXES):
        return True
    last = head.split(".")[-1]
    return last == "getenv" or last.startswith("_env")


def load_registry(repo_root: pathlib.Path) -> Dict[str, str]:
    """Parse KNOBS out of fsdkr_tpu/knobs.py without importing it."""
    path = repo_root / "fsdkr_tpu" / "knobs.py"
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "KNOBS":
                    knobs = ast.literal_eval(node.value)
                    if not isinstance(knobs, dict):
                        raise ValueError("KNOBS must be a dict literal")
                    return knobs
    raise ValueError("fsdkr_tpu/knobs.py: no KNOBS dict found")


def _registry_lines(repo_root: pathlib.Path) -> Dict[str, int]:
    path = repo_root / "fsdkr_tpu" / "knobs.py"
    lines = {}
    for i, raw in enumerate(path.read_text().splitlines(), start=1):
        m = re.search(r'"(FSDKR_[A-Z0-9_]+)"\s*:', raw)
        if m:
            lines[m.group(1)] = i
    return lines


def _knob_read(node: ast.Call) -> Optional[str]:
    # `env_var="FSDKR_X"` keywords mark deferred reads (NativeLib-style
    # gates) no matter what the call is
    for kw in node.keywords:
        if kw.arg in ("env_var", "env", "knob") \
                and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str) \
                and _KNOB_RE.match(kw.value.value):
            return kw.value.value
    head = dotted_name(node.func)
    if head is None or not _is_env_getter(head):
        return None
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str) \
            and _KNOB_RE.match(node.args[0].value):
        return node.args[0].value
    return None


def _subscript_read(node: ast.Subscript) -> Optional[str]:
    head = dotted_name(node.value)
    if head is not None and head.endswith(_ENV_SUBSCRIPT_SUFFIX) \
            and isinstance(node.slice, ast.Constant) \
            and isinstance(node.slice.value, str) \
            and _KNOB_RE.match(node.slice.value):
        return node.slice.value
    return None


def collect_reads(files: List[SourceFile]
                  ) -> List[Tuple[SourceFile, int, str, bool]]:
    """Every (file, line, knob, in_loop) env-read site."""
    out = []
    for sf in files:
        def visit(node, in_loop):
            name = None
            if isinstance(node, ast.Call):
                name = _knob_read(node)
            elif isinstance(node, ast.Subscript):
                name = _subscript_read(node)
            if name:
                out.append((sf, node.lineno, name, in_loop))
            enter_loop = isinstance(node, (ast.For, ast.While,
                                           ast.AsyncFor))
            for child in ast.iter_child_nodes(node):
                visit(child, in_loop or enter_loop)

        visit(sf.tree, False)
    return out


def run(files: List[SourceFile], index=None,
        repo_root: Optional[pathlib.Path] = None,
        registry_checks: bool = True) -> List[Finding]:
    """Per-read rules (undeclared/hot) always run; the REGISTRY-WIDE
    reconciliation (dead/undocumented) needs the full read surface, so
    callers linting a path subset pass registry_checks=False (the
    driver does this automatically for explicit path arguments) —
    otherwise every knob the subset doesn't read would read as dead."""
    repo_root = repo_root or pathlib.Path(".").resolve()
    registry = load_registry(repo_root)
    reg_lines = _registry_lines(repo_root)
    readme = (repo_root / "README.md").read_text()
    documented: Set[str] = set(re.findall(r"FSDKR_[A-Z0-9_]+", readme))

    findings: List[Finding] = []
    read_names: Set[str] = set()
    for sf, line, name, in_loop in collect_reads(files):
        read_names.add(name)
        if name not in registry:
            findings.append(Finding(
                sf.rel, line, "knob-undeclared",
                f"{name} read here but not declared in "
                f"fsdkr_tpu/knobs.py KNOBS",
            ))
        if in_loop:
            findings.append(Finding(
                sf.rel, line, "knob-hot-read",
                f"{name} read inside a loop body — hoist the env read "
                "out of the hot path",
            ))
    if not registry_checks:
        return findings
    for name in sorted(registry):
        line = reg_lines.get(name, 1)
        if name not in documented:
            findings.append(Finding(
                "fsdkr_tpu/knobs.py", line, "knob-undocumented",
                f"{name} declared but has no README.md knob-table row",
            ))
        if name not in read_names:
            findings.append(Finding(
                "fsdkr_tpu/knobs.py", line, "knob-dead",
                f"{name} declared but never read by any scanned file",
            ))
    return findings
