"""Lock-discipline pass: static lock-acquisition graph + blocking-call
audit over every ``with <lock>:`` body.

This is the defect class the last three review cycles kept finding by
hand (the submit-WAL-fsync stall, the parked-executor-thread
starvation, the ingress release-ordering wedge), mechanized:

- ``lock-order``: the pass collects every lock construction
  (``threading.Lock()`` / ``RLock()``, plus ``threading.Condition(L)``
  aliases), resolves ``with self._lock:`` / ``with _LOCK:`` acquisition
  sites, follows resolvable project calls (receiver types from the
  shared ProjectIndex) to a fixpoint "may acquire" summary per
  function, and flags any cycle in the resulting lock-order graph.
- ``lock-blocking-call``: flags blocking work — ``os.fsync``, socket
  I/O, ``time.sleep``, subprocess waits, bare ``.join()``/``.wait()``,
  and the native/powm batch entry points — executed while a lock is
  held, either directly in the ``with`` body or via a resolvable
  project call (one level of the chain is named in the finding).
  ``Condition.wait`` on a condition bound to the held lock is exempt
  (it *releases* the lock — that is the point of a CV).

Deliberate residuals carry inline suppressions with reasons (e.g. the
journal's fsync under its own lock IS the WAL ordering domain). The
static graph is validated at runtime by the FSDKR_LOCK_CHECK watchdog
(`fsdkr_tpu.analysis.lockwatch`) during tier-1.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .common import Finding, ProjectIndex, SourceFile, dotted_name, \
    iter_functions

__all__ = ["run", "RULES"]

RULES = ("lock-order", "lock-blocking-call")

# blocking calls by full dotted name
_BLOCKING_DOTTED = {
    "os.fsync": "fsync",
    "os.fdatasync": "fsync",
    "time.sleep": "sleep",
    "select.select": "select",
    "subprocess.run": "subprocess",
    "subprocess.call": "subprocess",
    "subprocess.check_call": "subprocess",
    "subprocess.check_output": "subprocess",
    "subprocess.Popen": "subprocess",
}
# blocking method names (attribute calls on any receiver)
_BLOCKING_METHODS = {
    "recv": "socket recv", "recv_into": "socket recv",
    "sendall": "socket send", "accept": "socket accept",
    "connect": "socket connect", "communicate": "subprocess wait",
}
# native / engine batch entry points: anything routed here does seconds
# of GIL-releasing work — never hold a service lock across it
_ENGINE_RE = re.compile(
    r"(^|\.)(modexp\w*|host_powm|tpu_powm\w*|crt_powm|multi_powm\w*|"
    r"miller_rabin\w*|keygen_batch|gen_primes\w*|gen_moduli\w*|"
    r"batch_scalar_mul|batch_msm|verify_pairs|distribute_batch|"
    r"collect\w*|finalize_streams)$"
)


@dataclass
class _FuncInfo:
    sf: SourceFile
    qual: str                      # module-level qualname (Class.meth)
    cls: Optional[str]
    node: ast.AST
    acquires: Set[str] = field(default_factory=set)   # lock ids
    blocks: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    # blocks: reason -> (line, depth) — depth 0 = blocks directly

    @property
    def fid(self) -> str:
        return f"{self.sf.module}:{self.qual}"


class _Locks:
    """Lock constructions and condition aliases for the whole project."""

    def __init__(self) -> None:
        # (module, class_or_None, attr) -> lock id
        self.defs: Dict[Tuple[str, Optional[str], str], str] = {}
        # condition alias -> lock id, same key shape
        self.cv: Dict[Tuple[str, Optional[str], str], str] = {}
        # attr name -> set of lock ids (for cross-class fallback)
        self.by_attr: Dict[str, Set[str]] = {}

    def define(self, module: str, cls: Optional[str], attr: str) -> str:
        lock_id = f"{module}.{cls}.{attr}" if cls else f"{module}.{attr}"
        self.defs[(module, cls, attr)] = lock_id
        self.by_attr.setdefault(attr, set()).add(lock_id)
        return lock_id

    def resolve(self, module: str, cls: Optional[str], expr: ast.AST,
                index: ProjectIndex) -> Optional[str]:
        """Lock id for a `with <expr>:` context, else None."""
        name = dotted_name(expr)
        if not name:
            return None
        parts = name.split(".")
        # self._lock / self._work_cv
        if len(parts) == 2 and parts[0] in ("self", "cls"):
            attr = parts[1]
            for table in (self.defs, self.cv):
                hit = table.get((module, cls, attr))
                if hit:
                    return hit
            # method defined in a different class of the same module
            # (mixins) — fall back on attr-name uniqueness
            ids = self.by_attr.get(attr, set())
            if len(ids) == 1:
                return next(iter(ids))
            return None
        # module-level _LOCK
        if len(parts) == 1:
            for table in (self.defs, self.cv):
                hit = table.get((module, None, parts[0]))
                if hit:
                    return hit
            return None
        # foreign attr chain x._lock: resolve receiver class by index
        attr = parts[-1]
        recv_cls = index.receiver_class(".".join(parts[:-1]))
        if recv_cls:
            info = index.classes.get(recv_cls)
            if info:
                for table in (self.defs, self.cv):
                    hit = table.get((info.module, recv_cls, attr))
                    if hit:
                        return hit
        ids = self.by_attr.get(attr, set())
        if len(ids) == 1:
            return next(iter(ids))
        return None

    def cv_lock(self, module: str, cls: Optional[str], recv: str
                ) -> Optional[str]:
        """If recv names a Condition alias, the lock it wraps."""
        parts = recv.split(".")
        attr = parts[-1]
        if parts[0] in ("self", "cls") or len(parts) == 1:
            return self.cv.get((module, cls, attr)) \
                or self.cv.get((module, None, attr))
        return None


def _collect_locks(files: List[SourceFile]) -> _Locks:
    locks = _Locks()
    for sf in files:
        def scan(node, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    scan(child, child.name)
                    continue
                if isinstance(child, ast.Assign) and isinstance(
                        child.value, ast.Call):
                    ctor = dotted_name(child.value.func) or ""
                    ctor_last = ctor.split(".")[-1]
                    for t in child.targets:
                        tn = dotted_name(t)
                        if not tn:
                            continue
                        tparts = tn.split(".")
                        owner = cls if tparts[0] in ("self", "cls") \
                            else None
                        attr = tparts[-1]
                        if len(tparts) > 2 or (len(tparts) == 2 and
                                               owner is None):
                            continue
                        if ctor_last in ("Lock", "RLock") and \
                                ctor.split(".")[0] in ("threading", "Lock",
                                                       "RLock"):
                            locks.define(sf.module, owner, attr)
                        elif ctor_last == "Condition":
                            args = child.value.args
                            if args:
                                inner = dotted_name(args[0])
                                if inner:
                                    iparts = inner.split(".")
                                    iowner = cls if iparts[0] in (
                                        "self", "cls") else None
                                    hit = locks.defs.get(
                                        (sf.module, iowner, iparts[-1]))
                                    if hit:
                                        locks.cv[(sf.module, owner,
                                                  attr)] = hit
                                        continue
                            # bare Condition(): owns a private lock
                            lid = locks.define(sf.module, owner, attr)
                            locks.cv[(sf.module, owner, attr)] = lid
                scan(child, cls)

        scan(sf.tree, None)
    return locks


def _direct_blocking(call: ast.Call, module: str, cls: Optional[str],
                     locks: _Locks, held: List[str]) -> Optional[str]:
    name = dotted_name(call.func)
    if not name:
        return None
    if name in _BLOCKING_DOTTED:
        return _BLOCKING_DOTTED[name]
    parts = name.split(".")
    meth = parts[-1]
    if meth in ("wait", "wait_for") and len(parts) > 1:
        cv = locks.cv_lock(module, cls, ".".join(parts[:-1]))
        if cv is not None and cv in held:
            return None  # CV wait on the held lock releases it: correct
        if cv is not None:
            return "condition wait (foreign lock)"
        return "wait"
    if meth == "join" and len(parts) > 1 and not call.args:
        # thread/process join; str.join always has the iterable arg
        return "join"
    if meth in _BLOCKING_METHODS:
        return _BLOCKING_METHODS[meth]
    if _ENGINE_RE.search(name):
        return f"engine entry point {meth}"
    return None


def _resolve_call(call: ast.Call, info: _FuncInfo, index: ProjectIndex,
                  funcs: Dict[str, _FuncInfo]) -> Optional[_FuncInfo]:
    """Resolve a call to a project function summary, best effort."""
    name = dotted_name(call.func)
    if not name:
        return None
    parts = name.split(".")
    module = info.sf.module
    if parts[0] in ("self", "cls") and len(parts) == 2 and info.cls:
        return funcs.get(f"{module}:{info.cls}.{parts[1]}")
    if len(parts) == 1:
        return funcs.get(f"{module}:{parts[0]}")
    # typed receiver: x.meth / self._journal.append
    recv_cls = index.receiver_class(".".join(parts[:-1]))
    if recv_cls:
        cinfo = index.classes.get(recv_cls)
        if cinfo and parts[-1] in cinfo.methods:
            return funcs.get(f"{cinfo.module}:{recv_cls}.{parts[-1]}")
    return None


def run(files: List[SourceFile], index: ProjectIndex) -> List[Finding]:
    return analyze(files, index)[0]


def analyze(files: List[SourceFile], index: ProjectIndex
            ) -> Tuple[List[Finding],
                       Dict[Tuple[str, str], Tuple[str, int]]]:
    """(findings, lock-order edge map) — the edge map is the static
    lock-acquisition graph, exposed for tests and for cross-validation
    against the FSDKR_LOCK_CHECK runtime watchdog."""
    locks = _collect_locks(files)
    funcs: Dict[str, _FuncInfo] = {}
    for sf in files:
        for qual, cls, node in iter_functions(sf.tree):
            info = _FuncInfo(sf, qual, cls, node)
            funcs[info.fid] = info

    # pass 1: per-function direct acquires + direct blocking reasons
    for info in funcs.values():
        module, cls = info.sf.module, info.cls

        def walk(node, held: List[str]):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not info.node:
                return  # nested functions summarized separately
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in node.items:
                    lid = locks.resolve(module, cls, item.context_expr,
                                        index)
                    if lid:
                        info.acquires.add(lid)
                        acquired.append(lid)
                for child in node.body:
                    walk(child, held + acquired)
                return
            if isinstance(node, ast.Call):
                reason = _direct_blocking(node, module, cls, locks, held)
                if reason and reason not in info.blocks:
                    info.blocks[reason] = (node.lineno, 0)
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for stmt in info.node.body:
            walk(stmt, [])

    # pass 2: fixpoint propagation through resolvable calls — `acquires`
    # flows transitively (lock-order edges care about the full closure);
    # blocking reasons flow at most TWO hops (callee direct, or callee's
    # own one-hop summary) so findings stay attributable and a deep call
    # chain into the engines doesn't flag every caller in the package
    changed = True
    rounds = 0
    while changed and rounds < 20:
        changed = False
        rounds += 1
        for info in funcs.values():
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = _resolve_call(node, info, index, funcs)
                if callee is None or callee is info:
                    continue
                new = callee.acquires - info.acquires
                if new:
                    info.acquires |= new
                    changed = True
                for reason, (line, depth) in callee.blocks.items():
                    if depth >= 2:
                        continue
                    if reason not in info.blocks:
                        info.blocks[reason] = (node.lineno, depth + 1)
                        changed = True

    # pass 3: findings — edges + blocking under held locks. Alongside
    # each lock-blocking-call finding, remember WHICH lock's critical
    # sections block: acquiring such a lock while holding another is
    # the submit-WAL-fsync stall shape even when the blocking work is
    # buried too deep for the chain cap (the journal fsyncs under its
    # OWN lock — the defect is taking that lock under the service's).
    findings: List[Finding] = []
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    edge_sites: Dict[Tuple[str, str], List[Tuple[str, int]]] = {}
    blocking_locks: Dict[str, str] = {}  # lock id -> first reason

    def _note_edge(h: str, lid: str, rel: str, lineno: int) -> None:
        edges.setdefault((h, lid), (rel, lineno))
        edge_sites.setdefault((h, lid), []).append((rel, lineno))

    def _note_blocking(held: List[str], reason: str) -> None:
        for h in held:
            blocking_locks.setdefault(h, reason.split(" [")[0])

    for info in funcs.values():
        module, cls = info.sf.module, info.cls

        def walk(node, held: List[str]):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not info.node:
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in node.items:
                    lid = locks.resolve(module, cls, item.context_expr,
                                        index)
                    if lid:
                        for h in held + acquired:
                            if h != lid:
                                _note_edge(h, lid, info.sf.rel,
                                           node.lineno)
                        acquired.append(lid)
                for child in node.body:
                    walk(child, held + acquired)
                return
            if isinstance(node, ast.Call) and held:
                reason = _direct_blocking(node, module, cls, locks, held)
                if reason:
                    findings.append(Finding(
                        info.sf.rel, node.lineno, "lock-blocking-call",
                        f"blocking call ({reason}) while holding "
                        f"{held[-1]}",
                    ))
                    _note_blocking(held, reason)
                else:
                    callee = _resolve_call(node, info, index, funcs)
                    if callee is not None and callee is not info:
                        for lid in callee.acquires:
                            for h in held:
                                if h != lid:
                                    _note_edge(h, lid, info.sf.rel,
                                               node.lineno)
                        for reason, (line, depth) in sorted(
                                callee.blocks.items()):
                            if depth > 1:
                                continue  # keep findings attributable
                            findings.append(Finding(
                                info.sf.rel, node.lineno,
                                "lock-blocking-call",
                                f"call into {callee.qual} may block "
                                f"({reason}) while holding {held[-1]}",
                            ))
                            _note_blocking(held, reason)
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for stmt in info.node.body:
            walk(stmt, [])

    # blocking-lock edges: taking a lock whose regions block (per the
    # _note_blocking facts above — `Journal.append` fsyncs under its
    # own lock, a documented-suppressed finding, which still marks
    # Journal._lock as blocking), while holding any other lock, stalls
    # every peer of the OUTER lock
    for (a, b), sites in sorted(edge_sites.items()):
        if b in blocking_locks:
            for rel, lineno in sites:
                findings.append(Finding(
                    rel, lineno, "lock-blocking-call",
                    f"acquires {b} — whose critical sections block "
                    f"({blocking_locks[b]}) — while holding {a}",
                ))

    # cycle detection over the static order graph
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)

    seen_cycles: Set[frozenset] = set()

    def find_cycle(start: str) -> Optional[List[str]]:
        stack: List[Tuple[str, List[str]]] = [(start, [start])]
        visited: Set[str] = set()
        while stack:
            node, path = stack.pop()
            for nxt in graph.get(node, ()):
                if nxt == start:
                    return path + [start]
                if nxt not in visited:
                    visited.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    for a in sorted(graph):
        cyc = find_cycle(a)
        if cyc:
            key = frozenset(cyc)
            if key in seen_cycles:
                continue
            seen_cycles.add(key)
            site = edges.get((cyc[0], cyc[1]), ("?", 0))
            findings.append(Finding(
                site[0], site[1], "lock-order",
                "lock-order cycle: " + " -> ".join(cyc),
            ))

    return findings, edges


def static_edges(files: List[SourceFile], index: ProjectIndex
                 ) -> Dict[Tuple[str, str], Tuple[str, int]]:
    """The static lock-order edge set (tests; lockwatch
    cross-validation tooling)."""
    return analyze(files, index)[1]
