"""fsdkr_tpu — a TPU-native framework with the capabilities of fs-dkr.

One-round Fouque-Stern Distributed Key Refresh for GG20 threshold-ECDSA
keys (reference: Leo-Li009/fs-dkr, mounted at /root/reference): proactive
share rotation, party add / replace / remove with identifiable abort, plus
the full supporting stack the Rust reference pulls from curv /
kzen-paillier / zk-paillier (Paillier, secp256k1, Feldman VSS,
PDL-with-slack, Alice/Bob range proofs, ring-Pedersen and correct-key
proofs).

Design: the protocol layer mirrors the reference API surface
(`RefreshMessage.{distribute,collect,replace}`, `JoinMessage`), while every
hot numeric path is expressed as batched, multi-modulus big-integer
arithmetic over fixed-shape limb tensors so it can run as JAX/Pallas
kernels on TPU (`fsdkr_tpu.ops`), with a pure-Python host backend as the
correctness oracle (`backend="host"`).
"""

from .config import ProtocolConfig, DEFAULT_CONFIG
from . import errors
from .errors import FsDkrError

__version__ = "0.1.0"

__all__ = [
    "ProtocolConfig",
    "DEFAULT_CONFIG",
    "errors",
    "FsDkrError",
    "__version__",
]
