"""One process-global metrics registry: labeled counters, gauges, and
fixed-bucket histograms.

Before this module every subsystem invented its own stats dict (`rlc`,
`crt`, `precompute`, `powm_cache`, `gen_stats`) and bench.py hand-
harvested five bespoke collectors. Those blocks now live here as labeled
metrics — the legacy module-level accessors (`rlc.stats()`,
`crt.crt_stats()`, `precompute.precompute_stats()`, ...) are thin views
over registry metrics, and `Registry.snapshot()` is the ONE structured
read bench.py embeds (schema-versioned, see `telemetry.export`).

Design points:

- **Histograms retain no samples.** Observations land in fixed buckets
  (default: a log-spaced latency ladder 100 us .. 120 s); p50/p95/p99
  are interpolated from the bucket counts at snapshot time. Memory per
  histogram child is O(buckets), regardless of call volume — safe to
  leave always-on around every pipeline phase.
- **Label values are allowlisted scalars** (short strings, small ints,
  floats, bools). A big integer — a modulus, a share, a pool entry —
  is rejected with ValueError at the call site: telemetry must be
  structurally unable to exfiltrate witness material (SECURITY.md
  "Telemetry discipline").
- **Function gauges** let subsystems with their own bounded state
  (the powm LRU, the CRT secret store, the precompute pools) expose
  point-in-time readings without double-bookkeeping: the callable is
  evaluated at snapshot time, and a raising callable yields no sample
  rather than killing the snapshot.
- `reset()` on a metric (or `reset_window()` on the registry) zeroes
  counters/histograms for the measured-window semantics the bench
  battery relies on (`stats_reset` before a warm run).
"""

from __future__ import annotations

import math
import re
import threading
from bisect import bisect_left
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "SCHEMA_VERSION",
    "DEFAULT_LATENCY_BUCKETS",
    "check_label_value",
    "sanitize_fields",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "get_registry",
    "counter",
    "gauge",
    "histogram",
    "vmhwm_bytes",
    "install_rss_gauge",
]

# bumped on any breaking change to the snapshot layout; consumers
# (scripts/digest_results.py, dashboards) key on it
SCHEMA_VERSION = "fsdkr-telemetry/1"

# log-spaced latency ladder: 100 us .. 120 s (the span between one
# modmul launch and a full cold n=256 collect), ~2.5x steps so p99
# interpolation stays within ~the step factor of the true value
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_STR_MAX = 120
_LABEL_INT_MAX = 1 << 63  # a value this wide is operand material, not a label


def check_label_value(v) -> str:
    """Validate one label value against the telemetry secrecy allowlist
    (scalars only, small ints only) and return its string form. Raises
    ValueError on anything that could smuggle operand material."""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, int):
        if abs(v) >= _LABEL_INT_MAX:
            raise ValueError(
                "label value too wide for telemetry (big ints are operand "
                "material — SECURITY.md 'Telemetry discipline')"
            )
        return str(v)
    if isinstance(v, float):
        if not math.isfinite(v):
            raise ValueError("non-finite label value")
        return repr(v)
    if isinstance(v, str):
        if len(v) > _LABEL_STR_MAX:
            raise ValueError("label string too long for telemetry")
        return v
    raise ValueError(
        f"label values must be small scalars, not {type(v).__name__}"
    )


def sanitize_fields(fields: Dict[str, object]):
    """Allowlist-filter an attribute/field dict against the telemetry
    secrecy rule (the ONE enforcement point shared by span attrs and
    flight-recorder fields): None values are skipped, values failing
    `check_label_value` are dropped and counted, keys are stringified
    and truncated. Returns (clean dict or None, dropped count)."""
    if not fields:
        return None, 0
    out = {}
    dropped = 0
    for k, v in fields.items():
        if v is None:
            continue
        try:
            check_label_value(v)
        except ValueError:
            dropped += 1
            continue
        out[str(k)[:64]] = v
    return (out or None), dropped


class _Metric:
    """Shared plumbing: children keyed by label-value tuples."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}

    def _labelkey(self, kw: Dict[str, object]) -> Tuple[str, ...]:
        if set(kw) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got "
                f"{tuple(kw)}"
            )
        return tuple(check_label_value(kw[k]) for k in self.labelnames)

    def _child(self, key: Tuple[str, ...]):
        with self._lock:
            ch = self._children.get(key)
            if ch is None:
                ch = self._children[key] = self._new_child()
            return ch

    def _new_child(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def labels(self, **kw):
        return self._child(self._labelkey(kw))

    def reset(self) -> None:
        with self._lock:
            self._children.clear()

    def snapshot_values(self) -> List[dict]:
        with self._lock:
            items = list(self._children.items())
        out = []
        for key, ch in items:
            rec = {"labels": dict(zip(self.labelnames, key))}
            rec.update(ch.snapshot())  # type: ignore[attr-defined]
            out.append(rec)
        return out


class _CounterChild:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        return {"value": self._value}


class Counter(_Metric):
    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, n: float = 1.0, **labels) -> None:
        self._child(self._labelkey(labels)).inc(n)

    def value(self, **labels) -> float:
        key = self._labelkey(labels)
        with self._lock:
            ch = self._children.get(key)
        return ch.value if ch is not None else 0.0

    def total(self) -> float:
        with self._lock:
            return sum(ch.value for ch in self._children.values())


class _GaugeChild:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        return {"value": self._value}


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help, labelnames=()):
        super().__init__(name, help, labelnames)
        self._fn: Optional[Callable[[], float]] = None
        self._labeled_fn: Optional[Callable[[], Dict[tuple, float]]] = None

    def _new_child(self):
        return _GaugeChild()

    def set(self, v: float, **labels) -> None:
        self._child(self._labelkey(labels)).set(v)

    def inc(self, n: float = 1.0, **labels) -> None:
        self._child(self._labelkey(labels)).inc(n)

    def dec(self, n: float = 1.0, **labels) -> None:
        self._child(self._labelkey(labels)).dec(n)

    def set_function(self, fn: Callable[[], float]) -> "Gauge":
        """Unlabeled gauge evaluated lazily at snapshot time (for
        subsystems that already hold their state — cache sizes, pool
        depths). A raising fn yields no sample, never a dead snapshot."""
        if self.labelnames:
            raise ValueError("set_function is for unlabeled gauges")
        self._fn = fn
        return self

    def set_labeled_function(
        self, fn: Callable[[], Dict[tuple, float]]
    ) -> "Gauge":
        """Labeled variant: fn returns {label-value-tuple: value} with
        tuples matching this gauge's labelnames order."""
        if not self.labelnames:
            raise ValueError("set_labeled_function needs labelnames")
        self._labeled_fn = fn
        return self

    def snapshot_values(self) -> List[dict]:
        if self._fn is not None:
            try:
                return [{"labels": {}, "value": float(self._fn())}]
            except Exception:
                return []
        if self._labeled_fn is not None:
            try:
                vals = self._labeled_fn()
            except Exception:
                return []
            out = []
            for key, v in vals.items():
                key = tuple(check_label_value(k) for k in key)
                out.append(
                    {"labels": dict(zip(self.labelnames, key)),
                     "value": float(v)}
                )
            return out
        return super().snapshot_values()


class _HistogramChild:
    __slots__ = ("_bounds", "_counts", "_sum", "_count", "_lock")

    def __init__(self, bounds: Tuple[float, ...]):
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # +1: the +inf bucket
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        i = bisect_left(self._bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def _percentile_from(self, counts: List[int], total: int, q: float) -> float:
        """q in (0, 1) over an already-copied bucket state: linear
        interpolation inside the bucket that crosses the q-quantile
        rank. No samples -> 0.0; ranks landing in the +inf bucket clamp
        to the last finite bound (the histogram's honest resolution
        limit)."""
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            lo_cum = cum
            cum += c
            if cum >= rank:
                if i >= len(self._bounds):  # +inf bucket
                    return self._bounds[-1]
                lo = self._bounds[i - 1] if i > 0 else 0.0
                hi = self._bounds[i]
                frac = (rank - lo_cum) / c
                return lo + (hi - lo) * frac
        return self._bounds[-1]

    def percentile(self, q: float) -> float:
        with self._lock:
            counts = list(self._counts)
            total = self._count
        return self._percentile_from(counts, total, q)

    def snapshot(self) -> dict:
        # ONE copy under the lock: buckets, count, sum, and all three
        # percentiles describe the same instant — a concurrent observe()
        # must not make the exported record internally inconsistent
        # (percentiles must be reproducible from the embedded buckets)
        with self._lock:
            counts = list(self._counts)
            s, n = self._sum, self._count
        cum = 0
        buckets = []
        for bound, c in zip(self._bounds, counts):
            cum += c
            buckets.append([bound, cum])
        return {
            "count": n,
            "sum": round(s, 9),
            "buckets": buckets,  # cumulative, +inf bucket implied by count
            "p50": round(self._percentile_from(counts, n, 0.50), 9),
            "p95": round(self._percentile_from(counts, n, 0.95), 9),
            "p99": round(self._percentile_from(counts, n, 0.99), 9),
        }


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames=(), buckets=None):
        super().__init__(name, help, labelnames)
        bounds = tuple(sorted(buckets or DEFAULT_LATENCY_BUCKETS))
        if not bounds or any(
            b <= a for a, b in zip(bounds, bounds[1:])
        ):
            raise ValueError("histogram buckets must be strictly increasing")
        self.buckets = bounds

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v: float, **labels) -> None:
        self._child(self._labelkey(labels)).observe(v)


class Registry:
    """Process-global named-metric store. `counter`/`gauge`/`histogram`
    are get-or-create (re-registering with a different type, label set,
    or explicit bucket ladder is a programming error and raises;
    `buckets=None` means "no opinion" and fetches the existing histogram
    whatever its ladder — the accessor idiom)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} re-registered with a different "
                        f"type/labels"
                    )
                b = kw.get("buckets")
                if b is not None and tuple(sorted(b)) != m.buckets:
                    raise ValueError(
                        f"metric {name!r} re-registered with different "
                        f"buckets"
                    )
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(), buckets=None) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self) -> dict:
        """The one structured read: every metric's labeled samples under
        a schema version (histograms with cumulative buckets and
        interpolated p50/p95/p99)."""
        out: Dict[str, dict] = {}
        for m in self.metrics():
            out[m.name] = {
                "type": m.kind,
                "help": m.help,
                "labelnames": list(m.labelnames),
                "values": m.snapshot_values(),
            }
        return {"schema": SCHEMA_VERSION, "metrics": out}

    def reset_window(self, names: Optional[Iterable[str]] = None) -> None:
        """Zero counters and histograms (all, or just `names`) for a
        fresh measurement window. Gauges keep their readings — they are
        point-in-time state, not window accumulation."""
        for m in self.metrics():
            if names is not None and m.name not in names:
                continue
            if m.kind in ("counter", "histogram"):
                m.reset()

    def reset_all(self) -> None:
        for m in self.metrics():
            m.reset()


_REGISTRY = Registry()


def vmhwm_bytes() -> int:
    """Process peak RSS in bytes — VmHWM from /proc/self/status, the
    kernel's high-water mark of resident set size. This is the ground
    truth the memory plan's staged-bytes estimates are sanity-checked
    against (ISSUE 10); 0 when the proc file is unavailable
    (non-Linux). Reading costs one small proc-file scan, so it is safe
    as a function gauge evaluated only at snapshot time."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return 0


def install_rss_gauge() -> Gauge:
    """Register the peak-RSS function gauge (idempotent). Installed at
    telemetry package import so every snapshot — bench JSONs, loadgen
    reports, Prometheus dumps — carries the process high-water mark."""
    g = _REGISTRY.gauge(
        "fsdkr_mem_rss_peak_bytes",
        "process peak RSS (VmHWM from /proc/self/status)",
    )
    g.set_function(lambda: float(vmhwm_bytes()))
    return g


def get_registry() -> Registry:
    return _REGISTRY


def counter(name, help="", labelnames=()) -> Counter:
    return _REGISTRY.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()) -> Gauge:
    return _REGISTRY.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=None) -> Histogram:
    return _REGISTRY.histogram(name, help, labelnames, buckets=buckets)
