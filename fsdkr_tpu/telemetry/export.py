"""Telemetry export: the schema-versioned JSON snapshot (embedded in
every bench JSON under the single `telemetry` key) and Prometheus text
exposition (`FSDKR_METRICS_DUMP=path`).

The JSON snapshot IS `registry.Registry.snapshot()` — one schema, one
read path; bench.py stopped hand-harvesting the five per-subsystem stat
dicts (those remain as legacy keys for old-BENCH comparability, but they
are views over the same registry metrics now).

Prometheus exposition follows the text format v0.0.4: counters get a
`_total`-suffixed sample when the name doesn't already carry one,
histograms emit cumulative `_bucket{le=...}` samples plus `_sum` and
`_count`, and function gauges are evaluated at dump time.
"""

from __future__ import annotations

import os
from typing import Optional

from .registry import SCHEMA_VERSION, get_registry

__all__ = [
    "SCHEMA_VERSION",
    "snapshot",
    "prometheus_text",
    "dump_metrics",
    "maybe_dump_metrics",
]


def snapshot() -> dict:
    """The one structured telemetry read (schema-versioned)."""
    return get_registry().snapshot()


def _fmt_labels(labels: dict, extra: Optional[tuple] = None) -> str:
    parts = [
        f'{k}="{_escape(str(v))}"' for k, v in labels.items()
    ]
    if extra is not None:
        parts.append(f'{extra[0]}="{_escape(str(extra[1]))}"')
    return "{" + ",".join(parts) + "}" if parts else ""


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def prometheus_text(snap: Optional[dict] = None) -> str:
    """Render a snapshot (default: the live registry) as Prometheus text
    exposition."""
    snap = snap or snapshot()
    lines = [f"# fsdkr telemetry schema {snap.get('schema', '?')}"]
    for name, m in sorted(snap.get("metrics", {}).items()):
        kind = m.get("type", "untyped")
        sample_name = name
        if kind == "counter" and not name.endswith("_total"):
            sample_name = name + "_total"
        if m.get("help"):
            lines.append(f"# HELP {sample_name} {_escape(m['help'])}")
        lines.append(f"# TYPE {sample_name} {kind}")
        for rec in m.get("values", []):
            labels = rec.get("labels", {})
            if kind == "histogram":
                cum = 0
                for le, cum in rec.get("buckets", []):
                    lines.append(
                        f"{name}_bucket{_fmt_labels(labels, ('le', le))} "
                        f"{_fmt_value(cum)}"
                    )
                lines.append(
                    f"{name}_bucket{_fmt_labels(labels, ('le', '+Inf'))} "
                    f"{_fmt_value(rec.get('count', 0))}"
                )
                lines.append(
                    f"{name}_sum{_fmt_labels(labels)} "
                    f"{_fmt_value(rec.get('sum', 0.0))}"
                )
                lines.append(
                    f"{name}_count{_fmt_labels(labels)} "
                    f"{_fmt_value(rec.get('count', 0))}"
                )
            else:
                lines.append(
                    f"{sample_name}{_fmt_labels(labels)} "
                    f"{_fmt_value(rec.get('value', 0.0))}"
                )
    return "\n".join(lines) + "\n"


def dump_metrics(path: str) -> str:
    """Write the Prometheus exposition to `path` (atomic replace)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(prometheus_text())
    os.replace(tmp, path)
    return path


def maybe_dump_metrics() -> Optional[str]:
    """Dump to FSDKR_METRICS_DUMP when set; the bench flows call this
    after their measured sections, and the package atexit hook calls it
    once more at interpreter exit (last write wins — a superset)."""
    path = os.environ.get("FSDKR_METRICS_DUMP")
    if not path:
        return None
    try:
        return dump_metrics(path)
    except OSError:
        return None
