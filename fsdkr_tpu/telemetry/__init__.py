"""Unified telemetry for the refresh pipeline (ISSUE 6).

Four pieces:

- `spans`    — hierarchical spans behind the back-compatible tracer
               (`get_tracer()`, `phase(...)`); Chrome-trace/Perfetto
               export via FSDKR_TRACE_OUT.
- `registry` — the process-global labeled metrics registry (counters /
               gauges / fixed-bucket histograms with interpolated
               p50/p95/p99); the five legacy per-subsystem stat blocks
               are views over it.
- `export`   — schema-versioned JSON snapshot (the `telemetry` key in
               every bench JSON) + Prometheus text exposition via
               FSDKR_METRICS_DUMP.
- `flight`   — always-on bounded flight recorder, flushed on unhandled
               exception / SIGTERM when FSDKR_FLIGHT names a
               destination.

Secrecy rule (SECURITY.md "Telemetry discipline"): span attributes,
metric labels, and flight-event fields accept allowlisted small scalars
only — never pool entries, rho coefficients, CRT contexts, or witness
material. Wide integers are rejected at the API boundary.

This package imports neither jax nor the native bridge: it must be
importable (and cheap) everywhere, including the flight-recorder crash
path.
"""

from __future__ import annotations

import atexit
import os

from . import export, flight, registry  # noqa: F401
from .registry import (  # noqa: F401
    SCHEMA_VERSION,
    counter,
    gauge,
    get_registry,
    histogram,
)
from .spans import (  # noqa: F401
    PhaseStats,
    Span,
    Tracer,
    get_tracer,
    jax_profile,
    phase,
)

__all__ = [
    "SCHEMA_VERSION",
    "PhaseStats",
    "Span",
    "Tracer",
    "get_tracer",
    "phase",
    "jax_profile",
    "counter",
    "gauge",
    "histogram",
    "get_registry",
    "export",
    "flight",
    "registry",
]

# crash-path handlers: only when FSDKR_FLIGHT names a destination
flight.install()

# the peak-RSS function gauge (ISSUE 10): always registered, evaluated
# only at snapshot time — every bench JSON / loadgen report / Prometheus
# dump carries the process VmHWM high-water mark
registry.install_rss_gauge()


def _atexit_exports() -> None:
    """Best-effort export at interpreter exit so a run that simply ends
    (no bench harness driving explicit writes) still leaves its
    artifacts when the env vars ask for them."""
    try:
        path = os.environ.get("FSDKR_TRACE_OUT")
        tr = get_tracer()
        if path and tr.spans():
            tr.write_chrome_trace(path)
    except Exception:
        pass
    try:
        export.maybe_dump_metrics()
    except Exception:
        pass


if os.environ.get("FSDKR_TRACE_OUT") or os.environ.get("FSDKR_METRICS_DUMP"):
    atexit.register(_atexit_exports)
