"""Always-on flight recorder: a bounded ring buffer of the last N span
events and counter deltas, flushed to disk on unhandled exception or
SIGTERM.

Motivation (ISSUE 6 / ROADMAP item 2): the on-chip tunnel windows are
~4 minutes and have died mid-battery repeatedly; a run that dies
mid-step currently leaves no artifact at all. The recorder costs one
deque append per phase/counter event (deque with maxlen — appends are
atomic under the GIL, no lock on the hot path), so it stays on even
with tracing disabled.

`FSDKR_FLIGHT` controls the dump destination only, never the recording:
  - unset/`0`  — record, never auto-dump (explicit `dump(path)` works)
  - `1`        — dump to `fsdkr_flight_<pid>.json` in the CWD
  - a path     — dump there

`install()` (called by the package __init__ when FSDKR_FLIGHT is set)
chains `sys.excepthook` and the SIGTERM handler: both write the dump and
then defer to the previous handler / default behavior, so the process
still dies the way it would have — it just leaves a postmortem.

Events never carry operand material: the payload is the same allowlisted
scalars the span/metric layer accepts (SECURITY.md "Telemetry
discipline").
"""

from __future__ import annotations

import json
import os
import re
import signal
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = [
    "FlightRecorder",
    "get_flight",
    "record",
    "dump",
    "install",
    "FLIGHT_SCHEMA",
]

FLIGHT_SCHEMA = "fsdkr-flight/1"


def _cap() -> int:
    try:
        return max(64, int(os.environ.get("FSDKR_FLIGHT_EVENTS", "4096")))
    except ValueError:
        return 4096


def _sanitize(fields: Dict[str, object]) -> Optional[Dict[str, object]]:
    """Allowlisted scalars only (the shared registry.sanitize_fields
    rule); a disallowed value is dropped silently — the recorder must
    never raise on the hot path, and a wide int is exactly what must
    not land in a postmortem file."""
    from .registry import sanitize_fields

    return sanitize_fields(fields)[0]


class FlightRecorder:
    def __init__(self, cap: Optional[int] = None):
        self._events: deque = deque(maxlen=cap or _cap())
        self._recorded = 0  # lifetime count (ring only keeps the tail)
        self._t0 = time.time()

    def record(
        self,
        kind: str,
        name: str,
        dur: Optional[float] = None,
        **fields,
    ) -> None:
        th = threading.current_thread()
        self._recorded += 1  # benign race: diagnostic counter
        self._events.append(
            (
                time.time(),
                th.name,
                kind,
                name,
                None if dur is None else round(dur, 6),
                _sanitize(fields),
            )
        )

    def snapshot(self) -> List[dict]:
        out = []
        for ts, thread, kind, name, dur, fields in list(self._events):
            rec = {
                "ts": round(ts, 6),
                "thread": thread,
                "kind": kind,
                "name": name,
            }
            if dur is not None:
                rec["dur_s"] = dur
            if fields:
                rec["fields"] = fields
            out.append(rec)
        return out

    def clear(self) -> None:
        self._events.clear()
        self._recorded = 0

    def dump(
        self,
        path: Optional[str] = None,
        reason: str = "manual",
        include_metrics: bool = True,
    ) -> Optional[str]:
        """Write the ring (plus a current metrics snapshot — a postmortem
        wants the counter state too) to `path` or the FSDKR_FLIGHT
        destination; returns the written path or None when no
        destination is configured. include_metrics=False skips the
        registry snapshot — the events-only fallback for contexts where
        metric locks may be unavailable (see _dump_on_signal)."""
        path = path or _env_path()
        if not path:
            return None
        metrics = None
        if include_metrics:
            try:
                from .registry import get_registry

                metrics = get_registry().snapshot()
            except Exception:
                metrics = None
        doc = {
            "schema": FLIGHT_SCHEMA,
            "pid": os.getpid(),
            "reason": reason,
            "started_at": round(self._t0, 3),
            "dumped_at": round(time.time(), 3),
            "events_recorded": self._recorded,
            "events": self.snapshot(),
            "metrics": metrics,
        }
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=None, separators=(",", ":"))
        os.replace(tmp, path)
        return path


def _env_path() -> Optional[str]:
    v = os.environ.get("FSDKR_FLIGHT", "")
    if v.lower() in ("", "0", "off", "false", "no"):
        return None
    if v.lower() in ("1", "true", "on", "yes"):
        return f"fsdkr_flight_{os.getpid()}.json"
    return v


_RECORDER = FlightRecorder()


def get_flight() -> FlightRecorder:
    return _RECORDER


def record(kind: str, name: str, dur: Optional[float] = None, **fields) -> None:
    _RECORDER.record(kind, name, dur=dur, **fields)


def dump(path: Optional[str] = None, reason: str = "manual") -> Optional[str]:
    return _RECORDER.dump(path, reason=reason)


def _dump_on_signal(reason: str, timeout: float = 2.0) -> None:
    """Dump from a signal handler without risking a deadlock. The
    handler interrupts the main thread between bytecodes — possibly
    INSIDE a registry critical section (metric locks are plain
    non-reentrant Locks, and function gauges call into subsystems with
    their own locks), so a direct dump() could block forever on a lock
    the interrupted frame itself holds. Run the full dump on a watchdog
    thread; if it cannot finish within `timeout`, write an events-only
    dump instead — the ring is a plain deque and needs no locks."""

    def work():
        try:
            _RECORDER.dump(reason=reason)
        except Exception:
            pass

    t = threading.Thread(target=work, daemon=True, name="fsdkr-flight-dump")
    t.start()
    t.join(timeout)
    if t.is_alive():
        _RECORDER.dump(reason=f"{reason}:events-only", include_metrics=False)


_INSTALL_LOCK = threading.Lock()
_INSTALLED = False


_WIDE_DEC = re.compile(r"\d{16,}")
_WIDE_HEX = re.compile(r"(?:0x)?[0-9a-fA-F]{32,}")


def _scrub_detail(msg: str) -> str:
    """Exception messages are free text and can interpolate operand
    material (a library ValueError embedding its argument); wide
    decimal/hex runs ARE operand material in this codebase, so redact
    them before the message reaches a persisted postmortem — same
    threshold philosophy as the int allowlist (2^63 ~ 19 digits)."""
    msg = _WIDE_DEC.sub("<wide-int>", msg)
    msg = _WIDE_HEX.sub("<wide-hex>", msg)
    return msg[:120]


def handle_exception(exc_type, exc, tb) -> None:
    """The excepthook body, callable directly (tests simulate a crash by
    invoking it): dump with the exception recorded as the final event,
    then defer to the interpreter's default traceback printer."""
    try:
        _RECORDER.record(
            "crash", exc_type.__name__, detail=_scrub_detail(str(exc))
        )
        _RECORDER.dump(reason=f"unhandled:{exc_type.__name__}")
    except Exception:
        pass


def install(force: bool = False) -> bool:
    """Chain the excepthook and SIGTERM handler (idempotent). No-op
    unless FSDKR_FLIGHT configures a destination (or force=True)."""
    global _INSTALLED
    with _INSTALL_LOCK:
        if _INSTALLED:
            return True
        if not force and _env_path() is None:
            return False

        prev_hook = sys.excepthook

        def hook(exc_type, exc, tb):
            handle_exception(exc_type, exc, tb)
            prev_hook(exc_type, exc, tb)

        sys.excepthook = hook

        try:
            prev_sig = signal.getsignal(signal.SIGTERM)

            def on_term(signum, frame):
                try:
                    _RECORDER.record("signal", "SIGTERM")
                    _dump_on_signal(reason="SIGTERM")
                except Exception:
                    pass
                if callable(prev_sig):
                    prev_sig(signum, frame)
                elif prev_sig is signal.SIG_IGN:
                    # the process had SIGTERM ignored (possibly
                    # inherited across exec) — dump but stay alive
                    return
                else:
                    # restore the default disposition and re-raise so the
                    # process still dies with the standard SIGTERM status
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

            signal.signal(signal.SIGTERM, on_term)
        except ValueError:
            pass  # not the main thread: excepthook coverage only
        _INSTALLED = True
        return True
