"""Hierarchical spans + the back-compatible aggregate tracer.

This is the new home of `utils.trace` (which re-exports from here). The
old flat name -> (calls, seconds, items, macs) aggregator kept every
caller's API — `get_tracer().phase(...)`, `.report()`, `.stats()`, the
`trace_*` bench fields — but each `phase()` now ALSO records a span:
start/end timestamps, thread, parent span (contextvar-tracked, so
nesting survives `with` blocks on any thread), and allowlisted scalar
attributes. The span stream exports as Chrome-trace/Perfetto JSON
(`FSDKR_TRACE_OUT=path`, or `Tracer.write_chrome_trace`), so a warm
collect() renders as a real timeline: verify families, RLC folds, tile
dispatch, the overlapped EC column, and the background producer's
pool-fill bouts on their own thread track.

Cost model (the 2%-of-baseline budget, gated in bench.py):

- tracing DISABLED: two `perf_counter` calls, one fixed-bucket histogram
  observation (`fsdkr_phase_seconds{phase=...}` — the per-phase latency
  percentiles stay live even without tracing), and one flight-recorder
  ring append per phase. Phases wrap batch launches, not rows, so this
  is tens of events per collect().
- tracing ENABLED: additionally the aggregate-stats update and one span
  record, bounded by FSDKR_TRACE_EVENTS (default 250k; overflow drops
  newest and counts them — a timeline with a hole beats an OOM).

Worker threads: `utils.pipeline` captures `current_span()` at submit
time and enters `inherit_phase(span)` in the worker, so tile spans and
MAC attribution parent to the submitting phase. Threads NOT primed this
way (the background producer) start their own span roots — their track
in the trace shows exactly what that thread did.

Span attributes go through the same scalar allowlist as metric labels
(registry.check_label_value); a disallowed value (e.g. any wide int) is
dropped and counted, never recorded — see SECURITY.md "Telemetry
discipline".
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from contextvars import ContextVar
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional

__all__ = [
    "PhaseStats",
    "Span",
    "Tracer",
    "get_tracer",
    "phase",
    "jax_profile",
]

_SPAN_IDS = itertools.count(1)  # CPython: count.__next__ is atomic

# perf_counter epoch shared by every span so timelines are comparable
_T0_PERF = time.perf_counter()
_T0_UNIX = time.time()

# stack of (tracer, span-like) tuples; contextvars give each thread its
# own stack by default AND survive into explicitly-propagated contexts
_STACK: ContextVar[tuple] = ContextVar("fsdkr_span_stack", default=())


def _max_spans() -> int:
    try:
        return max(1024, int(os.environ.get("FSDKR_TRACE_EVENTS", "250000")))
    except ValueError:
        return 250000


@dataclass
class PhaseStats:
    calls: int = 0
    seconds: float = 0.0
    items: int = 0
    macs: float = 0.0  # analytic u16-MAC count (utils.roofline)

    @property
    def items_per_second(self) -> float:
        return self.items / self.seconds if self.seconds > 0 else 0.0

    def mfu(self, peak: float) -> float:
        return self.macs / self.seconds / peak if self.seconds > 0 else 0.0


class Span:
    """One finished (or in-flight) phase instance. Timestamps are
    perf_counter seconds relative to the module epoch."""

    __slots__ = (
        "name", "span_id", "parent_id", "t0", "t1", "tid", "thread_name",
        "items", "macs", "attrs",
    )

    def __init__(self, name: str, parent_id: Optional[int], items: int,
                 attrs: Optional[dict]):
        self.name = name
        self.span_id = next(_SPAN_IDS)
        self.parent_id = parent_id
        self.t0 = time.perf_counter() - _T0_PERF
        self.t1: Optional[float] = None
        th = threading.current_thread()
        self.tid = th.ident or 0
        self.thread_name = th.name
        self.items = items
        self.macs = 0.0
        self.attrs = attrs

    @property
    def duration(self) -> float:
        return (self.t1 - self.t0) if self.t1 is not None else 0.0


class _Anchor:
    """Span-like stack entry for `inherit_phase`: carries attribution
    (name, and the parent span id when inherited from a real span)
    without owning any wall-clock."""

    __slots__ = ("name", "span_id", "macs")

    def __init__(self, name: str, span_id: Optional[int]):
        self.name = name
        self.span_id = span_id
        self.macs = 0.0


def _sanitize_attrs(attrs: dict):
    """(allowlisted attrs or None, dropped count)."""
    from .registry import sanitize_fields

    return sanitize_fields(attrs)


# per-phase latency histogram: always-on (cheap, bounded memory), the
# registry backbone the SLO work needs even when span tracing is off
_PHASE_HIST = None
_HIST_LOCK = threading.Lock()


def _phase_hist():
    global _PHASE_HIST
    if _PHASE_HIST is None:
        with _HIST_LOCK:
            if _PHASE_HIST is None:
                from .registry import histogram

                _PHASE_HIST = histogram(
                    "fsdkr_phase_seconds",
                    "wall-clock of each pipeline phase (telemetry.spans)",
                    labelnames=("phase",),
                )
    return _PHASE_HIST


class Tracer:
    """Aggregate stats + span recording, process-global via get_tracer().

    `enabled` gates aggregation and span recording (FSDKR_TRACE, or
    enable()); the phase latency histogram and the flight-recorder ring
    stay on regardless — they are bounded and cheap, and the flight
    recorder exists precisely for runs nobody thought to trace.
    """

    def __init__(self, enabled: Optional[bool] = None,
                 max_spans: Optional[int] = None):
        if enabled is None:
            enabled = os.environ.get("FSDKR_TRACE", "0") not in ("", "0")
        self.enabled = bool(enabled)
        self._stats: Dict[str, PhaseStats] = {}
        self._lock = threading.Lock()
        self._spans: List[Span] = []
        self._spans_dropped = 0  # ring overflow only (timeline is lossy)
        self._attrs_dropped = 0  # allowlist-rejected span attributes
        self._max_spans = max_spans or _max_spans()

    # -- lifecycle ------------------------------------------------------
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self, keep_spans: bool = False) -> None:
        """Clear the aggregate stats (a fresh measurement window).
        keep_spans=True preserves the recorded span stream — bench.py
        windows its stats repeatedly but wants ONE timeline covering
        setup, offline fill, and both measured runs."""
        with self._lock:
            self._stats.clear()
            if not keep_spans:
                self._spans.clear()
                self._spans_dropped = 0
                self._attrs_dropped = 0

    # -- the phase context manager --------------------------------------
    @contextlib.contextmanager
    def phase(self, name: str, items: int = 0, **attrs) -> Iterator[None]:
        if not self.enabled:
            t0 = time.perf_counter()
            try:
                yield
            finally:
                dt = time.perf_counter() - t0
                self._observe(name, dt, items)
            return
        clean, dropped = _sanitize_attrs(attrs)
        span = Span(name, self._current_span_id(), items, clean)
        if dropped:
            with self._lock:
                self._attrs_dropped += dropped
        tok = _STACK.set(_STACK.get() + ((self, span),))
        t0 = time.perf_counter()
        # re-stamp t0 at the instant the duration clock starts: the
        # constructor stamped it a few µs earlier (sanitize + contextvar
        # work in between), and t1 = t0 + dt with MISMATCHED origins
        # under-reported each span's end by its own construction gap —
        # a parent with a bigger gap could "end" microseconds before
        # its child, breaking interval nesting
        span.t0 = t0 - _T0_PERF
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            _STACK.reset(tok)
            span.t1 = span.t0 + dt
            with self._lock:
                st = self._stats.setdefault(name, PhaseStats())
                st.calls += 1
                st.seconds += dt
                st.items += items
                st.macs += span.macs
                if len(self._spans) < self._max_spans:
                    self._spans.append(span)
                else:
                    self._spans_dropped += 1
            self._observe(name, dt, items)

    def _observe(self, name: str, dt: float, items: int) -> None:
        try:
            _phase_hist().observe(dt, phase=name)
        except Exception:
            pass
        from . import flight

        flight.record("span", name, dur=dt, items=items or None)

    # -- context helpers ------------------------------------------------
    def _top(self):
        """Innermost stack entry owned by THIS tracer (None otherwise)."""
        for tracer, entry in reversed(_STACK.get()):
            if tracer is self:
                return entry
        return None

    def _current_span_id(self) -> Optional[int]:
        top = self._top()
        return top.span_id if top is not None else None

    def current_span(self) -> Optional[Span]:
        """Innermost active REAL span of this tracer on this thread
        (anchors from inherit_phase don't count — they have no clock)."""
        for tracer, entry in reversed(_STACK.get()):
            if tracer is self and isinstance(entry, Span):
                return entry
        return None

    def current_phase(self) -> Optional[str]:
        top = self._top()
        return top.name if top is not None else None

    @contextlib.contextmanager
    def inherit_phase(self, parent) -> Iterator[None]:
        """Attribute work on a worker thread to the submitting thread's
        phase WITHOUT timing it (the submitter's enclosing `phase`
        already owns the wall clock; a timed re-entry would double-count
        seconds). `parent` is a Span (preferred: child spans then carry
        the right parent_id across the thread hop), a phase-name string
        (legacy), or None (no-op). Used by utils.pipeline."""
        if not self.enabled or parent is None:
            yield
            return
        if isinstance(parent, str):
            anchor = _Anchor(parent, None)
        else:
            anchor = _Anchor(parent.name, parent.span_id)
        tok = _STACK.set(_STACK.get() + ((self, anchor),))
        try:
            yield
        finally:
            _STACK.reset(tok)

    # -- MAC / counter attribution --------------------------------------
    def add_macs(self, macs: float) -> None:
        """Attribute analytic device/host work (utils.roofline formulas)
        to the innermost active phase of this thread — the engine layer
        calls this without knowing which protocol phase it serves."""
        if not self.enabled:
            return
        top = self._top()
        if top is not None:
            top.macs += macs
            if isinstance(top, _Anchor):
                # anchors aren't recorded: credit the aggregate directly
                with self._lock:
                    self._stats.setdefault(top.name, PhaseStats()).macs += macs
            return
        with self._lock:
            self._stats.setdefault("(unphased)", PhaseStats()).macs += macs

    def count(self, name: str, items: int = 1) -> None:
        if not self.enabled:
            return
        with self._lock:
            st = self._stats.setdefault(name, PhaseStats())
            st.calls += 1
            st.items += items

    # -- reads -----------------------------------------------------------
    def stats(self) -> Dict[str, PhaseStats]:
        with self._lock:
            return {
                k: PhaseStats(v.calls, v.seconds, v.items, v.macs)
                for k, v in self._stats.items()
            }

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def spans_dropped(self) -> int:
        """Spans lost to ring overflow — 0 means the timeline is
        complete (attrs rejected by the allowlist count separately)."""
        return self._spans_dropped

    def attrs_dropped(self) -> int:
        return self._attrs_dropped

    def report(self) -> str:
        from ..utils.roofline import peak_macs

        peak = peak_macs()
        rows = sorted(self.stats().items(), key=lambda kv: -kv[1].seconds)
        if not rows:
            return "(no phases recorded)"
        width = max(len(k) for k, _ in rows)
        lines = [
            f"{'phase':{width}s} {'calls':>6s} {'seconds':>9s} {'items':>8s} "
            f"{'items/s':>10s} {'GMACs':>9s} {'mfu%':>7s}"
        ]
        for name, st in rows:
            lines.append(
                f"{name:{width}s} {st.calls:6d} {st.seconds:9.3f} "
                f"{st.items:8d} {st.items_per_second:10.1f} "
                f"{st.macs / 1e9:9.2f} {100 * st.mfu(peak):7.3f}"
            )
        return "\n".join(lines)

    # -- Chrome-trace / Perfetto export ----------------------------------
    def chrome_trace(self) -> dict:
        """The span stream as a Chrome-trace object (catapult JSON array
        format): complete ("X") events in microseconds, thread-name
        metadata so Perfetto labels the producer/pipeline tracks, and
        span/parent ids in args for programmatic nesting checks."""
        pid = os.getpid()
        events = [
            {
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": "fsdkr-tpu"},
            }
        ]
        seen_threads = {}
        for sp in self.spans():
            if sp.t1 is None:
                continue
            if sp.tid not in seen_threads:
                seen_threads[sp.tid] = sp.thread_name
                events.append(
                    {
                        "name": "thread_name", "ph": "M", "pid": pid,
                        "tid": sp.tid, "args": {"name": sp.thread_name},
                    }
                )
            args = {"span_id": sp.span_id}
            if sp.parent_id is not None:
                args["parent_id"] = sp.parent_id
            if sp.items:
                args["items"] = sp.items
            if sp.macs:
                args["gmacs"] = round(sp.macs / 1e9, 3)
            if sp.attrs:
                args.update(sp.attrs)
            events.append(
                {
                    "name": sp.name,
                    "cat": sp.name.split(".", 1)[0],
                    "ph": "X",
                    "ts": round(sp.t0 * 1e6, 1),
                    "dur": round((sp.t1 - sp.t0) * 1e6, 1),
                    "pid": pid,
                    "tid": sp.tid,
                    "args": args,
                }
            )
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "schema": "fsdkr-chrome-trace/1",
                "epoch_unix": round(_T0_UNIX, 3),
                "spans_dropped": self._spans_dropped,
                "attrs_dropped": self._attrs_dropped,
            },
        }

    def write_chrome_trace(self, path: str) -> str:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.chrome_trace(), f)
        os.replace(tmp, path)
        return path


_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def phase(name: str, items: int = 0, **attrs):
    """Module-level shorthand for `get_tracer().phase(...)`."""
    return _TRACER.phase(name, items=items, **attrs)


@contextlib.contextmanager
def jax_profile(log_dir: Optional[str] = None) -> Iterator[None]:
    """XLA profiler trace around a block (view with xprof/tensorboard).
    No-op when jax is unavailable or log_dir is None and FSDKR_XPROF is
    unset."""
    log_dir = log_dir or os.environ.get("FSDKR_XPROF")
    if not log_dir:
        yield
        return
    try:
        import jax
    except ImportError:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
