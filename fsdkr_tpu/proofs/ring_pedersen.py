"""Ring-Pedersen parameter proof: S = T^lambda mod N with T a square,
proven by an M-round binary-challenge sigma protocol, Fiat-Shamir batched.

Re-derivation of the reference's `RingPedersenProof`
(`/root/reference/src/ring_pedersen_proof.rs`; from the UC non-interactive
threshold-ECDSA paper). Challenge bits use the same Lsb0 digest-bit
semantics (`src/ring_pedersen_proof.rs:106,136`).

Conscious fix vs the reference (SURVEY.md §5 behavioral quirks): the
reference serializes the secret `phi` inside the broadcast statement
(`src/ring_pedersen_proof.rs:34` has no serde skip). Here `phi` lives in
the witness only; the wire statement is (S, T, N, ek).
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass
from typing import List

from ..config import ProtocolConfig, DEFAULT_CONFIG
from ..core import intops, primes
from ..core.paillier import EncryptionKey
from ..core.transcript import Transcript, challenge_bits
from ..errors import RingPedersenProofError

__all__ = ["RingPedersenStatement", "RingPedersenWitness", "RingPedersenProof"]

_DOMAIN = b"fsdkr/ring-pedersen/v1"


@dataclass(frozen=True)
class RingPedersenStatement:
    S: int
    T: int
    N: int
    ek: EncryptionKey

    @staticmethod
    def generate(
        config: ProtocolConfig = DEFAULT_CONFIG,
    ) -> tuple["RingPedersenStatement", "RingPedersenWitness"]:
        """Fresh modulus; T = r^2 mod N, S = T^lambda mod N
        (reference `src/ring_pedersen_proof.rs:48-74`)."""
        return RingPedersenStatement.generate_batch(1, config)[0]

    @staticmethod
    def generate_batch(
        count: int, config: ProtocolConfig = DEFAULT_CONFIG
    ) -> list:
        """`count` fresh statements: moduli through the batched prime
        pipeline (core.primes, FSDKR_THREADS windows), and S = T^lambda
        through the secret-CRT engine (FSDKR_CRT, backend.crt) — the
        prover owns this factorization, so the full-width ladder
        decomposes into two fault-checked half-width legs with lambda
        reduced mod p-1 / q-1. Bit-identical to the full-width path
        (same sampling order, same values; pinned by tests/test_crt.py)."""
        from ..backend import crt

        moduli = primes.gen_moduli_batch(config.paillier_bits, count)
        use_crt = crt.crt_enabled()
        out = []
        for n, p, q in moduli:
            phi = (p - 1) * (q - 1)
            r = secrets.randbelow(n)
            lam = secrets.randbelow(phi)
            t = pow(r, 2, n)
            if use_crt:
                s = crt.crt_modexp_batch(
                    [t], [lam], [crt.get_context(n, p, q)]
                )[0]
            else:
                s = pow(t, lam, n)
            out.append(
                (
                    RingPedersenStatement(
                        S=s, T=t, N=n, ek=EncryptionKey.from_n(n)
                    ),
                    RingPedersenWitness(p=p, q=q, lam=lam, phi=phi),
                )
            )
        return out


@dataclass(frozen=True)
class RingPedersenWitness:
    p: int
    q: int
    lam: int
    phi: int


@dataclass(frozen=True)
class RingPedersenProof:
    A: List[int]
    Z: List[int]

    @staticmethod
    def _challenge(a_vec: List[int], hash_alg: str | None = None) -> int:
        t = Transcript(_DOMAIN, algorithm=hash_alg)
        for a_i in a_vec:
            t.chain_int(a_i)
        return t.result_int()

    @staticmethod
    def prove(
        witness: RingPedersenWitness,
        st: RingPedersenStatement,
        m_security: int = DEFAULT_CONFIG.m_security,
        powm=None,
        hash_alg: str | None = None,
    ) -> "RingPedersenProof":
        return RingPedersenProof.prove_batch(
            [witness], [st], m_security, powm, hash_alg
        )[0]

    @staticmethod
    def sample_commit(
        witnesses: List[RingPedersenWitness],
        m_security: int = DEFAULT_CONFIG.m_security,
    ) -> List[List[int]]:
        """M-round commitment nonces a_i < phi per witness — THE one
        sampler for the inline prover and the offline key-material
        producer (fsdkr_tpu.precompute), split from the challenge-
        response so pooled and inline runs draw identically (the
        seeded-parity contract of tests/test_precompute.py)."""
        return [
            [secrets.randbelow(w.phi) for _ in range(m_security)]
            for w in witnesses
        ]

    @staticmethod
    def prove_batch(
        witnesses: List[RingPedersenWitness],
        statements: List[RingPedersenStatement],
        m_security: int = DEFAULT_CONFIG.m_security,
        powm=None,
        hash_alg: str | None = None,
    ) -> List["RingPedersenProof"]:
        """All provers' M-round commitment columns in ONE modexp launch;
        each prover's rows share (T, N), so the fixed-base comb kernel
        picks them up as a group.

        The proof depends on (witness, statement) ALONE — the challenge
        binds only the prover's own commitments — so whole proofs are
        input-independent and ride the precompute key-material pool
        (fsdkr_tpu/precompute) together with their statements."""
        if powm is None:
            from ..backend.powm import host_powm as powm
        if len(witnesses) != len(statements):
            raise ValueError(
                f"batch length mismatch: {len(witnesses)} witnesses, "
                f"{len(statements)} statements"
            )
        a_all = RingPedersenProof.sample_commit(witnesses, m_security)
        from ..backend import crt

        if crt.crt_enabled():
            # The prover owns each statement's factorization: the M=256
            # commitment rows T^{a_i} mod N decompose into two
            # fault-checked HALF-width fixed-base comb legs per prover
            # (exponents reduced mod p-1/q-1, one squaring ladder per
            # leg amortized over all M rows, tables built-used-wiped —
            # secret-derived, never cached). ~4x the full-width comb;
            # A values bit-identical (tests/test_crt.py).
            A_all = []
            for w, st, a_vec in zip(witnesses, statements, a_all):
                A_all += crt.crt_powm_shared(
                    st.T, a_vec, crt.get_context(st.N, w.p, w.q)
                )
        else:
            A_all = powm(
                [st.T for st in statements for _ in range(m_security)],
                [a for grp in a_all for a in grp],
                [st.N for st in statements for _ in range(m_security)],
            )
        out = []
        for k, (witness, a_vec) in enumerate(zip(witnesses, a_all)):
            A_vec = A_all[k * m_security : (k + 1) * m_security]
            e = RingPedersenProof._challenge(A_vec, hash_alg)
            bits = challenge_bits(e, m_security, hash_alg)
            Z_vec = [
                (a_i + (witness.lam if b else 0)) % witness.phi
                for a_i, b in zip(a_vec, bits)
            ]
            out.append(RingPedersenProof(A=A_vec, Z=Z_vec))
        intops.zeroize_ints(*a_all)  # drop the commitment nonces
        return out

    @staticmethod
    def rlc_fold(st: "RingPedersenStatement", proof: "RingPedersenProof",
                 bits, rhos):
        """Fold the M binary-challenge rows T^{Z_i} == A_i * S^{e_i}
        (mod N) into one Bellare-Garay-Rabin small-exponent RLC check

            T^{sum_i rho_i Z_i} == prod_i A_i^{rho_i} * S^{sum_{e_i=1} rho_i}

        over the caller's secret fresh rho_i (backend.rlc). Both sides
        are products of non-negative powers (no inversions), so the fold
        is evaluated as an equality of two computed elements. Returns
        (lhs_row, rhs_row) as (bases, exps, modulus) joint
        multi-exponentiation rows: lhs is the proof's ONE remaining
        full-width ladder (T's per-row exponents merge into a single
        ~|N|+136-bit exponent); rhs rides a short aggregated chain — M+1
        terms whose exponents are only 128-136 bits wide. Domain gating
        (verify's shape/range checks) must run BEFORE aggregation: the
        caller folds only in-domain proofs."""
        e_merged = sum(r * z for r, z in zip(rhos, proof.Z))
        e_s = sum(r for r, b in zip(rhos, bits) if b)
        lhs = ((st.T,), (e_merged,), st.N)
        rhs = (tuple(proof.A) + (st.S,), tuple(rhos) + (e_s,), st.N)
        return lhs, rhs

    def verify(
        self,
        st: RingPedersenStatement,
        m_security: int = DEFAULT_CONFIG.m_security,
        hash_alg: str | None = None,
    ) -> None:
        """Per-bit check T^{Z_i} == A_i * S^{e_i} mod N
        (reference `src/ring_pedersen_proof.rs:138-155`)."""
        if len(self.A) != m_security or len(self.Z) != m_security:
            raise RingPedersenProofError()
        # fail closed on out-of-domain integers (in-process objects; the
        # wire decode is strict): negatives crash transcript/pow paths
        if st.N <= 2 or any(a < 0 for a in self.A) or any(z < 0 for z in self.Z):
            raise RingPedersenProofError()
        e = RingPedersenProof._challenge(self.A, hash_alg)
        bits = challenge_bits(e, m_security, hash_alg)
        for a_i, z_i, b in zip(self.A, self.Z, bits):
            lhs = intops.mod_pow(st.T, z_i, st.N)
            rhs = a_i * (st.S if b else 1) % st.N
            if lhs != rhs:
                raise RingPedersenProofError()
