"""PDL-with-slack proof: a Paillier ciphertext c = Enc_ek(x, r) and an EC
point Q = x*G hide the same x, with range slack x in [-q^3, q^3].

Re-derivation of the reference's `PDLwSlackProof`
(`/root/reference/src/zk_pdl_with_slack.rs`, following eprint 2016/013 PIi):

  prover (witness x < q, r):
    alpha < q^3, beta <- [1, n), rho < q*Ntilde, gamma < q^3*Ntilde
    z  = h1^x h2^rho mod Ntilde
    u1 = alpha * G
    u2 = (1+n)^alpha beta^n mod n^2
    u3 = h1^alpha h2^gamma mod Ntilde
    e  = H(G, Q, c, z, u1, u2, u3)
    s1 = e*x + alpha;  s2 = r^e beta mod n;  s3 = e*rho + gamma

  verifier: recompute e; accept iff
    u1 == s1*G - e*Q
    u2 == (1+n)^s1 s2^n c^{-e} mod n^2
    u3 == h1^s1 h2^s3 z^{-e} mod Ntilde
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass

from ..core import intops
from ..core.paillier import EncryptionKey
from ..core.secp256k1 import N as CURVE_ORDER
from ..core.secp256k1 import Point, Scalar
from ..core.transcript import Transcript
from ..errors import PDLwSlackProofError

__all__ = ["PDLwSlackStatement", "PDLwSlackWitness", "PDLwSlackProof", "commitment_unknown_order"]

_DOMAIN = b"fsdkr/pdl-slack/v1"


def commitment_unknown_order(h1: int, h2: int, modulus: int, x: int, r: int) -> int:
    """h1^x * h2^r mod modulus over a group of unknown order; negative
    exponents via modular inverse (reference
    `/root/reference/src/zk_pdl_with_slack.rs:170-188`)."""
    return (
        intops.mod_pow_signed(h1, x, modulus)
        * intops.mod_pow_signed(h2, r, modulus)
        % modulus
    )


@dataclass(frozen=True)
class PDLwSlackStatement:
    # field set mirrors /root/reference/src/zk_pdl_with_slack.rs:24-32
    ciphertext: int
    ek: EncryptionKey
    Q: Point
    G: Point
    h1: int
    h2: int
    N_tilde: int


@dataclass(frozen=True)
class PDLwSlackWitness:
    x: Scalar
    r: int


@dataclass(frozen=True)
class PDLwSlackProof:
    z: int
    u1: Point
    u2: int
    u3: int
    s1: int
    s2: int
    s3: int

    @staticmethod
    def _challenge(
        st: PDLwSlackStatement, z: int, u1: Point, u2: int, u3: int,
        hash_alg: str | None = None,
    ) -> int:
        # transcript fields mirror /root/reference/src/zk_pdl_with_slack.rs:87-95
        return (
            Transcript(_DOMAIN, algorithm=hash_alg)
            .chain_point(st.G)
            .chain_point(st.Q)
            .chain_int(st.ciphertext)
            .chain_int(z)
            .chain_point(u1)
            .chain_int(u2)
            .chain_int(u3)
            .result_challenge()
        )

    @staticmethod
    def prove(
        witness: PDLwSlackWitness,
        st: PDLwSlackStatement,
        hash_alg: str | None = None,
    ) -> "PDLwSlackProof":
        return PDLwSlackProof.prove_batch([witness], [st], hash_alg=hash_alg)[0]

    # Two-phase batched prover: stage1 emits the modexp columns of the
    # round-1 commitments, stage2 (after the fused launch) emits the
    # response column. distribute_batch drives the PDL and Alice-range
    # provers (and the encryption column) in lockstep so same-width
    # columns of BOTH families share one launch — sequential modexp
    # depth, not row count, prices a launch (backend.powm.powm_columns).

    @staticmethod
    def sample_stage1(ntv, nv):
        """Input-independent stage-1 nonce sampling for len(ntv) rows —
        THE one sampler for both the inline prover and the offline
        precompute producer (fsdkr_tpu.precompute), so pooled and inline
        runs draw from identical distributions in identical per-row
        order (the seeded-parity contract of tests/test_precompute.py).
        Returns (alpha, beta, rho, gamma) columns."""
        q = CURVE_ORDER
        q3 = q**3
        alpha = [secrets.randbelow(q3) for _ in ntv]
        beta = [1 + secrets.randbelow(n - 1) for n in nv]
        rho = [secrets.randbelow(q * nt) for nt in ntv]
        gamma = [secrets.randbelow(q3 * nt) for nt in ntv]
        return alpha, beta, rho, gamma

    @staticmethod
    def produce_stage1(h1, h2, nt, n, count, powm=None):
        """Offline producer constructor (fsdkr_tpu.precompute): sample
        `count` rows of stage-1 nonces for ONE receiver environment and
        evaluate every input-independent power. Returns pool bundles
        (alpha, beta, rho, gamma, beta^n mod n^2, h2^rho mod N~,
        h1^alpha*h2^gamma mod N~) — exactly the values prove_stage1
        samples and computes inline (same sampler, same arithmetic), so
        consumption is bit-identical. The witness-dependent factor h1^x
        and everything downstream of the Fiat-Shamir challenge stay
        online by construction."""
        if powm is None:
            # plain batch engine (GMP host route): measured 1.8x faster
            # than the grouped own-core comb for the producer shape on
            # this box (a 16-row group cannot amortize a fresh comb
            # build, and the beta^n rows are secret-base loners anyway)
            from ..backend.powm import host_powm as powm
        from ..backend.powm import powm_columns

        nn = n * n
        alpha, beta, rho, gamma = PDLwSlackProof.sample_stage1(
            [nt] * count, [n] * count
        )
        h2rho, ca, cg, bn = powm_columns(
            powm,
            ([h2] * count, rho, [nt] * count),
            ([h1] * count, alpha, [nt] * count),
            ([h2] * count, gamma, [nt] * count),
            (beta, [n] * count, [nn] * count),
        )
        u3 = intops.mod_mul_col(ca, cg, [nt] * count)
        return [
            (alpha[i], beta[i], rho[i], gamma[i], bn[i], h2rho[i], u3[i])
            for i in range(count)
        ]

    @staticmethod
    def prove_stage1(witnesses, h1v, h2v, ntv, nv, nnv, hash_alg=None,
                     pooled=None):
        """Sample nonces, return (state, columns). Under FSDKR_MULTIEXP
        the two mod-N~ commitment pairs are submitted as joint
        multi-exponentiation rows (z = h1^x h2^rho, u3 = h1^alpha
        h2^gamma per row) — the planner routes the shared h1/h2 terms
        through the comb and recombines in-launch, so the host
        mod_mul_col columns disappear; =0 keeps the per-term column
        layout. CONTRACT: the beta^n mod n^2 column is LAST in either
        layout — distribute_batch splits it into the fused Paillier
        launch (its own sub-phase trace) by position.

        `pooled` (FSDKR_PRECOMPUTE): a per-row list of Optional
        produce_stage1 bundles. Pooled rows contribute NO offline-
        computable columns — only the witness factor h1^x remains (one
        column over all rows, which deduplicates with the Alice prover's
        identical share column in powm_columns); rows with a dry pool
        (None) ride fallback columns, bit-identical to inline."""
        from ..backend.powm import multiexp_enabled

        joint = multiexp_enabled()
        if pooled is None:
            alpha, beta, rho, gamma = PDLwSlackProof.sample_stage1(ntv, nv)
            state = dict(
                witnesses=witnesses, alpha=alpha, beta=beta, rho=rho,
                gamma=gamma, ntv=ntv, nv=nv, nnv=nnv, hash_alg=hash_alg,
                joint=joint,
            )
            if joint:
                cols = [
                    (
                        list(zip(h1v, h2v)),
                        [(w.x.to_int(), r) for w, r in zip(witnesses, rho)],
                        ntv,
                    ),
                    (list(zip(h1v, h2v)), list(zip(alpha, gamma)), ntv),
                    (beta, nv, nnv),
                ]
            else:
                cols = [
                    (h1v, [w.x.to_int() for w in witnesses], ntv),
                    (h2v, rho, ntv),
                    (h1v, alpha, ntv),
                    (h2v, gamma, ntv),
                    (beta, nv, nnv),
                ]
            return state, cols

        rows = len(ntv)
        fb = [i for i in range(rows) if pooled[i] is None]
        s_alpha, s_beta, s_rho, s_gamma = PDLwSlackProof.sample_stage1(
            [ntv[i] for i in fb], [nv[i] for i in fb]
        )
        alpha = [0] * rows
        beta = [0] * rows
        rho = [0] * rows
        gamma = [0] * rows
        pool_bn, pool_h2rho, pool_u3 = {}, {}, {}
        for i, p in enumerate(pooled):
            if p is not None:
                (alpha[i], beta[i], rho[i], gamma[i],
                 pool_bn[i], pool_h2rho[i], pool_u3[i]) = p
        for j, i in enumerate(fb):
            alpha[i], beta[i], rho[i], gamma[i] = (
                s_alpha[j], s_beta[j], s_rho[j], s_gamma[j]
            )
        state = dict(
            witnesses=witnesses, alpha=alpha, beta=beta, rho=rho, gamma=gamma,
            ntv=ntv, nv=nv, nnv=nnv, hash_alg=hash_alg, joint=joint,
            pooled_mode=True, fb=fb, pool_bn=pool_bn, pool_h2rho=pool_h2rho,
            pool_u3=pool_u3,
        )
        nt_fb = [ntv[i] for i in fb]
        if joint:
            u3_cols = [(
                [(h1v[i], h2v[i]) for i in fb],
                [(alpha[i], gamma[i]) for i in fb],
                nt_fb,
            )]
        else:
            u3_cols = [
                ([h1v[i] for i in fb], [alpha[i] for i in fb], nt_fb),
                ([h2v[i] for i in fb], [gamma[i] for i in fb], nt_fb),
            ]
        cols = [
            (h1v, [w.x.to_int() for w in witnesses], ntv),
            ([h2v[i] for i in fb], [rho[i] for i in fb], nt_fb),
            *u3_cols,
            ([beta[i] for i in fb], [nv[i] for i in fb],
             [nnv[i] for i in fb]),
        ]
        return state, cols

    @staticmethod
    def prove_stage2(state, results, statements, device_ec: bool = False):
        """Combine stage-1 results, recompute challenges, return
        (state, columns): the r^e response column."""
        ntv, nv, nnv = state["ntv"], state["nv"], state["nnv"]
        alpha = state["alpha"]
        from ..core import paillier

        if state.get("pooled_mode"):
            fb = state["fb"]
            rows = len(ntv)
            h2rho = [state["pool_h2rho"].get(i) for i in range(rows)]
            u3 = [state["pool_u3"].get(i) for i in range(rows)]
            bn = [state["pool_bn"].get(i) for i in range(rows)]
            for j, i in enumerate(fb):
                h2rho[i] = results[1][j]
                bn[i] = results[-1][j]
            if state.get("joint"):
                for j, i in enumerate(fb):
                    u3[i] = results[2][j]
            else:
                u3_fb = intops.mod_mul_col(
                    results[2], results[3], [ntv[i] for i in fb]
                )
                for j, i in enumerate(fb):
                    u3[i] = u3_fb[j]
            z = intops.mod_mul_col(results[0], h2rho, ntv)
        elif state.get("joint"):
            z, u3, bn = results
        else:
            c1, c2, c3, c4, bn = results
            z = intops.mod_mul_col(c1, c2, ntv)
            u3 = intops.mod_mul_col(c3, c4, ntv)
        u2 = paillier.combine_with_rn(alpha, bn, nv, nnv)  # Enc(alpha; beta)
        from ..core.secp256k1 import GENERATOR

        if device_ec and all(st.G == GENERATOR for st in statements):
            from ..ops.ec_batch import batch_generator_mul

            u1 = batch_generator_mul(alpha)
        else:
            u1 = [st.G * Scalar.from_int(al) for st, al in zip(statements, alpha)]
        e = [
            PDLwSlackProof._challenge(st, zi, u1i, u2i, u3i, state["hash_alg"])
            for st, zi, u1i, u2i, u3i in zip(statements, z, u1, u2, u3)
        ]
        state.update(z=z, u1=u1, u2=u2, u3=u3, e=e)
        return state, [([w.r for w in state["witnesses"]], e, nv)]

    @staticmethod
    def prove_finish(state, results):
        (re_,) = results
        alpha, beta, rho, gamma = (
            state["alpha"], state["beta"], state["rho"], state["gamma"],
        )
        proofs = [
            PDLwSlackProof(
                z=zi,
                u1=u1i,
                u2=u2i,
                u3=u3i,
                s1=ei * w.x.to_int() + al,
                s2=x * b % n,
                s3=ei * ro + ga,
            )
            for w, n, zi, u1i, u2i, u3i, ei, x, b, al, ro, ga in zip(
                state["witnesses"], state["nv"], state["z"], state["u1"],
                state["u2"], state["u3"], state["e"], re_, beta, alpha, rho,
                gamma,
            )
        ]
        intops.zeroize_ints(alpha, beta, rho, gamma)
        return proofs

    @staticmethod
    def prove_batch(
        witnesses: list[PDLwSlackWitness],
        statements: list[PDLwSlackStatement],
        powm=None,
        device_ec: bool = False,
        hash_alg: str | None = None,
    ) -> list["PDLwSlackProof"]:
        """Batched prover: the n-receiver fan-out of distribute (reference
        `/root/reference/src/refresh_message.rs:87-104`) as modexp columns
        through `powm` (host pow or one TPU launch per column).

        (1+n)^alpha mod n^2 uses the closed form 1 + (alpha mod n)*n, so
        the u2 column needs only the beta^n exponentiation.
        """
        if powm is None:
            from ..backend.powm import host_powm as powm
        if len(witnesses) != len(statements):
            raise ValueError(
                f"batch length mismatch: {len(witnesses)} witnesses, "
                f"{len(statements)} statements"
            )
        from ..backend.powm import powm_columns

        state, cols = PDLwSlackProof.prove_stage1(
            witnesses,
            [st.h1 for st in statements],
            [st.h2 for st in statements],
            [st.N_tilde for st in statements],
            [st.ek.n for st in statements],
            [st.ek.nn for st in statements],
            hash_alg,
        )
        state, cols2 = PDLwSlackProof.prove_stage2(
            state, powm_columns(powm, *cols), statements, device_ec
        )
        return PDLwSlackProof.prove_finish(state, powm_columns(powm, *cols2))

    @staticmethod
    def domain_gate(proof: "PDLwSlackProof", st: PDLwSlackStatement,
                    q: int = CURVE_ORDER) -> bool:
        """Wire-domain gate for one row of the batched verifier, applied
        BEFORE any staging, hashing, or aggregation. Exponent-position
        fields (s1, s3) are attacker-chosen integers: a negative value
        would crash the limb encoder mid-batch and an oversized one would
        inflate a whole fused launch's exponent width (or, under
        FSDKR_RLC, poison a combined group) — a one-row DoS. Width caps
        are the honest-value bounds: s1 = e*x + alpha < 2q^3 (832 bits of
        slack used), s3 = e*rho + gamma < 2q^3 * N_tilde.
        Transcript-position fields (z, u2, u3, ciphertext) must be
        non-negative for chain_int."""
        q3 = q**3
        return (
            proof.z >= 0
            and proof.u2 >= 0
            and proof.u3 >= 0
            and st.ciphertext >= 0
            and 0 <= proof.s1 <= 2 * q3
            and 0 <= proof.s3
            and proof.s3.bit_length() <= st.N_tilde.bit_length() + 832
        )

    @staticmethod
    def rlc_fold_nt(h1: int, h2: int, n_tilde: int, rows, rhos):
        """Fold the mod-N~ equations u3_j * z_j^{e_j} == h1^{s1_j} h2^{s3_j}
        of the rows sharing one receiver statement (h1, h2, N~) into one
        Bellare-Garay-Rabin small-exponent RLC check

            h1^{sum rho_j s1_j} * h2^{sum rho_j s3_j}
                == prod_j u3_j^{rho_j} * prod_j z_j^{rho_j e_j}  (mod N~)

        rows: [(z, u3, e, s1, s3)] per proof, already domain-gated.
        Returns (lhs_row, rhs_row) joint multi-exponentiation rows: the
        shared bases h1/h2 merge their exponents into lhs's single
        full-width 2-term ladder; the per-row bases keep only short
        (128/384-bit) exponents on rhs's aggregated chain."""
        s1_merged = sum(r * s1 for r, (_, _, _, s1, _) in zip(rhos, rows))
        s3_merged = sum(r * s3 for r, (_, _, _, _, s3) in zip(rhos, rows))
        lhs = ((h1, h2), (s1_merged, s3_merged), n_tilde)
        rhs = (
            tuple(u3 for _, u3, _, _, _ in rows)
            + tuple(z for z, _, _, _, _ in rows),
            tuple(rhos)
            + tuple(r * e for r, (_, _, e, _, _) in zip(rhos, rows)),
            n_tilde,
        )
        return lhs, rhs

    @staticmethod
    def rlc_fold_nn(n: int, nn: int, rows, rhos):
        """Fold the mod-n^2 equations u2_j * c_j^{e_j} == (1+n)^{s1_j} s2_j^n
        of the rows sharing one receiver Paillier key into

            prod_j u2_j^{rho_j} * prod_j c_j^{rho_j e_j}
                == (1 + (sum rho_j s1_j) n) * (prod_j s2_j^{rho_j})^n  (mod n^2)

        rows: [(u2, c, e, s1, s2)] per proof, already domain-gated.
        (1+n)^x has the closed form 1 + (x mod n) n, so the whole
        combined g-term costs one host multiply. Returns (s2_row,
        commit_row, gs1): s2_row aggregates prod s2_j^{rho_j} on a short
        chain — the caller raises its result to n, the group's single
        remaining full-width ladder — and commit_row aggregates the
        u2/c side; gs1 is the closed-form combined (1+n)-power."""
        s2_row = (
            tuple(s2 for _, _, _, _, s2 in rows),
            tuple(rhos),
            nn,
        )
        commit_row = (
            tuple(u2 for u2, _, _, _, _ in rows)
            + tuple(c for _, c, _, _, _ in rows),
            tuple(rhos)
            + tuple(r * e for r, (_, _, e, _, _) in zip(rhos, rows)),
            nn,
        )
        s1_merged = sum(
            r * (s1 % n) for r, (_, _, _, s1, _) in zip(rhos, rows)
        )
        gs1 = (1 + (s1_merged % n) * n) % nn
        return s2_row, commit_row, gs1

    def verify(self, st: PDLwSlackStatement, hash_alg: str | None = None) -> None:
        """Raises PDLwSlackProofError with per-equation booleans on failure
        (reference `src/zk_pdl_with_slack.rs:158-166`).

        Out-of-domain integers (negative proof fields or ciphertext —
        possible for in-process objects; the wire decode is strict) fail
        closed with the proof error instead of crashing the transcript."""
        if (
            min(self.z, self.u2, self.u3, self.s1, self.s2, self.s3) < 0
            or st.ciphertext < 0
        ):
            raise PDLwSlackProofError(False, False, False)
        e = PDLwSlackProof._challenge(
            st, self.z, self.u1, self.u2, self.u3, hash_alg
        )

        g_s1 = st.G * Scalar.from_int(self.s1)
        e_neg = Scalar.from_int(CURVE_ORDER - e % CURVE_ORDER)
        u1_test = g_s1 + st.Q * e_neg

        u2_test_tmp = commitment_unknown_order(
            st.ek.n + 1, self.s2, st.ek.nn, self.s1, st.ek.n
        )
        u2_test = commitment_unknown_order(u2_test_tmp, st.ciphertext, st.ek.nn, 1, -e)

        u3_test_tmp = commitment_unknown_order(
            st.h1, st.h2, st.N_tilde, self.s1, self.s3
        )
        u3_test = commitment_unknown_order(u3_test_tmp, self.z, st.N_tilde, 1, -e)

        ok1, ok2, ok3 = self.u1 == u1_test, self.u2 == u2_test, self.u3 == u3_test
        if not (ok1 and ok2 and ok3):
            raise PDLwSlackProofError(ok1, ok2, ok3)
