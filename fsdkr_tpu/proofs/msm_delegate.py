"""2G2T-style constant-size MSM delegation for the Feldman/EC column
(FSDKR_DELEGATE, arXiv:2602.23464 prototype; ISSUE 17 tentpole (c)).

The honest Feldman verifier evaluates sum_k A_k * u^k == S_u per share
row — n Horner chains of t small-scalar muls per scheme, the EC work a
loaded shard pays on every collect. Delegation moves the bulk of that
work to the prover: at distribute, the sender emits ONE extra point per
scheme, the certificate

    R = (sum_u rho_u * f(u) mod q) * G,

with Fiat-Shamir coefficients rho_u = H(domain, A_0..A_t, S_1..S_n, u)
at RHO_BITS = 64 statistical bits (nonzero by construction). The
verifier then checks the certificate instead of computing the per-row
MSMs:

    S-side:  sum_u rho_u * S_u           == R   (n points, 64-bit scalars)
    A-side:  sum_k c_k  * A_k            == R   (t+1 points, c_k integer)

with c_k = sum_u rho_u * u^k kept as PLAIN integers (~RHO_BITS +
t*log2(n) bits — never reduced mod q; the narrow scalars are the whole
advantage). Honest transcripts satisfy both sides identically
(S_u = sum_k u^k A_k implies sum_u rho_u S_u = sum_k c_k A_k), so a
correct certificate resolves every row of the scheme with TWO shared
doubling chains — and, crucially, resolves them ONCE per scheme no
matter how many fused sessions carry the same broadcast: try_delegate
groups rows by scheme identity, so an S-session launch pays one
certificate check where the honest arm pays S x n Horner chains. That
cross-session amortization is where the op-count win lives (measured
by a real op counter on the shared-chain wNAF MSM below; the
acceptance A/B publishes both counts): at a single n=16, t=8 session
the honest arm's tiny <=4-bit Horner scalars make delegation a near
wash, and the delegated count drops strictly below the honest model
from S >= 2 fused sessions (or single sessions with n >= ~32).

Soundness is STATISTICAL at the prototype parameter RHO_BITS = 64: a
scheme with at least one tampered row passes both checks with
probability <= ~2^-64 over the Fiat-Shamir coins (rho binds the A_k
AND the S_u, so an adversary cannot correlate share tampering against
fixed coefficients). This is a deliberately reduced prototype parameter
— the RLC machinery everywhere else in the repo uses 128-bit rho — and
the reason FSDKR_DELEGATE defaults OFF (see SECURITY.md "MSM
delegation").

Verdict bit-identity is structural: a missing certificate, partial row
coverage, conflicting share points, or a FAILING certificate check all
fall back to the honest per-row path for that scheme (and count
`certs_rejected`/`fallback_rows`), so tampered transcripts produce
exactly the honest arm's row verdicts in both knob positions — the
delegated arm can only ever short-circuit schemes whose rows all pass.

The certificate is broadcast-public (it rides the VSS scheme on the
wire, serialization._vss_enc) and derives only from public values.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from ..core.secp256k1 import (
    GENERATOR,
    N as CURVE_N,
    P as CURVE_P,
    Point,
    _jac_to_affine,
    _jadd,
    _jdouble,
)
from ..core.transcript import Transcript

__all__ = [
    "RHO_BITS",
    "delegate_enabled",
    "rho_vec",
    "emit_cert",
    "try_delegate",
    "honest_model_ops",
    "stats",
    "stats_reset",
    "count",
]

RHO_BITS = 64

_DOMAIN = b"fsdkr/msm-delegate/v1"


def delegate_enabled() -> bool:
    """FSDKR_DELEGATE gates the certificate arm on BOTH sides (cert
    emission at distribute, cert checking at collect): default OFF —
    the honest per-row MSM path — because the prototype soundness
    parameter is 64-bit statistical (module docstring). Read at call
    time so the bench battery and the CI legs can toggle it per step."""
    return os.environ.get("FSDKR_DELEGATE", "0").lower() in (
        "1", "on", "true", "yes",
    )


# ---------------------------------------------------------------------------
# Delegation statistics (the `delegate` field of the bench JSON): schemes
# and rows resolved by certificate, rejected certificates, actual counted
# group ops of the delegated checks, and rows that fell back to the
# honest path. Same registry-backed window view as backend.rlc.

_EVENTS = (
    "schemes_delegated", "rows_delegated", "certs_rejected",
    "fallback_rows", "group_ops",
)


def _metric():
    from ..telemetry import registry

    return registry.counter(
        "fsdkr_delegate_events",
        "Feldman MSM-delegation statistics (proofs.msm_delegate)",
        labelnames=("event",),
    )


def count(name: str, n: int = 1) -> None:
    _metric().inc(n, event=name)


def stats() -> Dict[str, int]:
    m = _metric()
    return {e: int(m.value(event=e)) for e in _EVENTS}


def stats_reset() -> None:
    _metric().reset()


# ---------------------------------------------------------------------------


def rho_vec(scheme, points: Sequence[Point], hash_alg=None) -> List[int]:
    """Fiat-Shamir coefficients rho_u, u = 1..n, each in [1, 2^64-1].

    The transcript binds the commitments A_k AND the public share
    points S_u: were rho derived from the A_k alone, an adversary could
    tamper shares in correlation against known coefficients
    (S_1 += D, S_2 -= rho_1/rho_2 * D) and keep the linear combination
    — binding the S_u re-randomizes every rho under any share edit.
    Nonzero by the [1, 2^64-1] reduction, so no row ever drops out of
    its own check."""
    base = Transcript(_DOMAIN, algorithm=hash_alg)
    for a_k in scheme.commitments:
        base.chain_point(a_k)
    for s_u in points:
        base.chain_point(s_u)
    seed = base.result_int()
    out = []
    for u in range(1, scheme.parameters.share_count + 1):
        t = Transcript(_DOMAIN, algorithm=hash_alg)
        t.chain_int(seed)
        t.chain_int(u)
        out.append(1 + t.result_challenge(RHO_BITS) % ((1 << RHO_BITS) - 1))
    return out


def emit_cert(scheme, shares, points: Sequence[Point], hash_alg=None) -> None:
    """Prover-side certificate at distribute: the prover HOLDS the
    shares f(u), so R = (sum_u rho_u * f(u) mod q) * G is one scalar
    fold plus ONE fixed-base generator mul — constant-size, constant
    work, attached in place as `scheme.delegate_cert` (rides the
    existing VSS wire encoding; broadcast-public by design)."""
    rho = rho_vec(scheme, points, hash_alg)
    sigma = 0
    for r, s in zip(rho, shares):
        sigma += r * s.to_int()
    scheme.delegate_cert = GENERATOR * (sigma % CURVE_N)


# -- shared-chain wNAF multi-scalar multiplication with a REAL op counter

_W = 4  # odd-multiple window width: {1,3,5,...,15}P per point


def _wnaf(k: int) -> List[int]:
    out = []
    while k:
        if k & 1:
            d = k & ((1 << (_W + 1)) - 1)
            if d >= (1 << _W):
                d -= 1 << (_W + 1)
            k -= d
        else:
            d = 0
        out.append(d)
        k >>= 1
    return out


def _msm(points: Sequence[Point], scalars: Sequence[int], ops: List[int]) -> Point:
    """sum_i scalars[i] * points[i] on ONE shared doubling chain
    (interleaved width-4 wNAF, Jacobian coordinates). `ops[0]` is
    incremented for every group double/add actually executed — the
    measured delegated-arm work of the acceptance A/B."""
    tables = []
    digit_vecs = []
    for pt, k in zip(points, scalars):
        k = int(k)
        if k == 0 or pt.infinity:
            continue
        dbl = _jdouble(pt.x, pt.y, 1)
        ops[0] += 1
        tbl = [(pt.x, pt.y, 1)]
        for _ in range((1 << (_W - 1)) - 1):
            tbl.append(_jadd(*tbl[-1], *dbl))
            ops[0] += 1
        tables.append(tbl)
        digit_vecs.append(_wnaf(k))
    if not tables:
        return Point.identity()
    top = max(len(d) for d in digit_vecs)
    rx, ry, rz = 0, 1, 0
    for i in range(top - 1, -1, -1):
        if rz != 0:
            rx, ry, rz = _jdouble(rx, ry, rz)
            ops[0] += 1
        for tbl, digits in zip(tables, digit_vecs):
            if i < len(digits) and digits[i]:
                d = digits[i]
                tx, ty, tz = tbl[(abs(d) - 1) >> 1]
                if d < 0:
                    ty = CURVE_P - ty
                rx, ry, rz = _jadd(rx, ry, rz, tx, ty, tz)
                ops[0] += 1
    return _jac_to_affine(rx, ry, rz)


def try_delegate(items, hash_alg=None) -> Optional[List[Optional[bool]]]:
    """Certificate pre-pass over validate_feldman items
    (scheme, public share point, 1-based index). Returns None when the
    arm is disabled (the caller runs its honest path untouched);
    otherwise a per-row list holding True for rows resolved by an
    ACCEPTED certificate and None for rows the caller must still verify
    honestly. Never returns False: a failing/missing certificate only
    ever demotes its scheme to the honest path (verdict bit-identity
    with FSDKR_DELEGATE=0 is structural — pinned by
    tests/test_delegate.py, including forged certificates)."""
    if not items or not delegate_enabled():
        return None
    out: List[Optional[bool]] = [None] * len(items)
    groups: Dict[int, List[int]] = {}
    for row, (scheme, _, _) in enumerate(items):
        groups.setdefault(id(scheme), []).append(row)
    for rows in groups.values():
        scheme = items[rows[0]][0]
        cert = getattr(scheme, "delegate_cert", None)
        n = scheme.parameters.share_count
        if cert is None or not scheme.commitments:
            count("fallback_rows", len(rows))
            continue
        by_u: Dict[int, Point] = {}
        consistent = True
        for row in rows:
            _, point, u = items[row]
            prev = by_u.get(u)
            if prev is not None and prev != point:
                consistent = False  # same slot, different claimed points
                break
            by_u[u] = point
        if not consistent or set(by_u) != set(range(1, n + 1)):
            # the certificate covers ALL n shares of the scheme; a
            # partial launch cannot check it and stays honest
            count("fallback_rows", len(rows))
            continue
        s_points = [by_u[u] for u in range(1, n + 1)]
        rho = rho_vec(scheme, s_points, hash_alg)
        ops = [0]
        s_side = _msm(s_points, rho, ops)
        t1 = len(scheme.commitments)
        c_vec = [0] * t1  # c_k = sum_u rho_u * u^k, PLAIN integers
        for u in range(1, n + 1):
            pw = rho[u - 1]
            for k in range(t1):
                c_vec[k] += pw
                pw *= u
        a_side = _msm(list(scheme.commitments), c_vec, ops)
        count("group_ops", ops[0])
        if s_side == cert and a_side == cert:
            count("schemes_delegated")
            count("rows_delegated", len(rows))
            for row in rows:
                out[row] = True
        else:
            count("certs_rejected")
            count("fallback_rows", len(rows))
    return out


def honest_model_ops(items) -> int:
    """Deterministic group-op model of the honest Feldman arm over the
    same rows: per row, Horner sum_k A_k u^k is t steps of mul-by-u
    plus add-A_k, with mul-by-u on a double-and-add chain costing
    (bitlen(u)-1) doublings + (popcount(u)-1) additions. The A/B
    publishes this count against the delegated arm's MEASURED ops —
    a model (not wall-time) because the honest arm runs in native C
    (native.ec.horner_batch), whose clock beats any Python MSM
    regardless of op count; ops are the implementation-neutral
    measure."""
    total = 0
    for scheme, _point, u in items:
        t_steps = max(0, len(scheme.commitments) - 1)
        per_step = (
            max(0, u.bit_length() - 1)
            + max(0, bin(u).count("1") - 1)
            + 1
        )
        total += t_steps * per_step
    return total
