"""Bob's MtA / MtAwc range proofs. PROTOCOL-DEAD in the refresh.

Re-derivation of the reference's `BobProof` / `BobProofExt`
(`/root/reference/src/range_proofs.rs:206-590`). These are protocol-dead in
the refresh itself (SURVEY.md §5 quirk 9 — kept for GG20 MtA
compatibility) but are part of the capability surface, and this framework's
GG20-style signing harness (`fsdkr_tpu.protocol.signing`) actually uses the
MtA algebra they attest to.

EXPLICIT DEAD-CODE MARKER (ISSUE 8 satellite): no collect()/verify_pairs
path constructs or verifies these proofs, and none of the batched
verifier families (backend.tpu_verifier) may grow a BobProof column
without first wiring domain gates + batch staging like the live
families — the per-row `verify` below is host-oracle-only. The module
is kept importable and round-tripping by
tests/test_range_engines.py::test_bob_range_importable_and_roundtrips
(cheap guard) and tests/test_proofs.py::TestBobRange (full MtA flow),
so it cannot silently rot or get pulled into the verifier by accident.

Statement: Alice's ciphertext c_a = Enc_ek(a), MtA output
c_out = b * c_a (+) Enc_ek(beta_prim, r). Bob proves b < q^3 (slack) and
consistency; the Ext variant additionally proves knowledge of b behind
X = b*G.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass
from typing import Optional

from ..core import intops
from ..core.paillier import EncryptionKey
from ..core.secp256k1 import N as CURVE_ORDER
from ..core.secp256k1 import Point, Scalar
from ..core.transcript import Transcript
from .composite_dlog import DLogStatement

__all__ = ["BobProof", "BobProofExt"]

_DOMAIN = b"fsdkr/bob-range/v1"


def _challenge(
    n: int,
    a_enc: int,
    mta_out: int,
    z: int,
    z_prim: int,
    t: int,
    v: int,
    w: int,
    check: Optional[tuple[Point, Point]],
    hash_alg: str | None = None,
) -> int:
    # transcript fields mirror /root/reference/src/range_proofs.rs:415-439
    tr = (
        Transcript(_DOMAIN, algorithm=hash_alg)
        .chain_int(n)
        .chain_int(n + 1)
        .chain_int(a_enc)
        .chain_int(mta_out)
        .chain_int(z)
        .chain_int(z_prim)
        .chain_int(t)
        .chain_int(v)
        .chain_int(w)
    )
    if check is not None:
        X, u = check
        tr.chain_int(X.x_coord()).chain_int(X.y_coord())
        tr.chain_int(u.x_coord()).chain_int(u.y_coord())
    return tr.result_challenge()


@dataclass(frozen=True)
class BobProof:
    t: int
    z: int
    e: int
    s: int
    s1: int
    s2: int
    t1: int
    t2: int

    @staticmethod
    def generate(
        a_encrypted: int,
        mta_encrypted: int,
        b: Scalar,
        beta_prim: int,
        alice_ek: EncryptionKey,
        dlog_statement: DLogStatement,
        r: int,
        check: bool = False,
        hash_alg: str | None = None,
    ) -> tuple["BobProof", Optional[Point]]:
        q = CURVE_ORDER
        h1, h2, n_tilde = dlog_statement.g, dlog_statement.ni, dlog_statement.N
        n, nn = alice_ek.n, alice_ek.nn
        b_int = b.to_int()

        # round 1 (reference :245-301); gamma/tau ranges per the reference's
        # documented deviation (range_proofs.rs:9)
        alpha = secrets.randbelow(q**3)
        beta = intops.sample_unit(n)
        gamma = secrets.randbelow(q**2 * n)
        rho = secrets.randbelow(q * n_tilde)
        rho_prim = secrets.randbelow(q**3 * n_tilde)
        sigma = secrets.randbelow(q * n_tilde)
        tau = secrets.randbelow(q**3 * n_tilde)

        z = intops.mod_pow(h1, b_int, n_tilde) * intops.mod_pow(h2, rho, n_tilde) % n_tilde
        z_prim = intops.mod_pow(h1, alpha, n_tilde) * intops.mod_pow(h2, rho_prim, n_tilde) % n_tilde
        t = intops.mod_pow(h1, beta_prim, n_tilde) * intops.mod_pow(h2, sigma, n_tilde) % n_tilde
        w = intops.mod_pow(h1, gamma, n_tilde) * intops.mod_pow(h2, tau, n_tilde) % n_tilde
        v = (
            intops.mod_pow(a_encrypted, alpha, nn)
            * ((1 + gamma * n) % nn)
            * intops.mod_pow(beta, n, nn)
            % nn
        )

        check_pair = None
        u_point = None
        if check:
            X = Point.generator() * b
            u_point = Point.generator() * Scalar.from_int(alpha)
            check_pair = (X, u_point)

        e = _challenge(
            n, a_encrypted, mta_encrypted, z, z_prim, t, v, w, check_pair,
            hash_alg,
        )

        # round 2 (reference :313-336)
        proof = BobProof(
            t=t,
            z=z,
            e=e,
            s=intops.mod_pow(r, e, n) * beta % n,
            s1=e * b_int + alpha,
            s2=e * rho + rho_prim,
            t1=e * beta_prim + gamma,
            t2=e * sigma + tau,
        )
        # round-1 nonces (alpha..tau) die with this frame on return — the
        # reference zeroizes BobZkpRound1 explicitly (range_proofs.rs:222-243)
        # because its round structs outlive the round; here they never
        # escape the prover call
        return proof, u_point

    def verify(
        self,
        a_enc: int,
        mta_avc_out: int,
        alice_ek: EncryptionKey,
        dlog_statement: DLogStatement,
        check: Optional[tuple[Point, Point]] = None,
        hash_alg: str | None = None,
    ) -> bool:
        q = CURVE_ORDER
        h1, h2, n_tilde = dlog_statement.g, dlog_statement.ni, dlog_statement.N
        n, nn = alice_ek.n, alice_ek.nn

        if self.s1 > q**3 or self.s1 < 0:
            return False

        z_e_inv = intops.mod_inv(intops.mod_pow(self.z, self.e, n_tilde), n_tilde)
        if z_e_inv is None:
            return False
        z_prim = intops.mod_pow(h1, self.s1, n_tilde) * intops.mod_pow(h2, self.s2, n_tilde) * z_e_inv % n_tilde

        mta_e_inv = intops.mod_inv(intops.mod_pow(mta_avc_out, self.e, nn), nn)
        if mta_e_inv is None:
            return False
        v = (
            intops.mod_pow(a_enc, self.s1, nn)
            * intops.mod_pow(self.s, n, nn)
            * ((1 + self.t1 * n) % nn)
            * mta_e_inv
            % nn
        )

        t_e_inv = intops.mod_inv(intops.mod_pow(self.t, self.e, n_tilde), n_tilde)
        if t_e_inv is None:
            return False
        w = intops.mod_pow(h1, self.t1, n_tilde) * intops.mod_pow(h2, self.t2, n_tilde) * t_e_inv % n_tilde

        return (
            _challenge(
                n, a_enc, mta_avc_out, self.z, z_prim, self.t, v, w, check,
                hash_alg,
            )
            == self.e
        )


@dataclass(frozen=True)
class BobProofExt:
    """Bob's proof extended with knowledge of B = b*G
    (reference `src/range_proofs.rs:518-590`)."""

    proof: BobProof
    u: Point

    @staticmethod
    def generate(
        a_encrypted: int,
        mta_encrypted: int,
        b: Scalar,
        beta_prim: int,
        alice_ek: EncryptionKey,
        dlog_statement: DLogStatement,
        r: int,
        hash_alg: str | None = None,
    ) -> "BobProofExt":
        proof, u = BobProof.generate(
            a_encrypted,
            mta_encrypted,
            b,
            beta_prim,
            alice_ek,
            dlog_statement,
            r,
            check=True,
            hash_alg=hash_alg,
        )
        assert u is not None
        return BobProofExt(proof=proof, u=u)

    def verify(
        self,
        a_enc: int,
        mta_avc_out: int,
        alice_ek: EncryptionKey,
        dlog_statement: DLogStatement,
        X: Point,
        hash_alg: str | None = None,
    ) -> bool:
        if not self.proof.verify(
            a_enc, mta_avc_out, alice_ek, dlog_statement, check=(X, self.u),
            hash_alg=hash_alg,
        ):
            return False
        # EC consistency: s1*G == e*X + u (reference :549-560)
        x1 = Point.generator() * Scalar.from_int(self.proof.s1)
        x2 = X * Scalar.from_int(self.proof.e) + self.u
        return x1 == x2
