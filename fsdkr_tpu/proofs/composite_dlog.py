"""Composite discrete-log proof over Z_N-tilde^*.

Equivalent of zk-paillier's `DLogStatement` / `CompositeDLogProof`
(consumed by the reference at `/root/reference/src/add_party_message.rs:84-85`
and verified in both base directions at `src/refresh_message.rs:415-425`).

Statement (N, g, ni) with secret x such that ni = g^{-x} mod N
(the join path supplies x = phi - xhi where ni = g^{xhi},
`src/add_party_message.rs:62-64`). Schnorr-style sigma protocol made
non-interactive via Fiat-Shamir:

    prove:  r <- [0, N * 2^STAT_BITS);  C = g^r mod N
            e = H(C, g, N, ni);         y = r + e*x   (over the integers)
    verify: g^y * ni^e == C  (mod N)
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass

from ..core import intops
from ..core.transcript import Transcript

__all__ = ["DLogStatement", "CompositeDLogProof", "STAT_BITS"]

# statistical hiding slack for the integer response y = r + e*x
STAT_BITS = 256 + 128

_DOMAIN = b"fsdkr/composite-dlog/v1"


@dataclass(frozen=True)
class DLogStatement:
    """(N, g, ni): field names mirror the reference's `DLogStatement`
    shape (`/root/reference/src/add_party_message.rs:72-82`); in protocol
    use g = h1, ni = h2, N = N_tilde."""

    N: int
    g: int
    ni: int


@dataclass(frozen=True)
class CompositeDLogProof:
    x_commit: int  # C = g^r mod N
    y: int  # integer response

    @staticmethod
    def _challenge(
        x_commit: int, st: DLogStatement, hash_alg: str | None = None
    ) -> int:
        return (
            Transcript(_DOMAIN, algorithm=hash_alg)
            .chain_int(x_commit)
            .chain_int(st.g)
            .chain_int(st.N)
            .chain_int(st.ni)
            .result_challenge()
        )

    @staticmethod
    def prove(
        st: DLogStatement, secret_x: int, hash_alg: str | None = None
    ) -> "CompositeDLogProof":
        r = secrets.randbelow(st.N << STAT_BITS)
        x_commit = intops.mod_pow(st.g, r, st.N)
        e = CompositeDLogProof._challenge(x_commit, st, hash_alg)
        return CompositeDLogProof(x_commit=x_commit, y=r + e * secret_x)

    def verify(self, st: DLogStatement, hash_alg: str | None = None) -> bool:
        if not (0 < self.x_commit < st.N) or self.y < 0:
            return False
        if st.N <= 2 or st.g < 0 or st.ni < 0:  # fail closed, no crash
            return False
        e = CompositeDLogProof._challenge(self.x_commit, st, hash_alg)
        lhs = intops.mod_pow(st.g, self.y, st.N) * intops.mod_pow(st.ni, e, st.N) % st.N
        return lhs == self.x_commit
