"""Zero-knowledge proof layer (SURVEY.md §2a L3).

Six proof systems, each with prove/generate + verify and a soundness
negative test in tests/test_proofs.py:

- alice_range: Paillier ciphertext encrypts a value < q^3 (slack range)
  — reference `src/range_proofs.rs` AliceProof.
- bob_range: Bob's MtA / MtAwc proofs — protocol-dead in the reference
  (SURVEY.md §5 quirk 9) but part of the capability surface.
- pdl_slack: ciphertext and EC point hide the same x — reference
  `src/zk_pdl_with_slack.rs`.
- ring_pedersen: well-formedness of ring-Pedersen parameters (S = T^lambda)
  — reference `src/ring_pedersen_proof.rs`.
- composite_dlog: discrete log over Z_N-tilde^* (zk-paillier
  CompositeDLogProof equivalent).
- correct_key: Paillier key correctness via N-th roots (zk-paillier
  NiCorrectKeyProof equivalent).

Every verifier here is the host oracle; the batched TPU verifiers in
`fsdkr_tpu.backend` evaluate the same equations over limb tensors.
"""

from .composite_dlog import DLogStatement, CompositeDLogProof
from .alice_range import AliceProof
from .bob_range import BobProof, BobProofExt
from .pdl_slack import PDLwSlackStatement, PDLwSlackWitness, PDLwSlackProof
from .ring_pedersen import RingPedersenStatement, RingPedersenWitness, RingPedersenProof
from .correct_key import NiCorrectKeyProof, SALT_STRING

__all__ = [
    "DLogStatement",
    "CompositeDLogProof",
    "AliceProof",
    "BobProof",
    "BobProofExt",
    "PDLwSlackStatement",
    "PDLwSlackWitness",
    "PDLwSlackProof",
    "RingPedersenStatement",
    "RingPedersenWitness",
    "RingPedersenProof",
    "NiCorrectKeyProof",
    "SALT_STRING",
]
