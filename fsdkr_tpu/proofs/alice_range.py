"""Alice's range proof: a Paillier ciphertext encrypts a value in the slack
range [0, q^3).

Re-derivation of the reference's `AliceProof`
(`/root/reference/src/range_proofs.rs:40-203`; GG19 Appendix-A MtA proof,
non-interactive via Fiat-Shamir). Notation matches the reference:

  prover (secret a < q, randomness r of c = Enc_ek(a, r)):
    alpha < q^3, beta <- Z_n^*, gamma < q^3*Ntilde, rho < q*Ntilde
    z = h1^a  h2^rho   mod Ntilde
    u = (1 + alpha*n) beta^n mod n^2          (= Enc(alpha, beta))
    w = h1^alpha h2^gamma mod Ntilde
    e = H(n, n+1, c, z, u, w)
    s = r^e beta mod n; s1 = e*a + alpha; s2 = e*rho + gamma

  verifier: reject if s1 > q^3; recompute
    w' = h1^s1 h2^s2 (z^e)^{-1} mod Ntilde
    u' = (1 + s1*n) s^n (c^e)^{-1} mod n^2
    accept iff H(n, n+1, c, z, u', w') == e
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass

from ..core import intops
from ..core.paillier import EncryptionKey
from ..core.secp256k1 import N as CURVE_ORDER
from ..core.transcript import Transcript
from .composite_dlog import DLogStatement

__all__ = ["AliceProof"]

_DOMAIN = b"fsdkr/alice-range/v1"


def _challenge(
    n: int, c: int, z: int, u: int, w: int, hash_alg: str | None = None
) -> int:
    # transcript fields mirror /root/reference/src/range_proofs.rs:150-157
    return (
        Transcript(_DOMAIN, algorithm=hash_alg)
        .chain_int(n)
        .chain_int(n + 1)
        .chain_int(c)
        .chain_int(z)
        .chain_int(u)
        .chain_int(w)
        .result_challenge()
    )


@dataclass(frozen=True)
class AliceProof:
    z: int
    e: int
    s: int
    s1: int
    s2: int

    @staticmethod
    def generate(
        a: int,
        cipher: int,
        alice_ek: EncryptionKey,
        dlog_statement: DLogStatement,
        r: int,
        q: int = CURVE_ORDER,
        hash_alg: str | None = None,
    ) -> "AliceProof":
        return AliceProof.generate_batch(
            [(a, cipher, alice_ek, dlog_statement, r)], q, hash_alg=hash_alg
        )[0]

    # Two-phase batched prover (same protocol as PDLwSlackProof's: stage1
    # emits columns, stage2 the response column) so distribute_batch can
    # fuse both families' same-width columns into shared launches.

    @staticmethod
    def sample_stage1(ntv, nv, q: int = CURVE_ORDER):
        """Input-independent stage-1 nonce sampling — THE one sampler
        for the inline prover and the offline precompute producer
        (fsdkr_tpu.precompute; see PDLwSlackProof.sample_stage1).
        Returns (alpha, beta, gamma, rho) columns (this prover's
        historical sampling order: beta before gamma/rho)."""
        q3 = q**3
        alpha = [secrets.randbelow(q3) for _ in ntv]
        beta = [intops.sample_unit(n) for n in nv]
        gamma = [secrets.randbelow(q3 * nt) for nt in ntv]
        rho = [secrets.randbelow(q * nt) for nt in ntv]
        return alpha, beta, gamma, rho

    @staticmethod
    def produce_stage1(h1, h2, nt, n, count, powm=None, q: int = CURVE_ORDER):
        """Offline producer constructor: `count` stage-1 bundles for ONE
        receiver environment — (alpha, beta, rho, gamma, beta^n mod n^2,
        h2^rho mod N~, h1^alpha*h2^gamma mod N~), the same 7-tuple shape
        as PDLwSlackProof.produce_stage1 (the two differ only in their
        beta distribution, kept by the shared samplers)."""
        if powm is None:
            # plain batch engine (GMP host route); see
            # PDLwSlackProof.produce_stage1 for the measured rationale
            from ..backend.powm import host_powm as powm
        from ..backend.powm import powm_columns

        nn = n * n
        alpha, beta, gamma, rho = AliceProof.sample_stage1(
            [nt] * count, [n] * count, q
        )
        h2rho, ca, cg, bn = powm_columns(
            powm,
            ([h2] * count, rho, [nt] * count),
            ([h1] * count, alpha, [nt] * count),
            ([h2] * count, gamma, [nt] * count),
            (beta, [n] * count, [nn] * count),
        )
        w = intops.mod_mul_col(ca, cg, [nt] * count)
        return [
            (alpha[i], beta[i], rho[i], gamma[i], bn[i], h2rho[i], w[i])
            for i in range(count)
        ]

    @staticmethod
    def generate_stage1(
        avals, rvals, h1v, h2v, ntv, nv, nnv, q: int = CURVE_ORDER,
        hash_alg: str | None = None, pooled=None,
    ):
        if q.bit_length() > 256:
            raise ValueError(
                "SHA-256 transcripts support group orders up to 256 bits"
            )
        from ..backend.powm import multiexp_enabled

        joint = multiexp_enabled()
        # CONTRACT: the beta^n mod n^2 column is LAST in every layout —
        # distribute_batch splits it into the fused Paillier launch (its
        # own sub-phase trace) by position.
        if pooled is None:
            alpha, beta, gamma, rho = AliceProof.sample_stage1(ntv, nv, q)
            state = dict(
                avals=avals, rvals=rvals, alpha=alpha, beta=beta,
                gamma=gamma, rho=rho, ntv=ntv, nv=nv, nnv=nnv,
                hash_alg=hash_alg, joint=joint,
            )
            if joint:
                # z/w as joint multi-exponentiation rows (see
                # PDLwSlackProof.prove_stage1): the mod_mul_col
                # recombination moves into the planner's launch plan
                cols = [
                    (list(zip(h1v, h2v)), list(zip(avals, rho)), ntv),
                    (list(zip(h1v, h2v)), list(zip(alpha, gamma)), ntv),
                    (beta, nv, nnv),
                ]
            else:
                cols = [
                    (h1v, avals, ntv),
                    (h2v, rho, ntv),
                    (h1v, alpha, ntv),
                    (h2v, gamma, ntv),
                    (beta, nv, nnv),
                ]
            return state, cols

        # FSDKR_PRECOMPUTE: pooled rows keep only the witness factor
        # h1^a online (the full-rows column below deduplicates with the
        # PDL prover's identical share column inside powm_columns); dry
        # rows ride fallback columns, bit-identical to inline
        rows = len(ntv)
        fb = [i for i in range(rows) if pooled[i] is None]
        s_alpha, s_beta, s_gamma, s_rho = AliceProof.sample_stage1(
            [ntv[i] for i in fb], [nv[i] for i in fb], q
        )
        alpha = [0] * rows
        beta = [0] * rows
        gamma = [0] * rows
        rho = [0] * rows
        pool_bn, pool_h2rho, pool_w = {}, {}, {}
        for i, p in enumerate(pooled):
            if p is not None:
                (alpha[i], beta[i], rho[i], gamma[i],
                 pool_bn[i], pool_h2rho[i], pool_w[i]) = p
        for j, i in enumerate(fb):
            alpha[i], beta[i], gamma[i], rho[i] = (
                s_alpha[j], s_beta[j], s_gamma[j], s_rho[j]
            )
        state = dict(
            avals=avals, rvals=rvals, alpha=alpha, beta=beta, gamma=gamma,
            rho=rho, ntv=ntv, nv=nv, nnv=nnv, hash_alg=hash_alg, joint=joint,
            pooled_mode=True, fb=fb, pool_bn=pool_bn, pool_h2rho=pool_h2rho,
            pool_w=pool_w,
        )
        nt_fb = [ntv[i] for i in fb]
        if joint:
            w_cols = [(
                [(h1v[i], h2v[i]) for i in fb],
                [(alpha[i], gamma[i]) for i in fb],
                nt_fb,
            )]
        else:
            w_cols = [
                ([h1v[i] for i in fb], [alpha[i] for i in fb], nt_fb),
                ([h2v[i] for i in fb], [gamma[i] for i in fb], nt_fb),
            ]
        cols = [
            (h1v, avals, ntv),
            ([h2v[i] for i in fb], [rho[i] for i in fb], nt_fb),
            *w_cols,
            ([beta[i] for i in fb], [nv[i] for i in fb],
             [nnv[i] for i in fb]),
        ]
        return state, cols

    @staticmethod
    def generate_stage2(state, results, ciphers):
        ntv, nv, nnv = state["ntv"], state["nv"], state["nnv"]
        alpha = state["alpha"]
        from ..core import paillier

        if state.get("pooled_mode"):
            fb = state["fb"]
            rows = len(ntv)
            h2rho = [state["pool_h2rho"].get(i) for i in range(rows)]
            w = [state["pool_w"].get(i) for i in range(rows)]
            bn = [state["pool_bn"].get(i) for i in range(rows)]
            for j, i in enumerate(fb):
                h2rho[i] = results[1][j]
                bn[i] = results[-1][j]
            if state.get("joint"):
                for j, i in enumerate(fb):
                    w[i] = results[2][j]
            else:
                w_fb = intops.mod_mul_col(
                    results[2], results[3], [ntv[i] for i in fb]
                )
                for j, i in enumerate(fb):
                    w[i] = w_fb[j]
            z = intops.mod_mul_col(results[0], h2rho, ntv)
        elif state.get("joint"):
            z, w, bn = results
        else:
            c1, c2, c3, c4, bn = results
            z = intops.mod_mul_col(c1, c2, ntv)
            w = intops.mod_mul_col(c3, c4, ntv)
        u = paillier.combine_with_rn(alpha, bn, nv, nnv)  # Enc(alpha; beta)
        e = [
            _challenge(n, cipher, zi, ui, wi, state["hash_alg"])
            for cipher, n, zi, ui, wi in zip(ciphers, nv, z, u, w)
        ]
        state.update(z=z, e=e)
        return state, [(state["rvals"], e, nv)]

    @staticmethod
    def generate_finish(state, results):
        (re_,) = results
        alpha, beta, rho, gamma = (
            state["alpha"], state["beta"], state["rho"], state["gamma"],
        )
        proofs = [
            AliceProof(
                z=zi,
                e=ei,
                s=x * b % n,
                s1=ei * a + al,
                s2=ei * ro + ga,
            )
            for a, n, zi, ei, x, b, al, ro, ga in zip(
                state["avals"], state["nv"], state["z"], state["e"], re_,
                beta, alpha, rho, gamma,
            )
        ]
        intops.zeroize_ints(alpha, beta, rho, gamma)
        return proofs

    @staticmethod
    def generate_batch(
        items, q: int = CURVE_ORDER, powm=None, hash_alg: str | None = None
    ) -> list["AliceProof"]:
        """Batched prover over items = [(a, cipher, ek, dlog_statement, r)].

        The per-receiver fan-out of distribute (reference
        `/root/reference/src/refresh_message.rs:106-116`) runs as six
        modexp columns (+ one post-challenge column) through `powm` —
        host pow or one TPU launch per column.
        """
        if powm is None:
            from ..backend.powm import host_powm as powm
        from ..backend.powm import powm_columns

        state, cols = AliceProof.generate_stage1(
            [a for a, *_ in items],
            [r for *_, r in items],
            [d.g for _, _, _, d, _ in items],
            [d.ni for _, _, _, d, _ in items],
            [d.N for _, _, _, d, _ in items],
            [ek.n for _, _, ek, _, _ in items],
            [ek.nn for _, _, ek, _, _ in items],
            q,
            hash_alg,
        )
        state, cols2 = AliceProof.generate_stage2(
            state, powm_columns(powm, *cols), [c for _, c, _, _, _ in items]
        )
        return AliceProof.generate_finish(state, powm_columns(powm, *cols2))

    # NOTE on FSDKR_RLC: this family does NOT fold into the cross-proof
    # randomized batch check (backend.rlc). The verifier accepts iff
    # H(n, c, z, u', w') == e with u', w' RECONSTRUCTED from the response
    # — the Fiat-Shamir hash binds the per-row group elements themselves,
    # so there is no per-row equation of the form lhs == rhs whose random
    # linear combination could replace computing u'/w' individually. The
    # range columns keep the joint/column path; only the domain gate
    # below is shared with the RLC-aggregating families (gating must run
    # before any aggregation or staging in every mode).

    @staticmethod
    def domain_gate(proof: "AliceProof", cipher: int,
                    dlog_statement: DLogStatement,
                    q: int = CURVE_ORDER) -> bool:
        """Wire-domain gate for one row of the batched verifier, applied
        BEFORE staging or hashing. s1's q^3 slack bound is the proof's
        own range gate (`/root/reference/src/range_proofs.rs:125`),
        enforced pre-launch; s2/e width caps are the honest-value bounds
        (s2 = e*rho + gamma < q^3 * N~ * 2^{small}); the remaining fields
        must be non-negative for chain_int / the limb encoder."""
        return (
            0 <= proof.s1 <= q**3
            and 0 <= proof.s2
            and proof.s2.bit_length() <= dlog_statement.N.bit_length() + 832
            and 0 <= proof.e < (1 << 256)
            and proof.z >= 0
            and proof.s >= 0
            and cipher >= 0
        )

    def verify(
        self,
        cipher: int,
        alice_ek: EncryptionKey,
        dlog_statement: DLogStatement,
        q: int = CURVE_ORDER,
        hash_alg: str | None = None,
    ) -> bool:
        h1, h2, n_tilde = dlog_statement.g, dlog_statement.ni, dlog_statement.N
        n, nn = alice_ek.n, alice_ek.nn

        # range gate (/root/reference/src/range_proofs.rs:125), plus
        # fail-closed domain gates for the remaining integers (negative
        # values would crash the transcript, not fail the proof)
        if self.s1 > q**3 or self.s1 < 0:
            return False
        if min(self.z, self.e, self.s, self.s2, cipher) < 0:
            return False

        z_e_inv = intops.mod_inv(intops.mod_pow(self.z, self.e, n_tilde), n_tilde)
        if z_e_inv is None:
            return False
        w = (
            intops.mod_pow(h1, self.s1, n_tilde)
            * intops.mod_pow(h2, self.s2, n_tilde)
            * z_e_inv
            % n_tilde
        )

        cipher_e_inv = intops.mod_inv(intops.mod_pow(cipher, self.e, nn), nn)
        if cipher_e_inv is None:
            return False
        gs1 = (1 + self.s1 * n) % nn
        u = gs1 * intops.mod_pow(self.s, n, nn) * cipher_e_inv % nn

        return _challenge(n, cipher, self.z, u, w, hash_alg) == self.e
