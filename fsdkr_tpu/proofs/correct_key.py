"""Paillier correct-key proof: the prover knows the factorization of N and
N is a well-formed Paillier modulus.

Equivalent of zk-paillier's `NiCorrectKeyProof` (consumed by the reference
at `/root/reference/src/refresh_message.rs:119,375-384`; mechanism cited in
the reference README: Fiat-Shamir-derived group elements, prover returns
their N-th roots, verifier re-derives and checks sigma_i^N == rho_i mod N).

Details of this framework's instantiation:
- rho_i = MGF(N, salt, i) mod N, where MGF is SHA-256 counter-mode
  expansion to |N| + 128 bits (uniform mod N up to negligible bias).
- The prover computes sigma_i = rho_i^{N^{-1} mod phi} mod N — possible
  iff gcd(N, phi(N)) = 1, which holds for products of two distinct
  random primes with overwhelming probability.
- The verifier additionally rejects N with prime factors < 4000 and N
  even / too small, mirroring zk-paillier's small-factor gate.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List

from ..config import DEFAULT_CONFIG
from ..core import intops
from ..core.paillier import DecryptionKey, EncryptionKey
from ..core.primes import _PRIMORIAL
from ..core.transcript import Transcript

__all__ = ["NiCorrectKeyProof", "SALT_STRING"]

# Same role as zk-paillier's SALT_STRING constant (a public domain-separation
# salt for the challenge derivation).
SALT_STRING = b"fsdkr/correct-key/salt/v1"

_DOMAIN = b"fsdkr/correct-key/v1"


def _derive_rho(
    n: int, salt: bytes, index: int, hash_alg: str | None = None
) -> int:
    """Hash-expand (N, salt, index) to |N|+128 bits, reduce mod N."""
    need_bytes = (n.bit_length() + 127) // 8 + 16
    out = b""
    counter = 0
    while len(out) < need_bytes:
        out += (
            Transcript(_DOMAIN, algorithm=hash_alg)
            .chain_int(n)
            .chain_bytes(salt)
            .chain_int(index)
            .chain_int(counter)
            .result_bytes()
        )
        counter += 1
    return int.from_bytes(out[:need_bytes], "big") % n


@dataclass(frozen=True)
class NiCorrectKeyProof:
    sigma_vec: List[int]

    @staticmethod
    def derive_targets(
        n: int,
        salt: bytes = SALT_STRING,
        rounds: int = DEFAULT_CONFIG.correct_key_rounds,
        hash_alg: str | None = None,
    ) -> List[int]:
        """The Fiat-Shamir-derived group elements rho_i the prover must
        root — a pure function of the PUBLIC modulus (no prover nonces
        at all), shared by proof_batch and the batched verifier. Because
        the whole proof depends on the key alone, complete proofs are
        input-independent and ride the precompute key-material pool
        (fsdkr_tpu/precompute)."""
        return [_derive_rho(n, salt, i, hash_alg) for i in range(rounds)]

    @staticmethod
    def proof(
        dk: DecryptionKey,
        salt: bytes = SALT_STRING,
        rounds: int = DEFAULT_CONFIG.correct_key_rounds,
        powm=None,
        hash_alg: str | None = None,
    ) -> "NiCorrectKeyProof":
        return NiCorrectKeyProof.proof_batch([dk], salt, rounds, powm, hash_alg)[0]

    @staticmethod
    def proof_batch(
        dks: List[DecryptionKey],
        salt: bytes = SALT_STRING,
        rounds: int = DEFAULT_CONFIG.correct_key_rounds,
        powm=None,
        hash_alg: str | None = None,
    ) -> List["NiCorrectKeyProof"]:
        """All provers' N-th-root columns in ONE modexp launch (the
        cross-sender batch axis of a refresh, SURVEY.md §1). The prover
        owns every row's factorization (d = N^{-1} mod phi exists only
        because it does), so the column rides the secret-CRT planner
        route (backend.powm.crt_powm, FSDKR_CRT): d reduced mod p-1/q-1
        halves both the exponent and the limb width per fault-checked
        leg; =0 keeps the full-width `powm` path bit-identically."""
        from ..backend.powm import crt_powm

        bases, exps, mods, factors = [], [], [], []
        for dk in dks:
            n = dk.p * dk.q
            phi = (dk.p - 1) * (dk.q - 1)
            d = pow(n, -1, phi)  # x -> x^d inverts x -> x^N on Z_N^*
            bases += NiCorrectKeyProof.derive_targets(n, salt, rounds, hash_alg)
            exps += [d] * rounds
            mods += [n] * rounds
            factors += [(dk.p, dk.q)] * rounds
        sigma = crt_powm(bases, exps, mods, factors, powm)
        return [
            NiCorrectKeyProof(sigma_vec=sigma[k * rounds : (k + 1) * rounds])
            for k in range(len(dks))
        ]

    @staticmethod
    def rlc_fold(sigma_vec, rho_targets, n: int, rhos):
        """Fold the per-round checks sigma_i^N == rho_i (mod N) into one
        Bellare-Garay-Rabin small-exponent RLC check

            (prod_i sigma_i^{rho_i})^N == prod_i rho_i^{rho_i}  (mod N)

        over the caller's secret fresh 128-bit coefficients (the shared
        exponent N factors out of the combination, so the proof's
        `rounds` full-width ladders collapse to ONE). Returns
        (sigma_row, target_row) joint multi-exponentiation rows riding
        short aggregated chains; the caller raises sigma_row's result to
        N — the single remaining full-width ladder — and compares.
        Domain gating (verify's parity/small-factor/range checks) must
        run BEFORE aggregation."""
        return (
            (tuple(sigma_vec), tuple(rhos), n),
            (tuple(rho_targets), tuple(rhos), n),
        )

    def verify(
        self,
        ek: EncryptionKey,
        salt: bytes = SALT_STRING,
        rounds: int = DEFAULT_CONFIG.correct_key_rounds,
        hash_alg: str | None = None,
    ) -> bool:
        n = ek.n
        if len(self.sigma_vec) != rounds:
            return False
        # small-factor / parity gate
        if n <= 0 or n % 2 == 0 or math.gcd(n, _PRIMORIAL) != 1:
            return False
        for i, sigma in enumerate(self.sigma_vec):
            if not (0 < sigma < n):
                return False
            if intops.mod_pow(sigma, n, n) != _derive_rho(n, salt, i, hash_alg):
                return False
        return True
