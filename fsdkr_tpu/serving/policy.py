"""Serving policies: finalize batching, admission overload shedding,
and the bisection-storm guard (ISSUE 9 batching; ISSUE 11 degradation).

`BatchPolicy` — quorum-ready streaming sessions are fused into one
`finalize_streams` launch; the policy decides WHEN to launch and HOW
MANY sessions to take. Classic size-or-linger batching: launch
immediately once `FSDKR_SERVE_BATCH` sessions are ready, otherwise wait
up to `FSDKR_SERVE_LINGER_MS` from the oldest ready session before
launching whatever is there — throughput from fusion without unbounded
latency (the SZKP-style producer/consumer decoupling needs the consumer
launch to stay full, but a p99 budget caps how long a session may sit
waiting for company).

Mesh awareness: on a real device mesh the fused pair launch row-shards
over all devices, so the policy prefers batch sizes whose total row
count divides the mesh (`parallel.shard_kernels.align_session_batch`);
on the host path (device count 1) alignment is a no-op.

`OverloadPolicy` — graceful degradation at admission (2G2T's
loaded-shard regime: keep the latency SLO by shedding, not by queueing
divergence). `submit()` is rejected with a retry-after hint when the
admission queue is past `FSDKR_SERVE_MAX_QUEUE` or the measured
end-to-end p99 exceeds `FSDKR_SERVE_SHED_P99` x the committee's SLO
budget. Both default OFF (0): an unconfigured service behaves exactly
as before.

`BisectGuard` — per-committee budget on RLC bisection work per sliding
window (ROADMAP 5b economics). Honest transcripts bisect ZERO times, so
bisections are an attributable cost of tampered traffic; a committee
whose sessions forced more than `FSDKR_SERVE_BISECT_BUDGET` bisection
fallbacks inside `FSDKR_SERVE_BISECT_WINDOW_S` seconds is shed at
admission until the window rolls — 5% malicious traffic pays with its
own committee's throughput instead of DoSing the shard's verify
engines. Default OFF (budget 0).

`PeerRateLimiter` — per-peer token bucket for the network ingress
(ISSUE 13), charged like the BisectGuard: a peer sending faster than
`FSDKR_INGRESS_PEER_RPS` requests/second (burst = 2x) gets its request
shed with a retry-after hint, and a peer that keeps hammering past the
shed threshold pays with its own connection — the other peers'
connections never feel it. Default OFF (rps 0).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, Optional

__all__ = ["BatchPolicy", "OverloadPolicy", "BisectGuard", "PeerRateLimiter"]


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


class BatchPolicy:
    """Size-or-linger coalescing. `max_sessions` counts collector
    streams (one committee refresh with n collecting parties contributes
    n of them)."""

    def __init__(
        self,
        max_sessions: int = 0,
        linger_s: float = -1.0,
        devices: int = 1,
    ):
        self.max_sessions = max_sessions or _env_int("FSDKR_SERVE_BATCH", 16)
        self.linger_s = (
            linger_s
            if linger_s >= 0
            else _env_float("FSDKR_SERVE_LINGER_MS", 50.0) / 1000.0
        )
        self.devices = max(1, devices)

    def take(
        self, ready: int, oldest_wait_s: float, rows_per_session: int = 0
    ) -> int:
        """How many ready sessions to fuse into a launch right now;
        0 = keep lingering. Never returns more than `ready`."""
        if ready <= 0:
            return 0
        if ready < self.max_sessions and oldest_wait_s < self.linger_s:
            return 0
        count = min(ready, self.max_sessions)
        if self.devices > 1 and rows_per_session > 0:
            from ..parallel.shard_kernels import align_session_batch

            count = align_session_batch(count, rows_per_session, self.devices)
        return count

    def wait_budget(self, oldest_wait_s: float) -> float:
        """Seconds the launcher may sleep before the linger deadline of
        the oldest ready session expires."""
        return max(0.0, self.linger_s - oldest_wait_s)


class OverloadPolicy:
    """Admission-time shedding. `check()` returns None (admit) or a
    retry-after hint in seconds (reject). Reads its thresholds from the
    environment at construction; both gates default off."""

    def __init__(
        self,
        max_queue: Optional[int] = None,
        shed_p99_factor: Optional[float] = None,
    ):
        self.max_queue = (
            max_queue
            if max_queue is not None
            else _env_int("FSDKR_SERVE_MAX_QUEUE", 0)
        )
        self.shed_p99_factor = (
            shed_p99_factor
            if shed_p99_factor is not None
            else _env_float("FSDKR_SERVE_SHED_P99", 0.0)
        )

    def engaged(self) -> bool:
        """False when both gates are off (the default) — the caller can
        then skip computing the measured p99 entirely, keeping the
        submit hot path free of histogram scans under the service
        lock."""
        return self.max_queue > 0 or self.shed_p99_factor > 0

    def check(
        self,
        queue_depth: int,
        measured_p99_s: float,
        p99_budget_s: float,
    ) -> Optional[float]:
        """None = admit. A float = reject, retry after that many
        seconds. The hint is honest but cheap: the measured p99 itself
        (the time by which the backlog that caused the shed has very
        likely cleared), floored at 100 ms."""
        if self.max_queue > 0 and queue_depth >= self.max_queue:
            return max(0.1, measured_p99_s)
        if (
            self.shed_p99_factor > 0
            and p99_budget_s > 0
            and measured_p99_s > self.shed_p99_factor * p99_budget_s
        ):
            return max(0.1, measured_p99_s)
        return None


class BisectGuard:
    """Sliding-window per-committee budget on RLC bisection fallbacks.
    `charge(committee, n)` records bisection work attributed to the
    committee; `blocked(committee)` returns the seconds until its
    window has room again, or None while it is under budget. Committees
    never forced a bisection (every honest committee) are never
    touched. Budget 0 disables the guard entirely."""

    def __init__(
        self,
        budget: Optional[int] = None,
        window_s: Optional[float] = None,
    ):
        self.budget = (
            budget
            if budget is not None
            else _env_int("FSDKR_SERVE_BISECT_BUDGET", 0)
        )
        self.window_s = (
            window_s
            if window_s is not None
            else _env_float("FSDKR_SERVE_BISECT_WINDOW_S", 60.0)
        )
        self._events: Dict[object, deque] = {}
        # charged by the launcher thread, read by submit() under the
        # service lock — the guard carries its own lock
        self._lock = threading.Lock()

    def enabled(self) -> bool:
        return self.budget > 0

    def _prune(self, q: deque, now: float) -> None:
        while q and now - q[0][0] > self.window_s:
            q.popleft()

    def reset(self) -> None:
        """Forget all charges (measurement-phase boundaries: a tamper
        curve must not inherit the previous window's blocks)."""
        with self._lock:
            self._events.clear()

    def charge(self, committee_id, n: int, now: Optional[float] = None) -> None:
        if not self.enabled() or n <= 0:
            return
        now = time.monotonic() if now is None else now
        with self._lock:
            q = self._events.setdefault(committee_id, deque())
            self._prune(q, now)
            q.append((now, int(n)))

    def blocked(self, committee_id, now: Optional[float] = None) -> Optional[float]:
        if not self.enabled():
            return None
        now = time.monotonic() if now is None else now
        with self._lock:
            q = self._events.get(committee_id)
            if not q:
                return None
            self._prune(q, now)
            if not q:
                del self._events[committee_id]
                return None
            if sum(n for _ts, n in q) <= self.budget:
                return None
            # retry once the oldest charge ages out of the window
            return max(0.1, self.window_s - (now - q[0][0]))


class PeerRateLimiter:
    """Token-bucket per peer (keyed by host address, never by anything
    the peer sends inside a frame). `charge(peer)` returns:

    - ``None`` — admit the request (a token was spent).
    - a float — shed this request; retry after that many seconds.
    - ``-1.0`` — the peer kept hammering past a whole burst of sheds:
      close its connection (it pays with its own connection, like an
      over-budget committee pays with its own throughput under the
      BisectGuard).

    rps 0 disables the limiter. The bucket holds at most ``burst``
    (default 2x rps) tokens, so a quiet peer can absorb a small spike;
    debt beyond another burst of rejected requests is the
    close-the-connection threshold. State stays O(recently active
    peers): `forget()` (a peer's last connection closed) drops only a
    bucket already refilled to a full burst — a spent or indebted
    bucket is RETAINED, so a hostile peer cannot reset the limiter
    with a tight connect/hammer/reconnect loop — and `charge()`
    lazily prunes retained buckets once they refill (at which point a
    fresh bucket would be no more permissive anyway)."""

    def __init__(self, rps: Optional[float] = None, burst: Optional[float] = None):
        self.rps = (
            rps if rps is not None else _env_float("FSDKR_INGRESS_PEER_RPS", 0.0)
        )
        self.burst = burst if burst is not None else max(1.0, 2.0 * self.rps)
        self._lock = threading.Lock()
        # peer -> [tokens, last_refill_monotonic, consecutive_sheds]
        self._buckets: Dict[object, list] = {}
        self._ops = 0

    def enabled(self) -> bool:
        return self.rps > 0

    def charge(self, peer, now: Optional[float] = None) -> Optional[float]:
        if not self.enabled():
            return None
        now = time.monotonic() if now is None else now
        with self._lock:
            self._ops += 1
            if self._ops % 512 == 0:
                self._prune_locked(now)
            b = self._buckets.get(peer)
            if b is None:
                b = self._buckets[peer] = [self.burst, now, 0]
            tokens = min(self.burst, b[0] + (now - b[1]) * self.rps)
            b[1] = now
            if tokens >= 1.0:
                b[0] = tokens - 1.0
                b[2] = 0
                return None
            b[0] = tokens
            b[2] += 1
            if b[2] > self.burst:
                return -1.0
            return max(0.05, (1.0 - tokens) / self.rps)

    def _refilled(self, b: list, now: float) -> bool:
        # THE droppability invariant: refilled to a full burst, the
        # bucket is behaviorally identical to a fresh one (the next
        # admit resets any shed debt anyway)
        return b[0] + (now - b[1]) * self.rps >= self.burst

    def _prune_locked(self, now: float) -> None:
        dead = [p for p, b in self._buckets.items() if self._refilled(b, now)]
        for p in dead:
            del self._buckets[p]

    def forget(self, peer, now: Optional[float] = None) -> None:
        """A peer's last connection closed. Drop its bucket ONLY if it
        has refilled to a full burst — behaviorally identical to a
        fresh one. A spent or indebted bucket is retained (an instant
        reconnect must not buy a fresh burst); `charge()`'s lazy prune
        reclaims it once burst/rps quiet seconds have passed."""
        if not self.enabled():
            return
        now = time.monotonic() if now is None else now
        with self._lock:
            b = self._buckets.get(peer)
            if b is not None and self._refilled(b, now):
                del self._buckets[peer]
