"""Batching policy for the coalescing finalize launcher (ISSUE 9).

Quorum-ready streaming sessions are fused into one `finalize_streams`
launch; the policy decides WHEN to launch and HOW MANY sessions to take.
Classic size-or-linger batching: launch immediately once
`FSDKR_SERVE_BATCH` sessions are ready, otherwise wait up to
`FSDKR_SERVE_LINGER_MS` from the oldest ready session before launching
whatever is there — throughput from fusion without unbounded latency
(the SZKP-style producer/consumer decoupling needs the consumer launch
to stay full, but a p99 budget caps how long a session may sit waiting
for company).

Mesh awareness: on a real device mesh the fused pair launch row-shards
over all devices, so the policy prefers batch sizes whose total row
count divides the mesh (`parallel.shard_kernels.align_session_batch`);
on the host path (device count 1) alignment is a no-op.
"""

from __future__ import annotations

import os

__all__ = ["BatchPolicy"]


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


class BatchPolicy:
    """Size-or-linger coalescing. `max_sessions` counts collector
    streams (one committee refresh with n collecting parties contributes
    n of them)."""

    def __init__(
        self,
        max_sessions: int = 0,
        linger_s: float = -1.0,
        devices: int = 1,
    ):
        self.max_sessions = max_sessions or _env_int("FSDKR_SERVE_BATCH", 16)
        self.linger_s = (
            linger_s
            if linger_s >= 0
            else _env_float("FSDKR_SERVE_LINGER_MS", 50.0) / 1000.0
        )
        self.devices = max(1, devices)

    def take(
        self, ready: int, oldest_wait_s: float, rows_per_session: int = 0
    ) -> int:
        """How many ready sessions to fuse into a launch right now;
        0 = keep lingering. Never returns more than `ready`."""
        if ready <= 0:
            return 0
        if ready < self.max_sessions and oldest_wait_s < self.linger_s:
            return 0
        count = min(ready, self.max_sessions)
        if self.devices > 1 and rows_per_session > 0:
            from ..parallel.shard_kernels import align_session_batch

            count = align_session_batch(count, rows_per_session, self.devices)
        return count

    def wait_budget(self, oldest_wait_s: float) -> float:
        """Seconds the launcher may sleep before the linger deadline of
        the oldest ready session expires."""
        return max(0.0, self.linger_s - oldest_wait_s)
