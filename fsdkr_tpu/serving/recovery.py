"""Crash recovery (ISSUE 12): replay a journal into fresh streaming
sessions through the ordinary offer()/finalize path.

`recover(service, journal_dir)` reads a journal
(`serving.journal.read_records`, torn-tail tolerant) and rebuilds the
dead incarnation's state inside a live `RefreshService`:

- **committee** records re-admit committees whose LocalKeys the
  keystore can supply (the supervisor re-admits explicitly before
  recovery; the keystore path covers in-process restarts).
- **terminal** records replay their stored verdict verbatim — state,
  blame flag, error string — with NO recompute
  (`RefreshService.restore_terminal`). Done epochs re-enter the
  idempotency index, so `submit(committee, epoch=N)` keeps deduping
  across the restart (ISSUE 12 satellite).
- **in-flight** sessions (admitted/collecting, no terminal record) are
  resumed only when BOTH their accepted broadcasts and their secret
  state are available: the journaled broadcasts are decoded with the
  wire codec and offered, in journal (= acceptance) order, into fresh
  `StreamingCollect` sessions built from the keystore's LocalKeys and
  per-session decryption keys. The resumed session rejoins the service
  lifecycle (`RefreshService.resume_session`) and finalizes through
  the same shared helpers as live traffic — verdict and
  identifiable-abort blame are bit-identical to the uninterrupted run
  by the same structural argument every prior equivalence held
  (pinned at n=3 and n=16, honest and tampered, in
  tests/test_recovery.py).
- a session whose secret state canNOT be re-derived (the common
  cross-process case: new decryption keys live only in the dead
  incarnation's memory) terminates ``aborted`` WITHOUT blame —
  `RecoverySecretsUnavailable` is deliberately not an FsDkrError, so
  the abort reads as transient/retryable and the epoch becomes
  resubmittable. Recovery NEVER fabricates a verdict.

Every replay decision stamps the flight recorder (kind="recovery"), so
a kill-storm postmortem shows exactly what each survivor did with the
dead shard's log.

## Secrets

The journal holds public data only; secrets come from the keystore.
`MemoryKeystore` is process-memory only — nothing it holds ever
touches disk (SECURITY.md "Journal discipline"). The service deposits
each session's new decryption keys at distribute time and drops them
at terminal; committee LocalKeys are deposited at admit.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..config import ProtocolConfig
from .journal import read_records

__all__ = [
    "MemoryKeystore",
    "RecoverySecretsUnavailable",
    "JournaledSession",
    "load_state",
    "recover",
    "config_from_record",
    "config_record",
]


class RecoverySecretsUnavailable(RuntimeError):
    """A journaled in-flight session whose secret state the keystore
    cannot re-derive. Deliberately NOT an FsDkrError: this is an
    infrastructure outcome (aborted_transient, retryable), never an
    identifiable-abort verdict."""


class MemoryKeystore:
    """Process-memory secret store backing recovery. Holds committee
    LocalKeys and per-session new decryption keys BY REFERENCE — it
    never serializes them and never writes them anywhere. A keystore
    outliving a service object is what makes an in-process restart
    fully recoverable; across real process death the session secrets
    are gone by design and recovery degrades to aborted_transient."""

    def __init__(self):
        self._lock = threading.Lock()
        self._committees: Dict[object, list] = {}
        self._session_dks: Dict[Tuple[object, int], list] = {}

    def put_committee(self, committee_id, keys) -> None:
        with self._lock:
            self._committees[committee_id] = list(keys)

    def committee_keys(self, committee_id) -> Optional[list]:
        with self._lock:
            return self._committees.get(committee_id)

    def drop_committee(self, committee_id) -> None:
        with self._lock:
            self._committees.pop(committee_id, None)
            for k in [
                k for k in self._session_dks if k[0] == committee_id
            ]:
                del self._session_dks[k]

    def put_session_dks(self, committee_id, session_id: int, dks) -> None:
        with self._lock:
            self._session_dks[(committee_id, session_id)] = list(dks)

    def session_dks(self, committee_id, session_id: int) -> Optional[list]:
        with self._lock:
            return self._session_dks.get((committee_id, session_id))

    def drop_session(self, committee_id, session_id: int) -> None:
        with self._lock:
            self._session_dks.pop((committee_id, session_id), None)


# ---------------------------------------------------------------------------
# journal state model


@dataclass
class JournaledSession:
    sid: int
    cid: object = None
    epoch: Optional[int] = None
    expected: Optional[List[int]] = None
    broadcasts: List[Tuple[int, str]] = field(default_factory=list)
    terminal: Optional[dict] = None


def config_record(config: ProtocolConfig) -> dict:
    """The PUBLIC config parameters a committee record carries — enough
    to reconstruct the ProtocolConfig on replay, nothing else."""
    return {
        "paillier_bits": config.paillier_bits,
        "m_security": config.m_security,
        "correct_key_rounds": config.correct_key_rounds,
        "backend": config.backend,
        "hash_alg": config.hash_alg,
        "curve": config.curve,
    }


def config_from_record(rec: dict) -> ProtocolConfig:
    return ProtocolConfig(**rec)


def load_state(journal_dir):
    """Parse a journal directory into (sessions, committees) — sessions
    keyed by journaled session id in first-seen order, committees keyed
    by committee id. Torn tails are dropped by the reader; corruption
    raises. Missing/empty directory -> ({}, {})."""
    sessions: Dict[int, JournaledSession] = {}
    committees: Dict[object, dict] = {}
    for rec in read_records(journal_dir):
        t = rec.get("t")
        if t == "committee":
            committees[rec["cid"]] = rec
            continue
        sid = rec.get("sid")
        if sid is None:
            continue
        sess = sessions.get(sid)
        if sess is None:
            sess = sessions[sid] = JournaledSession(sid=sid)
        if t == "admitted":
            sess.cid = rec["cid"]
            sess.epoch = rec.get("epoch")
        elif t == "collecting":
            sess.expected = list(rec["expected"])
            # a new attempt always opens with `collecting`: drop any
            # broadcasts from a previous attempt even if its `reset`
            # record was lost (best-effort append) — mixing one
            # attempt's messages with another's secrets is the one
            # replay shape that could produce a wrong result
            sess.broadcasts = []
        elif t == "broadcast":
            sess.broadcasts.append((rec["sender"], rec["wire"]))
        elif t == "reset":
            # a failed worker attempt requeued: the next attempt re-runs
            # distribute with fresh randomness, so the prior attempt's
            # broadcasts (and its deposited secrets) are stale
            sess.expected = None
            sess.broadcasts = []
        elif t == "terminal":
            sess.terminal = rec
            if sess.cid is None:
                sess.cid = rec.get("cid")
            if sess.epoch is None:
                sess.epoch = rec.get("epoch")
    return sessions, committees


def _flight(name: str, **fields) -> None:
    try:
        from ..telemetry import flight

        flight.record("recovery", name, **fields)
    except Exception:
        pass


def _replayed_counter():
    from ..telemetry import registry

    return registry.counter(
        "fsdkr_journal_replayed",
        "journal records consumed by recovery replay",
    )


def recover(service, journal_dir, keystore: Optional[MemoryKeystore] = None) -> dict:
    """Replay `journal_dir` into `service`. Returns a report dict:

    - ``sessions``: {journaled sid: {"disposition": ..., "sid": new sid
      or None, "cid", "epoch", "state"}} where disposition is one of
      ``replayed_terminal`` / ``resumed`` / ``aborted_transient`` /
      ``skipped_no_committee``.
    - ``replayed_terminal`` / ``resumed`` / ``aborted_transient`` /
      ``skipped`` counts, ``broadcasts_replayed``, and
      ``committees_admitted``.

    Idempotent in effect: terminal verdicts restore as finished history
    (done epochs keep deduping), in-flight sessions either resume into
    the live lifecycle or settle retryably. The caller decides what to
    do about aborted_transient sessions (the supervisor resubmits
    them)."""
    keystore = keystore or getattr(service, "keystore", None)
    from ..telemetry import registry

    torn_counter = registry.counter(
        "fsdkr_journal_torn_tails",
        "truncated segment tails dropped during replay",
    )
    torn0 = torn_counter.value()
    sessions, committees = load_state(journal_dir)
    torn = int(torn_counter.value() - torn0)
    report = {
        "journal_dir": str(journal_dir),
        "torn_tails": torn,
        "sessions": {},
        "replayed_terminal": 0,
        "resumed": 0,
        "aborted_transient": 0,
        "skipped": 0,
        "broadcasts_replayed": 0,
        "committees_admitted": 0,
    }
    if not sessions and not committees:
        _flight("replay_empty", dir=str(journal_dir))
        return report

    # journaled sids must never collide with sids this incarnation will
    # allocate (same-directory restart: new records append to the same
    # log the NEXT recovery reads)
    service.reserve_session_ids(max(sessions) if sessions else 0)
    # same-directory restart: the terminal records already live in the
    # log this service appends to — re-journaling them would double the
    # terminal set on every restart. A peer adopting a FOREIGN journal
    # re-journals, keeping its own log self-contained.
    import pathlib

    same_dir = (
        service.journal is not None
        and pathlib.Path(journal_dir).resolve()
        == pathlib.Path(service.journal.dir).resolve()
    )

    for cid, rec in committees.items():
        if service.has_committee(cid):
            continue
        keys = keystore.committee_keys(cid) if keystore else None
        if keys is None:
            continue
        service.admit(cid, keys, config_from_record(rec["config"]))
        report["committees_admitted"] += 1
        _flight("committee_readmitted", cid=str(cid))

    replayed = _replayed_counter()
    for sid, js in sessions.items():
        entry = {"cid": js.cid, "epoch": js.epoch, "sid": None}
        report["sessions"][sid] = entry
        if js.terminal is not None:
            new_sid = service.restore_terminal(
                js.cid,
                js.epoch,
                js.terminal["state"],
                bool(js.terminal.get("blame")),
                js.terminal.get("error"),
                rejournal=not same_dir,
            )
            entry.update(
                disposition="replayed_terminal",
                sid=new_sid,
                state=js.terminal["state"],
            )
            report["replayed_terminal"] += 1
            replayed.inc()
            _flight(
                "terminal_replayed",
                sid=sid,
                state=js.terminal["state"],
                blame=bool(js.terminal.get("blame")),
            )
            continue
        if js.cid is None or not service.has_committee(js.cid):
            entry["disposition"] = "skipped_no_committee"
            report["skipped"] += 1
            _flight("skipped_no_committee", sid=sid)
            continue
        dks = (
            keystore.session_dks(js.cid, sid)
            if keystore is not None
            else None
        )
        resumable = (
            js.expected is not None
            and dks is not None
            and len(dks) == service.committee_size(js.cid)
        )
        if not resumable:
            new_sid = service.finish_unrecoverable(
                js.cid,
                js.epoch,
                RecoverySecretsUnavailable(
                    f"session {sid} (committee {js.cid!r}, epoch "
                    f"{js.epoch!r}): secret state not re-derivable from "
                    f"the keystore; aborted transient (retryable)"
                ),
                origin_sid=sid,
            )
            entry.update(
                disposition="aborted_transient", sid=new_sid, state="aborted"
            )
            report["aborted_transient"] += 1
            _flight("aborted_transient", sid=sid)
            continue
        try:
            new_sid = service.resume_session(
                js.cid, js.epoch, dks, js.expected, js.broadcasts,
                origin_sid=sid,
            )
        except Exception as e:
            # one unresumable session (busy committee in a malformed
            # journal, journal IO) must not abort the whole replay —
            # settle it retryably like any other secrets-gone session
            new_sid = service.finish_unrecoverable(
                js.cid,
                js.epoch,
                RecoverySecretsUnavailable(
                    f"session {sid}: resume failed "
                    f"({type(e).__name__}: {e}); aborted transient"
                ),
                origin_sid=sid,
            )
            entry.update(
                disposition="aborted_transient", sid=new_sid, state="aborted"
            )
            report["aborted_transient"] += 1
            _flight("resume_failed", sid=sid)
            continue
        entry.update(disposition="resumed", sid=new_sid)
        report["resumed"] += 1
        report["broadcasts_replayed"] += len(js.broadcasts)
        replayed.inc(1 + len(js.broadcasts))
        _flight(
            "session_resumed",
            sid=sid,
            new_sid=new_sid,
            broadcasts=len(js.broadcasts),
        )
    _flight(
        "replay_done",
        dir=str(journal_dir),
        terminal=report["replayed_terminal"],
        resumed=report["resumed"],
        transient=report["aborted_transient"],
    )
    return report
