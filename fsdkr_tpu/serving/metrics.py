"""Serving telemetry: the `fsdkr_serving_*` metric family (ISSUE 9).

All metrics live in the process-global telemetry registry
(`fsdkr_tpu.telemetry.registry`), so they ride the same snapshot /
Prometheus-export paths as every other subsystem — the load generator
embeds one registry snapshot in its report, and FSDKR_METRICS_DUMP
exposes the gauges for scraping. Labels carry tiny enums only
(lifecycle phase, outcome) — never committee identifiers (unbounded
cardinality) and never anything derived from key material (SECURITY.md
"Telemetry discipline").
"""

from __future__ import annotations

from ..telemetry import registry

__all__ = [
    "sessions_counter",
    "phase_histogram",
    "batch_histogram",
    "inflight_gauge",
    "queue_gauge",
    "committees_gauge",
    "record_phase",
    "record_outcome",
    "rlc_bisect_count",
    "retries_counter",
]

# end-to-end latencies span ~10 ms smoke sessions to minutes under
# overload; log-spaced buckets keep the interpolated p99 honest at both
# ends without per-sample retention
_SECONDS_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 20.0,
    40.0, 80.0, 160.0, 320.0,
)


def sessions_counter():
    return registry.counter(
        "fsdkr_serving_sessions",
        "refresh sessions finished, by outcome "
        "(done/aborted/timed_out/rejected)",
        labelnames=("outcome",),
    )


def retries_counter():
    return registry.counter(
        "fsdkr_serving_retries",
        "transient-failure retries, by stage (worker/finalize)",
        labelnames=("stage",),
    )


def phase_histogram():
    return registry.histogram(
        "fsdkr_serving_phase_seconds",
        "per-session lifecycle phase latency "
        "(queue/distribute/stream/coalesce/finalize/total)",
        labelnames=("phase",),
        buckets=_SECONDS_BUCKETS,
    )


def batch_histogram():
    return registry.histogram(
        "fsdkr_serving_batch_sessions",
        "collector sessions fused per finalize launch",
        buckets=(1, 2, 4, 8, 16, 32, 64, 128),
    )


def inflight_gauge():
    return registry.gauge(
        "fsdkr_serving_inflight",
        "sessions admitted but not yet done/aborted",
    )


def queue_gauge():
    return registry.gauge(
        "fsdkr_serving_queue_depth",
        "sessions waiting in the admission queue (public metadata only)",
    )


def committees_gauge():
    return registry.gauge(
        "fsdkr_serving_committees",
        "committees currently admitted to the service",
    )


def record_phase(phase: str, seconds: float) -> None:
    phase_histogram().observe(seconds, phase=phase)


def record_outcome(outcome: str, total_seconds: float) -> None:
    sessions_counter().inc(outcome=outcome)
    # a rejected submission never became a session: no latency sample
    if outcome != "rejected":
        phase_histogram().observe(total_seconds, phase="total")


def rlc_bisect_count() -> int:
    """Lifetime RLC bisection fallbacks, read through the registry.
    The serving layer may not import `backend.rlc` (layering rule), but
    the counter is shared process state: look it up by name, 0 when the
    verifier has not created it yet (no re-declaration here — a name or
    label drift in backend.rlc must not silently fork a parallel
    always-zero counter)."""
    m = registry.get_registry().get("fsdkr_rlc_events")
    if m is None:
        return 0
    try:
        return int(m.value(event="bisect_fallbacks"))
    except Exception:
        return 0
