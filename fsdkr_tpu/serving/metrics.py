"""Serving telemetry: the `fsdkr_serving_*` metric family (ISSUE 9).

All metrics live in the process-global telemetry registry
(`fsdkr_tpu.telemetry.registry`), so they ride the same snapshot /
Prometheus-export paths as every other subsystem — the load generator
embeds one registry snapshot in its report, and FSDKR_METRICS_DUMP
exposes the gauges for scraping. Labels carry tiny enums only
(lifecycle phase, outcome) — never committee identifiers (unbounded
cardinality) and never anything derived from key material (SECURITY.md
"Telemetry discipline").
"""

from __future__ import annotations

from ..telemetry import registry

__all__ = [
    "sessions_counter",
    "phase_histogram",
    "batch_histogram",
    "inflight_gauge",
    "queue_gauge",
    "committees_gauge",
    "record_phase",
    "record_outcome",
    "rlc_bisect_count",
    "retries_counter",
    "ingress_connections",
    "ingress_open_gauge",
    "ingress_frames",
    "ingress_bytes",
    "ingress_rejected",
    "ingress_paused",
    "ingress_peer_shed",
    "ingress_snapshot",
]

# end-to-end latencies span ~10 ms smoke sessions to minutes under
# overload; log-spaced buckets keep the interpolated p99 honest at both
# ends without per-sample retention
_SECONDS_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 20.0,
    40.0, 80.0, 160.0, 320.0,
)


def sessions_counter():
    return registry.counter(
        "fsdkr_serving_sessions",
        "refresh sessions finished, by outcome "
        "(done/aborted/timed_out/rejected)",
        labelnames=("outcome",),
    )


def retries_counter():
    return registry.counter(
        "fsdkr_serving_retries",
        "transient-failure retries, by stage (worker/finalize)",
        labelnames=("stage",),
    )


def phase_histogram():
    return registry.histogram(
        "fsdkr_serving_phase_seconds",
        "per-session lifecycle phase latency "
        "(queue/distribute/stream/coalesce/finalize/total)",
        labelnames=("phase",),
        buckets=_SECONDS_BUCKETS,
    )


def batch_histogram():
    return registry.histogram(
        "fsdkr_serving_batch_sessions",
        "collector sessions fused per finalize launch",
        buckets=(1, 2, 4, 8, 16, 32, 64, 128),
    )


def inflight_gauge():
    return registry.gauge(
        "fsdkr_serving_inflight",
        "sessions admitted but not yet done/aborted",
    )


def queue_gauge():
    return registry.gauge(
        "fsdkr_serving_queue_depth",
        "sessions waiting in the admission queue (public metadata only)",
    )


def committees_gauge():
    return registry.gauge(
        "fsdkr_serving_committees",
        "committees currently admitted to the service",
    )


def record_phase(phase: str, seconds: float) -> None:
    phase_histogram().observe(seconds, phase=phase)


def record_outcome(outcome: str, total_seconds: float) -> None:
    sessions_counter().inc(outcome=outcome)
    # a rejected submission never became a session: no latency sample
    if outcome != "rejected":
        phase_histogram().observe(total_seconds, phase="total")


# -- network ingress (ISSUE 13) ---------------------------------------
# the fsdkr_ingress_* family: every byte/frame/shed decision the TCP
# ingress makes is countable from the registry, so a loadgen report or
# a Prometheus scrape can see a hostile peer or a backpressure stall
# without reading the server's logs. Labels are tiny cause/direction
# enums — never peer addresses (unbounded cardinality, and a peer list
# is operational data the metrics stream should not leak).


def ingress_connections():
    return registry.counter(
        "fsdkr_ingress_connections",
        "ingress TCP connections accepted, by how they ended "
        "(closed/error/shed/drained/faulted)",
        labelnames=("outcome",),
    )


def ingress_open_gauge():
    return registry.gauge(
        "fsdkr_ingress_open_connections",
        "ingress TCP connections currently open",
    )


def ingress_frames():
    return registry.counter(
        "fsdkr_ingress_frames",
        "wire frames processed, by direction (in/out)",
        labelnames=("direction",),
    )


def ingress_bytes():
    return registry.counter(
        "fsdkr_ingress_bytes",
        "wire bytes processed (frame headers included), by direction",
        labelnames=("direction",),
    )


def ingress_rejected():
    return registry.counter(
        "fsdkr_ingress_frames_rejected",
        "wire frames rejected, by cause (oversize/crc/malformed/"
        "bad_op/slow_read/slow_write/peer_rate/draining)",
        labelnames=("cause",),
    )


def ingress_paused():
    return registry.counter(
        "fsdkr_ingress_paused_reads",
        "TCP read pauses forced by the inflight byte budgets "
        "(connection-level or server-global backpressure)",
        labelnames=("scope",),
    )


def ingress_peer_shed():
    return registry.counter(
        "fsdkr_ingress_peer_rate_shed",
        "requests shed by the per-peer rate limiter",
    )


def ingress_snapshot() -> dict:
    """The ingress counter family as one plain dict (loadgen reports /
    digest tables). Reads through the registry so multi-server
    processes aggregate naturally."""
    out = {"connections": {}, "frames": {}, "bytes": {},
           "frames_rejected": {}, "paused_reads": {}}
    reg = registry.get_registry()
    for name, key, label in (
        ("fsdkr_ingress_connections", "connections", "outcome"),
        ("fsdkr_ingress_frames", "frames", "direction"),
        ("fsdkr_ingress_bytes", "bytes", "direction"),
        ("fsdkr_ingress_frames_rejected", "frames_rejected", "cause"),
        ("fsdkr_ingress_paused_reads", "paused_reads", "scope"),
    ):
        m = reg.get(name)
        if m is None:
            continue
        for rec in m.snapshot_values():
            out[key][rec["labels"].get(label, "?")] = int(rec["value"])
    m = reg.get("fsdkr_ingress_peer_rate_shed")
    out["peer_rate_shed"] = int(m.value()) if m is not None else 0
    m = reg.get("fsdkr_ingress_open_connections")
    vals = m.snapshot_values() if m is not None else []
    out["open_connections"] = int(vals[0]["value"]) if vals else 0
    return out


def rlc_bisect_count() -> int:
    """Lifetime RLC bisection fallbacks, read through the registry.
    The serving layer may not import `backend.rlc` (layering rule), but
    the counter is shared process state: look it up by name, 0 when the
    verifier has not created it yet (no re-declaration here — a name or
    label drift in backend.rlc must not silently fork a parallel
    always-zero counter)."""
    m = registry.get_registry().get("fsdkr_rlc_events")
    if m is None:
        return 0
    try:
        return int(m.value(event="bisect_fallbacks"))
    except Exception:
        return 0
