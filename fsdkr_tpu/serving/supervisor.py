"""Multi-process shard supervisor (ISSUE 12; ROADMAP 3b): partition
committees across N `RefreshService` shard processes, health-check them
by heartbeat, and on shard death reassign its committees to a peer that
replays the dead shard's journal and resumes.

The ASIC-serving deployments this repo tracks (PAPERS.md,
arXiv:2604.17808) assume a fleet of shards where individual shard death
is ROUTINE — the supervisor is the piece that makes that true here:

- **Partitioning**: committees shard by fingerprint
  (SHA-256 of the committee id, mod shard count) — sessions share
  nothing across committees but the config-keyed key pool, so the
  partition is clean. Reassignment after a death overrides the
  fingerprint (the assignment map, not the hash, is authoritative).
- **Shards** are child processes of THIS module
  (``python -m fsdkr_tpu.serving.supervisor --shard ...``), each
  running one `RefreshService` with its own journal directory and a
  flight-recorder dump beside it. Parent and child speak JSON lines
  over stdin/stdout; committee LocalKeys travel over that private pipe
  (never disk — SECURITY.md "Journal discipline") using the
  `protocol.serialization` checkpoint codec.
- **Health**: shards heartbeat every ``hb_interval`` with their
  serving stats and journal counters, and dump their flight ring to
  ``<journal_dir>/flight.json`` on every beat — SIGKILL is uncatchable,
  so the postmortem is the last completed beat, collected by the
  supervisor at failover. Death is detected by process exit, stdout
  EOF, or a stale heartbeat.
- **Failover**: the supervisor re-admits the dead shard's committees
  on a peer (admission-time key material), sends the peer a ``recover``
  command for the dead journal directory — terminal verdicts replay
  verbatim (idempotency index included), in-flight sessions settle
  ``aborted_transient`` (their new dks died with the shard, and
  recovery never fabricates a verdict) — then resubmits every pending
  epoch. The idempotency index makes that safe: a replayed-done epoch
  dedupes to its stored verdict instantly; a transiently-aborted epoch
  re-runs. MTTR is measured from death detection to the first pending
  epoch of that shard resolving.

Aggregate `fsdkr_serving_*` / `fsdkr_journal_*` readings across shards
come from the heartbeats (`ShardSupervisor.aggregate`).

The kill-storm harness on top of this lives in
``scripts/loadgen.py --crash-storm``; the deterministic 2-shard
SIGKILL/recovery smoke is a ci.sh leg.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import pathlib
import queue
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["ShardSupervisor", "ShardHandle", "shard_for"]


def shard_for(committee_id, shards: int) -> int:
    """Fingerprint partition (ROADMAP 3b): stable across processes —
    SHA-256 of the canonical JSON id, never Python's salted hash()."""
    h = hashlib.sha256(
        json.dumps(committee_id, sort_keys=True).encode()
    ).digest()
    return int.from_bytes(h[:8], "big") % max(1, shards)


# ---------------------------------------------------------------------------
# shard child process


def _emit(lock: threading.Lock, obj: dict) -> None:
    with lock:
        sys.stdout.write(json.dumps(obj, default=str) + "\n")
        sys.stdout.flush()


def _shard_main(args) -> int:
    """One shard: a RefreshService with a journal, driven by JSON-line
    commands on stdin, reporting events on stdout — and, with
    ``--ingress-port`` (ISSUE 13), by wire-protocol clients on a TCP
    socket (`serving.ingress`). Runs until stdin closes or a ``stop``
    command arrives."""
    from ..protocol.serialization import local_key_from_json
    from ..telemetry import flight
    from . import recovery
    from .service import RefreshService, ServeRejected

    out_lock = threading.Lock()
    svc = RefreshService(
        journal=args.journal_dir,
        deadline_s=args.deadline,
        retries=args.retries,
        workers=args.workers,
    )
    svc.start()
    stop_evt = threading.Event()

    # network ingress (ISSUE 13): committees this shard does not own
    # redirect to the fleet's port map (installed by the parent's
    # `ingress_peers` command once every shard reported its bound
    # port). The HINT is the fingerprint owner; failover reassignments
    # override fingerprints, so clients fall back to trying the rest.
    peer_ports: Dict[int, int] = {}

    def _router(cid):
        if not peer_ports:
            return None
        hint = peer_ports.get(shard_for(cid, args.shards))
        return {
            "ports": {str(k): v for k, v in peer_ports.items()},
            "hint": hint,
        }

    ingress = None
    if args.ingress_port >= 0:
        from .ingress import IngressServer

        ingress = IngressServer(
            svc, host=args.ingress_host, port=args.ingress_port,
            router=_router,
        ).start()

    def heartbeat():
        from . import metrics as smetrics

        while not stop_evt.wait(args.hb_interval):
            try:
                flight.dump(reason="heartbeat")  # postmortem-in-waiting
            except Exception:
                pass
            _emit(out_lock, {
                "ev": "hb",
                "shard": args.shard_id,
                "stats": svc.stats(),
                "journal": svc.journal_stats(),
                "ingress": (
                    smetrics.ingress_snapshot()
                    if ingress is not None else None
                ),
            })

    def waiter(cid, epoch, sid):
        s = svc.wait(sid)  # blocks until terminal
        _emit(out_lock, {
            "ev": "terminal",
            "shard": args.shard_id,
            "cid": cid,
            "epoch": epoch,
            "sid": sid,
            "state": s.state,
            "blame": s.blame,
            "error": s.error,
            "latency_s": round(
                max(0.0, s.finalized_at - s.submitted_at), 4
            ),
            "retries": s.retries,
        })

    threading.Thread(target=heartbeat, daemon=True, name="shard-hb").start()
    _emit(out_lock, {
        "ev": "ready", "shard": args.shard_id, "pid": os.getpid(),
        "ingress_port": ingress.port if ingress is not None else None,
    })

    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            cmd = json.loads(line)
        except ValueError:
            _emit(out_lock, {"ev": "error", "detail": "bad command json"})
            continue
        op = cmd.get("cmd")
        try:
            if op == "admit":
                cid = cmd["cid"]
                if not svc.has_committee(cid):
                    keys = [local_key_from_json(k) for k in cmd["keys"]]
                    svc.admit(
                        cid, keys, recovery.config_from_record(cmd["config"])
                    )
                _emit(out_lock, {"ev": "admitted", "shard": args.shard_id,
                                 "cid": cid})
            elif op == "submit":
                cid, epoch = cmd["cid"], cmd.get("epoch")
                try:
                    sid = svc.submit(cid, epoch=epoch)
                except ServeRejected as e:
                    _emit(out_lock, {
                        "ev": "rejected", "shard": args.shard_id,
                        "cid": cid, "epoch": epoch,
                        "retry_after_s": e.retry_after_s,
                    })
                    continue
                threading.Thread(
                    target=waiter, args=(cid, epoch, sid), daemon=True
                ).start()
            elif op == "recover":
                flight.record("recovery", "peer_journal_adopted",
                              dir=str(cmd["dir"]))
                report = recovery.recover(svc, cmd["dir"], svc.keystore)
                _emit(out_lock, {"ev": "recovered", "shard": args.shard_id,
                                 "report": report})
            elif op == "sync":
                if svc.journal is not None:
                    svc.journal.sync()
                _emit(out_lock, {"ev": "synced", "shard": args.shard_id})
            elif op == "ingress_peers":
                peer_ports.clear()
                peer_ports.update(
                    {int(k): int(v) for k, v in cmd["ports"].items()}
                )
                _emit(out_lock, {"ev": "peers_set", "shard": args.shard_id})
            elif op == "stop":
                break
            else:
                _emit(out_lock, {"ev": "error", "detail": f"unknown cmd {op!r}"})
        except Exception as e:  # a failing command must not kill the shard
            _emit(out_lock, {
                "ev": "error", "shard": args.shard_id, "cmd": op,
                "detail": f"{type(e).__name__}: {e}",
            })
    stop_evt.set()
    if ingress is not None:
        ingress.stop()  # drain first: stop accepting, answer in-flight
    svc.stop()
    try:
        flight.dump(reason="shard-exit")
    except Exception:
        pass
    _emit(out_lock, {"ev": "stopped", "shard": args.shard_id})
    return 0


# ---------------------------------------------------------------------------
# parent side


class ShardHandle:
    def __init__(self, idx: int, proc, journal_dir: pathlib.Path):
        self.idx = idx
        self.proc = proc
        self.journal_dir = journal_dir
        self.flight_path = journal_dir / "flight.json"
        self.stderr_path = journal_dir / "stderr.log"
        self.ingress_port: Optional[int] = None
        self.alive = True
        self.ready = False
        self.stopped = False  # clean shutdown acknowledged
        self.failed_over = False  # death already handled
        self.last_hb = time.monotonic()
        self.last_stats: dict = {}
        self.last_journal: dict = {}
        self.last_ingress: dict = {}
        self.committees: set = set()


class ShardSupervisor:
    """Parent-side fleet controller. Construct, `start()`, `admit` and
    `submit` committees/epochs, call `pump()` from the driving loop (it
    drains shard events AND runs health checks / failover), `drain()`
    for quiescence, `stop()` to tear down. `outcomes` accumulates one
    record per resolved (committee, epoch)."""

    def __init__(
        self,
        shards: int = 2,
        root=None,
        deadline_s: float = 10.0,
        retries: int = 2,
        workers: int = 1,
        hb_interval: float = 0.5,
        hb_timeout: Optional[float] = None,
        spawn_timeout: float = 240.0,
        max_resubmits: int = 2,
        env: Optional[dict] = None,
        ingress: bool = False,
        ingress_host: str = "127.0.0.1",
    ):
        self.n_shards = max(1, int(shards))
        self.root = pathlib.Path(root) if root else pathlib.Path(
            ".fsdkr_shards"
        )
        self.deadline_s = deadline_s
        self.retries = retries
        self.workers = workers
        self.hb_interval = hb_interval
        self.hb_timeout = hb_timeout or max(5.0, 8 * hb_interval)
        self.spawn_timeout = spawn_timeout
        self.max_resubmits = max_resubmits
        self.extra_env = dict(env or {})
        # ISSUE 13: each shard listens on a TCP ingress port (kernel-
        # assigned, reported in its ready event); after start() the
        # parent broadcasts the port map so shards can redirect clients
        # for committees they do not own
        self.ingress = bool(ingress)
        self.ingress_host = ingress_host
        self.shards: List[ShardHandle] = []
        self.events: "queue.Queue[Tuple[int, dict]]" = queue.Queue()
        self.assignment: Dict[object, int] = {}
        self._admissions: Dict[object, Tuple[list, dict]] = {}
        # (cid, epoch) -> pending record; resolved ones move to outcomes
        self.pending: Dict[Tuple[object, Optional[int]], dict] = {}
        self.outcomes: List[dict] = []
        self.failovers: List[dict] = []
        self.kills = 0
        self._gen = 0  # failover generation, for MTTR attribution
        self._stopping = False
        # single-threaded by contract: pending/outcomes/assignment are
        # touched only from the thread driving pump()/submit(); the
        # reader threads just enqueue onto self.events

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        self.root.mkdir(parents=True, exist_ok=True)
        for i in range(self.n_shards):
            self.shards.append(self._spawn(i))
        deadline = time.monotonic() + self.spawn_timeout
        while time.monotonic() < deadline:
            self.pump(0.2, health=False)
            if all(h.ready for h in self.shards):
                if self.ingress:
                    ports = self.ingress_ports()
                    for h in self.shards:
                        self._send(h, {"cmd": "ingress_peers",
                                       "ports": ports})
                return
        missing = [h.idx for h in self.shards if not h.ready]
        raise RuntimeError(f"shards never became ready: {missing}")

    def ingress_ports(self) -> Dict[int, int]:
        """Live shards' TCP ingress ports (empty unless ingress=True)."""
        return {
            h.idx: h.ingress_port
            for h in self.shards
            if h.alive and h.ingress_port is not None
        }

    def _spawn(self, idx: int) -> ShardHandle:
        jdir = self.root / f"shard{idx:02d}"
        jdir.mkdir(parents=True, exist_ok=True)
        env = dict(os.environ)
        env.update(self.extra_env)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["FSDKR_FLIGHT"] = str(jdir / "flight.json")
        stderr = open(jdir / "stderr.log", "ab")
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "fsdkr_tpu.serving.supervisor",
                "--shard", "--shard-id", str(idx),
                "--journal-dir", str(jdir),
                "--deadline", str(self.deadline_s),
                "--retries", str(self.retries),
                "--workers", str(self.workers),
                "--hb-interval", str(self.hb_interval),
                "--shards", str(self.n_shards),
                "--ingress-port", "0" if self.ingress else "-1",
                "--ingress-host", self.ingress_host,
            ],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=stderr,
            text=True,
            env=env,
            cwd=str(pathlib.Path(__file__).resolve().parents[2]),
        )
        stderr.close()
        handle = ShardHandle(idx, proc, jdir)
        threading.Thread(
            target=self._reader, args=(handle,), daemon=True,
            name=f"shard{idx}-reader",
        ).start()
        return handle

    def _reader(self, handle: ShardHandle) -> None:
        for line in handle.proc.stdout:
            line = line.strip()
            if not line:
                continue
            try:
                self.events.put((handle.idx, json.loads(line)))
            except ValueError:
                continue  # non-protocol noise on stdout
        self.events.put((handle.idx, {"ev": "_eof"}))

    def stop(self) -> None:
        self._stopping = True
        for h in self.shards:
            if h.alive:
                self._send(h, {"cmd": "stop"})
        for h in self.shards:
            try:
                h.proc.wait(timeout=10)
            except Exception:
                h.proc.kill()

    # -- plumbing -------------------------------------------------------
    def _send(self, handle: ShardHandle, obj: dict) -> bool:
        try:
            handle.proc.stdin.write(json.dumps(obj, default=str) + "\n")
            handle.proc.stdin.flush()
            return True
        except Exception:
            # a broken pipe IS a death signal — route it through the
            # same one-shot death handler as EOF and the health check,
            # or the shard's committees would wedge un-failed-over
            self._on_death(handle)
            return False

    def _on_death(self, handle: ShardHandle) -> None:
        """One-shot death handling shared by every detection path
        (stdout EOF, broken stdin pipe, process exit, stale heartbeat):
        mark the shard dead and fail its committees over exactly once.
        Clean shutdowns (acked `stopped`, or supervisor stop() in
        progress) never failover."""
        handle.alive = False
        if self._stopping or handle.stopped or handle.failed_over:
            return
        handle.failed_over = True
        self._failover(handle)

    def _alive(self) -> List[ShardHandle]:
        return [h for h in self.shards if h.alive]

    # -- committee / session intake -------------------------------------
    def admit(self, committee_id, keys, config) -> None:
        """Admit a committee fleet-wide: serialize its LocalKeys once
        (the failover re-admission source) and route to the fingerprint
        shard."""
        from ..protocol.serialization import local_key_to_json
        from .recovery import config_record

        wire = [local_key_to_json(k) for k in keys]
        crec = config_record(config)
        self._admissions[committee_id] = (wire, crec)
        owner = shard_for(committee_id, self.n_shards)
        if not self.shards[owner].alive:
            owner = self._peer_for(owner)
        self.assignment[committee_id] = owner
        self.shards[owner].committees.add(committee_id)
        self._send(self.shards[owner], {
            "cmd": "admit", "cid": committee_id, "keys": wire,
            "config": crec,
        })

    def submit(self, committee_id, epoch: Optional[int]) -> None:
        owner = self.assignment[committee_id]
        key = (committee_id, epoch)
        if key not in self.pending:
            self.pending[key] = {
                "shard": owner,
                "t0": time.monotonic(),
                "via": "primary",
                "resubmits": 0,
                "gen": None,
            }
        self._send(self.shards[owner], {
            "cmd": "submit", "cid": committee_id, "epoch": epoch,
        })

    # -- event / health loop --------------------------------------------
    def pump(self, max_wait: float = 0.1, health: bool = True) -> None:
        """Drain shard events (blocking up to `max_wait` for the first)
        and run the health check. Call this from the driving loop."""
        deadline = time.monotonic() + max_wait
        block = max_wait
        while True:
            try:
                idx, ev = self.events.get(timeout=max(0.0, block))
            except queue.Empty:
                break
            self._on_event(idx, ev)
            block = deadline - time.monotonic()
            if block <= 0:
                # drain whatever is already queued, without blocking
                while True:
                    try:
                        idx, ev = self.events.get_nowait()
                    except queue.Empty:
                        break
                    self._on_event(idx, ev)
                break
        if health:
            self.check_health()

    def _on_event(self, idx: int, ev: dict) -> None:
        h = self.shards[idx]
        kind = ev.get("ev")
        if kind == "ready":
            h.ready = True
            h.ingress_port = ev.get("ingress_port")
            h.last_hb = time.monotonic()
        elif kind == "hb":
            h.last_hb = time.monotonic()
            h.last_stats = ev.get("stats") or {}
            h.last_journal = ev.get("journal") or {}
            h.last_ingress = ev.get("ingress") or {}
        elif kind == "terminal":
            self._resolve(idx, ev)
        elif kind == "rejected":
            key = (ev.get("cid"), ev.get("epoch"))
            pend = self.pending.pop(key, None)
            if pend is not None:
                self.outcomes.append({
                    "cid": ev.get("cid"), "epoch": ev.get("epoch"),
                    "state": "rejected", "blame": False, "error": None,
                    "latency_s": None, "via": pend["via"], "shard": idx,
                })
        elif kind == "recovered":
            for fo in self.failovers:
                if fo.get("peer") == idx and "recovery" not in fo:
                    rep = ev.get("report") or {}
                    rep.pop("sessions", None)
                    fo["recovery"] = rep
                    # replay latency: death detection -> the peer
                    # finished adopting the journal (MTTR proper also
                    # needs an interrupted epoch to complete; this is
                    # the floor every failover pays)
                    fo["recover_s"] = round(
                        time.monotonic() - fo["detected_mono"], 4
                    )
                    break
        elif kind == "stopped":
            h.stopped = True
        elif kind == "_eof":
            # stdout EOF is the fastest death signal (a SIGKILL closes
            # the pipe immediately, long before the heartbeat staleness
            # window); a clean shutdown acked `stopped` first
            self._on_death(h)

    def _resolve(self, idx: int, ev: dict) -> None:
        key = (ev.get("cid"), ev.get("epoch"))
        pend = self.pending.get(key)
        if pend is None:
            return  # duplicate terminal for an already-resolved epoch
        state, blame = ev.get("state"), bool(ev.get("blame"))
        transient_failure = state in ("aborted", "timed_out") and not blame
        if transient_failure and pend["resubmits"] < self.max_resubmits:
            # the retry contract: transient failures (including
            # recovery's aborted_transient) are resubmittable — the
            # epoch index guarantees at most one effective run
            pend["resubmits"] += 1
            pend["via"] = "resubmit"
            owner = self.assignment[key[0]]
            self._send(self.shards[owner], {
                "cmd": "submit", "cid": key[0], "epoch": key[1],
            })
            return
        del self.pending[key]
        out = {
            "cid": key[0], "epoch": key[1], "state": state, "blame": blame,
            "error": ev.get("error"), "latency_s": ev.get("latency_s"),
            "total_s": round(time.monotonic() - pend["t0"], 4),
            "via": pend["via"], "resubmits": pend["resubmits"],
            "shard": idx,
        }
        self.outcomes.append(out)
        if pend.get("gen") is not None:
            for fo in self.failovers:
                if fo["gen"] == pend["gen"] and fo.get("mttr_s") is None:
                    fo["mttr_s"] = round(
                        time.monotonic() - fo["detected_mono"], 4
                    )

    def check_health(self) -> None:
        now = time.monotonic()
        for h in self.shards:
            if not h.alive:
                continue
            dead = h.proc.poll() is not None or (
                h.ready and now - h.last_hb > self.hb_timeout
            )
            if dead:
                self._on_death(h)

    def _peer_for(self, dead_idx: int) -> int:
        alive = [h.idx for h in self._alive()]
        if not alive:
            raise RuntimeError("no live shard left to adopt committees")
        # deterministic: the next live shard after the dead one
        for off in range(1, self.n_shards):
            cand = (dead_idx + off) % self.n_shards
            if cand in alive:
                return cand
        return alive[0]

    def _failover(self, dead: ShardHandle) -> None:
        """Reassign the dead shard's committees to a peer, replay its
        journal there, resubmit its pending epochs."""
        detected = time.monotonic()
        self._gen += 1
        gen = self._gen
        try:
            from ..telemetry import flight

            flight.record(
                "supervisor", "shard_death", shard=dead.idx, gen=gen
            )
        except Exception:
            pass
        peer = self.shards[self._peer_for(dead.idx)]
        fo = {
            "gen": gen,
            "dead": dead.idx,
            "peer": peer.idx,
            "detected_mono": detected,
            "detected_wall": time.time(),
            "committees": len(dead.committees),
            "journal_dir": str(dead.journal_dir),
            # the dead shard's postmortem: its last completed heartbeat
            # flight dump, collected beside its journal
            "flight_dump": (
                str(dead.flight_path) if dead.flight_path.exists() else None
            ),
            "mttr_s": None,
        }
        self.failovers.append(fo)
        moved = sorted(dead.committees, key=str)
        fo["moved"] = list(moved)
        for cid in moved:
            wire, crec = self._admissions[cid]
            self._send(peer, {
                "cmd": "admit", "cid": cid, "keys": wire, "config": crec,
            })
            self.assignment[cid] = peer.idx
            peer.committees.add(cid)
        dead.committees.clear()
        # peer hygiene (ISSUE 13): refresh every live shard's redirect
        # port map so no redirect keeps steering clients at the dead
        # shard's port — the fingerprint hint dies with the shard, the
        # ports list shrinks to the living
        ports = self.ingress_ports()
        if ports:
            for h in self._alive():
                self._send(h, {"cmd": "ingress_peers", "ports": ports})
        self._send(peer, {"cmd": "recover", "dir": str(dead.journal_dir)})
        # resubmit every unresolved epoch the dead shard owned; the
        # peer's restored idempotency index replays done epochs
        # instantly and re-runs transient ones
        moved_set = set(moved)
        for (cid, epoch), pend in list(self.pending.items()):
            if cid not in moved_set:
                continue
            pend["shard"] = peer.idx
            pend["via"] = "failover"
            pend["gen"] = gen
            self._send(peer, {
                "cmd": "submit", "cid": cid, "epoch": epoch,
            })

    # -- chaos ----------------------------------------------------------
    def kill_shard(self, idx: Optional[int] = None) -> Optional[int]:
        """SIGKILL a live shard (the `shard_kill` fault site acts
        through here). Returns the killed index, or None when no victim
        is available (never kill the last shard standing)."""
        alive = self._alive()
        if len(alive) < 2:
            return None
        victim = None
        for h in alive:
            if idx is None or h.idx == idx:
                victim = h
                break
        if victim is None:
            return None
        try:
            os.kill(victim.proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        self.kills += 1
        return victim.idx

    # -- quiescence / reporting -----------------------------------------
    def drain(self, timeout: float = 120.0) -> bool:
        deadline = time.monotonic() + timeout
        while self.pending and time.monotonic() < deadline:
            self.pump(0.2)
        return not self.pending

    def aggregate(self) -> dict:
        """Fleet-wide rollup from the last heartbeats (dead shards
        contribute their final beat — the aggregate survives kills)."""
        agg: Dict[str, float] = {}
        jagg: Dict[str, float] = {}
        iagg: Dict[str, object] = {}

        def _merge(into: dict, frm: dict) -> None:
            for k, v in frm.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    into[k] = into.get(k, 0) + v
                elif isinstance(v, dict):
                    _merge(into.setdefault(k, {}), v)

        for h in self.shards:
            for k, v in (h.last_stats or {}).items():
                if isinstance(v, (int, float)):
                    agg[k] = agg.get(k, 0) + v
            for k, v in (h.last_journal or {}).items():
                if isinstance(v, (int, float)):
                    jagg[k] = jagg.get(k, 0) + v
            _merge(iagg, h.last_ingress or {})
        return {
            "shards": self.n_shards,
            "alive": len(self._alive()),
            "kills": self.kills,
            "failovers": [
                {k: v for k, v in fo.items() if k != "detected_mono"}
                for fo in self.failovers
            ],
            "serving": agg,
            "journal": jagg,
            "ingress": iagg,
        }


# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--shard", action="store_true",
                   help="run as a shard child process (internal)")
    p.add_argument("--shard-id", type=int, default=0)
    p.add_argument("--journal-dir", default=None)
    p.add_argument("--deadline", type=float, default=10.0)
    p.add_argument("--retries", type=int, default=2)
    p.add_argument("--workers", type=int, default=1)
    p.add_argument("--hb-interval", type=float, default=0.5)
    p.add_argument("--shards", type=int, default=1,
                   help="fleet shard count (redirect fingerprint hints)")
    p.add_argument("--ingress-port", type=int, default=-1,
                   help="TCP ingress port (0 = kernel-assigned, "
                        "-1 = no ingress)")
    p.add_argument("--ingress-host", default="127.0.0.1")
    args = p.parse_args(argv)
    if not args.shard:
        p.error("supervisor is a library; only --shard mode runs directly "
                "(use ShardSupervisor or scripts/loadgen.py --crash-storm)")
    if not args.journal_dir:
        p.error("--journal-dir is required in --shard mode")
    return _shard_main(args)


if __name__ == "__main__":
    sys.exit(main())
