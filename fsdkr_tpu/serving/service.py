"""RefreshService: the long-running multi-committee serving loop
(ISSUE 9, ROADMAP open item 1; chaos-hardened in ISSUE 11).

fs-dkr's refresh is ONE broadcast round, so served throughput is a
scheduling problem: keep the verify/prove engines saturated while many
committees cycle through admit -> distribute -> collect. This service
composes the pieces the engine rounds built — `distribute_batch`'s fused
prover columns, the precompute pools + background producer, streaming
collect (`protocol.streaming`), and the fused quorum-time finalize — into
a scheduler:

- `admit(committee_id, keys, ...)` registers a committee and hands its
  SLO to the CapacityPlanner (pool depth targets under the committee's
  serving owner tag).
- `submit(committee_id)` enqueues one refresh session. The admission
  queue holds PUBLIC metadata only (ids, timestamps); key material stays
  in the per-committee table and is touched only by the protocol calls.
- Worker threads run the prover side (`distribute_batch` under the
  committee's precompute owner scope) and feed the broadcast messages
  into per-party `StreamingCollect` sessions — eager per-message
  verification happens here, spread over the arrival window.
- A launcher thread coalesces quorum-ready sessions into fused
  `finalize_streams` launches sized by the BatchPolicy (size-or-linger,
  mesh-aware), then rotates committee state and retargets the planner
  (the eks just rotated, so the pool targets must follow).

Lifecycle per session: admitted -> pooled (queued) -> distributing ->
collecting -> ready -> finalizing -> done | aborted | timed_out, each
transition stamped and exported through the `fsdkr_serving_*` metrics
(serving.metrics). A submission can also be REJECTED at admission
(overload / bisection-storm shedding, `ServeRejected` with a
retry-after hint) — a rejection never becomes a session.

## Failure semantics (ISSUE 11)

The service has the failure surface of a fleet component; every
submitted session reaches exactly one terminal state:

- **done** — verified and adopted; the committee's epoch advanced.
- **aborted** — a protocol verdict (`FsDkrError`: identifiable-abort
  blame; never retried — the transcript is the evidence) or a transient
  infrastructure failure that exhausted its retries (`sess.blame` is
  False there: infrastructure exhaustion must never read as blame).
- **timed_out** — the FSDKR_SERVE_DEADLINE_S deadline passed (monotonic
  reaper). The error names the missing senders when the session was
  collecting — a quorum gap is identifiable, like abort blame.
- **rejected** — shed at admission; `submit` raised ServeRejected with
  a retry-after hint and no session exists.

Transient failures (anything that is NOT an FsDkrError: a dying worker
thread, a failed finalize launch, injected chaos) retry with jittered
exponential backoff up to FSDKR_SERVE_RETRIES. Retries are SAFE:
distribute restarts from scratch before any key mutation, and collect
is a pure function of the staged public messages until `adopt` (the
repeated-finalize bit-identity test in tests/test_chaos.py pins this).
A worker thread killed mid-session (crash isolation) settles only its
own session and is respawned by its trampoline; the admission queue is
never wedged.

`FSDKR_SERVE=0` turns the scheduler off: `submit` runs the session
synchronously through today's single-shot barrier API
(`distribute_batch` + `collect_sessions`) with no streaming, batching,
or service threads — the A/B arm pinning that the serving layer adds
scheduling, not semantics.

Concurrency rules: at most one in-flight session per committee (a
refresh mutates the committee's LocalKeys; sessions for one committee
serialize through the busy flag while other committees proceed), and
`offer`/`finalize` for one streaming session never race (offers happen
on the worker or the reaper before the session is published to the
ready list; the launcher finalizes only published sessions, and marks
them `finalizing` under the service lock — the reaper never touches a
`finalizing` session, so `StreamingCollect.close` and a fused finalize
cannot race either).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .. import precompute
from ..config import ProtocolConfig, DEFAULT_CONFIG
from ..errors import FsDkrError
from ..protocol.refresh import RefreshMessage
from ..protocol.serialization import refresh_message_to_json
from ..protocol.streaming import finalize_streams
from . import faults, metrics
from .journal import Journal
from .planner import SLO, CapacityPlanner, serve_owner
from .policy import BatchPolicy, BisectGuard, OverloadPolicy, _env_float

__all__ = [
    "RefreshService",
    "ServeSession",
    "ServeRejected",
    "SessionTimeout",
    "enabled",
]

# terminal session states: _finish is idempotent against them, so a
# worker, the reaper, and the launcher can settle the same session
# concurrently and exactly one transition wins
TERMINAL = ("done", "aborted", "timed_out")


def enabled() -> bool:
    """FSDKR_SERVE gates the scheduler (default on). =0 makes submit()
    a synchronous single-shot barrier refresh — today's API, unchanged."""
    return os.environ.get("FSDKR_SERVE", "1").lower() not in (
        "0", "off", "false", "no",
    )


def _device_count() -> int:
    """Device count for the default BatchPolicy's mesh-aware batch
    alignment; 1 (alignment off) when JAX is unavailable or still
    uninitialized-fast-path. The fused finalize launches row-shard over
    all local devices, so the coalescer sizes batches to divide them."""
    try:
        import jax

        return max(1, jax.local_device_count())
    except Exception:
        return 1


def _shuffle_arrivals() -> bool:
    """FSDKR_SERVE_SHUFFLE (default on): feed each session's broadcast
    messages to the streaming collectors in a session-seeded random
    order, exercising the arrival-order independence the equivalence
    tests pin. =0 feeds canonical order (debugging)."""
    return os.environ.get("FSDKR_SERVE_SHUFFLE", "1").lower() not in (
        "0", "off", "false", "no",
    )


class ServeRejected(RuntimeError):
    """submit() shed this request at admission (overload or
    bisection-storm budget). Carries an honest retry-after hint; the
    request never became a session, so nothing was spent on it —
    clients retry with `retry_after_s` the way they would honor a
    429/Retry-After."""

    def __init__(self, committee_id, retry_after_s: float, reason: str):
        self.committee_id = committee_id
        self.retry_after_s = float(retry_after_s)
        self.reason = reason
        super().__init__(
            f"admission rejected for committee {committee_id!r} "
            f"({reason}); retry after {self.retry_after_s:.2f}s"
        )


class SessionTimeout(RuntimeError):
    """A session crossed its FSDKR_SERVE_DEADLINE_S deadline. When the
    session was collecting, `missing` names the senders whose broadcast
    never arrived — the quorum gap is identifiable, mirroring abort
    blame (a timed-out session is never confused with a verdict)."""

    def __init__(self, state: str, missing: Sequence[int], waited_s: float):
        self.state = state
        self.missing = list(missing)
        self.waited_s = waited_s
        detail = f"; missing senders {self.missing}" if self.missing else ""
        super().__init__(
            f"session deadline exceeded after {waited_s:.2f}s in state "
            f"{state!r}{detail}"
        )


@dataclass
class ServeSession:
    """Public per-session record. Queue/state fields are broadcast-safe
    metadata; the streaming collectors (which hold broadcast messages
    and verdicts) hang off the internal `_streams` and never enter the
    admission queue. `faults` lists the injected-fault sites that hit
    this session (site names + sender indices only — chaos-run
    accounting, never key material)."""

    session_id: int
    committee_id: object
    state: str = "admitted"
    epoch: Optional[int] = None
    submitted_at: float = 0.0
    started_at: float = 0.0
    quorum_at: float = 0.0
    finalized_at: float = 0.0
    deadline: float = 0.0
    retries: int = 0
    blame: bool = False
    error: Optional[str] = None
    faults: List[str] = field(default_factory=list)
    # network-fed session (ISSUE 13): the worker runs distribute and
    # parks the wire-serialized broadcasts in `_wire_msgs` instead of
    # self-feeding the collectors; the ingress hands them to the client
    # (the broadcast channel) and routes the returned broadcasts back
    # through `offer_external`. Broadcasts are public by definition —
    # `_wire_msgs` holds exactly what any party would see on the wire.
    external: bool = False
    _wire_msgs: List[Tuple[int, str]] = field(
        default_factory=list, repr=False
    )
    _not_before: float = 0.0
    _pending: List[Tuple[float, object]] = field(
        default_factory=list, repr=False
    )
    _streams: list = field(default_factory=list, repr=False)
    _config: Optional[ProtocolConfig] = field(default=None, repr=False)
    _done_evt: threading.Event = field(
        default_factory=threading.Event, repr=False
    )
    # set once distribute finished for an external session (wire
    # broadcasts available) — or at terminal, whichever comes first
    _dist_evt: threading.Event = field(
        default_factory=threading.Event, repr=False
    )


@dataclass
class _Committee:
    keys: list
    config: ProtocolConfig
    slo: SLO
    # session id currently holding the one-in-flight-per-committee
    # slot, or None. Ownership matters: only the holder's settle path
    # may free it — a reaper timing out a QUEUED sibling must not
    # release a slot some other live session owns (two concurrent
    # refreshes would adopt into the same LocalKeys)
    busy: Optional[int] = None
    epochs: int = 0


class RefreshService:
    """See module docstring. Construct, `admit` committees, `start()`,
    then `submit`/`wait`/`drain`; `stop()` joins the threads."""

    def __init__(
        self,
        policy: Optional[BatchPolicy] = None,
        planner: Optional[CapacityPlanner] = None,
        workers: Optional[int] = None,
        overload: Optional[OverloadPolicy] = None,
        guard: Optional[BisectGuard] = None,
        deadline_s: Optional[float] = None,
        retries: Optional[int] = None,
        backoff_s: Optional[float] = None,
        journal=None,
        keystore=None,
    ):
        # durability (ISSUE 12): `journal` is a serving.journal.Journal
        # or a directory path; when set, every session's public facts
        # (admission, accepted broadcasts via the wire codec, terminal
        # verdicts) are write-ahead logged so serving.recovery can
        # replay them after process death. `keystore` holds the SECRET
        # side (committee LocalKeys, per-session new dks) in process
        # memory only — defaulted so an in-process restart recovers
        # fully; across real death the session secrets are gone by
        # design and recovery degrades to retryable transient aborts.
        if isinstance(journal, (str, os.PathLike)):
            journal = Journal(journal)
        self.journal = journal
        if keystore is None and journal is not None:
            from .recovery import MemoryKeystore

            keystore = MemoryKeystore()
        self.keystore = keystore
        self.policy = policy or BatchPolicy(devices=_device_count())
        self.planner = planner or CapacityPlanner()
        self.overload = overload or OverloadPolicy()
        self.guard = guard or BisectGuard()
        if workers is None:
            try:
                workers = int(os.environ.get("FSDKR_SERVE_WORKERS", "1"))
            except ValueError:
                workers = 1
        self.workers = max(1, workers)
        # robustness knobs (ISSUE 11): deadline 0 = no reaper timeouts;
        # retries bound transient-failure requeues and finalize relaunches
        self.deadline_s = (
            deadline_s
            if deadline_s is not None
            else _env_float("FSDKR_SERVE_DEADLINE_S", 0.0)
        )
        self.retries = (
            retries
            if retries is not None
            else max(0, int(_env_float("FSDKR_SERVE_RETRIES", 2)))
        )
        self.backoff_s = (
            backoff_s
            if backoff_s is not None
            else max(0.0, _env_float("FSDKR_SERVE_BACKOFF_MS", 50.0) / 1000.0)
        )
        self._committees: Dict[object, _Committee] = {}
        # ACTIVE sessions only; finished ones move to the bounded
        # history below so a long-running service cannot grow without
        # bound (and stats() never scans more than inflight + history)
        self._sessions: Dict[int, ServeSession] = {}
        self._finished: "OrderedDict[int, ServeSession]" = OrderedDict()
        try:
            self._history = max(
                1, int(os.environ.get("FSDKR_SERVE_HISTORY", "65536"))
            )
        except ValueError:
            self._history = 65536
        self._queue: deque = deque()  # session ids, FIFO (public metadata)
        self._ready: List[int] = []  # quorum-ready session ids
        # failed finalize launches awaiting their backoff: (not-before,
        # attempt, batch) — requeued, NEVER slept out on the launcher
        # thread (other committees' ready sessions must not wait behind
        # one batch's backoff)
        self._retry_batches: List[Tuple[float, int, List[ServeSession]]] = []
        # client-retry idempotency: (committee_id, epoch) -> session id
        self._epoch_index: Dict[Tuple[object, int], int] = {}
        self._lock = threading.Lock()
        self._work_cv = threading.Condition(self._lock)
        self._ready_cv = threading.Condition(self._lock)
        self._reap_cv = threading.Condition(self._lock)
        self._idle_cv = threading.Condition(self._lock)
        self._next_id = 0
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._inflight = 0
        self.sessions_done = 0
        self.sessions_aborted = 0
        self.sessions_timed_out = 0
        self.sessions_rejected = 0
        self.sessions_replayed = 0
        self.workers_respawned = 0
        # windowed end-to-end latencies for THIS service's overload
        # gate (not the cumulative histogram, which never forgets a
        # storm; not process-global state, which a sibling service
        # would pollute). The ring turns over with traffic, so the
        # gate reads the current regime — timeouts included
        # deliberately: persistent overload producing timeouts is
        # exactly what should shed. Guarded by self._lock.
        self._recent_totals: deque = deque(maxlen=256)

    # -- journal plumbing (ISSUE 12) ------------------------------------
    def _jappend(self, rec: dict) -> None:
        """Append one record when journaling is on. Raises on IO
        failure — an admission or broadcast that cannot be made durable
        must fail loudly (the worker retry path treats it as any other
        transient infrastructure failure)."""
        if self.journal is not None:
            self.journal.append(rec)

    def _jappend_safe(self, rec: dict) -> None:
        """Best-effort append for the TERMINAL path: a dying journal
        must never leave a finished session's waiters hanging. A
        swallowed failure means the record is missing from the log, so
        replay sees the session in-flight and settles it retryably —
        degraded durability, never a wrong verdict."""
        try:
            self._jappend(rec)
        except Exception:
            try:
                from ..telemetry import flight

                flight.record("journal", "terminal_append_failed")
            except Exception:
                pass

    def _deposit_dks(self, sess: ServeSession, dks: Sequence) -> None:
        """Park the session's new decryption keys (party order) in the
        in-memory keystore so an in-process recovery can resume the
        session; dropped at terminal. Never serialized, never on
        disk."""
        if self.keystore is not None and self.journal is not None:
            self.keystore.put_session_dks(
                sess.committee_id, sess.session_id, dks
            )

    def _offer_all(self, sess: ServeSession, streams, msg, wire=None) -> str:
        """Offer one broadcast message to every collector of a session
        and journal it IFF it was accepted (first arrival wins: the
        accepted copy — tampered or honest — is what replay must
        re-offer). `wire` lets recovery re-journal the exact bytes it
        replayed instead of re-serializing."""
        res = None
        for st in streams:
            r = st.offer(msg)
            res = r if res is None else res
        if res == "accepted" and self.journal is not None:
            self._jappend(
                {
                    "t": "broadcast",
                    "sid": sess.session_id,
                    "sender": msg.party_index,
                    "wire": wire or refresh_message_to_json(msg),
                }
            )
        return res or "unexpected"

    # -- committee membership -------------------------------------------
    def admit(
        self,
        committee_id,
        keys: Sequence,
        config: ProtocolConfig = DEFAULT_CONFIG,
        slo: SLO = SLO(),
    ) -> None:
        """Register a committee (its parties' LocalKeys, in index order)
        and install its SLO-derived pool targets."""
        if self.journal is not None:
            # the id must survive the wire ROUND-TRIP, not just encode:
            # a tuple id serializes fine but decodes as an unhashable
            # list, which would abort the entire replay at recovery —
            # far too late to discover it
            try:
                ok = json.loads(json.dumps(committee_id)) == committee_id
            except TypeError:
                ok = False
            if not ok:
                raise TypeError(
                    "journaled committee ids must round-trip through "
                    "JSON (use str/int ids; got "
                    f"{type(committee_id).__name__})"
                )
            # WAL the committee record BEFORE any in-memory state: a
            # failed append must leave nothing half-admitted (the
            # caller can simply retry admit). A duplicate-admit that
            # fails below leaves a redundant record; replay keys
            # committees by id, so last-wins is harmless.
            from .recovery import config_record

            self._jappend(
                {
                    "t": "committee",
                    "cid": committee_id,
                    "n": len(keys),
                    "tt": keys[0].t,
                    "config": config_record(config),
                }
            )
        with self._lock:
            if committee_id in self._committees:
                raise ValueError(f"committee {committee_id!r} already admitted")
            self._committees[committee_id] = _Committee(
                keys=list(keys), config=config, slo=slo
            )
            metrics.committees_gauge().set(len(self._committees))
        if self.keystore is not None:
            self.keystore.put_committee(committee_id, keys)
        self.planner.register(committee_id, keys[0], len(keys), config, slo)

    def evict(self, committee_id) -> None:
        """Remove a committee; its pool targets are invalidated and the
        pooled single-use secrets wiped now (churn discipline). Its
        idempotency entries die with it — a committee re-admitted under
        the same id is a NEW incarnation whose epochs must actually
        run, not replay a dead predecessor's finished sessions."""
        with self._lock:
            com = self._committees.pop(committee_id, None)
            metrics.committees_gauge().set(len(self._committees))
            for key in [
                k for k in self._epoch_index if k[0] == committee_id
            ]:
                del self._epoch_index[key]
        if com is not None:
            self.planner.invalidate(committee_id)
        if self.keystore is not None:
            self.keystore.drop_committee(committee_id)

    def _measured_p99_s(self) -> float:
        """Exact p99 over this service's last 256 finished sessions
        (the overload gate's load signal; 0.0 before any finish).
        Caller holds self._lock."""
        if not self._recent_totals:
            return 0.0
        vals = sorted(self._recent_totals)
        return vals[min(len(vals) - 1, int(round(0.99 * (len(vals) - 1))))]

    # -- session intake -------------------------------------------------
    def submit(
        self,
        committee_id,
        epoch: Optional[int] = None,
        external: bool = False,
    ) -> int:
        """Enqueue one refresh session for the committee; returns the
        session id. With FSDKR_SERVE=0 the session runs synchronously
        (single-shot barrier semantics) before this returns.

        `epoch` makes the submission IDEMPOTENT: a resubmission with
        the same (committee fingerprint, epoch) returns the EXISTING
        session id — in flight or already finished — instead of
        enqueuing a double-spend of pooled key bundles. This is the
        client-retry contract a real ingress needs: retry the same
        logical refresh freely, observe one session. A FAILED epoch
        (aborted/timed_out) becomes retryable again — the next submit
        creates a fresh session. Retention bound: a completed epoch's
        dedupe entry lives as long as its session stays in the bounded
        history (FSDKR_SERVE_HISTORY finishes, like an idempotency-key
        TTL) — a retry arriving later than that re-runs the refresh.
        Without `epoch` every call is a new session (the pre-ISSUE-11
        behavior).

        `external=True` makes this a NETWORK-FED session (ISSUE 13):
        the worker still runs distribute (the service holds the
        committee's keys), but instead of simulating the broadcast
        channel in-process it parks the wire-serialized broadcasts for
        the client to fetch (`wait_broadcasts`) and re-deliver
        (`offer_external`) — the messages actually transit the network.
        An external session can only terminate via delivered broadcasts
        or the deadline reaper, so the service MUST have a deadline
        (an abandoned client must not wedge its committee forever).

        Raises `ServeRejected` (with a retry-after hint) when the
        overload policy or the committee's bisection-storm budget sheds
        the request at admission."""
        if external:
            if not enabled():
                raise ValueError(
                    "external sessions need the scheduler (FSDKR_SERVE=0 "
                    "runs submit synchronously; there is no window to "
                    "deliver broadcasts into)"
                )
            if self.deadline_s <= 0:
                raise ValueError(
                    "external sessions require a session deadline "
                    "(FSDKR_SERVE_DEADLINE_S / deadline_s > 0): an "
                    "abandoned client would wedge its committee forever"
                )
        now = time.monotonic()
        with self._lock:
            com = self._committees.get(committee_id)
            if com is None:
                raise KeyError(f"committee {committee_id!r} not admitted")
            if epoch is not None:
                sid = self._epoch_index.get((committee_id, epoch))
                if sid is not None:
                    return sid
            hint, reason = None, ""
            b = self.guard.blocked(committee_id, now)
            if b is not None:
                hint, reason = b, "bisection budget exhausted"
            if hint is None and self.overload.engaged():
                h = self.overload.check(
                    len(self._queue),
                    self._measured_p99_s(),
                    com.slo.p99_budget_s,
                )
                if h is not None:
                    hint, reason = h, "overload"
            if hint is not None:
                self.sessions_rejected += 1
                metrics.record_outcome("rejected", 0.0)
                raise ServeRejected(committee_id, hint, reason)
            self._next_id += 1
            sess = ServeSession(
                session_id=self._next_id,
                committee_id=committee_id,
                epoch=epoch,
                submitted_at=now,
                external=external,
            )
            if self.deadline_s > 0:
                sess.deadline = now + self.deadline_s
            # register fully (dedup index, session table, inflight) but
            # do NOT make it runnable yet — concurrent duplicate
            # submits dedupe to it and wait() finds it while we journal
            if epoch is not None:
                self._epoch_index[(committee_id, epoch)] = sess.session_id
            self._sessions[sess.session_id] = sess
            self._inflight += 1
            metrics.inflight_gauge().set(self._inflight)
        # WAL the admission OUTSIDE the lock (sync=always fsyncs here —
        # that must stall only this submitter, not every worker). The
        # session is not queued yet, so `admitted` still precedes any
        # `collecting` a worker could journal for it.
        try:
            self._jappend(
                {
                    "t": "admitted",
                    "sid": sess.session_id,
                    "cid": committee_id,
                    "epoch": epoch,
                }
            )
        except Exception as e:
            # a session that never became durable never runs — but a
            # concurrent duplicate submit may already hold its sid (the
            # dedup index was live while we journaled), so SETTLE it
            # (_finish: aborted without blame, epoch entry dropped,
            # waiters woken) instead of vanishing it, then surface the
            # journal failure to this submitter
            self._finish(sess, e, time.monotonic())
            raise
        with self._lock:
            if enabled():
                sess.state = "pooled"
                self._queue.append(sess.session_id)
                metrics.queue_gauge().set(len(self._queue))
                self._work_cv.notify()
                if sess.deadline:
                    self._reap_cv.notify()
                return sess.session_id
        # FSDKR_SERVE=0: today's single-shot path, inline
        self._run_single_shot(sess)
        return sess.session_id

    def wait(self, session_id: int, timeout: Optional[float] = None) -> ServeSession:
        """Block until the session reaches a terminal state and return
        it. Raises `TimeoutError` when `timeout` elapses first — a
        timeout is DISTINGUISHABLE from completion; this never hands
        back a possibly-unfinished session (ISSUE 11)."""
        with self._lock:
            sess = self._sessions.get(session_id) or self._finished.get(
                session_id
            )
        if sess is None:
            raise KeyError(
                f"session {session_id} unknown (finished sessions are "
                f"retained up to FSDKR_SERVE_HISTORY={self._history})"
            )
        if not sess._done_evt.wait(timeout):
            raise TimeoutError(
                f"session {session_id} still {sess.state!r} after "
                f"{timeout}s"
            )
        return sess

    # -- network-fed sessions (ISSUE 13; driven by serving.ingress) -----
    def wait_broadcasts(
        self, session_id: int, timeout: Optional[float] = None
    ) -> Tuple[str, List[Tuple[int, str]]]:
        """Block until an external session's distribute outputs exist
        (or the session went terminal first) and return
        ``(state, [(sender, wire_json), ...])``. The wire list is empty
        once terminal — the caller reads the state instead. Raises
        `TimeoutError` when `timeout` elapses, `KeyError` for unknown
        sessions (same retention contract as `wait`)."""
        with self._lock:
            sess = self._sessions.get(session_id) or self._finished.get(
                session_id
            )
        if sess is None:
            raise KeyError(f"session {session_id} unknown")
        if not sess._dist_evt.wait(timeout):
            raise TimeoutError(
                f"session {session_id} still {sess.state!r} after "
                f"{timeout}s (no broadcasts yet)"
            )
        with self._lock:
            return sess.state, list(sess._wire_msgs)

    def offer_external(self, session_id: int, wire: str) -> str:
        """Deliver one broadcast (wire JSON) into an external session's
        collectors through the SAME offer path every other arrival
        uses — journaled iff accepted, first arrival wins. Returns
        "accepted" / "duplicate" / "unexpected" (wrong sender, or the
        session is not network-fed) / "late" (already terminal or past
        quorum) / "unknown" (no such session) / "pending" (distribute
        still running — a protocol-violating client broadcasting before
        it ever received the session's broadcast set). Raises whatever
        the wire codec raises on an undecodable payload — the ingress
        translates that into its malformed-frame policy. Thread-safe:
        concurrent offers from many connections interleave freely
        (arrival-order independence is pinned), and quorum publishes
        exactly once via the state transition under the lock."""
        from ..protocol.serialization import refresh_message_from_json

        with self._lock:
            sess = self._sessions.get(session_id)
            if sess is None:
                return (
                    "late" if session_id in self._finished else "unknown"
                )
            if not sess.external:
                return "unexpected"
            if sess.state in TERMINAL or sess.state in (
                "ready", "finalizing",
            ):
                return "late"
            streams = list(sess._streams)
            if not streams:
                return "pending"
        msg = refresh_message_from_json(wire)  # codec outside the lock
        res = self._offer_all(sess, streams, msg, wire=wire)
        if res == "accepted":
            with self._lock:
                if (
                    sess.state == "collecting"
                    and sess._streams
                    and all(st.ready for st in sess._streams)
                ):
                    # exactly-one publish: the state transition is the
                    # guard (a racing offer sees "ready" and stops)
                    sess.state = "ready"
                    sess.quorum_at = time.monotonic()
                    self._ready.append(sess.session_id)
                    self._ready_cv.notify()
        return res

    def drain(self, timeout: float = 60.0) -> bool:
        """Block until every submitted session finished (True) or the
        timeout elapsed (False). Condition-variable wait — wakes on the
        final _finish, not on a poll tick."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle_cv.wait(timeout=remaining)
            return True

    # -- service threads ------------------------------------------------
    def start(self) -> None:
        if self._threads:
            return
        self._stop.clear()
        for w in range(self.workers):
            t = threading.Thread(
                target=self._worker_trampoline, args=(w,),
                name=f"fsdkr-serve-worker-{w}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        for target, name in (
            (self._launcher_loop, "fsdkr-serve-launcher"),
            (self._reaper_loop, "fsdkr-serve-reaper"),
        ):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        with self._lock:
            self._work_cv.notify_all()
            self._ready_cv.notify_all()
            self._reap_cv.notify_all()
            self._idle_cv.notify_all()
        for t in self._threads:
            t.join(timeout=timeout)
        self._threads.clear()
        if self.journal is not None:
            self.journal.close()

    # -- internals: prover/stream side ----------------------------------
    def _pop_work(self, now: float):
        """Under the lock: the first queued session whose committee is
        idle and whose retry backoff has elapsed (FIFO per committee;
        other committees' sessions overtake a busy one). Returns
        (session, None) or (None, seconds-until-next-backoff-expiry)."""
        next_wake: Optional[float] = None
        for idx, sid in enumerate(self._queue):
            sess = self._sessions.get(sid)
            if sess is None or sess.state in TERMINAL:
                del self._queue[idx]  # reaped while queued
                return None, 0.0  # rescan immediately
            com = self._committees.get(sess.committee_id)
            if com is None:
                # evicted mid-queue: abort below, outside the scan
                del self._queue[idx]
                return sess, None
            if sess._not_before > now:
                dt = sess._not_before - now
                next_wake = dt if next_wake is None else min(next_wake, dt)
                continue
            if com.busy is None:
                com.busy = sess.session_id
                del self._queue[idx]
                return sess, None
        return None, next_wake

    def _worker_trampoline(self, w: int) -> None:
        """Crash isolation: a worker whose loop dies (an injected
        worker crash, or any bug escaping the per-session handler) is
        respawned here — the failing session was already settled by
        `_session_failed`, the committee freed, and the admission queue
        keeps draining. One crash costs one session attempt, never the
        service."""
        while not self._stop.is_set():
            try:
                self._worker_loop()
                return  # clean stop
            except Exception:
                self.workers_respawned += 1

    def _worker_loop(self) -> None:
        while True:
            with self._lock:
                if self._stop.is_set():
                    return
                sess, wake = self._pop_work(time.monotonic())
                if sess is None:
                    if wake != 0.0:
                        self._work_cv.wait(timeout=wake)
                    continue
                metrics.queue_gauge().set(len(self._queue))
                com = self._committees.get(sess.committee_id)
            if com is None:
                self._finish(
                    sess, RuntimeError("committee evicted"), time.monotonic()
                )
                continue
            try:
                self._run_session(sess, com)
            except Exception as e:  # distribute/offer/injected failures
                self._session_failed(sess, com, e)
                if isinstance(e, faults.InjectedWorkerCrash):
                    raise  # the thread dies; the trampoline respawns it

    def _session_failed(self, sess: ServeSession, com, e: Exception) -> None:
        """Settle a failed worker attempt: protocol verdicts abort with
        blame immediately; transient failures requeue with jittered
        exponential backoff until FSDKR_SERVE_RETRIES is spent."""
        now = time.monotonic()
        requeue = False
        with self._lock:
            if com is not None and com.busy == sess.session_id:
                com.busy = None
                self._work_cv.notify()
            if sess.state in TERMINAL:
                return  # the reaper settled it first
            transient = not isinstance(e, FsDkrError)
            # external sessions never requeue: a retried attempt would
            # re-run distribute with FRESH randomness, and the client
            # may already hold (and re-deliver) the failed attempt's
            # broadcasts — pairing one attempt's messages with
            # another's secrets is exactly the replay shape recovery
            # forbids. The failed epoch drops its dedupe entry at
            # _finish, so the client's resubmit starts a clean session
            # under a NEW sid (stale broadcasts to the old sid are
            # "late", never mixed in).
            if transient and sess.retries < self.retries and not sess.external:
                sess.retries += 1
                backoff = self.backoff_s * (2 ** (sess.retries - 1))
                backoff *= 1.0 + random.random()  # jitter: decorrelate herds
                sess._not_before = now + backoff
                sess.state = "pooled"
                sess._streams = []
                requeue = True
        if not requeue:
            self._finish(sess, e, now)
            return
        # WAL the attempt boundary OUTSIDE the service lock (the
        # journal fsyncs under its own lock — fsdkr-lint lock-blocking
        # rule, same shape as submit's admission append): the retried
        # attempt re-runs distribute with fresh randomness, so the
        # failed attempt's journaled broadcasts (and deposited dks) are
        # stale — a replay mixing attempts would pair one attempt's
        # messages with another's secrets. The reset record makes
        # replay start from the latest attempt only; ordering is safe
        # because the session is not queued yet, so the next attempt
        # cannot journal anything before the reset lands.
        self._jappend_safe({"t": "reset", "sid": sess.session_id})
        if self.keystore is not None:
            self.keystore.drop_session(sess.committee_id, sess.session_id)
        with self._lock:
            if sess.state != "pooled":
                return  # the reaper timed it out while we journaled
            self._queue.append(sess.session_id)
            metrics.queue_gauge().set(len(self._queue))
            metrics.retries_counter().inc(stage="worker")
            self._work_cv.notify()

    def _advance(self, sess: ServeSession, state: str) -> bool:
        """Move a session to a non-terminal lifecycle state, under the
        lock, UNLESS it already reached a terminal state (the reaper
        can settle a session while a worker is mid-flight on it; a
        plain write here would resurrect it and double-finish). False
        = the session is already settled, the caller must discard its
        attempt."""
        with self._lock:
            if sess.state in TERMINAL:
                return False
            sess.state = state
            return True

    def _run_session(self, sess: ServeSession, com: _Committee) -> None:
        plan = faults.active()
        now = time.monotonic()
        metrics.record_phase("queue", now - sess.submitted_at)
        sess.started_at = now
        if not self._advance(sess, "distributing"):
            return  # reaped while queued; _finish already freed busy
        if plan and plan.fire("worker_crash", (sess.session_id, sess.retries)):
            sess.faults.append("worker_crash")
            raise faults.InjectedWorkerCrash(
                f"injected worker crash (session {sess.session_id}, "
                f"attempt {sess.retries})"
            )
        keys, config = com.keys, com.config
        new_n = len(keys)
        # roll EVERY broadcast-fault decision up front — decisions are
        # pure functions of (seed, session, sender index), so they need
        # no message content — and stamp sess.faults BEFORE distribute:
        # a deadline firing at any later point can already name the
        # full dropped-sender set (precedence per message: drop >
        # tamper > delay > dup)
        actions: Dict[int, Optional[str]] = {}
        if plan is not None and not sess.external:
            # external sessions skip the in-process arrival simulation
            # entirely — their chaos is the NETWORK's (conn_drop /
            # frame_truncate / net_* fire at the ingress, and the client
            # is free to drop/duplicate/tamper what it re-broadcasts)
            for k in keys:
                pid = k.i
                for site in ("msg_drop", "msg_tamper", "msg_delay",
                             "msg_dup"):
                    if plan.fire(site, (sess.session_id, pid)):
                        actions[pid] = site
                        sess.faults.append(f"{site}:{pid}")
                        break
        owner = serve_owner(sess.committee_id)
        with precompute.owner_scope(owner):
            results = RefreshMessage.distribute_batch(
                [(k.i, k) for k in keys], new_n, config
            )
        t_dist = time.monotonic()
        metrics.record_phase("distribute", t_dist - now)

        msgs = [m for m, _ in results]
        if not self._advance(sess, "collecting"):
            return  # reaped while distributing; attempt discarded
        expected = [k.i for k in keys]
        # secrets to the keystore (memory only), public facts to the WAL
        self._deposit_dks(sess, [dk for _m, dk in results])
        self._jappend(
            {"t": "collecting", "sid": sess.session_id, "expected": expected}
        )
        streams = [
            RefreshMessage.collect_stream(k, results[idx][1], expected, (), config)
            for idx, k in enumerate(keys)
        ]
        if sess.external:
            # network-fed: serialize the broadcasts ONCE (public wire
            # encoding), park them for the client, and hand the session
            # to the collecting state — every delivery from here on
            # comes through offer_external (ingress) or dies at the
            # deadline, which names the senders the network lost
            wire_msgs = [
                (m.party_index, refresh_message_to_json(m)) for m in msgs
            ]
            with self._lock:
                if sess.state in TERMINAL:
                    for st in streams:
                        st.close(RuntimeError("session already settled"))
                    return
                sess._streams = streams
                sess._config = config
                sess._wire_msgs = wire_msgs
                sess.state = "collecting"
                self._reap_cv.notify()
            sess._dist_evt.set()
            return
        # simulated broadcast arrival: each message lands at every
        # collector before the next arrives; order is session-seeded so
        # reordering is exercised continuously in production-like runs.
        # Under a fault plan a message may instead be dropped, tampered
        # (tampered copy first, honest copy as the corrected duplicate —
        # first arrival wins), delayed (delivered by the reaper after
        # delay_s), or duplicated.
        order = list(msgs)
        if _shuffle_arrivals():
            random.Random(sess.session_id).shuffle(order)
        pending: List[Tuple[float, object]] = []
        for m in order:
            if sess.state in TERMINAL:
                break  # reaped mid-arrival: stop burning verify time
            act = actions.get(m.party_index)
            if act == "msg_drop":
                continue
            if act == "msg_tamper":
                bad = faults.tamper_message(m)
                # the TAMPERED copy is what gets accepted (and hence
                # journaled — replay must reproduce the blame); the
                # honest copy lands as the corrected duplicate
                self._offer_all(sess, streams, bad)
                self._offer_all(sess, streams, m)
                continue
            if act == "msg_delay":
                pending.append((time.monotonic() + plan.delay_s, m))
                continue
            if act == "msg_dup":
                self._offer_all(sess, streams, m)
            self._offer_all(sess, streams, m)
        t_stream = time.monotonic()
        metrics.record_phase("stream", t_stream - t_dist)

        timeout_now = False
        with self._lock:
            if sess.state in TERMINAL:
                # the reaper settled this session while we were
                # distributing; discard the attempt's streams
                for st in streams:
                    st.close(RuntimeError("session already settled"))
                return
            sess._streams = streams
            sess._config = config
            sess.quorum_at = t_stream
            if all(st.ready for st in streams):
                sess.state = "ready"
                self._ready.append(sess.session_id)
                self._ready_cv.notify()
            else:
                # short of quorum: park for late (delayed) arrivals —
                # the reaper delivers `pending` and publishes at quorum,
                # or times the session out at its deadline, naming the
                # missing senders
                sess.state = "collecting"
                sess._pending = pending
                if pending or sess.deadline:
                    self._reap_cv.notify()
                else:
                    # nothing will ever arrive and no deadline is set:
                    # settle now instead of wedging (drop faults without
                    # FSDKR_SERVE_DEADLINE_S must still terminate)
                    timeout_now = True
        if timeout_now:
            self._timeout_session(sess)

    # -- internals: deadline reaper + delayed delivery ------------------
    def _reaper_loop(self) -> None:
        """Monotonic-clock timekeeper: delivers delayed broadcast
        messages when due and moves sessions past their deadline to the
        `timed_out` terminal state. Never touches a session the
        launcher already marked `finalizing`."""
        while True:
            deliveries: List[Tuple[ServeSession, list]] = []
            timeouts: List[ServeSession] = []
            with self._lock:
                if self._stop.is_set():
                    return
                now = time.monotonic()
                next_wake: Optional[float] = None
                for sess in list(self._sessions.values()):
                    if sess.state in TERMINAL or sess.state == "finalizing":
                        continue
                    if sess.deadline and now >= sess.deadline:
                        timeouts.append(sess)
                        continue
                    if sess._pending:
                        due = [m for t, m in sess._pending if t <= now]
                        if due:
                            sess._pending = [
                                (t, m) for t, m in sess._pending if t > now
                            ]
                            deliveries.append((sess, due))
                        for t, _m in sess._pending:
                            next_wake = (
                                t if next_wake is None else min(next_wake, t)
                            )
                    if sess.deadline:
                        next_wake = (
                            sess.deadline
                            if next_wake is None
                            else min(next_wake, sess.deadline)
                        )
                if not deliveries and not timeouts:
                    self._reap_cv.wait(
                        timeout=None if next_wake is None else
                        max(0.001, next_wake - now)
                    )
                    continue
            # timeouts FIRST: a delivery runs real proof verification
            # (StreamingCollect.offer) on this thread, and expired
            # sessions must not wait behind it. Deliveries stay on this
            # one thread deliberately — it serializes offers per parked
            # session (offer/finalize must never race) — so a deadline
            # expiring MID-delivery-batch is observed one batch late;
            # the lateness is bounded by one wake's delivery work and
            # only exists under injected msg_delay storms.
            for sess in timeouts:
                self._timeout_session(sess)
            for sess, due in deliveries:
                try:
                    for m in due:
                        self._offer_all(sess, sess._streams, m)
                except Exception as e:
                    # a failing delivery (journal IO, a codec bug) must
                    # settle the session, never kill the reaper thread;
                    # close the collectors like every other failure
                    # path (late offers -> "late", staged refs freed)
                    for st in sess._streams:
                        st.close(e)
                    self._finish(sess, e, time.monotonic())
                    continue
                dead_end = False
                with self._lock:
                    if (
                        sess.state == "collecting"
                        and sess._streams
                        and all(st.ready for st in sess._streams)
                    ):
                        sess.state = "ready"
                        sess.quorum_at = time.monotonic()
                        self._ready.append(sess.session_id)
                        self._ready_cv.notify()
                    elif (
                        sess.state == "collecting"
                        and not sess._pending
                        and not sess.deadline
                    ):
                        # the last delayed message just landed, the
                        # session is STILL short of quorum (a dropped
                        # sender), and no deadline will ever fire:
                        # settle now instead of wedging
                        dead_end = True
                if dead_end:
                    self._timeout_session(sess)

    def _timeout_session(self, sess: ServeSession) -> None:
        with self._lock:
            if sess.state in TERMINAL or sess.state == "finalizing":
                return
            try:
                self._queue.remove(sess.session_id)
            except ValueError:
                pass
            self._ready = [s for s in self._ready if s != sess.session_id]
            metrics.queue_gauge().set(len(self._queue))
            # name the quorum gap: senders the collectors are missing,
            # UNION the drops already rolled for this session (streams
            # may not be attached yet when the deadline fires mid-offer
            # — the pre-rolled fault stamps still name the culprits)
            missing = sorted(
                {pid for st in sess._streams for pid in st.missing()}
                | {
                    int(f.split(":", 1)[1])
                    for f in sess.faults
                    if f.startswith("msg_drop:")
                }
            )
            state0 = sess.state
            waited = time.monotonic() - sess.submitted_at
            streams = list(sess._streams)
        err = SessionTimeout(state0, missing, waited)
        for st in streams:
            st.close(err)  # late offers -> "late"; staged refs released
        self._finish(sess, err, time.monotonic(), state="timed_out")

    # -- internals: coalescing finalize side ----------------------------
    def _pick_batch(self) -> List[ServeSession]:
        """Under the lock: choose the batch to finalize now (oldest
        config group, policy-sized), or [] to keep lingering. Sessions
        the reaper settled while they sat in the ready list are swept
        out here."""
        live: List[ServeSession] = []
        for sid in self._ready:
            s = self._sessions.get(sid)
            if s is not None and s.state == "ready":
                live.append(s)
        if len(live) != len(self._ready):
            self._ready = [s.session_id for s in live]
        if not live:
            return []
        groups: Dict[object, List[ServeSession]] = {}
        for s in live:
            groups.setdefault(s._config, []).append(s)
        # oldest-first: the group containing the longest-waiting session
        group = min(groups.values(), key=lambda g: g[0].quorum_at)
        oldest_wait = time.monotonic() - group[0].quorum_at
        rows = 0
        if group[0]._streams:
            st0 = group[0]._streams[0]
            rows = len(st0.expected) * st0.new_n * len(group[0]._streams)
        count = self.policy.take(len(group), oldest_wait, rows)
        if count <= 0:
            return []
        batch = group[:count]
        taken = {s.session_id for s in batch}
        self._ready = [sid for sid in self._ready if sid not in taken]
        return batch

    def _launcher_loop(self) -> None:
        while True:
            with self._lock:
                if self._stop.is_set():
                    return
                now = time.monotonic()
                batch: List[ServeSession] = []
                attempt = 0
                next_retry: Optional[float] = None
                for i, (due, att, b) in enumerate(self._retry_batches):
                    if due <= now:
                        batch, attempt = b, att
                        del self._retry_batches[i]
                        break
                    next_retry = (
                        due if next_retry is None else min(next_retry, due)
                    )
                if not batch:
                    batch = self._pick_batch()
                    for sess in batch:
                        sess.state = "finalizing"  # reaper hands-off
                if not batch:
                    timeout = None
                    if self._ready:
                        oldest = min(
                            self._sessions[sid].quorum_at
                            for sid in self._ready
                        )
                        timeout = max(
                            0.005,
                            self.policy.wait_budget(
                                time.monotonic() - oldest
                            ),
                        )
                    if next_retry is not None:
                        dt = max(0.005, next_retry - now)
                        timeout = dt if timeout is None else min(timeout, dt)
                    self._ready_cv.wait(timeout=timeout)
                    continue
            self._finalize_batch(batch, attempt)

    def _finalize_batch(self, batch: List[ServeSession], attempt: int = 0) -> None:
        t0 = time.monotonic()
        config = batch[0]._config
        streams = []
        for sess in batch:
            if attempt == 0:
                metrics.record_phase("coalesce", t0 - sess.quorum_at)
            streams.extend(sess._streams)
        if attempt == 0:
            metrics.batch_histogram().observe(len(streams))
        plan = faults.active()
        bisect0 = metrics.rlc_bisect_count()
        batch_key = batch[0].session_id
        try:
            if plan and plan.fire("finalize_exc", (batch_key, attempt)):
                for sess in batch:
                    sess.faults.append("finalize_exc")
                raise faults.InjectedFinalizeError(
                    f"injected finalize failure (batch {batch_key}, "
                    f"attempt {attempt})"
                )
            errors = finalize_streams(streams, config)
        except Exception as e:
            # a raise here is infrastructure (protocol verdicts come
            # back in `errors`, isolated per session): retry with
            # jittered backoff — safe, finalize is pure over the
            # staged public messages until adoption, and an
            # already-finalized stream replays its stored verdict. The
            # batch is REQUEUED with a not-before, never slept out on
            # this (sole) launcher thread.
            if attempt >= self.retries:
                t1 = time.monotonic()
                for sess in batch:
                    for st in sess._streams:
                        st.close(e)
                    self._finish(sess, e, t1)
                return
            metrics.retries_counter().inc(stage="finalize")
            backoff = self.backoff_s * (2 ** attempt) * (1.0 + random.random())
            with self._lock:
                self._retry_batches.append(
                    (time.monotonic() + backoff, attempt + 1, batch)
                )
                self._ready_cv.notify()
            return
        t1 = time.monotonic()
        pos = 0
        for sess in batch:
            n = len(sess._streams)
            errs = [e for e in errors[pos : pos + n] if e is not None]
            pos += n
            metrics.record_phase("finalize", t1 - t0)
            self._finish(sess, errs[0] if errs else None, t1)
        # bisection-storm accounting (ROADMAP 5b): bisections in this
        # launch are the attributable cost of tampered traffic — honest
        # transcripts bisect zero times — so charge them to the blamed
        # sessions' committees; over-budget committees are shed at
        # admission until their window rolls
        delta = metrics.rlc_bisect_count() - bisect0
        if delta > 0 and self.guard.enabled():
            blamed = [s for s in batch if s.blame]
            if blamed:
                share = -(-delta // len(blamed))  # ceil-split
                for s in blamed:
                    self.guard.charge(s.committee_id, share)

    def _finish(
        self,
        sess: ServeSession,
        error: Optional[Exception],
        now: float,
        state: Optional[str] = None,
    ) -> None:
        """Move a session to its terminal state (exactly once: callers
        may race, the first transition wins) and release every resource
        it held — committee busy flag, stream references, inflight
        accounting."""
        with self._lock:
            if sess.state in TERMINAL:
                return
            sess.state = state or ("done" if error is None else "aborted")
            sess.finalized_at = now
            sess._streams = []
            sess._pending = []
            sess._wire_msgs = []
            if error is not None:
                sess.blame = isinstance(error, FsDkrError)
                sess.error = f"{type(error).__name__}: {error}"
            com = self._committees.get(sess.committee_id)
            if com is not None:
                # free the slot ONLY if this session holds it: a session
                # settled while still queued never acquired it, and the
                # current holder must keep its exclusivity
                if com.busy == sess.session_id:
                    com.busy = None
                    self._work_cv.notify()
                if sess.state == "done":
                    com.epochs += 1
            self._inflight -= 1
            self.sessions_done += sess.state == "done"
            self.sessions_aborted += sess.state == "aborted"
            self.sessions_timed_out += sess.state == "timed_out"
            metrics.inflight_gauge().set(self._inflight)
            if sess.state != "done" and sess.epoch is not None:
                # a FAILED epoch must stay retryable: drop the dedupe
                # entry so the client's next submit(cid, epoch) creates
                # a fresh session (done sessions keep deduping — that
                # refresh happened; handing it back is the contract)
                key = (sess.committee_id, sess.epoch)
                if self._epoch_index.get(key) == sess.session_id:
                    del self._epoch_index[key]
            # retire into the bounded history (memory stays O(history))
            self._sessions.pop(sess.session_id, None)
            self._finished[sess.session_id] = sess
            self._trim_history_locked()
            if self._inflight == 0:
                self._idle_cv.notify_all()
            final_state = sess.state
            self._recent_totals.append(now - sess.submitted_at)
        self._jappend_safe(
            {
                "t": "terminal",
                "sid": sess.session_id,
                "cid": sess.committee_id,
                "epoch": sess.epoch,
                "state": final_state,
                "blame": sess.blame,
                "error": sess.error,
            }
        )
        if self.keystore is not None:
            # terminal: the session's new dks are no longer re-derivable
            # material, they are either adopted or dead — drop them
            self.keystore.drop_session(sess.committee_id, sess.session_id)
        metrics.record_outcome(final_state, now - sess.submitted_at)
        # the committee's eks just rotated (or the session died): refresh
        # the SLO-derived pool targets against the live key state and
        # wake the producer — collect's kick has often drained by now
        if final_state == "done":
            self.planner.retarget(sess.committee_id)
            precompute.kick()
        # a terminal state also releases any wait_broadcasts() waiter
        sess._dist_evt.set()
        sess._done_evt.set()

    def _trim_history_locked(self) -> None:
        """Caller holds self._lock: evict finished sessions past the
        bounded history, dropping each evicted session's idempotency
        entry ONLY if it still maps to that session — a failed
        predecessor may have been superseded by a live retry session
        whose mapping must survive."""
        while len(self._finished) > self._history:
            _sid, old = self._finished.popitem(last=False)
            if old.epoch is not None:
                key = (old.committee_id, old.epoch)
                if self._epoch_index.get(key) == old.session_id:
                    del self._epoch_index[key]

    # -- recovery surface (ISSUE 12; driven by serving.recovery) --------
    def has_committee(self, committee_id) -> bool:
        with self._lock:
            return committee_id in self._committees

    def committee_size(self, committee_id) -> int:
        with self._lock:
            com = self._committees.get(committee_id)
            return len(com.keys) if com is not None else 0

    def reserve_session_ids(self, max_seen: int) -> None:
        """Never re-issue a session id a journal already used: a
        same-directory restart appends new records to the log the NEXT
        recovery reads, and colliding sids would merge two logical
        sessions in replay."""
        with self._lock:
            self._next_id = max(self._next_id, int(max_seen))

    def restore_terminal(
        self,
        committee_id,
        epoch: Optional[int],
        state: str,
        blame: bool,
        error: Optional[str],
        rejournal: bool = True,
    ) -> int:
        """Replay a journaled terminal verdict verbatim — no recompute,
        no adoption, no outcome metrics (the work happened in a prior
        incarnation; `fsdkr_journal_replayed` counts it instead). Done
        epochs re-enter the idempotency index so `submit(cid, epoch=N)`
        keeps deduping across the restart. `rejournal=False` skips the
        self-containment copy — recovery passes it when replaying the
        service's OWN journal directory, where the record already lives
        (re-journaling there would double the terminal set on every
        restart)."""
        if state not in TERMINAL:
            raise ValueError(f"not a terminal state: {state!r}")
        with self._lock:
            self._next_id += 1
            sess = ServeSession(
                session_id=self._next_id,
                committee_id=committee_id,
                state=state,
                epoch=epoch,
            )
            now = time.monotonic()
            sess.submitted_at = sess.finalized_at = now
            sess.blame = bool(blame)
            sess.error = error
            sess._done_evt.set()
            if state == "done":
                com = self._committees.get(committee_id)
                if com is not None:
                    com.epochs += 1
                if epoch is not None:
                    self._epoch_index[(committee_id, epoch)] = sess.session_id
            self.sessions_replayed += 1
            self._finished[sess.session_id] = sess
            self._trim_history_locked()
        # re-journal into THIS incarnation's log (when it is a
        # DIFFERENT directory) so the chain stays self-contained: a
        # second death recovers from this journal alone, without
        # walking predecessors
        if rejournal:
            self._jappend_safe(
                {
                    "t": "terminal",
                    "sid": sess.session_id,
                    "cid": committee_id,
                    "epoch": epoch,
                    "state": state,
                    "blame": bool(blame),
                    "error": error,
                    "replayed": True,
                }
            )
        return sess.session_id

    def _supersede_journaled(
        self, origin_sid: Optional[int], committee_id, epoch, new_sid: int
    ) -> None:
        """Close a journaled predecessor session's log entry once its
        work has been taken over under a new sid. Without this, a
        SECOND recovery of the same directory would see the origin sid
        still in-flight (its keystore dks possibly intact) and re-run
        it against already-rotated committee keys — a wrong verdict
        waiting to happen. The origin's dks are dropped with it."""
        if origin_sid is None:
            return
        self._jappend_safe(
            {
                "t": "terminal",
                "sid": origin_sid,
                "cid": committee_id,
                "epoch": epoch,
                "state": "aborted",
                "blame": False,
                "error": f"superseded by recovery into session {new_sid}",
                "replayed": True,
            }
        )
        if self.keystore is not None:
            self.keystore.drop_session(committee_id, origin_sid)

    def finish_unrecoverable(
        self,
        committee_id,
        epoch: Optional[int],
        error: Exception,
        origin_sid: Optional[int] = None,
    ) -> int:
        """A journaled in-flight session whose secret state cannot be
        re-derived: admit it and settle it `aborted` WITHOUT blame in
        one stroke — the error is not an FsDkrError, so the abort reads
        transient and the epoch becomes resubmittable (the `_finish`
        path drops the idempotency entry for non-done epochs). Never a
        fabricated verdict."""
        with self._lock:
            if committee_id not in self._committees:
                raise KeyError(f"committee {committee_id!r} not admitted")
            self._next_id += 1
            sess = ServeSession(
                session_id=self._next_id,
                committee_id=committee_id,
                epoch=epoch,
                submitted_at=time.monotonic(),
            )
            sess.state = "collecting"
            if epoch is not None:
                self._epoch_index[(committee_id, epoch)] = sess.session_id
            self._sessions[sess.session_id] = sess
            self._inflight += 1
            metrics.inflight_gauge().set(self._inflight)
        # WAL OUTSIDE the service lock (journal fsyncs under its own
        # lock); best-effort: this whole path is already degraded
        # durability, and one journal IO failure here must not abort
        # the caller's replay loop (a lost record just means the next
        # recovery settles the origin session again). `admitted` still
        # precedes the supersede/_finish terminals below.
        self._jappend_safe(
            {
                "t": "admitted",
                "sid": sess.session_id,
                "cid": committee_id,
                "epoch": epoch,
            }
        )
        self._supersede_journaled(
            origin_sid, committee_id, epoch, sess.session_id
        )
        self._finish(sess, error, time.monotonic())
        return sess.session_id

    def resume_session(
        self,
        committee_id,
        epoch: Optional[int],
        dks: Sequence,
        expected: Sequence[int],
        broadcasts: Sequence[Tuple[int, str]],
        origin_sid: Optional[int] = None,
    ) -> int:
        """Resume a journaled in-flight session: fresh StreamingCollect
        collectors from the committee's live LocalKeys + the keystore's
        re-derived dks, the journaled accepted broadcasts re-offered in
        acceptance order through the SAME offer path live traffic uses,
        then back into the ordinary lifecycle (launcher finalize at
        quorum, reaper deadline otherwise). Verdict + blame are
        bit-identical to the uninterrupted run by the shared-helper
        equivalence (tests/test_recovery.py)."""
        from ..protocol.serialization import refresh_message_from_json

        with self._lock:
            com = self._committees.get(committee_id)
            if com is None:
                raise KeyError(f"committee {committee_id!r} not admitted")
            if com.busy is not None:
                raise RuntimeError(
                    f"committee {committee_id!r} busy during recovery"
                )
            self._next_id += 1
            sess = ServeSession(
                session_id=self._next_id,
                committee_id=committee_id,
                epoch=epoch,
            )
            now = time.monotonic()
            sess.submitted_at = sess.started_at = now
            if self.deadline_s > 0:
                sess.deadline = now + self.deadline_s
            sess.state = "collecting"
            sess._config = com.config
            if epoch is not None:
                self._epoch_index[(committee_id, epoch)] = sess.session_id
            self._sessions[sess.session_id] = sess
            self._inflight += 1
            metrics.inflight_gauge().set(self._inflight)
            com.busy = sess.session_id
            keys = com.keys
        # from here on the session owns the committee's busy slot and
        # the inflight count: ANY failure must settle it through
        # _finish (which releases both) — raising out of this method
        # would leak the slot and wedge the committee forever. The
        # admission WAL append happens here, OUTSIDE the service lock
        # (journal fsyncs under its own lock) and inside the
        # settle-on-failure region; `admitted` still precedes
        # `collecting` because both moved with it, in order.
        streams = []
        try:
            self._jappend(
                {
                    "t": "admitted",
                    "sid": sess.session_id,
                    "cid": committee_id,
                    "epoch": epoch,
                }
            )
            self._jappend(
                {
                    "t": "collecting",
                    "sid": sess.session_id,
                    "expected": list(expected),
                }
            )
            self._supersede_journaled(
                origin_sid, committee_id, epoch, sess.session_id
            )
            self._deposit_dks(sess, dks)
            streams = [
                RefreshMessage.collect_stream(
                    k, dk, expected, (), sess._config
                )
                for k, dk in zip(keys, dks)
            ]
            for sender, wire in broadcasts:
                msg = refresh_message_from_json(wire)
                self._offer_all(sess, streams, msg, wire=wire)
        except Exception as e:
            for st in streams:
                st.close(e)
            self._finish(sess, e, time.monotonic())
            return sess.session_id
        timeout_now = False
        with self._lock:
            if sess.state in TERMINAL:
                # the deadline fired while the replay offers ran
                for st in streams:
                    st.close(RuntimeError("session settled during recovery"))
                return sess.session_id
            sess._streams = streams
            sess.quorum_at = time.monotonic()
            if all(st.ready for st in streams):
                sess.state = "ready"
                self._ready.append(sess.session_id)
                self._ready_cv.notify()
            elif sess.deadline:
                self._reap_cv.notify()
            else:
                # short of quorum with no deadline: the journal holds
                # everything that will ever arrive — settle now, naming
                # the missing senders, instead of wedging
                timeout_now = True
        if timeout_now:
            self._timeout_session(sess)
        return sess.session_id

    # -- FSDKR_SERVE=0: the single-shot arm -----------------------------
    def _run_single_shot(self, sess: ServeSession) -> None:
        """Today's barrier API, synchronously: distribute_batch + fused
        barrier collect_sessions for every party, no streaming and no
        coalescing. Keeps the lifecycle/metrics surface so A/B runs
        compare like for like."""
        com = self._committees[sess.committee_id]
        # same one-session-per-committee rule as the scheduler: a
        # concurrent synchronous submit would race the key mutation
        with self._lock:
            if com.busy is not None:
                # un-admit the session before refusing, so the inflight
                # accounting stays exact
                self._inflight -= 1
                self._sessions.pop(sess.session_id, None)
                if sess.epoch is not None:
                    self._epoch_index.pop(
                        (sess.committee_id, sess.epoch), None
                    )
                metrics.inflight_gauge().set(self._inflight)
                if self._inflight == 0:
                    self._idle_cv.notify_all()
                raise RuntimeError(
                    "committee busy: the single-shot arm serializes "
                    "sessions per committee in the caller"
                )
            com.busy = sess.session_id
        keys, config = com.keys, com.config
        now = time.monotonic()
        sess.started_at = now
        if not self._advance(sess, "distributing"):
            # the reaper settled the session before we started (its
            # _finish freed the slot; we re-acquired it above)
            with self._lock:
                if com.busy == sess.session_id:
                    com.busy = None
                    self._work_cv.notify()
            return
        error: Optional[Exception] = None
        try:
            with precompute.owner_scope(serve_owner(sess.committee_id)):
                results = RefreshMessage.distribute_batch(
                    [(k.i, k) for k in keys], len(keys), config
                )
            msgs = [m for m, _ in results]
            if not self._advance(sess, "collecting"):
                with self._lock:
                    if com.busy == sess.session_id:
                        com.busy = None
                        self._work_cv.notify()
                return  # reaped mid-run: never adopt for a settled session
            errs = RefreshMessage.collect_sessions(
                [(msgs, k, results[idx][1], ()) for idx, k in enumerate(keys)],
                config,
            )
            error = next((e for e in errs if e is not None), None)
        except Exception as e:
            error = e
        sess.quorum_at = time.monotonic()
        self._finish(sess, error, time.monotonic())

    # -- introspection --------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            # active sessions only: the scan is bounded by inflight, not
            # by the lifetime session count
            states: Dict[str, int] = {}
            for s in self._sessions.values():
                states[s.state] = states.get(s.state, 0) + 1
            states["done"] = self.sessions_done
            states["aborted"] = self.sessions_aborted
            states["timed_out"] = self.sessions_timed_out
            return {
                "committees": len(self._committees),
                "inflight": self._inflight,
                "queued": len(self._queue),
                "ready": len(self._ready),
                "sessions_done": self.sessions_done,
                "sessions_aborted": self.sessions_aborted,
                "sessions_timed_out": self.sessions_timed_out,
                "sessions_rejected": self.sessions_rejected,
                "sessions_replayed": self.sessions_replayed,
                "workers_respawned": self.workers_respawned,
                "states": states,
            }

    def journal_stats(self) -> Optional[dict]:
        return self.journal.stats() if self.journal is not None else None
