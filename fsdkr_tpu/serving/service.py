"""RefreshService: the long-running multi-committee serving loop
(ISSUE 9, ROADMAP open item 1).

fs-dkr's refresh is ONE broadcast round, so served throughput is a
scheduling problem: keep the verify/prove engines saturated while many
committees cycle through admit -> distribute -> collect. This service
composes the pieces the engine rounds built — `distribute_batch`'s fused
prover columns, the precompute pools + background producer, streaming
collect (`protocol.streaming`), and the fused quorum-time finalize — into
a scheduler:

- `admit(committee_id, keys, ...)` registers a committee and hands its
  SLO to the CapacityPlanner (pool depth targets under the committee's
  serving owner tag).
- `submit(committee_id)` enqueues one refresh session. The admission
  queue holds PUBLIC metadata only (ids, timestamps); key material stays
  in the per-committee table and is touched only by the protocol calls.
- Worker threads run the prover side (`distribute_batch` under the
  committee's precompute owner scope) and feed the broadcast messages
  into per-party `StreamingCollect` sessions — eager per-message
  verification happens here, spread over the arrival window.
- A launcher thread coalesces quorum-ready sessions into fused
  `finalize_streams` launches sized by the BatchPolicy (size-or-linger,
  mesh-aware), then rotates committee state and retargets the planner
  (the eks just rotated, so the pool targets must follow).

Lifecycle per session: admitted -> pooled (queued) -> distributing ->
collecting -> finalizing -> done | aborted, each transition stamped and
exported through the `fsdkr_serving_*` metrics (serving.metrics).

`FSDKR_SERVE=0` turns the scheduler off: `submit` runs the session
synchronously through today's single-shot barrier API
(`distribute_batch` + `collect_sessions`) with no streaming, batching,
or service threads — the A/B arm pinning that the serving layer adds
scheduling, not semantics.

Concurrency rules: at most one in-flight session per committee (a
refresh mutates the committee's LocalKeys; sessions for one committee
serialize through the busy flag while other committees proceed), and
`offer`/`finalize` for one streaming session never race (offers happen
on the worker before the session is published to the ready list; the
launcher finalizes only published sessions).
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from .. import precompute
from ..config import ProtocolConfig, DEFAULT_CONFIG
from ..protocol.refresh import RefreshMessage
from ..protocol.streaming import finalize_streams
from . import metrics
from .planner import SLO, CapacityPlanner, serve_owner
from .policy import BatchPolicy

__all__ = ["RefreshService", "ServeSession", "enabled"]


def enabled() -> bool:
    """FSDKR_SERVE gates the scheduler (default on). =0 makes submit()
    a synchronous single-shot barrier refresh — today's API, unchanged."""
    return os.environ.get("FSDKR_SERVE", "1").lower() not in (
        "0", "off", "false", "no",
    )


def _device_count() -> int:
    """Device count for the default BatchPolicy's mesh-aware batch
    alignment; 1 (alignment off) when JAX is unavailable or still
    uninitialized-fast-path. The fused finalize launches row-shard over
    all local devices, so the coalescer sizes batches to divide them."""
    try:
        import jax

        return max(1, jax.local_device_count())
    except Exception:
        return 1


def _shuffle_arrivals() -> bool:
    """FSDKR_SERVE_SHUFFLE (default on): feed each session's broadcast
    messages to the streaming collectors in a session-seeded random
    order, exercising the arrival-order independence the equivalence
    tests pin. =0 feeds canonical order (debugging)."""
    return os.environ.get("FSDKR_SERVE_SHUFFLE", "1").lower() not in (
        "0", "off", "false", "no",
    )


@dataclass
class ServeSession:
    """Public per-session record. Queue/state fields are broadcast-safe
    metadata; the streaming collectors (which hold broadcast messages
    and verdicts) hang off the internal `_streams` and never enter the
    admission queue."""

    session_id: int
    committee_id: object
    state: str = "admitted"
    submitted_at: float = 0.0
    started_at: float = 0.0
    quorum_at: float = 0.0
    finalized_at: float = 0.0
    error: Optional[str] = None
    _streams: list = field(default_factory=list, repr=False)
    _config: Optional[ProtocolConfig] = field(default=None, repr=False)
    _done_evt: threading.Event = field(
        default_factory=threading.Event, repr=False
    )


@dataclass
class _Committee:
    keys: list
    config: ProtocolConfig
    slo: SLO
    busy: bool = False
    epochs: int = 0


class RefreshService:
    """See module docstring. Construct, `admit` committees, `start()`,
    then `submit`/`wait`/`drain`; `stop()` joins the threads."""

    def __init__(
        self,
        policy: Optional[BatchPolicy] = None,
        planner: Optional[CapacityPlanner] = None,
        workers: Optional[int] = None,
    ):
        self.policy = policy or BatchPolicy(devices=_device_count())
        self.planner = planner or CapacityPlanner()
        if workers is None:
            try:
                workers = int(os.environ.get("FSDKR_SERVE_WORKERS", "1"))
            except ValueError:
                workers = 1
        self.workers = max(1, workers)
        self._committees: Dict[object, _Committee] = {}
        # ACTIVE sessions only; finished ones move to the bounded
        # history below so a long-running service cannot grow without
        # bound (and stats() never scans more than inflight + history)
        self._sessions: Dict[int, ServeSession] = {}
        self._finished: "OrderedDict[int, ServeSession]" = OrderedDict()
        try:
            self._history = max(
                1, int(os.environ.get("FSDKR_SERVE_HISTORY", "65536"))
            )
        except ValueError:
            self._history = 65536
        self._queue: deque = deque()  # session ids, FIFO (public metadata)
        self._ready: List[int] = []  # quorum-ready session ids
        self._lock = threading.Lock()
        self._work_cv = threading.Condition(self._lock)
        self._ready_cv = threading.Condition(self._lock)
        self._next_id = 0
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._inflight = 0
        self.sessions_done = 0
        self.sessions_aborted = 0

    # -- committee membership -------------------------------------------
    def admit(
        self,
        committee_id,
        keys: Sequence,
        config: ProtocolConfig = DEFAULT_CONFIG,
        slo: SLO = SLO(),
    ) -> None:
        """Register a committee (its parties' LocalKeys, in index order)
        and install its SLO-derived pool targets."""
        with self._lock:
            if committee_id in self._committees:
                raise ValueError(f"committee {committee_id!r} already admitted")
            self._committees[committee_id] = _Committee(
                keys=list(keys), config=config, slo=slo
            )
            metrics.committees_gauge().set(len(self._committees))
        self.planner.register(committee_id, keys[0], len(keys), config, slo)

    def evict(self, committee_id) -> None:
        """Remove a committee; its pool targets are invalidated and the
        pooled single-use secrets wiped now (churn discipline)."""
        with self._lock:
            com = self._committees.pop(committee_id, None)
            metrics.committees_gauge().set(len(self._committees))
        if com is not None:
            self.planner.invalidate(committee_id)

    # -- session intake -------------------------------------------------
    def submit(self, committee_id) -> int:
        """Enqueue one refresh session for the committee; returns the
        session id. With FSDKR_SERVE=0 the session runs synchronously
        (single-shot barrier semantics) before this returns."""
        now = time.monotonic()
        with self._lock:
            if committee_id not in self._committees:
                raise KeyError(f"committee {committee_id!r} not admitted")
            self._next_id += 1
            sess = ServeSession(
                session_id=self._next_id,
                committee_id=committee_id,
                submitted_at=now,
            )
            self._sessions[sess.session_id] = sess
            self._inflight += 1
            metrics.inflight_gauge().set(self._inflight)
            if enabled():
                sess.state = "pooled"
                self._queue.append(sess.session_id)
                metrics.queue_gauge().set(len(self._queue))
                self._work_cv.notify()
                return sess.session_id
        # FSDKR_SERVE=0: today's single-shot path, inline
        self._run_single_shot(sess)
        return sess.session_id

    def wait(self, session_id: int, timeout: Optional[float] = None) -> ServeSession:
        with self._lock:
            sess = self._sessions.get(session_id) or self._finished.get(
                session_id
            )
        if sess is None:
            raise KeyError(
                f"session {session_id} unknown (finished sessions are "
                f"retained up to FSDKR_SERVE_HISTORY={self._history})"
            )
        sess._done_evt.wait(timeout)
        return sess

    def drain(self, timeout: float = 60.0) -> bool:
        """Block until every submitted session finished (True) or the
        timeout elapsed (False)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight == 0:
                    return True
            time.sleep(0.01)
        with self._lock:
            return self._inflight == 0

    # -- service threads ------------------------------------------------
    def start(self) -> None:
        if self._threads:
            return
        self._stop.clear()
        for w in range(self.workers):
            t = threading.Thread(
                target=self._worker_loop, name=f"fsdkr-serve-worker-{w}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        t = threading.Thread(
            target=self._launcher_loop, name="fsdkr-serve-launcher", daemon=True
        )
        t.start()
        self._threads.append(t)

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        with self._lock:
            self._work_cv.notify_all()
            self._ready_cv.notify_all()
        for t in self._threads:
            t.join(timeout=timeout)
        self._threads.clear()

    # -- internals: prover/stream side ----------------------------------
    def _pop_work(self) -> Optional[ServeSession]:
        """Pop the first queued session whose committee is idle (FIFO
        per committee; other committees' sessions overtake a busy one)."""
        for idx, sid in enumerate(self._queue):
            sess = self._sessions[sid]
            com = self._committees.get(sess.committee_id)
            if com is None:
                # evicted mid-queue: abort below, outside the scan
                del self._queue[idx]
                return sess
            if not com.busy:
                com.busy = True
                del self._queue[idx]
                return sess
        return None

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                sess = self._pop_work()
                if sess is None:
                    self._work_cv.wait(timeout=0.05)
                    continue
                metrics.queue_gauge().set(len(self._queue))
                com = self._committees.get(sess.committee_id)
            if com is None:
                self._finish(sess, RuntimeError("committee evicted"), time.monotonic())
                continue
            try:
                self._run_session(sess, com)
            except Exception as e:  # distribute/offer failures
                with self._lock:
                    com.busy = False
                    self._work_cv.notify()
                self._finish(sess, e, time.monotonic())

    def _run_session(self, sess: ServeSession, com: _Committee) -> None:
        now = time.monotonic()
        metrics.record_phase("queue", now - sess.submitted_at)
        sess.started_at = now
        sess.state = "distributing"
        keys, config = com.keys, com.config
        new_n = len(keys)
        owner = serve_owner(sess.committee_id)
        with precompute.owner_scope(owner):
            results = RefreshMessage.distribute_batch(
                [(k.i, k) for k in keys], new_n, config
            )
        t_dist = time.monotonic()
        metrics.record_phase("distribute", t_dist - now)

        msgs = [m for m, _ in results]
        sess.state = "collecting"
        expected = [k.i for k in keys]
        streams = [
            RefreshMessage.collect_stream(k, results[idx][1], expected, (), config)
            for idx, k in enumerate(keys)
        ]
        # simulated broadcast arrival: each message lands at every
        # collector before the next arrives; order is session-seeded so
        # reordering is exercised continuously in production-like runs
        order = list(msgs)
        if _shuffle_arrivals():
            random.Random(sess.session_id).shuffle(order)
        for m in order:
            for st in streams:
                st.offer(m)
        t_stream = time.monotonic()
        metrics.record_phase("stream", t_stream - t_dist)

        sess._streams = streams
        sess._config = config
        sess.quorum_at = t_stream
        with self._lock:
            sess.state = "ready"
            self._ready.append(sess.session_id)
            self._ready_cv.notify()

    # -- internals: coalescing finalize side ----------------------------
    def _pick_batch(self) -> List[ServeSession]:
        """Under the lock: choose the batch to finalize now (oldest
        config group, policy-sized), or [] to keep lingering."""
        if not self._ready:
            return []
        groups: Dict[object, List[ServeSession]] = {}
        for sid in self._ready:
            s = self._sessions[sid]
            groups.setdefault(s._config, []).append(s)
        # oldest-first: the group containing the longest-waiting session
        group = min(groups.values(), key=lambda g: g[0].quorum_at)
        oldest_wait = time.monotonic() - group[0].quorum_at
        rows = 0
        if group[0]._streams:
            st0 = group[0]._streams[0]
            rows = len(st0.expected) * st0.new_n * len(group[0]._streams)
        count = self.policy.take(len(group), oldest_wait, rows)
        if count <= 0:
            return []
        batch = group[:count]
        taken = {s.session_id for s in batch}
        self._ready = [sid for sid in self._ready if sid not in taken]
        return batch

    def _launcher_loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                batch = self._pick_batch()
                if not batch:
                    self._ready_cv.wait(timeout=0.02)
                    continue
            self._finalize_batch(batch)

    def _finalize_batch(self, batch: List[ServeSession]) -> None:
        t0 = time.monotonic()
        config = batch[0]._config
        streams = []
        for sess in batch:
            sess.state = "finalizing"
            metrics.record_phase("coalesce", t0 - sess.quorum_at)
            streams.extend(sess._streams)
        metrics.batch_histogram().observe(len(streams))
        errors = finalize_streams(streams, config)
        t1 = time.monotonic()
        pos = 0
        for sess in batch:
            n = len(sess._streams)
            errs = [e for e in errors[pos : pos + n] if e is not None]
            pos += n
            metrics.record_phase("finalize", t1 - t0)
            self._finish(sess, errs[0] if errs else None, t1)

    def _finish(self, sess: ServeSession, error: Optional[Exception], now: float) -> None:
        sess.finalized_at = now
        sess._streams = []
        if error is None:
            sess.state = "done"
        else:
            sess.state = "aborted"
            sess.error = f"{type(error).__name__}: {error}"
        with self._lock:
            com = self._committees.get(sess.committee_id)
            if com is not None:
                com.busy = False
                if error is None:
                    com.epochs += 1
                self._work_cv.notify()
            self._inflight -= 1
            self.sessions_done += error is None
            self.sessions_aborted += error is not None
            metrics.inflight_gauge().set(self._inflight)
            # retire into the bounded history (memory stays O(history))
            self._sessions.pop(sess.session_id, None)
            self._finished[sess.session_id] = sess
            while len(self._finished) > self._history:
                self._finished.popitem(last=False)
        metrics.record_outcome(
            "done" if error is None else "aborted", now - sess.submitted_at
        )
        # the committee's eks just rotated (or the session died): refresh
        # the SLO-derived pool targets against the live key state and
        # wake the producer — collect's kick has often drained by now
        if error is None:
            self.planner.retarget(sess.committee_id)
            precompute.kick()
        sess._done_evt.set()

    # -- FSDKR_SERVE=0: the single-shot arm -----------------------------
    def _run_single_shot(self, sess: ServeSession) -> None:
        """Today's barrier API, synchronously: distribute_batch + fused
        barrier collect_sessions for every party, no streaming and no
        coalescing. Keeps the lifecycle/metrics surface so A/B runs
        compare like for like."""
        com = self._committees[sess.committee_id]
        # same one-session-per-committee rule as the scheduler: a
        # concurrent synchronous submit would race the key mutation
        with self._lock:
            if com.busy:
                # un-admit the session before refusing, so the inflight
                # accounting stays exact
                self._inflight -= 1
                self._sessions.pop(sess.session_id, None)
                metrics.inflight_gauge().set(self._inflight)
                raise RuntimeError(
                    "committee busy: the single-shot arm serializes "
                    "sessions per committee in the caller"
                )
            com.busy = True
        keys, config = com.keys, com.config
        now = time.monotonic()
        sess.started_at = now
        sess.state = "distributing"
        error: Optional[Exception] = None
        try:
            with precompute.owner_scope(serve_owner(sess.committee_id)):
                results = RefreshMessage.distribute_batch(
                    [(k.i, k) for k in keys], len(keys), config
                )
            msgs = [m for m, _ in results]
            sess.state = "collecting"
            errs = RefreshMessage.collect_sessions(
                [(msgs, k, results[idx][1], ()) for idx, k in enumerate(keys)],
                config,
            )
            error = next((e for e in errs if e is not None), None)
        except Exception as e:
            error = e
        sess.quorum_at = time.monotonic()
        self._finish(sess, error, time.monotonic())

    # -- introspection --------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            # active sessions only: the scan is bounded by inflight, not
            # by the lifetime session count
            states: Dict[str, int] = {}
            for s in self._sessions.values():
                states[s.state] = states.get(s.state, 0) + 1
            states["done"] = self.sessions_done
            states["aborted"] = self.sessions_aborted
            return {
                "committees": len(self._committees),
                "inflight": self._inflight,
                "queued": len(self._queue),
                "ready": len(self._ready),
                "sessions_done": self.sessions_done,
                "sessions_aborted": self.sessions_aborted,
                "states": states,
            }
