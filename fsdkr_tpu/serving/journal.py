"""Durable public-broadcast journal (ISSUE 12): an append-only
write-ahead log that makes serving sessions survive process death.

fs-dkr is proactive security — a refresh that fails to complete leaves
the fleet holding stale, compromisable shares — so the refresh service
itself must be crash-durable. The journal records the PUBLIC facts of
every session as they happen, in exactly the wire encoding broadcasts
already use (`protocol.serialization`), so a fresh process (or a peer
shard adopting a dead shard's committees) can replay the log through
the ordinary `StreamingCollect.offer()`/finalize path and land on
bit-identical verdicts (`serving.recovery`).

## What is journaled — and what never is

Record types (one JSON object per record; `t` is the discriminator):

- ``committee`` — a committee admission: id, sizes, and the PUBLIC
  config parameters (bits, m_security, rounds, backend, hash, curve).
- ``admitted``  — a session entered the service: session id, committee
  id, optional idempotency epoch.
- ``collecting`` — the session's expected-sender set at the moment its
  streaming collectors were created.
- ``broadcast`` — one ACCEPTED broadcast message, serialized with
  `refresh_message_to_json` (broadcast-public by definition), in
  acceptance order. First-arrival-wins is preserved: the accepted copy
  is what was journaled, so a tampered-then-corrected arrival replays
  to the same blame verdict.
- ``terminal``  — the session's terminal state (done / aborted /
  timed_out), the blame flag, and the error string. Recovery replays a
  terminal verdict verbatim, never recomputes it.

Secrets — LocalKeys, new decryption keys, pool entries, CRT contexts —
are NEVER journaled (SECURITY.md "Journal discipline"). Recovery
re-derives secret state from the committee keystore
(`recovery.MemoryKeystore`); a session whose secrets cannot be
re-derived terminates ``aborted_transient`` (retryable), never with a
fabricated verdict.

## Framing, rotation, durability

Segments are ``wal-NNNNNN.seg`` files: an 12-byte header (magic +
version) followed by CRC-framed records — ``<u32 payload-len>
<u32 crc32(payload)> <payload>``. A new Journal NEVER appends to an
existing segment (a predecessor's tail may be torn; a fresh segment
keeps that tail exactly where replay expects it). Segments rotate at
``FSDKR_JOURNAL_SEGMENT_MB`` (default 8).

Torn-tail tolerance: a record truncated at the END of a segment — the
signature of a crash mid-write — is dropped and counted
(``fsdkr_journal_torn_tails``). Anything else that fails the frame
(bad magic, CRC mismatch, undecodable payload) is REAL corruption and
raises `JournalCorruption` naming the segment and byte offset: silent
repair of non-tail damage could drop accepted broadcasts, which is the
one thing the journal exists to prevent.

fsync policy — ``FSDKR_JOURNAL_SYNC``:

- ``always`` — fsync after every record (safest; slowest).
- ``batch``  — default: fsync every ``FSDKR_JOURNAL_BATCH`` records
  (32) and at rotation/close. A crash can lose at most one batch of
  un-synced tail records — all dropped as a torn/clean tail, never
  corrupted reads.
- ``off``    — buffered writes only (OS page cache; for benchmarks).

Chaos: the ``journal_torn_write`` fault site (`serving.faults`)
truncates the active segment mid-record — the frame header and a
payload prefix land on disk, then the segment rotates — simulating a
crash mid-write so the torn-tail replay path is exercised end to end.
"""

from __future__ import annotations

import json
import os
import pathlib
import struct
import threading
import zlib
from typing import Iterator, List, Optional, Tuple

__all__ = [
    "Journal",
    "JournalCorruption",
    "read_records",
    "SEGMENT_MAGIC",
    "SEGMENT_VERSION",
]

SEGMENT_MAGIC = b"FSDKRWAL"
SEGMENT_VERSION = 1
_HEADER = SEGMENT_MAGIC + struct.pack("<I", SEGMENT_VERSION)
_FRAME = struct.Struct("<II")  # payload length, crc32(payload)


class JournalCorruption(RuntimeError):
    """Non-tail journal damage: the segment and byte offset are named
    so the operator can quarantine the exact file — recovery must not
    guess past a record it cannot trust."""

    def __init__(self, segment: str, offset: int, detail: str):
        self.segment = segment
        self.offset = offset
        super().__init__(
            f"journal corruption in {segment} at offset {offset}: {detail}"
        )


def _counters():
    from ..telemetry import registry

    return {
        "records": registry.counter(
            "fsdkr_journal_records", "journal records appended"
        ),
        "bytes": registry.counter(
            "fsdkr_journal_bytes", "journal bytes appended (frames included)"
        ),
        "segments": registry.counter(
            "fsdkr_journal_segments", "journal segments opened"
        ),
        "fsyncs": registry.counter(
            "fsdkr_journal_fsyncs", "journal fsync calls"
        ),
        "replayed": registry.counter(
            "fsdkr_journal_replayed",
            "journal records consumed by recovery replay",
        ),
        "torn_tails": registry.counter(
            "fsdkr_journal_torn_tails",
            "truncated segment tails dropped during replay",
        ),
    }


def _env_sync() -> str:
    v = os.environ.get("FSDKR_JOURNAL_SYNC", "batch").lower()
    if v not in ("always", "batch", "off"):
        raise ValueError(
            f"FSDKR_JOURNAL_SYNC={v!r}: expected always|batch|off"
        )
    return v


class Journal:
    """One shard's append-only journal directory. Thread-safe: the
    serving workers, launcher, and reaper all append through one lock
    (records are small; the fsync policy, not the lock, is the cost)."""

    def __init__(
        self,
        directory,
        sync: Optional[str] = None,
        segment_bytes: Optional[int] = None,
        batch_records: Optional[int] = None,
    ):
        self.dir = pathlib.Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.sync_policy = sync if sync is not None else _env_sync()
        if self.sync_policy not in ("always", "batch", "off"):
            raise ValueError(f"bad sync policy {self.sync_policy!r}")
        if segment_bytes is None:
            mb = float(os.environ.get("FSDKR_JOURNAL_SEGMENT_MB", "8"))
            segment_bytes = max(4096, int(mb * (1 << 20)))
        self.segment_bytes = segment_bytes
        if batch_records is None:
            batch_records = max(
                1, int(os.environ.get("FSDKR_JOURNAL_BATCH", "32"))
            )
        self.batch_records = batch_records
        self._lock = threading.Lock()
        self._fh = None
        self._seg_index = self._next_segment_index()
        self._seg_written = 0
        self._unsynced = 0
        self._closed = False
        # per-instance accounting (the registry counters aggregate
        # across every journal in the process; stats() is THIS journal)
        self.records = 0
        self.bytes = 0
        self.segments = 0
        self.fsyncs = 0
        self._c = _counters()

    # -- segment management ---------------------------------------------
    def _next_segment_index(self) -> int:
        existing = self.segment_paths(self.dir)
        if not existing:
            return 1
        return int(existing[-1].stem.split("-")[1]) + 1

    @staticmethod
    def segment_paths(directory) -> List[pathlib.Path]:
        d = pathlib.Path(directory)
        if not d.is_dir():
            return []
        return sorted(d.glob("wal-*.seg"))

    def _open_segment(self) -> None:
        path = self.dir / f"wal-{self._seg_index:06d}.seg"
        self._seg_index += 1
        self._fh = open(path, "ab")
        self._fh.write(_HEADER)
        self._seg_written = len(_HEADER)
        self.segments += 1
        self._c["segments"].inc()

    def _rotate_locked(self) -> None:
        if self._fh is not None:
            self._sync_locked(force=self.sync_policy != "off")
            self._fh.close()
            self._fh = None

    def _sync_locked(self, force: bool = False) -> None:
        if self._fh is None:
            return
        self._fh.flush()
        if force or self.sync_policy == "always" or (
            self.sync_policy == "batch"
            and self._unsynced >= self.batch_records
        ):
            os.fsync(self._fh.fileno())
            self._unsynced = 0
            self.fsyncs += 1
            self._c["fsyncs"].inc()

    # -- appending ------------------------------------------------------
    def append(self, rec: dict) -> None:
        """Append one record (a JSON-serializable dict of PUBLIC data).
        Raises on IO errors — a journal that silently drops records is
        worse than none (the serving retry path treats the raise as a
        transient failure)."""
        payload = json.dumps(
            rec, sort_keys=True, separators=(",", ":")
        ).encode()
        frame = _FRAME.pack(len(payload), zlib.crc32(payload)) + payload
        with self._lock:
            if self._closed:
                raise RuntimeError("journal is closed")
            if self._fh is None or self._seg_written >= self.segment_bytes:
                self._rotate_locked()  # fsdkr-lint: allow(lock-blocking-call) WAL fsync under the journal's own lock IS the ordering domain
                self._open_segment()
            torn = self._torn_write_injected()
            if torn:
                # crash-mid-write simulation: a frame prefix lands on
                # disk, the record is LOST (that is the point — replay
                # must drop it as a torn tail), and writes continue in
                # a fresh segment
                cut = max(1, len(frame) - max(4, len(payload) // 2))
                self._fh.write(frame[:cut])
                self._sync_locked(force=self.sync_policy != "off")  # fsdkr-lint: allow(lock-blocking-call) torn-write injection: crash simulation syncs by design
                self._rotate_locked()  # fsdkr-lint: allow(lock-blocking-call) same injected-crash path
                self._open_segment()
                return
            self._fh.write(frame)
            self._seg_written += len(frame)
            self._unsynced += 1
            self.records += 1
            self.bytes += len(frame)
            self._c["records"].inc()
            self._c["bytes"].inc(len(frame))
            self._sync_locked()  # fsdkr-lint: allow(lock-blocking-call) the fsync policy, not the lock, is the cost: callers must never hold service locks here (SECURITY.md journal discipline)

    @staticmethod
    def _torn_write_injected() -> bool:
        from . import faults

        plan = faults.active()
        return plan is not None and plan.fire_seq("journal_torn_write")

    def sync(self) -> None:
        with self._lock:
            self._sync_locked(force=self.sync_policy != "off")  # fsdkr-lint: allow(lock-blocking-call) explicit sync(): fsync is the point

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._rotate_locked()  # fsdkr-lint: allow(lock-blocking-call) close(): final fsync+close under the journal lock by design
            self._closed = True

    def stats(self) -> dict:
        with self._lock:
            return {
                "dir": str(self.dir),
                "sync": self.sync_policy,
                "records": self.records,
                "bytes": self.bytes,
                "segments": self.segments,
                "fsyncs": self.fsyncs,
                "segment_bytes": self.segment_bytes,
            }


# ---------------------------------------------------------------------------
# replay


def _iter_segment(
    path: pathlib.Path, is_last_segment: bool
) -> Iterator[Tuple[dict, int]]:
    """Yield (record, offset) from one segment. A truncated record at
    the segment's END is a torn tail: dropped and counted (crashes and
    injected torn writes both leave exactly this shape, in any segment
    — rotation only ever follows a write, so a mid-directory segment
    can carry a torn tail too). Everything else raises
    JournalCorruption. `is_last_segment` is accepted for symmetry with
    callers that want stricter policies; the tail rule applies to every
    segment."""
    data = path.read_bytes()
    name = path.name
    if len(data) < len(_HEADER):
        if data and not _HEADER.startswith(data):
            raise JournalCorruption(name, 0, "bad segment magic")
        # empty/truncated header: a crash immediately after rotation
        _counters()["torn_tails"].inc()
        return
    if data[: len(_HEADER)] != _HEADER:
        raise JournalCorruption(name, 0, "bad segment magic or version")
    off = len(_HEADER)
    while off < len(data):
        if off + _FRAME.size > len(data):
            _counters()["torn_tails"].inc()
            return  # torn frame header at EOF
        length, crc = _FRAME.unpack_from(data, off)
        start = off + _FRAME.size
        end = start + length
        if end > len(data):
            _counters()["torn_tails"].inc()
            return  # torn payload at EOF
        payload = data[start:end]
        if zlib.crc32(payload) != crc:
            raise JournalCorruption(name, off, "record CRC mismatch")
        try:
            rec = json.loads(payload)
        except ValueError:
            raise JournalCorruption(
                name, off, "record payload is not valid JSON"
            ) from None
        yield rec, off
        off = end


def read_records(directory) -> List[dict]:
    """Every surviving record across the directory's segments, in
    append order. A missing or empty directory is a clean no-op (a
    shard's very first boot has nothing to recover). Raises
    JournalCorruption on non-tail damage."""
    segs = Journal.segment_paths(directory)
    out: List[dict] = []
    for i, seg in enumerate(segs):
        for rec, _off in _iter_segment(seg, i == len(segs) - 1):
            out.append(rec)
    return out
