"""Refresh-as-a-service (ISSUE 9): the streaming multi-committee serving
loop — RefreshService scheduler (admission, per-session lifecycle,
coalesced fused finalize launches), SLO-driven capacity planning for the
precompute pools, batching policy, and the `fsdkr_serving_*` telemetry.

Layering rule (enforced by scripts/lint_imports.py): this package
orchestrates through `protocol`, `precompute`, `parallel.shard_kernels`,
`telemetry`, and `utils` only — never `proofs`, `backend`, `ops`,
`native`, or `core` internals. The cryptography stays behind the
protocol surface; serving is scheduling.

Gate: FSDKR_SERVE (default on). Fully off, `RefreshService.submit` runs
each session synchronously through the unchanged single-shot barrier
API (`distribute_batch` + `collect_sessions`).
"""

from .ingress import IngressClient, IngressServer  # noqa: F401
from .journal import Journal, JournalCorruption  # noqa: F401
from .planner import SLO, CapacityPlanner, serve_owner  # noqa: F401
from .policy import (  # noqa: F401
    BatchPolicy,
    BisectGuard,
    OverloadPolicy,
    PeerRateLimiter,
)
from .recovery import (  # noqa: F401
    MemoryKeystore,
    RecoverySecretsUnavailable,
    recover,
)
from .service import (  # noqa: F401
    RefreshService,
    ServeRejected,
    ServeSession,
    SessionTimeout,
    enabled,
)
from . import faults, journal, metrics, recovery  # noqa: F401

__all__ = [
    "SLO",
    "CapacityPlanner",
    "serve_owner",
    "BatchPolicy",
    "OverloadPolicy",
    "BisectGuard",
    "PeerRateLimiter",
    "IngressClient",
    "IngressServer",
    "RefreshService",
    "ServeSession",
    "ServeRejected",
    "SessionTimeout",
    "Journal",
    "JournalCorruption",
    "MemoryKeystore",
    "RecoverySecretsUnavailable",
    "recover",
    "enabled",
    "faults",
    "journal",
    "metrics",
    "recovery",
]
