"""Deterministic, seed-driven fault injection for the serving stack
(ISSUE 11, FSDKR_FAULTS).

The serving loop was proven only under perfectly healthy in-process
traffic. Before a network ingress or horizontal sharding can land, the
failure semantics need adversarial exercise: this module is the ONE
place chaos comes from — a parsed fault PLAN consulted by thin hooks at
named sites, so every injected fault is deliberate, reproducible, and
stamped into telemetry.

## Spec string

``FSDKR_FAULTS="seed=42,msg_tamper=0.05,worker_crash=0.02,..."`` —
comma-separated ``key=value`` pairs. Keys are either a SITE name with a
fire probability in [0, 1], a per-site total cap ``<site>_max=N``
(useful in tests to fire exactly once), or one of the scalar tuning
knobs (``seed``, ``delay_s``, ``squeeze_factor``). Unknown keys raise
at parse time — a typo must not silently disable a chaos run.

Sites (each hook passes a stable key; the decision is a pure function
of ``(seed, site, key)``, so a run with a fixed seed injects the same
faults at the same sessions every time, regardless of thread timing,
for every site whose key is schedule-independent):

- ``worker_crash``   — a serving worker thread dies at session start
  (keyed by session id + attempt; the service must respawn the worker
  and retry or abort only that session).
- ``finalize_exc``   — the fused finalize launch raises before running
  (keyed by batch + attempt; strictly BEFORE `finalize_streams`, so a
  retry replays a pure function over staged public messages).
- ``pool_dry``       — a precompute pool take is forced dry (keyed by a
  per-process call counter; the consumer falls back inline,
  bit-identically, and the dry is labeled cause=injected).
- ``msg_delay``      — a broadcast message arrives ``delay_s`` late
  (keyed by session id + sender).
- ``msg_drop``       — a broadcast message never arrives (same key);
  the session can only end via the deadline reaper, which names the
  missing senders.
- ``msg_dup``        — a broadcast message is delivered twice.
- ``msg_tamper``     — the delivered message is a tampered copy
  (the ``pdl_s1`` tamper family from tests/test_streaming.py); the
  honest copy follows as a duplicate (tampered-then-corrected), and
  first-arrival-wins means the session MUST abort with blame.
- ``mem_squeeze``    — the memory-plan budget is squeezed by
  ``squeeze_factor`` for one planning decision (keyed by a call
  counter; verification tiles harder but verdicts are budget-
  independent by the memplan contract).

Process-level sites (ISSUE 12; consulted by the shard supervisor /
crash-storm load generator and the journal, not by in-process hooks):

- ``shard_kill``     — SIGKILL a live RefreshService shard mid-window
  (keyed by the storm tick; the supervisor must detect the death,
  reassign the shard's committees to a peer, and replay its journal).
- ``journal_torn_write`` — truncate the active journal segment
  mid-record (keyed by a call counter): a frame header and payload
  prefix land on disk, exactly the shape a crash mid-write leaves, so
  the torn-tail replay path is exercised end to end.

Network sites (ISSUE 13; consulted by the asyncio ingress server,
`serving.ingress` — they act on CONNECTIONS and wire frames, never on
protocol state, so a network-chaos storm can only ever look like a
lossy network, not like a misbehaving verifier):

- ``conn_drop``      — abort the client's TCP connection right after a
  request frame arrives, before any response (keyed per connection +
  frame sequence; the client must reconnect and resubmit — the
  idempotent epoch submit dedupes).
- ``frame_truncate`` — write only a prefix of a response frame, then
  abort the connection (the torn-frame shape a crashed peer leaves;
  the client's CRC/length check must treat it as a dead connection).
- ``net_delay``      — hold a response for ``delay_s`` before writing
  it (keyed like conn_drop; exercises client timeouts and the
  server-side inflight-byte backpressure).
- ``net_dup``        — write the response frame twice (clients
  correlate by request id and must drop the duplicate).

## Zero cost when disabled

Without ``FSDKR_FAULTS`` (and without an explicit `configure()`),
`active()` returns None and every hook is one dict lookup. Hooks
outside the serving package (precompute/pools.py, backend/memplan.py)
go through ``sys.modules.get`` so they never even import this package
unless a chaos run already did (SECURITY.md "Fault-injection
discipline").

## Telemetry

Every fired fault increments ``fsdkr_fault_injected{site}`` and lands
in the flight recorder (kind="fault"), so a chaos postmortem shows
exactly which faults preceded a bad outcome. Fault keys are session
ids / sender indices / counters — never key material.
"""

from __future__ import annotations

import copy
import dataclasses
import hashlib
import os
import threading
from typing import Dict, Optional, Tuple

__all__ = [
    "SITES",
    "InjectedFault",
    "InjectedWorkerCrash",
    "InjectedFinalizeError",
    "FaultPlan",
    "active",
    "configure",
    "reset",
    "tamper_message",
]

SITES = (
    "worker_crash",
    "finalize_exc",
    "pool_dry",
    "msg_delay",
    "msg_drop",
    "msg_dup",
    "msg_tamper",
    "mem_squeeze",
    "shard_kill",
    "journal_torn_write",
    "conn_drop",
    "frame_truncate",
    "net_delay",
    "net_dup",
)

_SCALARS = ("seed", "delay_s", "squeeze_factor")


class InjectedFault(RuntimeError):
    """Base of every injected failure. Deliberately NOT an FsDkrError:
    injected faults are infrastructure failures (transient, retryable),
    never protocol verdicts — the service must never translate one into
    identifiable-abort blame."""


class InjectedWorkerCrash(InjectedFault):
    """Raised inside a serving worker to simulate the thread dying."""


class InjectedFinalizeError(InjectedFault):
    """Raised at the head of a fused finalize launch (transient)."""


def _counter():
    from ..telemetry import registry

    return registry.counter(
        "fsdkr_fault_injected",
        "faults injected by the FSDKR_FAULTS plan, by site",
        labelnames=("site",),
    )


class FaultPlan:
    """One parsed fault plan. Decisions are pure functions of
    (seed, site, key) via SHA-256, so they are reproducible across
    processes and independent of Python hash randomization."""

    def __init__(
        self,
        seed: int = 0,
        rates: Optional[Dict[str, float]] = None,
        caps: Optional[Dict[str, int]] = None,
        delay_s: float = 0.25,
        squeeze_factor: float = 0.25,
    ):
        self.seed = int(seed)
        self.rates = dict(rates or {})
        self.caps = dict(caps or {})
        self.delay_s = float(delay_s)
        self.squeeze_factor = min(1.0, max(0.01, float(squeeze_factor)))
        self._lock = threading.Lock()
        self._fired: Dict[str, int] = {}
        self._seq: Dict[str, int] = {}

    # -- construction ---------------------------------------------------
    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        seed, delay_s, squeeze = 0, 0.25, 0.25
        rates: Dict[str, float] = {}
        caps: Dict[str, int] = {}
        for part in spec.replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"FSDKR_FAULTS: bad entry {part!r}")
            k, v = (x.strip() for x in part.split("=", 1))
            if k == "seed":
                seed = int(v)
            elif k == "delay_s":
                delay_s = float(v)
            elif k == "squeeze_factor":
                squeeze = float(v)
            elif k in SITES:
                rates[k] = min(1.0, max(0.0, float(v)))
            elif k.endswith("_max") and k[:-4] in SITES:
                caps[k[:-4]] = int(v)
            else:
                raise ValueError(
                    f"FSDKR_FAULTS: unknown key {k!r} (sites: {SITES}, "
                    f"scalars: {_SCALARS}, caps: <site>_max)"
                )
        return cls(seed, rates, caps, delay_s, squeeze)

    def spec(self) -> str:
        """Canonical spec string (stamped into chaos reports)."""
        parts = [f"seed={self.seed}"]
        parts += [f"{s}={self.rates[s]}" for s in SITES if s in self.rates]
        parts += [f"{s}_max={self.caps[s]}" for s in SITES if s in self.caps]
        parts += [f"delay_s={self.delay_s}",
                  f"squeeze_factor={self.squeeze_factor}"]
        return ",".join(parts)

    # -- decisions ------------------------------------------------------
    def _roll(self, site: str, key: Tuple) -> bool:
        rate = self.rates.get(site, 0.0)
        if rate <= 0.0:
            return False
        h = hashlib.sha256(
            f"{self.seed}|{site}|{key!r}".encode()
        ).digest()
        return int.from_bytes(h[:8], "big") < rate * (1 << 64)

    def fire(self, site: str, key: Tuple = ()) -> bool:
        """Decide-and-record: True iff the plan injects `site` for this
        key (under the site's rate and its optional total cap). A True
        return is already stamped into telemetry + the flight
        recorder — the caller's only job is to act the fault out."""
        if not self._roll(site, key):
            return False
        cap = self.caps.get(site)
        with self._lock:
            n = self._fired.get(site, 0)
            if cap is not None and n >= cap:
                return False
            self._fired[site] = n + 1
        _counter().inc(site=site)
        try:
            from ..telemetry import flight

            flight.record("fault", site, key=repr(key)[:64])
        except Exception:
            pass
        return True

    def fire_seq(self, site: str) -> bool:
        """fire() keyed by a per-site process-wide call counter — for
        sites with no natural stable key (pool takes, memplan budget
        reads). Still seed-deterministic given the call order; the
        injected COUNT converges to rate x calls regardless."""
        with self._lock:
            k = self._seq[site] = self._seq.get(site, 0) + 1
        return self.fire(site, (k,))

    def squeeze_budget(self, budget: int) -> int:
        """mem_squeeze hook: one planning decision's bytes budget,
        possibly squeezed. The plan never raises a budget."""
        if self.fire_seq("mem_squeeze"):
            return max(1, int(budget * self.squeeze_factor))
        return budget

    def injected(self) -> Dict[str, int]:
        """Total fires per site so far (chaos-report accounting)."""
        with self._lock:
            return dict(self._fired)


def tamper_message(msg):
    """Tampered deep copy of a RefreshMessage — the ``pdl_s1`` family
    from tests/test_streaming.py (s1 of the first PDL proof bumped), a
    pure wire-level mutation of broadcast-public data. The session
    verifying it must abort with PDLwSlackProofError blame on this
    sender, streaming and barrier alike."""
    bad = copy.deepcopy(msg)
    bad.pdl_proof_vec[0] = dataclasses.replace(
        bad.pdl_proof_vec[0], s1=bad.pdl_proof_vec[0].s1 + 1
    )
    return bad


# ---------------------------------------------------------------------------
# module-level activation: env-driven (FSDKR_FAULTS) with an explicit
# programmatic override for tests and the chaos load generator

_OVERRIDE: Optional[FaultPlan] = None
_CACHED: Tuple[Optional[str], Optional[FaultPlan]] = (None, None)


def active() -> Optional[FaultPlan]:
    """The live fault plan, or None (the overwhelmingly common case:
    injection is inert without FSDKR_FAULTS or an explicit
    configure())."""
    if _OVERRIDE is not None:
        return _OVERRIDE
    spec = os.environ.get("FSDKR_FAULTS")
    if not spec:
        return None
    global _CACHED
    if _CACHED[0] != spec:
        _CACHED = (spec, FaultPlan.parse(spec))
    return _CACHED[1]


def configure(spec: str) -> FaultPlan:
    """Install a plan programmatically (wins over the env until
    reset()); returns it so callers can read `injected()` afterwards."""
    global _OVERRIDE
    _OVERRIDE = FaultPlan.parse(spec) if isinstance(spec, str) else spec
    return _OVERRIDE


def reset() -> None:
    global _OVERRIDE, _CACHED
    _OVERRIDE = None
    _CACHED = (None, None)
