"""Network ingress (ISSUE 13; ROADMAP 3a): an asyncio TCP server that
feeds a RefreshService over real sockets — the point where fs-dkr's
broadcast-channel assumption (`src/lib.rs:5-9` in the reference: one
message per party on a broadcast channel) finally meets a lossy,
adversarial network instead of an in-process loop.

## Wire protocol

Length-prefixed CRC-framed JSON, the journal's frame shape on a socket:

    <u32 payload-len, little-endian> <u32 crc32(payload)> <payload JSON>

Every request carries a client-chosen ``rid`` echoed in the response,
so a duplicated response (the ``net_dup`` fault, or a retransmitting
middlebox) is detectable and droppable. Request ops:

- ``submit``    ``{op, rid, cid, epoch}`` — admit one refresh session
  (idempotent per (committee, epoch), exactly like the in-process API).
  The response carries the session id and the session's broadcast set
  (the distribute outputs, wire-encoded): the CLIENT is the broadcast
  channel — it re-delivers each message as a ``broadcast`` frame, so
  every broadcast transits the network and a dropped frame is a real
  quorum gap. Large sets are returned as a sender list instead; the
  client pulls each message with ``fetch``.
- ``fetch``     ``{op, rid, sid, senders}`` — a subset of an external
  session's broadcast set (for sets too big to inline in ``submitted``).
- ``broadcast`` ``{op, rid, sid, wire}`` — deliver one broadcast into
  the session's collectors (`RefreshService.offer_external`: journaled
  iff accepted, first arrival wins, order-independent).
- ``wait``      ``{op, rid, sid, timeout}`` — block for the terminal
  verdict. A service-side timeout comes back as a TYPED error frame
  (``{"type": "error", "error": "timeout", ...}``) — never a closed
  connection (a closed connection means the NETWORK failed; a timeout
  is an answer).
- ``ping`` / ``stats`` — liveness and the ingress counter snapshot.

Responses: ``submitted`` / ``fetched`` / ``pending`` (the session is
alive but its distribute has not finished — retry the fetch; NOT the
same as ``unknown_session``, which means resubmit) / ``broadcast_ack``
/ ``terminal`` / ``rejected`` (admission shed — overload policy,
bisect guard, or the per-peer rate limiter; carries ``retry_after_s``)
/ ``redirect`` (this shard does not own the committee; carries the
peer port map so the client re-dials) / ``pong`` / ``stats`` /
``error``.

## Robustness (the point, not a bolt-on)

- **Backpressure, not queue growth**: every accepted frame charges a
  per-connection and a server-global inflight byte budget
  (``FSDKR_INGRESS_CONN_INFLIGHT_MB`` / ``FSDKR_INGRESS_INFLIGHT_MB``),
  released when its response has been written. Over budget, the server
  calls ``transport.pause_reading()`` — the kernel's TCP window closes
  and the SENDER stalls; nothing accumulates server-side
  (``fsdkr_ingress_paused_reads{scope}``).
- **Frame hygiene**: a length prefix over ``FSDKR_INGRESS_MAX_FRAME_MB``
  (oversize), a CRC mismatch, an undecodable payload, or an unknown op
  closes THAT connection (``fsdkr_ingress_frames_rejected{cause}``) and
  touches no other — one hostile peer cannot poison a sibling's stream.
- **Slow-loris**: connections idle past ``FSDKR_INGRESS_IDLE_S`` or
  whose peer stops reading our responses for ``FSDKR_INGRESS_WRITE_S``
  (write-buffer high-water sustained) are closed by the hygiene sweep.
- **Per-peer rate limiting** (`policy.PeerRateLimiter`,
  ``FSDKR_INGRESS_PEER_RPS``): charged like the BisectGuard — an
  over-rate peer is shed with a retry-after hint, and a peer that keeps
  hammering pays with its own connection.
- **Admission control**: `ServeRejected` from the service (overload /
  bisection budget) becomes an explicit ``rejected`` response carrying
  the retry-after hint — load shedding is an answer, not a dropped
  connection.
- **Graceful drain**: ``stop()`` stops accepting, lets in-flight
  requests finish (bounded), then closes what remains.

Chaos: the ``conn_drop`` / ``frame_truncate`` / ``net_delay`` /
``net_dup`` fault sites (`serving.faults`) act here, on connections and
frames only — a network-chaos storm can only ever look like a bad
network, never like a misbehaving verifier.

Secrecy: ONLY broadcast-public data transits the socket (wire-encoded
RefreshMessages, session metadata, verdicts). LocalKeys never do — they
reach a shard over the supervisor's private stdin pipe (SECURITY.md
"Ingress discipline"). The CRC is framing hygiene, not authentication:
an on-path adversary who tampers a broadcast is exactly the adversary
the proofs themselves blame (tamper -> identifiable abort), which is
why the wire needs no MAC to keep verdicts sound.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import json
import struct
import threading
import time
import zlib
from typing import Callable, Optional

from . import faults, metrics
from .policy import PeerRateLimiter, _env_float
from .service import RefreshService, ServeRejected, TERMINAL

__all__ = [
    "FRAME_HEADER",
    "FrameError",
    "encode_frame",
    "IngressServer",
    "IngressClient",
]

FRAME_HEADER = struct.Struct("<II")  # payload length, crc32(payload)


def _env_mb(name: str, default_mb: float) -> int:
    return max(1, int(_env_float(name, default_mb) * (1 << 20)))


class FrameError(RuntimeError):
    """A frame that must close its connection. `cause` is the tiny-enum
    rejection label (oversize/crc/malformed/bad_op)."""

    def __init__(self, cause: str, detail: str):
        self.cause = cause
        super().__init__(f"{cause}: {detail}")


def encode_frame(obj: dict) -> bytes:
    payload = json.dumps(obj, default=str).encode()
    return FRAME_HEADER.pack(len(payload), zlib.crc32(payload)) + payload


def _parse_frames(buf: bytearray, max_frame: int):
    """Yield decoded payload dicts from `buf`, consuming complete
    frames in place. Raises FrameError on oversize/CRC/JSON damage
    (leaving the buffer untouched — the caller closes the connection
    anyway)."""
    out = []
    off = 0
    while len(buf) - off >= FRAME_HEADER.size:
        length, crc = FRAME_HEADER.unpack_from(buf, off)
        if length > max_frame:
            raise FrameError(
                "oversize", f"length prefix {length} > cap {max_frame}"
            )
        if len(buf) - off - FRAME_HEADER.size < length:
            break  # incomplete tail: wait for more bytes
        start = off + FRAME_HEADER.size
        payload = bytes(buf[start : start + length])
        if zlib.crc32(payload) != crc:
            raise FrameError("crc", "frame CRC mismatch")
        try:
            obj = json.loads(payload)
        except ValueError:
            raise FrameError("malformed", "frame payload is not JSON") from None
        if not isinstance(obj, dict):
            raise FrameError("malformed", "frame payload is not an object")
        out.append((obj, FRAME_HEADER.size + length))
        off = start + length
    del buf[:off]
    return out


# ---------------------------------------------------------------------------
# server


class _Conn(asyncio.Protocol):
    """One client connection. All state here is touched only on the
    event-loop thread (protocol callbacks + response coroutines);
    blocking service calls run in the server's executor."""

    def __init__(self, server: "IngressServer"):
        self.server = server
        self.transport = None
        self.peer = "?"
        self.buf = bytearray()
        self.inflight = 0  # bytes of frames accepted, responses pending
        self.paused = False
        self.closed = False
        self.outcome = "closed"
        self.last_activity = time.monotonic()
        self.write_paused_at: Optional[float] = None
        # set while an INCOMPLETE frame sits in the buffer: a slow
        # loris dripping one byte at a time resets last_activity, but
        # not this — the sweep bounds how long one frame may take
        self.partial_since: Optional[float] = None
        self.conn_id = 0
        self.frame_seq = 0

    # -- lifecycle ------------------------------------------------------
    def connection_made(self, transport) -> None:
        self.transport = transport
        peername = transport.get_extra_info("peername") or ("?",)
        self.peer = str(peername[0])
        srv = self.server
        srv.conn_counter += 1
        self.conn_id = srv.conn_counter
        srv.conns.add(self)
        metrics.ingress_open_gauge().set(len(srv.conns))

    def connection_lost(self, exc) -> None:
        self.closed = True
        srv = self.server
        srv.conns.discard(self)
        metrics.ingress_open_gauge().set(len(srv.conns))
        metrics.ingress_connections().inc(outcome=self.outcome)
        srv._release(self, self.inflight)  # zeroes conn.inflight too
        if not any(c.peer == self.peer for c in srv.conns):
            # forget() only drops a refilled, debt-free bucket — a peer
            # closed for hammering keeps its rate state, so a tight
            # connect/hammer/reconnect loop buys no fresh burst
            srv.limiter.forget(self.peer)

    def pause_writing(self) -> None:
        self.write_paused_at = time.monotonic()

    def resume_writing(self) -> None:
        self.write_paused_at = None

    def close(self, outcome: str, cause: Optional[str] = None) -> None:
        if self.closed:
            return
        self.closed = True
        self.outcome = outcome
        if cause is not None:
            metrics.ingress_rejected().inc(cause=cause)
        if self.transport is not None and not self.transport.is_closing():
            # abort, not close: a connection being punished must not get
            # a graceful FIN that flushes whatever we still owed it
            self.transport.abort()

    def _write_frame(self, obj: dict) -> None:
        """Immediate control-path response (shed/drain answers): no
        fault injection, no executor round-trip."""
        if self.closed or self.transport.is_closing():
            return
        frame = encode_frame(obj)
        self.transport.write(frame)
        metrics.ingress_frames().inc(direction="out")
        metrics.ingress_bytes().inc(len(frame), direction="out")

    # -- inbound --------------------------------------------------------
    def data_received(self, data: bytes) -> None:
        if self.closed:
            return
        self.last_activity = time.monotonic()
        self.buf += data
        try:
            frames = _parse_frames(self.buf, self.server.max_frame)
        except FrameError as e:
            self.close("error", cause=e.cause)
            return
        if not self.buf:
            self.partial_since = None
        elif self.partial_since is None:
            self.partial_since = time.monotonic()
        for obj, nbytes in frames:
            if self.closed:
                return
            self._frame_in(obj, nbytes)

    def _frame_in(self, obj: dict, nbytes: int) -> None:
        srv = self.server
        self.frame_seq += 1
        metrics.ingress_frames().inc(direction="in")
        metrics.ingress_bytes().inc(nbytes, direction="in")
        rid = obj.get("rid")
        if srv.draining:
            # drain refuses NEW work with an answer, then the sweep
            # closes once in-flight responses are out
            metrics.ingress_rejected().inc(cause="draining")
            self._write_frame({"type": "error", "error": "draining",
                               "rid": rid})
            return
        plan = faults.active()
        if plan is not None and plan.fire(
            "conn_drop", (self.conn_id, self.frame_seq)
        ):
            self.close("faulted")
            return
        verdict = srv.limiter.charge(self.peer)
        if verdict is not None:
            metrics.ingress_peer_shed().inc()
            if verdict < 0:
                # hammering past a whole burst of sheds: the peer pays
                # with its own connection (BisectGuard-style charging)
                self.close("shed", cause="peer_rate")
                return
            self._write_frame({
                "type": "rejected", "reason": "peer_rate",
                "retry_after_s": round(verdict, 3), "rid": rid,
            })
            return
        op = obj.get("op")
        if op not in ("submit", "fetch", "broadcast", "wait", "ping",
                      "stats"):
            self.close("error", cause="bad_op")
            return
        srv._charge(self, nbytes)
        # the frame's OWN sequence rides along: fault decisions for its
        # response must key on it, not on whatever the counter says by
        # the time the response is written (overlapping responses would
        # share/skip keys and break seeded-storm reproducibility)
        asyncio.ensure_future(
            self._serve(obj, op, rid, nbytes, self.frame_seq)
        )

    # -- request handling ----------------------------------------------
    async def _serve(
        self, obj: dict, op: str, rid, nbytes: int, seq: int
    ) -> None:
        srv = self.server
        try:
            if op == "ping":
                resp = {"type": "pong"}
            elif op == "stats":
                resp = {"type": "stats", "ingress": metrics.ingress_snapshot(),
                        "serving": srv.service.stats()}
            elif op == "wait":
                resp = await self._await_terminal(obj)
            elif op == "submit":
                resp = await self._submit(obj)
            else:
                resp = await srv.loop.run_in_executor(
                    srv.pool, srv._handle_blocking, op, obj
                )
        except FrameError as e:
            if not self.closed:
                srv._release(self, nbytes)
            self.close("error", cause=e.cause)
            return
        except Exception as e:
            # a handler bug answers THIS request and touches nothing
            # else — the connection (and every other one) lives on
            resp = {"type": "error",
                    "error": f"{type(e).__name__}: {e}"}
        resp.setdefault("rid", rid)
        try:
            await self._respond(resp, seq)
        finally:
            # connection_lost releases a dead connection's WHOLE
            # remaining charge; only a live connection releases here
            # (both run on the loop thread, so the check cannot race)
            if not self.closed:
                srv._release(self, nbytes)

    async def _poll(
        self, probe, sid: int, deadline: float, timeout_resp: dict
    ) -> dict:
        """Slice-poll a non-blocking service probe on the executor:
        `probe(sid)` raising TimeoutError means "still running" (sleep
        100 ms, retry until `deadline` or the connection dies — the
        timeout is a TYPED answer, never a closed connection) and
        KeyError means the session is unknown. Polling instead of
        parking keeps the bounded pool free: neither a burst of cheap
        long-timeout `wait` frames nor a submit burst against a
        backlogged service may starve the broadcast/fetch ops other
        sessions need to reach quorum before their deadline."""
        srv = self.server
        while True:
            try:
                return await srv.loop.run_in_executor(srv.pool, probe, sid)
            except TimeoutError:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self.closed:
                    return timeout_resp
                await asyncio.sleep(min(0.1, remaining))
            except KeyError:
                return {"type": "error", "error": "unknown_session",
                        "sid": sid}

    async def _await_terminal(self, obj: dict) -> dict:
        sid = int(obj.get("sid", -1))
        timeout = min(600.0, float(obj.get("timeout", 30.0)))
        return await self._poll(
            self.server._wait_result, sid,
            deadline=time.monotonic() + timeout,
            timeout_resp={"type": "error", "error": "timeout",
                          "sid": sid, "timeout_s": timeout},
        )

    async def _submit(self, obj: dict) -> dict:
        """Admission runs one fast executor hop; the distribute wait
        then slice-polls via `_poll`."""
        srv = self.server
        pre = await srv.loop.run_in_executor(srv.pool, srv._submit_admit, obj)
        if isinstance(pre, dict):
            return pre
        sid = pre
        bound = srv.service.deadline_s + 10.0
        return await self._poll(
            srv._submit_result, sid,
            deadline=time.monotonic() + bound,
            timeout_resp={"type": "error", "error": "timeout",
                          "sid": sid, "timeout_s": round(bound, 3)},
        )

    async def _respond(self, resp: dict, seq: int) -> None:
        if self.closed:
            return
        plan = faults.active()
        key = (self.conn_id, seq)
        if plan is not None and plan.fire("net_delay", key):
            await asyncio.sleep(plan.delay_s)
        if self.closed:
            return
        frame = encode_frame(resp)
        if plan is not None and plan.fire("frame_truncate", key):
            # the torn shape a dying peer leaves: a prefix, then RST
            self.transport.write(frame[: max(1, len(frame) // 3)])
            metrics.ingress_frames().inc(direction="out")
            metrics.ingress_bytes().inc(len(frame) // 3, direction="out")
            self.close("faulted")
            return
        dup = plan is not None and plan.fire("net_dup", key)
        for _ in range(2 if dup else 1):
            self.transport.write(frame)
            metrics.ingress_frames().inc(direction="out")
            metrics.ingress_bytes().inc(len(frame), direction="out")
        self.last_activity = time.monotonic()


class IngressServer:
    """One shard's TCP ingress over a running `RefreshService`.

    Owns a dedicated event-loop thread, so it composes with the
    service's thread-based scheduler and with the shard child process
    (`serving.supervisor`). Short blocking service calls run on a
    bounded executor; the two long waits (`submit`'s distribute,
    `wait`'s verdict) poll in slices from coroutines so they can never
    park a pool thread for their full duration — the loop thread only
    frames, routes, and enforces hygiene.

    `router(cid)` — optional: return a redirect payload (dict) when
    this shard does not own `cid`, or None to serve locally. The
    supervisor wires it to the fleet's shard->port map.
    """

    def __init__(
        self,
        service: RefreshService,
        host: str = "127.0.0.1",
        port: int = 0,
        router: Optional[Callable[[object], Optional[dict]]] = None,
        max_frame: Optional[int] = None,
        inflight_budget: Optional[int] = None,
        conn_inflight_budget: Optional[int] = None,
        idle_s: Optional[float] = None,
        write_s: Optional[float] = None,
        limiter: Optional[PeerRateLimiter] = None,
        handlers: Optional[int] = None,
    ):
        self.service = service
        self.host = host
        self.port = port  # 0 = kernel-assigned; real port after start()
        self.router = router
        self.max_frame = max_frame or _env_mb("FSDKR_INGRESS_MAX_FRAME_MB", 8)
        self.inflight_budget = inflight_budget or _env_mb(
            "FSDKR_INGRESS_INFLIGHT_MB", 32
        )
        self.conn_inflight_budget = conn_inflight_budget or _env_mb(
            "FSDKR_INGRESS_CONN_INFLIGHT_MB", 4
        )
        self.idle_s = (
            idle_s if idle_s is not None
            else _env_float("FSDKR_INGRESS_IDLE_S", 60.0)
        )
        self.write_s = (
            write_s if write_s is not None
            else _env_float("FSDKR_INGRESS_WRITE_S", 10.0)
        )
        self.limiter = limiter or PeerRateLimiter()
        if handlers is None:
            handlers = max(4, int(_env_float("FSDKR_INGRESS_HANDLERS", 16)))
        self.pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=handlers, thread_name_prefix="fsdkr-ingress"
        )
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self.conns: set = set()
        self.conn_counter = 0
        self.inflight = 0  # server-global accepted-frame bytes
        self.draining = False
        self._server = None
        self._thread: Optional[threading.Thread] = None
        self._sweep_task = None
        self._ready = threading.Event()
        self._start_err: Optional[BaseException] = None

    # -- backpressure (loop thread only) --------------------------------
    def _charge(self, conn: _Conn, nbytes: int) -> None:
        conn.inflight += nbytes
        self.inflight += nbytes
        if not conn.paused and conn.inflight > self.conn_inflight_budget:
            conn.paused = True
            conn.transport.pause_reading()
            metrics.ingress_paused().inc(scope="conn")
        if self.inflight > self.inflight_budget:
            # global budget: REAL backpressure on every sender — the
            # alternative is unbounded queue growth, which is how an
            # overloaded server dies instead of slowing down
            for c in self.conns:
                if not c.paused and not c.closed:
                    c.paused = True
                    c.transport.pause_reading()
                    metrics.ingress_paused().inc(scope="server")

    def _release(self, conn: _Conn, nbytes: int) -> None:
        # the connection's own charge comes off FIRST: the resume
        # checks below must see the post-release value, or the final
        # release of a paused connection (e.g. one frame bigger than
        # half the conn budget) reads its own stale charge, never
        # resumes reading, and the connection is wedged forever — the
        # hygiene sweep deliberately spares paused conns
        conn.inflight = max(0, conn.inflight - nbytes)
        self.inflight = max(0, self.inflight - nbytes)
        if self.inflight <= self.inflight_budget // 2:
            for c in list(self.conns):
                if (
                    c.paused
                    and not c.closed
                    and c.inflight <= self.conn_inflight_budget // 2
                ):
                    c.paused = False
                    c.transport.resume_reading()
        elif (
            conn.paused
            and not conn.closed
            and conn.inflight <= self.conn_inflight_budget // 2
            and self.inflight <= self.inflight_budget
        ):
            conn.paused = False
            conn.transport.resume_reading()

    # -- blocking op handlers (executor threads) ------------------------
    def _submit_admit(self, obj: dict):
        """submit, phase 1 (executor, fast): route + admit + enqueue.
        Returns a final response dict (redirect/rejected/error) or the
        new session id for the async distribute poll."""
        svc = self.service
        cid = obj.get("cid")
        if cid is None:
            raise FrameError("bad_op", "submit without cid")
        if not svc.has_committee(cid):
            if self.router is not None:
                red = self.router(cid)
                if red is not None:
                    return dict(red, type="redirect")
            return {"type": "error", "error": "unknown_committee",
                    "cid": cid}
        try:
            return svc.submit(cid, epoch=obj.get("epoch"), external=True)
        except ServeRejected as e:
            return {
                "type": "rejected", "reason": e.reason,
                "retry_after_s": round(e.retry_after_s, 3),
            }

    def _submit_result(self, sid: int) -> dict:
        """submit, phase 2 (executor, one poll slice): non-blocking
        look at the distribute outputs; raises TimeoutError while they
        are still pending (the coroutine sleeps and retries)."""
        svc = self.service
        state, wires = svc.wait_broadcasts(sid, timeout=0)
        resp = {"type": "submitted", "sid": sid, "state": state}
        if state in TERMINAL:
            sess = svc.wait(sid, 0)
            resp.update(blame=sess.blame, error=sess.error)
        else:
            senders = [snd for snd, _w in wires]
            resp["senders"] = senders
            total = sum(len(w) for _s, w in wires)
            if total <= self.max_frame // 2:
                resp["broadcasts"] = wires
            # else: the client pulls per-sender `fetch` frames — a
            # full-width committee's broadcast set must not demand a
            # giant frame the cap exists to forbid
        return resp

    def _wait_result(self, sid: int) -> dict:
        """wait, one poll slice (executor): non-blocking look at the
        terminal verdict; raises TimeoutError while the session runs."""
        sess = self.service.wait(sid, 0)
        return {
            "type": "terminal", "sid": sid, "state": sess.state,
            "blame": sess.blame, "error": sess.error,
            "retries": sess.retries,
            "latency_s": round(
                max(0.0, sess.finalized_at - sess.submitted_at), 4
            ),
        }

    def _handle_blocking(self, op: str, obj: dict) -> dict:
        svc = self.service
        if op == "fetch":
            sid = int(obj.get("sid", -1))
            want = obj.get("senders")
            try:
                state, wires = svc.wait_broadcasts(sid, timeout=0)
            except KeyError:
                return {"type": "error", "error": "unknown_session",
                        "sid": sid}
            except TimeoutError:
                # the session EXISTS — distribute just hasn't finished.
                # Answering 'unknown' here would tell the client its
                # session died with a shard and push it into a
                # pointless resubmit; 'pending' says retry the fetch.
                return {"type": "pending", "sid": sid}
            if want is not None:
                want = {int(s) for s in want}
                wires = [(s, w) for s, w in wires if s in want]
            return {"type": "fetched", "sid": sid, "state": state,
                    "broadcasts": wires}
        if op == "broadcast":
            sid = int(obj.get("sid", -1))
            wire = obj.get("wire")
            if not isinstance(wire, str):
                raise FrameError("malformed", "broadcast without wire")
            try:
                result = svc.offer_external(sid, wire)
            except Exception:
                # a valid frame carrying an undecodable broadcast is a
                # hostile or broken peer: same policy as a bad frame —
                # close ITS connection, count it, touch nobody else
                raise FrameError(
                    "malformed", "broadcast wire payload undecodable"
                ) from None
            return {"type": "broadcast_ack", "sid": sid, "result": result}
        raise FrameError("bad_op", f"unroutable op {op!r}")

    # -- lifecycle ------------------------------------------------------
    def start(self, timeout: float = 10.0) -> "IngressServer":
        self._thread = threading.Thread(
            target=self._run_loop, name="fsdkr-ingress-loop", daemon=True
        )
        self._thread.start()
        if not self._ready.wait(timeout):
            raise RuntimeError("ingress server failed to start (timeout)")
        if self._start_err is not None:
            raise RuntimeError(
                f"ingress server failed to start: {self._start_err}"
            )
        return self

    def _run_loop(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self.loop = loop
        try:
            self._server = loop.run_until_complete(
                loop.create_server(lambda: _Conn(self), self.host, self.port)
            )
            self.port = self._server.sockets[0].getsockname()[1]
            self._sweep_task = loop.create_task(self._hygiene_sweep())
        except BaseException as e:
            self._start_err = e
            self._ready.set()
            return
        self._ready.set()
        try:
            loop.run_forever()
        finally:
            loop.close()

    async def _hygiene_sweep(self) -> None:
        """Idle and slow-write (slow-loris) policing, every 500 ms. A
        connection that sends nothing for idle_s, or whose peer stops
        draining our responses for write_s, is closed — it holds
        buffers and an fd someone honest could be using."""
        while True:
            await asyncio.sleep(0.5)
            now = time.monotonic()
            for c in list(self.conns):
                if c.closed:
                    continue
                if (
                    self.write_s > 0
                    and c.write_paused_at is not None
                    and now - c.write_paused_at > self.write_s
                ):
                    c.close("error", cause="slow_write")
                elif c.paused:
                    # the SERVER paused this connection's reads
                    # (backpressure): its bytes sit unread in the
                    # kernel by our own choice — aborting it as idle/
                    # slow-read would turn 'paused, not loss' into
                    # loss. (slow_write above still applies: that is
                    # the PEER not reading us.) But a conn paused by
                    # the GLOBAL pass while holding little or no
                    # charge of its own may have no release left to
                    # resume it — if global inflight oscillates in
                    # (budget/2, budget] the release-side checks never
                    # fire for it, so the sweep is its resume backstop
                    if (
                        self.inflight <= self.inflight_budget
                        and c.inflight <= self.conn_inflight_budget // 2
                    ):
                        c.paused = False
                        c.transport.resume_reading()
                elif (
                    self.idle_s > 0
                    and c.partial_since is not None
                    and now - c.partial_since > self.idle_s
                ):
                    # read-side slow loris: a frame dribbled in byte by
                    # byte keeps last_activity fresh, but no single
                    # frame gets longer than idle_s to complete
                    c.close("error", cause="slow_read")
                elif (
                    self.idle_s > 0
                    and c.inflight == 0
                    and now - c.last_activity > self.idle_s
                ):
                    c.close("idle")

    async def _shutdown(self, drain_s: float) -> None:
        """Graceful drain: stop accepting, answer what is in flight,
        then close the rest."""
        self.draining = True
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        deadline = time.monotonic() + drain_s
        while time.monotonic() < deadline:
            if all(c.inflight == 0 for c in self.conns):
                break
            await asyncio.sleep(0.05)
        for c in list(self.conns):
            if not c.closed:
                c.outcome = "drained"
                c.transport.close()
        if self._sweep_task is not None:
            self._sweep_task.cancel()

    def stop(self, drain_s: float = 10.0) -> None:
        if self.loop is None or self._thread is None:
            return
        if not self._thread.is_alive():
            return  # already stopped (stop() is idempotent)
        if self._start_err is None:
            fut = asyncio.run_coroutine_threadsafe(
                self._shutdown(drain_s), self.loop
            )
            try:
                fut.result(timeout=drain_s + 5.0)
            except Exception:
                pass
        try:
            self.loop.call_soon_threadsafe(self.loop.stop)
        except RuntimeError:
            pass  # loop already closed
        self._thread.join(timeout=10.0)
        self.pool.shutdown(wait=False)

    def stats(self) -> dict:
        return dict(
            metrics.ingress_snapshot(),
            inflight_bytes=self.inflight,
            draining=self.draining,
        )


# ---------------------------------------------------------------------------
# client


class IngressClient:
    """Synchronous wire-protocol client (the load-generator clients,
    tests, and the ci smoke speak through this). One in-flight request
    at a time unless the caller pipelines explicitly via send()/recv().

    Every transport-level defect — connection refused/reset, torn
    frame, CRC mismatch, oversize response — raises ConnectionError:
    to a client the network failing IS one condition, answered by
    reconnect + idempotent resubmit. Duplicated responses (net_dup) are
    dropped by rid matching."""

    def __init__(
        self,
        host: str,
        port: int,
        timeout: float = 30.0,
        max_frame: Optional[int] = None,
    ):
        import socket

        self.timeout = timeout
        self.max_frame = max_frame or _env_mb("FSDKR_INGRESS_MAX_FRAME_MB", 8)
        self._rid = 0
        self._buf = bytearray()
        # responses parsed while waiting for a different rid (client
        # pipelining: server answers in COMPLETION order, not request
        # order) — handed back when their recv() comes; rids already
        # handed back, so a net_dup duplicate is discarded. A rid
        # whose recv() TIMED OUT forfeits its response: the documented
        # recovery for a timeout is reconnect + idempotent resubmit,
        # never a re-recv of the same rid
        self._pending: dict = {}
        self._done_rids: set = set()
        self._outstanding: set = set()  # rids sent, not yet handed back
        self._sock = socket.create_connection((host, port), timeout=timeout)

    # -- framing --------------------------------------------------------
    def send(self, obj: dict) -> int:
        """Write one request frame; returns its rid (for recv)."""
        self._rid += 1
        obj = dict(obj, rid=self._rid)
        try:
            self._sock.sendall(encode_frame(obj))
        except OSError as e:
            raise ConnectionError(f"send failed: {e}") from None
        # only after the frame is on the wire: a failed send must not
        # leave a rid outstanding forever, pinning the prune floor
        self._outstanding.add(self._rid)
        return self._rid

    def _done(self, rid: int) -> None:
        """Record `rid` as handed back and bound the dup-tracking
        state: anything below the OLDEST rid still awaiting its recv
        can only ever be a duplicate — a long-lived client under
        dup-heavy chaos must not leak `_done_rids`/`_pending`, but a
        parked response a pipelining caller has yet to collect must
        survive the prune."""
        self._outstanding.discard(rid)
        self._done_rids.add(rid)
        floor = min(self._outstanding, default=self._rid)
        self._done_rids = {r for r in self._done_rids if r >= floor}
        for r in [r for r in self._pending if r < floor]:
            del self._pending[r]

    def recv(self, rid: Optional[int] = None, timeout: Optional[float] = None) -> dict:
        """Read frames until one matches `rid` (default: the last
        send), dropping duplicates/stale responses."""
        want = self._rid if rid is None else rid
        deadline = time.monotonic() + (
            timeout if timeout is not None else self.timeout
        )
        while True:
            if want in self._pending:
                resp = self._pending.pop(want)  # pop BEFORE the prune
                self._done(want)
                return resp
            got = None
            for obj, _n in _parse_frames(self._buf, self.max_frame):
                r = obj.get("rid")
                if r == want or r is None:
                    # a net_dup duplicate of the awaited rid in the
                    # SAME parse batch is discarded here, never parked
                    if got is None:
                        got = obj
                elif r not in self._pending and r not in self._done_rids:
                    # an out-of-order pipelined response: park it; a
                    # DUPLICATE (net_dup) of one already parked or
                    # already handed back is discarded
                    self._pending[r] = obj
            if got is not None:
                self._done(want)
                return got
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                # the caller is giving up on this rid: it must not pin
                # the prune floor for the rest of the client's life
                # (which also means a late response for it may be
                # pruned — a timed-out rid forfeits its response)
                self._outstanding.discard(want)
                raise ConnectionError(f"no response for rid {want} in time")
            self._sock.settimeout(min(remaining, 5.0))
            try:
                data = self._sock.recv(1 << 16)
            except OSError as e:
                import socket as _socket

                if isinstance(e, _socket.timeout):
                    continue
                raise ConnectionError(f"recv failed: {e}") from None
            if not data:
                raise ConnectionError("connection closed by server")
            self._buf += data

    def request(self, obj: dict, timeout: Optional[float] = None) -> dict:
        rid = self.send(obj)
        try:
            return self.recv(rid, timeout)
        except FrameError as e:
            raise ConnectionError(f"bad response frame: {e}") from None

    # -- ops ------------------------------------------------------------
    def submit(self, cid, epoch=None, timeout: Optional[float] = None) -> dict:
        return self.request(
            {"op": "submit", "cid": cid, "epoch": epoch}, timeout
        )

    def fetch(self, sid: int, senders=None, timeout=None) -> dict:
        req = {"op": "fetch", "sid": sid}
        if senders is not None:
            req["senders"] = list(senders)
        return self.request(req, timeout)

    def broadcast(self, sid: int, wire: str, timeout=None) -> dict:
        return self.request(
            {"op": "broadcast", "sid": sid, "wire": wire}, timeout
        )

    def wait(self, sid: int, timeout_s: float = 30.0) -> dict:
        return self.request(
            {"op": "wait", "sid": sid, "timeout": timeout_s},
            timeout=timeout_s + 10.0,
        )

    def ping(self) -> dict:
        return self.request({"op": "ping"})

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
