"""SLO-driven capacity planning for the precompute pools (ISSUE 9).

The background producer (fsdkr_tpu/precompute) was built as a
prefetcher: `distribute()` registers one epoch of demand for the keys it
just generated and the producer back-fills. A serving loop needs a
CAPACITY MANAGER instead — per-committee pool depth targets derived from
the committee's SLO (expected arrival rate, p99 latency budget), so the
pools hold enough single-use material to absorb bursts without dry
fallbacks, and are retargeted/invalidated when the committee's key
material rotates (every epoch) or churns (join/replace/remove).

The planner does not produce anything itself: it translates SLOs into
`precompute.retarget_committee` calls under the committee's serving
owner tag. Depth math, shaped by which pools survive an epoch:

- enc/pdl/alice are keyed by receiver Paillier moduli, which refresh
  ROTATES every epoch — any depth beyond one epoch of consumption
  (`new_n` entries per pool) is guaranteed wipe-waste, so the planner
  always asks for exactly one epoch there (measured: the naive
  epochs-ahead policy wiped ~5x more entries than it served).
- the config-keyed "keys" pool is epoch-stable and SHARED by every
  committee with that config, so it alone absorbs the SLO runway:
  want = clamp(ceil(sum of arrival rates * horizon), 1,
  FSDKR_SERVE_MAX_AHEAD * committees) * new_n, registered under the
  fleet-wide KEYS_POOL_OWNER (never a committee's own tag — one
  committee's churn must not wipe the fleet's key bundles).

Entry depth is still capped by FSDKR_POOL_DEPTH / FSDKR_POOL_BUDGET_MB
— the planner asks, the pool store enforces.
"""

from __future__ import annotations

import math
import os
import threading
from dataclasses import dataclass
from typing import Dict, Optional

from .. import precompute
from ..telemetry import registry

__all__ = ["SLO", "CapacityPlanner", "serve_owner"]


@dataclass(frozen=True)
class SLO:
    """Per-committee service-level objective. `arrival_rate_hz` is the
    expected refresh-request rate for this committee; `p99_budget_s` the
    end-to-end latency budget the operator wants honored (reported
    against the measured p99; the planner's depth math uses the rate)."""

    arrival_rate_hz: float = 0.05
    p99_budget_s: float = 30.0


def serve_owner(committee_id) -> tuple:
    """The precompute owner tag of one admitted committee. Distinct from
    the mod-N~ fingerprint `precompute.committee_owner` so that cloned /
    re-admitted committees sharing auxiliary parameters stay separately
    invalidatable."""
    return ("serve", committee_id)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


class CapacityPlanner:
    """Registry of admitted committees' SLOs + the retarget engine."""

    def __init__(
        self,
        horizon_s: Optional[float] = None,
        max_ahead: Optional[int] = None,
    ):
        self.horizon_s = (
            horizon_s
            if horizon_s is not None
            else _env_float("FSDKR_SERVE_HORIZON_S", 30.0)
        )
        self.max_ahead = (
            max_ahead
            if max_ahead is not None
            else int(_env_float("FSDKR_SERVE_MAX_AHEAD", 4))
        )
        self._lock = threading.Lock()
        # committee_id -> (representative LocalKey, new_n, config, slo);
        # the LocalKey is the live object the service mutates in place,
        # so retarget() always sees the CURRENT paillier_key_vec
        self._committees: Dict[object, tuple] = {}
        registry.gauge(
            "fsdkr_serving_planned_ahead",
            "mean epochs-ahead depth target across admitted committees",
        ).set_function(self._mean_ahead)

    # ------------------------------------------------------------------
    def epochs_ahead(self, slo: SLO) -> int:
        return max(
            1, min(self.max_ahead, math.ceil(slo.arrival_rate_hz * self.horizon_s))
        )

    def _mean_ahead(self) -> float:
        with self._lock:
            items = list(self._committees.values())
        if not items:
            return 0.0
        return sum(self.epochs_ahead(slo) for _k, _n, _c, slo in items) / len(items)

    # ------------------------------------------------------------------
    def register(self, committee_id, local_key, new_n: int, config, slo: SLO) -> None:
        """Admit a committee: record its SLO and install its initial
        pool targets (keyed by the CURRENT paillier_key_vec)."""
        with self._lock:
            self._committees[committee_id] = (local_key, new_n, config, slo)
        self.retarget(committee_id)

    def keys_want(self, config) -> int:
        """Fleet-wide key-material demand for this config: sessions
        expected over the horizon across every admitted committee
        sharing the config's pool key, times bundles per session."""
        kp = config.key_material_pool_key
        with self._lock:
            peers = [
                (n, slo)
                for _k, n, c, slo in self._committees.values()
                if c.key_material_pool_key == kp
            ]
        if not peers:
            return 1
        new_n = peers[0][0]
        rate = sum(slo.arrival_rate_hz for _n, slo in peers)
        sessions = max(1, min(
            self.max_ahead * len(peers), math.ceil(rate * self.horizon_s)
        ))
        return sessions * new_n

    def retarget(self, committee_id) -> None:
        """Re-derive this committee's pool targets from its live key
        state — called after every completed epoch (the eks just
        rotated) and after churn. Stale-keyed targets and their pooled
        secrets are wiped by retarget_committee (wipe-on-invalidate)."""
        with self._lock:
            ent = self._committees.get(committee_id)
        if ent is None or not precompute.enabled():
            return
        local_key, new_n, config, slo = ent
        precompute.retarget_committee(
            local_key, new_n, new_n, config,
            owner=serve_owner(committee_id),
            keys_want=self.keys_want(config),
        )

    def invalidate(self, committee_id) -> int:
        """Committee eviction / churn: drop every target registered
        under its owner and wipe the pooled entries now."""
        with self._lock:
            self._committees.pop(committee_id, None)
        return precompute.invalidate_owner(serve_owner(committee_id))

    def slo(self, committee_id) -> Optional[SLO]:
        with self._lock:
            ent = self._committees.get(committee_id)
        return ent[3] if ent else None

    def committees(self) -> int:
        with self._lock:
            return len(self._committees)
