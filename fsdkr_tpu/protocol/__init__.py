"""Protocol layer (SURVEY.md §2a L4-L5): the refresh protocol itself plus
the GG20-compatible host application surface the reference borrows from
`multi-party-ecdsa` (LocalKey, simulated keygen, threshold signing).
"""

from .local_key import LocalKey, SharedKeys, PaillierKeyPair
from .refresh import RefreshMessage
from .streaming import StreamingCollect, finalize_streams
from .join import JoinMessage
from .keygen import simulate_keygen, generate_h1_h2_n_tilde, generate_dlog_statement_proofs
from .signing import simulate_offline_stage, simulate_signing, ecdsa_verify
from .simulation import BroadcastChannel, simulate_dkr, simulate_dkr_removal

__all__ = [
    "LocalKey",
    "SharedKeys",
    "PaillierKeyPair",
    "RefreshMessage",
    "StreamingCollect",
    "finalize_streams",
    "JoinMessage",
    "simulate_keygen",
    "generate_h1_h2_n_tilde",
    "generate_dlog_statement_proofs",
    "simulate_offline_stage",
    "simulate_signing",
    "ecdsa_verify",
    "BroadcastChannel",
    "simulate_dkr",
    "simulate_dkr_removal",
]
